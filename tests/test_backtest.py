"""Time-batched backtest backend drills (ISSUE 6).

The backend (``binquant_tpu/backtest``) evaluates FULL-recompute tick
semantics over an ``(S, W+T)`` extended buffer — per-tick window views as
gathers, heavy math vmapped over the tick axis, sequential recursions in a
light scan — and must emit the EXACT signal set of the serial
full-recompute drive (``run_replay(incremental=False)``). Tier-1 pins one
small-shape equality drill plus the params-pytree default bit-parity; the
slow lane (``make backtest-smoke``) adds the recorded-stream equality, the
engineered overflow burst, the rewrite chunk break, and the ≥64-combo
vmapped grid smoke.
"""

from __future__ import annotations

import asyncio
from pathlib import Path

import numpy as np
import pytest

from binquant_tpu.io.replay import (
    generate_replay_file,
    load_klines_by_tick,
    make_stub_engine,
    run_replay,
)

CAPACITY, WINDOW = 32, 120
FIXTURE = Path(__file__).parent / "fixtures" / "market_36h_100sym.jsonl.gz"


def _tick_seq(path):
    by_tick = load_klines_by_tick(path)
    return [
        (
            (bucket + 1) * 900 * 1000,
            sorted(by_tick[bucket], key=lambda k: k["open_time"]),
        )
        for bucket in sorted(by_tick)
    ]


def _signal_tuples(fired):
    return [
        (s.tick_ms, s.strategy, s.symbol, str(s.value.direction),
         bool(s.value.autotrade))
        for s in fired
    ]


@pytest.fixture(scope="module")
def small_stream(tmp_path_factory):
    path = tmp_path_factory.mktemp("bt") / "bt_16.jsonl"
    generate_replay_file(path, n_symbols=16, n_ticks=112)
    return path


def test_backtest_matches_serial_full_drive(small_stream):
    """ISSUE 6 acceptance (tier-1 half): the time-batched backend emits
    the exact signal set of the serial full-recompute drive on a
    rewrite-free stream, with the cold-start churn tick routed serially
    and every other tick riding batched chunks."""
    from binquant_tpu.backtest import run_backtest

    serial: list = []
    s_stats = run_replay(
        small_stream, capacity=CAPACITY, window=WINDOW, collect=serial,
        incremental=False,
    )
    bt: list = []
    b_stats = run_backtest(
        small_stream, capacity=CAPACITY, window=WINDOW, collect=bt, chunk=16,
    )
    assert set(serial) == set(bt), {
        "only_serial": sorted(set(serial) - set(bt))[:5],
        "only_backtest": sorted(set(bt) - set(serial))[:5],
    }
    # non-vacuous: signals fired, the backend actually batched, and only
    # the cold-start churn tick re-entered the serial path
    assert len(serial) > 0
    assert b_stats["backtest_chunks"] >= 2
    assert b_stats["backtest_ticks"] > 0
    assert b_stats["serial_ticks"] == 1
    assert b_stats["ticks"] == s_stats["ticks"]
    assert b_stats["backtest_overflow_reruns"] == 0


def test_params_default_bit_parity():
    """Tentpole guard: threading an EXPLICIT default StrategyParams pytree
    through the live wire step produces the bit-identical wire (and carried
    state) as the baked-constant path — lifting the constants changed
    nothing at defaults."""
    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.step import (
        default_host_inputs,
        initial_engine_state,
        pad_updates,
        tick_step_wire,
    )
    from binquant_tpu.strategies.params import (
        default_strategy_params,
        dynamic_params,
    )

    S, W = 8, 120
    rng = np.random.default_rng(0)
    inputs0 = default_host_inputs(S)._replace(
        tracked=jnp.ones((S,), bool), btc_row=jnp.asarray(0, jnp.int32)
    )
    t0 = 1_780_272_000
    px = 20 + rng.random(S) * 50
    st1 = st2 = initial_engine_state(S, window=W)
    explicit = dynamic_params(default_strategy_params())
    for t in range(108):
        ts15 = t0 + t * 900
        newpx = px * (1 + rng.normal(0, 0.003, S))
        vals = np.zeros((S, 10), np.float32)
        vals[:, 0] = px
        vals[:, 1] = np.maximum(px, newpx) * 1.001
        vals[:, 2] = np.minimum(px, newpx) * 0.999
        vals[:, 3] = newpx
        vals[:, 4] = 1000.0
        vals[:, 5] = 1000.0 * newpx
        vals[:, 6] = 300.0
        vals[:, 9] = 900.0
        rows = np.arange(S, dtype=np.int32)
        u15 = pad_updates(rows, np.full(S, ts15, np.int32), vals)
        u5 = pad_updates(rows, np.full(S, ts15 + 600, np.int32), vals)
        inp = inputs0._replace(
            timestamp_s=jnp.asarray(ts15, jnp.int32),
            timestamp5_s=jnp.asarray(ts15 + 600, jnp.int32),
        )
        px = newpx
        st1, w1 = tick_step_wire(st1, u5, u15, inp)
        st2, w2 = tick_step_wire(st2, u5, u15, inp, params=explicit)
    assert np.array_equal(np.asarray(w1), np.asarray(w2), equal_nan=True)
    for a, b in zip(
        jax.tree_util.tree_leaves(st1), jax.tree_util.tree_leaves(st2)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)


def test_param_grid_helpers():
    """Grid builder contract: float axes sweep, structural axes refuse,
    combos enumerate the cartesian product."""
    from binquant_tpu.strategies.params import (
        grid_size,
        make_param_grid,
        sweepable_axes,
    )

    axes = sweepable_axes()
    assert "abp.volume_multiplier" in axes
    assert "pt.rsi_oversold" in axes  # lifted entry threshold
    assert "lsp.max_stress" in axes  # lifted routing veto
    assert "pt.weights.context_weight" in axes  # nested ScorerWeights
    assert "abp.lookback_window" not in axes  # structural int

    grid, combos = make_param_grid(
        {"mrf.rsi_long_max": [20.0, 25.0], "pt.rsi_oversold": [25.0, 30.0, 35.0]}
    )
    assert grid_size(grid) == 6 == len(combos)
    assert grid.mrf.rsi_long_max.shape == (6,)
    assert isinstance(grid.abp.lookback_window, int)
    with pytest.raises(KeyError):
        make_param_grid({"abp.nope": [1.0]})
    with pytest.raises(ValueError):
        make_param_grid({"abp.lookback_window": [10, 20]})


def test_backtest_rejects_incremental_engines_and_dormant_sets(small_stream):
    """Guard rails: the backend is full-recompute only, and only the
    strategies whose gated half is buffer-free are evaluable."""
    from binquant_tpu.backtest import run_backtest
    from binquant_tpu.backtest.driver import drive_ticks_backtest

    engine = make_stub_engine(
        capacity=CAPACITY, window=WINDOW, incremental=True
    )
    with pytest.raises(ValueError, match="full-recompute"):
        asyncio.run(drive_ticks_backtest(engine, []))
    with pytest.raises(ValueError, match="cannot evaluate"):
        run_backtest(
            small_stream, capacity=CAPACITY, window=WINDOW,
            enabled_strategies={"coinrule_buy_the_dip"},
        )


@pytest.mark.slow
def test_backtest_recorded_stream_equality():
    """ISSUE 6 acceptance (slow half): on the checked-in rewrite-free
    36 h recorded-market fixture the backend's emitted signal set equals
    the serial full-recompute drive's."""
    from binquant_tpu.backtest import run_backtest

    serial: list = []
    run_replay(
        FIXTURE, capacity=128, window=200, collect=serial, incremental=False,
    )
    bt: list = []
    b_stats = run_backtest(
        FIXTURE, capacity=128, window=200, collect=bt, chunk=16,
    )
    assert set(serial) == set(bt), {
        "only_serial": sorted(set(serial) - set(bt))[:5],
        "only_backtest": sorted(set(bt) - set(serial))[:5],
    }
    assert len(serial) > 0
    assert b_stats["backtest_chunks"] >= 2


@pytest.mark.slow
def test_backtest_breadth_engaged_equality(tmp_path):
    """Equality with the breadth-gated paths LIVE: scripted washed-out
    breadth engages LSP's routing ladder and the grid-only policy's
    device-side momentum recursion — the sequential half this backend
    reimplements in its scan."""
    from binquant_tpu.backtest import run_backtest
    from tests.test_ab_parity import WASHED_BREADTH

    path = tmp_path / "breadth.jsonl"
    generate_replay_file(path, n_symbols=24, n_ticks=120, seed=7)
    serial: list = []
    run_replay(
        path, capacity=64, window=200, collect=serial,
        breadth=WASHED_BREADTH, incremental=False,
    )
    bt: list = []
    b_stats = run_backtest(
        path, capacity=64, window=200, collect=bt, breadth=WASHED_BREADTH,
    )
    assert set(serial) == set(bt), {
        "only_serial": sorted(set(serial) - set(bt))[:5],
        "only_backtest": sorted(set(bt) - set(serial))[:5],
    }
    assert len(serial) > 0
    assert b_stats["backtest_chunks"] >= 1


@pytest.mark.slow
def test_backtest_burst_overflow_redrives_serially(tmp_path):
    """A market-wide crash tick fires more pairs than the wire's
    compaction slots inside a chunk: the chunk must rewind (engine state
    never advanced) and re-drive serially through the audited per-tick
    overflow fallback — emitted set still exact."""
    from binquant_tpu.backtest import run_backtest
    from binquant_tpu.io.replay import generate_burst_replay

    path = tmp_path / "burst.jsonl"
    generate_burst_replay(path, n_symbols=160, n_ticks=108)
    serial: list = []
    s_stats = run_replay(
        path, capacity=192, window=200, collect=serial, incremental=False,
    )
    bt: list = []
    b_stats = run_backtest(path, capacity=192, window=200, collect=bt)
    assert set(serial) == set(bt)
    assert s_stats["overflow_ticks"] >= 1  # the drill actually overflowed
    assert b_stats["backtest_overflow_reruns"] >= 1  # ...inside a chunk
    assert b_stats["backtest_ticks"] > 0  # earlier chunks still batched


@pytest.mark.slow
def test_backtest_rewrite_break(small_stream):
    """A corrected candle re-sent two ticks later (the exchange's re-send
    pattern) must break the chunk, route through the serial path, and
    leave the emitted set identical to a never-batched drive."""
    seq = _tick_seq(small_stream)
    donor_tick = len(seq) - 6
    donor = next(
        k for k in seq[donor_tick][1]
        if k["symbol"] == "S002USDT"
        and (k["close_time"] - k["open_time"]) // 1000 >= 899
    )
    corrected = dict(donor)
    corrected["close"] = round(donor["close"] * 1.004, 6)
    corrected["high"] = max(corrected["high"], corrected["close"])
    seq = [(ms, list(ks)) for ms, ks in seq]
    seq[donor_tick + 2][1].append(corrected)

    def drive_serial():
        engine = make_stub_engine(
            capacity=CAPACITY, window=WINDOW, incremental=False
        )
        out: list = []

        async def drive():
            for now_ms, klines in seq:
                for k in klines:
                    engine.ingest(k)
                out.extend(await engine.process_tick(now_ms=now_ms))
            out.extend(await engine.flush_pending())

        asyncio.run(drive())
        return _signal_tuples(out)

    def drive_backtest():
        engine = make_stub_engine(
            capacity=CAPACITY, window=WINDOW, incremental=False,
            backtest_chunk=16,
        )
        out: list = []

        async def drive():
            out.extend(await engine.process_ticks_backtest(seq))
            out.extend(await engine.flush_pending())

        asyncio.run(drive())
        return _signal_tuples(out), engine

    serial = drive_serial()
    bt, engine = drive_backtest()
    assert set(serial) == set(bt), {
        "only_serial": sorted(set(serial) - set(bt))[:5],
        "only_backtest": sorted(set(bt) - set(serial))[:5],
    }
    assert len(serial) > 0
    assert engine.backtest_chunks >= 2
    # cold-start churn + the rewrite tick both re-entered the serial path
    assert engine.ticks_processed - engine.backtest_ticks >= 2


@pytest.mark.slow
def test_sweep_grid_64_combos_single_dispatch(small_stream):
    """ISSUE 6 acceptance: ONE vmapped dispatch scores ≥64 parameter
    combos, and the combos genuinely diverge — the PriceTracker oversold
    axis must move its fire count monotonically."""
    from binquant_tpu.backtest import run_param_sweep
    from binquant_tpu.engine.step import STRATEGY_ORDER

    res = run_param_sweep(
        small_stream,
        axes={
            "pt.rsi_oversold": [10.0, 30.0, 60.0, 95.0],
            "pt.mfi_oversold": [5.0, 20.0, 60.0, 95.0],
            "mrf.rsi_long_max": [5.0, 25.0, 45.0, 65.0],
        },
        capacity=CAPACITY,
        window=WINDOW,
        # the whole stream in ONE chunk → one vmapped dispatch per plan
        chunk=128,
    )
    assert res["P"] == 64
    assert res["dispatches"] >= 1
    assert res["evaluated_ticks"] > 0
    totals = np.asarray(res["total_fired"])
    assert len(set(totals.tolist())) > 4  # combos genuinely diverge

    tc = np.asarray(res["trig_counts"])  # (P, N)
    pt_col = list(STRATEGY_ORDER).index("coinrule_price_tracker")
    # average PT fires per rsi_oversold level must be non-decreasing in
    # the threshold (a looser oversold gate can only fire more)
    pt_by_level = [
        tc[[i for i, c in enumerate(res["combos"])
            if c["pt.rsi_oversold"] == level], pt_col].mean()
        for level in (10.0, 30.0, 60.0, 95.0)
    ]
    assert pt_by_level == sorted(pt_by_level)
    assert pt_by_level[-1] > pt_by_level[0]
