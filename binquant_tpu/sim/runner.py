"""Scenario-corpus runner: every scenario driven scanned AND serial
through the full engine, with the graceful-degradation invariants checked
after each run.

Per scenario, three drives over the identical generated stream:

1. serial incremental (the live per-tick path),
2. scanned incremental (``process_ticks_scanned`` fused chunks),
3. serial full-recompute (``BQT_INCREMENTAL=0`` — the carried-path's
   in-engine oracle).

Checks: exact signal-set equality across all three; recompute-routing
reasons equal to the scenario's script (and identical between the serial
and scanned drives); zero crash-ring entries (errored traces, donated
state resets); per-bar emission dedupe holds; heartbeat live; overflow
expectations (a fire burst must overflow AND re-drive; everything else
must not); pinned-signal-set equality against the checked-in corpus
(``tests/fixtures/scenario_signals.json`` — regenerate deliberately with
``repin=True`` / ``BQT_SCENARIO_REPIN=1``).
"""

from __future__ import annotations

import asyncio
import json
import os
from pathlib import Path

from binquant_tpu.io.replay import signal_tuples, tick_seq
from binquant_tpu.obs.events import get_event_log
from binquant_tpu.sim.scenarios import SCENARIOS, Scenario, write_scenario_file

PINNED_FIXTURE = (
    Path(__file__).resolve().parents[2]
    / "tests"
    / "fixtures"
    / "scenario_signals.json"
)


def drive_scenario(
    scenario: Scenario, path, *, scanned: bool, incremental: bool
):
    """One drive of a generated scenario stream; returns (signal tuples,
    engine) — the engine is kept for invariant introspection."""
    from binquant_tpu.io.replay import make_stub_engine
    from binquant_tpu.io.websocket import WsHealth

    spec = scenario.spec
    engine = make_stub_engine(
        capacity=spec.capacity,
        window=spec.window,
        breadth=spec.breadth,
        incremental=incremental,
        scan_chunk=spec.scan_chunk,
        enabled_strategies=set(spec.enabled_strategies),
        trace_sample=1.0,  # every tick traced: the crash-ring invariant
        # ingest-health observatory (ISSUE 15): scenarios that script
        # feed faults pin the digest + monitor ON (zero budget — any
        # stale row burns) so the staleness SLO state machine and the
        # cross-drive digest equality become scripted invariants;
        # everything else keeps the lane's env default
        ingest_digest=True if spec.ingest else None,
        ingest_stale_budget=0 if spec.ingest else None,
        # signal-outcome observatory (ISSUE 12): pinned ON with short
        # horizons so the scripted streams' aftermaths show up as
        # per-family MAE/MFE columns in the verdict — and the matured set
        # becomes one more cross-drive equality invariant. Horizons stay
        # small because the corpus events land just past MIN_BARS near
        # EOF (longer horizons would never mature) and because the
        # retention bound (W >= 3*chunk + h) must hold at spec shapes.
        outcomes=True,
        outcome_horizons=(1, 4),
        # the corpus pins INLINE sink semantics (telegram-sent counts read
        # synchronously after the drive); the delivery plane has its own
        # drill (delivery_chaos_drill) with the at-least-once invariants
        delivery=False,
        # likewise the fan-out plane: the corpus pins pre-fanout event
        # logs and signal sets; the plane's own drill is
        # fanout_chaos_drill (churn storm + stalled consumers)
        fanout=False,
    )
    # isolated ws tracker: the module singleton may carry another drill's
    # reconnect storm, which would flip this run's health to degraded
    engine.ws_health = WsHealth()
    if spec.ingest:
        # capture every tick's raw digest vector: the runner pins
        # bit-identical digest streams across the three drives
        engine.ingest_monitor.record_history = True
    seq = tick_seq(path)
    out: list = []

    async def go() -> None:
        if scanned:
            out.extend(await engine.process_ticks_scanned(seq))
        else:
            for now_ms, klines in seq:
                for k in klines:
                    engine.ingest(k)
                out.extend(await engine.process_tick(now_ms=now_ms))
        out.extend(await engine.flush_pending())

    asyncio.run(go())
    return signal_tuples(out), engine


def _crash_ring_entries(engine) -> int:
    """Errored entries in the engine's completed-trace ring plus cold
    state resets — the 'something went down mid-run' tally that must be
    zero after every scenario."""
    errored = sum(
        1
        for e in engine.tracer.entries()
        if e["summary"].get("status") != "ok"
    )
    return errored + engine.donated_state_resets


def _dedupe_holds(signals: list[tuple]) -> bool:
    """Per-bar emission dedupe: at most one emission per (strategy,
    symbol) per producing tick, and no duplicated tuples at all."""
    keys = [(t, strat, sym) for t, strat, sym, *_ in signals]
    return len(keys) == len(set(keys)) and len(signals) == len(set(signals))


def run_scenario(
    name: str, workdir: str | Path, pinned: dict | None = None
) -> dict:
    """Generate + drive one scenario; returns the verdict dict (also
    emitted as a ``scenario_run`` event for tools/scenario_report.py)."""
    scenario = SCENARIOS[name]
    spec = scenario.spec
    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    path = workdir / f"{name}.jsonl"
    lines = write_scenario_file(scenario, path)

    serial, eng_s = drive_scenario(scenario, path, scanned=False, incremental=True)
    scanned, eng_c = drive_scenario(scenario, path, scanned=True, incremental=True)
    full, eng_f = drive_scenario(scenario, path, scanned=False, incremental=False)

    signal_set = sorted(set(serial))
    checks: dict[str, bool] = {}
    checks["serial_eq_scanned"] = set(serial) == set(scanned)
    checks["carried_eq_full_oracle"] = set(serial) == set(full)
    checks["scan_fused"] = eng_c.scanned_ticks > 0
    routing = dict(eng_s.full_recompute_reasons)
    checks["routing_matches_script"] = (
        set(routing) == set(spec.expect_routing)
        and eng_c.full_recompute_reasons == routing
        and all(routing.get(r, 0) >= n for r, n in spec.routing_min)
    )
    checks["zero_crash_ring_entries"] = (
        sum(_crash_ring_entries(e) for e in (eng_s, eng_c, eng_f)) == 0
    )
    checks["dedupe_holds"] = all(
        _dedupe_holds(sigs) for sigs in (serial, scanned, full)
    )
    checks["heartbeat_live"] = all(
        e.health_snapshot()["status"] == "ok" for e in (eng_s, eng_c, eng_f)
    )
    if spec.expect_overflow:
        checks["overflow_script"] = (
            eng_s.overflow_ticks >= 1 and eng_c.scan_overflow_reruns >= 1
        )
    else:
        checks["overflow_script"] = (
            eng_s.overflow_ticks == 0 and eng_c.scan_overflow_reruns == 0
        )
    checks["min_signals"] = len(signal_set) >= spec.min_signals
    checks["min_telegram"] = (
        len(eng_s._telegram_sent) >= spec.min_telegram  # type: ignore[attr-defined]
    )
    checks["numeric_clean"] = all(
        e.numeric.anomaly_ticks == 0 and e.drift.alarms == 0
        for e in (eng_s, eng_c, eng_f)
    )
    if pinned is not None and name in pinned:
        checks["pinned_signal_set"] = (
            [list(t) for t in signal_set] == pinned[name]["signals"]
        )
    # signal-outcome parity (ISSUE 12): the matured (strategy, symbol,
    # entry, horizon, fwd/mae/mfe) sets must agree across all three
    # drives — outcomes derive from the (pinned-equal) signal sets plus
    # the shared stream, so a mismatch means the maturation gather read
    # different history (a retention-bound violation or a drive bug)
    checks["outcome_parity"] = (
        eng_s.outcomes.matured_set()
        == eng_c.outcomes.matured_set()
        == eng_f.outcomes.matured_set()
    )
    outcomes = _outcome_columns(eng_s)
    ingest = None
    if spec.ingest:
        import numpy as np

        # bit-identical per-tick ingest digests across the three drives
        ds, dc, df = (
            np.stack(e.ingest_monitor.digests)
            for e in (eng_s, eng_c, eng_f)
        )
        checks["ingest_digest_parity"] = bool(
            ds.shape == dc.shape == df.shape
            and np.array_equal(ds, dc, equal_nan=True)
            and np.array_equal(ds, df, equal_nan=True)
        )
        if spec.expect_ingest_anomaly:
            # the staleness alarm must TRIP during the scripted fault and
            # CLEAR after catch-up — in every drive, with /healthz
            # degraded while burning resolved back to ok at EOF
            checks["ingest_alarm_trips_and_clears"] = all(
                e.ingest_monitor.anomaly_ticks > 0
                and e.ingest_monitor.recoveries >= 1
                and not e.ingest_monitor.burning
                and e.health_snapshot()["ingest"]["status"] == "ok"
                for e in (eng_s, eng_c, eng_f)
            )
        else:
            checks["ingest_quiet"] = all(
                e.ingest_monitor.anomaly_ticks == 0
                for e in (eng_s, eng_c, eng_f)
            )
        mon = eng_s.ingest_monitor
        ingest = {
            "anomaly_ticks": mon.anomaly_ticks,
            "recoveries": mon.recoveries,
            "peak_stale_rows": int(
                max(
                    (d["stale_total"] for d in map(_decode_digest, ds)),
                    default=0,
                )
            ),
        }

    verdict = {
        "scenario": name,
        "ok": all(checks.values()),
        "signals": len(signal_set),
        "telegram": len(eng_s._telegram_sent),  # type: ignore[attr-defined]
        "ticks": eng_s.ticks_processed,
        "lines": lines,
        "scan_chunks": eng_c.scan_chunks,
        "scanned_ticks": eng_c.scanned_ticks,
        "overflow_ticks": eng_s.overflow_ticks,
        "scan_overflow_reruns": eng_c.scan_overflow_reruns,
        "routing": routing,
        "outcomes": outcomes,
        "ingest": ingest,
        "checks": checks,
    }
    get_event_log().emit("scenario_run", **verdict)
    verdict["signal_set"] = signal_set  # not in the event: corpus pinning
    return verdict


def _decode_digest(vec):
    from binquant_tpu.engine.step import decode_ingest_digest

    return decode_ingest_digest(vec)


def _outcome_columns(engine) -> dict:
    """Per-scenario outcome summary for the verdict/report: matured-pair
    count plus hit-rate and average MAE/MFE folded over every strategy at
    the LARGEST matured horizon (the scripted aftermath's signature —
    flash-crash entries show deep MAE, pump-frenzy entries fat MFE)."""
    board = engine.outcomes.scoreboard()
    best_h = None
    for by_h in board["per_strategy"].values():
        for h in by_h:
            best_h = max(best_h or 0, int(h))
    if best_h is None:
        return {"matured": 0}
    n = hits = 0
    sum_mae = sum_mfe = 0.0
    for by_h in board["per_strategy"].values():
        cell = by_h.get(str(best_h))
        if not cell or not cell["n"]:
            continue
        n += cell["n"]
        hits += cell["hits"]
        sum_mae += cell["avg_mae"] * cell["n"]
        sum_mfe += cell["avg_mfe"] * cell["n"]
    return {
        "matured": board["matured"],
        "horizon": best_h,
        "n": n,
        "hit_rate": round(hits / n, 3) if n else None,
        "avg_mae": round(sum_mae / n, 5) if n else None,
        "avg_mfe": round(sum_mfe / n, 5) if n else None,
    }


def load_pinned(path: str | Path = PINNED_FIXTURE) -> dict | None:
    path = Path(path)
    if not path.exists():
        return None
    with open(path) as f:
        return json.load(f)


def run_corpus(
    names: list[str] | None = None,
    workdir: str | Path = "/tmp/bqt_scenarios",
    include_slow: bool = True,
    repin: bool = False,
    pinned_path: str | Path = PINNED_FIXTURE,
    chaos: bool = True,
) -> list[dict]:
    """Run the scenario corpus (+ the ws/sink chaos drill) and compare —
    or with ``repin`` rewrite — the pinned signal sets."""
    repin = repin or os.environ.get("BQT_SCENARIO_REPIN") == "1"
    pinned = None if repin else load_pinned(pinned_path)
    if names is None:
        names = [
            n
            for n, sc in SCENARIOS.items()
            if include_slow or not sc.spec.slow
        ]
    verdicts = [run_scenario(n, workdir, pinned=pinned) for n in names]
    if repin:
        # never pin a broken run: a scenario whose invariants failed
        # (drive inequality, routing mismatch, crash-ring entries) must
        # not have its signal set enshrined as the golden corpus
        corpus = {
            v["scenario"]: {
                "signals": [list(t) for t in v["signal_set"]],
                "count": v["signals"],
            }
            for v in verdicts
            if v["ok"]
        }
        skipped = [v["scenario"] for v in verdicts if not v["ok"]]
        if skipped:
            print(f"repin SKIPPED failing scenarios: {', '.join(skipped)}")
        existing = load_pinned(pinned_path) or {}
        existing.update(corpus)
        Path(pinned_path).parent.mkdir(parents=True, exist_ok=True)
        with open(pinned_path, "w") as f:
            json.dump(existing, f, indent=1, sort_keys=True)
            f.write("\n")
    if chaos:
        from binquant_tpu.sim.chaos import ws_chaos_drill

        facts = ws_chaos_drill()
        event = {
            "scenario": "chaos_drill",
            "ok": facts["ok"],
            "signals": 0,
            "ticks": facts["ticks"],
            "routing": {},
            "checks": {
                "stream_landed": facts["landed"],
                "zero_lost_candles": facts["lost_candles"] == 0,
                "engine_ticking": facts["ticks"] > 0,
                "reconnect_storm_ran": facts["reconnect_connects"] >= 3,
                "sink_faults_injected": facts["sink_faults"] > 0,
                "heartbeat_live": facts["heartbeat_live"],
            },
            "ws": facts["ws"],
        }
        get_event_log().emit("scenario_run", **event)
        verdicts.append(event)
        # ISSUE 13: the delivery-plane drill — sink 5xx/timeout storm,
        # scripted breaker cycle, queue-saturation burst, and a process
        # kill/restore with zero autotrade loss and zero duplicates past
        # the delivery dedupe key
        from binquant_tpu.sim.chaos import delivery_chaos_drill

        dfacts = delivery_chaos_drill()
        devent = {
            "scenario": "delivery_drill",
            "ok": dfacts["ok"],
            "signals": dfacts["delivered_autotrade"],
            "ticks": 0,
            "routing": {},
            "checks": dfacts["checks"],
            "delivery": {
                k: dfacts[k]
                for k in (
                    "oracle_autotrade",
                    "delivered_autotrade",
                    "lost_autotrade",
                    "duplicate_keys",
                    "unacked_at_kill",
                    "wal_replayed",
                    "breaker_transitions",
                    "analytics_shed",
                    "emit_ms",
                )
            },
        }
        get_event_log().emit("scenario_run", **devent)
        verdicts.append(devent)
        # ISSUE 14: the fan-out plane drill — subscriber churn storm +
        # stalled broadcast consumer, with device-vs-oracle recipient
        # equality through the churn, counted sheds, an unaffected
        # autotrade consumer group, and a cursor replay of the gap
        from binquant_tpu.sim.chaos import fanout_chaos_drill

        ffacts = fanout_chaos_drill()
        fevent = {
            "scenario": "fanout_drill",
            "ok": ffacts["ok"],
            "signals": ffacts["published"],
            "ticks": ffacts["ticks"],
            "routing": {},
            "checks": ffacts["checks"],
            "fanout": {
                k: ffacts[k]
                for k in (
                    "published",
                    "matched_ticks",
                    "churn_ops",
                    "subscriptions_live",
                    "slot_capacity",
                    "recompiles",
                    "hub_shed",
                    "watcher_frames",
                    "sloth_dropped",
                    "sloth_replayed",
                    "oracle_autotrade",
                    "delivered_autotrade",
                    "emit_ms",
                )
            },
        }
        get_event_log().emit("scenario_run", **fevent)
        verdicts.append(fevent)
    return verdicts


def render_verdict(event: dict) -> str:
    """One scenario_run event → the deterministic report line(s)
    tools/scenario_report.py prints (golden-pinned — keep format changes
    deliberate)."""
    checks = event.get("checks") or {}
    failed = sorted(k for k, v in checks.items() if not v)
    status = "PASS" if event.get("ok") else "FAIL"
    routing = ",".join(
        f"{k}={v}" for k, v in sorted((event.get("routing") or {}).items())
    )
    line = (
        f"{event.get('scenario', '?'):<20} {status}"
        f"  signals {event.get('signals', 0):>4}"
        f"  ticks {event.get('ticks', 0):>4}"
        f"  scan_chunks {event.get('scan_chunks', 0):>3}"
        f"  overflow {event.get('overflow_ticks', 0):>2}"
        f"  routing {routing or '-'}"
    )
    # per-family outcome columns (ISSUE 12) — appended only when the run
    # matured anything, so pre-observatory events render byte-identically
    outcomes = event.get("outcomes") or {}
    if outcomes.get("matured") and outcomes.get("n"):
        line += (
            f"  outcomes h{outcomes['horizon']}"
            f" n {outcomes['n']}"
            f" hit {outcomes['hit_rate']:.3f}"
            f" mae {outcomes['avg_mae']:+.5f}"
            f" mfe {outcomes['avg_mfe']:+.5f}"
        )
    # ingest columns (ISSUE 15) — appended only when a scenario drove
    # with the observatory on, so pre-observatory events render
    # byte-identically
    ingest = event.get("ingest") or {}
    if ingest.get("anomaly_ticks") is not None:
        line += (
            f"  ingest anomalies {ingest['anomaly_ticks']}"
            f" recovered {ingest['recoveries']}"
            f" peak_stale {ingest['peak_stale_rows']}"
        )
    if failed:
        line += f"\n  failed: {', '.join(failed)}"
    return line


def main_cli(arg: str) -> int:
    """``main.py --scenario`` entry: a scenario name, ``all``, or
    ``list``. Prints one verdict line per run; non-zero when any failed."""
    if arg == "list":
        for name, sc in sorted(SCENARIOS.items()):
            print(f"{name:<20} {'[slow] ' if sc.spec.slow else ''}"
                  f"{sc.spec.description}")
        return 0
    if arg == "all":
        verdicts = run_corpus()
    elif arg in SCENARIOS:
        verdicts = [run_scenario(arg, "/tmp/bqt_scenarios", pinned=load_pinned())]
    else:
        print(f"unknown scenario {arg!r}; try --scenario list")
        return 2
    for v in verdicts:
        print(render_verdict(v))
    return 0 if all(v["ok"] for v in verdicts) else 1
