"""Grid-only trading mode (host edge).

Behavioral equivalent of ``/root/reference/market_regime/grid_only_policy.py``
(:121-158): in RANGE/TRANSITIONAL regimes, non-flat market-breadth momentum
flips the engine into "grid ladders only" mode — ladder deploys allowed,
standard bots blocked. The breadth series arrives via REST, so this stays
host-side; the verdict feeds the autotrade gate chain and is mirrored into
the device gate mask by the engine.

Written in this codebase's gate-chain idiom (see
``binquant_tpu/regime/routing.py``): a plain-function decision ladder over
an explicit :class:`BreadthMomentum` reading, returning an immutable
verdict tuple. Reason strings are load-bearing (they ride Telegram and
analytics payloads) and follow the reference's vocabulary exactly.
"""

from __future__ import annotations

from math import isfinite
from typing import Any, NamedTuple

from binquant_tpu.enums import MarketRegimeCode
from binquant_tpu.schemas import MarketBreadthSeries


def _as_finite(value: Any) -> float | None:
    """float(value) if it parses to a finite number, else None."""
    try:
        parsed = float(value)
    except (TypeError, ValueError):
        return None
    return parsed if isfinite(parsed) else None


def timestamp_sort_key(value: Any) -> float | None:
    """Best-effort epoch-SECONDS sort key for mixed timestamp payloads —
    the live analytics API stamps breadth rows with ISO-8601 strings,
    older payloads with epoch numbers (ms or s). Everything lands in one
    comparable unit: numerics ≥1e11 are treated as epoch-ms, ISO strings
    are parsed with naive stamps pinned to UTC (a local-time
    interpretation would shift ordering by the host's UTC offset)."""
    numeric = _as_finite(value)
    if numeric is not None:
        return numeric / 1000.0 if abs(numeric) >= 1e11 else numeric
    if isinstance(value, str):
        from datetime import datetime, timezone

        UTC = timezone.utc  # datetime.UTC alias (3.11+) for py3.10 runtimes
        try:
            parsed = datetime.fromisoformat(value)
        except ValueError:
            return None
        if parsed.tzinfo is None:
            parsed = parsed.replace(tzinfo=UTC)
        return parsed.timestamp()
    return None


def _oldest_to_newest(
    values: list[Any], timestamps: list[Any], *, api_is_newest_first: bool
) -> list[float]:
    """Put a breadth series in oldest→newest order.

    Timestamps win when at least two rows carry usable ones; otherwise the
    raw list is trusted in its API order (the analytics endpoint serves the
    MA series newest-first, hence the reversal fallback)."""
    if len(values) >= 2 and len(timestamps) >= len(values):
        stamped = sorted(
            (
                (key, parsed)
                for ts, raw in zip(timestamps, values)
                if (key := timestamp_sort_key(ts)) is not None
                and (parsed := _as_finite(raw)) is not None
            ),
            key=lambda pair: pair[0],  # stable: ties keep arrival order
        )
        if len(stamped) >= 2:
            return [parsed for _, parsed in stamped]
    cleaned = [parsed for raw in values if (parsed := _as_finite(raw)) is not None]
    return cleaned[::-1] if api_is_newest_first else cleaned


class BreadthMomentum(NamedTuple):
    """The last two usable readings of the preferred breadth series."""

    source: str
    previous: float
    latest: float

    @property
    def leaning(self) -> str:
        """'toward_trend' | 'toward_range' | 'flat' — breadth magnitude
        growing means the market is picking a side; shrinking means it is
        settling into range; equal means no signal."""
        if abs(self.latest) > abs(self.previous):
            return "toward_trend"
        if abs(self.latest) < abs(self.previous):
            return "toward_range"
        return "flat"


def read_breadth_momentum(
    breadth: MarketBreadthSeries | None,
) -> BreadthMomentum | None:
    """Extract the momentum reading, preferring the smoothed MA series and
    falling back to the raw one — both served newest-first by the API."""
    if breadth is None or len(breadth.timestamp) < 2:
        return None
    for source in ("market_breadth_ma", "market_breadth"):
        series = _oldest_to_newest(
            getattr(breadth, source), breadth.timestamp, api_is_newest_first=True
        )
        if len(series) >= 2:
            return BreadthMomentum(source=source, previous=series[-2], latest=series[-1])
    return None


class GridOnlyPolicy(NamedTuple):
    """Immutable verdict of the grid-only decision ladder."""

    allow_grid_ladder: bool = False
    block_standard_bots: bool = False
    reason: str = "not_evaluated"
    direction: str | None = None
    source: str | None = None
    latest: float | None = None
    previous: float | None = None
    momentum_points: float | None = None

    @classmethod
    def disabled(cls, reason: str) -> "GridOnlyPolicy":
        return cls(reason=reason)

    @classmethod
    def active(
        cls, *, direction: str, source: str, latest: float, previous: float
    ) -> "GridOnlyPolicy":
        return cls(
            allow_grid_ladder=True,
            block_standard_bots=True,
            reason=f"breadth_momentum_{direction}_{source}",
            direction=direction,
            source=source,
            latest=latest,
            previous=previous,
            momentum_points=(latest - previous) * 100,
        )

    @classmethod
    def resolve(
        cls, market_regime: int | None, breadth: MarketBreadthSeries | None
    ) -> "GridOnlyPolicy":
        """Decision ladder. ``market_regime`` is the int regime code from
        the device context (None = no context, -1 = context invalid)."""
        if market_regime is None:
            return cls.disabled("market_context_unavailable")
        if market_regime < 0:
            return cls.disabled("market_regime_unavailable")
        regime = MarketRegimeCode(market_regime)
        if regime not in (MarketRegimeCode.RANGE, MarketRegimeCode.TRANSITIONAL):
            return cls.disabled(f"market_regime_{regime.name.lower()}")
        momentum = read_breadth_momentum(breadth)
        if momentum is None:
            return cls.disabled("breadth_momentum_unavailable")
        if momentum.leaning == "flat":
            return cls.disabled("breadth_momentum_flat")
        return cls.active(
            direction=momentum.leaning,
            source=momentum.source,
            latest=momentum.latest,
            previous=momentum.previous,
        )
