"""Benchmark: full-suite tick latency over the symbol batch.

Measures per-tick latency of the jit'd engine step (buffer update →
indicators → market context/regimes → all 14 strategy kernels → packed
wire D2H) at the north-star scale: 2000 symbols × 400-bar windows on one
chip (BASELINE.json: p99 < 50 ms @ 1 s ticks). Prints ONE JSON line:

    {"metric": "tick_p99_ms", "value": N, "unit": "ms", "vs_baseline": R}

``vs_baseline`` is the target budget ratio 50ms/value (>1 beats the
north-star; the reference itself is O(100ms–1s) *per symbol* serial —
SURVEY.md §6 — so any sub-50ms full-batch tick is ≥4 orders of magnitude
over the reference pipeline).

Measurement model: the production loop runs at a 1 s tick cadence with the
device pipelined one tick deep — while tick i computes, the host fetches
tick i-1's packed wire (the single per-tick D2H) and emits its signals.
The primary metric is therefore the steady-state per-tick wall time of
that loop (dispatch i + fetch i-1). The serial end-to-end latency
(dispatch→fetch of the same tick, including the full host↔device round
trip) is reported in ``detail`` as ``e2e_p99_ms``.

``--smoke`` runs tiny shapes for CI/CPU sanity.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def run(num_symbols: int, window: int, ticks: int, warmup: int) -> dict:
    import jax

    from binquant_tpu.engine.buffer import NUM_FIELDS, Field, apply_updates
    from binquant_tpu.engine.step import (
        default_host_inputs,
        initial_engine_state,
        pad_updates,
        tick_step_donated,
        unpack_wire,
    )
    from binquant_tpu.regime.context import ContextConfig

    rng = np.random.default_rng(7)
    cfg = ContextConfig()
    state = initial_engine_state(num_symbols, window=window)

    # preload full windows so the bench measures steady state
    t0 = 1_753_000_200
    px = 20.0 + rng.random(num_symbols).astype(np.float32) * 100

    def make_updates(ts_s: int, px: np.ndarray):
        rows = np.arange(num_symbols, dtype=np.int32)
        ts = np.full(num_symbols, ts_s, dtype=np.int32)
        closes = px * (1 + rng.normal(0, 0.004, num_symbols))
        vals = np.zeros((num_symbols, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = px
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(px, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(px, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(rng.normal(1000, 150, num_symbols))
        vals[:, Field.QUOTE_VOLUME] = vals[:, Field.VOLUME] * closes
        vals[:, Field.NUM_TRADES] = 150
        vals[:, Field.DURATION_S] = 900
        return rows, ts, vals, closes

    for b in range(window):
        rows, ts, vals, px = make_updates(t0 + b * 900, px)
        state = state._replace(
            buf5=apply_updates(state.buf5, rows, ts, vals),
            buf15=apply_updates(state.buf15, rows, ts, vals),
        )
    jax.block_until_ready(state.buf15.values)
    import jax.numpy as jnp

    tracked = jnp.asarray(np.ones(num_symbols, dtype=bool))
    now = t0 + window * 900
    # constant HostInputs leaves built ONCE — re-creating 16 device arrays
    # per tick costs a dozen extra transfers through the tunnel
    base_inputs = default_host_inputs(num_symbols)._replace(
        tracked=tracked, btc_row=np.int32(0)
    )

    def tick_inputs(i: int):
        rows, ts, vals, _ = make_updates(now + i * 900, px)
        upd = pad_updates(rows, ts, vals, size=num_symbols)
        inputs = base_inputs._replace(
            timestamp_s=np.int32(now + i * 900),
            timestamp5_s=np.int32(now + i * 900),
        )
        return upd, inputs

    # warm the compiled step
    for i in range(max(warmup, 1)):
        upd, inputs = tick_inputs(i)
        state, out = tick_step_donated(state, upd, upd, inputs, cfg)
    wire = np.asarray(out.wire)
    fired_w, ctx = unpack_wire(wire)
    assert "market_regime" in ctx and fired_w.n >= 0

    # --- pipelined steady state: dispatch tick i, start its async D2H
    # immediately, and consume tick i-DEPTH's wire (whose transfer has had
    # DEPTH ticks to complete — a blocking fetch pays the full tunnel RTT
    # per tick, serializing the loop at the RTT floor).
    from collections import deque

    # depth must cover (compute + D2H round trip) / per-tick host time so
    # the drained wire's transfer has already completed; ~6 covers a
    # ~100 ms tunneled RTT at ~25 ms ticks (a local chip needs ~1)
    DEPTH = 6
    import gc

    latencies = []
    pending: deque = deque()
    gc.collect()
    gc.disable()
    for i in range(warmup + ticks):
        upd, inputs = tick_inputs(1000 + i)
        start = time.perf_counter()
        # transfer the batch once; passing numpy twice ships it twice
        upd = jax.device_put(upd)
        state, out = tick_step_donated(state, upd, upd, inputs, cfg)
        try:
            out.wire.copy_to_host_async()
        except AttributeError:
            pass
        pending.append(out.wire)
        if len(pending) > DEPTH:
            np.asarray(pending.popleft())
        elapsed = (time.perf_counter() - start) * 1000.0
        if i >= warmup:
            latencies.append(elapsed)
    while pending:
        np.asarray(pending.popleft())
    gc.enable()

    # --- serial end-to-end: dispatch + same-tick wire fetch (full RTT);
    # runs AFTER the pipelined phase so its burst of blocking round trips
    # doesn't eat into any transport rate budget first
    e2e = []
    for i in range(3 + 20):
        upd, inputs = tick_inputs(2000 + i)
        start = time.perf_counter()
        upd = jax.device_put(upd)  # ship the batch once, same as pipelined
        state, out = tick_step_donated(state, upd, upd, inputs, cfg)
        np.asarray(out.wire)  # the ONE per-tick D2H
        elapsed = (time.perf_counter() - start) * 1000.0
        if i >= 3:
            e2e.append(elapsed)

    lat = np.array(latencies)
    e2e = np.array(e2e)
    return {
        "p50_ms": float(np.percentile(lat, 50)),
        "p99_ms": float(np.percentile(lat, 99)),
        "mean_ms": float(lat.mean()),
        "e2e_p50_ms": float(np.percentile(e2e, 50)),
        "e2e_p99_ms": float(np.percentile(e2e, 99)),
        "symbol_evals_per_sec": float(num_symbols * 14 / (lat.mean() / 1000.0)),
    }


def run_config4(num_symbols: int, window: int, ticks: int, warmup: int) -> dict:
    """BASELINE config #4: context scoring across all symbols × 4 timeframes.

    Four timeframe buffers (1m/5m/15m/1h) each get a full market-context
    build (symbol features → aggregates → regime ladders) plus the
    direction-vectorized signal-context scorer over every symbol, all in
    one jit'd step — the batched equivalent of the reference running
    ``market_regime/context_scoring.py`` per symbol per timeframe.
    """
    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import NUM_FIELDS, Field, apply_updates, empty_buffer, fresh_mask
    from binquant_tpu.regime.context import (
        ContextConfig,
        compute_market_context,
        initial_regime_carry,
    )
    from binquant_tpu.regime.scoring import score_signal_candidate

    rng = np.random.default_rng(11)
    cfg = ContextConfig()
    TIMEFRAMES = (60, 300, 900, 3600)
    t0 = 1_753_000_200 - 1_753_000_200 % 3600
    px = 20.0 + rng.random(num_symbols).astype(np.float32) * 100

    def updates(ts_s, px, dur):
        closes = px * (1 + rng.normal(0, 0.004, num_symbols))
        vals = np.zeros((num_symbols, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = px
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(px, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(px, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(rng.normal(1000, 150, num_symbols))
        vals[:, Field.DURATION_S] = dur
        rows = np.arange(num_symbols, dtype=np.int32)
        return rows, np.full(num_symbols, ts_s, np.int32), vals, closes

    bufs, carries = [], []
    for dur in TIMEFRAMES:
        buf = empty_buffer(num_symbols, window)
        p = px.copy()
        for b in range(window):
            rows, ts, vals, p = updates(t0 + b * dur, p, dur)
            buf = apply_updates(buf, rows, ts, vals)
        bufs.append(buf)
        carries.append(initial_regime_carry(num_symbols))
    jax.block_until_ready(bufs[-1].values)

    tracked = jnp.asarray(np.ones(num_symbols, dtype=bool))

    @jax.jit
    def step(bufs, carries, timestamps):
        outs, new_carries = [], []
        for buf, carry, ts in zip(bufs, carries, timestamps):
            fresh = fresh_mask(buf, ts)
            context, carry = compute_market_context(
                buf, fresh, tracked, jnp.int32(0), ts, carry, cfg
            )
            ev = score_signal_candidate(
                context,
                is_short=jnp.asarray(False),
                local_score=jnp.ones((num_symbols,), jnp.float32),
                symbol_rs=context.features.relative_strength_vs_btc,
                symbol_trend=context.features.trend_score,
            )
            outs.append(
                jnp.stack(
                    [
                        context.long_regime_score,
                        context.market_stress_score,
                        jnp.mean(ev.adjusted_score),
                    ]
                )
            )
            new_carries.append(carry)
        return jnp.stack(outs), new_carries

    # Evaluate AT the seeded last bar's timestamp every tick (mid-bucket
    # refinements): advancing the clock without appending bars would make
    # every symbol stale and benchmark the degenerate no-fresh-data path.
    ts_last = [
        jnp.asarray(np.int32(t0 + (window - 1) * dur)) for dur in TIMEFRAMES
    ]

    for _ in range(max(warmup, 1)):
        out, carries = step(bufs, carries, ts_last)
    jax.block_until_ready(out)
    # the context must actually be built (all symbols fresh at ts_last)
    assert np.isfinite(np.asarray(out)).all()

    latencies = []
    for _ in range(ticks):
        start = time.perf_counter()
        out, carries = step(bufs, carries, ts_last)
        np.asarray(out)
        latencies.append((time.perf_counter() - start) * 1000.0)
    lat = np.array(latencies)
    return {
        "p50_ms": float(np.percentile(lat, 50)),
        "p99_ms": float(np.percentile(lat, 99)),
        "scoring_evals_per_sec": float(
            num_symbols * len(TIMEFRAMES) / (lat.mean() / 1000.0)
        ),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="tiny shapes")
    parser.add_argument(
        "--config4",
        action="store_true",
        help="BASELINE config #4: context scoring over symbols x 4 timeframes",
    )
    parser.add_argument("--symbols", type=int, default=2048)
    parser.add_argument("--window", type=int, default=400)
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--warmup", type=int, default=10)
    args = parser.parse_args()

    if args.smoke:
        args.symbols, args.window, args.ticks, args.warmup = 32, 120, 5, 2

    if args.config4:
        stats = run_config4(args.symbols, args.window, args.ticks, args.warmup)
        value = round(stats["p99_ms"], 3)
        print(
            json.dumps(
                {
                    "metric": "context_scoring_4tf_p99_ms",
                    "value": value,
                    "unit": "ms",
                    "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                    "detail": {
                        "symbols": args.symbols,
                        "window": args.window,
                        "timeframes": 4,
                        "p50_ms": round(stats["p50_ms"], 3),
                        "scoring_evals_per_sec": round(
                            stats["scoring_evals_per_sec"]
                        ),
                    },
                }
            )
        )
        return

    stats = run(args.symbols, args.window, args.ticks, args.warmup)
    value = round(stats["p99_ms"], 3)
    print(
        json.dumps(
            {
                "metric": "tick_p99_ms",
                "value": value,
                "unit": "ms",
                "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                "detail": {
                    "symbols": args.symbols,
                    "window": args.window,
                    "p50_ms": round(stats["p50_ms"], 3),
                    "mean_ms": round(stats["mean_ms"], 3),
                    "e2e_p50_ms": round(stats["e2e_p50_ms"], 3),
                    "e2e_p99_ms": round(stats["e2e_p99_ms"], 3),
                    "measurement": "pipelined steady-state (dispatch i + fetch wire i-1); e2e = serial dispatch+fetch",
                    "symbol_strategy_evals_per_sec": round(
                        stats["symbol_evals_per_sec"]
                    ),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
