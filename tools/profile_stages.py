"""Per-stage latency profiler for the tick step (VERDICT r1 item 2/6).

Times each stage of the evaluation pipeline separately (jitted, warmed,
truly D2H-synced — see _sync) at bench scale, plus transfer/RTT costs
that a tunneled device makes dominant. Run:

    python tools/profile_stages.py [--symbols 2048] [--window 400]

Prints a stage table; use it to direct kernel work instead of guessing.
Optionally dumps a jax.profiler trace with --trace <dir>.

Through the tunneled device every stage's timing includes ONE device
round trip (the sync) — subtract the "rtt: tiny jit + D2H fetch" row to
get the stage's own cost; on a local chip the rtt row is ~0.1 ms and the
numbers read directly.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _sync(out):
    """Real device sync: fetch one leaf. jax.block_until_ready is a
    near-no-op through the axon tunnel (it returns before execution
    finishes), which silently turns timings into dispatch-only numbers;
    a D2H fetch on the serial device queue is a true barrier."""
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    if leaves:
        np.asarray(leaves[-1]).ravel()[:1]
    return out


def _bench(fn, *args, iters=8, warmup=2):
    for _ in range(warmup):
        _sync(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        times.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(times)), float(np.max(times))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--symbols", type=int, default=2048)
    parser.add_argument("--window", type=int, default=400)
    parser.add_argument("--trace", type=str, default=None)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import NUM_FIELDS, Field, apply_updates
    from binquant_tpu.engine.step import (
        default_host_inputs,
        initial_engine_state,
        pad_updates,
        tick_step,
    )
    from binquant_tpu.ops.indicators import log_returns, rolling_beta_corr
    from binquant_tpu.regime.context import ContextConfig, compute_market_context
    from binquant_tpu.strategies.features import compute_feature_pack
    from binquant_tpu.strategies.spike_hunter import detect_spikes

    S, W = args.symbols, args.window
    print(f"device={jax.devices()[0].platform} S={S} W={W}", file=sys.stderr)
    rng = np.random.default_rng(7)
    cfg = ContextConfig()
    state = initial_engine_state(S, window=W)
    t0 = 1_753_000_000
    px = 20.0 + rng.random(S).astype(np.float32) * 100

    def make_updates(ts_s, px):
        rows = np.arange(S, dtype=np.int32)
        ts = np.full(S, ts_s, dtype=np.int32)
        closes = px * (1 + rng.normal(0, 0.004, S))
        vals = np.zeros((S, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = px
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(px, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(px, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(rng.normal(1000, 150, S))
        vals[:, Field.QUOTE_VOLUME] = vals[:, Field.VOLUME] * closes
        vals[:, Field.NUM_TRADES] = 150
        vals[:, Field.DURATION_S] = 900
        return rows, ts, vals, closes

    # fill buffers (chunked to keep startup fast)
    for b in range(W):
        rows, ts, vals, px = make_updates(t0 + b * 900, px)
        state = state._replace(
            buf5=apply_updates(state.buf5, rows, ts, vals),
            buf15=apply_updates(state.buf15, rows, ts, vals),
        )
    _sync(state.buf15.values)

    now = t0 + W * 900
    rows, ts, vals, px = make_updates(now, px)
    upd = pad_updates(rows, ts, vals, size=S)
    inputs = default_host_inputs(S)._replace(
        tracked=jnp.asarray(np.ones(S, dtype=bool)),
        btc_row=np.int32(0),
        timestamp_s=np.int32(now),
        timestamp5_s=np.int32(now),
    )
    # device-resident copies for compute-only timings
    upd_dev = jax.device_put(upd)
    inputs_dev = jax.device_put(inputs)
    _sync((upd_dev, inputs_dev))

    results: list[tuple[str, float, float]] = []

    def stage(name, fn, *a, **kw):
        med, mx = _bench(fn, *a, **kw)
        results.append((name, med, mx))
        print(f"{name:38s} p50={med:9.3f} ms  max={mx:9.3f} ms", file=sys.stderr)

    # --- transfer / RTT costs
    tiny = jax.jit(lambda x: x + 1)
    tiny_in = jax.device_put(np.zeros(1, np.float32))
    stage("rtt: tiny jit + D2H fetch", lambda: np.asarray(tiny(tiny_in)))
    stage("h2d: update batch (3 arrays)", lambda: _sync(jax.device_put(upd)))
    stage("h2d: HostInputs (16 leaves)", lambda: _sync(jax.device_put(inputs)))

    # --- compute stages (inputs already on device)
    jitted_apply = jax.jit(apply_updates)
    stage("apply_updates (one buffer)", jitted_apply, state.buf5, *upd_dev)

    jitted_pack = jax.jit(compute_feature_pack)
    stage("compute_feature_pack", jitted_pack, state.buf15)

    # incremental twin: carried indicator state advanced by the newest bar
    # (the live fast path — engine/step.py incremental=True)
    from binquant_tpu.strategies.features import (
        compute_feature_pack_incremental,
        init_feature_carry,
    )

    carry15 = jax.jit(init_feature_carry)(state.buf15)
    _sync(carry15)
    jitted_pack_incr = jax.jit(compute_feature_pack_incremental)
    stage("compute_feature_pack (incremental)", jitted_pack_incr, state.buf15, carry15)

    jitted_spikes = jax.jit(detect_spikes)
    stage("detect_spikes", jitted_spikes, state.buf15)

    fresh = jnp.ones(S, dtype=bool)

    def ctx_fn(buf, fresh, tracked, btc_row, ts, carry):
        return compute_market_context(buf, fresh, tracked, btc_row, ts, carry, cfg)

    jitted_ctx = jax.jit(ctx_fn)
    stage(
        "compute_market_context",
        jitted_ctx,
        state.buf15,
        fresh,
        inputs_dev.tracked,
        inputs_dev.btc_row,
        inputs_dev.timestamp_s,
        state.regime_carry,
    )

    def beta_fn(buf):
        close15 = buf.values[:, :, Field.CLOSE]
        rets = log_returns(close15)
        return rolling_beta_corr(rets, rets[0][None, :], window=50)

    stage("btc beta/corr", jax.jit(beta_fn), state.buf15)

    # --- strategy kernels, each as its own jit over prebuilt packs/context
    pack5 = jitted_pack(state.buf5)
    pack15 = jitted_pack(state.buf15)
    ctx, _ = jitted_ctx(
        state.buf15, fresh, inputs_dev.tracked, inputs_dev.btc_row,
        inputs_dev.timestamp_s, state.regime_carry,
    )
    spikes = jitted_spikes(state.buf15)
    _sync((pack5, pack15, ctx, spikes))

    from binquant_tpu.strategies.activity_burst_pump import activity_burst_pump
    from binquant_tpu.strategies.dormant import (
        bb_extreme_reversion,
        buy_low_sell_high,
        buy_the_dip,
        inverse_price_tracker,
        range_bb_rsi_mean_reversion,
        range_failed_breakout_fade,
        relative_strength_reversal_range,
        supertrend_swing_reversal,
        twap_momentum_sniper,
    )
    from binquant_tpu.strategies.ladder_deployer import ladder_deployer
    from binquant_tpu.strategies.liquidation_sweep_pump import liquidation_sweep_pump
    from binquant_tpu.strategies.mean_reversion_fade import mean_reversion_fade
    from binquant_tpu.strategies.price_tracker import price_tracker
    from binquant_tpu.regime.routing import allows_long_autotrade_mask

    f = jnp.full((S,), jnp.nan, dtype=jnp.float32)
    nan = jnp.asarray(jnp.nan, dtype=jnp.float32)
    long_gate = jax.jit(allows_long_autotrade_mask)(ctx)
    mrf_last = state.mrf_last_emitted
    pt_last = state.pt_last_signal_close

    stage("abp", jax.jit(activity_burst_pump), state.buf5, ctx)
    stage("price_tracker", jax.jit(price_tracker), pack5, ctx, jnp.asarray(False), pt_last)
    stage("liquidation_sweep_pump", jax.jit(liquidation_sweep_pump), state.buf15, ctx, f, nan, nan, nan)
    stage("mean_reversion_fade", jax.jit(mean_reversion_fade), pack15, jnp.asarray(True), mrf_last)
    stage("ladder_deployer", jax.jit(ladder_deployer), pack15, ctx, jnp.asarray(False), jnp.asarray(True))
    stage("supertrend_swing_reversal", jax.jit(supertrend_swing_reversal), state.buf5, pack5, ctx, long_gate, nan, nan, jnp.asarray(False))
    stage("twap_momentum_sniper", jax.jit(twap_momentum_sniper), state.buf15, pack5)
    stage("buy_low_sell_high", jax.jit(buy_low_sell_high), state.buf15, pack15, jnp.asarray(False))
    stage("buy_the_dip", jax.jit(buy_the_dip), state.buf15, pack15, ctx, jnp.asarray(False))
    stage("bb_extreme_reversion", jax.jit(bb_extreme_reversion), state.buf15, pack15, ctx)
    stage("inverse_price_tracker", jax.jit(inverse_price_tracker), pack5, ctx)
    stage("range_bb_rsi_mean_reversion", jax.jit(range_bb_rsi_mean_reversion), state.buf15, pack15, ctx)
    stage("range_failed_breakout_fade", jax.jit(range_failed_breakout_fade), spikes, ctx)
    stage("relative_strength_reversal_range", jax.jit(relative_strength_reversal_range), state.buf15, pack15, ctx)

    # --- end-to-end: full recompute vs the incremental fast path
    def full_dev():
        s2, out = tick_step(state, upd_dev, upd_dev, inputs_dev, cfg)
        return out.summary.trigger

    stage("tick_step (device-resident inputs)", full_dev)

    from binquant_tpu.engine.step import init_indicator_carry

    state_sync = state._replace(
        indicator_carry=jax.jit(
            lambda b5, b15: init_indicator_carry(b5, b15, 0)
        )(state.buf5, state.buf15)
    )
    _sync(state_sync.indicator_carry)

    def incr_dev():
        s2, out = tick_step(
            state_sync, upd_dev, upd_dev, inputs_dev, cfg, incremental=True
        )
        return out.summary.trigger

    stage("tick_step (incremental carry)", incr_dev)

    def full_host():
        s2, out = tick_step(state, upd, upd, inputs, cfg)
        return np.asarray(out.summary.trigger)

    stage("tick_step (host inputs + D2H)", full_host)

    if args.trace:
        with jax.profiler.trace(args.trace):
            for _ in range(3):
                s2, out = tick_step(state, upd_dev, upd_dev, inputs_dev, cfg)
                _sync(out.summary.trigger)
        print(f"trace written to {args.trace}", file=sys.stderr)

    total_compute = sum(m for n, m, _ in results if not n.startswith(("rtt", "h2d", "tick_step")))
    print(f"{'sum of compute stages':38s} p50={total_compute:9.3f} ms", file=sys.stderr)

    by_name = {n: m for n, m, _ in results}
    full_ms = by_name.get("tick_step (device-resident inputs)")
    incr_ms = by_name.get("tick_step (incremental carry)")
    if full_ms and incr_ms:
        print(
            f"{'full vs incremental step':38s} "
            f"{full_ms:9.3f} ms vs {incr_ms:9.3f} ms "
            f"({full_ms / max(incr_ms, 1e-9):.2f}x)",
            file=sys.stderr,
        )
    # fallback accounting the live engine would report for this session —
    # zero here (no engine ran), printed so the obs wiring is visible from
    # the profiling workflow too
    from binquant_tpu.obs.instruments import FULL_RECOMPUTE, TICKS

    recompute = {
        labels: child.value for labels, child in FULL_RECOMPUTE.children()
    }
    print(
        f"bqt_full_recompute_total={recompute or 0} "
        f"bqt_ticks_total={TICKS.value}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
