"""Telegram sink: flood control, pending-set lifecycle, sanitizer edges.

Mirrors the reference's ``tests/test_telegram_consumer.py`` matrix:
retry-after backoff (l.46), dedupe-key parsing from message fields (l.59),
anchor/entity preservation (l.104-110), plus the pending-set release and
the send-lock min-interval the reference serializes under.
"""

import asyncio

import pytest

from binquant_tpu.io.telegram import RetryAfterError, TelegramConsumer


def make_consumer(transport, **kw):
    c = TelegramConsumer(token="", chat_id="chat", transport=transport, **kw)
    c._min_send_interval_seconds = 0.0  # keep tests fast unless testing it
    c._retry_after_pad_seconds = 0.0
    return c


class TestFloodControl:
    def test_retry_after_backoff_then_success(self):
        calls = []

        async def transport(chat_id, text):
            calls.append(text)
            if len(calls) == 1:
                raise RetryAfterError(0.01)

        c = make_consumer(transport)
        asyncio.run(c.send_msg("hello"))
        assert len(calls) == 2  # flood-controlled once, then delivered

    def test_min_interval_spacing(self):
        import time

        stamps = []

        async def transport(chat_id, text):
            stamps.append(time.monotonic())

        c = make_consumer(transport)
        c._min_send_interval_seconds = 0.05

        async def go():
            await c.send_msg("a")
            await c.send_msg("b")

        asyncio.run(go())
        assert stamps[1] - stamps[0] >= 0.05

    def test_transport_errors_never_propagate(self):
        async def transport(chat_id, text):
            raise RuntimeError("boom")

        c = make_consumer(transport)
        asyncio.run(c.send_signal("message"))  # must not raise


class TestPendingSetLifecycle:
    def test_pending_released_after_send_completes(self):
        sent = []

        async def transport(chat_id, text):
            sent.append(text)

        c = make_consumer(transport)
        c._signal_dedupe_seconds = 0.0  # pending-set-only dedupe

        async def go():
            msg = "<strong>#algo1 algorithm</strong> #BTCUSDT\n- Action: buy"
            t1 = c.dispatch_signal(msg)
            t2 = c.dispatch_signal(msg)  # pending -> dropped
            assert t2 is None
            await t1
            # pending released after completion: same key sends again
            t3 = c.dispatch_signal(msg)
            assert t3 is not None
            await t3

        asyncio.run(go())
        assert len(sent) == 2

    def test_cooldown_dedupe_blocks_even_after_completion(self):
        sent = []

        async def transport(chat_id, text):
            sent.append(text)

        c = make_consumer(transport)  # default 900 s cooldown

        async def go():
            msg = "<strong>#algo1 algorithm</strong> #BTCUSDT\n- Action: buy"
            t1 = c.dispatch_signal(msg)
            await t1
            assert c.dispatch_signal(msg) is None  # inside cooldown

        asyncio.run(go())
        assert len(sent) == 1

    def test_distinct_fields_are_distinct_keys(self):
        sent = []

        async def transport(chat_id, text):
            sent.append(text)

        c = make_consumer(transport)

        async def go():
            base = "<strong>#algo1 algorithm</strong> #BTCUSDT\n- Action: {a}"
            t1 = c.dispatch_signal(base.format(a="buy"))
            t2 = c.dispatch_signal(base.format(a="sell"))
            await asyncio.gather(t1, t2)

        asyncio.run(go())
        assert len(sent) == 2

    def test_background_task_set_gc(self):
        async def transport(chat_id, text):
            pass

        c = make_consumer(transport)

        async def go():
            t = c.dispatch_signal("- Action: hold\n#X")
            assert t in c._background_tasks
            await t
            await asyncio.sleep(0)  # let the done-callback run
            assert t not in c._background_tasks

        asyncio.run(go())


class TestSanitizerEdges:
    @pytest.fixture
    def consumer(self):
        async def transport(chat_id, text):
            pass

        return make_consumer(transport)

    def test_anchor_links_preserved(self, consumer):
        out = consumer._sanitize_html('<a href="https://x.y/z?a=1">link</a>')
        assert out == '<a href="https://x.y/z?a=1">link</a>'

    def test_existing_entities_preserved(self, consumer):
        out = consumer._sanitize_html("5 &lt; 6 &amp; 7 &gt; 2")
        assert out == "5 &lt; 6 &amp; 7 &gt; 2"

    def test_unknown_tags_escaped(self, consumer):
        out = consumer._sanitize_html("<script>alert(1)</script><b>ok</b>")
        assert "<script>" not in out
        assert "<b>ok</b>" in out

    def test_raw_angle_operators_escaped(self, consumer):
        out = consumer._sanitize_html("price < 5 and x > 3")
        assert out == "price &lt; 5 and x &gt; 3"
