"""PriceTracker + BBExtremeReversion gate matrices.

Completes the branch coverage of the reference's largest per-strategy
suite (``tests/test_coinrule_price_tracker.py``, 1290 LoC): PriceTracker
cooldown expiry and each autotrade-routing reason, and the
BBExtremeReversion direction-conditioned matrix (enabled via params — the
reference ships it ``ENABLED=False``).
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd

from binquant_tpu.enums import (
    Direction,
    MarketRegimeCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.strategies import bb_extreme_reversion, compute_feature_pack
from binquant_tpu.strategies.dormant import BBXParams
from binquant_tpu.strategies.price_tracker import (
    ROUTE_BREADTH_UNSTABLE,
    ROUTE_STRESS,
    ROUTE_SYMBOL_REGIME,
    ROUTE_TRANSITIONING,
    price_tracker,
)
from tests.conftest import df_from_closes, make_ohlcv
from tests.test_regime_routing_scoring import mk_context, mk_features
from tests.test_strategies_live import (
    S_CAP,
    WINDOW,
    craft_oversold,
    fill_buffer,
)

ENABLED = BBXParams(enabled=True)


def _pt_range_context(**over):
    micro = np.full(S_CAP, int(MicroRegimeCode.RANGE), np.int32)
    feats = over.pop(
        "features",
        mk_features(
            n=S_CAP,
            micro_regime=micro,
            relative_strength_vs_btc=np.full(S_CAP, 0.01, np.float32),
        ),
    )
    base = dict(
        features=feats,
        advancers_ratio=0.55,
        long_tailwind=0.1,
        short_tailwind=-0.05,
        market_stress_score=0.1,
    )
    base.update(over)
    return mk_context(n=S_CAP, **base)


def _oversold_pack():
    rng = np.random.default_rng(79)
    return compute_feature_pack(fill_buffer({0: craft_oversold(rng)}))


class TestPriceTrackerRouting:
    def _fire(self, ctx, carry=None, quiet=False):
        pack = _oversold_pack()
        if carry is None:
            carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
        return price_tracker(pack, ctx, jnp.asarray(quiet), carry)

    def test_uptrend_data_never_fires(self):
        rng = np.random.default_rng(7)
        df = pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.003, drift=0.005))
        pack = compute_feature_pack(fill_buffer({0: df}))
        out, _ = price_tracker(
            pack,
            _pt_range_context(),
            jnp.asarray(False),
            jnp.full((S_CAP,), -1, dtype=jnp.int32),
        )
        assert not bool(out.trigger[0])

    def test_cooldown_boundary_exact_expiry(self):
        pack = _oversold_pack()
        close_time = int(pack.close_time[0])
        ctx = _pt_range_context()
        # one second inside the 12-bar window: still cooling down
        inside = jnp.full((S_CAP,), close_time - 12 * 300 + 1, dtype=jnp.int32)
        out, _ = price_tracker(pack, ctx, jnp.asarray(False), inside)
        assert not bool(out.trigger[0])
        # exactly 12 bars elapsed: cooldown over, fires again
        expired = jnp.full((S_CAP,), close_time - 12 * 300, dtype=jnp.int32)
        out2, _ = price_tracker(pack, ctx, jnp.asarray(False), expired)
        assert bool(out2.trigger[0])

    def test_transitioning_market_blocks_autotrade(self):
        out, _ = self._fire(_pt_range_context(regime_is_transitioning=True))
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == ROUTE_TRANSITIONING

    def test_stress_blocks_autotrade(self):
        out, _ = self._fire(_pt_range_context(market_stress_score=0.31))
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == ROUTE_STRESS

    def test_unstable_breadth_blocks_autotrade(self):
        out, _ = self._fire(_pt_range_context(advancers_ratio=0.70))
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == ROUTE_BREADTH_UNSTABLE

    def test_transitional_micro_blocks_autotrade(self):
        micro = np.full(S_CAP, int(MicroRegimeCode.TRANSITIONAL), np.int32)
        ctx = _pt_range_context(
            features=mk_features(
                n=S_CAP,
                micro_regime=micro,
                relative_strength_vs_btc=np.full(S_CAP, 0.01, np.float32),
            )
        )
        out, _ = self._fire(ctx)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == ROUTE_SYMBOL_REGIME


# ---------------------------------------------------------------------------
# BBExtremeReversion (enabled) — direction-conditioned matrix
# ---------------------------------------------------------------------------


def craft_bbx(direction="buy", extreme=True, pure=True, n=WINDOW):
    """Low-noise base then a 2-bar move: RSI(2) pinned (two same-sign
    deltas when ``pure``) and close at/beyond the band when ``extreme``."""
    close = 100.0 * (1 + 0.001 * np.sin(np.arange(n) * 0.9))
    sign = -1.0 if direction == "buy" else 1.0
    step = 0.02 if extreme else 0.0005
    base = close[n - 3]
    close[n - 2] = base * (1 + sign * step)
    if pure:
        close[n - 1] = close[n - 2] * (1 + sign * step)
    else:  # mixed deltas of comparable size: RSI(2) lands mid-range
        close[n - 1] = close[n - 2] * (1 - sign * 0.01)
    return df_from_closes(close, start_price=100.0)


def run_bbx(df, ctx=None, params=ENABLED):
    buf = fill_buffer({0: df})
    pack = compute_feature_pack(buf)
    return bb_extreme_reversion(buf, pack, ctx or mk_context(n=S_CAP), params)


def strong_features(**over):
    base = dict(micro_regime_strength=np.full(S_CAP, 0.7, np.float32))
    base.update(over)
    return mk_features(n=S_CAP, **base)


class TestBBExtremeMatrix:
    def _range_ctx(self, **over):
        base = dict(features=strong_features())
        base.update(over)
        return mk_context(n=S_CAP, **base)

    def test_buy_at_oversold_below_band(self):
        out = run_bbx(craft_bbx("buy"), self._range_ctx())
        assert bool(out.trigger[0])
        assert int(out.direction[0]) == int(Direction.LONG)
        assert bool(out.autotrade[0])
        assert float(out.diagnostics["rsi2"][0]) <= 5.0
        assert float(out.diagnostics["band_position"][0]) <= 0.0

    def test_sell_at_overbought_above_band(self):
        out = run_bbx(craft_bbx("sell"), self._range_ctx())
        assert bool(out.trigger[0])
        assert int(out.direction[0]) == int(Direction.SHORT)
        assert bool(out.autotrade[0])
        assert float(out.diagnostics["rsi2"][0]) >= 95.0

    def test_disabled_by_default_params(self):
        out = run_bbx(craft_bbx("buy"), self._range_ctx(), params=BBXParams())
        assert not bool(out.trigger[0])

    def test_mixed_deltas_rsi_not_extreme(self):
        out = run_bbx(craft_bbx("buy", pure=False), self._range_ctx())
        assert 5.0 < float(out.diagnostics["rsi2"][0]) < 95.0
        assert not bool(out.trigger[0])

    def test_price_inside_band_blocks(self):
        out = run_bbx(craft_bbx("buy", extreme=False), self._range_ctx())
        assert float(out.diagnostics["band_position"][0]) > 0.0
        assert not bool(out.trigger[0])

    def test_non_range_market_blocks_autotrade(self):
        ctx = self._range_ctx(
            market_regime=np.int32(MarketRegimeCode.TREND_UP)
        )
        out = run_bbx(craft_bbx("buy"), ctx)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])

    def test_stress_blocks_autotrade(self):
        out = run_bbx(
            craft_bbx("buy"), self._range_ctx(market_stress_score=0.5)
        )
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])

    def test_trend_down_micro_blocks_buy_allows_short(self):
        micro = np.full(S_CAP, int(MicroRegimeCode.TREND_DOWN), np.int32)
        ctx = self._range_ctx(features=strong_features(micro_regime=micro))
        buy = run_bbx(craft_bbx("buy"), ctx)
        assert bool(buy.trigger[0]) and not bool(buy.autotrade[0])
        short = run_bbx(craft_bbx("sell"), ctx)
        assert bool(short.trigger[0]) and bool(short.autotrade[0])

    def test_trend_up_micro_blocks_short(self):
        micro = np.full(S_CAP, int(MicroRegimeCode.TREND_UP), np.int32)
        ctx = self._range_ctx(features=strong_features(micro_regime=micro))
        out = run_bbx(craft_bbx("sell"), ctx)
        assert bool(out.trigger[0]) and not bool(out.autotrade[0])

    def test_weak_micro_strength_blocks_autotrade(self):
        ctx = self._range_ctx(
            features=strong_features(
                micro_regime_strength=np.full(S_CAP, 0.3, np.float32)
            )
        )
        out = run_bbx(craft_bbx("buy"), ctx)
        assert bool(out.trigger[0]) and not bool(out.autotrade[0])

    def test_breakdown_transition_blocks_autotrade(self):
        ctx = self._range_ctx(
            features=strong_features(
                micro_transition=np.full(
                    S_CAP, int(MicroTransitionCode.BREAKDOWN), np.int32
                )
            )
        )
        out = run_bbx(craft_bbx("buy"), ctx)
        assert bool(out.trigger[0]) and not bool(out.autotrade[0])

    def test_flat_series_invalid_band_span_no_trigger(self):
        flat = df_from_closes(np.full(WINDOW, 100.0))
        out = run_bbx(flat, self._range_ctx())
        assert not bool(out.trigger[0])  # band_span == 0 -> invalid
