"""BinanceAIReport — external AI-report feature extraction (host-side).

Covers the capability of ``/root/reference/strategies/binance_report_ai.py``:
turn Binance's per-token AI-report JSON into a numeric feature vector, a
directional signal dict, social/community flags, and a ternary verdict.

The implementation is table-driven rather than a run of inline flag
assignments and repeated threshold expressions:

* ``_LEXICON`` declares every keyword flag once — its feature name, its
  bias polarity (bull/bear/neutral), whether it is exported in the feature
  dict, and the phrases that raise it. The phrase lists and thresholds are
  behavior constants shared with the reference; the machinery is not.
* ``ReportDigest`` is the parsed intermediate (freshness, point counts,
  community posts, raised flags) produced by pure functions over the JSON.
* The bull/bear cases are each a tuple of named predicates; the signal
  dict, the fired test, and the final verdict are all derived from those
  two tuples instead of restating the comparisons.

Network access is injected (``fetch``) so tests and offline replay never
touch the network; ``default_fetch`` POSTs to the public endpoint.
"""

from __future__ import annotations

import time
from collections.abc import Callable
from dataclasses import dataclass, field
from math import tanh
from typing import Any

BINANCE_AI_ENDPOINT = (
    "https://www.binance.com/bapi/bigdata/v3/friendly/bigdata/search/ai-report/report"
)
# Simple heuristic for deriving a base token from a trading symbol.
QUOTE_ASSETS = ("USDT", "USDC", "BUSD", "TRY", "EUR", "BTC", "ETH")

DEFAULT_FRESH_MINUTES = 8 * 60


def base_asset_of(symbol: str) -> str:
    """Strip a known quote asset suffix: BTCUSDT -> BTC."""
    plain = symbol.replace("-", "").upper()
    for quote in QUOTE_ASSETS:
        if plain.endswith(quote) and len(plain) > len(quote):
            return plain[: -len(quote)]
    return plain


def default_fetch(symbol: str, token: str) -> dict | None:  # pragma: no cover
    """POST to the Binance AI-report endpoint (reference l.33-57)."""
    import json
    import urllib.request

    body = {
        "lang": "en",
        "token": token,
        "symbol": symbol.upper(),
        "product": "web-spot",
        "timestamp": str(int(time.time() * 1000)),
        "translateToken": None,
    }
    try:
        request = urllib.request.Request(
            BINANCE_AI_ENDPOINT,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(request, timeout=10) as resp:
            return json.loads(resp.read())
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Lexicon: every keyword flag, declared once.
# polarity: +1 feeds bull support, -1 feeds bear pressure, 0 is contextual.
# exported: whether the flag appears in the feature dict (reference exports
# five of the nine; the other four only feed the bias sum).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Cue:
    name: str
    polarity: int
    exported: bool
    phrases: tuple[str, ...]


_LEXICON = (
    _Cue("macd_bullish_flag", +1, True, ("macd", "bullish crossover")),
    _Cue("price_resilience_flag", +1, False, ("resilience", "altcoins", "80-99%")),
    _Cue(
        "institutional_adoption_flag",
        +1,
        False,
        ("institutional", "adoption", "survey"),
    ),
    _Cue("ema_bearish_flag", -1, True, ("ema7", "ema25", "ema99", "bearish")),
    _Cue("outflow_flag", -1, False, ("net outflow", "outflow")),
    _Cue(
        "macro_headwind_flag", -1, False, ("geopolitical", "trade tensions", "tariff")
    ),
    _Cue("volatility_decreasing_flag", 0, True, ("decreasing volatility",)),
    _Cue(
        "coinbase_premium_weak_flag",
        0,
        True,
        ("premium gaps", "weak demand", "coinbase"),
    ),
    _Cue("sentiment_mixed_flag", 0, True, ("mixed sentiment", "mixed outlook")),
)

LARGE_DISCUSSION_POST_COUNT = 10


# ---------------------------------------------------------------------------
# Parsing: pure functions over the report JSON
# ---------------------------------------------------------------------------


def _report_original(report_json: dict) -> dict:
    data = report_json.get("data", {})
    if "report" in data:
        return data.get("report", {}).get("original", {})
    return data.get("original", {})


def _points_of(module: dict) -> list[dict]:
    return module.get("points", []) or []


def _corpus(modules: list[dict]) -> str:
    """All point contents + module overviews, lowercased for matching."""
    texts: list[str] = []
    for module in modules:
        texts.extend(p["content"] for p in _points_of(module) if p.get("content"))
        if module.get("overview"):
            texts.append(module["overview"])
    return " \n ".join(texts).lower()


def _post_citations(modules: list[dict]) -> int:
    posts = 0
    for module in modules:
        if module.get("type") != "community_sentiment":
            continue
        for point in _points_of(module):
            for ref in point.get("citationRefs", []) or []:
                if ref.get("type") == "post":
                    posts += int(ref.get("count", 0))
    return posts


def _point_total(modules: list[dict], module_type: str) -> int:
    return sum(
        len(_points_of(m)) for m in modules if m.get("type") == module_type
    )


@dataclass
class ReportDigest:
    """Parsed intermediate between the raw JSON and the feature dict."""

    age_minutes: float
    fresh: bool
    opportunity_points: int = 0
    risk_points: int = 0
    community_posts: int = 0
    flags: dict[str, int] = field(default_factory=dict)

    @property
    def net_bias(self) -> int:
        return sum(
            cue.polarity * self.flags.get(cue.name, 0) for cue in _LEXICON
        )


def digest_report(
    report_json: dict,
    *,
    now_ms: float,
    max_fresh_minutes: float = DEFAULT_FRESH_MINUTES,
) -> ReportDigest | None:
    original = _report_original(report_json)
    if not original:
        return None

    update_ms = int(original.get("reportMeta", {}).get("updateAt", 0))
    age_minutes = (now_ms - update_ms) / 60000.0 if update_ms else 1e9
    digest = ReportDigest(
        age_minutes=age_minutes, fresh=age_minutes <= max_fresh_minutes
    )
    if not digest.fresh:
        return digest

    modules = original.get("modules", []) or []
    digest.opportunity_points = _point_total(modules, "opportunities")
    digest.risk_points = _point_total(modules, "risks")
    digest.community_posts = _post_citations(modules)
    corpus = _corpus(modules)
    digest.flags = {
        cue.name: int(any(ph.lower() in corpus for ph in cue.phrases))
        for cue in _LEXICON
    }
    return digest


def digest_features(digest: ReportDigest, *, normalize: bool = True) -> dict:
    """The flat feature dict downstream consumers read."""
    features: dict[str, Any] = {
        "external_available": 1,
        "external_stale_flag": int(not digest.fresh),
        "external_age_minutes": round(digest.age_minutes, 2),
    }
    if not digest.fresh:
        return features

    opp, risk = digest.opportunity_points, digest.risk_points
    bias = digest.net_bias
    features.update(
        {
            "opp_count": opp,
            "risk_count": risk,
            "opp_risk_ratio": round((opp + 1) / (risk + 1), 4),
            "net_signal_score": opp - risk,
            "community_post_count": digest.community_posts,
            "large_discussion_flag": int(
                digest.community_posts >= LARGE_DISCUSSION_POST_COUNT
            ),
            "external_net_bias": bias,
            "external_bias_normalized": round(tanh(bias) if normalize else bias, 4),
        }
    )
    features.update(
        {cue.name: digest.flags.get(cue.name, 0) for cue in _LEXICON if cue.exported}
    )
    return features


# ---------------------------------------------------------------------------
# Signal derivation: the bull and bear cases as predicate tables
# ---------------------------------------------------------------------------

# Each entry: (feature key, predicate(features value, thresholds)).
# The bull case fires on strong positive bias, opportunity dominance, and a
# MACD cue; the bear case mirrors it with EMA weakness.
_BULL_CASE = (
    ("external_bias_normalized", lambda v, t: v > t.bias),
    ("opp_risk_ratio", lambda v, t: v > t.opp_risk),
    ("net_signal_score", lambda v, t: v > t.net),
    ("macd_bullish_flag", lambda v, t: v == 1),
)
_BEAR_CASE = (
    ("external_bias_normalized", lambda v, t: v < -t.bias),
    ("opp_risk_ratio", lambda v, t: v < 1),
    ("net_signal_score", lambda v, t: v < -t.net),
    ("ema_bearish_flag", lambda v, t: v == 1),
)

# Which fields land in the signal dict, and when. The ratio rides along
# whenever it exists so consumers always see the opportunity/risk balance.
_SIGNAL_EXPORTS = (
    ("external_bias_normalized", lambda v, t: v > t.bias or v < -t.bias),
    ("opp_risk_ratio", lambda v, t: bool(v)),
    ("net_signal_score", lambda v, t: v > t.net or v < -t.net),
    ("macd_bullish_flag", lambda v, t: v == 1),
    ("ema_bearish_flag", lambda v, t: v == 1),
)

# Social surface: (field, include-in-dict predicate, fires predicate).
_SOCIAL_EXPORTS = (
    ("large_discussion_flag", lambda v: v > 0, lambda v: v > 1),
    ("community_post_count", lambda v: v >= 2, lambda v: v > 1),
    ("sentiment_mixed_flag", lambda v: v > 0, lambda v: v > 1),
    ("coinbase_premium_weak_flag", lambda v: v > 1, lambda v: v > 1),
)

_FEATURE_DEFAULTS = {"opp_risk_ratio": 1}


@dataclass(frozen=True)
class Thresholds:
    bias: float = 0.5
    opp_risk: float = 1.2
    net: int = 1


def _case_votes(features: dict, case, thresholds: Thresholds) -> list[bool]:
    return [
        bool(check(features.get(name, _FEATURE_DEFAULTS.get(name, 0)), thresholds))
        for name, check in case
    ]


class BinanceAIReport:
    """Fetch + digest + derive, per symbol (reference l.11-279)."""

    def __init__(
        self,
        symbol: str,
        base_asset: str = "",
        fetch: Callable[[str, str], dict | None] = default_fetch,
        now_ms: Callable[[], float] | None = None,
    ) -> None:
        self.symbol = symbol.replace("-", "")
        # callers that know the exchange's base asset pass it; otherwise
        # fall back to the quote-suffix heuristic
        self.base_asset = base_asset or base_asset_of(self.symbol)
        self._fetch = fetch
        self._now_ms = now_ms or (lambda: time.time() * 1000)

    def fetch_report(self) -> dict | None:
        if not self.base_asset:
            return None
        return self._fetch(self.symbol, self.base_asset)

    def extract_features(
        self, max_fresh_minutes: int = DEFAULT_FRESH_MINUTES, normalize: bool = True
    ) -> dict | None:
        report_json = self.fetch_report()
        if not report_json:
            return None
        digest = digest_report(
            report_json,
            now_ms=self._now_ms(),
            max_fresh_minutes=max_fresh_minutes,
        )
        if digest is None:
            return None
        return digest_features(digest, normalize=normalize)

    def ai_report_signal(
        self, bias_thr: float = 0.5, opp_risk_thr: float = 1.2, net_score_thr: int = 1
    ) -> dict | None:
        """The notable directional fields, or None when nothing is notable."""
        features = self.extract_features()
        if not features:
            return None
        thresholds = Thresholds(bias_thr, opp_risk_thr, net_score_thr)

        fired = any(_case_votes(features, _BULL_CASE, thresholds)) or any(
            _case_votes(features, _BEAR_CASE, thresholds)
        )
        if not fired:
            return None
        return {
            name: features.get(name, _FEATURE_DEFAULTS.get(name, 0))
            for name, include in _SIGNAL_EXPORTS
            if include(features.get(name, _FEATURE_DEFAULTS.get(name, 0)), thresholds)
        }

    def social_features_flag(self) -> dict | None:
        """Notable social/community context. Polarity is the caller's call:
        mixed sentiment and weak premium signal caution, not bullishness."""
        features = self.extract_features()
        if not features:
            return None
        fired = any(
            fires(features.get(name, 0)) for name, _, fires in _SOCIAL_EXPORTS
        )
        if not fired:
            return None
        return {
            name: features[name]
            for name, include, _ in _SOCIAL_EXPORTS
            if include(features.get(name, 0))
        }

    def final_report(
        self, bias_thr: float = 0.5, opp_risk_thr: float = 1.2, net_score_thr: int = 1
    ) -> int:
        """Ternary verdict: 1 when the whole bull case holds, -1 when the
        whole bear case holds, else 0."""
        features = self.extract_features()
        if not features or not features.get("external_available", 0):
            return 0
        thresholds = Thresholds(bias_thr, opp_risk_thr, net_score_thr)
        if all(_case_votes(features, _BULL_CASE, thresholds)):
            return 1
        if all(_case_votes(features, _BEAR_CASE, thresholds)):
            return -1
        return 0
