"""Composable adversarial-scenario generator (the ISSUE-10 tentpole).

Every scenario is a GARCH base stream (``io/market_sim.py``'s stylized
facts at 15m granularity: Student-t innovations, GARCH(1,1) market factor
plus per-symbol idiosyncratic variance, betas, |r|-coupled volume) with
two layers of composable events on top:

* **array events** edit the (T, S) close/volume paths — flash crashes,
  liquidation cascades, depegs, regime flips — and **bar shapes** craft a
  specific (tick, symbol) bar's OHLC/sub-bars (green hammers, activity
  bursts: the exact recipes the crafted fixtures in ``io/replay.py``
  established);
* **stream events** rewrite the emitted kline stream itself — rewrite
  storms re-delivering corrected old candles, exchange-outage gaps whose
  bars all arrive in one catch-up drain, listing/delisting churn waves —
  using the ``_deliver_bucket`` transport key ``load_klines_by_tick``
  honors.

The output is the exact dual-interval (5m + 15m) ExtendedKline JSONL
every replay lane consumes; ``binquant_tpu/sim/runner.py`` drives each
scenario scanned AND serial through the full engine and checks the
graceful-degradation invariants.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

import numpy as np

from binquant_tpu.io.market_sim import _garch_path
from binquant_tpu.io.replay import kline_record

FIVE_MIN_S = 300
FIFTEEN_MIN_S = 900
# 15m-aligned epoch shared with the crafted fixtures (replay.py)
T0 = 1_780_272_000
assert T0 % FIFTEEN_MIN_S == 0


@dataclass(frozen=True)
class ScenarioSpec:
    """One corpus entry: the market shape, the engine shape it is driven
    at, and the graceful-degradation script the runner asserts."""

    name: str
    description: str
    n_symbols: int = 16
    n_ticks: int = 112  # 15m ticks; events sit past MIN_BARS(=100)
    seed: int = 29
    capacity: int = 32
    window: int = 120
    scan_chunk: int = 32
    # scripted market-breadth series (None = breadth-gated paths dormant)
    breadth: dict | None = None
    # The dispatch set the scenario drives with. Default: the live set
    # MINUS coinrule_price_tracker. The corpus pins EXACT signal-set
    # equality across three differently-compiled drives, and PT's
    # oversold gates (RSI<30 ∧ MACD<0 ∧ MFI<20) on an adversarial
    # oversold-rich stream cross their thresholds INSIDE the drives'
    # f32 accumulation-order spread (measured: carry leaves diverge
    # serial-vs-scanned by ~1e-2 abs after 40 folded ticks, flipping
    # PT ~10x/run) — a rounding lottery, not a semantic signal. PT is
    # carry-owning, so its dedupe/cooldown carries still advance
    # identically; every other strategy sees identical state.
    enabled_strategies: tuple[str, ...] = (
        "activity_burst_pump",
        "grid_ladder",
        "liquidation_sweep_pump",
        "mean_reversion_fade",
    )
    # full-recompute routing reasons that must appear — EXACTLY this set
    # (both drives; the scanned drive's chunk breaks must route the same)
    expect_routing: tuple[str, ...] = ("cold_start",)
    # per-reason minimum counts on top of the set equality
    routing_min: tuple[tuple[str, int], ...] = ()
    # >WIRE_MAX_FIRED compaction overflow expected (and asserted absent
    # when False)
    expect_overflow: bool = False
    min_signals: int = 0
    min_telegram: int = 0  # regime-notifier digests (btc_regime_flip)
    # drive with the ingest-health observatory pinned ON (digest riding
    # every backend's wire + the host monitor; ISSUE 15) — per-tick digest
    # equality across the three drives becomes one more invariant
    ingest: bool = False
    # staleness SLO script: the bqt_ingest stale alarm must TRIP during
    # the scripted fault and CLEAR after catch-up (requires ingest=True)
    expect_ingest_anomaly: bool = False
    # heavy shapes excluded from the tier-1 drill (make scenarios runs all)
    slow: bool = False


@dataclass(frozen=True)
class Scenario:
    spec: ScenarioSpec
    build: Callable[[ScenarioSpec], list[dict]] = field(repr=False)


SCENARIOS: dict[str, Scenario] = {}


def _scenario(spec: ScenarioSpec):
    def wrap(fn):
        SCENARIOS[spec.name] = Scenario(spec=spec, build=fn)
        return fn

    return wrap


def symbol_names(n_symbols: int) -> list[str]:
    return ["BTCUSDT"] + [f"S{i:03d}USDT" for i in range(1, n_symbols)]


# -- the GARCH base stream ---------------------------------------------------


def base_market(
    spec: ScenarioSpec,
    drift_per_tick: float | np.ndarray = 0.0,
    factor_vol: float = 0.002,
) -> tuple[np.ndarray, np.ndarray, np.random.Generator]:
    """(T, S) 15m close + volume paths with market_sim's stylized facts at
    scenario scale. ``drift_per_tick`` (scalar or (T, S)) shapes regimes;
    events then edit the returned arrays in place."""
    rng = np.random.default_rng(spec.seed)
    T, S = spec.n_ticks, spec.n_symbols
    t_df = 4.0
    scale = math.sqrt(t_df / (t_df - 2.0))
    innov_m = rng.standard_t(t_df, size=T) / scale
    innov_i = rng.standard_t(t_df, size=(T, S)) / scale
    r_m = _garch_path(innov_m[:, None], factor_vol, 0.12, 0.85)[:, 0]
    betas = rng.uniform(0.5, 1.6, size=S)
    betas[0] = 1.0  # BTC IS the factor
    idio_vol = rng.uniform(0.001, 0.004, size=S)
    idio_vol[0] = factor_vol * 0.15
    r_i = _garch_path(innov_i, 1.0, 0.12, 0.85)
    r = betas[None, :] * r_m[:, None] + r_i * idio_vol[None, :] + drift_per_tick
    p0 = np.exp(rng.uniform(np.log(0.5), np.log(300.0), size=S))
    p0[0] = 65_000.0
    closes = p0[None, :] * np.cumprod(1.0 + r, axis=0)
    base_v = rng.uniform(np.log(300.0), np.log(3000.0), size=S)
    zscore = np.abs(r) / (betas[None, :] * factor_vol + idio_vol[None, :])
    vols = np.exp(
        base_v[None, :]
        + 0.3 * np.minimum(zscore, 6.0)
        + 0.3 * rng.standard_normal((T, S))
    )
    return closes, vols, rng


# -- bar shapes (the crafted-fixture recipes, reusable per (tick, sym)) ------


def green_hammer(o, c, vol15):
    """MeanReversionFade's prey: deep gap down below the shifted lower
    band, green close, 3x volume (replay.py's single- and market-wide
    hammer recipe)."""
    o2 = o * 0.955
    c2 = o2 * 1.003
    return o2, c2 * 1.001, o2 * 0.997, c2, vol15 * 3.0, None


def tight_bar(o, c, vol15):
    """A bar with ±0.2% wicks — the steady-bleed shape whose stable true
    range keeps MeanReversionFade's ATR-spike veto (atr < 2·atr_ma)
    open for the hammer that follows."""
    return o, max(o, c) * 1.002, min(o, c) * 0.998, c, vol15, None


def activity_burst(o, c, vol15):
    """ActivityBurstPump's prey on the 5m stream: two +0.3% run-up
    sub-bars then a +3% green sub-bar at the highs on 8x volume."""
    subs = []
    sub_o = o
    for j in range(3):
        if j < 2:
            sub_c = sub_o * 1.003
            sh, sl, sv = sub_c * 1.001, sub_o * 0.999, vol15 / 3
        else:
            sub_c = sub_o * 1.03
            sh, sl, sv = sub_c * 1.002, sub_o * 0.998, vol15 / 3 * 8
        subs.append((sub_o, sh, sl, sub_c, sv))
        sub_o = sub_c
    c2 = subs[-1][3]
    high = max(s[1] for s in subs)
    low = min(s[2] for s in subs)
    return o, high, low, c2, vol15 * 2.0, subs


# -- emission: (T, S) paths -> the dual-interval kline stream ----------------


def _interp_sub_bars(o, c, vol15):
    subs = []
    sub_o = o
    for j in range(3):
        sub_c = o + (c - o) * (j + 1) / 3
        sh, sl = max(sub_o, sub_c) * 1.0005, min(sub_o, sub_c) * 0.9995
        subs.append((sub_o, sh, sl, sub_c, vol15 / 3))
        sub_o = sub_c
    return subs


def emit_stream(
    spec: ScenarioSpec,
    closes: np.ndarray,
    vols: np.ndarray,
    shapes: dict | None = None,
) -> list[dict]:
    """The (T, S) paths → a flat ExtendedKline dict stream: one 15m bar +
    three 5m sub-bars per (tick, symbol), the same dual-interval contract
    every crafted fixture uses. ``shapes`` maps (tick, sym) to a bar-shape
    callable ``(open, close, vol15) -> (o, h, l, c, vol15, sub_bars)``;
    its returned close is written back into the path so the next bar's
    open follows the crafted bar."""
    closes = np.array(closes, dtype=float)  # copy: shapes write back
    names = symbol_names(spec.n_symbols)
    shapes = shapes or {}
    out: list[dict] = []
    for t in range(spec.n_ticks):
        ts15 = T0 + t * FIFTEEN_MIN_S
        for s in range(spec.n_symbols):
            c = float(closes[t, s])
            o = float(closes[t - 1, s]) if t else c
            vol15 = float(vols[t, s])
            move = abs(c / o - 1.0) if o else 0.0
            h = max(o, c) * (1.0 + 0.3 * move + 0.0005)
            low = min(o, c) * (1.0 - 0.3 * move - 0.0005)
            sub_bars = None
            shape = shapes.get((t, s))
            if shape is not None:
                o, h, low, c, vol15, sub_bars = shape(o, c, vol15)
                closes[t, s] = c
            out.append(
                kline_record(names[s], ts15, FIFTEEN_MIN_S, o, h, low, c, vol15)
            )
            if sub_bars is None:
                sub_bars = _interp_sub_bars(o, c, vol15)
            for j, (so, sh, sl, sc, sv) in enumerate(sub_bars):
                out.append(
                    kline_record(
                        names[s], ts15 + j * FIVE_MIN_S, FIVE_MIN_S,
                        so, sh, sl, sc, sv,
                    )
                )
    return out


# -- stream events (delivery-scripted faults) --------------------------------


def _bucket0() -> int:
    return T0 // FIFTEEN_MIN_S


def _tick_of(k: dict) -> int:
    return int(k["open_time"]) // 1000 // FIFTEEN_MIN_S - _bucket0()


def rewrite_storm(
    klines: list[dict],
    ticks,
    lag: int = 3,
    per_tick: int = 2,
    shift: float = 0.004,
) -> None:
    """Correction storm: during each storm tick, re-deliver ``per_tick``
    already-applied 15m candles from ``lag`` ticks earlier with shifted
    closes. ``_deliver_bucket`` routes them to the storm tick, so the
    host latest-ts mirror sees a non-append and must route the tick to
    the full recompute (reason ``rewrite``) — in BOTH drives."""
    by_key = {
        (k["symbol"], k["open_time"]): k
        for k in klines
        if (k["close_time"] - k["open_time"]) // 1000 >= FIFTEEN_MIN_S - 1
    }
    syms = sorted({k["symbol"] for k in klines})
    extra = []
    for i, t in enumerate(ticks):
        src_ts = (_bucket0() + t - lag) * FIFTEEN_MIN_S * 1000
        for j in range(per_tick):
            sym = syms[(i * per_tick + j) % len(syms)]
            src = by_key.get((sym, src_ts))
            if src is None:
                continue
            corrected = dict(src)
            corrected["close"] = round(src["close"] * (1.0 + shift), 6)
            corrected["high"] = max(corrected["high"], corrected["close"])
            corrected["_deliver_bucket"] = _bucket0() + t
            extra.append(corrected)
    klines.extend(extra)


def outage(klines: list[dict], gap_ticks: range, recover_tick: int) -> None:
    """Exchange outage: every candle whose bucket falls in ``gap_ticks``
    is delivered in ONE catch-up drain at ``recover_tick``. The engine
    never ticks during the gap (no fresh candles), then folds a
    multi-bucket backlog of clean appends carry-forward — the deep
    ordered-sub-batch drain both the serial fold and the scan plan's
    slot depth must absorb."""
    gap = set(gap_ticks)
    for k in klines:
        if _tick_of(k) in gap:
            k["_deliver_bucket"] = _bucket0() + recover_tick


def listing_churn(
    klines: list[dict],
    listings: dict[int, int],
    delistings: dict[int, int],
    n_symbols: int,
) -> None:
    """Listing/delisting waves: a listed symbol's candles only exist from
    its listing tick (its first drain claims a registry row mid-stream —
    the churn full-recompute route); a delisted symbol goes quiet (its
    row stays, the freshness gate sidelines it)."""
    names = symbol_names(n_symbols)
    keep = []
    for k in klines:
        idx = names.index(k["symbol"])
        t = _tick_of(k)
        if idx in listings and t < listings[idx]:
            continue
        if idx in delistings and t >= delistings[idx]:
            continue
        keep.append(k)
    klines[:] = keep


# -- the corpus ---------------------------------------------------------------


def _bleed_then_hammer(
    closes, vols, shapes, syms, bleed_from, hammer_tick, rate=0.004
):
    """Per-symbol MeanReversionFade setup — the recipe the crafted
    fixtures established: OVERWRITE the symbol's path with a steady
    -0.4%/tick tight-wick bleed (all-red bars pin Wilder RSI(14) low
    while the stable true range keeps the ATR-spike veto open; crafted
    symbols deliberately bypass the scenario's market-wide shock, whose
    ATR spike would veto the reclaim) and steady volume (the hammer's 3x
    must clear the 20-bar volume floor), then the green-hammer bar."""
    for s in syms:
        base = closes[bleed_from - 1, s]
        for k, t in enumerate(range(bleed_from, hammer_tick)):
            closes[t, s] = base * (1.0 - rate) ** (k + 1)
            shapes[(t, s)] = tight_bar
        closes[hammer_tick:, s] = closes[hammer_tick - 1, s]
        vols[bleed_from : hammer_tick + 1, s] = 1000.0
        shapes[(hammer_tick, s)] = green_hammer


@_scenario(
    ScenarioSpec(
        name="flash_crash",
        description="market-wide -7% bar on 8x volume with partial "
        "rebound; four bleeding symbols print capitulation hammers",
        min_signals=1,
    )
)
def _flash_crash(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    crash = spec.n_ticks - 6
    hammer = spec.n_ticks - 1
    closes[crash:] *= 0.93
    closes[crash + 1 :] *= 1.018
    closes[crash + 2 :] *= 1.012
    vols[crash] *= 8.0
    vols[crash + 1] *= 4.0
    shapes: dict = {}
    _bleed_then_hammer(closes, vols, shapes, (2, 5, 9, 12), hammer - 26, hammer)
    return emit_stream(spec, closes, vols, shapes)


@_scenario(
    ScenarioSpec(
        name="liquidation_cascade",
        description="multi-bar market-wide cascade (market_sim's shape) "
        "with volume blowout and partial rebound, then reclaim hammers",
        min_signals=1,
    )
)
def _liquidation_cascade(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    start = spec.n_ticks - 9
    cascade = (-0.022, -0.034, -0.016, 0.013, 0.006)
    for i, dr in enumerate(cascade):
        closes[start + i :] *= 1.0 + dr
    vols[start : start + 5] *= np.array([7.0, 12.0, 8.0, 5.0, 3.0])[:, None]
    shapes: dict = {}
    _bleed_then_hammer(
        closes, vols, shapes, (4, 11), spec.n_ticks - 27, spec.n_ticks - 1
    )
    return emit_stream(spec, closes, vols, shapes)


@_scenario(
    ScenarioSpec(
        name="stablecoin_depeg",
        description="one $1-pinned symbol breaks peg hard (-9% over two "
        "bars on 12x volume, partial re-peg) while a second bleeds off "
        "its peg in a slow staircase ending in the capitulation hammer",
        min_signals=1,
    )
)
def _stablecoin_depeg(spec: ScenarioSpec) -> list[dict]:
    closes, vols, rng = base_market(spec)
    # S003: the hard depeg (ATR spike — MRF's veto must HOLD here)
    s = 3
    closes[:, s] = 1.0 + rng.normal(0.0, 0.0002, spec.n_ticks)
    depeg = spec.n_ticks - 7
    closes[depeg:, s] *= 0.95
    closes[depeg + 1 :, s] *= 0.96
    closes[depeg + 3 :, s] *= 1.05  # partial re-peg
    vols[depeg : depeg + 4, s] *= 12.0
    # S007: the slow staircase depeg ending in the reclaim hammer
    s2 = 7
    closes[:, s2] = 1.0 + rng.normal(0.0, 0.0002, spec.n_ticks)
    shapes: dict = {}
    _bleed_then_hammer(
        closes, vols, shapes, (s2,), spec.n_ticks - 27, spec.n_ticks - 1
    )
    return emit_stream(spec, closes, vols, shapes)


@_scenario(
    ScenarioSpec(
        name="btc_regime_flip",
        description="market-wide trend-up drift flips to trend-down at a "
        "15m bucket boundary — the regime ladder must transition (and "
        "the notifier digest it)",
        min_telegram=1,
    )
)
def _btc_regime_flip(spec: ScenarioSpec) -> list[dict]:
    flip = spec.n_ticks - 10
    drift = np.full((spec.n_ticks, spec.n_symbols), 0.0025)
    drift[flip:] = -0.004
    closes, vols, _rng = base_market(spec, drift_per_tick=drift)
    vols[flip : flip + 3] *= 3.0
    return emit_stream(spec, closes, vols)


@_scenario(
    ScenarioSpec(
        name="rewrite_storm",
        description="two correction-storm pulses (4 + 3 ticks) each "
        "re-deliver corrected copies of already-applied 15m candles — "
        "every storm tick must route to the full recompute "
        "(reason=rewrite) in both drives; the inter-pulse gap leaves a "
        "mid-phase ring cursor for the restore-under-fault drill",
        expect_routing=("cold_start", "rewrite"),
        routing_min=(("rewrite", 6),),
        min_signals=2,
    )
)
def _rewrite_storm(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    shapes: dict = {}
    # one hammer early in the storm and one at the end: the restore-
    # under-fault drill splits mid-storm and needs signals on both sides
    # (both hammers sit past MIN_BARS=100 bars, where strategies arm)
    _bleed_then_hammer(
        closes, vols, shapes, (8,), spec.n_ticks - 36, spec.n_ticks - 10
    )
    _bleed_then_hammer(
        closes, vols, shapes, (6,), spec.n_ticks - 27, spec.n_ticks - 1
    )
    klines = emit_stream(spec, closes, vols, shapes)
    rewrite_storm(
        klines,
        list(range(spec.n_ticks - 12, spec.n_ticks - 8))
        + list(range(spec.n_ticks - 6, spec.n_ticks - 3)),
    )
    return klines


@_scenario(
    ScenarioSpec(
        name="listing_churn",
        description="two listing waves claim registry rows mid-stream "
        "(full-recompute reason=churn re-anchors every carry) and one "
        "symbol delists (goes quiet; freshness sidelines its row)",
        expect_routing=("cold_start", "churn"),
        routing_min=(("churn", 2),),
        min_signals=1,
    )
)
def _listing_churn(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    shapes: dict = {}
    _bleed_then_hammer(
        closes, vols, shapes, (5,), spec.n_ticks - 27, spec.n_ticks - 1
    )
    klines = emit_stream(spec, closes, vols, shapes)
    listing_churn(
        klines,
        listings={10: 30, 11: 30, 12: 45, 13: 45},
        delistings={14: 80},
        n_symbols=spec.n_symbols,
    )
    return klines


@_scenario(
    ScenarioSpec(
        name="cold_start_gap",
        description="six-bucket exchange outage delivered as ONE catch-up "
        "drain: the engine never ticks through the gap, then folds the "
        "multi-bucket backlog carry-forward (clean appends — no "
        "full-recompute reroute)",
        min_signals=1,
    )
)
def _cold_start_gap(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    shapes: dict = {}
    _bleed_then_hammer(
        closes, vols, shapes, (7,), spec.n_ticks - 27, spec.n_ticks - 1
    )
    klines = emit_stream(spec, closes, vols, shapes)
    outage(
        klines,
        gap_ticks=range(spec.n_ticks - 32, spec.n_ticks - 26),
        recover_tick=spec.n_ticks - 26,
    )
    return klines


@_scenario(
    ScenarioSpec(
        name="pump_frenzy",
        description="idiosyncratic pumps: a 5m activity burst (ABP's "
        "prey) plus a +3% 8x-volume 15m pump with BTC momentum up and "
        "rising scripted breadth (LSP's routing engaged)",
        breadth={
            "timestamp": [1, 2, 3, 4],
            "market_breadth": [0.30, 0.34, 0.38, 0.42],
            "market_breadth_ma": [0.30, 0.36],
        },
        min_signals=1,
    )
)
def _pump_frenzy(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    last = spec.n_ticks - 1
    # BTC momentum up into the pump (LSP's long route needs it)
    closes[last, 0] = closes[last - 1, 0] * 1.005
    # S003: +3% 15m pump on 8x volume
    closes[last, 3] = closes[last - 1, 3] * 1.03
    vols[last, 3] *= 8.0
    shapes = {(last, 1): activity_burst}
    return emit_stream(spec, closes, vols, shapes)


@_scenario(
    ScenarioSpec(
        name="fire_burst",
        description=">WIRE_MAX_FIRED burst: a market-wide capitulation "
        "hammer fires MeanReversionFade on 160 symbols in one tick — the "
        "wire overflows, the scanned chunk rewinds and re-drives "
        "serially, and the emitted set stays exact",
        n_symbols=160,
        n_ticks=108,
        seed=23,
        capacity=192,
        window=200,
        expect_overflow=True,
        min_signals=129,
        slow=True,
    )
)
def _fire_burst(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec, factor_vol=0.001)
    last = spec.n_ticks - 1
    shapes: dict = {}
    # EVERY symbol runs the bleed-then-hammer recipe into the same tick:
    # 160 MeanReversionFade fires > WIRE_MAX_FIRED=128 compaction slots
    _bleed_then_hammer(
        closes, vols, shapes, range(spec.n_symbols), last - 26, last
    )
    return emit_stream(spec, closes, vols, shapes)


def btc_withhold(
    klines: list[dict], ticks, recover_tick: int
) -> None:
    """bc_dirty pressure (ROADMAP 5a): withhold ONLY the BTC row's
    candles during ``ticks`` and deliver them in one catch-up drain at
    ``recover_tick`` — every other symbol keeps appending, so each
    withheld 15m bucket is an ASYMMETRIC advance vs the BTC row and the
    beta/corr carry marks every advancing row dirty (engine/step.py
    ``bc_dirty``). Dirty rows decode btc_beta/corr as NaN → the analytics
    payload serializes null (the NaN-decode invariant) until a full
    recompute re-anchors them. The late BTC bars are still strictly-newer
    appends for their row, so routing stays clean (no rewrite reroute) —
    the pressure is purely on the carry's pairing, which is the point."""
    gap = set(ticks)
    for k in klines:
        if k["symbol"] == "BTCUSDT" and _tick_of(k) in gap:
            k["_deliver_bucket"] = _bucket0() + recover_tick


@_scenario(
    ScenarioSpec(
        name="bc_dirty_pressure",
        description="asymmetric BTC-row appends: BTC's candles are "
        "withheld for six mid-stream buckets (every other symbol keeps "
        "appending — the beta/corr carry marks advancing rows dirty and "
        "decodes their BTC posture as NaN/null) and arrive in one "
        "catch-up drain; a capitulation hammer fires INSIDE the dirty "
        "window so emitted analytics carry the null-not-zero invariant",
        min_signals=1,
    )
)
def _bc_dirty_pressure(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    shapes: dict = {}
    # the hammer lands mid-window WHILE the carry is dirty: its emitted
    # analytics record must serialize btc_beta/btc_corr as null
    _bleed_then_hammer(
        closes, vols, shapes, (4, 9), spec.n_ticks - 31, spec.n_ticks - 5
    )
    klines = emit_stream(spec, closes, vols, shapes)
    btc_withhold(
        klines,
        ticks=range(spec.n_ticks - 9, spec.n_ticks - 3),
        recover_tick=spec.n_ticks - 3,
    )
    return klines


def feed_outage(
    klines: list[dict], symbol_idx, ticks, recover_tick: int, n_symbols: int
) -> None:
    """Per-symbol feed death (ISSUE 15): ONLY the listed symbols' candles
    are withheld during ``ticks`` and delivered in one catch-up drain at
    ``recover_tick`` — every other symbol keeps appending, so the engine
    keeps ticking while the dead rows' staleness buckets grow (the
    dominant production failure mode the ingest observatory exists for;
    contrast :func:`outage`, which silences whole buckets so no tick ever
    observes the gap). The late bars are strictly-newer appends for their
    rows, so routing stays clean and the scanned drive stays fused."""
    names = symbol_names(n_symbols)
    dead = {names[i] for i in symbol_idx}
    gap = set(ticks)
    for k in klines:
        if k["symbol"] in dead and _tick_of(k) in gap:
            k["_deliver_bucket"] = _bucket0() + recover_tick


@_scenario(
    ScenarioSpec(
        name="feed_outage",
        description="per-symbol feed death: three symbols' streams go "
        "silent for seven mid-stream buckets while the rest keep "
        "appending — the ingest staleness alarm must trip while they are "
        "dark (bqt_ingest_stale_rows + ingest_anomaly + degraded "
        "/healthz ingest section) and clear after the one-drain catch-up",
        ingest=True,
        expect_ingest_anomaly=True,
        min_signals=1,
    )
)
def _feed_outage(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    shapes: dict = {}
    # the hammer symbol keeps a LIVE feed; the dead rows are elsewhere
    _bleed_then_hammer(
        closes, vols, shapes, (5,), spec.n_ticks - 27, spec.n_ticks - 1
    )
    klines = emit_stream(spec, closes, vols, shapes)
    feed_outage(
        klines,
        symbol_idx=(3, 7, 11),
        ticks=range(spec.n_ticks - 12, spec.n_ticks - 5),
        recover_tick=spec.n_ticks - 5,
        n_symbols=spec.n_symbols,
    )
    return klines


def _breadth_stall_schedule(n_ticks: int) -> dict:
    """Scripted per-bucket market-breadth stream (ROADMAP 5a breadth
    faults): healthy rising readings, then NaN-holed entries (the live
    API nulls individual points), then the stream VANISHES entirely
    (empty payloads — breadth-gated routing loses its inputs mid-run),
    then a recovered rising series. One schedule entry per 15m bucket
    (StubSession consumes them per market-breadth call)."""
    healthy = {
        "timestamp": [1, 2, 3, 4],
        "market_breadth": [0.30, 0.34, 0.38, 0.42],
        "market_breadth_ma": [0.30, 0.36],
    }
    holed = {
        "timestamp": [1, 2, 3, 4],
        "market_breadth": [0.30, None, None, 0.38],
        "market_breadth_ma": [None, None],
    }
    schedule: list = []
    for t in range(n_ticks):
        if t < n_ticks - 32:
            schedule.append(healthy)
        elif t < n_ticks - 24:
            schedule.append(holed)  # NaN holes mid-series
        elif t < n_ticks - 16:
            schedule.append(None)  # stream vanished (empty payload)
        else:
            schedule.append(healthy)  # recovered
    return {"schedule": schedule}


# one tick-count constant shared by the spec AND its breadth schedule —
# the schedule's fault windows are phased against n_ticks, so the two
# must never drift apart
_BREADTH_STALL_TICKS = 112


@_scenario(
    ScenarioSpec(
        name="breadth_stall",
        description="breadth-series fault family (ROADMAP 5a): the "
        "scripted breadth stream degrades mid-run — NaN-holed entries, "
        "then an empty (vanished) series, then recovery — while pumps "
        "and a capitulation hammer fire; the breadth-gated paths must "
        "degrade gracefully and all three drives stay signal-identical",
        n_ticks=_BREADTH_STALL_TICKS,
        breadth=_breadth_stall_schedule(_BREADTH_STALL_TICKS),
        min_signals=1,
    )
)
def _breadth_stall(spec: ScenarioSpec) -> list[dict]:
    closes, vols, _rng = base_market(spec)
    shapes: dict = {}
    # one hammer INSIDE the vanished-breadth window (MRF is not
    # breadth-gated — signals must keep flowing while breadth is dark)
    # and one after recovery
    _bleed_then_hammer(
        closes, vols, shapes, (4,), spec.n_ticks - 46, spec.n_ticks - 20
    )
    _bleed_then_hammer(
        closes, vols, shapes, (9,), spec.n_ticks - 27, spec.n_ticks - 1
    )
    # BTC momentum up + a 15m pump during the healthy tail (LSP's long
    # route re-engages once breadth recovers)
    last = spec.n_ticks - 1
    closes[last, 0] = closes[last - 1, 0] * 1.005
    closes[last, 3] = closes[last - 1, 3] * 1.03
    vols[last, 3] *= 8.0
    return emit_stream(spec, closes, vols, shapes)


def write_scenario_file(scenario: Scenario | str, path: str | Path) -> int:
    """Generate one scenario's kline stream to ``path`` (JSONL, with any
    ``_deliver_bucket`` transport keys); returns the line count."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    klines = scenario.build(scenario.spec)
    with open(path, "w") as f:
        for k in klines:
            f.write(json.dumps(k) + "\n")
    return len(klines)
