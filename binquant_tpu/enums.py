"""Enumerations and integer regime codes.

The TPU engine keeps every categorical as an int32 code inside jit (regime
decision ladders become vectorized comparisons); the string views here are the
host-edge vocabulary that matches the reference's Literal aliases
(``market_regime/models.py:7-42``) so emitted payloads are wire-compatible.
"""

from __future__ import annotations

from enum import Enum, IntEnum
from typing import Literal

direction_type = Literal["LONG", "SHORT"]


class Direction(IntEnum):
    LONG = 0
    SHORT = 1

    @property
    def label(self) -> str:
        return self.name


class MarketRegimeCode(IntEnum):
    """Macro market regime ladder (reference market_regime/regime_transitions.py:92-101)."""

    TRANSITIONAL = 0
    TREND_UP = 1
    TREND_DOWN = 2
    RANGE = 3
    HIGH_STRESS = 4


class MicroRegimeCode(IntEnum):
    """Per-symbol micro regime ladder (reference regime_transitions.py:197-206)."""

    TRANSITIONAL = 0
    TREND_UP = 1
    TREND_DOWN = 2
    RANGE = 3
    VOLATILE = 4


class MarketTransitionCode(IntEnum):
    """Macro transition events (reference regime_transitions.py:234-249)."""

    NONE = 0
    STRESS_SPIKE = 1
    STRESS_RELIEF = 2
    ENTERED_TREND_UP = 3
    ENTERED_TREND_DOWN = 4
    ENTERED_RANGE = 5
    LOST_REGIME_EDGE = 6


class MicroTransitionCode(IntEnum):
    """Micro transition events (reference regime_transitions.py:251-278)."""

    NONE = 0
    VOLATILITY_EXPANSION = 1
    BREAKOUT_UP = 2
    BREAKDOWN = 3
    RECOVERY = 4
    MEAN_REVERSION = 5
    ENTERED_TREND_UP = 6
    ENTERED_TREND_DOWN = 7
    ENTERED_RANGE = 8
    ENTERED_TRANSITIONAL = 9


MarketRegime = Literal[
    "TRANSITIONAL", "TREND_UP", "TREND_DOWN", "RANGE", "HIGH_STRESS"
]
MicroRegime = Literal["TRANSITIONAL", "TREND_UP", "TREND_DOWN", "RANGE", "VOLATILE"]
MarketRegimeTransition = Literal[
    "STRESS_SPIKE",
    "STRESS_RELIEF",
    "ENTERED_TREND_UP",
    "ENTERED_TREND_DOWN",
    "ENTERED_RANGE",
    "LOST_REGIME_EDGE",
]
MicroRegimeTransition = Literal[
    "VOLATILITY_EXPANSION",
    "BREAKOUT_UP",
    "BREAKDOWN",
    "RECOVERY",
    "MEAN_REVERSION",
    "ENTERED_TREND_UP",
    "ENTERED_TREND_DOWN",
    "ENTERED_RANGE",
    "ENTERED_TRANSITIONAL",
]


def market_regime_label(code: int) -> MarketRegime:
    return MarketRegimeCode(int(code)).name  # type: ignore[return-value]


def micro_regime_label(code: int) -> MicroRegime:
    return MicroRegimeCode(int(code)).name  # type: ignore[return-value]


def market_transition_label(code: int) -> str | None:
    c = MarketTransitionCode(int(code))
    return None if c == MarketTransitionCode.NONE else c.name


def micro_transition_label(code: int) -> str | None:
    c = MicroTransitionCode(int(code))
    return None if c == MicroTransitionCode.NONE else c.name


class ExchangeId(str, Enum):
    BINANCE = "binance"
    KUCOIN = "kucoin"


class MarketType(str, Enum):
    """Wire values are UPPERCASE — the pybinbot/binbot analytics contract
    (the reference's own tests pin bot_params.market_type == "FUTURES").
    Parsing is case-insensitive so config/env inputs like "futures" and
    legacy lowercase payloads keep working."""

    SPOT = "SPOT"
    FUTURES = "FUTURES"

    @classmethod
    def _missing_(cls, value):
        if isinstance(value, str):
            upper = value.upper()
            for member in cls:
                if member.value == upper:
                    return member
        return None


class Status(str, Enum):
    inactive = "inactive"
    # a submitted-but-not-yet-opened bot (limit entry resting) — the
    # activation path reports "submitted" vs "opened" on it
    # (reference shared/autotrade.py:326)
    pending = "pending"
    active = "active"
    completed = "completed"
    error = "error"
    archived = "archived"


class Strategy(str, Enum):
    long = "long"
    margin_short = "margin_short"


class DealType(str, Enum):
    base_order = "base_order"
    take_profit = "take_profit"
    stop_loss = "stop_loss"
    short_sell = "short_sell"
    short_buy = "short_buy"
    trailling_profit = "trailling_profit"


class MarketDominance(str, Enum):
    NEUTRAL = "NEUTRAL"
    GAINERS = "GAINERS"
    LOSERS = "LOSERS"


class SignalKind(str, Enum):
    standard = "standard"
    grid_deploy = "grid_deploy"
    notification = "notification"


class KlineInterval(str, Enum):
    """Candle intervals with millisecond arithmetic (pybinbot *KlineIntervals.get_ms())."""

    one_minute = "1m"
    three_minutes = "3m"
    five_minutes = "5m"
    fifteen_minutes = "15m"
    thirty_minutes = "30m"
    one_hour = "1h"
    two_hours = "2h"
    four_hours = "4h"
    six_hours = "6h"
    twelve_hours = "12h"
    one_day = "1d"
    one_week = "1w"

    def get_ms(self) -> int:
        unit = self.value[-1]
        qty = int(self.value[:-1])
        scale = {
            "m": 60_000,
            "h": 3_600_000,
            "d": 86_400_000,
            "w": 604_800_000,
        }[unit]
        return qty * scale

    def bars_per(self, other: "KlineInterval") -> int:
        """How many of `self` fit in `other` (e.g. 15m.bars_per(1h) == 4)."""
        return other.get_ms() // self.get_ms()
