"""Per-tick tracing: span trees, a slow-tick flight recorder, and an
on-demand jax.profiler window.

The metrics registry answers *how often* and the histograms *how slow in
aggregate*; this module answers **why was THIS tick slow** and **which
tick produced THIS order**. Three pieces, all dependency-free (no jax on
the hot path — the profiler import is lazy and only taken when a capture
window is actually requested):

* :class:`TickTrace` / :class:`Span` — one monotonic-clock span tree per
  engine tick (``trace_id`` / ``span_id`` / parent links, attributes,
  status). The pipeline opens the root in ``_dispatch_tick``, carries the
  trace on the ``_PendingTick``, and closes it when the tick finalizes —
  so a trace covers dispatch work, the pipeline dwell, and emission.
* :class:`Tracer` — sampling (``BQT_TRACE_SAMPLE``; deterministic
  accumulator, no RNG, so replays trace the same ticks), a bounded
  in-memory ring of completed traces, and the **flight recorder**: a tick
  whose busy time breaches ``BQT_TRACE_SLOW_MS`` (or whose any span
  errored) is force-emitted to the event log with an engine snapshot and
  attributed to its dominant stage in ``bqt_slow_ticks_total{stage}``.
  Every completed trace also lands as one ``trace`` event (span tree
  inlined) so ``tools/trace_report.py`` can render waterfalls offline.
* :class:`ProfileController` — an on-demand ``jax.profiler`` capture
  window (``/debug/profile?seconds=N`` on the metrics server, or
  SIGUSR2), for XLA-level detail below the host spans.

Budget semantics: a pipelined tick's *wall* time includes up to a full
cadence of intentional dwell between dispatch and finalize, so the
breach check uses **busy** time — the sum of the root's direct children,
which only cover actual work. Both numbers ride the summary.

Sampling OFF (``BQT_TRACE_SAMPLE=0``) must cost nothing on the hot path:
``begin_tick`` returns the shared :data:`NULL_TRACE`, whose ``span`` /
``activate`` are allocation-free no-ops.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Callable

from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import SLOW_TICKS

log = logging.getLogger(__name__)

# Process-unique ids: a random 64-bit base plus an atomic counter — two
# syscall-free hex ids per span instead of an os.urandom round per id.
_IDS = itertools.count(int.from_bytes(os.urandom(8), "big"))


def _next_id(hex_chars: int = 16) -> str:
    return format(next(_IDS) & ((1 << (4 * hex_chars)) - 1), f"0{hex_chars}x")


class Span:
    """One timed operation inside a tick trace."""

    __slots__ = ("name", "span_id", "parent_id", "start", "end", "attrs", "status")

    def __init__(self, name: str, parent_id: str | None) -> None:
        self.name = name
        self.span_id = _next_id(8)
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.end: float | None = None
        self.attrs: dict[str, Any] = {}
        self.status = "ok"

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    @property
    def duration_ms(self) -> float:
        end = self.end if self.end is not None else time.perf_counter()
        return (end - self.start) * 1000.0


class _NullSpan:
    __slots__ = ()

    def set(self, **attrs: Any) -> None:
        pass


class _NullTrace:
    """Shared no-op trace for unsampled ticks: every method is free."""

    __slots__ = ()
    active = False
    trace_id = None
    tick_seq = None

    @contextmanager
    def span(self, name: str, **attrs: Any):
        yield _NULL_SPAN

    def set_attr(self, **attrs: Any) -> None:
        pass

    def mark_error(self, exc: BaseException | None = None) -> None:
        pass

    def record_span(self, name: str, start: float, end: float | None = None,
                    **attrs: Any):
        return _NULL_SPAN

    @contextmanager
    def activate(self):
        yield self


_NULL_SPAN = _NullSpan()
NULL_TRACE = _NullTrace()

# The trace of the tick currently being dispatched/finalized: sink code
# (binbot REST, autotrade events, jit-compile telemetry) reads it to join
# its records back to the producing tick without plumbing a parameter
# through every call signature.
_CURRENT: ContextVar[Any] = ContextVar("bqt_current_trace", default=None)


def current_trace():
    """The active TickTrace of the tick being processed, or None."""
    trace = _CURRENT.get()
    return trace if trace is not None and trace.active else None


def current_trace_id() -> str | None:
    trace = current_trace()
    return None if trace is None else trace.trace_id


@contextmanager
def detached():
    """Clear the current trace for work handed to another task or thread.

    A TickTrace is single-threaded by design (its span stack is
    unsynchronized); background work spawned while a tick's trace is
    still active — the leverage-calibration worker in particular — must
    be created under this guard so its inherited context does not let a
    worker thread race the tick thread's span stack."""
    token = _CURRENT.set(None)
    try:
        yield
    finally:
        _CURRENT.reset(token)


class TickTrace:
    """The span tree of one engine tick (root span ``tick``).

    Spans nest via a stack — tick processing is sequential within a tick
    (dispatch, then finalize), even though several ticks' traces can be
    open at once under pipelining (each rides its own ``_PendingTick``).

    ``status`` semantics: a span that sees an exception is marked errored
    in the tree, but only :meth:`mark_error` — called by the pipeline's
    dispatch/finalize wrappers for exceptions that escape the tick —
    flags the TRACE as errored. Failures a caller deliberately catches
    and tolerates (fire-and-forget analytics, the grid-deploy race) stay
    visible as errored spans without tripping the flight recorder on
    every tick a flaky backend is down.

    ``Tracer.complete`` deactivates the trace: background work that
    inherited it via the contextvar (the leverage-calibration worker's
    REST calls land after the tick is filed) can no longer append spans
    to — or flip the status of — a tree that was already serialized.
    """

    def __init__(self, tick_seq: int, tick_ms: int | None = None) -> None:
        self.active = True
        self.trace_id = _next_id(16)
        self.tick_seq = int(tick_seq)
        self.status = "ok"
        self.root = Span("tick", None)
        if tick_ms is not None:
            self.root.attrs["tick_ms"] = int(tick_ms)
        self.spans: list[Span] = [self.root]
        self._stack: list[Span] = [self.root]

    @contextmanager
    def span(self, name: str, **attrs: Any):
        span = Span(name, self._stack[-1].span_id)
        if attrs:
            span.attrs.update(attrs)
        self.spans.append(span)
        self._stack.append(span)
        try:
            yield span
        except BaseException:
            span.status = "error"
            raise
        finally:
            span.end = time.perf_counter()
            self._stack.pop()

    def set_attr(self, **attrs: Any) -> None:
        self.root.attrs.update(attrs)

    def mark_error(self, exc: BaseException | None = None) -> None:
        """Flag the whole trace errored (force-emits on completion). For
        exceptions that escape the tick's dispatch/finalize — handled
        per-span failures only mark their own span."""
        self.status = "error"
        if exc is not None:
            self.root.attrs["error"] = repr(exc)

    def record_span(
        self, name: str, start: float, end: float | None = None, **attrs: Any
    ) -> Span:
        """A completed span from explicit ``perf_counter`` readings — for
        sections that already time themselves (shared timer, one span)."""
        span = Span(name, self._stack[-1].span_id)
        span.start = start
        span.end = end if end is not None else time.perf_counter()
        if attrs:
            span.attrs.update(attrs)
        self.spans.append(span)
        return span

    @contextmanager
    def activate(self):
        token = _CURRENT.set(self)
        try:
            yield self
        finally:
            _CURRENT.reset(token)

    # -- completion ---------------------------------------------------------

    def finish(self) -> None:
        while len(self._stack) > 1:  # leaked span (error path): close it
            self._stack.pop().end = time.perf_counter()
        if self.root.end is None:
            self.root.end = time.perf_counter()

    def _child_index(self) -> dict[str | None, list[Span]]:
        """parent_id → children, built in ONE pass over the span list —
        a burst tick holds hundreds of spans, and per-node rescans would
        make completion O(n²) on exactly the signal-heavy ticks the
        latency budget cares about. Spans keep insertion order."""
        index: dict[str | None, list[Span]] = {}
        for span in self.spans:
            if span.parent_id is not None:
                index.setdefault(span.parent_id, []).append(span)
        return index

    def children_of(self, span: Span) -> list[Span]:
        return self._child_index().get(span.span_id, [])

    def busy_ms(self) -> float:
        """Work attributable to this tick: the root's direct children.
        Excludes the intentional pipeline dwell between dispatch and
        finalize that the root's wall time includes."""
        return sum(s.duration_ms for s in self.children_of(self.root))

    def dominant_stage(self) -> str:
        """The top-level stage that cost the most — the label a slow-tick
        breach is attributed to (bounded cardinality: stage names are a
        small fixed set)."""
        children = self.children_of(self.root)
        if not children:
            return "untracked"
        return max(children, key=lambda s: s.duration_ms).name

    def tree(self, index: dict[str | None, list[Span]] | None = None) -> dict:
        """The nested span tree, JSON-ready (inlined into trace events)."""
        index = index if index is not None else self._child_index()

        def node(span: Span) -> dict:
            out: dict[str, Any] = {
                "name": span.name,
                "span_id": span.span_id,
                "ms": round(span.duration_ms, 3),
                # offset from the root's start, ms — the timeline
                # exporter's placement anchor (tools/timeline_export.py);
                # synthetic record_span entries can sit before the root
                "t0": round((span.start - self.root.start) * 1000.0, 3),
                "status": span.status,
            }
            if span.attrs:
                out["attrs"] = dict(span.attrs)
            kids = [node(s) for s in index.get(span.span_id, ())]
            if kids:
                out["children"] = kids
            return out

        return node(self.root)


class Tracer:
    """Per-tick trace lifecycle: sampling, the completed-trace ring, and
    the slow-tick flight recorder."""

    def __init__(
        self,
        sample: float = 1.0,
        slow_ms: float = 50.0,
        ring: int = 256,
    ) -> None:
        self.sample = max(float(sample), 0.0)
        self.slow_ms = float(slow_ms)
        self._ring: deque[dict] = deque(maxlen=max(int(ring), 1))
        self._accum = 0.0
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def begin_tick(self, tick_seq: int, tick_ms: int | None = None):
        """A TickTrace for this tick, or NULL_TRACE when sampled out.
        Sampling is a deterministic accumulator (sample=0.25 traces every
        4th tick) — no RNG, so a replayed stream traces the same ticks."""
        if not self.enabled:
            return NULL_TRACE
        self._accum += self.sample
        if self._accum < 1.0:
            return NULL_TRACE
        self._accum -= 1.0
        return TickTrace(tick_seq, tick_ms=tick_ms)

    def complete(
        self, trace, snapshot_fn: Callable[[], dict] | None = None
    ) -> dict | None:
        """Close a tick's trace: ring it, emit the ``trace`` event, and
        run the flight recorder (force-emit + ``bqt_slow_ticks_total``)
        when the busy time breached the budget or any span errored.
        ``snapshot_fn`` is only called on a breach (lazy — the engine
        snapshot is not hot-path work). Deactivates the trace: late
        arrivals from background work that inherited it (contextvar) must
        not mutate an already-serialized tree; double-complete is a
        no-op."""
        if not trace.active:
            return None
        trace.active = False
        trace.finish()
        # one child-index pass serves busy/slowest/dominant/tree alike
        index = trace._child_index()
        stage_spans = index.get(trace.root.span_id, [])
        busy = sum(s.duration_ms for s in stage_spans)
        wall = trace.root.duration_ms
        slowest = (
            max(stage_spans, key=lambda s: s.duration_ms) if stage_spans else None
        )
        summary = {
            "trace_id": trace.trace_id,
            "tick_seq": trace.tick_seq,
            "busy_ms": round(busy, 3),
            "wall_ms": round(wall, 3),
            "status": trace.status,
            "slowest_stage": None if slowest is None else slowest.name,
            "slowest_stage_ms": (
                None if slowest is None else round(slowest.duration_ms, 3)
            ),
            "path": trace.root.attrs.get("path"),
        }
        tree = trace.tree(index)
        with self._lock:
            self._ring.append({"summary": summary, "spans": tree})
        event_log = get_event_log()
        event_log.emit("trace", **summary, spans=tree)
        if trace.status == "error" or busy >= self.slow_ms:
            stage = (
                max(stage_spans, key=lambda s: s.duration_ms).name
                if stage_spans
                else "untracked"
            )
            SLOW_TICKS.labels(stage=stage).inc()
            event_log.emit(
                "slow_tick",
                **summary,
                budget_ms=self.slow_ms,
                stage=stage,
                engine=snapshot_fn() if snapshot_fn is not None else {},
                spans=tree,
            )
        return summary

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def last_tick_trace(self) -> dict | None:
        """The newest completed tick's summary (``/healthz`` block)."""
        with self._lock:
            return dict(self._ring[-1]["summary"]) if self._ring else None


# -- jax.profiler integration -------------------------------------------------

_STEP_ANNOTATION: Any = None  # resolved lazily; False = jax unavailable
_PROFILE_WINDOW = threading.Event()


def profiler_window_active() -> bool:
    """True while an on-demand capture window is open — the pipeline
    annotates device steps during a window even when tick-trace sampling
    is off."""
    return _PROFILE_WINDOW.is_set()


@contextmanager
def step_annotation(step_num: int):
    """``jax.profiler.StepTraceAnnotation`` around the jit step, so XLA
    traces group device work per engine tick; a plain no-op when jax (or
    its profiler) is unavailable."""
    global _STEP_ANNOTATION
    if _STEP_ANNOTATION is None:
        try:
            from jax.profiler import StepTraceAnnotation

            _STEP_ANNOTATION = StepTraceAnnotation
        except Exception:  # pragma: no cover - jax is baked into the image
            _STEP_ANNOTATION = False
    if _STEP_ANNOTATION is False:
        yield
        return
    with _STEP_ANNOTATION("bqt_tick", step_num=int(step_num)):
        yield


_AUTO = object()


class ProfileController:
    """On-demand ``jax.profiler`` capture windows.

    ``start_window(seconds)`` opens one trace window and schedules its
    close (asyncio task when a loop is running — the /debug/profile
    handler; a daemon timer thread otherwise — the SIGUSR2 path in odd
    contexts). One window at a time; the start/stop callables are
    injectable for tests and resolve to ``jax.profiler`` by default.
    """

    MAX_SECONDS = 300.0

    def __init__(
        self,
        log_dir: str = "/tmp/bqt_profile",
        start_fn: Any = _AUTO,
        stop_fn: Any = _AUTO,
    ) -> None:
        self.log_dir = log_dir
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._stop_task: Any = None

    def _resolve(self):
        if self._start_fn is not _AUTO:
            return self._start_fn, self._stop_fn
        try:
            from jax import profiler

            return profiler.start_trace, profiler.stop_trace
        except Exception:  # pragma: no cover - jax is baked into the image
            return None, None

    @property
    def active(self) -> bool:
        return _PROFILE_WINDOW.is_set()

    def start_window(self, seconds: float) -> dict:
        """Open a capture window for ``seconds``; returns a status dict
        (never raises — the exposition layer serves it as JSON)."""
        start, stop = self._resolve()
        if start is None:
            return {"started": False, "reason": "profiler_unavailable"}
        if _PROFILE_WINDOW.is_set():
            return {"started": False, "reason": "already_active"}
        try:
            start(self.log_dir)
        except Exception as exc:
            log.exception("profiler start_trace failed")
            return {"started": False, "reason": f"start_failed: {exc}"}
        _PROFILE_WINDOW.set()
        get_event_log().emit(
            "profile_window", seconds=float(seconds), log_dir=self.log_dir
        )

        def _close() -> None:
            try:
                if stop is not None:
                    stop()
            except Exception:
                log.exception("profiler stop_trace failed")
            finally:
                _PROFILE_WINDOW.clear()

        async def _close_later() -> None:
            try:
                await asyncio.sleep(seconds)
            finally:
                _close()

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            timer = threading.Timer(seconds, _close)
            timer.daemon = True
            timer.start()
            self._stop_task = timer
        else:
            self._stop_task = loop.create_task(_close_later())
        return {
            "started": True,
            "seconds": float(seconds),
            "log_dir": self.log_dir,
        }
