"""Checkpoint/resume: kill-and-restore must reproduce identical next-tick
behavior (VERDICT round-1 item 6; SURVEY.md §5 checkpoint paragraph).

The reference rebuilds all state on restart and pays a 30-minute regime
stability cold-start (``market_regime/regime_routing.py:41-44``). Here the
EngineState pytree + registry + host carries snapshot to one npz; a fresh
engine restored from it must be bitwise-identical going forward.
"""

import asyncio
import json

import numpy as np
import pytest

import jax

from binquant_tpu.io.checkpoint import CheckpointManager, save_state
from binquant_tpu.io.replay import generate_replay_file, make_stub_engine

CAP, WIN = 16, 130  # shared suite shape — tick_step compile cache hit
N_SYMBOLS, N_TICKS = 8, 6


@pytest.fixture(scope="module")
def replay_buckets(tmp_path_factory):
    path = tmp_path_factory.mktemp("ckpt") / "replay.jsonl"
    generate_replay_file(path, n_symbols=N_SYMBOLS, n_ticks=N_TICKS)
    by_bucket: dict[int, list[dict]] = {}
    with open(path) as f:
        for line in f:
            k = json.loads(line)
            by_bucket.setdefault(int(k["open_time"]) // 1000 // 900, []).append(k)
    return by_bucket


def _drive(engine, by_bucket, buckets):
    async def go():
        fired_all = []
        for b in buckets:
            for k in sorted(by_bucket[b], key=lambda k: k["open_time"]):
                engine.ingest(k)
            fired_all.extend(
                await engine.process_tick(now_ms=(b + 1) * 900 * 1000)
            )
        return fired_all

    return asyncio.run(go())


def _leaves(state):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(state)]


def _assert_states_equal(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        assert x.shape == y.shape, f"leaf {i}"
        if np.issubdtype(x.dtype, np.floating):
            np.testing.assert_array_equal(
                np.nan_to_num(x, nan=-9e9), np.nan_to_num(y, nan=-9e9),
                err_msg=f"leaf {i}",
            )
        else:
            np.testing.assert_array_equal(x, y, err_msg=f"leaf {i}")


def test_kill_and_restore_identical_next_tick(replay_buckets, tmp_path):
    buckets = sorted(replay_buckets)
    ckpt = tmp_path / "engine.ckpt.npz"

    # engine A: run all but the final bucket, snapshot, then the final one
    a = make_stub_engine(capacity=CAP, window=WIN)
    _drive(a, replay_buckets, buckets[:-1])
    save_state(ckpt, a.state, a.registry, host_carries=a.host_carries())

    # engine B: cold boot + restore (the "restarted process")
    b = make_stub_engine(capacity=CAP, window=WIN)
    mgr = CheckpointManager(ckpt)
    assert mgr.try_restore(b)
    assert b.ticks_processed == a.ticks_processed
    assert b.registry.to_mapping() == a.registry.to_mapping()
    _assert_states_equal(a.state, b.state)
    ca, cb = a.host_carries(), b.host_carries()
    ca.pop("saved_at_s"), cb.pop("saved_at_s")
    assert ca == cb

    # identical next tick: same fired signals, same resulting device state
    fired_a = _drive(a, replay_buckets, buckets[-1:])
    fired_b = _drive(b, replay_buckets, buckets[-1:])
    key = lambda s: (s.strategy, s.symbol, s.value.direction, s.value.score)
    assert [key(s) for s in fired_a] == [key(s) for s in fired_b]
    _assert_states_equal(a.state, b.state)
    assert a._last_regime == b._last_regime
    assert a._last_emitted == b._last_emitted


def test_restore_preserves_regime_stable_since(replay_buckets, tmp_path):
    """The whole point vs the reference: stable_since survives a restart,
    so routing does not re-impose the 30-minute stability block."""
    buckets = sorted(replay_buckets)
    a = make_stub_engine(capacity=CAP, window=WIN)
    _drive(a, replay_buckets, buckets)
    ckpt = tmp_path / "engine.ckpt.npz"
    save_state(ckpt, a.state, a.registry, host_carries=a.host_carries())

    b = make_stub_engine(capacity=CAP, window=WIN)
    assert CheckpointManager(ckpt).try_restore(b)
    np.testing.assert_array_equal(
        np.asarray(a.state.regime_carry.stable_since),
        np.asarray(b.state.regime_carry.stable_since),
    )


def test_shape_mismatch_starts_cold(replay_buckets, tmp_path):
    buckets = sorted(replay_buckets)
    a = make_stub_engine(capacity=CAP, window=WIN)
    _drive(a, replay_buckets, buckets[:1])
    ckpt = tmp_path / "engine.ckpt.npz"
    save_state(ckpt, a.state, a.registry, host_carries=a.host_carries())

    # a capacity change must refuse the snapshot, not load garbage
    c = make_stub_engine(capacity=CAP * 2, window=WIN)
    mgr = CheckpointManager(ckpt)
    assert not mgr.try_restore(c)
    assert c.ticks_processed == 0
    assert len(c.registry.to_mapping()) == 0


def test_prune_symbols_reconciles_restored_universe(replay_buckets, tmp_path):
    """Universe churn must not leak registry rows across restart cycles
    (stale rows eventually exhaust capacity and crash-loop the boot)."""
    buckets = sorted(replay_buckets)
    a = make_stub_engine(capacity=CAP, window=WIN)
    _drive(a, replay_buckets, buckets[:1])
    ckpt = tmp_path / "engine.ckpt.npz"
    save_state(ckpt, a.state, a.registry, host_carries=a.host_carries())

    b = make_stub_engine(capacity=CAP, window=WIN)
    assert CheckpointManager(ckpt).try_restore(b)
    before = a.registry.to_mapping()
    keep = ["BTCUSDT", "S001USDT"]
    assert b.prune_symbols(keep) == len(before) - 2
    assert set(b.registry.to_mapping()) == set(keep)
    filled5 = np.asarray(b.state.buf5.filled)
    filled15 = np.asarray(b.state.buf15.filled)
    for sym, row in before.items():
        if sym not in keep:
            assert filled5[row] == 0 and filled15[row] == 0
    # freed rows are reusable
    row = b.registry.add("NEWUSDT")
    assert 0 <= row < CAP


def test_missing_file_is_cold_start(tmp_path):
    e = make_stub_engine(capacity=CAP, window=WIN)
    assert not CheckpointManager(tmp_path / "absent.npz").try_restore(e)


def test_maybe_save_cadence(replay_buckets, tmp_path):
    buckets = sorted(replay_buckets)
    e = make_stub_engine(capacity=CAP, window=WIN)
    mgr = CheckpointManager(tmp_path / "cadence.npz", every_ticks=2)
    assert not mgr.maybe_save(e)  # tick 0: nothing to save yet
    _drive(e, replay_buckets, buckets[:1])
    assert e.ticks_processed == 1
    assert not mgr.maybe_save(e)
    _drive(e, replay_buckets, buckets[1:2])
    assert mgr.maybe_save(e)
    assert mgr.path.exists()


def test_v3_archive_migrates_to_v4(replay_buckets, tmp_path):
    """A v3 archive (pre-ring-cursor; right-aligned buffers, no cursor
    leaves) restores into the v4 engine: same leaf layout (v4 strips the
    cursor on save), zero cursors re-attached, identical next tick."""
    import jax

    from binquant_tpu.io.checkpoint import _archive_leaves

    buckets = sorted(replay_buckets)
    a = make_stub_engine(capacity=CAP, window=WIN)
    _drive(a, replay_buckets, buckets[:-1])

    # craft the v3 archive by hand: v4's leaf sequence under version 3
    # (bit-compatible by design — canonicalize-on-save + cursor strip)
    from binquant_tpu.engine.step import canonicalize_state

    leaves = _archive_leaves(canonicalize_state(a.state))
    meta = {
        "version": 3,
        "n_leaves": len(leaves),
        "registry": a.registry.to_mapping(),
        "host_carries": a.host_carries(),
    }
    ckpt = tmp_path / "v3.ckpt.npz"
    np.savez(
        ckpt,
        __meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )

    b = make_stub_engine(capacity=CAP, window=WIN)
    assert CheckpointManager(ckpt).try_restore(b)
    assert np.all(np.asarray(b.state.buf5.cursor) == 0)
    assert np.all(np.asarray(b.state.buf15.cursor) == 0)
    _assert_states_equal(a.state, b.state)
    fired_a = _drive(a, replay_buckets, buckets[-1:])
    fired_b = _drive(b, replay_buckets, buckets[-1:])
    key = lambda s: (s.strategy, s.symbol, s.value.direction, s.value.score)
    assert [key(s) for s in fired_a] == [key(s) for s in fired_b]


def test_save_canonicalizes_mid_phase_cursor(tmp_path):
    """save_state with a MID-PHASE ring cursor canonicalizes: the archive
    holds the right-aligned view, restores with cursor 0, and reads the
    same bars the live ring held."""
    from binquant_tpu.engine.buffer import Field, materialize
    from binquant_tpu.engine.step import (
        apply_updates_step,
        initial_engine_state,
        pad_updates,
    )
    from binquant_tpu.io.checkpoint import load_state
    from binquant_tpu.engine.buffer import SymbolRegistry

    S, W = 4, 8
    state = initial_engine_state(S, window=W)
    for i in range(W + 3):  # wraps the ring past W → cursor mid-phase
        upd = pad_updates(
            np.arange(S, dtype=np.int32),
            np.full(S, 1000 + i, np.int32),
            np.full((S, 10), float(i), np.float32),
            size=S,
        )
        state = apply_updates_step(state, upd, upd)
    assert int(np.asarray(state.buf5.cursor)[0]) == (W + 3) % W != 0

    reg = SymbolRegistry(S)
    for i in range(S):
        reg.add(f"S{i}USDT")
    ckpt = tmp_path / "midphase.ckpt.npz"
    save_state(ckpt, state, reg)

    template = initial_engine_state(S, window=W)
    restored, _ = load_state(ckpt, template, SymbolRegistry(S))
    assert np.all(np.asarray(restored.buf5.cursor) == 0)
    want = materialize(state.buf5)
    np.testing.assert_array_equal(
        np.asarray(restored.buf5.times), np.asarray(want.times)
    )
    np.testing.assert_array_equal(
        np.asarray(restored.buf5.values[:, :, Field.CLOSE]),
        np.asarray(want.values[:, :, Field.CLOSE]),
    )


@pytest.mark.slow
def test_kill_and_restore_mid_phase_cursor_incremental(replay_buckets, tmp_path):
    """Kill-and-restore with the cursor genuinely mid-phase: an
    INCREMENTAL engine's ticks advance the ring without canonicalizing
    (only full/audit ticks do), so the save must canonicalize and the
    restored engine — reading the same values through cursor-relative
    gathers — must produce the identical next tick."""
    buckets = sorted(replay_buckets)
    ckpt = tmp_path / "midphase_incr.ckpt.npz"

    a = make_stub_engine(capacity=CAP, window=WIN, incremental=True)
    _drive(a, replay_buckets, buckets[:-1])
    # the post-cold-start incremental ticks left the ring mid-phase
    assert int(np.asarray(a.state.buf15.cursor).max()) > 0
    save_state(ckpt, a.state, a.registry, host_carries=a.host_carries())

    b = make_stub_engine(capacity=CAP, window=WIN, incremental=True)
    assert CheckpointManager(ckpt).try_restore(b)
    # carry synced → the restored engine continues on the fast path
    assert b._carry_desync_reason is None
    fired_a = _drive(a, replay_buckets, buckets[-1:])
    fired_b = _drive(b, replay_buckets, buckets[-1:])
    key = lambda s: (s.strategy, s.symbol, s.value.direction, s.value.score)
    assert [key(s) for s in fired_a] == [key(s) for s in fired_b]
    from binquant_tpu.engine.step import canonicalize_state

    _assert_states_equal(
        canonicalize_state(a.state), canonicalize_state(b.state)
    )
