"""SpikeHunterV3 — spike detector + breadth-momentum routing, batched.

Re-implements ``/root/reference/strategies/spike_hunter_v3_kucoin.py``'s
detector as one pass over the 15m buffer: per-symbol auto-calibration from
full-window quantiles (l.187-215), volume-cluster / dynamic-quantile
price-break / cumulative-break / acceleration flags (l.308-402), long+short
preliminary labels (l.404-446), and 3-candle streaks (l.480-489) — the
``latest_signal()`` dict (l.504-551) becomes a NamedTuple of (S,) arrays.

Live dispatch of this strategy is disabled in the reference after a
production-validated losing week (``producers/context_evaluator.py:47-52``);
the detector itself stays live because RangeFailedBreakoutFade consumes its
flags, so the kernel is exported standalone.

Notes on live-edge semantics: the reference's ``volume_cluster_label_mode
== "last"`` inspects the *next* bar (``shift(-1)``), which at the live edge
is always absent — the last-bar flag equals the base flag, which is what
this kernel computes. ``post_spike_cooldown_bars`` defaults to 0 (no
suppression), matching l.453-457.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, MarketBuffer
from binquant_tpu.enums import Direction
from binquant_tpu.ops.pallas_rolling import rolling_quantile_tail_auto
from binquant_tpu.ops.rolling import (
    rolling_mean,
    shift,
)
from binquant_tpu.regime.context import MarketContext
from binquant_tpu.strategies.base import StrategyOutputs

# Routing codes (breadth_momentum_direction, l.142-161)
ROUTE_LONG = 0  # "breadth_momentum_up_*"
ROUTE_SHORT = 1  # "breadth_momentum_down_*"
ROUTE_NO_CONTEXT = 2
ROUTE_STRESS = 3
ROUTE_BREADTH_UNAVAILABLE = 4
ROUTE_BREADTH_FLAT = 5
ROUTE_SYMBOL_NOT_CONFIRMED = 6


class SpikeParams(NamedTuple):
    """Thresholds (l.53-77) + auto-calibration knobs (l.187-193)."""

    volume_cluster_min_ratio: float = 1.6
    volume_cluster_window: int = 8
    volume_cluster_min_count: int = 2
    price_break_base_threshold: float = 0.03
    price_break_dynamic_q: float = 0.85
    cumulative_price_window: int = 3
    cumulative_price_threshold: float = 0.025
    accel_volume_deriv_window: int = 3
    accel_volume_deriv_min: float = 0.45
    accel_price_change_min: float = 0.015
    require_bullish_spike: bool = True
    base_window: int = 12  # compute_base_features window
    # auto_calibrate
    calib_volume_quantile: float = 0.97
    calib_price_floor_quantile: float = 0.75
    calib_min_volume_ratio: float = 1.15
    calib_min_price_abs_floor: float = 0.015
    max_market_stress: float = 0.35


class SpikeSignal(NamedTuple):
    """latest_signal() as (S,) arrays (l.528-551)."""

    close: jnp.ndarray
    label: jnp.ndarray  # bool — bullish final spike
    label_short: jnp.ndarray  # bool
    volume_cluster_flag: jnp.ndarray  # bool
    price_break_flag: jnp.ndarray  # bool
    cumulative_price_break_flag: jnp.ndarray  # bool
    accel_spike_flag: jnp.ndarray  # bool
    cumulative_price_break_short_flag: jnp.ndarray  # bool
    accel_spike_short_flag: jnp.ndarray  # bool
    upward: jnp.ndarray  # bool — 3 green candles
    downward: jnp.ndarray  # bool
    volume: jnp.ndarray
    quote_asset_volume: jnp.ndarray
    volume_ratio_threshold: jnp.ndarray  # calibrated per symbol
    price_break_threshold: jnp.ndarray


def _nanquantile_last(x: jnp.ndarray, q: float) -> jnp.ndarray:
    """np.quantile over finite values along the last axis (linear interp)."""
    finite = jnp.isfinite(x)
    cnt = jnp.sum(finite, axis=-1)
    s = jnp.sort(jnp.where(finite, x, jnp.inf), axis=-1)
    W = x.shape[-1]
    rank = q * (cnt - 1.0)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, W - 1)
    hi = jnp.clip(lo + 1, 0, W - 1)
    frac = rank - lo
    v_lo = jnp.take_along_axis(s, lo[..., None], axis=-1)[..., 0]
    v_hi = jnp.take_along_axis(
        s, jnp.minimum(hi, jnp.maximum(cnt - 1, 0))[..., None], axis=-1
    )[..., 0]
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(cnt > 0, out, jnp.nan)


def detect_spikes(buf15: MarketBuffer, params: SpikeParams = SpikeParams()) -> SpikeSignal:
    """The full detector (detect() l.492-502), last-bar outputs.

    Only the last bar is consumed downstream, so every flag is computed on
    its trailing slice; the sole full-window work is the auto-calibration
    quantiles over the whole (S, W) distribution — the round-1 version
    materialized and sorted an (S, W, 60) windowed view per tick for a
    single consumed row.
    """
    p = params
    close = buf15.values[:, :, Field.CLOSE]
    open_ = buf15.values[:, :, Field.OPEN]
    volume = buf15.values[:, :, Field.VOLUME]

    price_change = close / shift(close, 1) - 1.0
    price_change_abs = jnp.abs(price_change)
    volume_ma = rolling_mean(volume, p.base_window)
    volume_ratio = volume / (volume_ma + 1e-6)
    pc_last = price_change[:, -1]
    pc_abs_last = price_change_abs[:, -1]
    vr_last = volume_ratio[:, -1]

    # --- auto-calibration from full-window distributions (l.187-215)
    vol_thr = jnp.maximum(
        p.calib_min_volume_ratio,
        _nanquantile_last(volume_ratio, p.calib_volume_quantile),
    )
    vol_thr = jnp.where(jnp.isfinite(vol_thr), vol_thr, p.volume_cluster_min_ratio)
    price_floor = jnp.maximum(
        p.calib_min_price_abs_floor,
        _nanquantile_last(price_change_abs, p.calib_price_floor_quantile),
    )
    price_floor = jnp.maximum(
        p.price_break_base_threshold,
        jnp.where(jnp.isfinite(price_floor), price_floor, 0.0),
    )

    # --- volume cluster at the live edge (l.308-318): count of threshold
    # crossings in the trailing cluster window (>=1 finite sample)
    vrw = volume_ratio[:, -p.volume_cluster_window:]
    finite_vrw = jnp.isfinite(vrw)
    cond_w = vrw >= vol_thr[:, None]
    cluster_count = jnp.sum(jnp.where(finite_vrw, cond_w, False), axis=-1)
    has_any = jnp.any(finite_vrw, axis=-1)
    vc_flag = (
        has_any
        & (cluster_count >= p.volume_cluster_min_count)
        & (vr_last >= vol_thr)
    )

    # --- dynamic price break (l.320-358): trailing 60-bar quantile only
    # (same backend dispatch as ABP's threshold — the two hot tail
    # quantiles must route identically for BQT_ENABLE_PALLAS A/Bs)
    dyn = rolling_quantile_tail_auto(
        price_change_abs, 60, p.price_break_dynamic_q, num_out=1, min_periods=20
    )[:, -1]
    thr = jnp.maximum(price_floor, dyn)  # NaN dyn -> NaN (pre-warmup)
    pb_flag = pc_abs_last >= thr

    # --- cumulative break (l.360-379) over the trailing w bars
    w = p.cumulative_price_window
    pcw = price_change[:, -w:]
    finite_pcw = jnp.isfinite(pcw)
    full_w = jnp.sum(finite_pcw, axis=-1) >= w  # min_periods == window
    cum_pos = jnp.sum(jnp.where(finite_pcw, jnp.maximum(pcw, 0.0), 0.0), axis=-1)
    cum_neg = jnp.sum(
        jnp.where(finite_pcw, jnp.abs(jnp.minimum(pcw, 0.0)), 0.0), axis=-1
    )
    vrw3 = volume_ratio[:, -w:]
    finite_vrw3 = jnp.isfinite(vrw3)
    vol_cond = (jnp.sum(finite_vrw3, axis=-1) >= w) & jnp.any(
        finite_vrw3 & (vrw3 >= vol_thr[:, None] * 0.8), axis=-1
    )
    cum_flag = full_w & (cum_pos >= p.cumulative_price_threshold) & vol_cond
    cum_short_flag = full_w & (cum_neg >= p.cumulative_price_threshold) & vol_cond

    # --- acceleration (l.381-402)
    k = p.accel_volume_deriv_window
    vr_lag = volume_ratio[:, -1 - k] if volume_ratio.shape[-1] > k else jnp.full_like(vr_last, jnp.nan)
    vol_deriv = vr_last - vr_lag
    accel_base = (vol_deriv >= p.accel_volume_deriv_min) & (
        pc_abs_last >= p.accel_price_change_min
    )
    accel_flag = accel_base & (pc_last > 0)
    accel_short_flag = accel_base & (pc_last < 0)

    # --- labels (l.404-446); require_both_patterns=False default
    base_combo = vc_flag | pb_flag
    bullish = close[:, -1] > open_[:, -1]
    bearish = close[:, -1] < open_[:, -1]
    label = base_combo | cum_flag | accel_flag
    if p.require_bullish_spike:
        label = label & bullish
    label_short = (base_combo | cum_short_flag | accel_short_flag) & bearish

    # --- streaks (l.480-489): all of the last 3 candles green/red
    upward = jnp.all(close[:, -3:] > open_[:, -3:], axis=-1)
    downward = jnp.all(close[:, -3:] < open_[:, -3:], axis=-1)

    return SpikeSignal(
        close=close[:, -1],
        label=label & (buf15.filled > 0),
        label_short=label_short & (buf15.filled > 0),
        volume_cluster_flag=vc_flag,
        price_break_flag=pb_flag,
        cumulative_price_break_flag=cum_flag,
        accel_spike_flag=accel_flag,
        cumulative_price_break_short_flag=cum_short_flag,
        accel_spike_short_flag=accel_short_flag,
        upward=upward,
        downward=downward,
        volume=buf15.values[:, -1, Field.VOLUME],
        quote_asset_volume=buf15.values[:, -1, Field.QUOTE_VOLUME],
        volume_ratio_threshold=vol_thr,
        price_break_threshold=jnp.where(jnp.isfinite(thr), thr, price_floor),
    )


def spike_hunter(
    spikes: SpikeSignal,
    context: MarketContext,
    breadth_momentum_points: jnp.ndarray,  # scalar f32, NaN = unavailable
    params: SpikeParams = SpikeParams(),
) -> StrategyOutputs:
    """Full strategy: breadth-momentum direction (l.142-161) + symbol spike
    confirmation (l.163-185). Kept for capability parity — live dispatch is
    disabled in the reference (context_evaluator.py:460-469)."""
    has_context = context.valid
    stress_ok = context.market_stress_score < params.max_market_stress
    has_momentum = jnp.isfinite(breadth_momentum_points)
    go_long = has_momentum & (breadth_momentum_points > 0.0)
    go_short = has_momentum & (breadth_momentum_points < 0.0)

    long_confirm = (
        spikes.cumulative_price_break_flag
        | spikes.volume_cluster_flag
        | spikes.accel_spike_flag
    ) & spikes.upward
    short_confirm = (
        spikes.cumulative_price_break_short_flag
        | spikes.volume_cluster_flag
        | spikes.accel_spike_short_flag
    ) & spikes.downward

    fired = (
        has_context
        & stress_ok
        & ((go_long & long_confirm) | (go_short & short_confirm))
    )
    S = spikes.close.shape[0]
    direction = jnp.broadcast_to(
        jnp.where(go_short, Direction.SHORT, Direction.LONG).astype(jnp.int32), (S,)
    )
    route = jnp.where(
        ~has_context,
        ROUTE_NO_CONTEXT,
        jnp.where(
            ~stress_ok,
            ROUTE_STRESS,
            jnp.where(
                ~has_momentum,
                ROUTE_BREADTH_UNAVAILABLE,
                jnp.where(
                    go_long,
                    jnp.where(long_confirm, ROUTE_LONG, ROUTE_SYMBOL_NOT_CONFIRMED),
                    jnp.where(
                        go_short,
                        jnp.where(
                            short_confirm, ROUTE_SHORT, ROUTE_SYMBOL_NOT_CONFIRMED
                        ),
                        ROUTE_BREADTH_FLAT,
                    ),
                ),
            ),
        ),
    ).astype(jnp.int32)
    route = jnp.broadcast_to(route, (S,))

    return StrategyOutputs(
        trigger=fired,
        direction=direction,
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=fired,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "route": route,
            "volume": spikes.volume,
            "quote_asset_volume": spikes.quote_asset_volume,
            "upward": spikes.upward,
            "downward": spikes.downward,
        },
    )
