"""pytest plugin that lets the REFERENCE's own test suite run here.

Usage (see ``tools/run_reference_suite.py``):

    pytest /root/reference/tests -p binquant_tpu.refdiff.pytest_plugin

Two jobs:

* install the pybinbot/pandera/telegram/dotenv shims BEFORE the reference
  conftest imports them — so the reference's 300-odd unit tests execute
  against THIS repo's SDK-surface replica (``binquant_tpu.schemas`` et
  al.), turning the reference suite into a behavioral-compatibility check
  of that replica;
* run ``async def`` tests (the reference uses pytest-asyncio, not
  installed in this environment) with a minimal asyncio runner.
"""

from __future__ import annotations

import asyncio
import inspect

from binquant_tpu.refdiff.shims import install_shims

install_shims()


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
