"""Transport/sink fault injection for the chaos lane.

Exploits the seams the io layer already exposes instead of monkeypatching:
the websocket connectors take an injectable ``connect`` factory
(:class:`FaultyConnectFactory` scripts disconnect storms, malformed and
partial frames, refused/delayed reconnects), ``BinbotApi`` takes an
injectable session (:class:`FlakySession` injects 5xx and timeout storms
around the replay stub), and ``TelegramConsumer`` takes an injectable
transport (:func:`flaky_transport`).

:func:`ws_chaos_drill` is the end-to-end drill `make scenarios` runs: a
real ``KlinesConnector`` + ``SignalEngine.consume_loop`` stack under a
scripted disconnect storm, garbage frames, AND flaky sinks — asserting
the engine keeps ticking, the heartbeat stays live, and ZERO closed
candles are lost across the reconnects.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any

import numpy as np


class ScriptedWs:
    """One scripted websocket session: an async context manager + async
    frame iterator driven by an event list:

    * ``("frame", payload)`` — yield one raw frame;
    * ``("drop", msg)``      — raise (the connector reconnects);
    * ``("sleep", seconds)`` — stall the stream;
    * ``("idle",)``          — stay connected, delivering nothing.
    """

    def __init__(self, events: list[tuple]) -> None:
        self._events = list(events)
        self.sent: list[str] = []

    async def __aenter__(self) -> "ScriptedWs":
        return self

    async def __aexit__(self, *exc) -> bool:
        return False

    async def send(self, payload: str) -> None:
        self.sent.append(payload)

    def __aiter__(self) -> "ScriptedWs":
        return self

    async def __anext__(self) -> str:
        while self._events:
            kind, *args = self._events[0]
            if kind == "frame":
                self._events.pop(0)
                return args[0]
            if kind == "sleep":
                self._events.pop(0)
                await asyncio.sleep(args[0])
                continue
            if kind == "drop":
                self._events.pop(0)
                raise ConnectionError(args[0] if args else "scripted drop")
            if kind == "idle":
                await asyncio.sleep(3600.0)
            else:  # unknown event: skip rather than wedge the drill
                self._events.pop(0)
        raise StopAsyncIteration


class RefusedConnect:
    """A connect attempt that fails at the handshake — the delayed-
    reconnect case (exchange still down when the client retries)."""

    def __init__(self, msg: str = "scripted connection refused") -> None:
        self.msg = msg

    async def __aenter__(self):
        raise ConnectionError(self.msg)

    async def __aexit__(self, *exc) -> bool:
        return False


class FaultyConnectFactory:
    """Injectable ``connect`` for the connectors: each call hands out the
    next scripted session; exhausted scripts idle connected so the drill
    ends with a healthy stream."""

    def __init__(self, sessions: list[Any]) -> None:
        self._sessions = list(sessions)
        self.connects = 0

    def __call__(self, url: str, **_kw):
        self.connects += 1
        if self._sessions:
            return self._sessions.pop(0)
        return ScriptedWs([("idle",)])


def binance_frame(k: dict) -> str:
    """One closed-candle Binance kline frame for an ExtendedKline dict —
    the inverse of ``parse_binance_kline_frame``'s field mapping."""
    return json.dumps(
        {
            "e": "kline",
            "k": {
                "s": k["symbol"],
                "t": k["open_time"],
                "T": k["close_time"],
                "x": True,
                "o": str(k["open"]),
                "h": str(k["high"]),
                "l": str(k["low"]),
                "c": str(k["close"]),
                "v": str(k["volume"]),
                "q": str(k.get("quote_asset_volume", 0.0)),
                "n": k.get("number_of_trades", 0.0),
                "V": str(k.get("taker_buy_base_volume", 0.0)),
                "Q": str(k.get("taker_buy_quote_volume", 0.0)),
            },
        }
    )


GARBAGE_FRAMES = (
    "{not json at all",
    '{"e": "kline", "k": ',  # torn mid-frame
    "\x00\x01\x02binary noise",
)


class FlakySession:
    """Wraps the replay ``StubSession`` (or any session) with a scripted
    per-request fault plan: ``"ok"`` passes through, ``"5xx"`` returns a
    503 error body, ``"timeout"`` raises. The plan is consumed one entry
    per request; exhausted → ok. ``failures`` counts injected faults."""

    def __init__(self, inner: Any, plan: list[str] | tuple = ()) -> None:
        self.inner = inner
        self.plan = list(plan)
        self.failures = 0

    def _mode(self) -> str:
        return self.plan.pop(0) if self.plan else "ok"

    def request(self, method: str, url: str, **kwargs):
        mode = self._mode()
        if mode == "timeout":
            self.failures += 1
            raise TimeoutError(f"scripted timeout: {method} {url}")
        if mode == "5xx":
            self.failures += 1
            resp = self.inner.request(method, url, **kwargs)
            resp.status_code = 503
            return resp
        return self.inner.request(method, url, **kwargs)

    def get(self, url, params=None):
        return self.request("GET", url, params=params)


def flaky_transport(plan: list[str] | tuple = ()):
    """An async Telegram transport failing per plan entry (``"error"`` /
    ``"ok"``; exhausted → ok). ``transport.calls`` tallies attempts and
    injected failures."""
    plan_list = list(plan)
    calls = {"attempts": 0, "failed": 0}

    async def transport(chat_id: str, text: str) -> None:
        calls["attempts"] += 1
        mode = plan_list.pop(0) if plan_list else "ok"
        if mode == "error":
            calls["failed"] += 1
            raise RuntimeError("scripted telegram transport failure")

    transport.calls = calls  # type: ignore[attr-defined]
    return transport


# -- the end-to-end chaos drill ----------------------------------------------


def ws_chaos_drill(
    n_symbols: int = 8,
    n_ticks: int = 6,
    timeout_s: float = 30.0,
) -> dict:
    """Disconnect storm + garbage frames + sink 5xx storm through the REAL
    ingest stack: a ``KlinesConnector`` (scripted factory, fast jittered
    backoff) feeding ``SignalEngine.consume_loop`` whose binbot session
    and Telegram transport are flaky. Returns the facts the scenario lane
    asserts: the engine ticked, the heartbeat stayed live, reconnects
    were observed (and surfaced via the ws health tracker), and every
    closed candle in the script landed in the device buffers exactly
    once (``lost_candles == 0``)."""
    from binquant_tpu.io.replay import StubSession, make_stub_engine
    from binquant_tpu.io.websocket import KlinesConnector, WsHealth
    from binquant_tpu.schemas import SymbolModel
    from binquant_tpu.sim.scenarios import (
        ScenarioSpec,
        base_market,
        emit_stream,
        symbol_names,
    )

    spec = ScenarioSpec(
        name="chaos", description="", n_symbols=n_symbols, n_ticks=n_ticks
    )
    closes, vols, _rng = base_market(spec)
    klines = emit_stream(spec, closes, vols)
    frames = [binance_frame(k) for k in klines]
    cut = len(frames) // 3

    # session 1: a third of the stream, then a hard drop mid-feed;
    # session 2: the exchange refuses the reconnect (delayed recovery);
    # session 3: garbage + torn frames mixed into the rest, then idle.
    sessions = [
        ScriptedWs([("frame", f) for f in frames[:cut]] + [("drop", "storm")]),
        RefusedConnect(),
        ScriptedWs(
            [("frame", GARBAGE_FRAMES[0]), ("frame", GARBAGE_FRAMES[1])]
            + [("frame", f) for f in frames[cut:]]
            + [("frame", GARBAGE_FRAMES[2]), ("idle",)]
        ),
    ]
    factory = FaultyConnectFactory(sessions)
    health = WsHealth(window_s=60.0, degrade_reconnects=2)

    flaky_session = FlakySession(
        StubSession(),
        # a FULL sink outage: every backend call during the drill eats a
        # timeout or a 503 (the drill ticks on a wall clock, so only a
        # handful of calls — e.g. the per-bucket breadth refresh — happen;
        # all of them must fail and the engine must not care)
        plan=["timeout", "5xx"] * 50,
    )
    telegram = flaky_transport(plan=["error", "ok"] * 20)
    engine = make_stub_engine(
        capacity=32,
        window=120,
        session=flaky_session,
        telegram_transport=telegram,
        # this drill pins the INLINE sink path's isolation; the delivery
        # plane's storm/kill/restore drill is delivery_chaos_drill below
        # and the fan-out plane's churn/stall drill is fanout_chaos_drill
        delivery=False,
        fanout=False,
    )
    engine.ws_health = health

    symbols = [
        SymbolModel(id=name, base_asset=name[:-4], quote_asset="USDT")
        for name in symbol_names(n_symbols)
    ]
    queue: asyncio.Queue = asyncio.Queue()
    connector = KlinesConnector(
        queue,
        symbols,
        connect=factory,
        reconnect_seed=7,
        initial_backoff_s=0.02,
        max_backoff_s=0.1,
        health=health,
    )

    expected15 = n_ticks
    expected5 = n_ticks * 3

    async def drill() -> dict:
        await connector.start_stream()
        consume = asyncio.create_task(
            engine.consume_loop(queue, tick_interval_s=0.05)
        )
        deadline = time.monotonic() + timeout_s

        def all_landed() -> bool:
            rows = [engine.registry.row_of(s.id) for s in symbols]
            if any(r is None for r in rows):
                return False
            f15 = np.asarray(engine.state.buf15.filled)
            f5 = np.asarray(engine.state.buf5.filled)
            return all(
                f15[r] >= expected15 and f5[r] >= expected5 for r in rows
            )

        landed = False
        while time.monotonic() < deadline:
            await asyncio.sleep(0.05)
            if engine.ticks_processed > 0 and all_landed():
                landed = True
                break
        # a couple more intervals so the post-storm engine provably keeps
        # ticking with the stream idle-connected
        ticks_at_land = engine.ticks_processed
        await asyncio.sleep(0.2)
        consume.cancel()
        await asyncio.gather(consume, return_exceptions=True)
        await connector.stop()

        lost = 0
        for s_idx, name in enumerate(symbol_names(n_symbols)):
            row = engine.registry.row_of(name)
            if row is None:
                lost += expected15 + expected5
                continue
            lost += max(
                0, expected15 - int(np.asarray(engine.state.buf15.filled)[row])
            )
            lost += max(
                0, expected5 - int(np.asarray(engine.state.buf5.filled)[row])
            )
        return {
            "landed": landed,
            "lost_candles": lost,
            "ticks": engine.ticks_processed,
            "ticks_after_storm": engine.ticks_processed - ticks_at_land,
            "reconnect_connects": factory.connects,
            "ws": health.snapshot(),
            "sink_faults": flaky_session.failures,
            "telegram": dict(telegram.calls),
            "health": engine.health_snapshot(),
            "heartbeat_live": engine.health_snapshot()["heartbeat_age_s"]
            is not None,
        }

    facts = asyncio.run(drill())
    facts["ok"] = bool(
        facts["landed"]
        and facts["lost_candles"] == 0
        and facts["ticks"] > 0
        and facts["reconnect_connects"] >= 3
        and facts["sink_faults"] > 0
        and facts["heartbeat_live"]
    )
    return facts


# -- the delivery-plane chaos drill (ISSUE 13) --------------------------------


class FlakySink:
    """Wraps a :class:`~binquant_tpu.io.emission.SignalSink` with a
    scripted per-ATTEMPT fault plan — the delivery plane's chaos seam.
    Plan entries: ``"ok"`` records the payload in ``delivered``;
    ``"5xx"``/``"timeout"``/anything else raises (exhausted → ok).
    ``latency_s`` stalls every attempt first, so a drill can prove the
    tick thread never waits on the sink."""

    def __init__(
        self, inner: Any, plan: list[str] | tuple = (), latency_s: float = 0.0
    ) -> None:
        self.inner = inner
        self.name = inner.name
        self.policy = inner.policy
        self.plan = list(plan)
        self.latency_s = float(latency_s)
        self.failures = 0
        self.delivered: list[Any] = []

    def encode(self, signal):
        return self.inner.encode(signal)

    def to_wal(self, payload):
        return self.inner.to_wal(payload)

    def from_wal(self, data):
        return self.inner.from_wal(data)

    async def deliver(self, payload) -> None:
        if self.latency_s:
            await asyncio.sleep(self.latency_s)
        mode = self.plan.pop(0) if self.plan else "ok"
        if mode != "ok":
            self.failures += 1
            raise ConnectionError(f"scripted sink fault: {mode}")
        self.delivered.append(payload)


def _autotrade_key(payload) -> tuple:
    """Content identity of a delivered autotrade payload — stable across
    independent drives (trace ids differ per process; price/direction/
    symbol/strategy pin the producing bar on a deterministic stream)."""
    return (
        str(payload.algorithm_name),
        str(payload.symbol),
        str(payload.direction),
        round(float(payload.current_price), 8),
    )


def _burst_signal(i: int):
    """A synthetic FiredSignal for the queue-saturation burst."""
    from binquant_tpu.io.emission import FiredSignal
    from binquant_tpu.schemas import SignalsConsumer

    value = SignalsConsumer(
        autotrade=False,
        current_price=1.0 + i,
        direction="LONG",
        algorithm_name="burst",
        symbol=f"BURST{i:03d}USDT",
    )
    return FiredSignal(
        "burst", value.symbol, i, value, f"burst {i}", {"symbol": value.symbol}
    )


def delivery_chaos_drill(workdir: str | None = None) -> dict:
    """The ISSUE-13 acceptance drill: a scripted autotrade 5xx/timeout
    storm, scripted breaker open→half_open→open→half_open→closed cycle,
    an analytics queue-saturation burst, and a process kill mid-storm
    (workers cancelled hard, WAL left unacked and uncompacted) followed
    by a checkpoint restore — asserting

    * ZERO autotrade-signal loss and ZERO duplicates past the delivery
      dedupe key: victim+resumed delivered set == the uninterrupted
      oracle's, each key exactly once;
    * the WAL replay actually carried entries across the kill;
    * the breaker walked the scripted transition sequence;
    * lossy queue saturation shed with reason=queue_full (counted, not
      silent);
    * finalize's ``emit`` host-phase dwell stayed bounded while the sink
      burned orders of magnitude more wall time (the tick thread never
      blocks on a sink);
    * (ISSUE 16) the unified SLO plane judged the storm: the
      delivery.autotrade SLO emitted ``slo_burn`` during the 5xx storm
      and ``slo_recover`` after the post-restore clean soak, the
      mid-storm ``slo_verdict()`` read NOT-ok while the breaker was open
      (no false green), the post-recovery verdict read ok, and the
      close→ack lag histograms populated for every sink.
    """
    import tempfile
    from pathlib import Path

    from binquant_tpu.io.checkpoint import load_state, save_state
    from binquant_tpu.io.replay import make_stub_engine, tick_seq
    from binquant_tpu.obs.events import get_event_log
    from binquant_tpu.obs.slo import slo_verdict
    from binquant_tpu.sim.scenarios import (
        Scenario,
        ScenarioSpec,
        _bleed_then_hammer,
        base_market,
        emit_stream,
        write_scenario_file,
    )

    workdir = Path(workdir or tempfile.mkdtemp(prefix="bqt_delivery_"))
    workdir.mkdir(parents=True, exist_ok=True)
    # the drill's own two-pulse stream (not a registered corpus family):
    # THREE capitulation hammers before the kill point — the first walks
    # the scripted breaker cycle to a delivery, the other two sit unacked
    # in the WAL when the kill lands — and two more after it
    spec = ScenarioSpec(
        name="delivery_storm",
        description="two signal pulses bracketing a mid-storm kill",
    )

    def _build(sp: ScenarioSpec) -> list[dict]:
        closes, vols, _rng = base_market(sp)
        shapes: dict = {}
        _bleed_then_hammer(
            closes, vols, shapes, (2, 5, 8), sp.n_ticks - 36, sp.n_ticks - 10
        )
        _bleed_then_hammer(
            closes, vols, shapes, (3, 6), sp.n_ticks - 27, sp.n_ticks - 1
        )
        return emit_stream(sp, closes, vols, shapes)

    stream = workdir / "delivery_storm.jsonl"
    write_scenario_file(Scenario(spec=spec, build=_build), stream)
    seq = tick_seq(stream)
    # split between the storm's two signal pulses (the restore-under-
    # fault geometry): signals exist on BOTH sides of the kill
    split = spec.n_ticks - 6

    knobs = dict(
        delivery_queue_max=64,
        delivery_attempt_timeout_s=2.0,
        delivery_retry_max=2,
        delivery_backoff_s=0.01,
        delivery_backoff_max_s=0.05,
        delivery_breaker_threshold=2,
        delivery_breaker_cooldown_s=0.05,
        wal_compact_every=0,  # the kill must find an uncompacted WAL
        # unified SLO plane (ISSUE 16): judge the storm live. Tiny p99
        # window so the post-restore clean soak deterministically washes
        # the storm's lags back under budget (nearest-rank p99 over 4
        # samples is the window max — one retained storm lag pins it)
        slo_enabled=True,
        delivery_health_enabled=True,
        delivery_slo_ms=25.0,
        slo_window=4,
        slo_event_every=4,
        # keep the verdict scoped to the delivery plane: a synthetic
        # replay stream must not fold ingest staleness into the
        # ok-after-recovery assertion
        ingest_stale_budget=10_000,
    )

    def build(wal: Path):
        return make_stub_engine(
            capacity=spec.capacity,
            window=spec.window,
            incremental=True,
            scan_chunk=spec.scan_chunk,
            enabled_strategies=set(spec.enabled_strategies),
            host_phase=True,
            delivery=True,
            delivery_wal=str(wal),
            delivery_overrides=dict(knobs),
            # this drill pins the pre-fanout three-sink delivery story
            # (lane names, healthz shapes); fanout_chaos_drill owns the
            # four-lane composition
            fanout=False,
        )

    async def drive(engine, ticks) -> None:
        engine.delivery.start()
        for now_ms, klines in ticks:
            for k in klines:
                engine.ingest(k)
            await engine.process_tick(now_ms=now_ms)
        await engine.flush_pending()

    # -- the uninterrupted oracle (healthy recorder sinks) -------------------
    oracle = build(workdir / "oracle.wal.jsonl")
    at_oracle = FlakySink(oracle.delivery.lane("autotrade").sink)
    oracle.delivery.lane("autotrade").sink = at_oracle

    async def run_oracle() -> None:
        await drive(oracle, seq)
        await oracle.delivery.aclose(drain_s=10.0)

    asyncio.run(run_oracle())
    oracle_keys = {_autotrade_key(p) for p in at_oracle.delivered}

    # -- the victim: storm + breaker script + burst, then a hard kill --------
    wal_path = workdir / "victim.wal.jsonl"
    victim = build(wal_path)
    # breaker choreography (threshold 2): two failures OPEN, the first
    # half-open probe FAILS (re-open), the second probe succeeds (CLOSE);
    # then the storm resumes failing everything until the kill
    at_victim = FlakySink(
        victim.delivery.lane("autotrade").sink,
        plan=["5xx", "timeout", "5xx", "ok"] + ["5xx"] * 10_000,
        latency_s=0.002,
    )
    victim.delivery.lane("autotrade").sink = at_victim
    # analytics saturation target: a slow sink behind a 64-slot queue
    an_victim = FlakySink(
        victim.delivery.lane("analytics").sink, latency_s=0.5
    )
    victim.delivery.lane("analytics").sink = an_victim

    async def run_victim() -> dict:
        await drive(victim, seq[:split])
        # queue-saturation burst: 80 synthetic lossy records against the
        # 64-slot analytics queue while its worker crawls — the overflow
        # must shed with an explicit counter, never block or grow
        # (analytics lane only: a burst into the autotrade lane would be
        # WAL-durable by design and pollute the oracle-equality check)
        from binquant_tpu.io.delivery import Envelope

        an_lane = victim.delivery.lane("analytics")
        for i in range(80):
            sig = _burst_signal(i)
            victim.delivery.enqueue(
                Envelope(
                    entry_id=f"burst/{i}",
                    sink="analytics",
                    payload=an_lane.sink.encode(sig),
                    ts_ms=0,
                )
            )
        # give the breaker script room to complete its scripted cycle,
        # and catch one slo_verdict() WHILE the breaker is open (the
        # storm keeps failing the still-unacked WAL entries, so the
        # breaker re-opens after the scripted close) — the no-false-green
        # probe (ISSUE 16)
        deadline = time.monotonic() + 8.0
        breaker = victim.delivery.breaker("autotrade")
        storm_verdict: dict | None = None
        while time.monotonic() < deadline:
            if storm_verdict is None and breaker.state == "open":
                storm_verdict = slo_verdict(victim.slo)
            if len(breaker.transitions) >= 5 and storm_verdict is not None:
                break
            await asyncio.sleep(0.01)
        # HARD KILL: cancel the workers mid-flight — no drain, no ack
        # flush, no WAL compaction (what SIGKILL leaves behind). closed
        # goes up BEFORE the cancels and the join re-cancels on a short
        # timeout — 3.10's wait_for can swallow a cancel that lands as
        # the inner attempt completes (bpo-42130, same defense as
        # DeliveryPlane.aclose); a swallow-survivor then parks on
        # queue.get forever and a bare gather deadlocks the drill.
        # Neither flag nor re-cancel acks or compacts anything, so the
        # WAL state the restore finds is still exactly SIGKILL residue.
        victim.delivery.closed = True
        workers = [
            lane.worker
            for lane in victim.delivery._lanes.values()
            if lane.worker is not None
        ]
        for w in workers:
            w.cancel()
        for w in workers:
            for _ in range(25):
                done, _pending = await asyncio.wait({w}, timeout=0.2)
                if done:
                    try:
                        await w
                    except (asyncio.CancelledError, Exception):
                        pass
                    break
                w.cancel()
        victim.delivery.wal.close()
        return {
            "breaker_transitions": list(breaker.transitions),
            "storm_verdict": storm_verdict,
            "analytics_shed": dict(
                victim.delivery.lane("analytics").shed
            ),
            "emit_ms": (
                victim.host_phase.totals.get("serial", {})
                .get("emit", [0.0, 0])[0]
            ),
            "sink_wall_ms": 1000.0
            * (
                0.5 * (len(an_victim.delivered) + an_victim.failures)
                + 0.002 * (len(at_victim.delivered) + at_victim.failures)
            ),
        }

    # tap slo_burn/slo_recover off the event-log emit path (works even
    # with the process log disabled, and without redirecting it away from
    # a smoke run's BQT_EVENT_LOG file) — the same monkeypatch idiom as
    # the fanout drill's on_fired spy
    slo_events: list[dict] = []
    _evlog = get_event_log()
    _orig_emit = _evlog.emit

    def _tap_emit(event: str, **fields):
        if event in ("slo_burn", "slo_recover"):
            slo_events.append({"event": event, **fields})
        return _orig_emit(event, **fields)

    _evlog.emit = _tap_emit  # type: ignore[method-assign]
    after_verdict: dict = {}
    try:
        victim_facts = asyncio.run(run_victim())
        victim_keys = {_autotrade_key(p) for p in at_victim.delivered}
        from binquant_tpu.io.delivery import DeliveryWal

        wal_probe = DeliveryWal(wal_path, fsync=False, compact_every=0)
        unacked_at_kill = len(wal_probe.unacked())
        wal_probe.close()
        ckpt = workdir / "victim.ckpt.npz"
        save_state(ckpt, victim.state, victim.registry, victim.host_carries())

        # -- restore: same WAL, healthy sink; replay then the stream tail ----
        resumed = build(wal_path)
        at_resumed = FlakySink(resumed.delivery.lane("autotrade").sink)
        resumed.delivery.lane("autotrade").sink = at_resumed
        state, carries = load_state(ckpt, resumed.state, resumed.registry)
        resumed.state = state
        resumed.restore_host_carries(carries)
        resumed.note_state_restored(
            migrated=bool(carries.get("_carry_rebuilt", False))
        )

        async def run_resumed() -> None:
            await drive(resumed, seq[split:])
            await resumed.delivery.drain(timeout_s=10.0)
            # post-storm clean soak THROUGH the collector path: replayed
            # entries report their true cross-kill lag (seconds — they
            # keep the delivery.autotrade SLO burning), so wash the tiny
            # p99 window with in-budget acks to drive the recover edge
            # deterministically (pulse 2 may deliver fewer fresh acks
            # than the window holds). Every lane is washed: an event-loop
            # stall (a jit compile mid-drive) can push ANY lane's queue
            # dwell past the drill budget, and the final-verdict check
            # is about the recover edge, not residual stall lag
            for sink in ("autotrade", "telegram", "analytics"):
                for _ in range(resumed.delivery_health.window):
                    resumed.delivery_health.on_ack(sink, 1.0)
            after_verdict.update(slo_verdict(resumed.slo))
            await resumed.delivery.aclose(drain_s=10.0)

        asyncio.run(run_resumed())
    finally:
        _evlog.emit = _orig_emit  # type: ignore[method-assign]
    resumed_keys = {_autotrade_key(p) for p in at_resumed.delivered}

    delivered = [
        _autotrade_key(p)
        for p in (*at_victim.delivered, *at_resumed.delivered)
    ]
    facts = {
        "oracle_autotrade": len(oracle_keys),
        "delivered_autotrade": len(set(delivered)),
        "lost_autotrade": len(oracle_keys - set(delivered)),
        "duplicate_keys": len(delivered) - len(set(delivered)),
        "extra_keys": len(set(delivered) - oracle_keys),
        "victim_delivered": len(victim_keys),
        "resumed_delivered": len(resumed_keys),
        "unacked_at_kill": unacked_at_kill,
        "wal_replayed": resumed.delivery.wal_replayed,
        "breaker_transitions": victim_facts["breaker_transitions"],
        "analytics_shed": victim_facts["analytics_shed"],
        "emit_ms": round(victim_facts["emit_ms"], 3),
        "sink_wall_ms": round(victim_facts["sink_wall_ms"], 1),
        "slo_burns": sum(
            1 for e in slo_events if e["event"] == "slo_burn"
        ),
        "slo_recovers": sum(
            1 for e in slo_events if e["event"] == "slo_recover"
        ),
        "storm_verdict_ok": (victim_facts["storm_verdict"] or {}).get("ok"),
        "after_verdict_ok": after_verdict.get("ok"),
        "lag_sinks": sorted(
            set(victim.delivery_health.snapshot()["sinks"])
            | set(resumed.delivery_health.snapshot()["sinks"])
        ),
    }
    checks = {
        "zero_autotrade_loss": facts["lost_autotrade"] == 0
        and facts["extra_keys"] == 0
        and len(oracle_keys) > 0,
        "zero_duplicates_past_key": facts["duplicate_keys"] == 0,
        "signals_on_both_sides": len(victim_keys) > 0
        and len(resumed_keys - victim_keys) > 0,
        "kill_left_unacked_wal": unacked_at_kill > 0,
        "wal_replay_ran": facts["wal_replayed"] > 0,
        "breaker_cycle_scripted": facts["breaker_transitions"][:5]
        == ["open", "half_open", "open", "half_open", "closed"],
        "queue_saturation_shed": facts["analytics_shed"].get("queue_full", 0)
        > 0,
        # the tick thread enqueues; the sinks burn wall time elsewhere
        "emit_dwell_bounded": facts["emit_ms"]
        < max(0.1 * facts["sink_wall_ms"], 250.0),
        # unified SLO plane (ISSUE 16): the storm burned the autotrade
        # delivery SLO, the clean soak recovered it, and the burn
        # preceded the recover
        "slo_burn_then_recover": _burn_then_recover(
            slo_events, "delivery.autotrade"
        ),
        # no false green: the verdict caught mid-storm (breaker open)
        # read NOT-ok, with the breaker invariant naming the sink
        "no_false_green_breaker_open": (
            (victim_facts["storm_verdict"] or {}).get("ok") is False
            and not (victim_facts["storm_verdict"] or {})
            .get("invariants", {})
            .get("delivery_breakers_closed", {})
            .get("ok", True)
        ),
        # ...and the post-recovery verdict folds back to one green ok
        "verdict_ok_after_recovery": after_verdict.get("enabled") is True
        and after_verdict.get("ok") is True,
        # close→ack lag histograms populated for every sink in the drill
        "lag_histograms_per_sink": {
            "autotrade",
            "telegram",
            "analytics",
        }
        <= set(facts["lag_sinks"]),
    }
    facts["checks"] = checks
    facts["ok"] = all(checks.values())
    return facts


def _burn_then_recover(slo_events: list[dict], slo_name: str) -> bool:
    """True when ``slo_name`` emitted a burn AND a later recover — the
    ISSUE-16 drill contract for the burn→recover event sequence."""
    burn_at = next(
        (
            i
            for i, e in enumerate(slo_events)
            if e["event"] == "slo_burn" and e.get("slo") == slo_name
        ),
        None,
    )
    recover_at = next(
        (
            i
            for i, e in enumerate(slo_events)
            if e["event"] == "slo_recover" and e.get("slo") == slo_name
        ),
        None,
    )
    return (
        burn_at is not None
        and recover_at is not None
        and burn_at < recover_at
    )


def fanout_chaos_drill(workdir: str | None = None) -> dict:
    """The ISSUE-14 acceptance drill: a subscriber churn storm riding the
    whole stream (adds/updates/removes between every tick, growing the
    slot planes mid-storm) while signal pulses broadcast to a healthy
    WebSocket watcher AND a stalled consumer whose 2-slot queue can never
    drain — asserting

    * device recipient sets equal the Python oracle on EVERY fired tick
      of the churn storm (the compiled planes track churn exactly);
    * the plane resynced incrementally through churn, with full
      recompiles only at first use / capacity growth;
    * zero tick-thread stall: every tick processed, the finalize emit
      dwell stays bounded while the stalled consumer wedges, and the
      healthy watcher still receives every frame addressed to it;
    * sheds are COUNTED, never silent (hub.shed == the stalled
      connection's drops, and the shed reason is slow_consumer);
    * the autotrade consumer group is unaffected: delivered set == the
      fanout-off oracle run's, zero loss, zero duplicates;
    * a reconnect presenting a cursor replays the stalled consumer's
      whole gap from the broadcast outbox;
    * (ISSUE 16) the unified SLO plane judged the wedge: the hub's
      cursor-lag watermark caught the sloth's backlog, a slow-ack probe
      through the delivery-health collector burned the delivery.fanout
      SLO (verdict NOT-ok while burning — no false green), the
      post-replay clean soak recovered it, and the final verdict folded
      back to one green ok with the recipient-set invariant passing.
    """
    import tempfile
    from pathlib import Path

    from binquant_tpu.fanout.hub import _Connection, ws_read_frame
    from binquant_tpu.fanout.registry import Subscription
    from binquant_tpu.io.replay import make_stub_engine, tick_seq
    from binquant_tpu.obs.events import get_event_log
    from binquant_tpu.obs.slo import slo_verdict
    from binquant_tpu.sim.scenarios import (
        Scenario,
        ScenarioSpec,
        _bleed_then_hammer,
        base_market,
        emit_stream,
        symbol_names,
        write_scenario_file,
    )

    workdir = Path(workdir or tempfile.mkdtemp(prefix="bqt_fanout_"))
    workdir.mkdir(parents=True, exist_ok=True)
    spec = ScenarioSpec(
        name="fanout_storm",
        description="three hammer pulses under a subscription churn storm",
    )

    def _build(sp: ScenarioSpec) -> list[dict]:
        closes, vols, _rng = base_market(sp)
        shapes: dict = {}
        # three pulses, all past MIN_BARS(=100) where strategies arm:
        # t-10 / t-4 / t-1, so the t-7 flash mob's plane growth lands
        # BETWEEN live matches and the t-4 -> t-1 gap is churn-only (the
        # incremental column-scatter resync the drill must exercise)
        _bleed_then_hammer(
            closes, vols, shapes, (2, 5, 8), sp.n_ticks - 40, sp.n_ticks - 10
        )
        _bleed_then_hammer(
            closes, vols, shapes, (3, 6), sp.n_ticks - 31, sp.n_ticks - 4
        )
        _bleed_then_hammer(
            closes, vols, shapes, (4, 7), sp.n_ticks - 24, sp.n_ticks - 1
        )
        return emit_stream(sp, closes, vols, shapes)

    stream = workdir / "fanout_storm.jsonl"
    write_scenario_file(Scenario(spec=spec, build=_build), stream)
    seq = tick_seq(stream)
    symbols = symbol_names(spec.n_symbols)

    def build(fanout: bool, wal: Path):
        return make_stub_engine(
            capacity=spec.capacity,
            window=spec.window,
            incremental=True,
            scan_chunk=spec.scan_chunk,
            enabled_strategies=set(spec.enabled_strategies),
            host_phase=True,
            delivery=True,
            delivery_wal=str(wal),
            delivery_overrides={
                "delivery_backoff_s": 0.005,
                # unified SLO plane (ISSUE 16): same drill-scale knobs
                # as delivery_chaos_drill (tiny p99 window so the clean
                # soak deterministically recovers the burned SLO)
                "slo_enabled": True,
                "delivery_health_enabled": True,
                "delivery_slo_ms": 25.0,
                "slo_window": 4,
                "slo_event_every": 4,
                "ingest_stale_budget": 10_000,
            },
            fanout=fanout,
            fanout_overrides=(
                # small slot capacity so the churn storm forces plane
                # growth (the match kernel's one legitimate retrace);
                # roomy outbox so the stalled user's whole gap replays;
                # a small tail ring so the reconnect storm exercises BOTH
                # resume sources (in-window cursors from memory, stale /
                # trace cursors falling back to the outbox scan);
                # compaction pinned off — the dedicated compaction tests
                # own that seam, and a mid-storm slot re-pack would
                # invalidate the storm's replay oracle
                {
                    "fanout_capacity": 64,
                    "fanout_outbox_cap": 4096,
                    "fanout_resume_tail": 64,
                    "fanout_compact_frac": 0.0,
                }
                if fanout
                else {}
            ),
        )

    async def drive(engine, churn=None) -> list:
        engine.delivery.start()
        ticks = []
        for i, (now_ms, klines) in enumerate(seq):
            if churn is not None:
                churn(i)
            for k in klines:
                engine.ingest(k)
            t0 = time.perf_counter()
            await engine.process_tick(now_ms=now_ms)
            ticks.append((time.perf_counter() - t0) * 1000)
        await engine.flush_pending()
        return ticks

    # -- the fanout-off oracle: what autotrade must deliver regardless ------
    oracle = build(False, workdir / "oracle.wal.jsonl")
    at_oracle = FlakySink(oracle.delivery.lane("autotrade").sink)
    oracle.delivery.lane("autotrade").sink = at_oracle

    async def run_oracle() -> None:
        await drive(oracle)
        await oracle.delivery.aclose(drain_s=10.0)

    asyncio.run(run_oracle())
    oracle_keys = {_autotrade_key(p) for p in at_oracle.delivered}

    # -- the subject: churn storm + stalled consumer + healthy watcher ------
    subject = build(True, workdir / "subject.wal.jsonl")
    at_subject = FlakySink(subject.delivery.lane("autotrade").sink)
    subject.delivery.lane("autotrade").sink = at_subject
    plane = subject.fanout
    rng = np.random.default_rng(spec.seed)
    strategies = list(spec.enabled_strategies)

    # standing population: the watcher and the sloth subscribe to all,
    # plus the reconnect-storm cohort (ISSUE 20) — subscribed up front so
    # every published frame addresses them and their post-drive cursor
    # replays have a full gap to prove against
    plane.subscribe(Subscription("watcher"))
    plane.subscribe(Subscription("sloth"))
    storm_cohort = [f"storm{i}" for i in range(6)]
    for uid in storm_cohort:
        plane.subscribe(Subscription(uid))
    churn_pool: list[str] = []
    churn_ops = {"subscribe": 0, "update": 0, "unsubscribe": 0}
    next_id = 0

    def _random_sub(uid: str) -> Subscription:
        return Subscription(
            uid,
            symbols=(
                None
                if rng.random() < 0.5
                else frozenset(
                    str(s)
                    for s in rng.choice(
                        symbols, size=int(rng.integers(1, 4)), replace=False
                    )
                )
            ),
            strategies=(
                None
                if rng.random() < 0.5
                else frozenset(
                    str(s)
                    for s in rng.choice(
                        strategies,
                        size=int(rng.integers(1, 3)),
                        replace=False,
                    )
                )
            ),
            min_strength=float(np.float32(rng.random() * 0.5)),
        )

    def churn(tick: int) -> None:
        nonlocal next_id
        # a flash mob BETWEEN the signal pulses: 300 signups in one tick
        # force a slot-capacity growth bracketed by two live matches, so
        # the storm exercises the grow -> full-device-resync path (the
        # match kernel's one legitimate retrace) mid-stream
        adds = 6 + (300 if tick == spec.n_ticks - 7 else 0)
        for _ in range(adds):
            uid = f"churn{next_id:05d}"
            next_id += 1
            plane.subscribe(_random_sub(uid))
            churn_pool.append(uid)
            churn_ops["subscribe"] += 1
        for _ in range(2):
            if churn_pool:
                plane.update(_random_sub(str(rng.choice(churn_pool))))
                churn_ops["update"] += 1
        for _ in range(2):
            if churn_pool:
                uid = str(rng.choice(churn_pool))
                churn_pool.remove(uid)
                plane.unsubscribe(uid)
                churn_ops["unsubscribe"] += 1

    # per-fired-tick oracle equality spy over the churning population
    mismatches: list = []
    matched_ticks = {"n": 0}
    orig_on_fired = plane.on_fired

    def spy(fired, ctx_scalars, tick_ms=None):
        from binquant_tpu.enums import MarketRegimeCode
        from binquant_tpu.fanout.kernel import unpack_words_np

        stats = orig_on_fired(fired, ctx_scalars, tick_ms=tick_ms)
        regime = int(ctx_scalars.get("market_regime", -1))
        valid = bool(ctx_scalars.get("valid", False))
        want = plane.subscriptions.match_oracle(
            [
                (s.strategy, s.symbol, float(s.value.score or 0.0))
                for s in fired
            ],
            regime if valid and 0 <= regime < len(MarketRegimeCode) else None,
        )
        matched_ticks["n"] += 1
        for s, w in zip(fired, want):
            _frame, words, _t = s.fanout_frame
            got = set(
                plane.subscriptions.users_of_slots(
                    np.flatnonzero(unpack_words_np(words))
                )
            )
            if got != w:
                mismatches.append((tick_ms, s.strategy, s.symbol))
        return stats

    plane.on_fired = spy

    watcher_frames: list[dict] = []
    facts: dict = {}

    async def run_subject() -> None:
        port = await plane.serve(0, host="127.0.0.1")
        # healthy watcher over a real WS socket
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            b"GET /ws?user=watcher HTTP/1.1\r\nHost: x\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Key: dGhlIHNhbXBsZQ==\r\n\r\n"
        )
        await writer.drain()
        await reader.readline()
        while (await reader.readline()) not in (b"\r\n", b""):
            pass

        async def watch() -> None:
            try:
                while True:
                    opcode, payload = await ws_read_frame(reader)
                    if opcode == 0x1:
                        watcher_frames.append(json.loads(payload))
            except (ConnectionError, asyncio.CancelledError):
                pass

        watch_task = asyncio.ensure_future(watch())
        # the stalled consumer: a registered connection whose writer task
        # never drains its 2-slot queue (the bounded-queue chaos seam — a
        # live socket's kernel buffer would mask the wedge)
        sloth = _Connection(
            "sloth", plane.subscriptions.slot_of("sloth"), "ws", queue_max=2
        )
        plane.hub._conns.add(sloth)

        tick_ms_list = await drive(subject, churn=churn)
        facts["drained"] = await subject.delivery.drain(timeout_s=15.0)
        # let the watcher catch the tail
        deadline = time.monotonic() + 5.0
        while (
            time.monotonic() < deadline
            and len(watcher_frames) < plane.published
        ):
            await asyncio.sleep(0.02)
        # the cursor-lag watermark must catch the wedge WHILE the sloth
        # is still registered: its never-drained 2-slot queue is the
        # hub's laggiest consumer (ISSUE 16)
        facts["wedged_cursor_lag"] = plane.hub.cursor_lag()
        # wedge-period SLO probe through the delivery-health collector:
        # four over-budget fanout acks burn the delivery.fanout SLO, and
        # the verdict must read NOT-ok while it burns (no false green)
        for _ in range(subject.delivery_health.window):
            subject.delivery_health.on_ack("fanout", 500.0)
        facts["wedged_verdict_ok"] = slo_verdict(subject.slo).get("ok")
        plane.hub._conns.discard(sloth)

        # reconnect-with-cursor: the sloth's gap replays from the outbox
        r2, w2 = await asyncio.open_connection("127.0.0.1", port)
        w2.write(
            b"GET /ws?user=sloth&cursor=-1 HTTP/1.1\r\nHost: x\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Key: dGhlIHNhbXBsZQ==\r\n\r\n"
        )
        await w2.drain()
        await r2.readline()
        while (await r2.readline()) not in (b"\r\n", b""):
            pass
        sloth_slot = plane.subscriptions.slot_of("sloth")
        addressed = [
            f["seq"]
            for f, words in plane.outbox.entries()
            if (
                sloth_slot >> 5 < len(words)
                and (int(words[sloth_slot >> 5]) >> (sloth_slot & 31)) & 1
            )
        ]
        replayed = []
        try:
            while len(replayed) < len(addressed):
                opcode, payload = await asyncio.wait_for(
                    ws_read_frame(r2), timeout=5.0
                )
                if opcode == 0x1:
                    replayed.append(json.loads(payload)["seq"])
        except (TimeoutError, asyncio.TimeoutError):
            pass
        writer.close()
        w2.close()
        watch_task.cancel()

        # -- churn × reconnect storm (ISSUE 20): the cohort reconnects
        # with fresh cursors WHILE a 100-op subscription churn burst
        # races the handshakes on the same loop. Replay must stay
        # bit-exact per user whichever source serves it — the in-memory
        # tail ring for in-window cursors, the outbox scan for stale and
        # trace-id cursors (fallbacks counted by reason, never silent).
        entries_pre = plane.outbox.entries()
        head = plane.seq - 1

        def _addressed_after(user: str, after_seq: int) -> list[int]:
            s = plane.subscriptions.slot_of(user)
            return [
                int(f["seq"])
                for f, words in entries_pre
                if int(f["seq"]) > after_seq
                and s >> 5 < len(words)
                and (int(words[s >> 5]) >> (s & 31)) & 1
            ]

        # cursor mix: in-window numerics (tail ring), a stale numeric
        # (outbox path when the ring has evicted past it), and one
        # trace-id cursor (always an outbox resolution → counted fallback)
        tr_frame = entries_pre[-1][0]
        trace_cursor = f"{tr_frame['trace_id']}/{tr_frame['tick_seq']}"
        trace_resolved = max(
            int(f["seq"])
            for f, _ in entries_pre
            if f.get("trace_id") == tr_frame["trace_id"]
            and f.get("tick_seq") == tr_frame["tick_seq"]
        )
        cursor_of = {
            "storm0": str(max(head - 3, -1)),
            "storm1": str(max(head - 5, -1)),
            "storm2": str(max(head - 2, -1)),
            "storm3": "0",
            "storm4": "-1",
            "storm5": trace_cursor,
        }
        expected_of = {
            u: _addressed_after(
                u,
                trace_resolved if u == "storm5" else int(cursor_of[u]),
            )
            for u in storm_cohort
        }

        def _storm_burst(n: int) -> None:
            nonlocal next_id
            for _ in range(n):
                r = rng.random()
                if r < 0.4 or not churn_pool:
                    uid = f"burst{next_id:05d}"
                    next_id += 1
                    plane.subscribe(_random_sub(uid))
                    churn_pool.append(uid)
                elif r < 0.7:
                    plane.update(_random_sub(str(rng.choice(churn_pool))))
                else:
                    uid = str(rng.choice(churn_pool))
                    churn_pool.remove(uid)
                    plane.unsubscribe(uid)
                facts["storm_churn_ops"] = (
                    facts.get("storm_churn_ops", 0) + 1
                )

        async def _storm_reconnect(user: str) -> tuple[str, list[int]]:
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(
                (
                    f"GET /ws?user={user}&cursor={cursor_of[user]} "
                    "HTTP/1.1\r\nHost: x\r\n"
                    "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                    "Sec-WebSocket-Key: dGhlIHNhbXBsZQ==\r\n\r\n"
                ).encode()
            )
            await w.drain()
            await r.readline()
            while (await r.readline()) not in (b"\r\n", b""):
                pass
            got: list[int] = []
            try:
                while len(got) < len(expected_of[user]):
                    opcode, payload = await asyncio.wait_for(
                        ws_read_frame(r), timeout=5.0
                    )
                    if opcode == 0x1:
                        got.append(json.loads(payload)["seq"])
            except (TimeoutError, asyncio.TimeoutError):
                pass
            w.close()
            return user, got

        storm_tasks = [
            asyncio.ensure_future(_storm_reconnect(u)) for u in storm_cohort
        ]
        # interleave the churn burst with the in-flight handshakes: every
        # yield lets accept/upgrade/replay steps run between ops
        for _ in range(10):
            _storm_burst(10)
            await asyncio.sleep(0)
        storm_got = dict(await asyncio.gather(*storm_tasks))
        facts["storm_replays"] = {
            u: {"got": len(storm_got[u]), "want": len(expected_of[u])}
            for u in storm_cohort
        }
        facts["storm_replay_exact"] = all(
            storm_got[u] == expected_of[u] for u in storm_cohort
        )
        facts["storm_expected_total"] = sum(
            len(v) for v in expected_of.values()
        )
        facts["tail_resumes"] = plane.hub.tail_resumes
        facts["resume_fallbacks"] = dict(plane.hub.resume_fallbacks)

        # post-replay clean soak: in-budget acks wash the tiny p99
        # window and fire the recover edge; the final verdict must fold
        # back to green with the recipient-set invariant passing. Every
        # lane is washed — an event-loop stall (the flash mob's plane
        # recompile) can push any lane's queue dwell past the drill
        # budget, and this check is about the recover edge
        for sink in ("autotrade", "telegram", "analytics", "fanout"):
            for _ in range(subject.delivery_health.window):
                subject.delivery_health.on_ack(sink, 1.0)
        facts["final_verdict"] = slo_verdict(subject.slo)
        await subject.delivery.aclose(drain_s=5.0)
        await subject.aclose_fanout()
        facts["tick_p99_ms"] = float(np.percentile(tick_ms_list, 99))
        facts["sloth_addressed"] = len(addressed)
        facts["sloth_replayed"] = len(replayed)
        facts["sloth_gap_replayed"] = replayed == addressed
        facts["sloth_dropped"] = sloth.dropped
        facts["sloth_gapped"] = sloth.gapped

    # tap slo_burn/slo_recover off the emit path (same idiom as the
    # delivery drill — works with the process event log disabled)
    slo_events: list[dict] = []
    _evlog = get_event_log()
    _orig_emit = _evlog.emit

    def _tap_emit(event: str, **fields):
        if event in ("slo_burn", "slo_recover"):
            slo_events.append({"event": event, **fields})
        return _orig_emit(event, **fields)

    _evlog.emit = _tap_emit  # type: ignore[method-assign]
    try:
        asyncio.run(run_subject())
    finally:
        _evlog.emit = _orig_emit  # type: ignore[method-assign]
    subject_keys = {_autotrade_key(p) for p in at_subject.delivered}
    delivered = [_autotrade_key(p) for p in at_subject.delivered]
    watcher_seqs = sorted(f["seq"] for f in watcher_frames)
    emit_ms = (
        subject.host_phase.totals.get("serial", {}).get("emit", [0.0, 0])[0]
    )
    facts.update(
        {
            "ticks": subject.ticks_processed,
            "published": plane.published,
            "matched_ticks": matched_ticks["n"],
            "oracle_mismatches": mismatches[:5],
            "churn_ops": dict(churn_ops),
            "subscriptions_live": len(plane.subscriptions),
            "slot_capacity": plane.subscriptions.capacity,
            "recompiles": dict(plane.recompiles),
            "hub_shed": plane.hub.shed,
            "watcher_frames": len(watcher_frames),
            "oracle_autotrade": len(oracle_keys),
            "delivered_autotrade": len(subject_keys),
            "duplicate_keys": len(delivered) - len(subject_keys),
            "emit_ms": round(emit_ms, 3),
        }
    )
    checks = {
        "delivery_drained": bool(facts.get("drained")),
        # churn storm correctness: the compiled planes tracked every op
        "oracle_equal_through_churn": not mismatches
        and matched_ticks["n"] >= 2,
        "churn_storm_ran": churn_ops["subscribe"] > 300
        and churn_ops["unsubscribe"] > 50,
        "plane_grew_mid_storm": plane.subscriptions.capacity > 64
        and plane.recompiles.get("full", 0) >= 2,
        "incremental_resyncs": plane.recompiles.get("incremental", 0) > 0,
        # zero tick-thread stall: every tick processed while the sloth
        # wedged, and finalize's emit dwell stayed an enqueue
        "all_ticks_processed": subject.ticks_processed == len(seq),
        "emit_dwell_bounded": emit_ms < 250.0,
        # sheds counted, never silent
        "sheds_counted": facts["sloth_dropped"] > 0
        and plane.hub.shed == facts["sloth_dropped"],
        # the healthy consumer missed nothing
        "watcher_complete": watcher_seqs == list(range(plane.published))
        and plane.published > 0,
        # the trade path is a different consumer group entirely
        "autotrade_unaffected": subject_keys == oracle_keys
        and len(oracle_keys) > 0
        and facts["duplicate_keys"] == 0,
        # reconnect-with-cursor replays the whole gap from the outbox
        "cursor_replayed_gap": facts["sloth_gap_replayed"]
        and facts["sloth_addressed"] > 0,
        # churn × reconnect storm (ISSUE 20): every cohort reconnect
        # replayed its exact gap while 100 churn ops raced the handshakes
        "storm_replay_exact": bool(facts.get("storm_replay_exact"))
        and facts.get("storm_expected_total", 0) > 0
        and facts.get("storm_churn_ops", 0) >= 100,
        # in-window cursors resumed from the tail ring (no outbox scan)...
        "storm_tail_resume_engaged": facts.get("tail_resumes", 0) > 0,
        # ...and the cursors the ring can't serve fell back with a
        # counted reason (the trace cursor always needs the log)
        "storm_fallback_counted": (
            facts.get("resume_fallbacks", {}).get("trace_cursor", 0) >= 1
        ),
        # unified SLO plane (ISSUE 16): the hub's cursor-lag watermark
        # caught the sloth's wedged backlog (its 2-slot queue full)
        "cursor_lag_caught_wedge": facts.get("wedged_cursor_lag", 0) >= 2,
        # the wedge-period probe burned delivery.fanout and the
        # post-replay soak recovered it, in that order
        "slo_burn_then_recover": _burn_then_recover(
            slo_events, "delivery.fanout"
        ),
        # no false green while the SLO burned...
        "no_false_green_while_burning": facts.get("wedged_verdict_ok")
        is False,
        # ...and the final verdict folds back to one green ok with the
        # recipient-set invariant passing
        "verdict_ok_after_recovery": (
            (facts.get("final_verdict") or {}).get("ok") is True
            and (facts.get("final_verdict") or {})
            .get("invariants", {})
            .get("fanout_recipient_set", {})
            .get("ok")
            is True
        ),
    }
    facts["checks"] = checks
    facts["ok"] = all(checks.values())
    return facts
