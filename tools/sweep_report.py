"""Strategy-parameter sweep report (ISSUE 6 satellite, economic scoring
columns since ISSUE 12).

Runs ``binquant_tpu.backtest.run_param_sweep`` over a kline stream and
prints a per-combo table of signal fire counts PLUS the outcome columns
(hit-rate / avg signed forward return / avg MAE at the scoring horizon) —
the ROADMAP-4 economic proxies. One dispatch per chunk scores EVERY combo;
outcomes mature through the same kernel the live tracker uses.

Usage::

    python tools/sweep_report.py STREAM.jsonl \
        --axis pt.rsi_oversold=20,30,40 \
        --axis mrf.rsi_long_max=15,25,35 \
        [--capacity 64] [--window 200] [--chunk 32] [--top 10] [--json OUT] \
        [--horizons 1,4,16] [--rank-by return|fires]

    python tools/sweep_report.py --demo   # synthesize a stream + default grid

Axis names are dotted float leaves of ``strategies.params.StrategyParams``
(``--list-axes`` prints them); int/bool leaves are structural and cannot
be swept.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _parse_axis(spec: str) -> tuple[str, list[float]]:
    if "=" not in spec:
        raise SystemExit(f"bad --axis {spec!r}: expected name=v1,v2,...")
    name, _, values = spec.partition("=")
    try:
        parsed = [float(v) for v in values.split(",") if v.strip()]
    except ValueError as exc:
        raise SystemExit(f"bad --axis {spec!r}: {exc}") from exc
    if not parsed:
        raise SystemExit(f"bad --axis {spec!r}: no values")
    return name.strip(), parsed


def main() -> int:
    parser = argparse.ArgumentParser(
        description="vmapped strategy-parameter sweep report"
    )
    parser.add_argument("stream", nargs="?", help="JSONL kline stream")
    parser.add_argument(
        "--axis", action="append", default=[],
        metavar="name=v1,v2,...",
        help="grid axis (repeatable); dotted StrategyParams float leaf",
    )
    parser.add_argument("--capacity", type=int, default=64)
    parser.add_argument("--window", type=int, default=200)
    parser.add_argument("--chunk", type=int, default=None)
    parser.add_argument(
        "--top", type=int, default=10, help="combos shown (by total fires)"
    )
    parser.add_argument("--json", help="also dump the full result as JSON")
    parser.add_argument(
        "--horizons", default="1,4,16,96",
        help="outcome maturation horizons in 5m bars (comma-separated)",
    )
    parser.add_argument(
        "--rank-by", choices=("return", "fires"), default="return",
        help="rank combos by total signed forward return at the scoring "
        "horizon (the economic proxy) or by raw fire counts",
    )
    parser.add_argument(
        "--list-axes", action="store_true",
        help="print the sweepable axis names and exit",
    )
    parser.add_argument(
        "--demo", action="store_true",
        help="synthesize a small market and sweep a default grid",
    )
    args = parser.parse_args()

    from binquant_tpu.strategies.params import sweepable_axes

    if args.list_axes:
        for name in sweepable_axes():
            print(name)
        return 0

    axes = dict(_parse_axis(spec) for spec in args.axis)
    if args.demo:
        import tempfile

        from binquant_tpu.io.replay import generate_outcome_replay

        td = tempfile.mkdtemp(prefix="bqt_sweep_")
        args.stream = f"{td}/demo.jsonl"
        # mid-stream fires (unlike generate_replay_file's last-tick
        # setups) so the demo's outcome columns actually mature
        generate_outcome_replay(args.stream, n_symbols=24, n_ticks=128)
        args.capacity, args.window = 32, 160
        axes = axes or {
            "pt.rsi_oversold": [15.0, 30.0, 45.0, 60.0],
            "mrf.rsi_long_max": [10.0, 25.0, 40.0, 55.0],
            "abp.volume_multiplier": [1.5, 2.75, 4.0, 8.0],
        }
    if not args.stream or not axes:
        parser.error("need a stream and at least one --axis (or --demo)")

    from binquant_tpu.backtest import run_param_sweep

    horizons = tuple(
        int(v) for v in str(args.horizons).split(",") if v.strip()
    )
    res = run_param_sweep(
        args.stream,
        axes=axes,
        capacity=args.capacity,
        window=args.window,
        chunk=args.chunk,
        horizons=horizons or (1, 4, 16, 96),
    )

    strategies = res["strategies"]
    live_cols = [
        i for i, s in enumerate(strategies)
        if any(res["trig_counts"][p][i] for p in range(res["P"]))
    ]
    axis_names = list(axes)
    outcomes = res.get("outcomes") or {}
    scored = bool(outcomes.get("enabled"))
    print(
        f"sweep: P={res['P']} combos x {res['evaluated_ticks']} ticks "
        f"({res['candles']} candles) in {res['dispatches']} dispatches, "
        f"{res['wall_s']}s "
        f"({res['combo_candles_per_sec']} combo-candles/s)"
    )
    if scored:
        H = outcomes["score_horizon"]
        print(
            f"outcomes: {outcomes['matured_pairs']} matured pairs "
            f"({outcomes['truncated']} truncated, "
            f"{outcomes['unmatured_pair_horizons']} unmatured horizons), "
            f"scored at h={H} bars of 5m; ranked by {args.rank_by}"
        )
    else:
        print("outcomes: scoring disabled (no positive horizons); "
              "ranked by fires")
    ranking = (
        outcomes["ranking_by_return"]
        if scored and args.rank_by == "return"
        else res["ranking"]
    )

    def _fmt(v, pct=False):
        if v is None:
            return "-"
        return f"{v * 100:.1f}%" if pct else f"{v:+.4f}"

    score_cols = [f"hit@{H}", f"fwd@{H}", f"mae@{H}"] if scored else []
    header = (
        ["#", "total", *score_cols]
        + [strategies[i] for i in live_cols]
        + axis_names
    )
    rows = []
    for rank, p in enumerate(ranking[: args.top]):
        combo = res["combos"][p]
        score_cells = []
        if scored:
            score = outcomes["combo_score"][p]
            score_cells = [
                _fmt(score["hit_rate"], pct=True),
                _fmt(score["avg_fwd"]),
                _fmt(score["avg_mae"]),
            ]
        rows.append(
            [str(rank + 1), str(res["total_fired"][p]), *score_cells]
            + [str(res["trig_counts"][p][i]) for i in live_cols]
            + [f"{combo[name]:g}" for name in axis_names]
        )
    widths = [
        max(len(header[c]), *(len(r[c]) for r in rows)) if rows else len(header[c])
        for c in range(len(header))
    ]
    fmt = "  ".join(f"{{:>{w}}}" for w in widths)
    print(fmt.format(*header))
    for r in rows:
        print(fmt.format(*r))

    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
        print(f"full result written to {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
