"""Live tick timestamping vs reality (VERDICT round-1 weak item 5).

``process_tick`` derives the evaluated bar from wall clock
(``bucket*interval - interval``); these tests pin the behavior when the
clock and the data disagree: a tick firing late (>1 interval after the bar
closed) or early must evaluate an EMPTY freshness mask — going blind for a
tick — rather than silently evaluating a stale bar as fresh, and a
catch-up tick at the right bucket must recover the signal.
"""

import asyncio

import pytest

from binquant_tpu.io.replay import (
    generate_replay_file,
    load_klines_by_tick,
    make_stub_engine,
)

CAP, WIN = 16, 130


@pytest.fixture(scope="module")
def market(tmp_path_factory):
    path = tmp_path_factory.mktemp("ts") / "rp.jsonl"
    # enough ticks that MIN_BARS is irrelevant to the assertion target:
    # we inspect freshness via the engine's own wire, not strategy fires
    generate_replay_file(path, n_symbols=8, n_ticks=5)
    return load_klines_by_tick(path)


def _drive(engine, by_tick, buckets, now_ms_of):
    fired_all = []

    async def go():
        for b in buckets:
            for k in sorted(by_tick[b], key=lambda k: k["open_time"]):
                engine.ingest(k)
            fired_all.append(await engine.process_tick(now_ms=now_ms_of(b)))

    asyncio.run(go())
    return fired_all


def test_on_time_tick_sees_fresh_bars(market):
    import numpy as np

    engine = make_stub_engine(capacity=CAP, window=WIN)
    buckets = sorted(market)
    _drive(engine, market, buckets, lambda b: (b + 1) * 900 * 1000)
    # the last evaluated 15m bucket matches the final bars: all 8 fresh
    ts15 = buckets[-1] * 900
    from binquant_tpu.engine.buffer import fresh_mask

    fresh = np.asarray(fresh_mask(engine.state.buf15, ts15))
    assert fresh.sum() == 8


def test_late_tick_evaluates_empty_freshness_not_stale(market):
    """Clock lands >1 interval after the bar closed: the engine must see
    ZERO fresh symbols (blind tick), never a stale bar counted as fresh."""
    import numpy as np

    engine = make_stub_engine(capacity=CAP, window=WIN)
    buckets = sorted(market)
    # deliver bars on time for all but the last bucket
    _drive(engine, market, buckets[:-1], lambda b: (b + 1) * 900 * 1000)
    # last bucket's bars arrive, but the tick fires TWO buckets later
    late_ms = (buckets[-1] + 3) * 900 * 1000
    fired = _drive(engine, market, buckets[-1:], lambda b: late_ms)
    from binquant_tpu.engine.buffer import fresh_mask

    evaluated_ts15 = (late_ms // 1000) // 900 * 900 - 900
    fresh = np.asarray(fresh_mask(engine.state.buf15, evaluated_ts15))
    assert fresh.sum() == 0  # blind, not stale
    assert fired[-1] == []  # and silent — no signals from stale bars


def test_catchup_tick_recovers_the_bucket(market):
    """After a late/blind tick, a re-tick at the CORRECT bucket boundary
    still finds the bars fresh (the buffer holds them; only the clock
    mapping was off)."""
    import numpy as np

    engine = make_stub_engine(capacity=CAP, window=WIN)
    buckets = sorted(market)
    _drive(engine, market, buckets[:-1], lambda b: (b + 1) * 900 * 1000)
    # bars ingested, blind tick fires late
    _drive(engine, market, buckets[-1:], lambda b: (b + 3) * 900 * 1000)
    # catch-up: evaluate again at the right boundary
    asyncio.run(engine.process_tick(now_ms=(buckets[-1] + 1) * 900 * 1000))
    from binquant_tpu.engine.buffer import fresh_mask

    fresh = np.asarray(
        fresh_mask(engine.state.buf15, buckets[-1] * 900)
    )
    assert fresh.sum() == 8


def test_clock_skew_before_bar_close_is_blind(market):
    """A tick whose clock is in the bucket BEFORE the delivered bars
    (skewed-behind clock) also evaluates empty freshness."""
    import numpy as np

    engine = make_stub_engine(capacity=CAP, window=WIN)
    buckets = sorted(market)
    early_ms = buckets[0] * 900 * 1000  # bars' own open bucket: bar not closed
    fired = _drive(engine, market, buckets[:1], lambda b: early_ms)
    from binquant_tpu.engine.buffer import fresh_mask

    evaluated_ts15 = (early_ms // 1000) // 900 * 900 - 900
    fresh = np.asarray(fresh_mask(engine.state.buf15, evaluated_ts15))
    assert fresh.sum() == 0
    assert fired[-1] == []
