"""Unified SLO plane drills (ISSUE 16).

Tier-1 keeps the cheap units: the registry's burn/recover hysteresis +
event cadence, lazy vs re-parameterizing registration, verdict folding
(including crashing invariant probes and the disabled ``ok: None``
shape), the ``GET /debug/slo`` route, the delivery-health collector's
p99 window + SLO feed, close→FINAL-ack lag accounting under
retry/backoff, cursor-lag math across a WAL replay-at-boot and across
the fan-out hub, and the golden-pinned slo_report / health_report
renders. The slow lane (``make delivery-smoke`` / ``make scenarios``)
adds the chaos drills asserting the burn→recover sequence and a sane
verdict through a 5xx storm and a reconnect storm.
"""

import asyncio
import json
from types import SimpleNamespace

import pytest

from binquant_tpu.io.delivery import (
    AT_LEAST_ONCE,
    LOSSY,
    DeliveryPlane,
    Envelope,
)
from binquant_tpu.obs.delivery_health import DeliveryHealth, _p99
from binquant_tpu.obs.events import EventLog, set_event_log
from binquant_tpu.obs.slo import SloRegistry, slo_verdict

DISABLED_VERDICT = {"enabled": False, "ok": None, "slos": {}, "invariants": {}}


@pytest.fixture
def event_log(tmp_path):
    path = tmp_path / "events.jsonl"
    log = EventLog(path)
    set_event_log(log)
    yield path
    log.close()
    set_event_log(None)


def _read_events(path) -> list[dict]:
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


class FakeSink:
    """Scriptable SignalSink: fail the first ``fail_times`` attempts."""

    def __init__(
        self, name="analytics", policy=LOSSY, fail_times=0, latency_s=0.0
    ):
        self.name = name
        self.policy = policy
        self.fail_times = fail_times
        self.latency_s = latency_s
        self.attempts = 0
        self.delivered = []

    def encode(self, signal):
        return {
            "strategy": signal.strategy,
            "symbol": signal.symbol,
            "seq": getattr(signal, "tick_seq", 0),
        }

    def to_wal(self, payload):
        return payload

    def from_wal(self, data):
        return data

    async def deliver(self, payload):
        self.attempts += 1
        if self.latency_s:
            await asyncio.sleep(self.latency_s)
        if self.fail_times > 0:
            self.fail_times -= 1
            raise ConnectionError("scripted sink failure")
        self.delivered.append(payload)


def make_plane(sinks, tmp_path=None, **kw):
    kw.setdefault("queue_max", 8)
    kw.setdefault("attempt_timeout_s", 1.0)
    kw.setdefault("retry_max", 3)
    kw.setdefault("backoff_s", 0.001)
    kw.setdefault("backoff_max_s", 0.005)
    kw.setdefault("breaker_threshold", 10)
    kw.setdefault("breaker_cooldown_s", 0.02)
    kw.setdefault("wal_fsync", False)
    if tmp_path is not None:
        kw.setdefault("wal_path", tmp_path / "outbox.wal.jsonl")
    return DeliveryPlane(sinks=sinks, **kw)


def fake_signal(i=0, strategy="mrf"):
    return SimpleNamespace(
        strategy=strategy,
        symbol=f"S{i:03d}USDT",
        trace_id=f"trace{i}",
        tick_seq=i,
    )


# -- registry hysteresis ------------------------------------------------------


def test_registry_burn_recover_hysteresis(event_log):
    reg = SloRegistry(event_every=3)
    reg.register("freshness", "freshness", 100.0)
    reg.observe("freshness", ok=True)
    assert _read_events(event_log) == []

    # burn ENTRY force-emits; the next two breaching obs stay silent
    # until the cadence (burn_obs % 3 == 0) re-emits
    for _ in range(4):
        reg.observe("freshness", ok=False, worst_ms=250.0)
    events = _read_events(event_log)
    assert [e["event"] for e in events] == ["slo_burn", "slo_burn"]
    assert events[0]["entering"] is True and events[0]["burn_obs"] == 1
    assert events[1]["entering"] is False and events[1]["burn_obs"] == 3

    # first clean observation: slo_recover with the burn length, state reset
    reg.observe("freshness", ok=True)
    events = _read_events(event_log)
    assert events[-1]["event"] == "slo_recover"
    assert events[-1]["burn_obs"] == 4
    cell = reg.verdict()["slos"]["freshness"]
    assert cell["ok"] is True and cell["burning"] is False
    assert cell["breaches"] == 4 and cell["recoveries"] == 1
    assert cell["burn_obs"] == 0

    # re-entry is a NEW burn entry event (hysteresis, not a one-shot)
    reg.observe("freshness", ok=False)
    assert _read_events(event_log)[-1]["entering"] is True


def test_register_reparameterizes_but_keeps_burn_state(event_log):
    reg = SloRegistry()
    reg.register("freshness", "freshness", 100.0)
    reg.observe("freshness", ok=False)
    assert reg.verdict()["slos"]["freshness"]["burning"] is True
    # config reload: budget moves, the in-progress burn survives
    cell = reg.register("freshness", "freshness", 200.0)
    assert cell["budget"] == 200.0 and cell["burning"] is True
    # ensure() never re-parameterizes (lazy per-sink minting)
    cell = reg.ensure("freshness", "freshness", 999.0)
    assert cell["budget"] == 200.0
    # unregistered observations are ignored, not minted
    reg.observe("nonesuch", ok=False)
    assert "nonesuch" not in reg.verdict()["slos"]


def test_verdict_folding_and_invariants(event_log):
    reg = SloRegistry()
    reg.register("a", "freshness", 1.0)
    reg.register("b", "delivery", 2.0)
    reg.observe("a", ok=True)
    reg.observe("b", ok=True)
    reg.add_invariant("good", lambda: {"ok": True, "detail": 7})
    assert reg.verdict()["ok"] is True

    # one burning SLO flips the fold
    reg.observe("b", ok=False)
    v = reg.verdict()
    assert v["ok"] is False and v["slos"]["b"]["ok"] is False

    # a failing invariant flips it even with every SLO green
    reg.observe("b", ok=True)
    reg.add_invariant("bad", lambda: {"ok": False, "count": 3})
    v = reg.verdict()
    assert v["ok"] is False
    assert v["invariants"]["bad"] == {"ok": False, "count": 3}
    assert v["invariants"]["good"]["detail"] == 7

    # a CRASHING probe reads failed, never green; bare truthy coerces
    reg.add_invariant(
        "crash", lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    reg.add_invariant("bare", lambda: True)
    inv = reg.invariants_report()
    assert inv["crash"]["ok"] is False and "boom" in inv["crash"]["error"]
    assert inv["bare"] == {"ok": True}
    # a dict without ok defaults to failed (no accidental green)
    reg.add_invariant("shapeless", lambda: {"count": 1})
    assert reg.invariants_report()["shapeless"]["ok"] is False


def test_disabled_registry_and_missing_registry_verdict(event_log):
    reg = SloRegistry(enabled=False)
    reg.register("a", "freshness", 1.0)
    reg.observe("a", ok=False)
    assert reg.verdict() == DISABLED_VERDICT
    assert _read_events(event_log) == []
    assert slo_verdict(None) == DISABLED_VERDICT
    assert slo_verdict(reg) == DISABLED_VERDICT


# -- GET /debug/slo -----------------------------------------------------------


def test_debug_slo_route(event_log):
    from binquant_tpu.obs.exposition import MetricsServer

    def get(server, target="/debug/slo"):
        raw = server._route(target)
        head, body = raw.split(b"\r\n\r\n", 1)
        return head.decode().split()[1], json.loads(body)

    # unconfigured: a JSON no-op at 200 — probes read disabled, not down
    bare = MetricsServer(health_fn=lambda: {"status": "ok"})
    status, payload = get(bare)
    assert status == "200" and payload == DISABLED_VERDICT

    reg = SloRegistry()
    reg.register("freshness", "freshness", 100.0)
    reg.observe("freshness", ok=False)
    reg.add_invariant("zero_loss", lambda: {"ok": True})
    server = MetricsServer(health_fn=lambda: {"status": "ok"}, slo=reg)
    status, payload = get(server)
    assert status == "200"
    assert payload["enabled"] is True and payload["ok"] is False
    assert payload["slos"]["freshness"]["burning"] is True
    assert payload["invariants"]["zero_loss"]["ok"] is True
    assert payload["event_every"] == reg.event_every

    # a crashing snapshot must not read as success to probes
    reg.snapshot = lambda: (_ for _ in ()).throw(RuntimeError())
    status, payload = get(server)
    assert status == "500" and payload == {"error": "slo_snapshot_failed"}


# -- delivery-health collector ------------------------------------------------


def test_p99_nearest_rank():
    assert _p99([5.0]) == 5.0
    assert _p99(list(range(1, 101))) == 99
    assert _p99([1.0, 2.0, 3.0, 50.0]) == 50.0  # small window -> max


def test_delivery_health_window_and_slo_feed(event_log):
    reg = SloRegistry(event_every=1)
    dh = DeliveryHealth(enabled=True, window=4, slo=reg, slo_ms=10.0)
    for _ in range(4):
        dh.on_ack("analytics", 2.0)
    assert reg.verdict()["slos"]["delivery.analytics"]["ok"] is True

    # one breaching lag pins the 4-sample p99 (= window max) over budget
    dh.on_ack("analytics", 50.0, attempts=2)
    v = reg.verdict()["slos"]["delivery.analytics"]
    assert v["burning"] is True and v["last"]["p99_ms"] == 50.0
    assert v["last"]["attempts"] == 2

    # the breach washes out of the rolling window -> recover
    for _ in range(4):
        dh.on_ack("analytics", 1.0)
    assert reg.verdict()["slos"]["delivery.analytics"]["ok"] is True
    kinds = [e["event"] for e in _read_events(event_log)]
    assert "slo_burn" in kinds and "slo_recover" in kinds

    snap = dh.snapshot()
    assert snap["sinks"]["analytics"]["acks"] == 9
    assert snap["sinks"]["analytics"]["last_lag_ms"] == 1.0

    # negative lag clamps (clock skew must not corrupt the window);
    # disabled collectors are no-ops
    dh.on_ack("analytics", -5.0)
    assert dh.last_lag_ms["analytics"] == 0.0
    off = DeliveryHealth(enabled=False, slo=reg, slo_ms=10.0)
    off.on_ack("analytics", 1e9)
    assert off.snapshot()["sinks"] == {}


def test_lag_measured_to_final_ack_under_retry(tmp_path, event_log):
    """Two scripted failures + backoff before the third attempt lands:
    ONE on_ack per envelope, with the lag spanning every attempt — not
    the first try's."""
    at = FakeSink(
        "autotrade", policy=AT_LEAST_ONCE, fail_times=2, latency_s=0.02
    )
    dh = DeliveryHealth(enabled=True, window=8)
    plane = make_plane([at], tmp_path, health=dh)

    async def go():
        plane.start()
        plane.enqueue_fired(fake_signal(0), tick_ms=1000)
        assert await plane.drain(timeout_s=5.0)
        await plane.aclose()

    asyncio.run(go())
    assert at.attempts == 3 and len(at.delivered) == 1
    snap = dh.snapshot()["sinks"]["autotrade"]
    assert snap["acks"] == 1  # final ack only, not one per attempt
    # 3 attempts x 20ms sink latency (+ backoff) — first-attempt
    # accounting would read ~20ms
    assert snap["last_lag_ms"] >= 40.0
    # per-attempt sink spans joined to the tick's trace rode the log
    spans = [e for e in _read_events(event_log) if e["event"] == "sink_span"]
    assert [s["attempt"] for s in spans] == [1, 2, 3]
    assert {s["trace_id"] for s in spans} == {"trace0"}
    assert [s["outcome"] for s in spans] == [
        "ConnectionError", "ConnectionError", "ok",
    ]


# -- cursor lag ---------------------------------------------------------------


def test_cursor_lag_across_replay_at_boot(tmp_path, event_log):
    """Unacked WAL records from a killed process count behind head at
    boot (queued + deferred), then drain to zero — and the replayed acks
    report cross-process lag through the WAL wall-clock anchor."""
    victim = make_plane(
        [FakeSink("autotrade", policy=AT_LEAST_ONCE)], tmp_path
    )
    for i in range(3):
        victim.enqueue(
            Envelope(
                entry_id=f"t{i}/{i}/mrf/S{i:03d}USDT",
                sink="autotrade",
                payload={"seq": i},
                ts_ms=1000 + i,
                lag0_ms=5.0,
                trace_id=f"t{i}",
            )
        )
    assert victim.watermarks()["autotrade"]["cursor_lag"] == 3
    victim.wal.close()  # hard kill: nothing acked

    # boot replay re-enqueues the backlog; probe the watermark BEFORE
    # any worker runs (a separate never-started plane — start() would
    # replay again)
    probe = make_plane(
        [FakeSink("autotrade", policy=AT_LEAST_ONCE)], tmp_path
    )
    probe._replay_wal()
    marks = probe.watermarks()["autotrade"]
    assert marks["cursor_lag"] == 3
    assert marks["oldest_unacked_ms"] > 0.0
    probe.wal.close()

    at = FakeSink("autotrade", policy=AT_LEAST_ONCE)
    dh = DeliveryHealth(enabled=True, window=8)
    resumed = make_plane([at], tmp_path, health=dh)

    async def go():
        resumed.start()
        assert await resumed.drain(timeout_s=5.0)
        await resumed.aclose()

    asyncio.run(go())
    assert len(at.delivered) == 3
    marks = resumed.watermarks()["autotrade"]
    assert marks["cursor_lag"] == 0 and marks["oldest_unacked_ms"] == 0.0
    # replayed acks carried the lag0 + wall-delta anchor (>= lag0, never
    # the meaningless in-process perf_counter delta)
    assert dh.snapshot()["sinks"]["autotrade"]["acks"] == 3
    assert dh.last_lag_ms["autotrade"] >= 5.0


def test_fanout_hub_cursor_lag_math():
    from binquant_tpu.fanout.hub import FanoutHub, _Connection

    hub = FanoutHub(slot_of=lambda u: None, conn_queue_max=4)
    assert hub.cursor_lag() == 0  # no conns, no head

    written = _Connection("u0", 0, "ws", 4)
    written.last_seq = 6
    fresh = _Connection("u1", 1, "ws", 4)  # connected, nothing written
    fresh.queue.put_nowait((0, "{}", None))
    fresh.queue.put_nowait((1, "{}", None))
    hub._conns.update({written, fresh})
    hub.head_seq = 10
    # laggiest consumer wins: head - last_seq for writers, queued
    # backlog for connections that have not written yet
    assert hub.cursor_lag() == 4
    written.last_seq = 1
    assert hub.cursor_lag() == 9
    assert hub.snapshot()["cursor_lag"] == 9


# -- report goldens -----------------------------------------------------------


SLO_EVENTS = [
    {"event": "slo_burn", "slo": "delivery.autotrade", "kind": "delivery",
     "budget": 25.0, "unit": "ms", "burn_obs": 1, "entering": True},
    {"event": "slo_burn", "slo": "delivery.autotrade", "kind": "delivery",
     "budget": 25.0, "unit": "ms", "burn_obs": 4, "entering": False},
    {"event": "slo_recover", "slo": "delivery.autotrade",
     "kind": "delivery", "burn_obs": 6},
    {"event": "slo_burn", "slo": "staleness", "kind": "staleness",
     "budget": 0.0, "unit": "rows", "burn_obs": 1, "entering": True},
]


def test_slo_report_golden(tmp_path):
    from tools.slo_report import load_slo_events, render_report

    log = tmp_path / "events.jsonl"
    lines = [json.dumps(e) for e in SLO_EVENTS]
    lines.insert(1, '{"torn')  # corrupt line skipped, not fatal
    log.write_text("\n".join(lines) + "\n")
    report = render_report(load_slo_events(log))
    assert report == (
        "burn     delivery.autotrade     kind=delivery budget=25.0ms\n"
        "burning  delivery.autotrade     still breaching (obs 4)\n"
        "recover  delivery.autotrade     after 6 breaching obs\n"
        "burn     staleness              kind=staleness budget=0.0rows\n"
        "\n"
        "slo                    kind           budget  burns"
        " recovers  longest  status\n"
        "delivery.autotrade     delivery       25.0ms      1"
        "        1        6  ok\n"
        "staleness              staleness     0.0rows      1"
        "        0        0  BURNING\n"
        "verdict  BURNING (staleness)"
    )
    # the filter keeps only one SLO's history
    filtered = render_report(load_slo_events(log), slo="delivery.autotrade")
    assert "staleness" not in filtered
    assert filtered.endswith("verdict  ok (1 slo clean at log tail)")


def test_health_report_delivery_slo_section(tmp_path):
    from tools.health_report import load_events, render, summarize

    log = tmp_path / "events.jsonl"
    records = [
        {"event": "delivery_ack", "sink": "autotrade", "attempts": 2},
        {"event": "delivery_ack", "sink": "telegram", "attempts": 1},
        {"event": "delivery_shed", "sink": "analytics", "reason": "x"},
        {"event": "delivery_breaker", "sink": "autotrade", "state": "open"},
    ] + SLO_EVENTS
    log.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    report = render(summarize(load_events(log)))
    assert (
        "== delivery / SLO ==\n"
        "  acks autotrade=1 telegram=1  sheds 1  breaker_transitions 1\n"
        "  slo delivery.autotrade     kind delivery   budget     25.0ms"
        "  burns 1  recovers 1  status ok\n"
        "  slo staleness              kind staleness  budget    0.0rows"
        "  burns 1  recovers 0  status BURNING"
    ) in report

    # logs without delivery/SLO events render the section-free report
    # byte-identically to the pre-ISSUE-16 format
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps({"event": "compile_summary"}) + "\n")
    assert "delivery / SLO" not in render(summarize(load_events(bare)))
