"""The concurrent soak judge (ISSUE 18).

Every chaos lane so far judged one plane in isolation; the soak drill
runs them all at once, so the verdict needs an attribution layer on top
of the unified SLO registry (obs/slo.py): WHICH injected fault does each
burn/recover episode belong to, and did every injected fault actually
trip the plane it targets?

* :class:`FaultWindow` / :class:`FaultSchedule` — the fault script: one
  named tick window per injected fault, declaring the planes it MAY trip
  (attribution set), the planes it MUST trip (non-vacuity set), and/or a
  named engine probe the drill resolves at the end (faults whose
  signature is a routing reason or a WAL fact, not an SLO burn).

* :class:`SoakJudge` — rides the registry's ``slo_burn``/``slo_recover``
  and ``invariant_probe_failed`` events (the same event-log tap idiom as
  the chaos drills), accumulates per-plane burn/recover EPISODES, and
  attributes each episode to the fault window(s) it overlaps. Folding
  rules, per the ISSUE-18 contract:

  - a burn whose ENTRY tick sits inside no matching fault window is an
    **unattributed breach** → verdict failure;
  - a fault window whose must-trip planes never burned (and whose probe,
    if any, read false) is a **non-vacuity failure** → the drill proved
    nothing about that fault → verdict failure;
  - an episode still burning at drill end fails its plane;
  - end-state invariants must all pass.

  The judge survives the drill's kill/checkpoint-restore: the resumed
  engine's fresh registry is re-:meth:`attach`-ed and an episode that was
  open at the kill continues (a post-restore ``entering`` burn of the
  same SLO extends it instead of opening a second one); an open episode
  that never burns again after the restore is closed AT the restore —
  the restart healed it.

The judge is observation-driven and engine-free: tests feed it synthetic
events through :meth:`on_event` directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.slo import SloRegistry

#: canonical SLO/invariant name → judged plane
_PLANES = ("freshness", "staleness", "delivery", "fanout", "parity")


def plane_of(name: str) -> str:
    """Map an SLO or invariant name to its judged plane."""
    if name.startswith("delivery.fanout") or name.startswith("fanout"):
        return "fanout"
    if name.startswith("delivery"):
        return "delivery"
    if name == "freshness":
        return "freshness"
    if name == "staleness" or name.startswith("ingest"):
        return "staleness"
    if name.endswith("parity"):
        return "parity"
    return "other"


@dataclass
class FaultWindow:
    """One injected fault's script entry: tick window + expectations."""

    name: str
    kind: str
    start: int
    end: int
    #: planes whose burns inside [start, end] attribute to this fault
    may: tuple[str, ...] = ()
    #: planes that MUST burn (or the probe must pass) — non-vacuity
    expect: tuple[str, ...] = ()
    #: named engine probe the drill resolves at finish() (routing
    #: reasons, WAL facts, cursor lag — fault signatures with no SLO)
    probe: str | None = None
    tripped: set = field(default_factory=set)

    def covers(self, tick: int) -> bool:
        return self.start <= tick <= self.end

    def overlaps(self, start: int, end: int) -> bool:
        return start <= self.end and end >= self.start


class FaultSchedule:
    """The drill's ordered fault script."""

    def __init__(self, windows: list[FaultWindow]) -> None:
        self.windows = list(windows)

    def active(self, tick: int) -> list[FaultWindow]:
        return [w for w in self.windows if w.covers(tick)]

    def phase_label(self, tick: int) -> str:
        """The registry phase label for one tick: the active fault names
        joined (stable order), or ``clear``."""
        names = [w.name for w in self.active(tick)]
        return "+".join(names) if names else "clear"

    def matching(self, plane: str, tick: int) -> list[FaultWindow]:
        return [
            w
            for w in self.active(tick)
            if plane in w.may or plane in w.expect
        ]


class SoakJudge:
    """Concurrent per-plane/per-fault episode accumulator + verdict."""

    def __init__(
        self, schedule: FaultSchedule, probe_every: int = 2
    ) -> None:
        self.schedule = schedule
        self.probe_every = max(int(probe_every), 1)
        self.registry: SloRegistry | None = None
        self.tick = -1
        self.attaches: list[int] = []
        self.episodes: list[dict] = []
        self._open: dict[str, dict] = {}
        self.probe_failures: list[dict] = []
        self._probe_results: dict[str, bool] = {}
        self._evlog = None
        self._orig_emit = None

    # -- lifecycle ------------------------------------------------------------

    def attach(self, registry: SloRegistry | None) -> None:
        """Bind (or re-bind after a kill/restore) the engine's registry.
        Episodes open at re-attach are marked pending: a post-restore
        burn of the same SLO continues them; silence closes them at the
        restore tick (the restart healed the plane)."""
        self.registry = registry
        self.attaches.append(self.tick)
        if len(self.attaches) > 1:
            for ep in self._open.values():
                ep["pending_restore"] = self.tick
                ep["segments"] = ep.get("segments", 1)

    def install(self) -> None:
        """Tap slo_burn/slo_recover/invariant_probe_failed off the event
        log emit path (the chaos-drill idiom — works with the process
        log disabled)."""
        self._evlog = get_event_log()
        self._orig_emit = self._evlog.emit

        def _tap(event: str, **fields):
            if event in (
                "slo_burn",
                "slo_recover",
                "invariant_probe_failed",
            ):
                self.on_event(event, fields)
            return self._orig_emit(event, **fields)

        self._evlog.emit = _tap  # type: ignore[method-assign]

    def uninstall(self) -> None:
        if self._evlog is not None and self._orig_emit is not None:
            self._evlog.emit = self._orig_emit  # type: ignore[method-assign]
            self._evlog = None
            self._orig_emit = None

    def note_tick(self, tick: int) -> None:
        """Advance the judge clock: stamp the registry's phase window and
        run the mid-drill invariant probe cadence."""
        self.tick = int(tick)
        if self.registry is not None:
            self.registry.begin_phase(self.schedule.phase_label(self.tick))
            if self.tick % self.probe_every == 0:
                self.registry.probe_invariants()

    # -- event accumulation ----------------------------------------------------

    def on_event(self, event: str, fields: dict) -> None:
        if event == "slo_burn":
            self._on_burn(fields)
        elif event == "slo_recover":
            self._on_recover(fields)
        elif event == "invariant_probe_failed":
            self._on_probe_failure(fields)

    def _attribute(self, plane: str, tick: int) -> list[str]:
        faults = self.schedule.matching(plane, tick)
        for w in faults:
            w.tripped.add(plane)
        return [w.name for w in faults]

    def _on_burn(self, fields: dict) -> None:
        name = str(fields.get("slo", "?"))
        ep = self._open.get(name)
        if ep is not None:
            # continuation: cadence re-emits extend the open episode, and
            # a post-restore entering burn resumes it (episode continuity
            # across the kill — the fresh registry forgot it was burning,
            # so its burn_obs restarts; the carry keeps the true length)
            if (
                fields.get("entering")
                and ep.pop("pending_restore", None) is not None
            ):
                ep["segments"] = ep.get("segments", 1) + 1
                ep["carry"] = ep.get("burn_obs", 0)
            ep["burn_obs"] = ep.get("carry", 0) + int(
                fields.get("burn_obs", 1)
            )
            return
        plane = plane_of(name)
        ep = {
            "slo": name,
            "plane": plane,
            "start_tick": self.tick,
            "phase": fields.get("phase"),
            "burn_obs": int(fields.get("burn_obs", 1)),
            "faults": self._attribute(plane, self.tick),
        }
        self._open[name] = ep

    def _on_recover(self, fields: dict) -> None:
        name = str(fields.get("slo", "?"))
        ep = self._open.pop(name, None)
        if ep is None:
            return
        ep.pop("pending_restore", None)
        carry = ep.pop("carry", 0)
        ep["end_tick"] = self.tick
        ep["burn_obs"] = max(
            ep.get("burn_obs", 0), carry + int(fields.get("burn_obs", 0))
        )
        # recovery-overlap credit: a fault window the episode burned
        # THROUGH counts as tripped even when the burn entered during an
        # earlier overlapping fault (one global staleness SLO, two
        # staggered outages → one long episode spanning both windows)
        for w in self.schedule.windows:
            if (
                (ep["plane"] in w.may or ep["plane"] in w.expect)
                and w.overlaps(ep["start_tick"], ep["end_tick"])
            ):
                w.tripped.add(ep["plane"])
                if w.name not in ep["faults"]:
                    ep["faults"].append(w.name)
        self.episodes.append(ep)

    def _on_probe_failure(self, fields: dict) -> None:
        name = str(fields.get("invariant", "?"))
        plane = plane_of(name)
        self.probe_failures.append(
            {
                "invariant": name,
                "plane": plane,
                "tick": self.tick,
                "phase": fields.get("phase"),
                "faults": self._attribute(plane, self.tick),
            }
        )

    # -- the fold --------------------------------------------------------------

    def resolve_probe(self, name: str, ok: bool) -> None:
        """Record one engine-side fault probe's outcome (the drill calls
        this at the end for every FaultWindow.probe)."""
        self._probe_results[name] = bool(ok)

    def finish(self) -> None:
        """Close the books: episodes still open either heal at a pending
        restore boundary or stay open (= burning at drill end)."""
        for name in list(self._open):
            ep = self._open[name]
            restored_at = ep.pop("pending_restore", None)
            ep.pop("carry", None)
            if restored_at is not None:
                ep["end_tick"] = restored_at
                ep["recovered_by"] = "restore"
                self.episodes.append(ep)
                del self._open[name]

    def verdict(self) -> dict:
        """Fold everything into ONE machine-readable soak verdict."""
        episodes = sorted(
            self.episodes + list(self._open.values()),
            key=lambda e: (e["start_tick"], e["slo"]),
        )
        burning_at_end = sorted(self._open)
        unattributed = [
            e for e in episodes if not e.get("faults")
        ] + [p for p in self.probe_failures if not p.get("faults")]
        planes: dict[str, dict] = {}
        for plane in _PLANES:
            eps = [e for e in episodes if e["plane"] == plane]
            pfails = [
                p for p in self.probe_failures if p["plane"] == plane
            ]
            planes[plane] = {
                "episodes": len(eps),
                "max_burn_obs": max(
                    (e.get("burn_obs", 0) for e in eps), default=0
                ),
                "probe_failures": len(pfails),
                "unattributed": sum(
                    1 for e in eps + pfails if not e.get("faults")
                ),
                "burning_at_end": sorted(
                    e["slo"] for e in eps if e["slo"] in burning_at_end
                ),
                "ok": all(e.get("faults") for e in eps + pfails)
                and not any(e["slo"] in burning_at_end for e in eps),
            }
        faults = []
        vacuous: list[str] = []
        for w in self.schedule.windows:
            probe_ok = (
                self._probe_results.get(w.probe)
                if w.probe is not None
                else None
            )
            satisfied = bool(set(w.expect) & w.tripped) or bool(probe_ok)
            if (w.expect or w.probe is not None) and not satisfied:
                vacuous.append(w.name)
            faults.append(
                {
                    "name": w.name,
                    "kind": w.kind,
                    "window": [w.start, w.end],
                    "may": list(w.may),
                    "expect": list(w.expect),
                    "probe": w.probe,
                    "probe_ok": probe_ok,
                    "tripped": sorted(w.tripped),
                    "non_vacuous": w.name not in vacuous,
                }
            )
        end_state = (
            self.registry.verdict()
            if self.registry is not None
            else {"enabled": False, "ok": None, "slos": {}, "invariants": {}}
        )
        end_invariants_ok = all(
            inv.get("ok", False)
            for inv in end_state.get("invariants", {}).values()
        ) and end_state.get("enabled") is True
        ok = (
            not unattributed
            and not vacuous
            and not burning_at_end
            and end_invariants_ok
            and all(p["ok"] for p in planes.values())
        )
        return {
            "ok": ok,
            "ticks": self.tick + 1,
            "attaches": len(self.attaches),
            "planes": planes,
            "faults": faults,
            "episodes": episodes,
            "probe_failures": self.probe_failures,
            "unattributed": [
                {k: v for k, v in e.items() if k != "faults"}
                for e in unattributed
            ],
            "non_vacuity_failures": vacuous,
            "burning_at_end": burning_at_end,
            "end_state": end_state,
        }
