"""Live strategy kernels vs pandas oracles + crafted scenarios.

Oracles re-derive the reference pipelines (activity_burst_pump.py:51-158,
mean_reversion_fade.py:102-135, liquidation_sweep_pump.py:110-180) in pandas
on the same data the kernels see, then last-bar verdicts are compared across
a randomized symbol batch.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from binquant_tpu.engine import Field, apply_updates, empty_buffer
from binquant_tpu.enums import Direction, MicroRegimeCode, MicroTransitionCode
from binquant_tpu.strategies import (
    ABPParams,
    activity_burst_pump,
    compute_feature_pack,
    ladder_deployer,
    liquidation_sweep_pump,
    mean_reversion_fade,
    price_tracker,
)
from binquant_tpu.strategies.liquidation_sweep_pump import (
    ROUTE_ADP_NOT_EXTREME,
    ROUTE_LONG,
    ROUTE_SHORT,
)
from binquant_tpu.strategies.price_tracker import (
    ROUTE_NOT_RANGE,
    ROUTE_QUIET_HOURS,
    ROUTE_RS_INSUFFICIENT,
    ROUTE_SYMBOL_RANGE,
)
from tests.conftest import make_ohlcv
from tests.test_regime_routing_scoring import mk_context, mk_features

S_CAP = 16
WINDOW = 150


def fill_buffer(frames: dict[int, pd.DataFrame], window=WINDOW, cap=S_CAP):
    """Load row->DataFrame into a buffer (timestamps aligned per row)."""
    buf = empty_buffer(cap, window=window)
    n = max(len(df) for df in frames.values())
    for b in range(n):
        idx, tss, vals = [], [], []
        for row, df in frames.items():
            if b >= len(df):
                continue
            r = df.iloc[b]
            v = np.zeros(len(Field), dtype=np.float32)
            v[Field.OPEN], v[Field.HIGH] = r["open"], r["high"]
            v[Field.LOW], v[Field.CLOSE] = r["low"], r["close"]
            v[Field.VOLUME] = r["volume"]
            v[Field.QUOTE_VOLUME] = r.get("quote_asset_volume", r["volume"] * r["close"])
            v[Field.NUM_TRADES] = r.get("number_of_trades", 100)
            v[Field.DURATION_S] = 900
            idx.append(row)
            tss.append(int(r["open_time"]) // 1000)
            vals.append(v)
        buf = apply_updates(
            buf, np.array(idx, np.int32), np.array(tss, np.int32), np.stack(vals)
        )
    from binquant_tpu.engine import materialize

    # strategy kernels consume right-aligned windows; canonicalize the ring
    return materialize(buf)


def random_frames(rng, n_rows=10, n=WINDOW, vol=0.02):
    return {
        i: pd.DataFrame(make_ohlcv(rng, n=n, start_price=20 + i, vol=vol))
        for i in range(n_rows)
    }


# ---------------------------------------------------------------------------
# FeaturePack parity
# ---------------------------------------------------------------------------


class TestFeaturePack:
    @pytest.fixture(scope="class")
    def setup(self):
        rng = np.random.default_rng(31)
        frames = random_frames(rng)
        buf = fill_buffer(frames)
        pack = compute_feature_pack(buf)
        return frames, pack

    def test_rsi_variants(self, setup):
        frames, pack = setup
        for i, df in frames.items():
            closes = df["close"].astype(float)
            delta = closes.diff()
            gain, loss = delta.clip(lower=0), -delta.clip(upper=0)
            ag = gain.ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
            al = loss.ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
            wilder = float((100 * ag / (ag + al)).where((ag + al) != 0, 50.0).iloc[-1])
            np.testing.assert_allclose(float(pack.rsi_wilder[i]), wilder, rtol=1e-3)
            ags = gain.rolling(14).mean()
            als = loss.rolling(14).mean()
            sma = float((100 * ags / (ags + als)).where((ags + als) != 0, 50.0).iloc[-1])
            np.testing.assert_allclose(float(pack.rsi[i]), sma, rtol=1e-3)

    def test_macd_and_signal(self, setup):
        frames, pack = setup
        for i, df in frames.items():
            closes = df["close"].astype(float)
            line = (
                closes.ewm(span=12, adjust=False).mean()
                - closes.ewm(span=26, adjust=False).mean()
            )
            sig = line.ewm(span=9, adjust=False).mean()
            np.testing.assert_allclose(float(pack.macd[i]), float(line.iloc[-1]), rtol=1e-3, atol=1e-5)
            np.testing.assert_allclose(float(pack.macd_signal[i]), float(sig.iloc[-1]), rtol=1e-3, atol=1e-5)

    def test_mfi_bb_atr_vol(self, setup):
        frames, pack = setup
        for i, df in frames.items():
            h, l, c, v = (df[k].astype(float) for k in ("high", "low", "close", "volume"))
            tp = (h + l + c) / 3
            flow = tp * v
            d = tp.diff()
            pos = flow.where(d > 0, 0.0).rolling(14).sum()
            neg = flow.where(d < 0, 0.0).rolling(14).sum()
            mfi = float((100 * pos / (pos + neg)).where((pos + neg) != 0, 50.0).iloc[-1])
            np.testing.assert_allclose(float(pack.mfi[i]), mfi, rtol=1e-3)

            mid = c.rolling(20).mean()
            std = c.rolling(20).std(ddof=0)
            np.testing.assert_allclose(float(pack.bb_upper[i]), float((mid + 2 * std).iloc[-1]), rtol=1e-4)
            np.testing.assert_allclose(float(pack.bb_lower[i]), float((mid - 2 * std).iloc[-1]), rtol=1e-4)

            pc = c.shift(1)
            tr = pd.concat([h - l, (h - pc).abs(), (l - pc).abs()], axis=1).max(axis=1)
            atr = tr.rolling(14).mean()
            np.testing.assert_allclose(float(pack.atr[i]), float(atr.iloc[-1]), rtol=1e-3)
            np.testing.assert_allclose(float(pack.atr_ma[i]), float(atr.rolling(20).mean().iloc[-1]), rtol=1e-3)
            np.testing.assert_allclose(float(pack.volume_ma[i]), float(v.rolling(20).mean().iloc[-1]), rtol=1e-4)


# ---------------------------------------------------------------------------
# ActivityBurstPump: full pandas-oracle parity over a random batch
# ---------------------------------------------------------------------------


def abp_oracle_last(df: pd.DataFrame, p: ABPParams) -> tuple[bool, float]:
    """Reference compute_indicators (activity_burst_pump.py:51-158), last row."""
    bw = max(p.lookback_window, 2)
    v, qv = df["volume"].astype(float), df["quote_asset_volume"].astype(float)
    baseline = v.shift(2).rolling(bw - 1, min_periods=bw - 1).median()
    baseline_safe = baseline.clip(lower=p.min_baseline_volume)
    vr = v / baseline_safe
    qbaseline = qv.shift(2).rolling(bw - 1, min_periods=bw - 1).median()
    qbaseline_safe = qbaseline.clip(lower=p.min_baseline_volume)
    qr = qv / qbaseline_safe
    c, o, h, lo = (df[k].astype(float) for k in ("close", "open", "high", "low"))
    prev_close = c.shift(1).clip(lower=p.min_baseline_volume)
    rng_ = (h - lo).clip(lower=p.min_baseline_volume)
    body = (c - o).abs()
    jump = (c - c.shift(1)) / prev_close
    range_frac = rng_ / c.clip(lower=p.min_baseline_volume)
    body_frac = body / rng_
    cth = (h - c) / rng_
    bullish = c > o
    up3 = (c > c.shift(1)).rolling(3).sum()
    score = vr * qr * jump.clip(lower=0) * (1 + body_frac)
    thr = score.shift(1).rolling(p.score_lookback, min_periods=p.lookback_window).quantile(p.score_quantile)
    raw = (
        (v > p.volume_multiplier * baseline_safe)
        & (qv > p.quote_volume_multiplier * qbaseline_safe)
        & (jump > p.price_threshold)
        & (range_frac > p.min_range_frac)
        & (bullish & (body_frac > p.min_body_frac) & (cth < p.max_close_to_high))
        & (up3 >= p.min_recent_up_closes)
        & (score >= thr.fillna(0))
    )
    recent = raw.shift(1).rolling(p.cooldown_bars, min_periods=1).max().fillna(False).astype(bool)
    qualified = raw & ~recent
    return bool(qualified.iloc[-1]), float(score.iloc[-1])


def inject_burst(df: pd.DataFrame, at: int = -1, fix_trend: bool = True) -> pd.DataFrame:
    """Append/replace a bar that satisfies every burst condition."""
    df = df.copy()
    i = len(df) + at if at < 0 else at
    prev_close = df["close"].iloc[i - 1]
    o = prev_close
    c = prev_close * 1.03  # 3% jump
    h = c * 1.003  # close near high
    lo = o * 0.995
    v = df["volume"].iloc[max(0, i - 21):i - 1].median() * 4
    for col, val in (("open", o), ("close", c), ("high", h), ("low", lo), ("volume", v)):
        df.loc[df.index[i], col] = val
    df.loc[df.index[i], "quote_asset_volume"] = v * c
    if fix_trend:
        # two prior up-closes for the trend flag
        df.loc[df.index[i - 1], "close"] = df["close"].iloc[i - 2] * 1.001
    return df


class TestActivityBurstPump:
    def test_oracle_parity_random_batch(self):
        rng = np.random.default_rng(41)
        p = ABPParams()
        frames = random_frames(rng, n_rows=12, vol=0.03)
        # make some rows bursty so both verdicts appear
        for i in (2, 5, 9):
            frames[i] = inject_burst(frames[i])
        buf = fill_buffer(frames)
        ctx = mk_context(n=S_CAP, valid=False)  # no context -> emit allowed, autotrade off
        out = activity_burst_pump(buf, ctx, p)
        for i, df in frames.items():
            want, want_score = abp_oracle_last(df, p)
            got = bool(out.trigger[i])
            assert got == want, f"row {i}: kernel {got} oracle {want}"
            if want:
                np.testing.assert_allclose(float(out.score[i]), want_score, rtol=1e-3)
                assert not bool(out.autotrade[i])  # no context

    def test_context_gate(self):
        rng = np.random.default_rng(43)
        frames = {0: inject_burst(pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.03)))}
        buf = fill_buffer(frames)
        # valid context, gate open (RANGE regime, stable) -> autotrade on
        micro = np.full(S_CAP, int(MicroRegimeCode.RANGE), np.int32)
        ctx_open = mk_context(n=S_CAP, features=mk_features(n=S_CAP, micro_regime=micro))
        out = activity_burst_pump(buf, ctx_open)
        assert bool(out.trigger[0]) and bool(out.autotrade[0])
        # valid context, gate closed (transitioning) -> suppressed entirely
        ctx_closed = mk_context(n=S_CAP, regime_is_transitioning=True)
        out2 = activity_burst_pump(buf, ctx_closed)
        assert not bool(out2.trigger[0])

    def test_cooldown_after_recent_raw_signal(self):
        rng = np.random.default_rng(47)
        df = pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.03))
        df = inject_burst(df, at=-2)  # burst on the PREVIOUS bar
        # burst again on the last bar WITHOUT rewriting bar -2 (the previous
        # burst already closed up, satisfying the trend flag)
        df = inject_burst(df, at=-1, fix_trend=False)
        buf = fill_buffer({0: df})
        out = activity_burst_pump(buf, mk_context(n=S_CAP, valid=False))
        want, _ = abp_oracle_last(df, ABPParams())
        assert bool(out.trigger[0]) == want
        assert not want  # oracle agrees: cooldown suppresses the second


# ---------------------------------------------------------------------------
# MeanReversionFade
# ---------------------------------------------------------------------------


def craft_mrf_long(rng, n=WINDOW):
    """Monotonic decline then a green hammer at the lower band."""
    d = make_ohlcv(rng, n=n, start_price=100, vol=0.004, drift=-0.004)
    df = pd.DataFrame(d)
    i = len(df) - 1
    prev_close = df["close"].iloc[i - 1]
    o = prev_close * 0.97
    c = o * 1.004  # green
    df.loc[df.index[i], "open"] = o
    df.loc[df.index[i], "close"] = c
    df.loc[df.index[i], "high"] = c * 1.001
    df.loc[df.index[i], "low"] = o * 0.998
    df.loc[df.index[i], "volume"] = df["volume"].iloc[-21:-1].mean() * 2
    return df


class TestMeanReversionFade:
    def test_long_fire_and_dedupe(self):
        rng = np.random.default_rng(53)
        df = craft_mrf_long(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
        out, carry2 = mean_reversion_fade(pack, jnp.asarray(True), carry)

        # oracle setup check
        closes = df["close"].astype(float)
        delta = closes.diff()
        ag = delta.clip(lower=0).ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
        al = (-delta.clip(upper=0)).ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
        rsi = float((100 * ag / (ag + al)).where((ag + al) != 0, 50.0).iloc[-1])
        mid = closes.rolling(20).mean()
        std = closes.rolling(20).std(ddof=0)
        bb_low = float((mid - 2 * std).iloc[-1])
        want = rsi <= 25 and float(closes.iloc[-1]) <= bb_low
        assert bool(out.trigger[0]) == want
        if want:
            assert int(out.direction[0]) == int(Direction.LONG)
            assert bool(out.autotrade[0])
            assert float(out.stop_loss_pct[0]) > 0
            np.testing.assert_allclose(
                float(out.score[0]), round(1.0 + max(0.0, (25 - rsi) / 25), 4), rtol=1e-3
            )
            # same candle again -> deduped
            out2, _ = mean_reversion_fade(pack, jnp.asarray(True), carry2)
            assert not bool(out2.trigger[0])

    def test_futures_gate_and_vetoes(self):
        rng = np.random.default_rng(59)
        df = craft_mrf_long(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
        out, _ = mean_reversion_fade(pack, jnp.asarray(False), carry)
        assert not bool(out.trigger[0])  # spot -> never

        # volume veto: volume below its 20-bar MA
        df2 = df.copy()
        df2.loc[df2.index[-1], "volume"] = df2["volume"].iloc[-21:-1].mean() * 0.1
        pack2 = compute_feature_pack(fill_buffer({0: df2}))
        out2, _ = mean_reversion_fade(pack2, jnp.asarray(True), carry)
        assert not bool(out2.trigger[0])


# ---------------------------------------------------------------------------
# LiquidationSweepPump
# ---------------------------------------------------------------------------


def lsp_oracle(df: pd.DataFrame, oi_growth, wh=3):
    """pump score pipeline (liquidation_sweep_pump.py:110-145,163-180)."""
    v, c, h, lo = (df[k].astype(float) for k in ("volume", "close", "high", "low"))
    rel = v / v.rolling(wh * 2).mean().shift(wh)
    mom = c.pct_change(periods=wh)
    rf = (h.rolling(wh * 2).max() - lo.rolling(wh * 2).min()) / c
    oi = 1 + max(0, (oi_growth - 1)) if oi_growth else 1.0
    ps = rel * (1 + mom) * oi / rf
    smooth = ps.rolling(2).mean()
    thr = smooth.iloc[-48:].quantile(0.80)
    trigger_score = max(float(smooth.iloc[-1]), float(ps.iloc[-1]))
    return trigger_score >= thr, trigger_score


class TestLiquidationSweepPump:
    def _favorable_context(self):
        # washed-out breadth recovering + BTC up -> LONG route
        return mk_context(n=S_CAP, market_stress_score=0.1)

    def test_oracle_score_parity_and_routing(self):
        rng = np.random.default_rng(61)
        frames = random_frames(rng, n_rows=10, vol=0.02)
        # pump the last bars of a few rows
        for i in (1, 4, 7):
            df = frames[i]
            df.loc[df.index[-1], "volume"] = df["volume"].iloc[-10:-4].mean() * 6
            df.loc[df.index[-1], "close"] = df["close"].iloc[-4] * 1.05
        buf = fill_buffer(frames)
        ctx = self._favorable_context()
        oi = np.full(S_CAP, 1.05, np.float32)
        out = liquidation_sweep_pump(
            buf, ctx, jnp.asarray(oi),
            jnp.asarray(-0.5), jnp.asarray(-0.6),  # washed & increasing
            jnp.asarray(0.003),  # btc up
        )
        for i, df in frames.items():
            want_fire, want_score = lsp_oracle(df, 1.05)
            assert bool(out.trigger[i]) == want_fire, f"row {i}"
            if want_fire:
                np.testing.assert_allclose(float(out.score[i]), want_score, rtol=1e-2)
                assert int(out.direction[i]) == int(Direction.LONG)
                assert int(out.diagnostics["route"][i]) == ROUTE_LONG

    def test_oi_confirmation_blocks(self):
        rng = np.random.default_rng(67)
        frames = random_frames(rng, n_rows=2, vol=0.02)
        df = frames[0]
        df.loc[df.index[-1], "volume"] = df["volume"].iloc[-10:-4].mean() * 6
        df.loc[df.index[-1], "close"] = df["close"].iloc[-4] * 1.05
        buf = fill_buffer(frames)
        oi = np.full(S_CAP, 1.01, np.float32)  # below 1.02
        out = liquidation_sweep_pump(
            buf, self._favorable_context(), jnp.asarray(oi),
            jnp.asarray(-0.5), jnp.asarray(-0.6), jnp.asarray(0.003),
        )
        assert not bool(out.trigger[0])

    def test_short_route_needs_weak_symbol(self):
        rng = np.random.default_rng(71)
        frames = random_frames(rng, n_rows=1, vol=0.02)
        df = frames[0]
        df.loc[df.index[-1], "volume"] = df["volume"].iloc[-10:-4].mean() * 8
        df.loc[df.index[-1], "close"] = df["close"].iloc[-4] * 1.06
        buf = fill_buffer(frames)
        weak = mk_features(n=S_CAP, 
            relative_strength_vs_btc=np.full(S_CAP, -0.01, np.float32),
            trend_score=np.full(S_CAP, -0.01, np.float32),
            above_ema20=np.zeros(S_CAP, dtype=bool),
        )
        ctx = mk_context(n=S_CAP, market_stress_score=0.1, features=weak)
        out = liquidation_sweep_pump(
            buf, ctx, jnp.asarray(np.full(S_CAP, 1.05, np.float32)),
            jnp.asarray(0.5), jnp.asarray(0.6),  # hot & falling
            jnp.asarray(0.001),  # btc stalled
        )
        if bool(out.trigger[0]):
            assert int(out.direction[0]) == int(Direction.SHORT)
            assert int(out.diagnostics["route"][0]) == ROUTE_SHORT

    def test_adp_not_extreme_blocks(self):
        rng = np.random.default_rng(73)
        frames = random_frames(rng, n_rows=1, vol=0.02)
        df = frames[0]
        df.loc[df.index[-1], "volume"] = df["volume"].iloc[-10:-4].mean() * 8
        df.loc[df.index[-1], "close"] = df["close"].iloc[-4] * 1.06
        buf = fill_buffer(frames)
        out = liquidation_sweep_pump(
            buf, self._favorable_context(),
            jnp.asarray(np.full(S_CAP, 1.05, np.float32)),
            jnp.asarray(0.0), jnp.asarray(-0.1), jnp.asarray(0.003),
        )
        assert not bool(out.trigger[0])
        assert int(out.diagnostics["route"][0]) == ROUTE_ADP_NOT_EXTREME


# ---------------------------------------------------------------------------
# PriceTracker
# ---------------------------------------------------------------------------


def craft_oversold(rng, n=WINDOW):
    """Persistent selloff: RSI pinned low, MACD negative, MFI starved."""
    d = make_ohlcv(rng, n=n, start_price=100, vol=0.002, drift=-0.006)
    df = pd.DataFrame(d)
    # force strictly falling typical price over the last 20 bars so every
    # money flow is negative -> MFI = 0 deterministically
    tail = 20
    base = float(df["close"].iloc[-tail - 1])
    for j in range(tail):
        i = len(df) - tail + j
        c = base * (1 - 0.004 * (j + 1))
        df.loc[df.index[i], "open"] = c * 1.002
        df.loc[df.index[i], "close"] = c
        df.loc[df.index[i], "high"] = c * 1.003
        df.loc[df.index[i], "low"] = c * 0.998
    return df


class TestPriceTracker:
    def _range_context(self, rs=0.01):
        micro = np.full(S_CAP, int(MicroRegimeCode.RANGE), np.int32)
        return mk_context(n=S_CAP, 
            features=mk_features(n=S_CAP, 
                micro_regime=micro,
                relative_strength_vs_btc=np.full(S_CAP, rs, np.float32),
            ),
            advancers_ratio=0.55,
            long_tailwind=0.1,
            short_tailwind=-0.05,
            market_stress_score=0.1,
        )

    def test_fires_with_autotrade_in_stable_range(self):
        rng = np.random.default_rng(79)
        df = craft_oversold(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        assert float(pack.rsi[0]) < 30 and float(pack.macd[0]) < 0
        if not float(pack.mfi[0]) < 20:
            pytest.skip("crafted data did not starve MFI")
        carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
        out, carry2 = price_tracker(
            pack, self._range_context(), jnp.asarray(False), carry
        )
        assert bool(out.trigger[0])
        assert bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == ROUTE_SYMBOL_RANGE
        assert float(out.score[0]) > 1.0
        # cooldown: same close_time again -> suppressed
        out2, _ = price_tracker(pack, self._range_context(), jnp.asarray(False), carry2)
        assert not bool(out2.trigger[0])

    def test_routing_blocks_autotrade_but_emits(self):
        rng = np.random.default_rng(83)
        df = craft_oversold(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        if not (float(pack.rsi[0]) < 30 and float(pack.mfi[0]) < 20):
            pytest.skip("crafted data did not reach entry thresholds")
        carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
        # weak RS -> autotrade denied, signal still emitted
        out, _ = price_tracker(
            pack, self._range_context(rs=0.0), jnp.asarray(False), carry
        )
        if bool(out.trigger[0]):
            assert not bool(out.autotrade[0])
            assert int(out.diagnostics["route"][0]) == ROUTE_RS_INSUFFICIENT
        # TREND_UP market -> not RANGE
        micro = np.full(S_CAP, int(MicroRegimeCode.RANGE), np.int32)
        from binquant_tpu.enums import MarketRegimeCode

        ctx = mk_context(n=S_CAP, 
            market_regime=np.int32(MarketRegimeCode.TREND_UP),
            features=mk_features(n=S_CAP, micro_regime=micro),
            advancers_ratio=0.55,
            market_stress_score=0.1,
        )
        out2, _ = price_tracker(pack, ctx, jnp.asarray(False), carry)
        if bool(out2.trigger[0]):
            assert int(out2.diagnostics["route"][0]) == ROUTE_NOT_RANGE

    def test_quiet_hours_flips_autotrade(self):
        rng = np.random.default_rng(89)
        df = craft_oversold(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        if not (float(pack.rsi[0]) < 30 and float(pack.mfi[0]) < 20):
            pytest.skip("crafted data did not reach entry thresholds")
        carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
        out, _ = price_tracker(pack, self._range_context(), jnp.asarray(True), carry)
        if bool(out.trigger[0]):
            assert not bool(out.autotrade[0])
            assert int(out.diagnostics["route"][0]) == ROUTE_QUIET_HOURS


# ---------------------------------------------------------------------------
# LadderDeployer
# ---------------------------------------------------------------------------


def craft_stable_range(rng, n=WINDOW):
    """Flat low-vol series: stable BB width, price mid-range."""
    d = make_ohlcv(rng, n=n, start_price=50, vol=0.004, drift=0.0)
    return pd.DataFrame(d)


def craft_deploying_range(n=WINDOW):
    """Deterministic range that MUST deploy: a ~1% sinusoidal oscillation
    around 50 gives a rolling-20 BB width of ~2.8% (inside the 1.5–8%
    band), stable over 8 candles, with the last close inside the bands."""
    t = np.arange(n, dtype=float)
    close = 50.0 * (1 + 0.01 * np.sin(t * 0.7))
    open_ = np.concatenate([[50.0], close[:-1]])
    high = np.maximum(open_, close) * 1.0005
    low = np.minimum(open_, close) * 0.9995
    volume = np.full(n, 1000.0)
    open_time = 1_700_000_000_000 + 900_000 * np.arange(n, dtype=np.int64)
    return pd.DataFrame(
        {
            "open_time": open_time,
            "close_time": open_time + 900_000 - 1,
            "open": open_,
            "high": high,
            "low": low,
            "close": close,
            "volume": volume,
            "quote_asset_volume": volume * close,
            "number_of_trades": np.full(n, 500.0),
            "taker_buy_base_volume": volume * 0.5,
            "taker_buy_quote_volume": volume * close * 0.5,
        }
    )


class TestLadderDeployer:
    def _grid_context(self, long_score=0.4):
        micro = np.full(S_CAP, int(MicroRegimeCode.RANGE), np.int32)
        return mk_context(n=S_CAP, 
            features=mk_features(n=S_CAP, micro_regime=micro),
            long_regime_score=long_score,
        )

    def test_deploys_in_stable_range(self):
        rng = np.random.default_rng(97)
        df = craft_stable_range(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        out = ladder_deployer(
            pack, self._grid_context(), jnp.asarray(True), jnp.asarray(True)
        )
        closes = df["close"].astype(float)
        mid = closes.rolling(20).mean()
        std = closes.rolling(20).std(ddof=0)
        width_pct = float(((mid + 2 * std) - (mid - 2 * std)).iloc[-1] / mid.iloc[-1]) * 100
        in_range = float((mid - 2 * std).iloc[-1]) < float(closes.iloc[-1]) < float((mid + 2 * std).iloc[-1])
        expected = 1.5 <= width_pct <= 8.0 and in_range
        assert bool(out.trigger[0]) == expected
        if expected:
            d = out.diagnostics
            assert float(d["breakout_low"][0]) < float(d["range_low"][0])
            assert float(d["breakout_high"][0]) > float(d["range_high"][0])
            assert 0.5 <= float(d["atr_buffer_pct"][0]) <= 4.0

    def test_gates(self):
        df = craft_deploying_range()
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        ctx = self._grid_context()
        base = ladder_deployer(pack, ctx, jnp.asarray(True), jnp.asarray(True))
        # the crafted range is deterministic — the base scenario MUST deploy
        assert bool(base.trigger[0])
        # grid policy off
        out = ladder_deployer(pack, ctx, jnp.asarray(False), jnp.asarray(True))
        assert not bool(out.trigger[0])
        # spot market
        out = ladder_deployer(pack, ctx, jnp.asarray(True), jnp.asarray(False))
        assert not bool(out.trigger[0])
        # bearish breadth
        out = ladder_deployer(
            pack, self._grid_context(long_score=0.1), jnp.asarray(True), jnp.asarray(True)
        )
        assert not bool(out.trigger[0])
        # blocking micro transition
        trans = np.full(S_CAP, int(MicroTransitionCode.BREAKDOWN), np.int32)
        micro = np.full(S_CAP, int(MicroRegimeCode.RANGE), np.int32)
        ctx2 = mk_context(n=S_CAP, 
            features=mk_features(n=S_CAP, micro_regime=micro, micro_transition=trans),
            long_regime_score=0.4,
        )
        out = ladder_deployer(pack, ctx2, jnp.asarray(True), jnp.asarray(True))
        assert not bool(out.trigger[0])
