"""Spike detector oracle parity + dormant strategy kernels.

Oracle re-derives the reference detector pipeline
(spike_hunter_v3_kucoin.py:187-502) in pandas; routing/kernels exercised via
crafted contexts (mirrors the reference's per-strategy test files).
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd

from binquant_tpu.enums import Direction, MarketRegimeCode, MicroRegimeCode
from binquant_tpu.strategies import (
    BinanceAIReport,
    MarketRegimeNotifier,
    SpikeParams,
    bb_extreme_reversion,
    buy_low_sell_high,
    buy_the_dip,
    compute_feature_pack,
    detect_spikes,
    inverse_price_tracker,
    range_bb_rsi_mean_reversion,
    range_failed_breakout_fade,
    relative_strength_reversal_range,
    spike_hunter,
    supertrend_swing_reversal,
    twap_momentum_sniper,
)
from binquant_tpu.strategies.dormant import BBXParams
from tests.conftest import make_ohlcv
from tests.test_regime_routing_scoring import mk_context, mk_features
from tests.test_strategies_live import S_CAP, WINDOW, fill_buffer, random_frames


def spike_oracle_last(df: pd.DataFrame, p: SpikeParams) -> dict:
    """Reference detect() pipeline at the last bar (l.218-502)."""
    c, o, v = (df[k].astype(float) for k in ("close", "open", "volume"))
    pc = c.pct_change()
    pca = pc.abs()
    vma = v.rolling(p.base_window).mean()
    vr = v / (vma + 1e-6)

    # auto_calibrate (l.187-215)
    vols, pcs = vr.dropna(), pca.dropna()
    vol_thr = max(p.calib_min_volume_ratio, float(np.quantile(vols, p.calib_volume_quantile)))
    price_floor = max(
        p.price_break_base_threshold,
        max(p.calib_min_price_abs_floor, float(np.quantile(pcs, p.calib_price_floor_quantile))),
    )

    cond = vr >= vol_thr
    count = cond.rolling(p.volume_cluster_window, min_periods=1).sum()
    base_flag = (count >= p.volume_cluster_min_count) & cond
    vc = bool(base_flag.iloc[-1])  # live edge: "last" mode == base flag

    dyn = pca.rolling(60, min_periods=20).quantile(p.price_break_dynamic_q)
    thr = pd.Series(np.maximum(price_floor, dyn), index=df.index).ffill()
    pb = bool((pca >= thr).iloc[-1])

    w = p.cumulative_price_window
    cum_pos = pc.clip(lower=0).rolling(w).sum()
    cum_neg = pc.clip(upper=0).abs().rolling(w).sum()
    vol_cond = (vr >= vol_thr * 0.8).rolling(w).max().astype(bool)
    cum = bool(((cum_pos >= p.cumulative_price_threshold) & vol_cond).iloc[-1])
    cum_s = bool(((cum_neg >= p.cumulative_price_threshold) & vol_cond).iloc[-1])

    vd = vr - vr.shift(p.accel_volume_deriv_window)
    accel_base = (vd >= p.accel_volume_deriv_min) & (pca >= p.accel_price_change_min)
    accel = bool((accel_base & (pc > 0)).fillna(False).iloc[-1])
    accel_s = bool((accel_base & (pc < 0)).fillna(False).iloc[-1])

    bullish = bool((c > o).iloc[-1])
    bearish = bool((c < o).iloc[-1])
    base_combo = vc or pb
    label = (base_combo or cum or accel) and bullish
    label_short = (base_combo or cum_s or accel_s) and bearish
    upward = bool(((c > o).astype(int).rolling(3).sum() >= 3).iloc[-1])
    downward = bool(((c < o).astype(int).rolling(3).sum() >= 3).iloc[-1])
    return dict(
        label=label, label_short=label_short, volume_cluster_flag=vc,
        price_break_flag=pb, cumulative_price_break_flag=cum,
        accel_spike_flag=accel, upward=upward, downward=downward,
        vol_thr=vol_thr, price_floor=price_floor,
    )


class TestSpikeDetector:
    def test_oracle_parity_random_batch(self):
        rng = np.random.default_rng(103)
        p = SpikeParams()
        frames = random_frames(rng, n_rows=10, vol=0.025)
        # craft spikes on some rows: 3 green candles + volume blast
        for i in (2, 6):
            df = frames[i]
            for j in range(3):
                k = len(df) - 3 + j
                prev = df["close"].iloc[k - 1]
                df.loc[df.index[k], "open"] = prev
                df.loc[df.index[k], "close"] = prev * (1.02 + 0.01 * j)
                df.loc[df.index[k], "high"] = prev * 1.04
                df.loc[df.index[k], "low"] = prev * 0.999
                df.loc[df.index[k], "volume"] = df["volume"].iloc[:k].mean() * (3 + 2 * j)
        buf = fill_buffer(frames)
        sig = detect_spikes(buf, p)
        for i, df in frames.items():
            want = spike_oracle_last(df, p)
            for key in ("label", "label_short", "volume_cluster_flag",
                        "cumulative_price_break_flag", "accel_spike_flag",
                        "upward", "downward"):
                got = bool(getattr(sig, key)[i])
                assert got == want[key], f"row {i} {key}: kernel {got} oracle {want[key]}"
            np.testing.assert_allclose(
                float(sig.volume_ratio_threshold[i]), want["vol_thr"], rtol=1e-3
            )

    def test_spike_hunter_routing(self):
        rng = np.random.default_rng(107)
        frames = random_frames(rng, n_rows=1, vol=0.025)
        df = frames[0]
        for j in range(3):
            k = len(df) - 3 + j
            prev = df["close"].iloc[k - 1]
            df.loc[df.index[k], "open"] = prev
            df.loc[df.index[k], "close"] = prev * 1.03
            df.loc[df.index[k], "high"] = prev * 1.035
            df.loc[df.index[k], "low"] = prev * 0.999
            df.loc[df.index[k], "volume"] = df["volume"].iloc[:k].mean() * 6
        buf = fill_buffer(frames)
        sig = detect_spikes(buf)
        assert bool(sig.label[0]) and bool(sig.upward[0])
        ctx = mk_context(n=S_CAP, market_stress_score=0.1)
        out = spike_hunter(sig, ctx, jnp.asarray(2.0))  # breadth momentum up
        assert bool(out.trigger[0])
        assert int(out.direction[0]) == int(Direction.LONG)
        # flat momentum -> no trade
        out2 = spike_hunter(sig, ctx, jnp.asarray(0.0))
        assert not bool(out2.trigger[0])
        # stress kills it
        out3 = spike_hunter(sig, mk_context(n=S_CAP, market_stress_score=0.5), jnp.asarray(2.0))
        assert not bool(out3.trigger[0])


class TestRangeFailedBreakoutFade:
    def test_fades_spike_in_weak_range(self):
        rng = np.random.default_rng(109)
        frames = random_frames(rng, n_rows=1, vol=0.025)
        df = frames[0]
        for j in range(3):
            k = len(df) - 3 + j
            prev = df["close"].iloc[k - 1]
            df.loc[df.index[k], "open"] = prev
            df.loc[df.index[k], "close"] = prev * 1.03
            df.loc[df.index[k], "high"] = prev * 1.035
            df.loc[df.index[k], "low"] = prev * 0.999
            df.loc[df.index[k], "volume"] = df["volume"].iloc[:k].mean() * 6
        buf = fill_buffer(frames)
        sig = detect_spikes(buf)
        rs = np.full(S_CAP, 0.01, np.float32)
        ctx = mk_context(
            n=S_CAP,
            average_return=-0.01,
            features=mk_features(n=S_CAP, relative_strength_vs_btc=rs),
        )
        out = range_failed_breakout_fade(sig, ctx)
        assert bool(out.trigger[0])
        assert int(out.direction[0]) == int(Direction.SHORT)
        # market rallying -> no fade
        ctx2 = mk_context(n=S_CAP, average_return=0.01)
        assert not bool(range_failed_breakout_fade(sig, ctx2).trigger[0])
        # underperformer -> no fade
        ctx3 = mk_context(
            n=S_CAP,
            average_return=-0.01,
            features=mk_features(n=S_CAP, relative_strength_vs_btc=np.full(S_CAP, -0.01, np.float32)),
        )
        assert not bool(range_failed_breakout_fade(sig, ctx3).trigger[0])


class TestCoinruleRules:
    def test_twap_momentum_sniper(self):
        rng = np.random.default_rng(113)
        # declining price -> TWAP above current close
        frames = {0: pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.003, drift=-0.003))}
        buf15 = fill_buffer(frames)
        pack5 = compute_feature_pack(buf15)  # reuse as the 5m pack
        out = twap_momentum_sniper(buf15, pack5)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])  # manual_only
        assert float(out.diagnostics["twap"][0]) > float(pack5.close[0])

    def test_supertrend_swing_reversal_gates(self):
        rng = np.random.default_rng(127)
        df = pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.003, drift=-0.004))
        # sharp reversal up at the end to flip supertrend while RSI still low
        for j in range(6):
            k = len(df) - 6 + j
            prev = df["close"].iloc[k - 1]
            df.loc[df.index[k], "open"] = prev
            df.loc[df.index[k], "close"] = prev * 1.012
            df.loc[df.index[k], "high"] = prev * 1.014
            df.loc[df.index[k], "low"] = prev * 0.999
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        gate = jnp.ones((S_CAP,), dtype=bool)
        ctx = mk_context(n=S_CAP)
        out = supertrend_swing_reversal(
            buf, pack, ctx, gate,
            jnp.asarray(0.1), jnp.asarray(0.05), jnp.asarray(True),
        )
        # RSI may have recovered above 30 after the bounce; condition-check
        if float(pack.rsi[0]) < 30 and bool(out.diagnostics["supertrend_up"][0]):
            assert bool(out.trigger[0])
        # falling ADP blocks regardless
        out2 = supertrend_swing_reversal(
            buf, pack, ctx, gate,
            jnp.asarray(-0.1), jnp.asarray(0.05), jnp.asarray(True),
        )
        assert not bool(out2.trigger[0])

    def test_buy_low_sell_high(self):
        rng = np.random.default_rng(131)
        # dip below-ish but above MA25: downtrend then stabilize
        df = pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.003, drift=-0.004))
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        fired_expected = (
            float(pack.rsi[0]) < 35
            and float(pack.close[0]) > float(np.asarray(
                pd.Series(df["close"]).rolling(25, min_periods=1).mean().iloc[-1]
            ))
        )
        out = buy_low_sell_high(buf, pack, jnp.asarray(True))
        assert bool(out.trigger[0]) == fired_expected
        out2 = buy_low_sell_high(buf, pack, jnp.asarray(False))
        assert not bool(out2.trigger[0])


class TestBuyTheDip:
    def craft_dip(self, rng):
        # timestamps past the strategy's go-live gate
        # (buy_the_dip.py:34 START_TIME 2026-04-12)
        df = pd.DataFrame(
            make_ohlcv(rng, n=WINDOW, vol=0.002, drift=0.0, t0=1_776_040_000_000)
        )
        # 6h (24 bars) ago reference, dip ~3%, then reclaim
        ref = float(df["close"].iloc[-25])
        target = ref * 0.97
        for j in range(8):
            k = len(df) - 8 + j
            df.loc[df.index[k], "close"] = target * (1 - 0.002 * (8 - j))
            df.loc[df.index[k], "open"] = df["close"].iloc[k] * 1.001
            df.loc[df.index[k], "high"] = df["close"].iloc[k] * 1.002
            df.loc[df.index[k], "low"] = df["close"].iloc[k] * 0.998
        # last bar: green reclaim above prev close (and hopefully ema20)
        prev = float(df["close"].iloc[-2])
        df.loc[df.index[-1], "open"] = prev
        df.loc[df.index[-1], "close"] = prev * 1.006
        df.loc[df.index[-1], "high"] = prev * 1.007
        df.loc[df.index[-1], "low"] = prev * 0.999
        return df

    def test_dip_reclaim_fires(self):
        rng = np.random.default_rng(137)
        df = self.craft_dip(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        ctx = mk_context(n=S_CAP)  # RANGE market, RANGE micro
        out = buy_the_dip(buf, pack, ctx, jnp.asarray(False))
        change = float(out.diagnostics["change_6h"][0])
        if -5.0 < change <= -2.0:
            ema20 = float(
                pd.Series(df["close"]).ewm(span=20, adjust=False, min_periods=1).mean().iloc[-1]
            )
            reclaims = float(df["close"].iloc[-1]) > max(float(df["close"].iloc[-2]), ema20)
            assert bool(out.trigger[0]) == reclaims
            if reclaims:
                assert bool(out.autotrade[0])
        # trend market blocks entry entirely
        ctx2 = mk_context(n=S_CAP, market_regime=np.int32(MarketRegimeCode.TREND_UP))
        assert not bool(buy_the_dip(buf, pack, ctx2, jnp.asarray(False)).trigger[0])

    def test_quiet_hours_flips_autotrade(self):
        rng = np.random.default_rng(139)
        df = self.craft_dip(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        ctx = mk_context(n=S_CAP)
        out = buy_the_dip(buf, pack, ctx, jnp.asarray(True))
        if bool(out.trigger[0]):
            assert not bool(out.autotrade[0])


class TestBBExtremeReversion:
    def test_disabled_by_default(self):
        rng = np.random.default_rng(149)
        buf = fill_buffer(random_frames(rng, n_rows=1))
        pack = compute_feature_pack(buf)
        out = bb_extreme_reversion(buf, pack, mk_context(n=S_CAP))
        assert not np.asarray(out.trigger).any()

    def test_enabled_oversold_extreme(self):
        rng = np.random.default_rng(151)
        df = pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.002, drift=0.0))
        # two hard down bars -> RSI(2)=0 and close below lower band
        for j, pct in ((2, 0.97), (1, 0.94)):
            k = len(df) - j
            prev = float(df["close"].iloc[k - 1])
            df.loc[df.index[k], "open"] = prev
            df.loc[df.index[k], "close"] = prev * pct
            df.loc[df.index[k], "high"] = prev * 1.001
            df.loc[df.index[k], "low"] = prev * pct * 0.999
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        params = BBXParams(enabled=True)
        out = bb_extreme_reversion(buf, pack, mk_context(n=S_CAP), params)
        below_band = float(pack.close[0]) <= float(pack.bb_lower[0])
        assert bool(out.trigger[0]) == below_band
        if below_band:
            assert int(out.direction[0]) == int(Direction.LONG)
            assert float(out.diagnostics["rsi2"][0]) <= 5.0


class TestInversePriceTracker:
    def test_routes_to_trend_up_market(self):
        from tests.test_strategies_live import craft_oversold

        rng = np.random.default_rng(157)
        df = craft_oversold(rng)
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        micro = np.full(S_CAP, int(MicroRegimeCode.TREND_UP), np.int32)
        ctx = mk_context(
            n=S_CAP,
            market_regime=np.int32(MarketRegimeCode.TREND_UP),
            btc_regime_score=0.3,
            long_tailwind=0.3,
            features=mk_features(n=S_CAP, micro_regime=micro),
        )
        out = inverse_price_tracker(pack, ctx)
        if bool(out.trigger[0]):
            assert not bool(out.autotrade[0])  # telemetry-only
        # RANGE market without a leader blocks
        ctx2 = mk_context(n=S_CAP)
        out2 = inverse_price_tracker(pack, ctx2)
        assert not bool(out2.trigger[0])


class TestRangeBbRsi:
    def test_long_rejection_setup(self):
        rng = np.random.default_rng(163)
        df = pd.DataFrame(make_ohlcv(rng, n=WINDOW, vol=0.002, drift=0.0))
        # hammer at the lower band: deep low, close back up in upper part
        k = len(df) - 1
        mid = pd.Series(df["close"]).rolling(20).mean().iloc[-2]
        std = pd.Series(df["close"]).rolling(20).std(ddof=0).iloc[-2]
        bb_low_approx = mid - 2 * std
        o = bb_low_approx * 1.001
        df.loc[df.index[k], "open"] = o
        df.loc[df.index[k], "close"] = o * 1.003
        df.loc[df.index[k], "high"] = o * 1.004
        df.loc[df.index[k], "low"] = o * 0.985  # long lower wick
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        ctx = mk_context(n=S_CAP)  # RANGE x RANGE
        out = range_bb_rsi_mean_reversion(buf, pack, ctx, )
        # conditional: this is a statistical craft; assert internal consistency
        if bool(out.trigger[0]):
            assert int(out.direction[0]) == int(Direction.LONG)
            assert float(out.diagnostics["adx"][0]) <= 32.0
            assert float(out.diagnostics["zscore"][0]) <= -2.0

    def test_non_range_blocks(self):
        rng = np.random.default_rng(167)
        buf = fill_buffer(random_frames(rng, n_rows=1))
        pack = compute_feature_pack(buf)
        ctx = mk_context(n=S_CAP, market_regime=np.int32(MarketRegimeCode.TREND_UP))
        out = range_bb_rsi_mean_reversion(buf, pack, ctx)
        assert not bool(out.trigger[0])


class TestRelativeStrengthReversal:
    def test_leader_in_selloff(self):
        rng = np.random.default_rng(173)
        frames = random_frames(rng, n_rows=1, n=WINDOW)
        buf = fill_buffer(frames)
        pack = compute_feature_pack(buf)
        rs = np.full(S_CAP, 0.08, np.float32)
        ctx = mk_context(
            n=S_CAP,
            average_return=-0.03,
            features=mk_features(n=S_CAP, relative_strength_vs_btc=rs),
        )
        out = relative_strength_reversal_range(buf, pack, ctx)
        # window=150 >= 96 bars, volume above its 20th pct almost surely
        vol_floor = float(out.diagnostics["volume_floor"][0])
        want = float(pack.volume[0]) > vol_floor
        assert bool(out.trigger[0]) == want
        if want:
            assert not bool(out.autotrade[0])  # telemetry-only
        # weak RS blocks
        ctx2 = mk_context(n=S_CAP, average_return=-0.03)
        assert not bool(relative_strength_reversal_range(buf, pack, ctx2).trigger[0])


class TestBinanceAIReport:
    def _report(self, texts, opp=3, risk=1, update_age_min=10):
        import time as _t

        now = _t.time() * 1000
        modules = [
            {"type": "opportunities", "points": [{"content": t} for t in texts[:opp]]},
            {"type": "risks", "points": [{"content": "risk"} for _ in range(risk)]},
            {
                "type": "community_sentiment",
                "points": [
                    {"content": "chatter", "citationRefs": [{"type": "post", "count": 12}]}
                ],
            },
        ]
        return {
            "data": {
                "report": {
                    "original": {
                        "reportMeta": {"updateAt": int(now - update_age_min * 60000)},
                        "modules": modules,
                    }
                }
            }
        }

    def test_bullish_final_report(self):
        texts = ["macd bullish crossover", "institutional adoption rising", "strong resilience"]
        rep = self._report(texts, opp=3, risk=0)
        ai = BinanceAIReport("BTCUSDT", "BTC", fetch=lambda s, t: rep)
        feats = ai.extract_features()
        assert feats["macd_bullish_flag"] == 1
        assert feats["net_signal_score"] == 3
        assert feats["large_discussion_flag"] == 1
        assert ai.final_report() == 1
        assert ai.ai_report_signal() is not None

    def test_stale_report_only_base_fields(self):
        rep = self._report(["macd"], update_age_min=10_000)
        ai = BinanceAIReport("BTCUSDT", "BTC", fetch=lambda s, t: rep)
        feats = ai.extract_features()
        assert feats["external_stale_flag"] == 1
        assert "opp_count" not in feats
        assert ai.final_report() == 0

    def test_unavailable(self):
        ai = BinanceAIReport("BTCUSDT", "BTC", fetch=lambda s, t: None)
        assert ai.extract_features() is None
        assert ai.final_report() == 0


class TestMarketRegimeNotifier:
    def test_emits_once_per_transition(self):
        from binquant_tpu.enums import MarketTransitionCode

        notifier = MarketRegimeNotifier(env="test")
        ctx = mk_context(
            n=S_CAP,
            market_regime=np.int32(MarketRegimeCode.HIGH_STRESS),
            previous_market_regime=np.int32(MarketRegimeCode.RANGE),
            market_regime_transition=np.int32(MarketTransitionCode.STRESS_SPIKE),
            market_regime_transition_strength=0.8,
        )
        msg = notifier.build_message(ctx)
        assert msg is not None
        assert "#market_regime_transition" in msg
        assert "STRESS_SPIKE" in msg
        assert "RANGE -> HIGH_STRESS" in msg
        # same transition again -> deduped
        assert notifier.build_message(ctx) is None
        # no transition -> nothing
        assert notifier.build_message(mk_context(n=S_CAP)) is None
