"""Legacy A/B parity harness (VERDICT round-1 item 8; BASELINE config #1).

The TPU batch path and the reference-shaped per-symbol pandas oracle
(``binquant_tpu/oracle``) replay the same synthetic market and must emit
the IDENTICAL signal set — (tick, strategy, symbol, direction, autotrade)
for every fired signal. This is the correctness oracle for the batched
evaluation: any formula drift between the device kernels and the
reference semantics shows up as a set difference here.
"""

import pytest

from binquant_tpu.io.replay import (
    generate_replay_file,
    run_replay_ab,
    run_replay_oracle,
)

CAPACITY, WINDOW = 64, 200

# Washed-out breadth recovering (adp <= -0.4 and rising) with non-flat MA
# momentum: engages LiquidationSweepPump's LONG route and flips the
# grid-only policy active in RANGE/TRANSITIONAL regimes.
WASHED_BREADTH = {
    "timestamp": [1, 2, 3],
    "market_breadth": [-0.50, -0.47, -0.44],
    "market_breadth_ma": [-0.50, -0.46],
}


@pytest.fixture(scope="module")
def replay_path(tmp_path_factory):
    path = tmp_path_factory.mktemp("ab") / "ab_7.jsonl"
    generate_replay_file(path, n_symbols=24, n_ticks=120, seed=7)
    return path


@pytest.fixture(scope="module")
def oracle_cache(replay_path):
    """Memoized pandas-oracle sweeps over the module fixture (ISSUE 5
    tier-1 wall-time relief): each oracle run costs ~45 s of per-symbol
    pandas and the same (breadth,) argument sets are swept by THREE tests
    in this module — compute each once, share the result (run_replay_ab
    accepts it via ``oracle_signals``)."""
    cache: dict = {}

    def get(key: str, breadth: dict | None):
        if key not in cache:
            cache[key] = run_replay_oracle(
                replay_path, window=WINDOW, breadth=breadth
            )
        return cache[key]

    return get


def _assert_match(result):
    assert result["match"], {
        "only_tpu": result["only_tpu"][:5],
        "only_oracle": result["only_oracle"][:5],
    }
    # the crafted market must actually exercise the emission path — an
    # empty-vs-empty match would be vacuous
    assert result["tpu_count"] > 0


def test_ab_signal_sets_identical(replay_path, oracle_cache):
    # ISSUE 2 acceptance: the tier-1 oracle A/B runs with the incremental
    # indicator fast path pinned ON (conftest defaults it off for compile
    # budget) — and asserts it actually ENGAGED, so this parity can never
    # silently degrade to full-path-only coverage. Since ISSUE 4 the
    # donated dispatch is pinned ON too (the production default pair), so
    # this compile is shared with the breadth run below.
    result = run_replay_ab(
        replay_path, capacity=CAPACITY, window=WINDOW, incremental=True,
        donate=True, oracle_signals=oracle_cache("none", None),
    )
    _assert_match(result)
    assert result["tpu_stats"]["incremental_ticks"] > 0
    assert result["tpu_stats"]["donated_ticks"] > 0
    # these three engage even without a scripted breadth series — assert
    # it, or their parity could silently become vacuous (VERDICT r2 item 5)
    for name in (
        "activity_burst_pump",
        "coinrule_price_tracker",
        "mean_reversion_fade",
    ):
        assert name in result["strategies"], result["strategies"]


@pytest.mark.slow
def test_ab_alternate_seed(tmp_path):
    """Redundancy drill (same parity surface, different seed) — slow-marked
    since ISSUE 5 for tier-1 wall-time relief (the primary-seed tests above
    keep the coverage); run by ``make replay-smoke``."""
    path = tmp_path / "ab_99.jsonl"
    generate_replay_file(path, n_symbols=24, n_ticks=120, seed=99)
    _assert_match(run_replay_ab(path, capacity=CAPACITY, window=WINDOW))


def test_ab_with_breadth_all_five_live_strategies_engage(
    replay_path, oracle_cache
):
    """With a scripted breadth series the breadth-gated paths (LSP
    routing, grid-only policy lag) run in BOTH backends and must agree —
    and ALL FIVE live strategies must actually ENGAGE in the matching run,
    or the parity is vacuous for the missing ones (VERDICT r2 item 5).

    ISSUE 4 acceptance: this run pins the INCREMENTAL path ON (so the
    carried ABP order-statistic and LSP quantile strategy stages — not
    just the indicator packs — are what the oracle certifies, for all five
    strategies including both carried ones) AND the donated dispatch ON
    (the production default): the replayed burst's signal set must be
    identical through donated ticks too."""
    result = run_replay_ab(
        replay_path, capacity=CAPACITY, window=WINDOW, breadth=WASHED_BREADTH,
        incremental=True, donate=True,
        oracle_signals=oracle_cache("washed", WASHED_BREADTH),
    )
    _assert_match(result)
    assert result["tpu_stats"]["incremental_ticks"] > 0
    assert result["tpu_stats"]["donated_ticks"] > 0
    assert result["tpu_stats"]["donated_state_resets"] == 0
    for name in (
        "activity_burst_pump",
        "coinrule_price_tracker",
        "liquidation_sweep_pump",
        "mean_reversion_fade",
        "grid_ladder",
    ):
        assert name in result["strategies"], result["strategies"]


def test_ab_dormant_oracle_set(tmp_path):
    """VERDICT r2 item 6: the highest-risk dormant strategies (inline
    indicator variants — BuyTheDip's 6h reference, BBX's Connors RSI(2),
    RBR's rolling-sum ADX) have an independent oracle and are A/B'd via
    the enabled_strategies override. All three must ENGAGE and match."""
    from binquant_tpu.io.replay import generate_dormant_replay
    from binquant_tpu.oracle.evaluator import DORMANT_ORACLE_STRATEGIES

    path = tmp_path / "dormant.jsonl"
    generate_dormant_replay(path)
    result = run_replay_ab(
        path, capacity=CAPACITY, window=WINDOW,
        enabled_strategies=set(DORMANT_ORACLE_STRATEGIES),
    )
    _assert_match(result)
    assert sorted(result["strategies"]) == sorted(DORMANT_ORACLE_STRATEGIES)


def test_ab_dormant_extended_oracle_set(tmp_path):
    """Round-3 extension beyond VERDICT item 6: oracle + A/B for the
    REMAINING dormant strategies (coinrule twap sniper / supertrend swing
    reversal / buy-low-sell-high, InversePriceTracker, RS reversal range,
    and RangeFailedBreakoutFade with its SpikeHunter-detector mirror) —
    every one of the 14 strategy kernels now has an independent oracle.
    Dominance flags are scripted through both backends; all six must
    ENGAGE and match."""
    from binquant_tpu.io.replay import generate_dormant_extended_replay
    from binquant_tpu.oracle.evaluator import DORMANT_ORACLE_EXTENDED

    rising_breadth = {
        "timestamp": [1, 2, 3, 4],
        "market_breadth": [0.30, 0.34, 0.38, 0.42],
        "market_breadth_ma": [0.30, 0.36],
    }
    path = tmp_path / "dormant_ext.jsonl"
    generate_dormant_extended_replay(path)
    result = run_replay_ab(
        path, capacity=CAPACITY, window=WINDOW,
        enabled_strategies=set(DORMANT_ORACLE_EXTENDED),
        breadth=rising_breadth,
        dominance_is_losers=True,
        market_domination_reversal=True,
    )
    _assert_match(result)
    assert sorted(result["strategies"]) == sorted(DORMANT_ORACLE_EXTENDED)


def test_oracle_emits_crafted_signals(replay_path, oracle_cache):
    """The oracle independently finds the crafted setups: the MRF hammer
    on S005 and — with breadth — the LSP pump on S003. Reads the
    module-shared oracle sweeps (same arguments as the A/B tests above)."""
    signals = oracle_cache("none", None)
    by_strategy = {}
    for _, strategy, sym, direction, _ in signals:
        by_strategy.setdefault(strategy, []).append((sym, direction))
    assert any(
        sym == "S005USDT" and direction == "LONG"
        for sym, direction in by_strategy.get("mean_reversion_fade", [])
    )

    with_breadth = oracle_cache("washed", WASHED_BREADTH)
    lsp = [
        (sym, direction)
        for _, strategy, sym, direction, _ in with_breadth
        if strategy == "liquidation_sweep_pump"
    ]
    assert ("S003USDT", "LONG") in lsp


@pytest.mark.slow
def test_ab_parity_holds_on_both_indicator_paths(replay_path):
    """Both evaluation paths pinned EXPLICITLY against the oracle (the
    tier-1 lane covers incremental-by-default in the tests above and
    incremental==full engine-vs-engine in tests/test_incremental.py;
    this slow-lane drill closes the triangle directly)."""
    result_incr = run_replay_ab(
        replay_path, capacity=CAPACITY, window=WINDOW, incremental=True
    )
    _assert_match(result_incr)
    assert result_incr["tpu_stats"]["incremental_ticks"] > 0
    result_full = run_replay_ab(
        replay_path, capacity=CAPACITY, window=WINDOW, incremental=False
    )
    _assert_match(result_full)
    assert result_full["tpu_stats"]["incremental_ticks"] == 0
    assert result_incr["tpu_count"] == result_full["tpu_count"]
