"""Production-day soak observatory (ISSUE 18).

One compressed-time full-stack drill — multi-exchange stream, seven
overlapping fault kinds including a kill/checkpoint-restore — judged
concurrently by every SLO plane into a single machine-readable verdict.

* :mod:`binquant_tpu.soak.judge` — fault schedule, per-plane episode
  attribution, non-vacuity enforcement, THE verdict fold;
* :mod:`binquant_tpu.soak.stream` — live-format kucoin frames through
  the real connector seam, merged with the binance scenario stream;
* :mod:`binquant_tpu.soak.drill` — the orchestrator behind ``make soak``
  / ``make soak-smoke``.
"""

from binquant_tpu.soak.judge import (
    FaultSchedule,
    FaultWindow,
    SoakJudge,
    plane_of,
)
from binquant_tpu.soak.stream import (
    kucoin_frame,
    kucoin_scenario_stream,
    merge_streams,
    synthetic_klines,
)

__all__ = [
    "FaultSchedule",
    "FaultWindow",
    "SoakJudge",
    "plane_of",
    "kucoin_frame",
    "kucoin_scenario_stream",
    "merge_streams",
    "synthetic_klines",
    "soak_drill",
]


def soak_drill(*args, **kwargs):
    """Lazy forwarder — importing the package must not pull the engine
    stack (jax) until a drill actually runs."""
    from binquant_tpu.soak.drill import soak_drill as _drill

    return _drill(*args, **kwargs)
