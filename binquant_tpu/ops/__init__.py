"""Batched rolling-window and indicator kernels (last-axis = time)."""

from binquant_tpu.ops import indicators, rolling  # noqa: F401
from binquant_tpu.ops.indicators import *  # noqa: F401,F403
from binquant_tpu.ops.rolling import *  # noqa: F401,F403
