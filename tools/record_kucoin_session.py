"""Record a live KuCoin websocket session into the test fixture format.

Captures, for spot and futures: the full bullet-public response and the
first N frames of a real candle subscription, writing
``tests/fixtures/kucoin_session.json``. Run from a host WITH network
egress; the checked-in fixture then pins the connector's protocol tests
(tests/test_kucoin_session_fixture.py) to genuine wire shapes.

    python tools/record_kucoin_session.py --frames 20 \
        --spot BTC-USDT --futures XBTUSDTM
"""

from __future__ import annotations

import argparse
import asyncio
import json
from pathlib import Path

FIXTURE = Path(__file__).parent.parent / "tests" / "fixtures" / "kucoin_session.json"

SPOT_BULLET = "https://api.kucoin.com/api/v1/bullet-public"
FUTURES_BULLET = "https://api-futures.kucoin.com/api/v1/bullet-public"


async def record(market_type: str, symbol: str, n_frames: int) -> tuple[dict, list]:
    import httpx
    import websockets

    bullet_url = FUTURES_BULLET if market_type == "futures" else SPOT_BULLET
    bullet = httpx.post(bullet_url, timeout=10).json()
    server = bullet["data"]["instanceServers"][0]
    url = f"{server['endpoint']}?token={bullet['data']['token']}&connectId=rec0"
    topic = (
        f"/contractMarket/limitCandle:{symbol}_15min"
        if market_type == "futures"
        else f"/market/candles:{symbol}_15min"
    )
    frames: list = []
    async with websockets.connect(url) as ws:
        await ws.send(
            json.dumps(
                {
                    "id": 1,
                    "type": "subscribe",
                    "topic": topic,
                    "privateChannel": False,
                    "response": True,
                }
            )
        )
        while len(frames) < n_frames:
            raw = await asyncio.wait_for(ws.recv(), timeout=120)
            frames.append(json.loads(raw))
    return bullet, frames


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--frames", type=int, default=20)
    parser.add_argument("--spot", default="BTC-USDT")
    parser.add_argument("--futures", default="XBTUSDTM")
    args = parser.parse_args()

    spot_bullet, spot_frames = asyncio.run(
        record("spot", args.spot, args.frames)
    )
    fut_bullet, fut_frames = asyncio.run(
        record("futures", args.futures, args.frames)
    )
    FIXTURE.write_text(
        json.dumps(
            {
                "_comment": "Recorded live KuCoin session (record_kucoin_session.py).",
                "spot_bullet_response": spot_bullet,
                "futures_bullet_response": fut_bullet,
                "futures_frames": fut_frames,
                "spot_frames": spot_frames,
            },
            indent=2,
        )
    )
    print(f"wrote {FIXTURE}: {len(spot_frames)} spot + {len(fut_frames)} futures frames")


if __name__ == "__main__":
    main()
