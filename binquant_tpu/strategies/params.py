"""Strategy-parameter pytree: every tunable threshold in one place.

The live strategy kernels historically baked their thresholds as Python
constants (each strategy's ``*Params`` NamedTuple default). This module
aggregates those per-strategy tuples into ONE :class:`StrategyParams`
pytree that threads through ``engine/step.py`` and the backtest backend:

* ``None`` (the live engine's default) leaves every kernel on its baked
  Python-float constants — the traced graph is unchanged, so the live
  wire step stays bit-identical (pinned by
  tests/test_backtest.py::test_params_default_bit_parity);
* an explicit pytree turns the float leaves into traced device scalars —
  the enabling change for the vmapped parameter sweeps: a ``(P,)``-leaved
  grid plus ``param_axes`` evaluates P strategy variants in one dispatch
  (``binquant_tpu/backtest/kernel.py``).

**Sweepable vs structural**: float leaves may be swept (vmapped); int and
bool leaves are STRUCTURAL — they size rolling windows, rings and carry
shapes, so they stay static Python values and cannot ride a grid axis
(``make_param_grid`` rejects them).
"""

from __future__ import annotations

from itertools import product
from typing import NamedTuple, Sequence

import jax
import numpy as np

from binquant_tpu.strategies.activity_burst_pump import ABPParams
from binquant_tpu.strategies.ladder_deployer import LadderParams
from binquant_tpu.strategies.liquidation_sweep_pump import LSPParams
from binquant_tpu.strategies.mean_reversion_fade import MRFParams
from binquant_tpu.strategies.price_tracker import PTParams


class StrategyParams(NamedTuple):
    """The live dispatch set's tunables, one sub-tuple per strategy.

    Defaults ARE the reference's class constants — evaluating at the
    default pytree must reproduce the constant-folded kernels exactly.
    """

    abp: ABPParams = ABPParams()
    pt: PTParams = PTParams()
    lsp: LSPParams = LSPParams()
    mrf: MRFParams = MRFParams()
    ladder: LadderParams = LadderParams()


def default_strategy_params() -> StrategyParams:
    return StrategyParams()


def _is_static_leaf(value) -> bool:
    """int/bool leaves are STRUCTURAL (window lengths, ring sizes, enable
    flags) — they steer Python control flow and array shapes inside the
    kernels, so they must never become tracers."""
    return isinstance(value, (bool, int)) and not isinstance(value, float)


@jax.tree_util.register_pytree_node_class
class DynamicParams:
    """jit/vmap-safe carrier for an explicit :class:`StrategyParams`.

    Flattens the float leaves as pytree children (traced scalars — or
    ``(P,)`` grid axes under vmap) while the int/bool leaves ride the
    treedef as static aux data, hashable into the jit cache key. Passing a
    raw ``StrategyParams`` through ``jax.jit`` would trace ``int`` fields
    like ``lookback_window`` and crash the kernels' static window
    arithmetic — wrap with :func:`dynamic_params` instead.
    """

    __slots__ = ("tree",)

    def __init__(self, tree: StrategyParams) -> None:
        self.tree = tree

    def tree_flatten(self):
        leaves, treedef = jax.tree_util.tree_flatten(self.tree)
        statics = tuple(_is_static_leaf(v) for v in leaves)
        dyn = [v for v, s in zip(leaves, statics) if not s]
        aux = (
            treedef,
            statics,
            tuple(v if s else None for v, s in zip(leaves, statics)),
        )
        return dyn, aux

    @classmethod
    def tree_unflatten(cls, aux, dyn):
        treedef, statics, static_vals = aux
        it = iter(dyn)
        leaves = [
            static_vals[i] if statics[i] else next(it)
            for i in range(len(statics))
        ]
        return cls(jax.tree_util.tree_unflatten(treedef, leaves))


def dynamic_params(params: StrategyParams) -> DynamicParams:
    """Wrap an explicit params pytree for a jit boundary (see
    :class:`DynamicParams`)."""
    return DynamicParams(params)


def resolve_params(params) -> StrategyParams:
    """The kernels' unwrap: None → baked defaults, DynamicParams → its
    tree, a raw StrategyParams passes through (non-jit callers)."""
    if params is None:
        return StrategyParams()
    if isinstance(params, DynamicParams):
        return params.tree
    return params


def declared_gate_margins() -> dict[str, float]:
    """Per-strategy gate margins for the extension-invariant tolerance
    contract (ISSUE 17, README §Backtest).

    A strategy listed here declares: its entry gates compare indicator
    values against thresholds, and a fired/not-fired flip between the
    vmapped and extension-invariant precompute paths is only admissible
    when the tick's margin-proximity digest field (the minimum distance,
    in the indicator's own units, between any gated indicator and its
    threshold over eligible rows) sits INSIDE this band. Outside the
    band, the corpus pins assert exact fired-set equality — the extended
    forms' cumsum/EWM ulp drift cannot legally cross a gate that far
    from its threshold. Strategies absent here either have no governed
    drift on their gated inputs (positional fields are bit-exact) or are
    not evaluated by the backtest backend.

    Units are RSI/MFI points (0-100 scale) — the gated indicators for
    all three declared strategies.
    """
    return {
        "coinrule_price_tracker": 0.25,
        "mean_reversion_fade": 0.25,
        "inverse_price_tracker": 0.25,
    }


def _leaf_path_items(params: StrategyParams):
    """Yield ("strategy.field", sub_name, field_name, value) per leaf of
    the two-level params pytree (ScorerWeights nests one level deeper and
    is addressed as e.g. ``pt.weights.context_weight``)."""
    for sub_name, sub in params._asdict().items():
        for field, value in sub._asdict().items():
            if hasattr(value, "_asdict"):  # nested NamedTuple (weights)
                for f2, v2 in value._asdict().items():
                    yield f"{sub_name}.{field}.{f2}", sub_name, (field, f2), v2
            else:
                yield f"{sub_name}.{field}", sub_name, (field,), value


def sweepable_axes(params: StrategyParams | None = None) -> list[str]:
    """Dotted names of every float leaf (the legal grid axes)."""
    params = params or StrategyParams()
    return [
        path
        for path, _, _, value in _leaf_path_items(params)
        if isinstance(value, float)
    ]


def _set_leaf(params: StrategyParams, sub: str, fields: tuple, value):
    sub_tuple = getattr(params, sub)
    if len(fields) == 1:
        sub_tuple = sub_tuple._replace(**{fields[0]: value})
    else:
        inner = getattr(sub_tuple, fields[0])._replace(**{fields[1]: value})
        sub_tuple = sub_tuple._replace(**{fields[0]: inner})
    return params._replace(**{sub: sub_tuple})


def make_param_grid(
    axes: dict[str, Sequence[float]],
    base: StrategyParams | None = None,
) -> tuple[StrategyParams, list[dict[str, float]]]:
    """Cartesian-product parameter grid as one batched pytree.

    ``axes`` maps dotted float-leaf names (see :func:`sweepable_axes`) to
    value sequences. Returns ``(params, combos)`` where the swept leaves
    of ``params`` are ``(P,)`` float32 arrays (P = product of axis
    lengths), every other leaf keeps its static Python value, and
    ``combos[i]`` names combo i's axis values (the sweep report's label
    row). Feed ``params`` + :func:`param_axes` to ``jax.vmap``.
    """
    base = base or StrategyParams()
    legal = set(sweepable_axes(base))
    by_path = {path: (s, f) for path, s, f, _ in _leaf_path_items(base)}
    for name in axes:
        if name not in by_path:
            raise KeyError(f"unknown param axis {name!r}")
        if name not in legal:
            raise ValueError(
                f"param axis {name!r} is structural (int/bool) — only float "
                "leaves can be swept"
            )
    names = list(axes)
    grids = [np.asarray(axes[n], dtype=np.float32) for n in names]
    combos_nd = list(product(*[range(len(g)) for g in grids]))
    params = base
    for j, (name, grid) in enumerate(zip(names, grids)):
        sub, fields = by_path[name]
        col = np.asarray([grid[idx[j]] for idx in combos_nd], dtype=np.float32)
        params = _set_leaf(params, sub, fields, col)
    combos = [
        {name: float(grids[j][idx[j]]) for j, name in enumerate(names)}
        for idx in combos_nd
    ]
    return params, combos


def param_axes(params: StrategyParams):
    """The matching ``jax.vmap`` in_axes pytree: 0 for batched ``(P,)``
    leaves, None for static scalars."""
    return jax.tree_util.tree_map(
        lambda leaf: 0 if (hasattr(leaf, "ndim") and leaf.ndim >= 1) else None,
        params,
    )


def grid_size(params: StrategyParams) -> int:
    """P of a batched grid (1 for an unbatched params pytree)."""
    sizes = {
        leaf.shape[0]
        for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "ndim") and getattr(leaf, "ndim", 0) >= 1
    }
    if not sizes:
        return 1
    if len(sizes) > 1:
        raise ValueError(f"inconsistent grid axis lengths: {sorted(sizes)}")
    return int(sizes.pop())
