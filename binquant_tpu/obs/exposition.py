"""Prometheus text exposition + the /metrics + /healthz HTTP endpoint.

``render_text`` turns a :class:`~binquant_tpu.obs.registry.MetricsRegistry`
into Prometheus text format 0.0.4. :class:`MetricsServer` is a tiny asyncio
HTTP server (stdlib only — the image carries no aiohttp, and httpx is a
client) that serves:

* ``GET /metrics``  — the rendered registry;
* ``GET /healthz``  — liveness JSON from an injected callable (heartbeat
  age + last-tick status; ``SignalEngine.health_snapshot`` in production).
  HTTP 200 while the process is live (``status`` of ``ok`` or
  ``degraded`` — a ticking engine whose heartbeat writes fail is alive;
  restarting it would not fix a full disk) and 503 otherwise, so
  orchestrators can probe it directly without killing live engines;
* ``GET /debug/profile?seconds=N`` — opens an on-demand ``jax.profiler``
  capture window through an injected
  :class:`~binquant_tpu.obs.tracing.ProfileController` (400 on a
  missing/invalid/out-of-range ``seconds``, 409 while a window is already
  open, and a JSON no-op when the profiler is unavailable). Unlike the
  read-only routes this one has a side effect (profiling overhead on the
  live tick loop + capture files on disk), so it only answers loopback
  peers unless ``profile_remote_ok`` is set (``BQT_PROFILE_REMOTE=1``) —
  the scrape port is commonly reachable by the whole cluster;
* ``GET /debug/executables`` — the executable/compile ledger
  (:data:`binquant_tpu.obs.ledger.LEDGER` by default): every jit entry
  the engine owns with compile wall-time, warm-vs-cold persistent-cache
  outcome, and per-dispatch ``cost_analysis`` bytes/flops. Read-only —
  served to any peer like ``/metrics``;
* ``GET /debug/symbols?offset=&limit=&prefix=&min_score=`` — the ingest
  monitor's paginated worst-first per-symbol stream-health scoreboard
  (health score, staleness ages, gap/rewrite/out-of-order/churn counts,
  watermarks). Read-only — served to any peer like ``/metrics``;
* ``GET /debug/slo`` — the unified SLO verdict plane (ISSUE 16): every
  registered SLO's burn state + every invariant probe folded into one
  machine-readable pass/fail JSON
  (:meth:`binquant_tpu.obs.slo.SloRegistry.snapshot`). Read-only —
  served to any peer like ``/metrics``.

Started from ``main.py`` when ``BQT_METRICS_PORT`` is set; ``port=0``
binds an ephemeral port (tests), reported by :meth:`MetricsServer.start`.
"""

from __future__ import annotations

import asyncio
import json
import logging
from collections.abc import Callable

from binquant_tpu.obs.registry import (
    REGISTRY,
    MetricFamily,
    MetricsRegistry,
    format_value,
)

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"

log = logging.getLogger(__name__)


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(text: str) -> str:
    return (
        text.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape_label_value(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _render_family(fam: MetricFamily, out: list[str]) -> None:
    out.append(f"# HELP {fam.name} {_escape_help(fam.documentation)}")
    out.append(f"# TYPE {fam.name} {fam.kind}")
    for key, child in sorted(fam.children()):
        if fam.kind == "histogram":
            bounds = list(fam.bucket_bounds) + [float("inf")]
            for bound, cum in zip(bounds, child.cumulative_counts()):
                labels = _label_str(
                    fam.label_names, key, extra=(("le", format_value(bound)),)
                )
                out.append(f"{fam.name}_bucket{labels} {cum}")
            base = _label_str(fam.label_names, key)
            out.append(f"{fam.name}_sum{base} {format_value(child.sum)}")
            out.append(f"{fam.name}_count{base} {child.count}")
        else:
            labels = _label_str(fam.label_names, key)
            out.append(f"{fam.name}{labels} {format_value(child.value)}")


def render_text(registry: MetricsRegistry | None = None) -> str:
    """The whole registry in Prometheus text format (trailing newline)."""
    registry = registry if registry is not None else REGISTRY
    out: list[str] = []
    for fam in registry.collect():
        _render_family(fam, out)
    return "\n".join(out) + "\n"


class MetricsServer:
    """``/metrics`` + ``/healthz`` on an asyncio socket server.

    ``health_fn`` returns the liveness JSON payload (a dict with at least
    ``status``); it runs inline on the event loop, so it must be cheap and
    non-blocking — ``SignalEngine.health_snapshot`` only reads attributes.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        health_fn: Callable[[], dict] | None = None,
        port: int = 9464,
        host: str = "0.0.0.0",
        profiler=None,
        profile_remote_ok: bool = False,
        ledger=None,
        ingest=None,
        slo=None,
    ) -> None:
        self.registry = registry if registry is not None else REGISTRY
        self.health_fn = health_fn
        self.host = host
        self.port = port
        self.profiler = profiler
        self.profile_remote_ok = profile_remote_ok
        if ledger is None:
            from binquant_tpu.obs.ledger import LEDGER as ledger
        self.ledger = ledger
        # the engine's IngestHealthMonitor (GET /debug/symbols); None
        # keeps the route answering with a JSON not-configured no-op
        self.ingest = ingest
        # the engine's SloRegistry (GET /debug/slo); same no-op contract
        self.slo = slo
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> int:
        """Bind and serve; returns the bound port (resolves ``port=0``)."""
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("metrics exporter listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- request handling ---------------------------------------------------

    def _respond(self, status: int, reason: str, ctype: str, body: str) -> bytes:
        payload = body.encode("utf-8")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(payload)}\r\n"
            "Connection: close\r\n\r\n"
        )
        return head.encode("ascii") + payload

    def _route(self, target: str, peer: tuple | None = None) -> bytes:
        path, _, query = target.partition("?")
        if path == "/debug/profile":
            return self._route_profile(query, peer)
        if path == "/debug/symbols":
            return self._route_symbols(query)
        if path == "/debug/slo":
            return self._route_slo()
        if path == "/debug/executables":
            # read-only like /metrics; snapshot() is attribute reads under
            # a lock, safe inline on the event loop
            try:
                payload = self.ledger.snapshot()
            except Exception:
                log.exception("executable-ledger snapshot crashed")
                payload = {"error": "ledger_snapshot_failed"}
            return self._respond(
                200, "OK", "application/json", json.dumps(payload)
            )
        if path == "/metrics":
            return self._respond(
                200, "OK", CONTENT_TYPE_LATEST, render_text(self.registry)
            )
        if path == "/healthz":
            if self.health_fn is None:
                payload: dict = {"status": "unknown"}
            else:
                try:
                    payload = self.health_fn()
                except Exception:
                    log.exception("health_fn crashed")
                    payload = {"status": "error"}
            # degraded = alive-but-impaired: visible in the payload (and
            # the heartbeat-failure counter) but NOT a probe failure — a
            # restart doesn't fix the underlying write failure
            ok = payload.get("status") in ("ok", "degraded")
            return self._respond(
                200 if ok else 503,
                "OK" if ok else "Service Unavailable",
                "application/json",
                json.dumps(payload),
            )
        return self._respond(404, "Not Found", "text/plain", "not found\n")

    def _route_symbols(self, query: str) -> bytes:
        """``/debug/symbols?offset=&limit=&prefix=&min_score=`` — the
        ingest monitor's worst-first per-symbol stream-health scoreboard
        (ISSUE 15). Read-only, served to any peer like ``/metrics``;
        strict 400 on malformed numeric args so a typo'd probe reads as a
        typo, not as page one."""
        from urllib.parse import parse_qs

        if self.ingest is None or not getattr(self.ingest, "enabled", False):
            return self._respond(
                200, "OK", "application/json",
                json.dumps({"enabled": False, "symbols": []}),
            )
        qs = parse_qs(query)
        try:
            offset = int(qs.get("offset", ["0"])[0])
            limit = int(qs.get("limit", ["50"])[0])
            raw_min = qs.get("min_score", [None])[0]
            min_score = None if raw_min is None else float(raw_min)
        except ValueError:
            return self._respond(
                400, "Bad Request", "application/json",
                json.dumps({"error": "offset/limit must be integers, "
                            "min_score a float"}),
            )
        prefix = qs.get("prefix", [None])[0]
        try:
            payload = self.ingest.symbols_report(
                offset=offset, limit=limit, prefix=prefix,
                min_score=min_score,
            )
            payload["enabled"] = True
        except Exception:
            log.exception("ingest symbols_report crashed")
            # a broken scoreboard must not read as success to probes
            return self._respond(
                500, "Internal Server Error", "application/json",
                json.dumps({"error": "symbols_report_failed"}),
            )
        return self._respond(
            200, "OK", "application/json", json.dumps(payload)
        )

    def _route_slo(self) -> bytes:
        """``/debug/slo`` — the unified verdict (ISSUE 16): SLO burn
        states + invariant probes folded to one top-level ``ok``.
        Read-only, served to any peer like ``/metrics``. A crashed
        snapshot is a 500 — the judging surface must never read as
        passing by accident."""
        if self.slo is None or not getattr(self.slo, "enabled", False):
            return self._respond(
                200, "OK", "application/json",
                json.dumps(
                    {"enabled": False, "ok": None,
                     "slos": {}, "invariants": {}}
                ),
            )
        try:
            payload = self.slo.snapshot()
        except Exception:
            log.exception("slo snapshot crashed")
            return self._respond(
                500, "Internal Server Error", "application/json",
                json.dumps({"error": "slo_snapshot_failed"}),
            )
        return self._respond(
            200, "OK", "application/json", json.dumps(payload)
        )

    @staticmethod
    def _is_loopback(peer: tuple | None) -> bool:
        if peer is None:  # non-inet transport (tests, unix sockets)
            return True
        host = str(peer[0])
        return host in ("127.0.0.1", "::1") or host.startswith("::ffff:127.")

    def _route_profile(self, query: str, peer: tuple | None = None) -> bytes:
        """``/debug/profile?seconds=N``: open one jax.profiler capture
        window. Arg validation is strict (400) — a typo'd probe must not
        silently start a multi-minute trace; an unavailable profiler is a
        200 no-op so probing the endpoint is always safe. The route is
        side-effectful (live profiling overhead + capture files on disk),
        so non-loopback peers are refused unless ``profile_remote_ok``."""
        from urllib.parse import parse_qs

        if not self.profile_remote_ok and not self._is_loopback(peer):
            return self._respond(
                403, "Forbidden", "application/json",
                json.dumps({"error": "profiling is loopback-only "
                            "(set BQT_PROFILE_REMOTE=1 to allow remote)"}),
            )
        if self.profiler is None:
            return self._respond(
                200, "OK", "application/json",
                json.dumps({"started": False, "reason": "profiler_not_configured"}),
            )
        raw = parse_qs(query).get("seconds", [])
        try:
            seconds = float(raw[0])
        except (IndexError, ValueError):
            return self._respond(
                400, "Bad Request", "application/json",
                json.dumps({"error": "seconds=N required (0 < N <= "
                            f"{self.profiler.MAX_SECONDS:g})"}),
            )
        if not (0 < seconds <= self.profiler.MAX_SECONDS):
            return self._respond(
                400, "Bad Request", "application/json",
                json.dumps({"error": "seconds out of range (0 < N <= "
                            f"{self.profiler.MAX_SECONDS:g})"}),
            )
        result = self.profiler.start_window(seconds)
        busy = result.get("reason") == "already_active"
        return self._respond(
            409 if busy else 200,
            "Conflict" if busy else "OK",
            "application/json",
            json.dumps(result),
        )

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), timeout=5)
            parts = request_line.decode("latin-1").split()
            # drain headers, HARD-capped: a slow-drip client feeding one
            # header line per <5s would otherwise hold this task (and its
            # socket) open forever — a scraper sends a handful of lines
            for _ in range(100):
                line = await asyncio.wait_for(reader.readline(), timeout=5)
                if line in (b"\r\n", b"\n", b""):
                    break
            if len(parts) < 2 or parts[0] != "GET":
                writer.write(
                    self._respond(
                        405, "Method Not Allowed", "text/plain", "GET only\n"
                    )
                )
            else:
                peer = writer.get_extra_info("peername")
                writer.write(self._route(parts[1], peer=peer))
            await writer.drain()
        except (TimeoutError, asyncio.TimeoutError, ConnectionError, OSError):
            pass  # scraper went away (or never spoke); nothing to salvage
        except Exception:
            log.exception("metrics request handling failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass
