"""Ring-buffer semantics vs the reference MarketStateStore contract.

Reference behavior pinned here: concat → drop_duplicates(keep='last') →
sort → tail(max_bars) per candle (market_state_store.py:19-32), exact-ts
freshness (l.49-54).

Since ISSUE 9 the live layout is a circular-cursor ring (appends are a
one-column scatter + cursor bump); the canonical right-aligned view is
reconstructed by ``materialize``. The original shift-append update is kept
as ``apply_updates_shift`` and serves as the bit-equality oracle for the
property suite at the bottom (clean appends, dedupe re-sends, mid-history
rewrites, churn, warm-up/min_periods edges, wrap-around).
"""

import numpy as np
import pytest

from binquant_tpu.exceptions import BufferCapacityError
from binquant_tpu.engine import (
    Field,
    IngestBatcher,
    SymbolRegistry,
    apply_updates,
    apply_updates_shift,
    empty_buffer,
    fresh_mask,
    materialize,
    materialize_tail,
    ms_to_s,
    reset_rows,
    ring_latest_times,
)


def mk_vals(close: float, n_fields: int = 10) -> np.ndarray:
    v = np.zeros((1, n_fields), dtype=np.float32)
    v[0, Field.OPEN] = close - 1
    v[0, Field.HIGH] = close + 1
    v[0, Field.LOW] = close - 2
    v[0, Field.CLOSE] = close
    v[0, Field.VOLUME] = 100.0
    return v


def test_append_and_right_alignment():
    buf = empty_buffer(4, window=8)
    for i, ts in enumerate([100, 200, 300]):
        buf = apply_updates(
            buf, np.array([2], dtype=np.int32), np.array([ts], dtype=np.int32), mk_vals(10.0 + i)
        )
    assert int(buf.filled[2]) == 3
    assert int(buf.cursor[2]) == 3  # three appends bumped the write cursor
    assert int(ring_latest_times(buf)[2]) == 300
    m = materialize(buf)
    assert int(m.times[2, -1]) == 300
    assert int(m.times[2, -2]) == 200
    assert float(m.values[2, -1, Field.CLOSE]) == 12.0
    assert np.all(np.asarray(m.cursor) == 0)  # canonical
    # untouched rows stay empty
    assert int(buf.filled[0]) == 0
    assert np.all(np.asarray(m.times[0]) == -1)


def test_duplicate_timestamp_overwrites_last():
    buf = empty_buffer(2, window=4)
    buf = apply_updates(buf, np.array([0], np.int32), np.array([100], np.int32), mk_vals(1.0))
    buf = apply_updates(buf, np.array([0], np.int32), np.array([100], np.int32), mk_vals(2.0))
    assert int(buf.filled[0]) == 1
    m = materialize(buf)
    assert float(m.values[0, -1, Field.CLOSE]) == 2.0


def test_stale_update_ignored():
    buf = empty_buffer(2, window=4)
    buf = apply_updates(buf, np.array([0], np.int32), np.array([200], np.int32), mk_vals(5.0))
    buf = apply_updates(buf, np.array([0], np.int32), np.array([100], np.int32), mk_vals(9.0))
    assert int(buf.filled[0]) == 1
    m = materialize(buf)
    assert float(m.values[0, -1, Field.CLOSE]) == 5.0
    assert int(m.times[0, -1]) == 200


def test_mid_history_rewrite_in_place():
    """A re-sent candle whose timestamp already sits mid-window overwrites
    THAT bar (reference dedupe-by-timestamp keep-last,
    market_state_store.py:19-32) without touching order, fill count, or
    the write cursor."""
    buf = empty_buffer(2, window=4)
    for i, ts in enumerate([100, 200, 300]):
        buf = apply_updates(
            buf, np.array([0], np.int32), np.array([ts], np.int32),
            mk_vals(float(i + 1)),
        )
    cursor_before = int(buf.cursor[0])
    # correction for the MIDDLE bar (ts=200)
    buf = apply_updates(
        buf, np.array([0], np.int32), np.array([200], np.int32), mk_vals(77.0)
    )
    assert int(buf.filled[0]) == 3
    assert int(buf.cursor[0]) == cursor_before  # rewrite never bumps
    m = materialize(buf)
    assert [int(t) for t in m.times[0, -3:]] == [100, 200, 300]
    assert float(m.values[0, -2, Field.CLOSE]) == 77.0
    assert float(m.values[0, -1, Field.CLOSE]) == 3.0  # latest untouched


def test_older_absent_timestamp_still_dropped():
    """An older timestamp with NO matching bar cannot be inserted into a
    fixed-shape window; it is dropped (documented divergence)."""
    buf = empty_buffer(2, window=4)
    for ts, v in [(100, 1.0), (300, 3.0)]:
        buf = apply_updates(
            buf, np.array([0], np.int32), np.array([ts], np.int32), mk_vals(v)
        )
    buf = apply_updates(
        buf, np.array([0], np.int32), np.array([200], np.int32), mk_vals(9.0)
    )
    assert int(buf.filled[0]) == 2
    m = materialize(buf)
    assert [int(t) for t in m.times[0, -2:]] == [100, 300]
    assert not (np.asarray(m.values[0, :, Field.CLOSE]) == 9.0).any()


def test_window_rolls_oldest_off():
    buf = empty_buffer(1, window=3)
    for i in range(5):
        buf = apply_updates(
            buf, np.array([0], np.int32), np.array([100 + i], np.int32), mk_vals(float(i))
        )
    assert int(buf.filled[0]) == 3
    assert int(buf.cursor[0]) == 5 % 3  # wrapped cursor
    m = materialize(buf)
    assert list(np.asarray(m.times[0])) == [102, 103, 104]
    assert list(np.asarray(m.values[0, :, Field.CLOSE])) == [2.0, 3.0, 4.0]


def test_batched_update_multiple_symbols():
    buf = empty_buffer(8, window=4)
    rows = np.array([0, 3, 5], dtype=np.int32)
    ts = np.array([100, 100, 100], dtype=np.int32)
    vals = np.concatenate([mk_vals(1.0), mk_vals(2.0), mk_vals(3.0)], axis=0)
    buf = apply_updates(buf, rows, ts, vals)
    assert list(np.asarray(buf.filled)) == [1, 0, 0, 1, 0, 1, 0, 0]
    fm = np.asarray(fresh_mask(buf, 100))
    assert list(np.nonzero(fm)[0]) == [0, 3, 5]
    assert not np.any(np.asarray(fresh_mask(buf, 200)))


def test_out_of_range_rows_dropped():
    buf = empty_buffer(2, window=4)
    rows = np.array([-1, 5, 1], dtype=np.int32)
    ts = np.array([100, 100, 100], dtype=np.int32)
    vals = np.concatenate([mk_vals(1.0), mk_vals(2.0), mk_vals(3.0)], axis=0)
    buf = apply_updates(buf, rows, ts, vals)
    assert int(buf.filled[0]) == 0
    assert int(buf.filled[1]) == 1
    m = materialize(buf)
    assert float(m.values[1, -1, Field.CLOSE]) == 3.0


def test_registry_free_list_reuse():
    reg = SymbolRegistry(3)
    a, b = reg.add("btcusdt"), reg.add("ETHUSDT")
    assert a == 0 and b == 1
    assert reg.add("BTCUSDT") == 0  # case-normalized idempotent
    reg.add("XRPUSDT")
    with pytest.raises(BufferCapacityError):
        reg.add("SOLUSDT")
    assert reg.remove("ethusdt") == 1
    assert reg.add("SOLUSDT") == 1  # reclaimed row
    assert reg.name_of(1) == "SOLUSDT"


def test_reset_rows_clears_state():
    buf = empty_buffer(3, window=4)
    buf = apply_updates(buf, np.array([1], np.int32), np.array([100], np.int32), mk_vals(5.0))
    buf = reset_rows(buf, np.array([1], dtype=np.int32))
    assert int(buf.filled[1]) == 0
    assert int(buf.cursor[1]) == 0  # cleared rows restart canonical
    assert np.all(np.asarray(buf.times[1]) == -1)
    assert np.all(np.isnan(np.asarray(buf.values[1])))


def test_ingest_batcher_dedupes_keep_last():
    reg = SymbolRegistry(4)
    batcher = IngestBatcher(reg)
    t0 = 1_700_000_000_000
    batcher.add(
        {"symbol": "BTCUSDT", "open_time": t0, "close_time": t0 + 899_999,
         "open": 1, "high": 2, "low": 0.5, "close": 1.5, "volume": 10}
    )
    batcher.add(
        {"symbol": "btcusdt", "open_time": t0, "close_time": t0 + 899_999,
         "open": 1, "high": 2, "low": 0.5, "close": 1.7, "volume": 11}
    )
    batcher.add(
        {"symbol": "ETHUSDT", "open_time": t0, "close_time": t0 + 899_999,
         "open": 1, "high": 2, "low": 0.5, "close": 9.9, "volume": 12}
    )
    batches = batcher.drain()
    assert len(batches) == 1
    rows, ts, vals = batches[0]
    assert len(rows) == 2
    assert len(batcher) == 0
    i_btc = list(rows).index(reg.row_of("BTCUSDT"))
    assert vals[i_btc, Field.CLOSE] == np.float32(1.7)
    assert ts[i_btc] == ms_to_s(t0)

    buf = empty_buffer(4, window=4)
    buf = apply_updates(buf, rows, ts, vals)
    assert int(buf.filled[reg.row_of("BTCUSDT")]) == 1
    assert int(buf.filled[reg.row_of("ETHUSDT")]) == 1


def test_ingest_batcher_multi_timestamp_subbatches():
    """A late frame plus the current frame for one symbol must produce two
    ordered sub-batches (reference keeps both rows after dedupe-by-ts)."""
    reg = SymbolRegistry(4)
    batcher = IngestBatcher(reg)
    t0 = 1_700_000_000_000
    k = {"open": 1, "high": 2, "low": 0.5, "volume": 10}
    batcher.add({"symbol": "A", "open_time": t0 + 900_000,
                 "close_time": t0 + 1_799_999, "close": 2.0, **k})
    batcher.add({"symbol": "A", "open_time": t0,
                 "close_time": t0 + 899_999, "close": 1.0, **k})  # late frame
    batcher.add({"symbol": "B", "open_time": t0 + 900_000,
                 "close_time": t0 + 1_799_999, "close": 3.0, **k})
    batches = batcher.drain()
    assert len(batches) == 2

    buf = empty_buffer(4, window=4)
    for rows, ts, vals in batches:
        buf = apply_updates(buf, rows, ts, vals)
    ra = reg.row_of("A")
    assert int(buf.filled[ra]) == 2
    closes = np.asarray(materialize(buf).values[ra, :, Field.CLOSE])
    assert list(closes[-2:]) == [1.0, 2.0]
    assert int(buf.filled[reg.row_of("B")]) == 1


# ---------------------------------------------------------------------------
# Cursor ring vs shift-append: bit-equality property suite (ISSUE 9)
# ---------------------------------------------------------------------------


def _assert_same(ring, shift, ctx=""):
    """materialize(ring) must be BIT-identical to the shift layout."""
    m = materialize(ring)
    assert np.array_equal(np.asarray(m.times), np.asarray(shift.times)), ctx
    mv, sv = np.asarray(m.values), np.asarray(shift.values)
    assert ((mv == sv) | (np.isnan(mv) & np.isnan(sv))).all(), ctx
    assert np.array_equal(np.asarray(m.filled), np.asarray(shift.filled)), ctx
    assert np.array_equal(
        np.asarray(ring_latest_times(ring)), np.asarray(shift.times)[:, -1]
    ), ctx
    # the cursor stays in [0, W) and equals filled-mod-W for pure-append
    # histories (no structural invariant is broken by rewrites/resets)
    W = ring.times.shape[1]
    cur = np.asarray(ring.cursor)
    assert ((cur >= 0) & (cur < W)).all(), ctx


def _batch(entries):
    rows = np.array([r for r, _, _ in entries], np.int32)
    ts = np.array([t for _, t, _ in entries], np.int32)
    vals = np.zeros((len(entries), 10), np.float32)
    for i, (_, _, c) in enumerate(entries):
        vals[i, Field.CLOSE] = c
        vals[i, Field.VOLUME] = 1.0 + i
    return rows, ts, vals


class TestCursorRingParity:
    """Every update class the live stream produces, driven through BOTH
    implementations from the same empty buffer."""

    def _drive(self, window, batches, resets=()):
        ring = empty_buffer(3, window=window)
        shift = empty_buffer(3, window=window)
        resets = dict(resets)
        for i, entries in enumerate(batches):
            rows, ts, vals = _batch(entries)
            ring = apply_updates(ring, rows, ts, vals)
            shift = apply_updates_shift(shift, rows, ts, vals)
            if i in resets:
                rr = np.array([resets[i]], np.int32)
                ring = reset_rows(ring, rr)
                shift = reset_rows(shift, rr)
            _assert_same(ring, shift, ctx=f"batch {i}")
        return ring, shift

    def test_clean_append_run_past_wraparound(self):
        batches = [[(0, 100 + i, float(i)), (1, 100 + i, float(-i))] for i in range(11)]
        ring, _ = self._drive(4, batches)
        assert int(ring.cursor[0]) == 11 % 4

    def test_dedupe_resend_of_latest_bar(self):
        batches = [
            [(0, 100, 1.0)],
            [(0, 200, 2.0)],
            [(0, 200, 2.5)],  # exchange re-sent the same bucket corrected
            [(0, 300, 3.0)],
        ]
        self._drive(4, batches)

    def test_mid_history_rewrite_after_wrap(self):
        batches = [[(0, 100 + i, float(i))] for i in range(6)]  # wraps W=4
        batches.append([(0, 103, 99.0)])  # rewrite a bar now mid-ring
        batches.append([(0, 106, 6.0)])  # appends continue cleanly
        self._drive(4, batches)

    def test_stale_absent_timestamp_dropped_after_wrap(self):
        batches = [[(0, 100 + 2 * i, float(i))] for i in range(6)]
        batches.append([(0, 101, 50.0)])  # never stored → dropped
        self._drive(4, batches)

    def test_churn_reset_and_reclaim(self):
        batches = [[(1, 100 + i, float(i))] for i in range(5)]
        batches += [[(1, 50, 9.0)]]  # the RECLAIMED row starts a new epoch
        batches += [[(1, 51 + i, float(i))] for i in range(6)]
        self._drive(4, batches, resets={4: 1})

    def test_min_periods_warmup_edges(self):
        """Partially-filled rings: every fill level below the window must
        read the same warm-up sentinels through the canonical view."""
        for n in range(1, 5):
            batches = [[(0, 100 + i, float(i))] for i in range(n)]
            ring, shift = self._drive(4, batches)
            m = materialize(ring)
            empties = np.asarray(m.times[0]) == -1
            assert empties.sum() == 4 - n
            assert empties[: 4 - n].all()  # warm-up NaN at the FRONT

    def test_tail_view_matches_canonical_suffix(self):
        batches = [[(0, 100 + i, float(i)), (2, 100 + i, float(i) * 2)] for i in range(9)]
        ring, shift = self._drive(6, batches)
        for k in (1, 2, 5):
            tail = materialize_tail(ring, k)
            assert np.array_equal(
                np.asarray(tail.times), np.asarray(shift.times)[:, -k:]
            )
            tv = np.asarray(tail.values)
            sv = np.asarray(shift.values)[:, -k:]
            assert ((tv == sv) | (np.isnan(tv) & np.isnan(sv))).all()
            # filled stays the TRUE count even when it exceeds the width
            assert np.array_equal(np.asarray(tail.filled), np.asarray(shift.filled))

    def test_randomized_stream(self):
        rng = np.random.default_rng(1234)
        ring = empty_buffer(4, window=5)
        shift = empty_buffer(4, window=5)
        last = np.zeros(4, int)
        for step in range(120):
            entries = []
            for s in range(4):
                if rng.random() < 0.6:
                    roll = rng.random()
                    if roll < 0.65 or last[s] == 0:
                        last[s] += 1
                        t = 1000 + last[s]
                    elif roll < 0.85:
                        t = 1000 + int(rng.integers(1, last[s] + 1))
                    else:
                        t = 1000 + last[s]
                    entries.append((s, t, float(rng.random() * 100)))
            if not entries:
                continue
            rows, ts, vals = _batch(entries)
            ring = apply_updates(ring, rows, ts, vals)
            shift = apply_updates_shift(shift, rows, ts, vals)
            if step % 17 == 0:
                rr = np.array([int(rng.integers(0, 4))], np.int32)
                ring = reset_rows(ring, rr)
                shift = reset_rows(shift, rr)
                last[int(rr[0])] = 0
            _assert_same(ring, shift, ctx=f"step {step}")
