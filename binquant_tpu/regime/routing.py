"""Autotrade regime routing, batched.

Re-implements ``/root/reference/market_regime/regime_routing.py`` as masks
over the whole symbol batch: the policy that blocks long autotrade on
transitioning/unstable/hostile regimes (l.47-76) becomes one ``(S,)`` bool
array computed inside the jit'd tick step, and a host-side explainer
reproduces the same decision with a reason string for Telegram/analytics
payloads (reasons are load-bearing in the reference's messages).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from binquant_tpu.enums import MarketRegimeCode, MicroRegimeCode, MicroTransitionCode
from binquant_tpu.regime.context import MarketContext

# Reference: 30 min minimum regime age (regime_routing.py:10), in seconds
# (device times are int32 seconds).
DEFAULT_REGIME_STABILITY_S = 30 * 60


def regime_age_s(context: MarketContext) -> jnp.ndarray:
    """Seconds the current market regime has held (clamped at 0); -1 when no
    stability anchor exists yet (reference returns None)."""
    has_anchor = context.regime_stable_since >= 0
    age = jnp.maximum(context.timestamp - context.regime_stable_since, 0)
    return jnp.where(has_anchor, age, -1)


def is_regime_stable(
    context: MarketContext, min_age_s: int = DEFAULT_REGIME_STABILITY_S
) -> jnp.ndarray:
    """Scalar bool: regime held ≥ min_age and no in-flight transition
    (regime_routing.py:30-44)."""
    age = regime_age_s(context)
    return (
        context.valid
        & ~context.regime_is_transitioning
        & (age >= 0)
        & (age >= min_age_s)
    )


def allows_long_autotrade_mask(
    context: MarketContext, min_age_s: int = DEFAULT_REGIME_STABILITY_S
) -> jnp.ndarray:
    """(S,) bool — the reference's `allows_long_autotrade(context, symbol)`
    for every symbol at once (regime_routing.py:47-76).

    Rows with no valid features fall back to the symbol-less policy
    (market regime in {TREND_UP, RANGE}), as the reference does when
    `resolve_symbol_features` returns None.
    """
    R = MarketRegimeCode
    M = MicroRegimeCode

    # is_regime_stable already enforces context.valid and
    # ~regime_is_transitioning; with HIGH_STRESS/TREND_DOWN/TRANSITIONAL
    # excluded, only TREND_UP and RANGE remain in the 5-regime ladder.
    market_regime_ok = (context.market_regime == R.TREND_UP) | (
        context.market_regime == R.RANGE
    )
    market_ok = (
        is_regime_stable(context, min_age_s)
        & market_regime_ok
        & (context.market_stress_score < 0.35)
    )

    f = context.features
    micro = f.micro_regime
    micro_allows = jnp.where(
        micro == M.TREND_DOWN,
        f.micro_transition == MicroTransitionCode.RECOVERY,
        jnp.where(
            micro == M.VOLATILE,
            False,
            (micro == M.TREND_UP) | (micro == M.RANGE) | (micro == M.TRANSITIONAL),
        ),
    )
    per_symbol = jnp.where(f.valid & (micro >= 0), micro_allows, market_regime_ok)
    return market_ok & per_symbol


# ---------------------------------------------------------------------------
# Host-side explainer (reason strings for emitted payloads)
# ---------------------------------------------------------------------------

_MARKET_REGIME_NAMES = {c.value: c.name for c in MarketRegimeCode}
_MICRO_REGIME_NAMES = {c.value: c.name for c in MicroRegimeCode}


def long_autotrade_decision(
    context_np: MarketContext, row: int, min_age_s: int = DEFAULT_REGIME_STABILITY_S
) -> tuple[bool, str]:
    """(allowed, reason) for one symbol row, from a host snapshot of the
    context (numpy'd MarketContext). Mirrors the mask exactly; used by the
    emission path to annotate blocked signals."""
    c = context_np
    if not bool(np.asarray(c.valid)):
        return False, "market_context_unavailable"
    if bool(np.asarray(c.regime_is_transitioning)):
        return False, "regime_transitioning"
    anchor = int(np.asarray(c.regime_stable_since))
    if anchor < 0:
        return False, "regime_stability_unknown"
    age = max(int(np.asarray(c.timestamp)) - anchor, 0)
    if age < min_age_s:
        return False, f"regime_unstable_{age}s"
    regime = int(np.asarray(c.market_regime))
    name = _MARKET_REGIME_NAMES.get(regime, "UNKNOWN")
    if name in {"HIGH_STRESS", "TREND_DOWN", "TRANSITIONAL"}:
        return False, f"market_regime_{name.lower()}"
    if float(np.asarray(c.market_stress_score)) >= 0.35:
        return False, "market_stress_elevated"
    if name not in {"TREND_UP", "RANGE"}:
        return False, f"market_regime_{name.lower()}"
    f = c.features
    if not bool(np.asarray(f.valid)[row]) or int(np.asarray(f.micro_regime)[row]) < 0:
        return True, f"market_regime_{name.lower()}_no_symbol_features"
    micro = int(np.asarray(f.micro_regime)[row])
    micro_name = _MICRO_REGIME_NAMES.get(micro, "UNKNOWN")
    if micro == MicroRegimeCode.TREND_DOWN:
        if int(np.asarray(f.micro_transition)[row]) == MicroTransitionCode.RECOVERY:
            return True, "micro_trend_down_recovery"
        return False, "micro_regime_trend_down"
    if micro == MicroRegimeCode.VOLATILE:
        return False, "micro_regime_volatile"
    if micro in {
        MicroRegimeCode.TREND_UP,
        MicroRegimeCode.RANGE,
        MicroRegimeCode.TRANSITIONAL,
    }:
        return True, f"micro_regime_{micro_name.lower()}"
    return False, f"micro_regime_{micro_name.lower()}"
