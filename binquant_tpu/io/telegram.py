"""Telegram emission sink.

Equivalent of ``/root/reference/consumers/telegram_consumer.py``: HTML
sanitizer preserving whitelisted tags (l.44-76), content-based dedupe key
from algo/symbol/action fields with a 900 s cooldown and pending-set
(l.82-137), a global send lock with 1 s min interval and flood-control
backoff (l.139-172), and fire-and-forget dispatch with a task-set GC guard
(l.193-212). Transport is injectable (an async callable posting to the Bot
API) so tests never hit the network; the default uses httpx against
api.telegram.org — no python-telegram-bot dependency.
"""

from __future__ import annotations

import asyncio
import hashlib
import html
import logging
import re
import time
from collections.abc import Awaitable, Callable


class RetryAfterError(Exception):
    """Telegram flood control: retry after N seconds."""

    def __init__(self, retry_after: float) -> None:
        super().__init__(f"retry after {retry_after}s")
        self.retry_after = retry_after


def make_httpx_transport(token: str) -> Callable[[str, str], Awaitable[None]]:
    """Default transport: POST sendMessage via httpx (async)."""
    import httpx

    url = f"https://api.telegram.org/bot{token}/sendMessage"

    async def send(chat_id: str, text: str) -> None:
        async with httpx.AsyncClient(timeout=10) as client:
            resp = await client.post(
                url,
                json={"chat_id": chat_id, "text": text, "parse_mode": "HTML"},
            )
            if resp.status_code == 429:
                retry = float(resp.json().get("parameters", {}).get("retry_after", 5))
                raise RetryAfterError(retry)
            resp.raise_for_status()

    return send


class TelegramConsumer:
    _ALLOWED_HTML_TAGS = ("b", "strong", "i", "em", "u", "s", "code", "pre", "a")
    _MIN_SEND_INTERVAL_SECONDS = 1.0
    _RETRY_AFTER_PAD_SECONDS = 2.0
    _SIGNAL_DEDUPE_SECONDS = 900.0

    def __init__(
        self,
        token: str,
        chat_id: str,
        is_enabled: bool = True,
        transport: Callable[[str, str], Awaitable[None]] | None = None,
    ) -> None:
        self.chat_id = chat_id
        self.is_enabled = is_enabled
        self._transport = transport or (
            make_httpx_transport(token) if token else None
        )
        self._send_lock = asyncio.Lock()
        self._min_send_interval_seconds = self._MIN_SEND_INTERVAL_SECONDS
        self._retry_after_pad_seconds = self._RETRY_AFTER_PAD_SECONDS
        self._signal_dedupe_seconds = self._SIGNAL_DEDUPE_SECONDS
        self._last_send_at = 0.0
        self._recent_signal_keys: dict[str, float] = {}
        self._pending_signal_keys: set[str] = set()
        # Keep created tasks alive until the Telegram round-trip completes.
        self._background_tasks: set[asyncio.Task] = set()

    # -- sanitization (reference l.44-76) -----------------------------------

    def _sanitize_html(self, message: str) -> str:
        sanitized = html.escape(message, quote=True)
        for tag in self._ALLOWED_HTML_TAGS:
            sanitized = sanitized.replace(f"&lt;{tag}&gt;", f"<{tag}>")
            sanitized = sanitized.replace(f"&lt;/{tag}&gt;", f"</{tag}>")
        sanitized = re.sub(
            r"&lt;(pre|code)\s+([^&]*)&gt;",
            lambda m: f"<{m.group(1)} {m.group(2)}>",
            sanitized,
        )
        sanitized = re.sub(
            r"&lt;a\s+href=(?:&#x27;|&quot;)(.+?)(?:&#x27;|&quot;)&gt;",
            lambda m: f'<a href="{m.group(1)}">',
            sanitized,
        )
        sanitized = re.sub(
            r"&amp;(lt|gt|amp|quot|#x27);",
            lambda m: f"&{m.group(1)};",
            sanitized,
        )
        return sanitized

    # -- dedupe (reference l.78-137) ----------------------------------------

    @staticmethod
    def _clean_signal_message(message: str) -> str:
        lines = [line.strip() for line in message.splitlines() if line.strip()]
        return "\n".join(lines)

    def _message_field(self, cleaned: str, label: str) -> str:
        match = re.search(rf"^- {re.escape(label)}:\s*(.+)$", cleaned, re.M)
        return match.group(1).strip() if match else ""

    def _signal_dedupe_key(self, cleaned: str) -> str:
        hashtags = re.findall(r"#([A-Za-z0-9_]+)", cleaned)
        symbol = hashtags[-1] if hashtags else ""
        algo_match = re.search(r"<strong>#([^<\s]+)\s+algorithm</strong>", cleaned)
        algo = algo_match.group(1) if algo_match else ""
        fields = {
            "action": self._message_field(cleaned, "Action"),
            "strategy": self._message_field(cleaned, "Strategy"),
            "route": self._message_field(cleaned, "Autotrade route"),
            "autotrade": "enabled"
            if "Autotrade is enabled" in cleaned
            else "disabled"
            if "Autotrade is disabled" in cleaned
            else "",
        }
        key_parts = [algo, symbol, *fields.values()]
        if any(key_parts):
            return "|".join(key_parts)
        return hashlib.sha1(cleaned.encode("utf-8")).hexdigest()

    def _drop_duplicate_signal(self, signal_key: str) -> bool:
        if self._signal_dedupe_seconds <= 0:
            if signal_key in self._pending_signal_keys:
                return True
            self._pending_signal_keys.add(signal_key)
            return False

        now = time.monotonic()
        expired = [
            k
            for k, sent_at in self._recent_signal_keys.items()
            if now - sent_at >= self._signal_dedupe_seconds
        ]
        for k in expired:
            self._recent_signal_keys.pop(k, None)

        if signal_key in self._pending_signal_keys:
            logging.info("Telegram duplicate signal already pending; skipping")
            return True
        if signal_key in self._recent_signal_keys:
            logging.info("Telegram duplicate signal inside cooldown; skipping")
            return True

        self._recent_signal_keys[signal_key] = now
        self._pending_signal_keys.add(signal_key)
        return False

    # -- send path (reference l.139-184) ------------------------------------

    async def _sleep_for_send_interval(self) -> None:
        if self._min_send_interval_seconds <= 0 or self._last_send_at <= 0:
            return
        elapsed = time.monotonic() - self._last_send_at
        remaining = self._min_send_interval_seconds - elapsed
        if remaining > 0:
            await asyncio.sleep(remaining)

    async def send_msg(self, message: str) -> None:
        if self._transport is None:
            return
        async with self._send_lock:
            while True:
                await self._sleep_for_send_interval()
                try:
                    await self._transport(self.chat_id, self._sanitize_html(message))
                    self._last_send_at = time.monotonic()
                    return
                except RetryAfterError as e:
                    sleep_s = e.retry_after + self._retry_after_pad_seconds
                    logging.warning(
                        "Telegram flood control active; retrying in %.1fs", sleep_s
                    )
                    await asyncio.sleep(sleep_s)

    async def send_signal(self, message: str) -> None:
        try:
            cleaned = self._clean_signal_message(message)
            if not cleaned:
                return
            await self.send_msg(cleaned)
        except Exception as e:
            logging.error("Error sending telegram signal: %s", e)
            logging.error("Original message: %s", message)

    def _finish_signal_task(
        self, task: asyncio.Task, signal_key: str | None = None
    ) -> None:
        self._background_tasks.discard(task)
        if signal_key is not None:
            self._pending_signal_keys.discard(signal_key)

    def dispatch_signal(self, message: str) -> asyncio.Task | None:
        """Fire-and-forget send; never propagates exceptions (l.193-212)."""
        if not self.is_enabled:
            return None
        cleaned = self._clean_signal_message(message)
        if not cleaned:
            return None
        signal_key = self._signal_dedupe_key(cleaned)
        if self._drop_duplicate_signal(signal_key):
            return None
        task = asyncio.create_task(self.send_signal(cleaned))
        self._background_tasks.add(task)
        task.add_done_callback(lambda t: self._finish_signal_task(t, signal_key))
        return task
