"""Websocket ingest: chunked multi-client kline streams.

Equivalent of ``/root/reference/producers/klines_connector.py`` and
``/root/reference/shared/streaming/websocket_factory.py``: symbols are
chunked across N websocket connections (400/client Binance, 300/connection
KuCoin), frames are JSON-parsed, **closed candles only** are pushed onto the
asyncio queue as ``KlineProduceModel`` dicts, and a closed socket triggers
reconnect-and-resubscribe. Uses the ``websockets`` library; the connection
factory is injectable so tests drive the parser with fake frames.

The richer ``ExtendedKline`` fields (quote volume, trade count, taker-buy
splits) are captured here too — the reference drops them at the connector
(KlineProduceModel has only OHLCV) and several strategies then lack them on
the 5m path; the TPU buffer keeps the full payload.
"""

from __future__ import annotations

import asyncio
import json
import logging
from collections.abc import Callable
from typing import Any

from binquant_tpu.exceptions import WebSocketError
from binquant_tpu.schemas import SymbolModel

BINANCE_WS_BASE = "wss://stream.binance.com:9443/ws"
MAX_MARKETS_PER_CLIENT = 400  # Binance (klines_connector.py:24)
MAX_TOPICS_PER_CONNECTION = 300  # KuCoin (websocket_factory.py:30)

FIAT_PREFIXES = ("USDT", "USDC", "BUSD", "EUR", "TRY", "DAI")


def filter_fiat_symbols(symbols: list[SymbolModel]) -> list[SymbolModel]:
    """Drop fiat-to-fiat pairs (websocket_factory.py:49)."""
    return [
        s
        for s in symbols
        if s.active and not any(s.id.startswith(p) for p in FIAT_PREFIXES)
    ]


def parse_binance_kline_frame(raw: str | bytes) -> dict | None:
    """One frame → ExtendedKline-shaped dict for closed candles, else None
    (klines_connector.py:148-164 + the extra payload fields)."""
    try:
        res = json.loads(raw)
    except Exception as e:
        logging.error("Failed to decode ws message: %s; len=%s", e, len(str(raw)))
        return None
    if res.get("e") != "kline":
        logging.debug("Non-kline event received: %s", res.get("e"))
        return None
    k = res.get("k", {})
    if not k.get("s") or not k.get("x"):  # closed candles only
        return None
    return {
        "symbol": k["s"],
        "open_time": int(k["t"]),
        "close_time": int(k["T"]),
        "open": float(k["o"]),
        "high": float(k["h"]),
        "low": float(k["l"]),
        "close": float(k["c"]),
        "volume": float(k["v"]),
        "quote_asset_volume": float(k.get("q", 0.0)),
        "number_of_trades": float(k.get("n", 0.0)),
        "taker_buy_base_volume": float(k.get("V", 0.0)),
        "taker_buy_quote_volume": float(k.get("Q", 0.0)),
    }


class KlinesConnector:
    """Binance kline streams over N chunked connections with reconnect."""

    def __init__(
        self,
        queue: asyncio.Queue,
        symbols: list[SymbolModel],
        interval: str = "15m",
        connect: Callable[..., Any] | None = None,
        max_markets_per_client: int = MAX_MARKETS_PER_CLIENT,
    ) -> None:
        self.queue = queue
        self.symbols = filter_fiat_symbols(symbols)
        self.interval = interval
        self.max_markets_per_client = max_markets_per_client
        if connect is None:
            import websockets

            connect = websockets.connect
        self._connect = connect
        self._tasks: list[asyncio.Task] = []

    def _chunks(self) -> list[list[str]]:
        streams = [
            f"{s.id.lower()}@kline_{self.interval}" for s in self.symbols
        ]
        n = self.max_markets_per_client
        return [streams[i : i + n] for i in range(0, len(streams), n)]

    async def _run_client(self, idx: int, markets: list[str]) -> None:
        """One connection: subscribe, pump frames, reconnect on close
        (klines_connector.py:53-69)."""
        backoff = 1.0
        while True:
            try:
                async with self._connect(BINANCE_WS_BASE) as ws:
                    await ws.send(
                        json.dumps(
                            {"method": "SUBSCRIBE", "params": markets, "id": 1}
                        )
                    )
                    logging.info(
                        "Subscribed client %d to %d markets", idx, len(markets)
                    )
                    backoff = 1.0
                    async for raw in ws:
                        kline = parse_binance_kline_frame(raw)
                        if kline is not None:
                            await self.queue.put(kline)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logging.warning(
                    "ws client %d dropped (%s); reconnecting in %.0fs",
                    idx,
                    e,
                    backoff,
                )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    async def start_stream(self) -> None:
        chunks = self._chunks()
        if not chunks:
            raise WebSocketError("no symbols to subscribe")
        for idx, markets in enumerate(chunks):
            self._tasks.append(
                asyncio.create_task(self._run_client(idx, markets))
            )

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()


class WebsocketClientFactory:
    """Chooses the exchange connector from autotrade settings
    (websocket_factory.py:21-158)."""

    def __init__(
        self,
        queue: asyncio.Queue,
        symbols: list[SymbolModel],
        exchange_id: str = "binance",
        interval: str = "15m",
        connect: Callable[..., Any] | None = None,
    ) -> None:
        self.queue = queue
        self.symbols = symbols
        self.exchange_id = exchange_id
        self.interval = interval
        self._connect = connect

    def create_connector(self) -> KlinesConnector:
        # KuCoin spot/futures use the same chunked-subscription shape with a
        # lower per-connection topic cap (websocket_factory.py:30,86-143).
        max_markets = (
            MAX_TOPICS_PER_CONNECTION
            if self.exchange_id == "kucoin"
            else MAX_MARKETS_PER_CLIENT
        )
        return KlinesConnector(
            self.queue,
            self.symbols,
            interval=self.interval,
            connect=self._connect,
            max_markets_per_client=max_markets,
        )
