"""Strategy kernel contract.

Uniform output batch mirroring what every reference strategy ultimately
feeds into its three sinks (``SignalsConsumer`` fields + routing reason):
trigger mask, direction, scores, autotrade flag, stop-loss, and a
diagnostics dict of per-symbol telemetry arrays that the host edge formats
into the Telegram/analytics payloads (reference messages carry these values
line by line, e.g. ``strategies/activity_burst_pump.py:197-221``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class StrategyOutputs(NamedTuple):
    """One strategy's verdict for every symbol row this tick."""

    trigger: jnp.ndarray  # (S,) bool — emit a signal for this row
    direction: jnp.ndarray  # (S,) int32 — 0 LONG / 1 SHORT (Direction enum)
    score: jnp.ndarray  # (S,) f32 — local score (0 when unused)
    autotrade: jnp.ndarray  # (S,) bool — device-side autotrade verdict
    stop_loss_pct: jnp.ndarray  # (S,) f32 — 0 when strategy doesn't set one
    diagnostics: dict[str, jnp.ndarray]  # (S,) telemetry for host formatting


def no_signal(num_symbols: int) -> StrategyOutputs:
    return StrategyOutputs(
        trigger=jnp.zeros((num_symbols,), dtype=bool),
        direction=jnp.zeros((num_symbols,), dtype=jnp.int32),
        score=jnp.zeros((num_symbols,), dtype=jnp.float32),
        autotrade=jnp.zeros((num_symbols,), dtype=bool),
        stop_loss_pct=jnp.zeros((num_symbols,), dtype=jnp.float32),
        diagnostics={},
    )
