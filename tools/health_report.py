#!/usr/bin/env python
"""Render the numeric-health observatory's state from the JSONL event log.

The engine emits ``numeric_digest`` (sampled) / ``numeric_anomaly``
(forced) events carrying the decoded wire digest, ``carry_drift`` /
``carry_drift_alarm`` events with the audit-tick drift meters, and
``compile`` / ``compile_summary`` events from the executable ledger.
This tool folds a log back into the "is the fast path still numerically
honest, and what did this executable cost" view with no service in the
loop:

    python tools/health_report.py /var/log/bqt/events.jsonl
    python tools/health_report.py events.jsonl --json

Output format is golden-pinned (tests/test_numeric_health.py) — keep
changes deliberate, like tools/trace_report.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def load_events(path: str | Path) -> list[dict]:
    """All events from a JSONL log, in file order; corrupt lines (a torn
    write at rotation) are skipped, not fatal."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def summarize(events: list[dict]) -> dict:
    """The report's data model: latest digest, latest drift, anomaly and
    alarm tallies, and the per-executable compile aggregate."""
    digest = None
    digest_kind = None
    drift = None
    anomalies = 0
    alarms = 0
    compiles: dict[str, dict] = {}
    summary = None
    ingest = None
    ingest_kind = None
    ingest_anomalies = 0
    ingest_recoveries = 0
    slo: dict[str, dict] = {}
    delivery_acks: dict[str, int] = {}
    delivery_sheds = 0
    breaker_transitions = 0
    for ev in events:
        kind = ev.get("event")
        if kind in ("numeric_digest", "numeric_anomaly") and "digest" in ev:
            digest, digest_kind = ev["digest"], kind
            if kind == "numeric_anomaly":
                anomalies += 1
        elif kind in (
            "ingest_digest", "ingest_anomaly", "ingest_recovered"
        ) and "digest" in ev:
            ingest, ingest_kind = ev["digest"], kind
            if kind == "ingest_anomaly":
                ingest_anomalies += 1
            elif kind == "ingest_recovered":
                ingest_recoveries += 1
        elif kind in ("carry_drift", "carry_drift_alarm") and "drift" in ev:
            drift = ev["drift"]
            if kind == "carry_drift_alarm":
                alarms += 1
        elif kind == "compile":
            entry = compiles.setdefault(
                ev.get("executable", "?"),
                {"compiles": 0, "seconds": 0.0, "cache": "unknown"},
            )
            entry["compiles"] += 1
            entry["seconds"] += float(ev.get("seconds", 0.0) or 0.0)
            entry["cache"] = ev.get("cache", "unknown")
        elif kind == "compile_summary":
            summary = ev
        elif kind in ("slo_burn", "slo_recover"):
            cell = slo.setdefault(
                ev.get("slo", "?"),
                {
                    "kind": ev.get("kind", "?"),
                    "budget": ev.get("budget"),
                    "unit": ev.get("unit", ""),
                    "burns": 0,
                    "recovers": 0,
                    "burning": False,
                },
            )
            if kind == "slo_burn":
                cell["burning"] = True
                if ev.get("entering"):
                    cell["burns"] += 1
            else:
                cell["burning"] = False
                cell["recovers"] += 1
        elif kind == "delivery_ack":
            name = ev.get("sink", "?")
            delivery_acks[name] = delivery_acks.get(name, 0) + 1
        elif kind == "delivery_shed":
            delivery_sheds += 1
        elif kind == "delivery_breaker":
            breaker_transitions += 1
    return {
        "digest": digest,
        "digest_kind": digest_kind,
        "drift": drift,
        "anomalies": anomalies,
        "drift_alarms": alarms,
        "compiles": compiles,
        "compile_summary": summary,
        "ingest": ingest,
        "ingest_kind": ingest_kind,
        "ingest_anomalies": ingest_anomalies,
        "ingest_recoveries": ingest_recoveries,
        "slo": slo,
        "delivery_acks": delivery_acks,
        "delivery_sheds": delivery_sheds,
        "breaker_transitions": breaker_transitions,
    }


def render(model: dict) -> str:
    lines: list[str] = []
    digest = model["digest"]
    lines.append("== numeric digest ==")
    if digest is None:
        lines.append("  (no digest events — BQT_NUMERIC_DIGEST off?)")
    else:
        lines.append(
            f"  source {model['digest_kind']}  nan_total "
            f"{digest.get('nan_total', 0)}  inf_total "
            f"{digest.get('inf_total', 0)}  anomaly_events "
            f"{model['anomalies']}"
        )
        for stage in sorted(digest.get("nan_rows", {})):
            lines.append(
                f"  {stage:<12} nan_rows {digest['nan_rows'][stage]:>5}  "
                f"inf_rows {digest['inf_rows'][stage]:>5}"
            )
        bad = {
            k: v
            for k, v in digest.get("strategy_nonfinite", {}).items()
            if v
        }
        lines.append(f"  strategies   nonfinite {sum(bad.values()):>5}"
                     + (f"  ({', '.join(sorted(bad))})" if bad else ""))
        fired = digest.get("fired", {})
        hot = [f"{k}={v}" for k, v in sorted(fired.items()) if v]
        lines.append("  fired        " + (" ".join(hot) if hot else "(none)"))
        for series in sorted(digest.get("series", {})):
            st = digest["series"][series]
            lines.append(
                f"  {series:<12} min {_fmt(st.get('min')):>12}  max "
                f"{_fmt(st.get('max')):>12}  absmax "
                f"{_fmt(st.get('absmax')):>12}"
            )
    lines.append("")
    lines.append("== carry drift (latest audit) ==")
    drift = model["drift"]
    if drift is None:
        lines.append("  (no carry_drift events — BQT_DRIFT_METER off or no "
                     "audit tick yet)")
    else:
        lines.append(f"  alarm_events {model['drift_alarms']}")
        for family in sorted(drift):
            v = drift[family]
            lines.append(
                f"  {family:<12} max_abs {_fmt(v.get('max_abs')):>12}  "
                f"max_rel {_fmt(v.get('max_rel')):>12}  "
                f"max_ulp {_fmt(v.get('max_ulp')):>10}  "
                f"compared {v.get('compared', 0):>8}"
            )
    # ingest section (ISSUE 15) — rendered only when ingest events exist,
    # so pre-observatory logs render byte-identically
    if model.get("ingest") is not None:
        lines.append("")
        lines.append("== ingest health (latest digest) ==")
        ing = model["ingest"]
        lines.append(
            f"  source {model['ingest_kind']}  tracked "
            f"{ing.get('tracked', 0)}  stale_total "
            f"{ing.get('stale_total', 0)}  anomaly_events "
            f"{model['ingest_anomalies']}  recoveries "
            f"{model['ingest_recoveries']}"
        )
        for interval in ("5m", "15m"):
            sect = ing.get(interval) or {}
            lines.append(
                f"  {interval:<4} stale 1x/3x/10x "
                f"{sect.get('stale_1x', 0)}/{sect.get('stale_3x', 0)}/"
                f"{sect.get('stale_10x', 0)}  max_age "
                f"{_fmt(sect.get('max_age_s'))}s  covered "
                f"{sect.get('covered', 0)}  min_bars "
                f"{sect.get('min_bars', 0)}  fresh {sect.get('fresh', 0)}"
            )
    # delivery / SLO section (ISSUE 16) — rendered only when delivery or
    # SLO events exist, so pre-observatory logs render byte-identically
    if model.get("slo") or model.get("delivery_acks"):
        lines.append("")
        lines.append("== delivery / SLO ==")
        acks = model.get("delivery_acks") or {}
        if acks:
            tally = " ".join(
                f"{name}={acks[name]}" for name in sorted(acks)
            )
            lines.append(
                f"  acks {tally}  sheds {model.get('delivery_sheds', 0)}  "
                f"breaker_transitions {model.get('breaker_transitions', 0)}"
            )
        for name in sorted(model.get("slo") or {}):
            cell = model["slo"][name]
            budget = (
                f"{cell['budget']}{cell['unit']}"
                if cell.get("budget") is not None
                else "?"
            )
            status = "BURNING" if cell.get("burning") else "ok"
            lines.append(
                f"  slo {name:<22} kind {cell.get('kind', '?'):<10} "
                f"budget {budget:>10}  burns {cell['burns']}  "
                f"recovers {cell['recovers']}  status {status}"
            )
    lines.append("")
    lines.append("== executable ledger ==")
    if not model["compiles"]:
        lines.append("  (no compile events)")
    else:
        for name in sorted(model["compiles"]):
            e = model["compiles"][name]
            lines.append(
                f"  {name:<24} compiles {e['compiles']:>3}  "
                f"seconds {e['seconds']:>8.3f}  cache {e['cache']}"
            )
    summary = model["compile_summary"]
    if summary is not None:
        lines.append(
            f"  boot total: {_fmt(summary.get('compile_seconds'))}s over "
            f"{summary.get('executables', 0)} executables  "
            f"(persistent cache {summary.get('persistent_cache_hits', 0)} "
            f"hit / {summary.get('persistent_cache_misses', 0)} miss)"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("log", help="JSONL event log (BQT_EVENT_LOG file)")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the raw data model instead of the rendered report",
    )
    args = parser.parse_args(argv)

    events = load_events(args.log)
    if not events:
        print(f"no events in {args.log}", file=sys.stderr)
        return 1
    model = summarize(events)
    if args.json:
        print(json.dumps(model, indent=2, sort_keys=True))
    else:
        print(render(model))
    return 0


if __name__ == "__main__":
    sys.exit(main())
