#!/usr/bin/env python
"""Render delivery-plane activity from the JSONL event log.

The durable delivery plane (``binquant_tpu/io/delivery.py``) emits
``delivery_*`` events as it works: ``delivery_breaker`` on every circuit
transition, ``delivery_shed`` per counted loss, ``delivery_ack`` per
confirmed delivery, ``delivery_wal_replay`` when a boot re-enqueues
unacked entries, and one ``delivery_summary`` scoreboard when a plane
retires. This tool turns an event log back into the per-sink delivery
story without any service in the loop (golden-pinned like
scenario_report — keep format changes deliberate):

    python tools/delivery_report.py /tmp/bqt_delivery_events.jsonl
    python tools/delivery_report.py events.jsonl --sink autotrade
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

DELIVERY_EVENTS = (
    "delivery_breaker",
    "delivery_shed",
    "delivery_ack",
    "delivery_wal_replay",
    "delivery_summary",
    "binbot_retry_exhausted",
)


def load_delivery_events(path: str | Path) -> list[dict]:
    """All delivery-plane events, in file order; corrupt lines (a torn
    write at rotation) are skipped, not fatal."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if record.get("event") in DELIVERY_EVENTS:
                out.append(record)
    return out


def render_summary(summary: dict) -> list[str]:
    """One ``delivery_summary`` scoreboard → the per-sink table."""
    lines = [
        f"{'sink':<12} {'policy':<14} {'enq':>5} {'ack':>5} "
        f"{'retry':>5} {'shed':>5} {'replay':>6}  breaker"
    ]
    for name in sorted(summary.get("sinks", {})):
        cell = summary["sinks"][name]
        shed_total = sum((cell.get("shed") or {}).values())
        transitions = cell.get("breaker_transitions") or []
        breaker = cell.get("breaker", "closed")
        if transitions:
            breaker += " (" + ">".join(transitions) + ")"
        lines.append(
            f"{name:<12} {cell.get('policy', '?'):<14}"
            f" {cell.get('enqueued', 0):>5} {cell.get('acked', 0):>5}"
            f" {cell.get('retries', 0):>5} {shed_total:>5}"
            f" {cell.get('wal_replayed', 0):>6}  {breaker}"
        )
        for reason in sorted(cell.get("shed") or {}):
            lines.append(
                f"{'':<12}   shed[{reason}] = {cell['shed'][reason]}"
            )
    return lines


def render_report(events: list[dict], sink: str | None = None) -> str:
    """The deterministic report: breaker/shed/replay timeline, ack
    tallies, and the final per-sink summary table."""
    lines: list[str] = []
    acks: dict[str, int] = {}
    ack_attempts: dict[str, int] = {}
    replays: dict[str, int] = {}
    last_summary: dict | None = None
    exhausted = 0
    for e in events:
        if sink and e.get("sink") not in (None, sink):
            continue
        kind = e.get("event")
        if kind == "delivery_breaker":
            lines.append(
                f"breaker  {e.get('sink', '?'):<12} -> {e.get('state', '?'):<10}"
                f" after {e.get('consecutive_failures', 0)} consecutive"
                " failures"
            )
        elif kind == "delivery_shed":
            lines.append(
                f"shed     {e.get('sink', '?'):<12} reason={e.get('reason', '?')}"
            )
        elif kind == "delivery_ack":
            name = e.get("sink", "?")
            acks[name] = acks.get(name, 0) + 1
            ack_attempts[name] = ack_attempts.get(name, 0) + int(
                e.get("attempts", 1) or 1
            )
            if e.get("replayed"):
                replays[name] = replays.get(name, 0) + 1
        elif kind == "delivery_wal_replay":
            lines.append(
                f"replay   WAL -> {e.get('entries', 0)} unacked"
                " entries re-enqueued at boot"
            )
        elif kind == "binbot_retry_exhausted":
            exhausted += 1
        elif kind == "delivery_summary":
            last_summary = e
    for name in sorted(acks):
        mean = ack_attempts[name] / acks[name]
        extra = (
            f" ({replays[name]} via WAL replay)" if replays.get(name) else ""
        )
        lines.append(
            f"acked    {name:<12} {acks[name]} deliveries,"
            f" {mean:.2f} attempts/ack{extra}"
        )
    if exhausted:
        lines.append(f"binbot   {exhausted} retry-budget exhaustions")
    if last_summary is not None:
        lines.append("")
        lines.extend(render_summary(last_summary))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("log", help="JSONL event log (BQT_EVENT_LOG file)")
    parser.add_argument("--sink", help="render only this sink's activity")
    args = parser.parse_args(argv)

    events = load_delivery_events(args.log)
    if not events:
        print(f"no delivery events in {args.log}", file=sys.stderr)
        return 1
    print(render_report(events, sink=args.sink))
    return 0


if __name__ == "__main__":
    sys.exit(main())
