"""Unified observability subsystem (ROADMAP: production-scale serving).

Three dependency-free pieces, importable everywhere (no jax, no httpx):

* :mod:`registry` — ``Counter`` / ``Gauge`` / ``Histogram`` primitives with
  labels, thread-safe, plus the process-global default :data:`REGISTRY`.
* :mod:`exposition` — Prometheus text rendering and a tiny asyncio HTTP
  server exposing ``/metrics`` + ``/healthz`` (``BQT_METRICS_PORT``).
* :mod:`events` — a structured JSONL event log for discrete facts
  (reconnects, signals, autotrade attempts, checkpoint saves, JIT
  compiles), each stamped with wall + monotonic time and the tick number.
* :mod:`tracing` — per-tick ``Tracer``/``Span`` trees with trace_id
  provenance, the slow-tick flight recorder ring, and the on-demand
  ``jax.profiler`` capture window (``/debug/profile`` + SIGUSR2).
* :mod:`numeric` — the numeric-health observatory's host side: wire
  digest decode → ``bqt_numeric_*`` metrics + ``numeric_anomaly``
  force-emits, and the carry-drift audit meters (``bqt_carry_drift``).
* :mod:`ledger` — the executable/compile ledger: per-jit-entry compile
  wall time, persistent-cache warm/cold verdicts, lowered cost_analysis
  bytes/flops (``/debug/executables``).

The metric name catalogue lives in :mod:`instruments` (one definition per
family — importing any instrumented module registers the whole catalogue,
so ``/metrics`` always exposes every family name). The human-readable
catalogue is in README.md §Observability.
"""

from binquant_tpu.obs.registry import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
