"""Offline replay harness.

Feeds a JSONL file of closed klines through the full pipeline with every
network sink stubbed — the correctness oracle and benchmark A/B the
reference lacks (SURVEY.md §4 implication; BASELINE.json config #2). Each
line is an ``ExtendedKline``-shaped dict; lines are replayed in file order,
with one engine tick per distinct (15m bucket) timestamp group.

Also provides ``generate_replay_file`` to synthesize a market for smoke
runs.
"""

from __future__ import annotations

import asyncio
import json
import time
from pathlib import Path
from typing import Any

import numpy as np


class StubSession:
    """In-memory binbot backend for replay (the reference's tests cut the
    same seam by patching BinbotApi)."""

    class _Resp:
        def __init__(self, payload: Any, status_code: int = 200) -> None:
            self._payload = payload
            self.status_code = status_code
            self.text = json.dumps(payload)

        def json(self) -> Any:
            return self._payload

    def __init__(self, breadth: dict | None = None) -> None:
        self.requests: list[tuple[str, str, Any]] = []
        # scripted market-breadth payload (None = the empty default, which
        # leaves breadth-gated strategies dormant)
        self.breadth = breadth

    def request(self, method: str, url: str, **kwargs):
        self.requests.append((method, url, kwargs.get("json")))
        if "available-fiat" in url:
            return self._Resp({"data": {"amount": 1000.0}})
        if "active-pairs" in url or "excluded" in url or "grid-ladders/active" in url:
            return self._Resp({"data": []})
        if "/bot" in url and method == "POST":
            return self._Resp(
                {"message": "ok", "error": 0, "data": {"pair": "X", "id": "00000000-0000-0000-0000-000000000000"}}
            )
        if "activate" in url:
            return self._Resp(
                {"message": "ok", "error": 0, "data": {"pair": "X"}}
            )
        if "market-breadth" in url:
            return self._Resp({"data": self.breadth or {}})
        return self._Resp({"data": {}})

    def get(self, url, params=None):
        return self.request("GET", url, params=params)


def make_stub_engine(
    capacity: int = 256, window: int = 200, breadth: dict | None = None
):
    """A SignalEngine wired entirely to stubs (no network)."""
    import os

    os.environ.setdefault("ENV", "CI")
    from binquant_tpu.config import Config
    from binquant_tpu.io.autotrade import AutotradeConsumer
    from binquant_tpu.io.binbot import BinbotApi
    from binquant_tpu.io.pipeline import SignalEngine
    from binquant_tpu.io.telegram import TelegramConsumer
    from binquant_tpu.regime.context import ContextConfig
    from binquant_tpu.schemas import (
        AutotradeSettingsSchema,
        TestAutotradeSettingsSchema,
    )

    Config.reset()
    config = Config()
    config.__dict__["max_symbols"] = capacity
    config.__dict__["window_bars"] = window
    binbot_api = BinbotApi("http://stub", session=StubSession(breadth=breadth))

    sent: list[str] = []

    async def capture_transport(chat_id: str, text: str) -> None:
        sent.append(text)

    telegram = TelegramConsumer(
        token="", chat_id="stub", transport=capture_transport
    )
    # futures market type so futures-only strategies (MeanReversionFade)
    # are exercised; autotrade stays off (no trade side effects in replay)
    from binquant_tpu.schemas import MarketType

    at_consumer = AutotradeConsumer(
        autotrade_settings=AutotradeSettingsSchema(
            autotrade=False, market_type=MarketType.FUTURES
        ),
        active_test_bots=[],
        all_symbols=[],
        test_autotrade_settings=TestAutotradeSettingsSchema(autotrade=False),
        active_grid_ladders=[],
        binbot_api=binbot_api,
    )
    engine = SignalEngine(
        config=config,
        binbot_api=binbot_api,
        telegram_consumer=telegram,
        at_consumer=at_consumer,
        window=window,
        context_config=ContextConfig(required_fresh_symbols=4, min_coverage_ratio=0.5),
    )
    engine._telegram_sent = sent  # type: ignore[attr-defined]
    return engine


def load_klines_by_tick(path: str | Path) -> dict[int, list[dict]]:
    """Group a JSONL kline file by 15m bucket (one engine tick each)."""
    klines_by_tick: dict[int, list[dict]] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            k = json.loads(line)
            bucket = int(k["open_time"]) // 1000 // 900
            klines_by_tick.setdefault(bucket, []).append(k)
    return klines_by_tick


def run_replay(
    path: str | Path,
    capacity: int = 256,
    window: int = 200,
    collect: list | None = None,
    breadth: dict | None = None,
) -> dict:
    """Replay a JSONL kline file; returns run statistics.

    When ``collect`` is a list, every fired signal is appended as a
    ``(tick_ms, strategy, symbol, direction, autotrade)`` tuple — the
    comparison surface for the A/B parity harness. ``breadth`` scripts the
    stub backend's market-breadth series so the breadth-gated paths
    (LiquidationSweepPump routing, grid-only policy) engage.
    """
    engine = make_stub_engine(capacity=capacity, window=window, breadth=breadth)
    klines_by_tick = load_klines_by_tick(path)

    fired_total = 0
    t_start = time.perf_counter()
    latencies = []

    async def drive() -> None:
        nonlocal fired_total
        for bucket in sorted(klines_by_tick):
            for k in sorted(klines_by_tick[bucket], key=lambda k: k["open_time"]):
                engine.ingest(k)
            # the tick fires just after the bucket's bars CLOSE
            tick_ms = (bucket + 1) * 900 * 1000
            t0 = time.perf_counter()
            fired = await engine.process_tick(now_ms=tick_ms)
            latencies.append((time.perf_counter() - t0) * 1000)
            fired_total += len(fired)
            if collect is not None:
                for s in fired:
                    collect.append(
                        (
                            tick_ms,
                            s.strategy,
                            s.symbol,
                            str(s.value.direction),
                            bool(s.value.autotrade),
                        )
                    )

    asyncio.run(drive())
    wall = time.perf_counter() - t_start
    return {
        "ticks": engine.ticks_processed,
        "signals": fired_total,
        "telegram_messages": len(engine._telegram_sent),  # type: ignore[attr-defined]
        "wall_s": round(wall, 3),
        "tick_p50_ms": round(float(np.percentile(latencies, 50)), 3) if latencies else None,
        "tick_p99_ms": round(float(np.percentile(latencies, 99)), 3) if latencies else None,
    }


def run_replay_oracle(
    path: str | Path, window: int = 200, breadth: dict | None = None
) -> list[tuple]:
    """Replay through the legacy per-symbol pandas backend
    (``backend=reference``, BASELINE config #1); returns the fired
    ``(tick_ms, strategy, symbol, direction, autotrade)`` tuples.

    Mirrors the pipeline's host-side breadth handling: adp pair from the
    (static) series, and the grid-only policy resolved from the PREVIOUS
    tick's regime — the engine reads last tick's policy when building
    HostInputs and refreshes it after the evaluation.
    """
    from binquant_tpu.io.pipeline import breadth_scalars
    from binquant_tpu.oracle import OracleEvaluator
    from binquant_tpu.regime.grid_policy import GridOnlyPolicy
    from binquant_tpu.schemas import MarketBreadthSeries

    evaluator = OracleEvaluator(
        window=window,
        required_fresh_symbols=4,
        min_coverage_ratio=0.5,
        is_futures=True,
    )
    mb = MarketBreadthSeries(**breadth) if breadth else None
    # the SAME resolution the live pipeline uses (one copy of semantics)
    adp_latest, adp_prev, _, _, _ = breadth_scalars(mb)

    policy = GridOnlyPolicy.disabled("not_evaluated")
    klines_by_tick = load_klines_by_tick(path)
    out: list[tuple] = []
    for bucket in sorted(klines_by_tick):
        for k in sorted(klines_by_tick[bucket], key=lambda k: k["open_time"]):
            evaluator.ingest(k)
        tick_ms = (bucket + 1) * 900 * 1000
        for strategy, sym, direction, autotrade in evaluator.evaluate(
            tick_ms,
            grid_policy_allows=policy.allow_grid_ladder,
            adp_latest=adp_latest,
            adp_prev=adp_prev,
        ):
            out.append((tick_ms, strategy, sym, direction, autotrade))
        # next tick's policy from THIS tick's regime (None when invalid)
        policy = GridOnlyPolicy.resolve(evaluator.last_regime, mb)
    return out


def run_replay_ab(
    path: str | Path,
    capacity: int = 256,
    window: int = 200,
    breadth: dict | None = None,
) -> dict:
    """A/B parity: the TPU batch path and the per-symbol pandas oracle run
    the same replay and must emit the identical signal set (SURVEY.md §7
    step 8 — the correctness oracle for the batched evaluation)."""
    tpu_signals: list[tuple] = []
    stats = run_replay(
        path,
        capacity=capacity,
        window=window,
        collect=tpu_signals,
        breadth=breadth,
    )
    oracle_signals = run_replay_oracle(path, window=window, breadth=breadth)
    tpu_set, oracle_set = set(tpu_signals), set(oracle_signals)
    return {
        "match": tpu_set == oracle_set,
        "tpu_count": len(tpu_set),
        "oracle_count": len(oracle_set),
        "only_tpu": sorted(tpu_set - oracle_set),
        "only_oracle": sorted(oracle_set - tpu_set),
        "strategies": sorted({s for _, s, _, _, _ in tpu_set}),
        "tpu_stats": stats,
    }


def generate_replay_file(
    path: str | Path,
    n_symbols: int = 100,
    n_ticks: int = 150,
    seed: int = 7,
) -> None:
    """Synthesize a dual-interval (5m + 15m) market replay with crafted
    setups: an activity burst on S001's 5m stream and a MeanReversionFade
    hammer on S005's 15m stream, so the emission path is exercised."""
    rng = np.random.default_rng(seed)
    # MUST be 15m-bucket-aligned: process_tick derives the evaluated bar's
    # open time from wall clock as bucket*900-900; misaligned open times
    # never match the freshness mask and silently disable every strategy.
    t0 = 1_753_000_200
    assert t0 % 900 == 0
    px = 20 + rng.random(n_symbols) * 100

    def bar(symbol, ts_s, interval_s, o, h, low, c, volume):
        return json.dumps(
            {
                "symbol": symbol,
                "open_time": ts_s * 1000,
                "close_time": (ts_s + interval_s) * 1000 - 1,
                "open": round(float(o), 6),
                "high": round(float(h), 6),
                "low": round(float(low), 6),
                "close": round(float(c), 6),
                "volume": round(float(volume), 3),
                "quote_asset_volume": round(float(volume * c), 3),
                "number_of_trades": 300,
                "taker_buy_base_volume": round(float(volume / 2), 3),
                "taker_buy_quote_volume": round(float(volume * c / 2), 3),
            }
        ) + "\n"

    with open(path, "w") as f:
        for tick in range(n_ticks):
            ts15 = t0 + tick * 900
            # S005 drifts hard down so its RSI pins low before the hammer
            rets = rng.normal(0, 0.004, n_symbols)
            rets[5] -= 0.008
            last_tick = tick == n_ticks - 1
            if last_tick and n_symbols > 3:
                # LSP setup: BTC up (long route needs btc_momentum > 0)
                # and a +3% pump on S003 (8x volume below)
                rets[0] = 0.005
                rets[3] = 0.03
            new_px = px * (1 + rets)
            for i in range(n_symbols):
                symbol = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
                o, c = px[i], new_px[i]
                vol15 = abs(rng.normal(1000, 200))
                if last_tick and i == 3:
                    vol15 *= 8.0
                h, low = max(o, c) * 1.002, min(o, c) * 0.998
                if last_tick and i == 5:
                    # green hammer: deep gap down (clears the 20-bar lower
                    # band even after it shifts), green close, 3x volume
                    o = px[i] * 0.955
                    c = o * 1.003
                    h, low = c * 1.001, o * 0.997
                    new_px[i] = c
                    vol15 *= 3.0
                f.write(bar(symbol, ts15, 900, o, h, low, c, vol15))
                # three 5m sub-bars splitting the 15m move
                sub_o = o
                for j in range(3):
                    frac = (j + 1) / 3
                    sub_c = o + (c - o) * frac
                    vol5 = vol15 / 3
                    sh, sl = max(sub_o, sub_c) * 1.001, min(sub_o, sub_c) * 0.999
                    if last_tick and i == 1:
                        # activity burst on the LAST 5m bar: +3% jump, green
                        # body at highs, 6x volume, after two up sub-bars
                        if j < 2:
                            sub_c = sub_o * 1.003
                            sh, sl = sub_c * 1.001, sub_o * 0.999
                        else:
                            sub_c = sub_o * 1.03
                            sh, sl = sub_c * 1.002, sub_o * 0.998
                            vol5 *= 8.0
                        new_px[i] = sub_c
                    f.write(bar(symbol, ts15 + j * 300, 300, sub_o, sh, sl, sub_c, vol5))
                    sub_o = sub_c
            px = new_px
