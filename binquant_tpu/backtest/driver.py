"""Host driver for the time-batched backtest backend.

Mirrors the scanned drive's shape (``SignalEngine.process_ticks_scanned``):
runs of clean-append ticks accumulate into a plan, each tick's host inputs
captured with the serial drive's exact ordering via the SAME
``_plan_scan_tick`` planner; ineligible ticks (cold-start churn, rewrites,
mesh) flush the plan and re-enter the serial per-tick path, which — on the
full-recompute engines this backend requires — evaluates identically to a
never-batched drive. A chunk whose fired count overflows the wire's
compaction slots is re-driven serially from the plan-start snapshot
(``_redrive_serial``), so the emitted signal set stays exact.

What differs from the scanned drive: instead of stacked update slots
feeding a serial ``lax.scan`` of the carried tick body, the planner lays
the chunk's appends out as an ``(S, W+N)`` extended buffer + per-tick
cumulative bar counts, and dispatches ``backtest_chunk`` — the
time-vectorized FULL-recompute kernel. Post-chunk, the engine's ring
buffers are rebuilt host-side from the extension's final window (bit-equal
to serially applied appends) and the scan's regime/dedupe carries are
committed, so serial ticks can interleave freely.

``run_backtest`` is the top-level entry (stub-sinked engine over a JSONL
stream, same contract as ``run_replay``); ``run_param_sweep`` drives the
``vmap``-over-params kernel, scoring a whole parameter grid per dispatch.
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from binquant_tpu.engine.buffer import NUM_FIELDS, MarketBuffer
from binquant_tpu.engine.step import (
    STRATEGY_ORDER,
    WIRE_FIRED_COUNT_OFF,
    WIRE_MAX_FIRED,
    EngineState,
)
from binquant_tpu.backtest.kernel import (
    BACKTEST_STRATEGIES,
    backtest_chunk,
    backtest_chunk_sweep,
)
from binquant_tpu.strategies.params import (
    StrategyParams,
    dynamic_params,
    grid_size,
    make_param_grid,
)


def _build_extension(
    base_times: np.ndarray,
    base_vals: np.ndarray,
    ticks_batches: list[list],
    window: int,
):
    """Lay a chunk's clean appends out past the pre-chunk ring.

    Returns ``(ext_times (S, W+N), ext_vals (S, W+N, F), counts (T, S))``
    where ``counts[t, s]`` is how many bars symbol s has applied through
    tick t — the window-view gather offset. Column ``W + k`` holds symbol
    s's k-th appended bar; a tick-t window ``[counts[t], counts[t]+W)``
    then reproduces the serially-applied ring bit for bit (appends only —
    the drive routes anything else to the serial path)."""
    S = base_times.shape[0]
    T = len(ticks_batches)
    totals = np.zeros(S, np.int64)
    for batches in ticks_batches:
        for rows, _, _ in batches:
            rows = np.asarray(rows)
            ok = (rows >= 0) & (rows < S)
            np.add.at(totals, rows[ok], 1)
    n_ext = int(totals.max()) if S else 0
    ext_t = np.full((S, window + n_ext), -1, np.int32)
    ext_t[:, :window] = base_times
    ext_v = np.full((S, window + n_ext, NUM_FIELDS), np.nan, np.float32)
    ext_v[:, :window] = base_vals
    cnt = np.zeros(S, np.int64)
    counts = np.zeros((T, S), np.int32)
    for t, batches in enumerate(ticks_batches):
        for rows, ts, vals in batches:
            rows = np.asarray(rows, np.int64)
            ok = (rows >= 0) & (rows < S)
            r = rows[ok]
            cols = window + cnt[r]
            ext_t[r, cols] = np.asarray(ts)[ok]
            ext_v[r, cols] = np.asarray(vals, np.float32)[ok]
            cnt[r] += 1
        counts[t] = cnt
    return ext_t, ext_v, counts


def _final_window(
    ext_t: np.ndarray,
    ext_v: np.ndarray,
    start: np.ndarray,
    filled0: np.ndarray,
    window: int,
) -> MarketBuffer:
    """The post-chunk ring: each symbol's last W extension columns —
    exactly what serial shift-appends would have produced."""
    cols = start.astype(np.int64)[:, None] + np.arange(window)
    times = np.take_along_axis(ext_t, cols, axis=1)
    vals = np.take_along_axis(ext_v, cols[:, :, None], axis=1)
    filled = np.minimum(filled0.astype(np.int64) + start, window).astype(
        np.int32
    )
    return MarketBuffer(
        times=jnp.asarray(times), values=jnp.asarray(vals),
        filled=jnp.asarray(filled),
        cursor=jnp.zeros(filled.shape, jnp.int32),  # canonical rebuild
    )


def _stack_inputs(engine, ticks, tb):
    """Stacked (tb, ...) HostInputs + active/momentum vectors — the ONE
    shared stacking on the engine (``_stack_plan_inputs``, also used by
    the scanned flush) so the two multi-tick backends can never drift."""
    return engine._stack_plan_inputs(ticks, tb)


def _pad_counts(counts: np.ndarray, tb: int) -> np.ndarray:
    """Pad the (T, S) cumulative counts to the scan bucket by repeating
    the final row — padded (inactive) ticks gather a valid window and are
    skipped by the scan's cond."""
    T = counts.shape[0]
    if tb == T:
        return counts
    return np.vstack([counts, np.repeat(counts[-1:], tb - T, axis=0)])


async def _flush_backtest_plan(engine, plan, params) -> list:
    """Dispatch one planned chunk through the time-batched kernel, commit
    the post-chunk state, and finalize tick-by-tick through the standard
    decode path. Overflow ⇒ serial re-drive from the plan-start snapshot.

    Trace-span parity with the scanned drive (ISSUE 7 satellite, grown by
    ISSUE 11): one ``backtest_chunk`` span per chunk with
    stack/dispatch/device_wait children plus synthetic plan/finalize root
    spans (ticks/padded/overflow_rerun attrs, ``path=backtest`` root
    attr), so ``tools/trace_report.py`` renders backtest drives exactly
    like scanned ones — phase waterfalls, not one opaque bar."""
    from binquant_tpu.io.pipeline import (
        _PendingTick,
        _pow2_bucket,
        _scan_fallback_unavailable,
    )
    from binquant_tpu.obs.events import get_event_log
    from binquant_tpu.obs.instruments import (
        BACKTEST_CHUNKS,
        BACKTEST_OVERFLOW_RERUNS,
        BACKTEST_TICKS,
        TICKS,
    )
    from binquant_tpu.obs.ledger import LEDGER, abstract_args, lowered_cost
    from binquant_tpu.obs.tracing import NULL_TRACE

    ticks = plan["ticks"]
    if not ticks:
        return []
    if len(ticks) < engine._SCAN_MIN_TICKS or engine.mesh is not None:
        return await engine._redrive_serial(plan)
    fired_all: list = await engine.flush_pending()

    T = len(ticks)
    tb = _pow2_bucket(T)
    W = engine.window

    engine._tick_seq += 1
    trace = engine.tracer.begin_tick(
        engine._tick_seq, tick_ms=ticks[-1].now_ms
    )
    trace.set_attr(path="backtest")
    # chunk-phase dwell (ISSUE 11): same taxonomy and bracketing as the
    # scanned flush — accumulated planning dwell, then live stack/
    # dispatch/device_wait brackets, closed by the finalize loop
    engine.host_phase.begin_chunk("backtest")
    plan_ms = float(plan.get("plan_ms", 0.0))
    engine.host_phase.record("backtest", "plan", plan_ms)
    t_chunk0 = time.perf_counter()
    if plan_ms:
        trace.record_span(
            "plan", t_chunk0 - plan_ms / 1000.0, t_chunk0,
            accumulated=True, ticks=T,
        )
    try:
        with engine.latency.stage("backtest_chunk"), trace.span(
            "backtest_chunk", ticks=T, padded=tb,
        ), trace.activate():
            with trace.span("stack"), engine.host_phase.phase(
                "backtest", "stack"
            ):
                # the host-side extension lays appends past a
                # RIGHT-ALIGNED base: a mid-phase ring cursor (folded
                # updates since the last full tick) canonicalizes here —
                # one gather per chunk, amortized over T ticks
                from binquant_tpu.engine.step import canonicalize_state

                state = canonicalize_state(engine.state)
                base5_t = np.asarray(state.buf5.times)
                base5_v = np.asarray(state.buf5.values)
                base15_t = np.asarray(state.buf15.times)
                base15_v = np.asarray(state.buf15.values)
                ext5_t, ext5_v, counts5 = _build_extension(
                    base5_t, base5_v, [p.batches5 for p in ticks], W
                )
                ext15_t, ext15_v, counts15 = _build_extension(
                    base15_t, base15_v, [p.batches15 for p in ticks], W
                )
                filled0 = (
                    np.asarray(state.buf5.filled),
                    np.asarray(state.buf15.filled),
                )
                inputs_seq, active, momentum_seq = _stack_inputs(
                    engine, ticks, tb
                )
                policy_prev = (
                    np.bool_(engine._last_regime is not None),
                    np.int32(
                        -1 if engine._last_regime is None
                        else engine._last_regime
                    ),
                )
                key = engine._wire_enabled_key()
                # extension-invariant routing (ISSUE 17): the ext kernel's
                # single-bench BTC block assumes ONE btc_row across the
                # chunk, so only the ACTIVE rows count (pad rows carry the
                # -1 defaults and are skipped by the scan's cond) — a
                # mid-chunk registry move of the bench symbol falls back
                # to the vmapped precompute
                btc_rows = np.asarray(inputs_seq.btc_row)[
                    np.asarray(active, bool)
                ]
                ext_invariant = bool(
                    getattr(engine, "ext_invariant", False)
                ) and bool(
                    btc_rows.size == 0 or np.all(btc_rows == btc_rows[0])
                )
                chunk_args = (
                    (ext5_t, ext5_v),
                    (ext15_t, ext15_v),
                    _pad_counts(counts5, tb),
                    _pad_counts(counts15, tb),
                    filled0,
                    (state.regime_carry, state.mrf_last_emitted,
                     state.pt_last_signal_close),
                    inputs_seq,
                    active,
                    momentum_seq,
                    policy_prev,
                )
                chunk_kwargs = dict(
                    wire_enabled=key,
                    window=W,
                    params=None if params is None else dynamic_params(params),
                    numeric_digest=engine.numeric_digest,
                    ingest_digest=engine.ingest_digest,
                    ext_invariant=ext_invariant,
                )
                ledger_sig = (
                    f"S{engine.capacity}xW{W} T{tb}"
                    f" ext5[{ext5_t.shape[1] - W}]"
                    f" ext15[{ext15_t.shape[1] - W}]"
                    f" digest={int(engine.numeric_digest)}"
                    + (" ingest=1" if engine.ingest_digest else "")
                    + (" ext=1" if ext_invariant else "")
                )

                def cost_fn(
                    args=chunk_args, kwargs=chunk_kwargs,
                    cfg=engine.context_config,
                ):
                    # abstract-ify lazily: this thunk is only consumed
                    # when the watch actually observed a compile — the
                    # steady-state chunk loop must not pay a per-chunk
                    # tree_map over the extended buffers
                    a_args, a_kwargs = abstract_args(args, kwargs)
                    return lowered_cost(
                        backtest_chunk, *a_args, cfg, **a_kwargs
                    )

            t_launch0 = time.perf_counter()
            with trace.span("dispatch"), engine.host_phase.phase(
                "backtest", "dispatch"
            ):
                # newness is detected by the ledger's compile monitoring
                # (the kernel's jit cache keys on shapes the drive doesn't
                # mirror host-side the way observe_dispatch does for the
                # tick steps)
                with LEDGER.watch(
                    "backtest_chunk", ledger_sig, expect_compile=False,
                    cost_fn=cost_fn, tick=engine.ticks_processed,
                ):
                    carries, _policy, wires_dev, _fired, _counts = (
                        backtest_chunk(
                            *chunk_args, engine.context_config,
                            **chunk_kwargs
                        )
                    )
            with trace.span("device_wait"), engine.host_phase.phase(
                "backtest", "device_wait"
            ):
                wires = np.asarray(wires_dev)
    except BaseException as exc:
        trace.mark_error(exc)
        engine.tracer.complete(trace, snapshot_fn=engine._flight_snapshot)
        raise
    # chunk-level dispatch→wire-fetch freshness, measured from the LAUNCH
    # (stack packing excluded — comparable with the serial drive's stamp;
    # per-tick finalizes below read an already-landed host array)
    engine.freshness.observe_stage(
        "dispatch_to_fetch", (time.perf_counter() - t_launch0) * 1000.0
    )
    if np.any(wires[:T, WIRE_FIRED_COUNT_OFF] > WIRE_MAX_FIRED):
        # a tick's fired set overflowed the wire's compaction slots: drop
        # the chunk's outputs (engine.state never advanced) and re-drive
        # serially through the audited per-tick overflow fallback
        trace.set_attr(overflow_rerun=True)
        engine.tracer.complete(trace, snapshot_fn=engine._flight_snapshot)
        # close the discarded chunk's occupancy accounting (the host
        # really spent this wall; an open chunk must not linger)
        engine.host_phase.note_chunk(
            "backtest",
            plan_ms + (time.perf_counter() - t_chunk0) * 1000.0,
            T,
        )
        engine.backtest_overflow_reruns += 1
        BACKTEST_OVERFLOW_RERUNS.inc()
        fired_all.extend(await engine._redrive_serial(plan))
        return fired_all

    regime_carry, mrf_carry, pt_carry = carries
    engine.state = EngineState(
        buf5=_final_window(ext5_t, ext5_v, counts5[-1], filled0[0], W),
        buf15=_final_window(ext15_t, ext15_v, counts15[-1], filled0[1], W),
        regime_carry=regime_carry,
        mrf_last_emitted=mrf_carry,
        pt_last_signal_close=pt_carry,
        # full-recompute backend: the indicator carry is never consumed
        # (the drive requires BQT_INCREMENTAL=0) — passed through untouched
        indicator_carry=state.indicator_carry,
    )
    engine.backtest_chunks += 1
    BACKTEST_CHUNKS.inc()

    # batch decode (ISSUE 17): one vectorized pass over the landed (T, L)
    # wire block replaces T per-tick unpack_wire re-slices — finalize
    # consumes the pre-decoded (WireFired, ctx) tuples
    from binquant_tpu.engine.step import unpack_wire_block

    t_dec0 = time.perf_counter()
    seq = unpack_wire_block(
        wires[:T], numeric_digest=engine.numeric_digest,
        ingest_digest=engine.ingest_digest,
    )
    engine.host_phase.record(
        "backtest", "decode", (time.perf_counter() - t_dec0) * 1000.0
    )

    per_tick_ms = (time.perf_counter() - t_chunk0) * 1000.0 / T
    t_fin0 = time.perf_counter()
    try:
        for i, p in enumerate(ticks):
            engine.market_breadth = p.breadth
            pending = _PendingTick(
                wire=wires[i],
                fallback=_scan_fallback_unavailable,
                ts_ms=p.now_ms,
                ts5=p.ts5,
                ts15=p.ts15,
                bucket15=p.bucket15,
                dispatched_at=t_chunk0,
                rows=p.rows,
                trace=NULL_TRACE,
                drive="backtest",
                ingest_mono=p.ingest_mono,
                unpacked=seq[i],
            )
            fired_all.extend(await engine._finalize_tick(pending))
            engine.latency.record("tick_total", per_tick_ms)
            engine.ticks_processed += 1
            engine._last_tick_wall_s = time.time()
            TICKS.inc()
            get_event_log().tick = engine.ticks_processed
            engine.backtest_ticks += 1
            BACKTEST_TICKS.inc()
    finally:
        # chunk trace closes AFTER its finalizes (waterfall shows the
        # decode/emit half; an errored finalize still flight-records)
        trace.record_span("finalize", t_fin0, ticks=T)
        engine.tracer.complete(trace, snapshot_fn=engine._flight_snapshot)
        engine.host_phase.note_chunk(
            "backtest",
            plan_ms + (time.perf_counter() - t_chunk0) * 1000.0,
            T,
        )
    engine.touch_heartbeat()
    return fired_all


def _check_supported(enabled, window: int | None = None) -> None:
    unsupported = set(enabled) - BACKTEST_STRATEGIES
    if unsupported:
        raise ValueError(
            f"backtest backend cannot evaluate {sorted(unsupported)}; "
            f"supported: {sorted(BACKTEST_STRATEGIES)} (use the serial "
            "replay drives for buffer-consuming dormant strategies)"
        )
    from binquant_tpu.strategies.activity_burst_pump import (
        ABP_EXT_MIN_WINDOW,
    )

    if (
        window is not None
        and "activity_burst_pump" in enabled
        and window < ABP_EXT_MIN_WINDOW
    ):
        raise ValueError(
            f"window {window} too short for the backtest backend's "
            f"extended-series ABP core (need >= {ABP_EXT_MIN_WINDOW}); "
            "grow the window or disable activity_burst_pump"
        )


async def drive_ticks_backtest(engine, ticks, params=None, chunk=None) -> list:
    """Drive a replayed tick sequence through the time-batched backend.

    Same contract as ``process_ticks_scanned``: ``ticks`` iterates
    ``(now_ms, feed)`` pairs, every emitted signal is returned in tick
    order stamped with its producing tick. Requires a FULL-recompute
    engine (``incremental=False``) — this backend evaluates full-path
    semantics and commits chunk state the carried fast path could not
    resync from."""
    from binquant_tpu.io.pipeline import FIFTEEN_MIN_S

    if engine.incremental:
        raise ValueError(
            "the backtest backend requires a full-recompute engine — "
            "construct it with incremental=False (BQT_INCREMENTAL=0)"
        )
    _check_supported(engine._wire_enabled_key(), engine.window)
    chunk = int(chunk or engine.backtest_chunk)
    # Serial re-entries (cold start, rewrites, overflow re-drives) go
    # through process_tick — install the params on the engine for the
    # DURATION of this drive so those ticks evaluate with the SAME
    # thresholds as the batched chunks, then restore: a later drive (or a
    # resumed live loop) at defaults must not inherit a stale override.
    prev_params = engine.strategy_params
    if params is not None:
        engine.strategy_params = params
    try:
        fired_all: list = []
        fired_all.extend(await engine.flush_pending())
        plan: dict | None = None
        for now_ms, feed in ticks:
            t_plan0 = time.perf_counter()
            if callable(feed):
                feed()
            else:
                for k in feed:
                    engine.ingest(k)
            version0 = engine.registry.version
            ingest_mono = engine._oldest_pending_mono()
            batches5 = engine.batcher5.drain()
            batches15 = engine.batcher15.drain()
            churn = engine.registry.version != version0
            clean = engine._note_applied(batches5, batches15, commit=False)
            eligible = clean and not churn and engine.mesh is None
            if not eligible:
                if plan is not None:
                    fired_all.extend(
                        await _flush_backtest_plan(engine, plan, params)
                    )
                    plan = None
                engine._requeue_batches(batches5, batches15)
                fired_all.extend(await engine.process_tick(now_ms=now_ms))
                continue
            if plan is None:
                plan = engine._begin_scan_plan()
            engine._note_applied(batches5, batches15)
            momentum_ok = engine._grid_momentum_ok()
            bucket15 = (now_ms // 1000) // FIFTEEN_MIN_S
            await engine._refresh_market_breadth(bucket15)
            plan["ticks"].append(
                engine._plan_scan_tick(
                    now_ms, batches5, batches15, momentum_ok,
                    ingest_mono=ingest_mono,
                )
            )
            plan["plan_ms"] += (time.perf_counter() - t_plan0) * 1000.0
            if len(plan["ticks"]) >= chunk:
                fired_all.extend(
                    await _flush_backtest_plan(engine, plan, params)
                )
                plan = None
        if plan is not None:
            fired_all.extend(await _flush_backtest_plan(engine, plan, params))
        return fired_all
    finally:
        if params is not None:
            engine.strategy_params = prev_params


def run_backtest(
    path: str | Path,
    capacity: int = 256,
    window: int = 200,
    collect: list | None = None,
    breadth: dict | None = None,
    enabled_strategies: set | None = None,
    dominance_is_losers: bool = False,
    market_domination_reversal: bool = False,
    context_config=None,
    params: StrategyParams | None = None,
    chunk: int | None = None,
    outcomes: bool | None = None,
    outcome_horizons: tuple[int, ...] | None = None,
    collect_outcomes: list | None = None,
    ext_invariant: bool | None = None,
) -> dict:
    """Backtest a JSONL kline stream through the time-batched backend.

    The ``run_replay`` twin for the backtest subsystem: stubbed sinks, one
    engine tick per 15m bucket, fired signals appended to ``collect`` as
    ``(tick_ms, strategy, symbol, direction, autotrade)`` tuples. At
    default ``params`` the emitted signal set is EXACTLY the serial
    full-recompute drive's (``run_replay(incremental=False)``) — pinned by
    tests/test_backtest.py."""
    from binquant_tpu.io.replay import load_klines_by_tick, make_stub_engine

    if enabled_strategies is not None:
        _check_supported(
            frozenset(enabled_strategies) or frozenset()
        )
    engine = make_stub_engine(
        capacity=capacity,
        window=window,
        breadth=breadth,
        pipeline_depth=0,
        enabled_strategies=enabled_strategies,
        context_config=context_config,
        incremental=False,
        donate=False,
        outcomes=outcomes,
        outcome_horizons=outcome_horizons,
        ext_invariant=ext_invariant,
        # inline sinks: the backtest lane pins sink-visible effects
        # synchronously; the delivery + fan-out planes have their own
        # lanes
        delivery=False,
        fanout=False,
    )
    engine.at_consumer.market_domination_reversal = market_domination_reversal
    engine.at_consumer.current_market_dominance_is_losers = dominance_is_losers
    klines_by_tick = load_klines_by_tick(path)
    candles = sum(len(v) for v in klines_by_tick.values())
    seq = [
        (
            (bucket + 1) * 900 * 1000,
            sorted(klines_by_tick[bucket], key=lambda k: k["open_time"]),
        )
        for bucket in sorted(klines_by_tick)
    ]

    fired_total = 0

    def record(fired) -> None:
        nonlocal fired_total
        fired_total += len(fired)
        if collect is not None:
            for s in fired:
                collect.append(
                    (
                        s.tick_ms,
                        s.strategy,
                        s.symbol,
                        str(s.value.direction),
                        bool(s.value.autotrade),
                    )
                )

    async def drive() -> None:
        record(
            await drive_ticks_backtest(engine, seq, params=params, chunk=chunk)
        )
        record(await engine.flush_pending())

    t_start = time.perf_counter()
    asyncio.run(drive())
    wall = time.perf_counter() - t_start
    if engine.outcomes.enabled and collect_outcomes is not None:
        collect_outcomes.extend(sorted(engine.outcomes.matured_set()))
    return {
        **(
            {"outcomes": engine.outcomes.scoreboard()}
            if engine.outcomes.enabled
            else {}
        ),
        "ticks": engine.ticks_processed,
        "backtest_ticks": engine.backtest_ticks,
        "backtest_chunks": engine.backtest_chunks,
        "backtest_overflow_reruns": engine.backtest_overflow_reruns,
        "serial_ticks": engine.ticks_processed - engine.backtest_ticks,
        "signals": fired_total,
        "candles": candles,
        "wall_s": round(wall, 3),
        "candles_per_sec": round(candles / wall, 1) if wall > 0 else None,
    }


class _SweepOutcomeScorer:
    """Economic scoring bed for :func:`run_param_sweep` (ISSUE 12).

    The sweep shares ONE price stream across every combo, so a fired
    signal's outcome depends only on its (symbol row, entry bar) pair and
    the horizon — not on which combo fired it. The scorer therefore
    matures each UNIQUE (row, entry_ts) pair once through the SAME jit'd
    gather the live tracker uses (``obs.outcomes.outcome_gather``, fed
    the sweep's committed host rings) and attributes the raw result to
    every (combo, strategy, direction) reference, signed per direction —
    P combos cost one gather, not P.

    Maturation runs at each chunk flush against the post-commit rings:
    the gather is timestamp-bounded, so gathering later than the due tick
    changes nothing as long as the ring still holds the window (clipped
    windows are detected via the row's oldest retained bar and counted as
    truncated, exactly like the live tracker).
    """

    def __init__(self, P: int, horizons: tuple[int, ...]) -> None:
        from binquant_tpu.obs.outcomes import _Agg

        self.P = int(P)
        self.horizons = tuple(
            sorted({int(h) for h in (horizons or ()) if int(h) > 0})
        )
        self._agg_cls = _Agg
        self._pair_ids: dict[tuple[int, int], int] = {}
        self._pairs: list[dict] = []  # {row, entry_ts, pending}
        self._refs: list[list[tuple[int, int, int]]] = []  # (p, si, sign)
        # per combo: (si, horizon) -> the SAME scoreboard cell the live
        # tracker keeps (obs.outcomes._Agg) — one fold, one rounding
        self.agg: list[dict[tuple[int, int], object]] = [
            {} for _ in range(self.P)
        ]
        self.matured_pairs = 0
        self.truncated = 0
        # fired slots beyond the wire's compaction width on a burst tick
        # (the live drives re-drive such ticks serially; the sweep has no
        # serial path, so the tail is DROPPED from scoring — counted
        # here, never silently)
        self.overflow_dropped = 0

    @property
    def enabled(self) -> bool:
        """No positive horizons = scoring off (the bench's throughput
        arms and a `--horizons 0` opt-out both land here cleanly)."""
        return bool(self.horizons)

    def register_chunk(self, slots, nfired, tick_ts5: list[int]) -> None:
        """One flushed chunk's fired compactions: ``slots`` (P, T, 3, K)
        rows (strategy_idx, row, direction), ``nfired`` (P, T)."""
        from binquant_tpu.obs.outcomes import direction_sign

        if not self.enabled:
            return
        slots = np.asarray(slots)
        nfired = np.asarray(nfired)
        K = slots.shape[-1]
        for t, ts5 in enumerate(tick_ts5):
            for p in range(self.P):
                n = int(nfired[p, t])
                k = min(n, K)
                if n > K:
                    # >WIRE_MAX_FIRED burst: the compaction kept the
                    # first K pairs — score those, count the dropped tail
                    self.overflow_dropped += n - K
                if k <= 0:
                    continue
                si = slots[p, t, 0, :k].astype(np.int64)
                row = slots[p, t, 1, :k].astype(np.int64)
                dirn = slots[p, t, 2, :k].astype(np.int64)
                ok = row >= 0
                for s, r, d in zip(si[ok], row[ok], dirn[ok]):
                    key = (int(r), int(ts5))
                    pid = self._pair_ids.get(key)
                    if pid is None:
                        pid = len(self._pairs)
                        self._pair_ids[key] = pid
                        self._pairs.append(
                            {
                                "row": int(r),
                                "entry_ts": int(ts5),
                                "pending": list(self.horizons),
                            }
                        )
                        self._refs.append([])
                    self._refs[pid].append(
                        (p, int(s), direction_sign(int(d)))
                    )

    def mature(self, times5, vals5, now_ts5: int) -> None:
        """Mature every due (pair, horizon) against the committed rings."""
        from binquant_tpu.obs.outcomes import (
            FIVE_MIN_S,
            _pow2,
            outcome_gather,
            signed_outcome,
        )

        if not self.enabled:
            return
        due: list[tuple[int, int]] = []
        for pid, pair in enumerate(self._pairs):
            for h in pair["pending"]:
                if pair["entry_ts"] + h * FIVE_MIN_S <= now_ts5:
                    due.append((pid, h))
        if not due:
            return
        K = _pow2(len(due))
        rows = np.full(K, -1, np.int32)
        entry = np.zeros(K, np.int32)
        horizon = np.zeros(K, np.int32)
        for i, (pid, h) in enumerate(due):
            rows[i] = self._pairs[pid]["row"]
            entry[i] = self._pairs[pid]["entry_ts"]
            horizon[i] = entry[i] + h * FIVE_MIN_S
        floats, ints = outcome_gather(times5, vals5, rows, entry, horizon)
        for i, (pid, h) in enumerate(due):
            pair = self._pairs[pid]
            pair["pending"].remove(h)
            self.matured_pairs += 1
            clipped = int(ints[1, i]) > pair["entry_ts"]
            # unusable raw gather (empty window / NaN entry) counts as
            # truncated too — the live tracker's exact accounting
            # (OutcomeTracker.on_tick: ``outcome is None or clipped``);
            # raw usability is direction-independent, so judge it once
            # per pair, not per combo reference
            usable = signed_outcome(
                1, float(floats[0, i]), float(floats[1, i]),
                float(floats[2, i]), float(floats[3, i]),
            )
            if clipped or usable is None:
                self.truncated += 1
                continue
            for p, si, sign in self._refs[pid]:
                fwd, mae, mfe = signed_outcome(
                    sign, float(floats[0, i]), float(floats[1, i]),
                    float(floats[2, i]), float(floats[3, i]),
                )
                cell = self.agg[p].get((si, h))
                if cell is None:
                    cell = self.agg[p][(si, h)] = self._agg_cls()
                cell.add(fwd, mae, mfe)

    def result(self, score_horizon: int | None = None) -> dict:
        """The sweep result's ``outcomes`` section: per-combo per-strategy
        scoreboards (the live tracker's exact cell shape — one fold, one
        rounding) plus one scalar score row per combo at the scoring
        horizon (the largest horizon that matured anything, unless
        pinned) — the ROADMAP-4 economic proxy the ranking reads."""
        if not self.enabled:
            return {"enabled": False}
        matured_h = {
            h for by in self.agg for (_, h) in by
        }
        if score_horizon is None:
            score_horizon = max(matured_h) if matured_h else max(self.horizons)
        per_combo = []
        combo_score = []
        for p in range(self.P):
            by_strategy: dict[str, dict[str, dict]] = {}
            for (si, h), cell in sorted(self.agg[p].items()):
                by_strategy.setdefault(STRATEGY_ORDER[si], {})[str(h)] = (
                    cell.as_dict()
                )
            per_combo.append(by_strategy)
            n = hits = 0
            sum_fwd = sum_mae = 0.0
            for (si, h), cell in self.agg[p].items():
                if h != score_horizon:
                    continue
                n += cell.n
                hits += cell.hits
                sum_fwd += cell.sum_fwd
                sum_mae += cell.sum_mae
            combo_score.append(
                {
                    "n": n,
                    "hit_rate": round(hits / n, 4) if n else None,
                    "avg_fwd": round(sum_fwd / n, 6) if n else None,
                    "sum_fwd": round(sum_fwd, 6),
                    "avg_mae": round(sum_mae / n, 6) if n else None,
                }
            )
        ranking = sorted(
            range(self.P),
            key=lambda p: (-combo_score[p]["sum_fwd"], p),
        )
        unmatured = sum(len(pair["pending"]) for pair in self._pairs)
        return {
            "enabled": True,
            "horizons": list(self.horizons),
            "score_horizon": int(score_horizon),
            "per_combo": per_combo,
            "combo_score": combo_score,
            "ranking_by_return": [int(p) for p in ranking],
            "matured_pairs": self.matured_pairs,
            "truncated": self.truncated,
            "unmatured_pair_horizons": int(unmatured),
            # burst-tick slots the wire compaction could not carry — a
            # nonzero value means the ranking was computed on a capped
            # subset of those ticks' signals (re-run with fewer symbols
            # or a narrower enabled set for full fidelity)
            "overflow_dropped_slots": self.overflow_dropped,
        }


def _apply_host_updates(times, vals, filled, batches, window):
    """apply_updates semantics on host numpy rings (the sweep's state):
    strictly-newer append → shift-append; matching-timestamp bar →
    overwrite in place; stale no-match → dropped."""
    for rows, ts, v in batches:
        rows = np.asarray(rows)
        for i, row in enumerate(np.asarray(rows, np.int64)):
            if not 0 <= row < times.shape[0]:
                continue
            t_i = int(np.asarray(ts)[i])
            if filled[row] == 0 or t_i > times[row, -1]:
                times[row, :-1] = times[row, 1:]
                times[row, -1] = t_i
                vals[row, :-1] = vals[row, 1:]
                vals[row, -1] = np.asarray(v, np.float32)[i]
                filled[row] = min(filled[row] + 1, window)
            else:
                match = np.nonzero(times[row] == t_i)[0]
                if len(match):
                    vals[row, match[0]] = np.asarray(v, np.float32)[i]


def _auto_sweep_chunk(
    base_chunk: int, P: int, capacity: int, budget_mb: int
) -> int:
    """Derive the sweep's per-dispatch chunk from a device-memory budget.

    The sweep's dominant batched allocation is the outcome scorer's
    quantile windows — P combos x S rows x ~80 window floats per chunk
    tick (the PR 6 NOTE's P x S x n_out x 80 term, f32). A huge grid at
    the configured ``backtest_chunk`` wedges on that product, so instead
    of requiring callers to hand-tune ``chunk=`` per grid size, drop the
    chunk until the product fits ``budget_mb`` (BQT_SWEEP_MEM_BUDGET_MB,
    default 1024). Small grids are untouched: the budget divides out to
    far more ticks than the configured chunk."""
    per_tick_bytes = max(1, P * capacity * 80 * 4)
    fit = int((int(budget_mb) << 20) // per_tick_bytes)
    return max(1, min(int(base_chunk), fit))


def run_param_sweep(
    path: str | Path,
    axes: dict,
    capacity: int = 64,
    window: int = 200,
    breadth: dict | None = None,
    enabled_strategies: set | None = None,
    context_config=None,
    chunk: int | None = None,
    base_params: StrategyParams | None = None,
    horizons: tuple[int, ...] | None = (1, 4, 16, 96),
    score_horizon: int | None = None,
) -> dict:
    """Score a strategy-parameter grid over a kline stream: ONE vmapped
    dispatch per chunk evaluates every combo (``backtest_chunk_sweep``).

    The per-combo scan carries (regime state, dedupe cooldowns, grid
    policy) are ``(P,)``-batched across chunks, so combos evolve
    independent histories; buffers and features are shared (no batch dim).
    Non-append ticks (rewrites) flush the chunk, apply host-side, and keep
    sweeping — there is no serial path here (nothing to emit; the sweep
    SCORES, it does not emit signals). Returns per-combo per-strategy
    trigger/autotrade counts PLUS the economic proxies ROADMAP item 4
    asked for (ISSUE 12): each combo's fired signals mature through the
    same outcome kernel the live tracker uses (forward return / MAE /
    MFE / hit-rate at ``horizons`` 5m bars, deduped across combos via the
    shared price stream), and ``outcomes.ranking_by_return`` ranks combos
    by total signed forward return at ``score_horizon`` instead of raw
    fire counts. ``tools/sweep_report.py`` renders both. ``horizons``
    with no positive entries (or None) disables scoring entirely — the
    kernel then skips the fired-slot slice and the sweep measures the
    pre-scoring throughput graph (the bench arms pass None)."""
    from binquant_tpu.io.pipeline import FIFTEEN_MIN_S
    from binquant_tpu.io.replay import load_klines_by_tick, make_stub_engine
    from binquant_tpu.regime.context import initial_regime_carry

    grid, combos = make_param_grid(axes, base_params)
    P = max(grid_size(grid), 1)
    engine = make_stub_engine(
        capacity=capacity,
        window=window,
        breadth=breadth,
        pipeline_depth=0,
        enabled_strategies=enabled_strategies,
        context_config=context_config,
        incremental=False,
        donate=False,
        delivery=False,
        fanout=False,
    )
    key = engine._wire_enabled_key()
    _check_supported(key, window)
    if not chunk:
        # huge grids: derive the chunk from the memory budget instead of
        # wedging at the configured backtest_chunk (ISSUE 17 satellite)
        from binquant_tpu.config import Config

        chunk = _auto_sweep_chunk(
            engine.backtest_chunk, P, capacity,
            int(getattr(Config(), "sweep_mem_budget_mb", 1024) or 1024),
        )
    chunk = int(chunk)
    S, W = capacity, window

    # host ring state shared by every combo (params never touch buffers)
    times5 = np.full((S, W), -1, np.int32)
    vals5 = np.full((S, W, NUM_FIELDS), np.nan, np.float32)
    filled5 = np.zeros(S, np.int64)
    times15 = np.full((S, W), -1, np.int32)
    vals15 = np.full((S, W, NUM_FIELDS), np.nan, np.float32)
    filled15 = np.zeros(S, np.int64)

    # per-combo sequential carries, (P,)-batched leaves
    def tile(leaf):
        return jnp.broadcast_to(leaf, (P,) + leaf.shape)

    carriesP = jax.tree_util.tree_map(
        tile,
        (
            initial_regime_carry(S),
            jnp.full((S,), -1, jnp.int32),
            jnp.full((S,), -1, jnp.int32),
        ),
    )
    policyP = (np.zeros(P, np.bool_), np.full(P, -1, np.int32))

    n_strat = len(STRATEGY_ORDER)
    trig_totals = np.zeros((P, n_strat), np.int64)
    at_totals = np.zeros((P, n_strat), np.int64)
    evaluated = 0
    dispatches = 0
    candles = 0
    scorer = _SweepOutcomeScorer(P, horizons)

    klines_by_tick = load_klines_by_tick(path)
    seq = [
        (
            (bucket + 1) * 900 * 1000,
            sorted(klines_by_tick[bucket], key=lambda k: k["open_time"]),
        )
        for bucket in sorted(klines_by_tick)
    ]

    plan: list = []  # (scan_tick_plan, append_batches5, append_batches15)

    def flush() -> None:
        nonlocal carriesP, policyP, evaluated, dispatches
        nonlocal times5, vals5, filled5, times15, vals15, filled15
        nonlocal trig_totals, at_totals
        if not plan:
            return
        from binquant_tpu.io.pipeline import _pow2_bucket

        ticks = [p for p, _, _ in plan]
        T = len(ticks)
        tb = _pow2_bucket(T)
        ext5_t, ext5_v, counts5 = _build_extension(
            times5, vals5, [b5 for _, b5, _ in plan], W
        )
        ext15_t, ext15_v, counts15 = _build_extension(
            times15, vals15, [b15 for _, _, b15 in plan], W
        )
        inputs_seq, active, momentum_seq = _stack_inputs(engine, ticks, tb)
        (carriesP, policyP, _fired, tc, ac, fired_slots) = backtest_chunk_sweep(
            (ext5_t, ext5_v),
            (ext15_t, ext15_v),
            _pad_counts(counts5, tb),
            _pad_counts(counts15, tb),
            (filled5.astype(np.int32), filled15.astype(np.int32)),
            carriesP,
            inputs_seq,
            active,
            momentum_seq,
            policyP,
            engine.context_config,
            wire_enabled=key,
            window=W,
            params=dynamic_params(grid),
            # scoring off (no positive horizons — the bench's throughput
            # arms) restores the pre-scoring graph: the fired-slot slice
            # is never computed and the wire tail stays DCE'd
            with_fired_slots=scorer.enabled,
        )
        trig_totals += np.asarray(tc)[:, :T].sum(axis=1)
        at_totals += np.asarray(ac)[:, :T].sum(axis=1)
        evaluated += T
        dispatches += 1
        # outcome scoring (ISSUE 12): register every combo's fired slots
        # against their entry anchors BEFORE committing the rings...
        if scorer.enabled:
            scorer.register_chunk(
                np.asarray(fired_slots)[:, :T],
                np.asarray(_fired)[:, :T],
                [p.ts5 for p in ticks],
            )
        # commit the post-chunk rings
        buf5 = _final_window(ext5_t, ext5_v, counts5[-1], filled5, W)
        buf15 = _final_window(ext15_t, ext15_v, counts15[-1], filled15, W)
        times5, vals5 = np.asarray(buf5.times), np.asarray(buf5.values)
        filled5 = np.asarray(buf5.filled).astype(np.int64)
        times15, vals15 = np.asarray(buf15.times), np.asarray(buf15.values)
        filled15 = np.asarray(buf15.filled).astype(np.int64)
        # ...then mature everything due through the chunk's last evaluated
        # bar against the committed 5m ring (timestamp-bounded gather)
        scorer.mature(times5, vals5, ticks[-1].ts5)
        plan.clear()

    t_start = time.perf_counter()
    for now_ms, klines in seq:
        for k in klines:
            engine.ingest(k)
        candles += len(klines)
        batches5 = engine.batcher5.drain()
        batches15 = engine.batcher15.drain()
        clean = engine._note_applied(batches5, batches15)
        momentum_ok = engine._grid_momentum_ok()
        bucket15 = (now_ms // 1000) // FIFTEEN_MIN_S
        asyncio.run(engine._refresh_market_breadth(bucket15))
        tick_plan = engine._plan_scan_tick(
            now_ms, batches5, batches15, momentum_ok
        )
        if not clean:
            # rewrite/out-of-order: flush, apply with overwrite semantics,
            # then evaluate this tick against the corrected rings (its
            # appends — if any — ride the extension as usual only when
            # clean; here everything lands host-side, zero appends)
            flush()
            _apply_host_updates(times5, vals5, filled5, batches5, W)
            _apply_host_updates(times15, vals15, filled15, batches15, W)
            plan.append((tick_plan, [], []))
        else:
            plan.append((tick_plan, batches5, batches15))
        if len(plan) >= chunk:
            flush()
    flush()
    wall = time.perf_counter() - t_start

    order = np.argsort(-trig_totals.sum(axis=1), kind="stable")
    return {
        "P": P,
        "outcomes": scorer.result(score_horizon=score_horizon),
        "combos": combos,
        "axes": {k: [float(v) for v in vs] for k, vs in axes.items()},
        "strategies": list(STRATEGY_ORDER),
        "trig_counts": trig_totals.tolist(),
        "autotrade_counts": at_totals.tolist(),
        "total_fired": trig_totals.sum(axis=1).tolist(),
        "ranking": [int(i) for i in order],
        "evaluated_ticks": evaluated,
        "dispatches": dispatches,
        "candles": candles,
        "wall_s": round(wall, 3),
        "combo_candles_per_sec": (
            round(P * candles / wall, 1) if wall > 0 else None
        ),
    }
