"""LiquidationSweepPump — 15m pump detector with breadth-fade routing.

Re-implements ``/root/reference/strategies/liquidation_sweep_pump.py``:
pump score = rel_volume · (1+momentum) · OI-growth / range-fraction, 2-bar
smoothed (l.110-145); trigger when max(smooth, raw) clears the 80th
percentile of the last 48 smoothed scores (l.163-181); optional open-interest
confirmation ≥1.02 (l.183-185); direction from breadth-fade routing — hot
ADP fading + BTC stalled + weak symbol → short, washed-out ADP recovering +
BTC up → long (l.76-108). ADP (advancers-decliners pressure) comes from the
REST breadth series when available, else from the context's
advancers−decliners ratio (l.56-63) — the host passes the resolved pair.

Two evaluation paths share the routing/gating block: the full-tail kernel
(:func:`liquidation_sweep_pump`) and the carry twins
(:func:`lsp_init_from_window` / :func:`lsp_advance_one_bar` /
:func:`liquidation_sweep_pump_from_carry`). The carry tracks the 48-bar
sorted window of the UNSCALED smoothed score (OI growth is a per-row
positive scalar multiplying the whole series uniformly, so the quantile
scales linearly and the factor is applied at readout — exact when the OI
factor is 1.0, the no-futures/replay case); entering values come from ~20
(S,) column reads per bar instead of the full-tail rolling pipeline.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, MarketBuffer
from binquant_tpu.enums import Direction, MicroRegimeCode
from binquant_tpu.ops.incremental import (
    SortedCarry,
    sorted_advance,
    sorted_init,
    sorted_quantile,
)
from binquant_tpu.ops.rolling import rolling_mean, rolling_max, rolling_min, shift
from binquant_tpu.regime.context import MarketContext
from binquant_tpu.strategies.base import StrategyOutputs

# Route codes (breadth_fade_routing, l.76-108)
ROUTE_SHORT = 0  # "breadth_hot_fading_btc_stalled_symbol_weak"
ROUTE_LONG = 1  # "breadth_washed_out_recovering_btc_up"
ROUTE_NO_CONTEXT = 2
ROUTE_STRESS = 3
ROUTE_HOT_NOT_FALLING = 4
ROUTE_BTC_NOT_STALLED = 5
ROUTE_NO_SYMBOL_FEATURES = 6
ROUTE_FOLLOWTHROUGH_NOT_WEAK = 7
ROUTE_WASHED_NOT_INCREASING = 8
ROUTE_BTC_NOT_INCREASING = 9
ROUTE_ADP_NOT_EXTREME = 10


class LSPParams(NamedTuple):
    """Class constants (l.22-25) + windows (l.110-145, 163-180)."""

    short_adp_threshold: float = 0.3
    long_adp_threshold: float = -0.4
    btc_stalled_momentum_abs: float = 0.002
    window_hours: int = 3  # 15m bars per unit (reference window_hours)
    score_window: int = 48
    score_quantile: float = 0.80
    min_oi_growth: float = 1.02
    # routing's market-stress veto (l.92; was a literal in _routing)
    max_stress: float = 0.35


# score series needs rel_volume back score_window+1 bars, each needing
# volume 9 bars back -> 64 covers 49+9 with margin.
TAIL = 64

# The deepest column the one-bar advance reads: the shifted volume-mean
# window's oldest sample at -(3*window_hours).
LSP_MIN_WINDOW = 3 * LSPParams().window_hours + 1
# The init's deeper need: the sorted score window keeps score_window
# trailing smooth scores (lsp_init_from_window's shape-pinning assert).
LSP_INIT_MIN_WINDOW = LSPParams().score_window


def _routing(
    context: MarketContext,
    adp_latest: jnp.ndarray,
    adp_prev: jnp.ndarray,
    btc_momentum: jnp.ndarray,
    p: LSPParams,
):
    """Breadth-fade routing (l.76-108) — one copy shared by both paths.
    Returns (routed, short_ok, route, has_context)."""
    feats = context.features
    has_context = context.valid
    stress_ok = context.market_stress_score < p.max_stress
    has_breadth_pair = jnp.isfinite(adp_prev)
    falling = has_breadth_pair & (adp_latest < adp_prev)
    increasing = has_breadth_pair & (adp_latest > adp_prev)
    btc_stalled = jnp.abs(btc_momentum) <= p.btc_stalled_momentum_abs

    weak_followthrough = (feats.relative_strength_vs_btc <= 0) & (
        (feats.trend_score <= 0)
        | ~feats.above_ema20
        | (feats.micro_regime != MicroRegimeCode.TREND_UP)
    )

    hot = adp_latest > p.short_adp_threshold
    washed = adp_latest <= p.long_adp_threshold

    short_ok = hot & falling & btc_stalled & feats.valid & weak_followthrough
    long_ok = washed & increasing & (btc_momentum > 0)

    route = jnp.where(
        ~has_context,
        ROUTE_NO_CONTEXT,
        jnp.where(
            ~stress_ok,
            ROUTE_STRESS,
            jnp.where(
                hot,
                jnp.where(
                    ~falling,
                    ROUTE_HOT_NOT_FALLING,
                    jnp.where(
                        ~btc_stalled,
                        ROUTE_BTC_NOT_STALLED,
                        jnp.where(
                            ~feats.valid,
                            ROUTE_NO_SYMBOL_FEATURES,
                            jnp.where(
                                weak_followthrough,
                                ROUTE_SHORT,
                                ROUTE_FOLLOWTHROUGH_NOT_WEAK,
                            ),
                        ),
                    ),
                ),
                jnp.where(
                    washed,
                    jnp.where(
                        ~increasing,
                        ROUTE_WASHED_NOT_INCREASING,
                        jnp.where(
                            btc_momentum > 0, ROUTE_LONG, ROUTE_BTC_NOT_INCREASING
                        ),
                    ),
                    ROUTE_ADP_NOT_EXTREME,
                ),
            ),
        ),
    ).astype(jnp.int32)

    routed = has_context & stress_ok & (short_ok | long_ok)
    return routed, short_ok, route, has_context


def _oi_factor(oi_growth: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(
        jnp.isfinite(oi_growth), 1.0 + jnp.maximum(0.0, oi_growth - 1.0), 1.0
    )


def _lsp_outputs(
    filled: jnp.ndarray,
    score_ok: jnp.ndarray,
    trigger_score: jnp.ndarray,
    threshold: jnp.ndarray,
    routed: jnp.ndarray,
    short_ok: jnp.ndarray,
    route: jnp.ndarray,
    oi_growth: jnp.ndarray,
    adp_latest: jnp.ndarray,
    btc_momentum: jnp.ndarray,
    volume_last: jnp.ndarray,
    p: LSPParams,
) -> StrategyOutputs:
    """Shared output assembly (keys/order/dtypes identical across paths —
    the wire's emission layout is recorded once per wire_enabled combo).
    Takes ``filled`` rather than a buffer so the backtest backend's
    sequential half can gate precomputed cores without window views."""
    # OI confirmation (l.184-185)
    oi_ok = ~jnp.isfinite(oi_growth) | (oi_growth >= p.min_oi_growth)
    fired = score_ok & oi_ok & routed & (filled > 0)
    direction = jnp.where(short_ok, Direction.SHORT, Direction.LONG).astype(jnp.int32)

    S = filled.shape[0]
    return StrategyOutputs(
        trigger=fired,
        direction=direction,
        score=jnp.where(jnp.isfinite(trigger_score), trigger_score, 0.0),
        autotrade=fired,  # autotrade always on for routed signals (l.210)
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "trigger_score": jnp.where(jnp.isfinite(trigger_score), trigger_score, 0.0),
            "threshold": jnp.where(jnp.isfinite(threshold), threshold, 0.0),
            "oi_growth": jnp.where(jnp.isfinite(oi_growth), oi_growth, 1.0),
            "adp": jnp.broadcast_to(adp_latest, (S,)),
            "btc_momentum": jnp.broadcast_to(btc_momentum, (S,)),
            "route": route,
            "volume": volume_last,
        },
    )


def lsp_core(
    buf15: MarketBuffer,
    oi_growth: jnp.ndarray,
    params: LSPParams = LSPParams(),
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The kernel's context-free heavy half: pump-score pipeline + the
    48-bar quantile trigger (OI factor applied; OI rides HostInputs, not
    the context). Returns ``(score_ok, trigger_score, threshold,
    volume_last)`` for the routing/gating half — split out so the backtest
    backend can time-vectorize this over a chunk of ticks."""
    p = params
    wh = p.window_hours
    volume = buf15.values[:, -TAIL:, Field.VOLUME]
    close = buf15.values[:, -TAIL:, Field.CLOSE]
    high = buf15.values[:, -TAIL:, Field.HIGH]
    low = buf15.values[:, -TAIL:, Field.LOW]

    # --- pump score pipeline (l.120-145)
    rel_volume = volume / shift(rolling_mean(volume, wh * 2), wh)
    momentum = close / shift(close, wh) - 1.0
    range_frac = (rolling_max(high, wh * 2) - rolling_min(low, wh * 2)) / close

    oi_factor = _oi_factor(oi_growth)[:, None]
    pump_score = rel_volume * (1.0 + momentum) * oi_factor / range_frac
    smooth = rolling_mean(pump_score, 2)

    # --- trigger: top-quintile of last 48 smoothed scores (l.165-181)
    recent = smooth[:, -p.score_window:]
    finite = jnp.isfinite(recent)
    cnt = jnp.sum(finite, axis=-1)
    s = jnp.sort(jnp.where(finite, recent, jnp.inf), axis=-1)
    rank = p.score_quantile * (cnt - 1.0)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, p.score_window - 1)
    hi = jnp.clip(lo + 1, 0, p.score_window - 1)
    frac = rank - lo
    v_lo = jnp.take_along_axis(s, lo[:, None], axis=-1)[:, 0]
    v_hi = jnp.take_along_axis(
        s, jnp.minimum(hi, jnp.maximum(cnt - 1, 0))[:, None], axis=-1
    )[:, 0]
    threshold = v_lo + (v_hi - v_lo) * frac

    latest_smooth = smooth[:, -1]
    latest_raw = pump_score[:, -1]
    trigger_score = jnp.maximum(latest_smooth, latest_raw)
    score_ok = (
        jnp.isfinite(latest_smooth)
        & (cnt > 0)
        & (trigger_score >= threshold)
    )
    return score_ok, trigger_score, threshold, volume[:, -1]


def liquidation_sweep_pump(
    buf15: MarketBuffer,
    context: MarketContext,
    oi_growth: jnp.ndarray,  # (S,) f32, NaN = unavailable (KuCoin OI cache)
    adp_latest: jnp.ndarray,  # scalar f32 — resolved ADP (breadth or context)
    adp_prev: jnp.ndarray,  # scalar f32, NaN = no history
    btc_momentum: jnp.ndarray,  # scalar f32 — BTC close pct_change last bar
    params: LSPParams = LSPParams(),
) -> StrategyOutputs:
    p = params
    score_ok, trigger_score, threshold, volume_last = lsp_core(
        buf15, oi_growth, p
    )
    routed, short_ok, route, _ = _routing(
        context, adp_latest, adp_prev, btc_momentum, p
    )
    return _lsp_outputs(
        buf15.filled, score_ok, trigger_score, threshold, routed, short_ok,
        route, oi_growth, adp_latest, btc_momentum, volume_last, p,
    )


# ---------------------------------------------------------------------------
# Incremental carry: the same kernel from column reads + one sorted merge
# ---------------------------------------------------------------------------


class LSPCarry(NamedTuple):
    """Carried LiquidationSweepPump state, (S,)/(S, k) leaves.

    All carried scores are UNSCALED (no OI factor): the factor is a
    per-row positive scalar multiplying the whole series uniformly, so
    linear-interpolated quantiles commute with it and the readout applies
    it once. ``smooth_ring`` is the eviction source for the sorted window
    (its newest entry is also this bar's smooth readout); ``prev_raw`` is
    the previous bar's unscaled raw score, feeding the 2-bar smoother.
    """

    score_q: SortedCarry  # (S, score_window) — unscaled smooth scores
    smooth_ring: jnp.ndarray  # (S, score_window) f32, oldest first
    prev_raw: jnp.ndarray  # (S,) f32 — unscaled pump score, newest bar


def empty_lsp_carry(num_symbols: int, p: LSPParams = LSPParams()) -> LSPCarry:
    return LSPCarry(
        score_q=SortedCarry(
            sorted=jnp.full((num_symbols, p.score_window), jnp.inf, jnp.float32),
            cnt=jnp.zeros((num_symbols,), jnp.int32),
        ),
        smooth_ring=jnp.full(
            (num_symbols, p.score_window), jnp.nan, jnp.float32
        ),
        prev_raw=jnp.full((num_symbols,), jnp.nan, jnp.float32),
    )


def lsp_init_from_window(
    buf15: MarketBuffer, p: LSPParams = LSPParams()
) -> LSPCarry:
    """Carry from the full tail: the kernel's series with the OI factor
    pinned to 1.0 (multiplying by 1.0 is exact, so the stored history is
    bit-identical to the full path's oi_factor==1 series)."""
    wh = p.window_hours
    # score_window columns pin the carry's leaf shapes (see the ABP twin)
    assert buf15.window >= p.score_window, (
        f"window {buf15.window} too short for the LSP carry init "
        f"(need >= {p.score_window})"
    )
    volume = buf15.values[:, -TAIL:, Field.VOLUME]
    close = buf15.values[:, -TAIL:, Field.CLOSE]
    high = buf15.values[:, -TAIL:, Field.HIGH]
    low = buf15.values[:, -TAIL:, Field.LOW]

    rel_volume = volume / shift(rolling_mean(volume, wh * 2), wh)
    momentum = close / shift(close, wh) - 1.0
    range_frac = (rolling_max(high, wh * 2) - rolling_min(low, wh * 2)) / close
    pump_u = rel_volume * (1.0 + momentum) / range_frac
    smooth_u = rolling_mean(pump_u, 2)

    return LSPCarry(
        score_q=sorted_init(smooth_u, p.score_window),
        smooth_ring=smooth_u[:, -p.score_window:].astype(jnp.float32),
        prev_raw=pump_u[:, -1].astype(jnp.float32),
    )


def _lsp_new_bar(
    buf15: MarketBuffer, prev_raw: jnp.ndarray, p: LSPParams
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(pump_u, smooth_u) at the newest bar from ~20 column reads — the
    full kernel's formulas with the NaN-aware min_periods semantics of the
    rolling primitives reproduced on stacked columns."""
    wh = p.window_hours
    col = lambda pos, f: buf15.values[:, pos, int(f)]

    # shift(rolling_mean(volume, 2*wh), wh) at the last position: the mean
    # over the 2*wh bars ending wh+1 back (all finite required, mp=window)
    vols = jnp.stack(
        [col(-(wh + 1) - k, Field.VOLUME) for k in range(2 * wh)], axis=-1
    )
    vm = jnp.isfinite(vols)
    v_ok = jnp.sum(vm, axis=-1) >= 2 * wh
    v_mean = jnp.where(
        v_ok, jnp.sum(jnp.where(vm, vols, 0.0), axis=-1) / (2.0 * wh), jnp.nan
    )
    rel_u = col(-1, Field.VOLUME) / v_mean

    momentum = col(-1, Field.CLOSE) / col(-(wh + 1), Field.CLOSE) - 1.0

    highs = jnp.stack([col(-1 - k, Field.HIGH) for k in range(2 * wh)], axis=-1)
    lows = jnp.stack([col(-1 - k, Field.LOW) for k in range(2 * wh)], axis=-1)
    hm, lm = jnp.isfinite(highs), jnp.isfinite(lows)
    h_max = jnp.where(
        jnp.sum(hm, axis=-1) >= 2 * wh,
        jnp.max(jnp.where(hm, highs, -jnp.inf), axis=-1),
        jnp.nan,
    )
    l_min = jnp.where(
        jnp.sum(lm, axis=-1) >= 2 * wh,
        jnp.min(jnp.where(lm, lows, jnp.inf), axis=-1),
        jnp.nan,
    )
    range_frac = (h_max - l_min) / col(-1, Field.CLOSE)

    pump_u = rel_u * (1.0 + momentum) / range_frac
    both = jnp.isfinite(pump_u) & jnp.isfinite(prev_raw)
    smooth_u = jnp.where(both, (pump_u + prev_raw) / 2.0, jnp.nan)
    return pump_u.astype(jnp.float32), smooth_u.astype(jnp.float32)


def lsp_advance_one_bar(
    buf15: MarketBuffer,
    carry: LSPCarry,
    advanced: jnp.ndarray,
    p: LSPParams = LSPParams(),
) -> LSPCarry:
    """Advance per-symbol carries by the buffer's newest bar."""
    # == LSP_MIN_WINDOW at default params
    assert buf15.window >= 3 * p.window_hours + 1, (
        f"window {buf15.window} too short for the LSP carry advance "
        f"(deepest read -(3*window_hours) with the shifted mean's +1)"
    )
    pump_u, smooth_u = _lsp_new_bar(buf15, carry.prev_raw, p)
    new = LSPCarry(
        score_q=sorted_advance(carry.score_q, smooth_u, carry.smooth_ring[:, 0]),
        smooth_ring=jnp.concatenate(
            [carry.smooth_ring[:, 1:], smooth_u[:, None]], axis=1
        ),
        prev_raw=pump_u,
    )

    def sel(n, o):
        mask = advanced if n.ndim == 1 else advanced[:, None]
        return jnp.where(mask, n, o)

    return jax.tree_util.tree_map(sel, new, carry)


def liquidation_sweep_pump_from_carry(
    buf15: MarketBuffer,
    carry: LSPCarry,
    context: MarketContext,
    oi_growth: jnp.ndarray,
    adp_latest: jnp.ndarray,
    adp_prev: jnp.ndarray,
    btc_momentum: jnp.ndarray,
    stale: jnp.ndarray,
    params: LSPParams = LSPParams(),
) -> StrategyOutputs:
    """The fast-path twin of :func:`liquidation_sweep_pump`: latest raw/
    smooth scores read back from the carry the advance just pushed, the
    threshold from one sorted-window quantile, the OI factor applied at
    readout. STALE rows cannot fire (the host is already routing to a
    full recompute)."""
    p = params
    oi = _oi_factor(oi_growth)
    latest_raw = carry.prev_raw * oi
    latest_smooth = carry.smooth_ring[:, -1] * oi
    threshold = sorted_quantile(carry.score_q, p.score_quantile, min_periods=1) * oi

    trigger_score = jnp.maximum(latest_smooth, latest_raw)
    score_ok = (
        jnp.isfinite(latest_smooth)
        & (carry.score_q.cnt > 0)
        & (trigger_score >= threshold)
        & ~stale
    )

    routed, short_ok, route, _ = _routing(
        context, adp_latest, adp_prev, btc_momentum, p
    )
    return _lsp_outputs(
        buf15.filled, score_ok, trigger_score, threshold, routed, short_ok,
        route, oi_growth, adp_latest, btc_momentum,
        buf15.values[:, -1, Field.VOLUME], p,
    )
