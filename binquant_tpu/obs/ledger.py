"""Executable/compile ledger: every jit entry the engine owns, accounted.

The engine's cost story lives in a handful of jit executables (the wire
step, its donated twin, the full-outputs fallback, the fused scan, the
backtest chunk and its sweep). Until now their compile cost was visible
only as a counter (``bqt_jit_recompiles_total``) — nothing recorded how
long each compile took, whether the XLA persistent compilation cache
(PR 4 session-2) actually served it warm, or what the resulting
executable costs per dispatch. This module is that registry:

* **Compile wall-time + cache outcome** — the dispatch sites wrap their
  first-per-signature launch in :meth:`ExecutableLedger.watch`; the
  ledger listens on ``jax.monitoring`` (``backend_compile_duration``,
  persistent-cache ``cache_hits``/``cache_misses``) and attributes events
  fired during the watched window (compiles run synchronously on the
  launching thread, so a thread-local watch is attribution enough) —
  ``warm`` means the persistent cache deserialized the executable,
  ``cold`` a full XLA compile, ``cache_off`` no cache configured.
* **Per-dispatch cost** — callers hand the watch a ``cost_fn`` thunk
  (typically ``lambda: fn.lower(*abstract_args).cost_analysis()`` over
  ``jax.ShapeDtypeStruct`` trees captured BEFORE any donation); thunks
  run on a background worker (a re-trace, not a recompile) and fill the
  entry's bytes/flops — ``compute_costs()`` drains synchronously for
  tests and tools.
* **Exports** — ``bqt_compile_seconds{executable}`` /
  ``bqt_executable_bytes{executable}`` / ``bqt_executable_flops`` metrics,
  one ``compile`` event per recorded compile, a once-per-boot
  ``compile_summary`` event (total compile seconds, warm/cold split), and
  the ``GET /debug/executables`` JSON (obs/exposition.py).

Everything here is hot-path-safe: a watch over an already-recorded
signature that triggers no compile costs two perf_counter reads and a
thread-local store.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable

log = logging.getLogger(__name__)

_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def abstract_args(args: tuple, kwargs: dict | None = None):
    """(args, kwargs) with every array leaf replaced by its
    ``jax.ShapeDtypeStruct`` — a cost thunk built from these can lower the
    executable long after the concrete buffers were donated/deleted."""
    import jax

    def to_abstract(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
        return leaf

    return (
        jax.tree_util.tree_map(to_abstract, args),
        jax.tree_util.tree_map(to_abstract, kwargs or {}),
    )


def lowered_cost(fn, *args, **kwargs) -> dict:
    """``cost_analysis`` of ``fn`` lowered at these (abstract or concrete)
    args — a jaxpr trace + lowering, NOT an XLA compile. Missing/NaN
    fields become None (the snapshot is served as strict JSON — a bare
    NaN token would break every downstream parser)."""
    ca = fn.lower(*args, **kwargs).cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}

    def field(key):
        v = ca.get(key)
        if v is None:
            return None
        v = float(v)
        return v if v == v else None

    return {
        "flops": field("flops"),
        "bytes_accessed": field("bytes accessed"),
    }


class ExecutableLedger:
    """Thread-safe registry of (executable, signature) compile records."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._entries: dict[tuple[str, str], dict[str, Any]] = {}
        self._pending_cost: list[tuple[tuple[str, str], Callable[[], dict]]] = []
        self._cost_worker: threading.Thread | None = None
        self._listeners_installed = False
        self._summary_emitted = False
        self._active_watches = 0
        # process-wide tallies incl. compiles no watch was open for
        # (library-internal jits, helper steps)
        self.total_backend_compile_s = 0.0
        self.unattributed_compile_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0

    # -- jax.monitoring attribution ----------------------------------------

    def _install_listeners(self) -> None:
        if self._listeners_installed:
            return
        with self._lock:
            if self._listeners_installed:
                return
            try:
                import jax.monitoring as monitoring

                monitoring.register_event_duration_secs_listener(
                    self._on_duration
                )
                monitoring.register_event_listener(self._on_event)
            except Exception:  # pragma: no cover - jax baked into the image
                log.exception("jax.monitoring unavailable; ledger degrades "
                              "to wall-time-only records")
            self._listeners_installed = True

    def _on_duration(self, name: str, duration: float, **kw: Any) -> None:
        if name != _COMPILE_DURATION_EVENT:
            return
        watch = getattr(self._tls, "watch", None)
        with self._lock:
            self.total_backend_compile_s += duration
            if watch is not None:
                watch["backend_compile_s"] += duration
                watch["compiled"] = True
            else:
                self.unattributed_compile_s += duration

    def _on_event(self, name: str, **kw: Any) -> None:
        if name not in (_CACHE_HIT_EVENT, _CACHE_MISS_EVENT):
            return
        hit = name == _CACHE_HIT_EVENT
        watch = getattr(self._tls, "watch", None)
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1
            if watch is not None:
                watch["cache_hits" if hit else "cache_misses"] += 1

    # -- recording -----------------------------------------------------------

    @contextmanager
    def watch(
        self,
        executable: str,
        signature: str,
        expect_compile: bool = True,
        cost_fn: Callable[[], dict] | None = None,
        tick: int | None = None,
    ):
        """Time the wrapped launch and record a ledger entry when it
        compiled (``expect_compile`` marks the caller's own new-signature
        verdict; a monitored compile records even without it — jit cache
        evictions the caller's signature set missed)."""
        self._install_listeners()
        watch = {
            "backend_compile_s": 0.0,
            "compiled": False,
            "cache_hits": 0,
            "cache_misses": 0,
        }
        prev = getattr(self._tls, "watch", None)
        self._tls.watch = watch
        with self._lock:
            self._active_watches += 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            wall = time.perf_counter() - t0
            self._tls.watch = prev
            if expect_compile or watch["compiled"]:
                self._record(
                    executable, signature, wall, watch, cost_fn, tick
                )
            with self._lock:
                self._active_watches -= 1

    def _record(
        self,
        executable: str,
        signature: str,
        wall_s: float,
        watch: dict,
        cost_fn: Callable[[], dict] | None,
        tick: int | None,
    ) -> None:
        from binquant_tpu.obs.events import get_event_log
        from binquant_tpu.obs.instruments import COMPILE_SECONDS

        if watch["cache_hits"] and not watch["cache_misses"]:
            cache = "warm"
        elif watch["cache_misses"]:
            cache = "cold"
        else:
            cache = "cache_off" if watch["compiled"] else "unknown"
        key = (executable, signature)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = {
                    "executable": executable,
                    "signature": signature,
                    "compiles": 0,
                    "compile_seconds": 0.0,
                    "backend_compile_seconds": 0.0,
                    "cache": cache,
                    "cost": None,
                    "cost_status": "none",
                    "first_recorded_s": time.time(),
                    "tick": tick,
                }
                self._entries[key] = entry
            entry["compiles"] += 1
            entry["compile_seconds"] += wall_s
            entry["backend_compile_seconds"] += watch["backend_compile_s"]
            entry["cache"] = cache
            if cost_fn is not None and entry["cost_status"] in ("none", "error"):
                entry["cost_status"] = "pending"
                self._pending_cost.append((key, cost_fn))
                start_worker = True
            else:
                start_worker = False
        COMPILE_SECONDS.labels(executable=executable).inc(wall_s)
        get_event_log().emit(
            "compile",
            executable=executable,
            signature=signature,
            seconds=round(wall_s, 3),
            backend_compile_s=round(watch["backend_compile_s"], 3),
            cache=cache,
        )
        if start_worker:
            self._ensure_cost_worker()

    # -- cost analysis (background) ------------------------------------------

    def _ensure_cost_worker(self) -> None:
        with self._lock:
            worker = self._cost_worker
            if worker is not None and worker.is_alive():
                return
            worker = threading.Thread(
                target=self._drain_costs, name="bqt-ledger-cost", daemon=True
            )
            self._cost_worker = worker
        worker.start()

    def _drain_costs(self) -> None:
        while True:
            with self._lock:
                if not self._pending_cost:
                    # retire under the SAME lock _record appends under: a
                    # thunk queued after this point sees _cost_worker dead
                    # (None) and starts a fresh worker — without this, a
                    # record racing an exiting-but-alive thread would
                    # strand its thunk as cost_status='pending' forever
                    self._cost_worker = None
                    return
                key, cost_fn = self._pending_cost.pop(0)
            self._compute_one(key, cost_fn)

    def _compute_one(
        self, key: tuple[str, str], cost_fn: Callable[[], dict]
    ) -> None:
        from binquant_tpu.obs.instruments import (
            EXECUTABLE_BYTES,
            EXECUTABLE_FLOPS,
        )

        try:
            cost = cost_fn()
        except Exception as exc:
            log.warning("cost analysis failed for %s: %r", key, exc)
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    entry["cost_status"] = "error"
                    entry["cost"] = {"error": repr(exc)}
            return
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            entry["cost"] = cost
            entry["cost_status"] = "ok"
            executable = entry["executable"]
        b = cost.get("bytes_accessed")
        f = cost.get("flops")
        if b is not None and b == b:
            EXECUTABLE_BYTES.labels(executable=executable).set(b)
        if f is not None and f == f:
            EXECUTABLE_FLOPS.labels(executable=executable).set(f)

    def compute_costs(self, timeout_s: float = 120.0) -> bool:
        """Drain the queue inline AND wait out any thunk the background
        worker already claimed, so callers (tests, tools) observe a settled
        ledger; False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                item = self._pending_cost.pop(0) if self._pending_cost else None
            if item is not None:
                self._compute_one(*item)
                continue
            with self._lock:
                settled = not any(
                    e["cost_status"] == "pending"
                    for e in self._entries.values()
                )
            if settled:
                return True
            time.sleep(0.01)  # worker mid-thunk: let it finish
        return False

    # -- exports -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/debug/executables`` payload (JSON-safe)."""
        with self._lock:
            entries = [dict(e) for e in self._entries.values()]
            totals = {
                "executables": len(entries),
                "compiles": sum(e["compiles"] for e in entries),
                "compile_seconds": round(
                    sum(e["compile_seconds"] for e in entries), 3
                ),
                "backend_compile_seconds_total": round(
                    self.total_backend_compile_s, 3
                ),
                "unattributed_compile_seconds": round(
                    self.unattributed_compile_s, 3
                ),
                "persistent_cache_hits": self.cache_hits,
                "persistent_cache_misses": self.cache_misses,
                "cost_pending": len(self._pending_cost),
            }
        entries.sort(key=lambda e: -e["compile_seconds"])
        for e in entries:
            e["compile_seconds"] = round(e["compile_seconds"], 3)
            e["backend_compile_seconds"] = round(
                e["backend_compile_seconds"], 3
            )
        return {"totals": totals, "executables": entries}

    def emit_summary(self, reason: str = "startup") -> dict | None:
        """One ``compile_summary`` event per boot (the satellite's
        boot-cost visibility): total compile seconds, warm/cold split.
        Subsequent calls are no-ops."""
        from binquant_tpu.obs.events import get_event_log

        with self._lock:
            if self._summary_emitted:
                return None
            self._summary_emitted = True
        snap = self.snapshot()
        return get_event_log().emit(
            "compile_summary", reason=reason, **snap["totals"]
        )

    def emit_summary_when_quiet(self, reason: str = "startup") -> dict | None:
        """Emit the boot summary only once NO watch is in flight — the
        background fallback pre-warm's multi-second compile is usually
        still running when the first tick finalizes, and a summary
        snapshotted then would permanently under-report the boot's
        largest single compile (once-guarded, so there is no second
        chance). Callers poll this once per tick until it fires."""
        with self._lock:
            if self._summary_emitted or self._active_watches > 0:
                return None
        return self.emit_summary(reason=reason)

    @property
    def summary_emitted(self) -> bool:
        return self._summary_emitted

    def reset(self) -> None:
        """Test isolation: drop entries/tallies (listeners stay installed —
        jax.monitoring offers no targeted unregister)."""
        with self._lock:
            self._entries.clear()
            self._pending_cost.clear()
            self._summary_emitted = False
            self.total_backend_compile_s = 0.0
            self.unattributed_compile_s = 0.0
            self.cache_hits = 0
            self.cache_misses = 0


#: Process-global ledger: every engine dispatch site records here, and
#: /debug/executables serves it.
LEDGER = ExecutableLedger()
