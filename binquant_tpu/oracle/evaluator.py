"""Per-symbol pandas evaluation — the reference's control flow, verbatim in
shape: rolling DataFrames per symbol, indicator enrichment with pandas
``rolling``/``ewm``, a Python loop over fresh symbols for the market
context, and per-strategy Python evaluation with dict-carried cooldowns.

This is deliberately NOT the TPU architecture: it exists as the independent
A/B oracle (``/root/repo/BASELINE.json`` config #1; SURVEY.md §7 step 8).
Formulas mirror the reference (same constants and clamps the device kernels
pin): context/regime — ``live_market_context_accumulator.py:95-297``,
``regime_transitions.py:45-232``; strategies — ``activity_burst_pump.py``,
``coinrule/price_tracker.py``, ``liquidation_sweep_pump.py``,
``mean_reversion_fade.py``, ``grid/ladder_deployer.py``; routing —
``regime_routing.py:47-76``. The TPU path and this oracle must emit the
identical signal set over a replay (tests/test_ab_parity.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np
import pandas as pd

from binquant_tpu.enums import (
    Direction,
    MarketRegimeCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.utils import clamp, non_negative, safe_pct

MIN_BARS = 100  # context_evaluator.py:361-365 (MA-100 sufficiency)
FIFTEEN_MIN_S = 900
FIVE_MIN_S = 300
REGIME_STABILITY_S = 30 * 60
TRANSITION_STRENGTH_FLOOR = 0.08

LIVE_STRATEGIES = (
    "activity_burst_pump",
    "coinrule_price_tracker",
    "liquidation_sweep_pump",
    "mean_reversion_fade",
    "grid_ladder",
)

# Dormant strategies with an independent oracle (VERDICT r2 item 6: the
# ones whose inline indicator variants — rolling-sum ADX, Connors RSI(2),
# 6h-dip reference — carry the highest formula-drift risk). A/B'd via the
# ``enabled_strategies`` override in run_replay_ab.
DORMANT_ORACLE_STRATEGIES = (
    "coinrule_buy_the_dip",
    "bb_extreme_reversion",
    "range_bb_rsi_mean_reversion",
)

# Remaining dormant set with oracle coverage (round 3 extension): the
# coinrule rules, InversePriceTracker, RelativeStrengthReversalRange, and
# RangeFailedBreakoutFade (with a full pandas mirror of the SpikeHunter
# detector's flag pipeline). Every one of the 14 strategy kernels now has
# an independent oracle.
DORMANT_ORACLE_EXTENDED = (
    "coinrule_twap_momentum_sniper",
    "coinrule_supertrend_swing_reversal",
    "coinrule_buy_low_sell_high",
    "inverse_price_tracker",
    "relative_strength_reversal_range",
    "range_failed_breakout_fade",
)


def _nz(x: float, default: float = 0.0) -> float:
    return float(x) if math.isfinite(float(x)) else default


def _rsi14_sma(close: pd.Series) -> float | None:
    """Simple-rolling-mean RSI(14) — the ``Indicators.rsi`` column variant
    every oracle strategy that reads plain RSI must share (MRF's Wilder
    variant stays inline there). None when the 14-bar warm-up is unmet."""
    delta = close.diff()
    avg_gain = delta.clip(lower=0).rolling(14, min_periods=14).mean().iloc[-1]
    avg_loss = (-delta).clip(lower=0).rolling(14, min_periods=14).mean().iloc[-1]
    if not (
        math.isfinite(_nz(avg_gain, np.nan)) and math.isfinite(_nz(avg_loss, np.nan))
    ):
        return None
    denom = avg_gain + avg_loss
    return 100.0 * avg_gain / denom if denom != 0 else 50.0


# ---------------------------------------------------------------------------
# Rolling store (reference MarketStateStore: dedupe, sort, tail)
# ---------------------------------------------------------------------------


class FrameStore:
    def __init__(self, window: int) -> None:
        self.window = window
        self.frames: dict[str, pd.DataFrame] = {}

    def update(self, kline: dict) -> None:
        sym = kline["symbol"]
        row = {
            k: float(kline[k])
            for k in (
                "open",
                "high",
                "low",
                "close",
                "volume",
                "quote_asset_volume",
                "number_of_trades",
            )
        }
        row["open_time"] = int(kline["open_time"])
        df = self.frames.get(sym)
        new = pd.DataFrame([row])
        if df is None:
            df = new
        else:
            df = pd.concat([df[df["open_time"] != row["open_time"]], new])
        self.frames[sym] = (
            df.sort_values("open_time").tail(self.window).reset_index(drop=True)
        )

    def fresh(self, ts_s: int) -> list[str]:
        return [
            s
            for s, df in self.frames.items()
            if int(df["open_time"].iloc[-1]) // 1000 == ts_s
        ]


# ---------------------------------------------------------------------------
# Market context (accumulator + regime transitions, per-symbol Python loop)
# ---------------------------------------------------------------------------


@dataclass
class SymbolFeatures:
    valid: bool = False
    close: float = 0.0
    return_pct: float = 0.0
    ema20: float = 0.0
    ema50: float = 0.0
    above_ema20: bool = False
    above_ema50: bool = False
    trend_score: float = 0.0
    relative_strength_vs_btc: float = 0.0
    atr_pct: float = 0.0
    bb_width: float = 0.0
    micro_regime: int = -1
    micro_strength: float = 0.0
    micro_transition: int = -1


@dataclass
class OracleContext:
    valid: bool = False
    timestamp: int = -1
    advancers_ratio: float = 0.0
    pct_above_ema20: float = 0.0
    pct_above_ema50: float = 0.0
    average_trend_score: float = 0.0
    average_return: float = 0.0
    market_stress_score: float = 0.0
    btc_regime_score: float = 0.0
    long_tailwind: float = 0.0
    short_tailwind: float = 0.0
    market_regime: int = -1
    market_regime_transition_strength: float = 0.0
    regime_is_transitioning: bool = False
    regime_stable_since: int = -1
    long_regime_score: float = 0.0
    short_regime_score: float = 0.0
    range_regime_score: float = 0.0
    stress_regime_score: float = 0.0
    features: dict[str, SymbolFeatures] = field(default_factory=dict)


def _symbol_features(df: pd.DataFrame) -> SymbolFeatures | None:
    """_compute_symbol_features (accumulator l.244-297); None if <2 bars."""
    if len(df) < 2:
        return None
    close = df["close"]
    latest = float(close.iloc[-1])
    prev = float(close.iloc[-2])
    ema20 = float(close.ewm(span=20, adjust=False, min_periods=1).mean().iloc[-1])
    ema50 = float(close.ewm(span=50, adjust=False, min_periods=1).mean().iloc[-1])
    tail = df.tail(15)
    prev_close = tail["close"].shift(1)
    tr = pd.concat(
        [
            tail["high"] - tail["low"],
            (tail["high"] - prev_close).abs(),
            (tail["low"] - prev_close).abs(),
        ],
        axis=1,
    ).max(axis=1)
    atr = float(tr.rolling(14, min_periods=1).mean().iloc[-1])
    mid = float(close.rolling(20, min_periods=1).mean().iloc[-1])
    std = close.rolling(20, min_periods=1).std(ddof=0).iloc[-1]
    std = _nz(std, 0.0)
    bb_upper, bb_lower = mid + 2 * std, mid - 2 * std
    f = SymbolFeatures(
        valid=True,
        close=latest,
        return_pct=safe_pct(latest, prev),
        ema20=ema20,
        ema50=ema50,
        above_ema20=latest > ema20,
        above_ema50=latest > ema50,
        trend_score=(ema20 - ema50) / abs(ema50) if ema50 != 0 else 0.0,
        atr_pct=atr / latest if latest != 0 else 0.0,
        bb_width=(bb_upper - bb_lower) / abs(mid) if mid != 0 else 0.0,
    )
    return f


def _micro_scores(f: SymbolFeatures) -> tuple[int, float]:
    """Per-symbol regime ladder (regime_transitions.py:167-206)."""
    up = clamp(
        0.45 * non_negative(f.trend_score * 30.0)
        + 0.2 * float(f.above_ema20)
        + 0.15 * float(f.above_ema50)
        + 0.2 * non_negative(f.relative_strength_vs_btc * 20.0),
        0.0,
        1.0,
    )
    down = clamp(
        0.45 * non_negative(-f.trend_score * 30.0)
        + 0.2 * float(not f.above_ema20)
        + 0.15 * float(not f.above_ema50)
        + 0.2 * non_negative(-f.relative_strength_vs_btc * 20.0),
        0.0,
        1.0,
    )
    rng = clamp(
        0.38 * (1.0 - min(abs(f.trend_score) * 30.0, 1.0))
        + 0.34 * (1.0 - min(f.bb_width / 0.08, 1.0))
        + 0.28 * (1.0 - min(f.atr_pct / 0.04, 1.0)),
        0.0,
        1.0,
    )
    vol = clamp(
        0.55 * min(f.atr_pct / 0.05, 1.0) + 0.45 * min(f.bb_width / 0.12, 1.0),
        0.0,
        1.0,
    )
    strength = max(up, down, rng, vol)
    if vol >= 0.72 and abs(f.return_pct) >= 0.015:
        regime = int(MicroRegimeCode.VOLATILE)
    elif up >= 0.52 and up >= down + 0.1:
        regime = int(MicroRegimeCode.TREND_UP)
    elif down >= 0.52 and down >= up + 0.1:
        regime = int(MicroRegimeCode.TREND_DOWN)
    elif rng >= 0.5:
        regime = int(MicroRegimeCode.RANGE)
    else:
        regime = int(MicroRegimeCode.TRANSITIONAL)
    return regime, strength


def _micro_transition(prev: int, regime: int) -> int:
    T, R = MicroTransitionCode, MicroRegimeCode
    from_range_like = prev in (int(R.RANGE), int(R.TRANSITIONAL))
    if regime == int(R.VOLATILE):
        return int(T.VOLATILITY_EXPANSION)
    if from_range_like and regime == int(R.TREND_UP):
        return int(T.BREAKOUT_UP)
    if from_range_like and regime == int(R.TREND_DOWN):
        return int(T.BREAKDOWN)
    if prev == int(R.TREND_DOWN) and regime == int(R.TREND_UP):
        return int(T.RECOVERY)
    if prev == int(R.TREND_UP) and regime == int(R.RANGE):
        return int(T.MEAN_REVERSION)
    if regime == int(R.TREND_UP):
        return int(T.ENTERED_TREND_UP)
    if regime == int(R.TREND_DOWN):
        return int(T.ENTERED_TREND_DOWN)
    if regime == int(R.RANGE):
        return int(T.ENTERED_RANGE)
    return int(T.ENTERED_TRANSITIONAL)


# ---------------------------------------------------------------------------
# Context-conditioned scoring (context_scoring.py + signal_context_scorer.py)
# ---------------------------------------------------------------------------


def _context_score(
    ctx: OracleContext, is_short: bool, symbol_rs: float, symbol_trend: float
) -> dict:
    confidence = 1.0 if ctx.valid else 0.0
    breadth = ctx.short_tailwind if is_short else ctx.long_tailwind
    btc_align = clamp(-ctx.btc_regime_score if is_short else ctx.btc_regime_score)
    rs_signed = -symbol_rs if is_short else symbol_rs
    trend_signed = -symbol_trend if is_short else symbol_trend
    cross_asset = clamp(0.6 * rs_signed + 0.4 * trend_signed)
    override = clamp(
        0.6 * non_negative(rs_signed) + 0.4 * non_negative(trend_signed), 0.0, 1.0
    )
    directional_stress = (
        ctx.market_stress_score * 0.35 if is_short else -ctx.market_stress_score
    )
    supportiveness = clamp(
        0.35 * breadth + 0.25 * btc_align + 0.25 * cross_asset
        + 0.15 * directional_stress
    )
    followthrough = clamp(0.45 * breadth + 0.3 * btc_align + 0.25 * cross_asset)
    risk = clamp(
        0.55 * ctx.market_stress_score
        + 0.25 * non_negative(-supportiveness)
        + 0.2 * (1.0 - override),
        0.0,
        1.0,
    )
    if breadth < 0 and override > 0:
        if not is_short:
            supportiveness = clamp(supportiveness + 0.2 * override)
            followthrough = clamp(followthrough + 0.15 * override)
        else:
            supportiveness = clamp(supportiveness + 0.1 * override)
    z = confidence
    return {
        "confidence": confidence,
        "followthrough": followthrough * z,
        "risk": risk * z,
        "supportiveness": supportiveness * z,
    }


def _allows_long_autotrade(ctx: OracleContext, sym: str) -> bool:
    """regime_routing.py:47-76."""
    if not ctx.valid or ctx.regime_is_transitioning:
        return False
    if ctx.regime_stable_since < 0:
        return False
    age = max(ctx.timestamp - ctx.regime_stable_since, 0)
    if age < REGIME_STABILITY_S:
        return False
    market_regime_ok = ctx.market_regime in (
        int(MarketRegimeCode.TREND_UP),
        int(MarketRegimeCode.RANGE),
    )
    if not market_regime_ok or ctx.market_stress_score >= 0.35:
        return False
    f = ctx.features.get(sym)
    if f is None or not f.valid or f.micro_regime < 0:
        return market_regime_ok
    if f.micro_regime == int(MicroRegimeCode.TREND_DOWN):
        return f.micro_transition == int(MicroTransitionCode.RECOVERY)
    if f.micro_regime == int(MicroRegimeCode.VOLATILE):
        return False
    return f.micro_regime in (
        int(MicroRegimeCode.TREND_UP),
        int(MicroRegimeCode.RANGE),
        int(MicroRegimeCode.TRANSITIONAL),
    )


# ---------------------------------------------------------------------------
# The evaluator
# ---------------------------------------------------------------------------


class OracleEvaluator:
    """Reference-shaped engine: ingest klines, evaluate per tick, emit
    (strategy, symbol, direction, autotrade) tuples."""

    def __init__(
        self,
        window: int = 200,
        btc_symbol: str = "BTCUSDT",
        required_fresh_symbols: int = 40,
        min_coverage_ratio: float = 0.70,
        is_futures: bool = True,
        enabled_strategies: set[str] | frozenset[str] | tuple | None = None,
    ) -> None:
        self.store5 = FrameStore(window)
        self.store15 = FrameStore(window)
        self.btc_symbol = btc_symbol
        self.required_fresh = required_fresh_symbols
        self.min_coverage = min_coverage_ratio
        self.is_futures = is_futures
        self.enabled = frozenset(
            LIVE_STRATEGIES if enabled_strategies is None else enabled_strategies
        )
        # regime carry: previous (strictly older ts) + stage (current ts)
        self._prev_market: tuple[int, tuple, int] | None = None  # regime, scores, since
        self._prev_micro: dict[str, tuple[int, float]] = {}
        self._stage_ts: int = -1
        self._stage_market: tuple[int, tuple, int] | None = None
        self._stage_micro: dict[str, tuple[int, float]] = {}
        # strategy carries
        self.pt_last_close: dict[str, int] = {}
        self.mrf_last_open: dict[str, int] = {}
        self.last_emitted: dict[tuple[str, str], int] = {}
        # most recent VALID regime (grid-only policy input next tick; the
        # quiet-hours override itself reads the CURRENT tick's context,
        # matching the device step)
        self._last_regime: int | None = None
        self._last_strength: float = 0.0

    @property
    def last_regime(self) -> int | None:
        """The most recent VALID evaluation's market regime (None when the
        last context failed coverage) — the input the host-side grid-only
        policy and quiet-hours filter consume next tick."""
        return self._last_regime

    @property
    def last_strength(self) -> float:
        """The most recent evaluation's regime-transition strength (0.0
        when the context was invalid) — paired with :attr:`last_regime`."""
        return self._last_strength

    # -- ingest ------------------------------------------------------------

    def ingest(self, kline: dict) -> None:
        duration_s = (int(kline["close_time"]) - int(kline["open_time"])) // 1000
        if abs(duration_s - FIVE_MIN_S) <= 1:
            self.store5.update(kline)
        elif abs(duration_s - FIFTEEN_MIN_S) <= 1:
            self.store15.update(kline)

    # -- context -----------------------------------------------------------

    def _build_context(self, ts15: int) -> OracleContext:
        # promote the stage when a strictly newer timestamp arrives
        if ts15 > self._stage_ts:
            if self._stage_market is not None:
                self._prev_market = self._stage_market
            self._prev_micro.update(self._stage_micro)
            self._stage_market = None
            self._stage_micro = {}
            self._stage_ts = ts15

        tracked = set(self.store5.frames) | set(self.store15.frames)
        fresh = self.store15.fresh(ts15)  # always a subset of tracked
        feats: dict[str, SymbolFeatures] = {}
        for sym in fresh:
            f = _symbol_features(self.store15.frames[sym])
            if f is not None:
                feats[sym] = f

        # BTC features from its frame regardless of freshness (l.105-106)
        btc_df = self.store15.frames.get(self.btc_symbol)
        btc_present = btc_df is not None and len(btc_df) >= 2
        btc_f = _symbol_features(btc_df) if btc_present else None
        btc_return = btc_f.return_pct if btc_f else 0.0
        btc_trend = btc_f.trend_score if btc_f else 0.0

        for sym, f in feats.items():
            if btc_present and sym != self.btc_symbol:
                f.relative_strength_vs_btc = f.return_pct - btc_return

        effective = len(feats)
        total_tracked = max(len(tracked), effective)
        ctx = OracleContext(timestamp=ts15, features=feats)
        vals = list(feats.values())
        n = max(effective, 1)
        advancers = sum(1 for f in vals if f.return_pct > 0)
        decliners = sum(1 for f in vals if f.return_pct < 0)
        ctx.advancers_ratio = advancers / n
        decliners_ratio = decliners / n
        ctx.average_return = sum(f.return_pct for f in vals) / n
        ctx.pct_above_ema20 = sum(f.above_ema20 for f in vals) / n
        ctx.pct_above_ema50 = sum(f.above_ema50 for f in vals) / n
        ctx.average_trend_score = sum(f.trend_score for f in vals) / n
        average_atr_pct = sum(f.atr_pct for f in vals) / n
        average_bb_width = sum(f.bb_width for f in vals) / n

        breadth_balance = clamp((ctx.advancers_ratio - decliners_ratio) * 1.5)
        ema_balance = clamp(
            ((ctx.pct_above_ema20 + ctx.pct_above_ema50) - 1.0) * 1.5
        )
        average_return_score = clamp(ctx.average_return * 12.0)
        ctx.btc_regime_score = (
            clamp(btc_return * 12.0 + btc_trend * 6.0) if btc_present else 0.0
        )
        stress_vol = clamp((average_atr_pct - 0.02) * 12.0, 0.0, 1.0)
        stress_bw = clamp((average_bb_width - 0.08) * 4.0, 0.0, 1.0)
        stress_sell = clamp((-ctx.average_return) * 16.0, 0.0, 1.0)
        ctx.market_stress_score = (
            0.4 * stress_vol + 0.25 * stress_bw + 0.35 * stress_sell
        )
        ctx.long_tailwind = clamp(
            0.4 * breadth_balance
            + 0.2 * ema_balance
            + 0.25 * ctx.btc_regime_score
            + 0.15 * average_return_score
            - 0.35 * ctx.market_stress_score
        )
        ctx.short_tailwind = clamp(
            -0.35 * breadth_balance
            - 0.15 * ema_balance
            - 0.2 * ctx.btc_regime_score
            - 0.15 * average_return_score
            + 0.45 * ctx.market_stress_score
        )

        # effective >= required already implies the fresh-count and
        # coverage-ratio gates (required = max of both thresholds)
        required = max(
            self.required_fresh, math.ceil(total_tracked * self.min_coverage)
        )
        ctx.valid = total_tracked > 0 and effective >= required

        # --- macro ladder + transition (regime_transitions.py:45-160)
        R = MarketRegimeCode
        breadth_score = clamp((ctx.advancers_ratio - 0.5) / 0.25)
        trend_participation = clamp(
            ((ctx.pct_above_ema20 + ctx.pct_above_ema50) - 1.0) * 1.4
        )
        avg_trend_bias = clamp(ctx.average_trend_score * 20.0)
        calm = clamp(1.0 - ctx.market_stress_score, 0.0, 1.0)
        long_score = clamp(
            0.3 * non_negative(ctx.long_tailwind)
            + 0.24 * non_negative(ctx.btc_regime_score)
            + 0.2 * non_negative(breadth_score)
            + 0.14 * non_negative(trend_participation)
            + 0.12 * calm,
            0.0,
            1.0,
        )
        short_score = clamp(
            0.28 * non_negative(ctx.short_tailwind)
            + 0.24 * non_negative(-ctx.btc_regime_score)
            + 0.16 * non_negative(-breadth_score)
            + 0.1 * non_negative(-avg_trend_bias)
            + 0.22 * ctx.market_stress_score,
            0.0,
            1.0,
        )
        range_score = clamp(
            0.32 * (1.0 - abs(breadth_score))
            + 0.22 * (1.0 - abs(ctx.btc_regime_score))
            + 0.24 * calm
            + 0.12 * (1.0 - abs(avg_trend_bias))
            + 0.1 * (1.0 - abs(ctx.long_tailwind - ctx.short_tailwind)),
            0.0,
            1.0,
        )
        stress_score = clamp(
            0.7 * ctx.market_stress_score
            + 0.18 * non_negative(-ctx.average_return * 20.0)
            + 0.12 * non_negative(short_score - long_score),
            0.0,
            1.0,
        )
        dominant = max(long_score, short_score, range_score, stress_score)
        if stress_score >= 0.5 and ctx.market_stress_score >= 0.35:
            regime = int(R.HIGH_STRESS)
        elif long_score >= 0.44 and long_score >= short_score + 0.08:
            regime = int(R.TREND_UP)
        elif short_score >= 0.42 and short_score >= long_score + 0.08:
            regime = int(R.TREND_DOWN)
        elif range_score >= 0.5:
            regime = int(R.RANGE)
        else:
            regime = int(R.TRANSITIONAL)
        ctx.long_regime_score = long_score
        ctx.short_regime_score = short_score
        ctx.range_regime_score = range_score
        ctx.stress_regime_score = stress_score
        ctx.market_regime = regime

        prev = self._prev_market
        changed = prev is not None and prev[0] != regime
        scores = (long_score, short_score, range_score, stress_score)
        if changed:
            max_delta = max(
                abs(a - b) for a, b in zip(scores, prev[1])
            )
            strength = clamp(dominant + max_delta - 0.25, 0.0, 1.0)
        else:
            strength = 0.0
        ctx.market_regime_transition_strength = strength
        ctx.regime_is_transitioning = regime == int(R.TRANSITIONAL) or (
            changed and strength >= TRANSITION_STRENGTH_FLOOR
        )
        keep_anchor = prev is not None and prev[0] == regime and prev[2] >= 0
        ctx.regime_stable_since = prev[2] if keep_anchor else ts15

        # --- micro ladders + transitions against carried previous
        for sym, f in feats.items():
            m_regime, m_strength = _micro_scores(f)
            f.micro_regime = m_regime
            f.micro_strength = m_strength
            p = self._prev_micro.get(sym)
            if p is not None and p[0] >= 0 and p[0] != m_regime:
                f.micro_transition = _micro_transition(p[0], m_regime)
            else:
                f.micro_transition = -1

        # --- stage update (only valid evaluations are staged, l.101-103)
        if ctx.valid:
            self._stage_market = (regime, scores, ctx.regime_stable_since)
            for sym, f in feats.items():
                self._stage_micro[sym] = (f.micro_regime, f.micro_strength)
        return ctx

    # -- strategies --------------------------------------------------------

    def _abp(self, sym: str, ctx: OracleContext) -> tuple[bool, bool] | None:
        """activity_burst_pump.py: (fired, autotrade)."""
        df = self.store5.frames[sym]
        if len(df) < 21:
            return None
        volume = df["volume"]
        qav = df["quote_asset_volume"]
        close, open_ = df["close"], df["open"]
        high, low = df["high"], df["low"]
        eps = 1e-8
        bw = 19
        baseline = volume.shift(2).rolling(bw, min_periods=bw).median()
        baseline_safe = baseline.clip(lower=eps)
        volume_ratio = volume / baseline_safe
        has_qav = bool((qav > 0).any())
        q_baseline = qav.shift(2).rolling(bw, min_periods=bw).median().clip(lower=eps)
        quote_ratio = qav / q_baseline if has_qav else pd.Series(1.0, index=qav.index)
        prev_close = close.shift(1).clip(lower=eps)
        candle_range = (high - low).clip(lower=eps)
        body = (close - open_).abs()
        price_jump = (close - close.shift(1)) / prev_close
        range_frac = candle_range / close.clip(lower=eps)
        body_frac = body / candle_range
        close_to_high = (high - close) / candle_range
        is_bullish = close > open_
        up_close = (close > close.shift(1)).astype(float)
        recent_up = up_close.rolling(3, min_periods=1).sum()

        vol_spike = volume > 2.75 * baseline_safe
        quote_spike = qav > 2.5 * q_baseline if has_qav else pd.Series(True, index=qav.index)
        jump_flag = price_jump > 0.01
        range_flag = range_frac > 0.012
        body_flag = is_bullish & (body_frac > 0.45) & (close_to_high < 0.35)
        trend_flag = recent_up >= (2 if has_qav else 1)
        if has_qav:
            score = volume_ratio * quote_ratio * price_jump.clip(lower=0) * (1 + body_frac)
        else:
            score = volume_ratio * price_jump.clip(lower=0)
        threshold = score.shift(1).rolling(80, min_periods=20).quantile(0.92)
        raw = (
            vol_spike & quote_spike & jump_flag & range_flag & body_flag
            & trend_flag & score.notna() & (score >= threshold.fillna(0.0))
        )
        qualified = bool(raw.iloc[-1]) and not bool(raw.iloc[-4:-1].any())
        if not qualified:
            return None
        # context gate (l.175-179)
        gate = _allows_long_autotrade(ctx, sym)
        if ctx.valid and not gate:
            return None
        return True, ctx.valid and gate

    def _pt(self, sym: str, ctx: OracleContext, quiet: bool) -> tuple[bool, bool] | None:
        """coinrule/price_tracker.py: (fired, autotrade)."""
        df = self.store5.frames[sym]
        close = df["close"]
        if len(df) < 30 or not ctx.valid:
            return None
        rsi = _rsi14_sma(close)
        if rsi is None:
            return None
        macd = float(
            (
                close.ewm(span=12, adjust=False, min_periods=1).mean()
                - close.ewm(span=26, adjust=False, min_periods=1).mean()
            ).iloc[-1]
        )
        tp = (df["high"] + df["low"] + df["close"]) / 3.0
        flow = tp * df["volume"]
        tp_delta = tp.diff()
        last14 = tp_delta.tail(14)
        if last14.isna().any() or len(last14) < 14:
            return None
        pos = float(flow.tail(14)[last14 > 0].sum())
        neg = float(flow.tail(14)[last14 < 0].sum())
        total = pos + neg
        mfi = 100.0 * pos / total if total != 0 else 50.0

        if not (rsi < 30.0 and macd < 0.0 and mfi < 20.0):
            return None
        # telemetry gates (l.229-234)
        ema9 = float(close.ewm(span=9, adjust=False, min_periods=1).mean().iloc[-1])
        ema21 = float(close.ewm(span=21, adjust=False, min_periods=1).mean().iloc[-1])
        trend_score = (ema9 - ema21) / abs(ema21) if ema21 != 0 else 0.0
        f = ctx.features.get(sym)
        rs = f.relative_strength_vs_btc if f else 0.0
        cs = _context_score(ctx, is_short=False, symbol_rs=rs, symbol_trend=trend_score)
        if not (
            cs["followthrough"] >= -0.2
            and cs["risk"] <= 0.6
            and cs["confidence"] >= 0.5
        ):
            return None
        # cooldown on close_time (l.78-94)
        close_time = int(df["open_time"].iloc[-1]) // 1000 + FIVE_MIN_S
        last = self.pt_last_close.get(sym)
        if last is not None and 0 <= close_time - last < 12 * FIVE_MIN_S:
            return None
        self.pt_last_close[sym] = close_time
        # routing (l.96-155)
        stable_breadth = (
            0.48 <= ctx.advancers_ratio <= 0.62
            and abs(ctx.long_tailwind - ctx.short_tailwind) <= 0.35
        )
        autotrade = (
            not ctx.regime_is_transitioning
            and ctx.market_stress_score < 0.3
            and stable_breadth
            and ctx.market_regime == int(MarketRegimeCode.RANGE)
            and f is not None
            and f.valid
            and f.micro_regime >= 0
            and f.micro_transition
            not in (
                int(MicroTransitionCode.BREAKDOWN),
                int(MicroTransitionCode.VOLATILITY_EXPANSION),
            )
            and rs > 0.005
            and f.micro_regime == int(MicroRegimeCode.RANGE)
        )
        return True, autotrade and not quiet

    def _lsp(
        self,
        sym: str,
        ctx: OracleContext,
        oi_growth: float,
        adp_latest: float,
        adp_prev: float,
        btc_momentum: float,
    ) -> tuple[bool, bool, int] | None:
        """liquidation_sweep_pump.py: (fired, autotrade, direction)."""
        df = self.store15.frames[sym]
        wh = 3
        volume, close = df["volume"], df["close"]
        high, low = df["high"], df["low"]
        rel_volume = volume / volume.rolling(wh * 2).mean().shift(wh)
        momentum = close / close.shift(wh) - 1.0
        range_frac = (
            high.rolling(wh * 2).max() - low.rolling(wh * 2).min()
        ) / close
        oi_factor = 1.0 + max(0.0, oi_growth - 1.0) if math.isfinite(oi_growth) else 1.0
        pump_score = rel_volume * (1.0 + momentum) * oi_factor / range_frac
        smooth = pump_score.rolling(2).mean()
        recent = smooth.tail(48).to_numpy()
        finite = recent[np.isfinite(recent)]
        latest_smooth = float(smooth.iloc[-1]) if len(smooth) else float("nan")
        if not (math.isfinite(latest_smooth) and len(finite)):
            return None
        threshold = float(np.quantile(finite, 0.80))
        trigger_score = max(latest_smooth, _nz(pump_score.iloc[-1], -np.inf))
        if trigger_score < threshold:
            return None
        if math.isfinite(oi_growth) and oi_growth < 1.02:
            return None
        # breadth-fade routing (l.76-108)
        if not ctx.valid or ctx.market_stress_score >= 0.35:
            return None
        has_pair = math.isfinite(adp_prev)
        falling = has_pair and adp_latest < adp_prev
        increasing = has_pair and adp_latest > adp_prev
        btc_stalled = abs(btc_momentum) <= 0.002
        f = ctx.features.get(sym)
        weak = (
            f is not None
            and f.valid
            and f.relative_strength_vs_btc <= 0
            and (
                f.trend_score <= 0
                or not f.above_ema20
                or f.micro_regime != int(MicroRegimeCode.TREND_UP)
            )
        )
        hot = adp_latest > 0.3
        washed = adp_latest <= -0.4
        short_ok = hot and falling and btc_stalled and f is not None and f.valid and weak
        long_ok = washed and increasing and btc_momentum > 0
        if not (short_ok or long_ok):
            return None
        direction = int(Direction.SHORT) if short_ok else int(Direction.LONG)
        return True, True, direction

    def _mrf(self, sym: str) -> tuple[bool, bool, int] | None:
        """mean_reversion_fade.py: (fired, autotrade, direction)."""
        if not self.is_futures:
            return None
        df = self.store15.frames[sym]
        close, open_ = df["close"], df["open"]
        delta = close.diff()
        gain = delta.clip(lower=0)
        loss = (-delta).clip(lower=0)
        avg_gain = gain.ewm(alpha=1 / 14, adjust=False, min_periods=14).mean().iloc[-1]
        avg_loss = loss.ewm(alpha=1 / 14, adjust=False, min_periods=14).mean().iloc[-1]
        volume_ma = df["volume"].rolling(20).mean().iloc[-1]
        tail = df.tail(35)
        prev_close = tail["close"].shift(1)
        tr = pd.concat(
            [
                tail["high"] - tail["low"],
                (tail["high"] - prev_close).abs(),
                (tail["low"] - prev_close).abs(),
            ],
            axis=1,
        ).max(axis=1).iloc[1:]
        atr_series = tr.rolling(14).mean()
        atr = atr_series.iloc[-1]
        atr_ma = atr_series.rolling(20).mean().iloc[-1]
        if not all(
            math.isfinite(_nz(v, np.nan))
            for v in (avg_gain, avg_loss, volume_ma, atr, atr_ma)
        ):
            return None
        denom = avg_gain + avg_loss
        rsi = 100.0 * avg_gain / denom if denom != 0 else 50.0
        if not (atr < 2.0 * atr_ma):
            return None
        if not (df["volume"].iloc[-1] >= volume_ma):
            return None
        mid = close.rolling(20).mean().iloc[-1]
        std = close.rolling(20).std(ddof=0).iloc[-1]
        if not (math.isfinite(_nz(mid, np.nan)) and math.isfinite(_nz(std, np.nan))):
            return None
        bb_upper, bb_lower = mid + 2 * std, mid - 2 * std
        c, o = float(close.iloc[-1]), float(open_.iloc[-1])
        long_setup = rsi <= 25.0 and c <= bb_lower and c > o
        short_setup = rsi >= 75.0 and c >= bb_upper and c < o
        if not (long_setup or short_setup):
            return None
        open_time = int(df["open_time"].iloc[-1]) // 1000
        if self.mrf_last_open.get(sym) == open_time:
            return None
        self.mrf_last_open[sym] = open_time
        direction = int(Direction.SHORT) if short_setup else int(Direction.LONG)
        return True, True, direction

    def _ladder(
        self, sym: str, ctx: OracleContext, grid_policy_allows: bool
    ) -> tuple[bool, bool] | None:
        """grid/ladder_deployer.py: (fired, autotrade)."""
        if not (self.is_futures and grid_policy_allows and ctx.valid):
            return None
        f = ctx.features.get(sym)
        if f is None or not f.valid:
            return None
        if f.micro_regime not in (
            int(MicroRegimeCode.RANGE),
            int(MicroRegimeCode.TRANSITIONAL),
        ):
            return None
        if f.micro_transition in (
            int(MicroTransitionCode.BREAKDOWN),
            int(MicroTransitionCode.VOLATILITY_EXPANSION),
            int(MicroTransitionCode.ENTERED_TREND_DOWN),
        ):
            return None
        if ctx.long_regime_score < 0.2:
            return None
        df = self.store15.frames[sym]
        if len(df) < 27:
            return None
        close = df["close"]
        mid = close.rolling(20).mean()
        std = close.rolling(20).std(ddof=0)
        widths = ((mid + 2 * std) - (mid - 2 * std)) / mid
        w = widths.tail(8)
        if len(w) < 8 or not bool((np.isfinite(w) & (w > 0)).all()):
            return None
        change_pct = abs(
            (float(w.iloc[-1]) - float(w.iloc[0]))
            / (float(w.iloc[0]) if w.iloc[0] != 0 else 1.0)
        ) * 100.0
        if change_pct > 20.0:
            return None
        range_low = float((mid - 2 * std).iloc[-1])
        range_high = float((mid + 2 * std).iloc[-1])
        price = float(close.iloc[-1])
        if not (range_low < price < range_high):
            return None
        bb_mid = float(mid.iloc[-1])
        width_pct = (range_high - range_low) / bb_mid * 100.0 if bb_mid > 0 else 0.0
        if not (1.5 <= width_pct <= 8.0):
            return None
        return True, True

    # -- dormant-set oracles (VERDICT r2 item 6) ---------------------------

    def _btd(
        self, sym: str, ctx: OracleContext, quiet: bool
    ) -> tuple[bool, bool] | None:
        """coinrule/buy_the_dip.py: −2..−5% dip over 24×15m bars + reclaim
        of prev close AND EMA20; trend regimes blocked; RANGE/TRANSITIONAL
        autotrade."""
        df = self.store15.frames[sym]
        lookback = 24
        if len(df) <= lookback:
            return None
        # go-live gate (buy_the_dip.py:34,147-149: START_TIME 2026-04-12
        # 23:21 UTC, judged on the bar's close_time)
        if int(df["open_time"].iloc[-1]) // 1000 + 900 < 1_776_036_060:
            return None
        close = df["close"]
        current = float(close.iloc[-1])
        reference = float(close.iloc[-1 - lookback])
        if not math.isfinite(reference) or reference == 0:
            return None
        change_6h = (current - reference) / abs(reference) * 100.0
        if not (-5.0 < change_6h <= -2.0):
            return None
        ema20 = float(close.ewm(span=20, adjust=False, min_periods=1).mean().iloc[-1])
        prev_close = float(close.iloc[-2])
        if not (current > prev_close and current > ema20):
            return None
        f = ctx.features.get(sym)
        R, M = MarketRegimeCode, MicroRegimeCode
        market_trend_blocked = ctx.valid and ctx.market_regime in (
            int(R.TREND_DOWN), int(R.TREND_UP),
        )
        symbol_trend_blocked = (
            f is not None
            and f.valid
            and f.micro_regime in (int(M.TREND_DOWN), int(M.TREND_UP))
        )
        if market_trend_blocked or symbol_trend_blocked:
            return None
        market_rt = ctx.market_regime in (int(R.RANGE), int(R.TRANSITIONAL))
        if f is not None and f.valid:
            micro_blocked = f.micro_regime in (
                int(M.TREND_DOWN), int(M.TREND_UP), int(M.VOLATILE),
            )
            micro_ok = (
                not micro_blocked
                and f.micro_regime in (int(M.RANGE), int(M.TRANSITIONAL))
            )
        else:
            micro_ok = True
        autotrade = (
            ctx.valid
            and not ctx.regime_is_transitioning
            and ctx.market_stress_score < 0.35
            and market_rt
            and micro_ok
            and not quiet
        )
        return True, autotrade

    def _bbx(self, sym: str, ctx: OracleContext) -> tuple[bool, bool, int] | None:
        """coinrule/bb_extreme_reversion.py: Connors RSI(2) ≤5/≥95 at or
        beyond the Bollinger bands; direction-specific routing."""
        df = self.store15.frames[sym]
        close = df["close"]
        delta = close.diff()
        gain = delta.clip(lower=0).rolling(2, min_periods=2).mean().iloc[-1]
        loss = (-delta).clip(lower=0).rolling(2, min_periods=2).mean().iloc[-1]
        if not (math.isfinite(_nz(gain, np.nan)) and math.isfinite(_nz(loss, np.nan))):
            return None
        if loss == 0:
            if gain == 0:
                return None  # flat: RSI undefined (device: NaN)
            rsi2 = 100.0
        else:
            rsi2 = clamp(100.0 - 100.0 / (1.0 + gain / loss), 0.0, 100.0)
        mid = close.rolling(20).mean().iloc[-1]
        std = close.rolling(20).std(ddof=0).iloc[-1]
        if not (math.isfinite(_nz(mid, np.nan)) and math.isfinite(_nz(std, np.nan))):
            return None
        bb_upper, bb_lower = mid + 2 * std, mid - 2 * std
        span = bb_upper - bb_lower
        if not span > 0:
            return None
        price = float(close.iloc[-1])
        band_position = (price - bb_lower) / span
        buy = rsi2 <= 5.0 and band_position <= 0.0
        sell = rsi2 >= 95.0 and band_position >= 1.0
        if not (buy or sell):
            return None
        f = ctx.features.get(sym)
        M, T = MicroRegimeCode, MicroTransitionCode
        base_ok = (
            ctx.valid
            and not ctx.regime_is_transitioning
            and ctx.market_stress_score < 0.35
            and ctx.market_regime == int(MarketRegimeCode.RANGE)
        )
        directional_ok = False
        if f is not None and f.valid:
            trans_blocked = f.micro_transition in (
                int(T.VOLATILITY_EXPANSION), int(T.BREAKDOWN),
                int(T.ENTERED_TRANSITIONAL),
            )
            if sell:
                direction_micro_ok = f.micro_regime in (
                    int(M.RANGE), int(M.TRANSITIONAL), int(M.TREND_DOWN),
                )
            else:
                direction_micro_ok = f.micro_regime != int(M.TREND_DOWN)
            directional_ok = (
                not trans_blocked
                and f.micro_strength >= 0.5
                and direction_micro_ok
            )
        direction = int(Direction.SHORT) if sell else int(Direction.LONG)
        return True, base_ok and directional_ok, direction

    def _rbr(self, sym: str, ctx: OracleContext) -> tuple[bool, bool, int] | None:
        """range_bb_rsi_mean_reversion.py: RANGE×RANGE fade — rolling-sum
        ADX<32 veto, ±2σ z-score, wick-rejection candle filters."""
        f = ctx.features.get(sym)
        M, T = MicroRegimeCode, MicroTransitionCode
        if not (
            ctx.valid
            and ctx.market_stress_score < 0.35
            and ctx.market_regime == int(MarketRegimeCode.RANGE)
            and f is not None
            and f.valid
            and f.micro_regime == int(M.RANGE)
            and f.micro_transition
            not in (
                int(T.BREAKOUT_UP), int(T.BREAKDOWN), int(T.VOLATILITY_EXPANSION),
            )
            and f.atr_pct <= 0.04
            and f.bb_width <= 0.08
        ):
            return None
        df = self.store15.frames[sym]
        if len(df) < 40:
            return None
        close, high, low, open_ = df["close"], df["high"], df["low"], df["open"]
        rsi = _rsi14_sma(close)
        if rsi is None:
            return None
        # inline rolling-SUM ADX (NOT Wilder EWM; reference l.101-128).
        # sdiv mirrors the device's jsafe_div: 0 where the denominator is
        # exactly 0, NaN propagation elsewhere.
        def sdiv(a, b):
            a, b = np.asarray(a, float), np.asarray(b, float)
            ok = b != 0
            with np.errstate(invalid="ignore", divide="ignore"):
                return np.where(ok, a / np.where(ok, b, 1.0), 0.0)

        hd = high.diff()
        ld = low.shift(1) - low
        plus_dm = hd.where((hd > ld) & (hd > 0), 0.0).fillna(0.0)
        minus_dm = ld.where((ld > hd) & (ld > 0), 0.0).fillna(0.0)
        pc = close.shift(1)
        tr = pd.concat(
            [high - low, (high - pc).abs(), (low - pc).abs()], axis=1
        ).max(axis=1)
        tr = tr.where(pc.notna(), high - low)
        atr_sum = tr.rolling(14).sum().to_numpy()
        plus_di = 100.0 * sdiv(plus_dm.rolling(14).sum().to_numpy(), atr_sum)
        minus_di = 100.0 * sdiv(minus_dm.rolling(14).sum().to_numpy(), atr_sum)
        di_total = plus_di + minus_di
        with np.errstate(invalid="ignore"):
            dx = np.where(
                di_total != 0, 100.0 * sdiv(np.abs(plus_di - minus_di), di_total), 0.0
            )
        dx = np.where(np.isfinite(atr_sum), dx, np.nan)
        adx = _nz(pd.Series(dx).rolling(14).mean().iloc[-1], 100.0)
        if adx > 32.0:
            return None
        mean = close.rolling(20).mean().iloc[-1]
        std = close.rolling(20).std(ddof=0).iloc[-1]
        if math.isfinite(_nz(std, np.nan)) and std > 0:
            z = (float(close.iloc[-1]) - mean) / std
        else:
            z = 0.0
        mid = mean
        bb_std = std
        if not (math.isfinite(_nz(mid, np.nan)) and math.isfinite(_nz(bb_std, np.nan))):
            return None
        bb_upper, bb_lower = mid + 2 * bb_std, mid - 2 * bb_std
        c, o = float(close.iloc[-1]), float(open_.iloc[-1])
        h, lo_ = float(high.iloc[-1]), float(low.iloc[-1])
        candle_range = h - lo_
        if not candle_range > 0:
            return None
        lower_wick = min(o, c) - lo_
        upper_wick = h - max(o, c)
        close_position = (c - lo_) / candle_range
        bullish_rej = (
            lo_ <= bb_lower * 1.002
            and c > o
            and lower_wick / candle_range >= 0.30
            and close_position >= 0.55
        )
        bearish_rej = (
            h >= bb_upper * (1.0 - 0.002)
            and c < o
            and upper_wick / candle_range >= 0.30
            and close_position <= 0.45
        )
        long_setup = c <= mid and rsi <= 35.0 and z <= -2.0 and bullish_rej
        short_setup = c >= mid and rsi >= 65.0 and z >= 2.0 and bearish_rej
        if not (long_setup or short_setup):
            return None
        direction = int(Direction.SHORT) if short_setup else int(Direction.LONG)
        return True, True, direction

    def _twap(self, sym: str) -> tuple[bool, bool] | None:
        """coinrule/twap_momentum_sniper: TWAP(last 20 calendar hours) >
        price, no sharp selloff. Calendar-aligned 15m→1h resample exactly
        as the reference's ``df.resample('1h')``
        (producers/context_evaluator.py:392-395), last (partial) hour
        included, empty hours skipped by the nan-mean — mirroring the
        device's ``_resample_1h`` (strategies/dormant.py)."""
        df15 = self.store15.frames[sym]
        df5 = self.store5.frames.get(sym)
        if df5 is None or len(df5) < 10 or len(df15) < 8:
            return None
        hours = df15["open_time"] // 3_600_000
        grouped = df15.groupby(hours)
        last_hr = int(hours.iloc[-1])
        # the device resamples into twap_window + 2 = 22 hour buckets
        span = pd.RangeIndex(last_hr - 21, last_hr + 1)
        o = grouped["open"].first().reindex(span)
        h = grouped["high"].max().reindex(span)
        lo = grouped["low"].min().reindex(span)
        c = grouped["close"].last().reindex(span)
        bar_avg = ((o + h + lo + c) / 4.0).to_numpy()
        with np.errstate(invalid="ignore"):
            twap = float(np.nanmean(bar_avg[-20:]))
        close_1h = c.to_numpy()
        if not (np.isfinite(close_1h[-1]) and np.isfinite(close_1h[-2])):
            return None
        price = float(df5["close"].iloc[-1])
        # "price_decrease" exactly as written in the reference (l.68-70)
        price_decrease = close_1h[-1] - close_1h[-2] / close_1h[-1]
        if not (twap > price and price_decrease > -0.05):
            return None
        return True, False  # manual_only

    def _sts(
        self,
        sym: str,
        ctx: OracleContext,
        adp_diff: float,
        adp_diff_prev: float,
        dominance_is_losers: bool,
    ) -> tuple[bool, bool] | None:
        """coinrule/supertrend_swing_reversal: supertrend(10,3) uptrend ∧
        RSI(14)<30 ∧ trades>5 ∧ rising ADP twice ∧ LOSERS dominance."""
        if not (
            math.isfinite(adp_diff)
            and math.isfinite(adp_diff_prev)
            and adp_diff > 0
            and adp_diff_prev > 0
            and dominance_is_losers
        ):
            return None
        df = self.store5.frames[sym]
        close, high, low = df["close"], df["high"], df["low"]
        rsi = _rsi14_sma(close)
        trades = float(df["number_of_trades"].iloc[-1])
        if rsi is None or not (rsi < 30.0 and trades > 5):
            return None
        # supertrend(10,3) on the dropna'd enriched frame (coinrule.py:
        # 140-143 via pre_process): the series begins after the ma_100
        # warm-up, 99 rows past the first available bar — the ratchet is
        # path-dependent so the seed point matters (ops supertrend_from)
        if len(df) <= 99:
            return None
        tail_df = df.iloc[99:]
        close, high, low = tail_df["close"], tail_df["high"], tail_df["low"]
        pc = close.shift(1)
        tr = pd.concat(
            [high - low, (high - pc).abs(), (low - pc).abs()], axis=1
        ).max(axis=1)
        tr = tr.where(pc.notna(), high - low)
        atr = tr.ewm(alpha=1.0 / 10, adjust=False, min_periods=10).mean()
        hl2 = (high + low) / 2.0
        upper = (hl2 + 3.0 * atr).to_numpy()
        lower = (hl2 - 3.0 * atr).to_numpy()
        closes = close.to_numpy()
        fu, fl, d, prev_close = np.inf, -np.inf, 1.0, 0.0
        for ub, lb, cl in zip(upper, lower, closes):
            ub = ub if math.isfinite(ub) else np.inf
            lb = lb if math.isfinite(lb) else -np.inf
            fu = ub if (ub < fu or prev_close > fu) else fu
            fl = lb if (lb > fl or prev_close < fl) else fl
            d = 1.0 if cl > fu else (-1.0 if cl < fl else d)
            prev_close = cl
        st_up = math.isfinite(float(atr.iloc[-1])) and d > 0
        if not st_up:
            return None
        # autotrade via the standard long gate; an invalid context passes
        # (device: jnp.where(context.valid, long_gate, True))
        autotrade = _allows_long_autotrade(ctx, sym) if ctx.valid else True
        return True, autotrade

    def _blsh(
        self, sym: str, market_domination_reversal: bool
    ) -> tuple[bool, bool] | None:
        """coinrule/buy_low_sell_high: RSI(14)<35 ∧ price>MA25 ∧
        domination reversal; telemetry-only."""
        if not market_domination_reversal:
            return None
        df = self.store15.frames[sym]
        close = df["close"]
        rsi = _rsi14_sma(close)
        if rsi is None:
            return None
        ma25 = float(close.rolling(25, min_periods=1).mean().iloc[-1])
        if not (rsi < 35.0 and float(close.iloc[-1]) > ma25):
            return None
        return True, False  # manual_only

    def _ipt(self, sym: str, ctx: OracleContext) -> tuple[bool, bool] | None:
        """inverse_price_tracker: PriceTracker's oversold trio routed to
        TREND_UP / bullish-TRANSITIONAL / RANGE-leader markets;
        telemetry-only."""
        df = self.store5.frames[sym]
        close = df["close"]
        if len(df) < 30:
            return None
        rsi = _rsi14_sma(close)
        if rsi is None:
            return None
        macd = float(
            (
                close.ewm(span=12, adjust=False, min_periods=1).mean()
                - close.ewm(span=26, adjust=False, min_periods=1).mean()
            ).iloc[-1]
        )
        tp = (df["high"] + df["low"] + df["close"]) / 3.0
        flow = tp * df["volume"]
        tp_delta = tp.diff()
        last14 = tp_delta.tail(14)
        if last14.isna().any() or len(last14) < 14:
            return None
        pos = float(flow.tail(14)[last14 > 0].sum())
        neg = float(flow.tail(14)[last14 < 0].sum())
        total = pos + neg
        mfi = 100.0 * pos / total if total != 0 else 50.0
        if not (rsi < 30.0 and macd < 0.0 and mfi < 20.0):
            return None
        f = ctx.features.get(sym)
        M, R = MicroRegimeCode, MarketRegimeCode
        if not (ctx.valid and ctx.market_stress_score < 0.35 and f and f.valid):
            return None
        bullish_transitional_symbol = (
            f.micro_regime == int(M.TRANSITIONAL)
            and f.trend_score > 0
            and f.above_ema20
            and f.relative_strength_vs_btc >= 0
        )
        range_leader = (
            f.micro_regime in (int(M.TREND_UP), int(M.TRANSITIONAL))
            and f.trend_score > 0
            and f.relative_strength_vs_btc >= 0.05
        )
        symbol_ok = f.micro_regime == int(M.TREND_UP) or bullish_transitional_symbol
        routed = (
            ctx.market_regime in (int(R.TREND_UP), int(R.TRANSITIONAL))
            and symbol_ok
        ) or (ctx.market_regime == int(R.RANGE) and range_leader)
        if not routed:
            return None
        # telemetry gates (confidence 0.4 / followthrough -0.1 / risk 0.65)
        ema9 = float(close.ewm(span=9, adjust=False, min_periods=1).mean().iloc[-1])
        ema21 = float(close.ewm(span=21, adjust=False, min_periods=1).mean().iloc[-1])
        trend = (ema9 - ema21) / abs(ema21) if ema21 != 0 else 0.0
        cs = _context_score(
            ctx, is_short=False,
            symbol_rs=f.relative_strength_vs_btc, symbol_trend=trend,
        )
        if not (
            cs["confidence"] >= 0.4
            and cs["followthrough"] >= -0.1
            and cs["risk"] <= 0.65
        ):
            return None
        return True, False  # telemetry-only

    def _rfbf(self, sym: str, ctx: OracleContext) -> tuple[bool, bool] | None:
        """range_failed_breakout_fade: short a fresh bullish spike when the
        market is RANGE with average return < −0.5% and the symbol is an
        outperformer. Mirrors the SpikeHunter detector's flag pipeline
        (strategies/spike_hunter.py detect_spikes — auto-calibrated volume
        cluster, dynamic price break, cumulative break, acceleration)."""
        f = ctx.features.get(sym)
        if not (
            ctx.valid
            and ctx.market_regime == int(MarketRegimeCode.RANGE)
            and ctx.average_return < -0.005
            and f is not None
            and f.valid
            and f.relative_strength_vs_btc >= 0
        ):
            return None
        df = self.store15.frames[sym]
        close, open_, volume = df["close"], df["open"], df["volume"]
        # upward streak: ALL of the last 3 candles green
        if len(df) < 4:
            return None
        c3 = close.tail(3).to_numpy(float)
        o3 = open_.tail(3).to_numpy(float)
        if not bool((c3 > o3).all()):
            return None

        def nanq(arr: np.ndarray, q: float) -> float:
            a = arr[np.isfinite(arr)]
            return float(np.quantile(a, q)) if len(a) else float("nan")

        pc = (close / close.shift(1) - 1.0).to_numpy(float)
        pc_abs = np.abs(pc)
        vma = volume.rolling(12, min_periods=12).mean()
        vr = (volume / (vma + 1e-6)).to_numpy(float)
        pc_last, pc_abs_last, vr_last = pc[-1], pc_abs[-1], vr[-1]

        # auto-calibration over the full stored window. Resolve the NaN
        # fallbacks BEFORE the max (Python's max(a, nan) keeps a, unlike
        # jnp.maximum which propagates NaN — the device's order is
        # quantile → isfinite fallback → max).
        q_vol = nanq(vr, 0.97)
        vol_thr = max(1.15, q_vol) if math.isfinite(q_vol) else 1.6
        q_pf = nanq(pc_abs, 0.75)
        pf = max(0.015, q_pf) if math.isfinite(q_pf) else 0.0
        price_floor = max(0.03, pf)

        # volume cluster: trailing 8 ratios, >=2 crossings and a hot last bar
        vrw = vr[-8:]
        fin = np.isfinite(vrw)
        vc_flag = (
            bool(fin.any())
            and int(np.where(fin, vrw >= vol_thr, False).sum()) >= 2
            and bool(vr_last >= vol_thr)
        )
        # dynamic price break: trailing-60 quantile(0.85), min 20 finite
        t60 = pc_abs[-60:]
        fin60 = t60[np.isfinite(t60)]
        dyn = float(np.quantile(fin60, 0.85)) if len(fin60) >= 20 else float("nan")
        pb_flag = math.isfinite(dyn) and bool(
            pc_abs_last >= max(price_floor, dyn)
        )
        # cumulative break over the trailing 3 bars
        pcw = pc[-3:]
        finw = np.isfinite(pcw)
        vr3 = vr[-3:]
        fin3 = np.isfinite(vr3)
        vol_cond = int(fin3.sum()) >= 3 and bool(
            (vr3[fin3] >= vol_thr * 0.8).any()
        )
        cum_flag = (
            int(finw.sum()) >= 3
            and float(np.maximum(pcw, 0.0)[finw].sum()) >= 0.025
            and vol_cond
        )
        # acceleration: volume-ratio derivative over 3 bars + a real move
        vr_lag = vr[-4] if len(vr) > 3 else float("nan")
        accel_base = (
            math.isfinite(vr_lag)
            and math.isfinite(vr_last)
            and vr_last - vr_lag >= 0.45
            and pc_abs_last >= 0.015
        )
        accel_flag = accel_base and pc_last > 0
        if not (cum_flag or vc_flag or pb_flag or accel_flag):
            return None
        return True, True  # shorts the spike; autotrade on

    def _rsr(self, sym: str, ctx: OracleContext) -> tuple[bool, bool] | None:
        """relative_strength_reversal_range: contrarian long on an RS
        leader during a broad RANGE selloff, volume above the 20th
        percentile of the last 96 bars; telemetry-only."""
        f = ctx.features.get(sym)
        if not (
            ctx.valid
            and ctx.market_regime == int(MarketRegimeCode.RANGE)
            and ctx.average_return < -0.02
            and f is not None
            and f.valid
            and f.relative_strength_vs_btc > 0.05
        ):
            return None
        df = self.store15.frames[sym]
        if len(df) < 96:
            return None
        vol = df["volume"].tail(96).to_numpy(dtype=float)
        finite = vol[np.isfinite(vol)]
        if not len(finite):
            return None
        floor = float(np.quantile(finite, 0.20))
        if not float(df["volume"].iloc[-1]) > floor:
            return None
        return True, False  # telemetry-only

    # -- the tick ----------------------------------------------------------

    def evaluate(
        self,
        now_ms: int,
        quiet: bool | None = None,
        grid_policy_allows: bool = False,
        oi_growth: dict[str, float] | None = None,
        adp_latest: float = float("nan"),
        adp_prev: float = float("nan"),
        adp_diff: float = float("nan"),
        adp_diff_prev: float = float("nan"),
        dominance_is_losers: bool = False,
        market_domination_reversal: bool = False,
    ) -> list[tuple[str, str, str, bool]]:
        """One tick; returns fired (strategy, symbol, direction, autotrade).

        ``quiet=None`` resolves the quiet-hours filter from the evaluated
        tick time and the context built THIS tick — the same inputs the
        device step uses (the strong-trend override is applied against the
        current context on both sides).
        """
        ts_s = now_ms // 1000
        ts15 = ts_s // FIFTEEN_MIN_S * FIFTEEN_MIN_S - FIFTEEN_MIN_S
        ts5 = ts_s // FIVE_MIN_S * FIVE_MIN_S - FIVE_MIN_S

        ctx = self._build_context(ts15)
        if ctx.valid:
            self._last_regime = ctx.market_regime
            self._last_strength = ctx.market_regime_transition_strength
        else:
            self._last_regime = None
            self._last_strength = 0.0

        if quiet is None:
            from datetime import datetime, timezone

            UTC = timezone.utc  # datetime.UTC alias (3.11+) for py3.10
            from binquant_tpu.regime.time_filter import is_autotrade_suppressed

            # judged at the EVALUATED tick time against the context built
            # THIS tick — the reference reads the live context
            # (time_of_day_filter.py:60-76), and so does the device step
            quiet = is_autotrade_suppressed(
                ctx.market_regime if ctx.valid else None,
                ctx.market_regime_transition_strength if ctx.valid else 0.0,
                now=datetime.fromtimestamp(now_ms / 1000, tz=UTC),
            )

        btc_df = self.store15.frames.get(self.btc_symbol)
        btc_momentum = 0.0
        if btc_df is not None and len(btc_df) >= 2:
            prev = float(btc_df["close"].iloc[-2])
            if prev != 0 and math.isfinite(prev):
                btc_momentum = float(btc_df["close"].iloc[-1]) / prev - 1.0

        fresh5 = {
            s
            for s in self.store5.fresh(ts5)
            if len(self.store5.frames[s]) >= MIN_BARS
        }
        fresh15 = {
            s
            for s in self.store15.fresh(ts15)
            if len(self.store15.frames[s]) >= MIN_BARS
        }
        oi = oi_growth or {}

        fired: list[tuple[str, str, str, bool]] = []

        def emit(strategy, sym, direction, autotrade, bar_ts):
            key = (strategy, sym)
            if self.last_emitted.get(key) == bar_ts:
                return
            self.last_emitted[key] = bar_ts
            fired.append((strategy, sym, direction, autotrade))

        if "activity_burst_pump" in self.enabled:
            for sym in sorted(fresh5):
                r = self._abp(sym, ctx)
                if r:
                    emit("activity_burst_pump", sym, "LONG", r[1], ts5)
        if "coinrule_price_tracker" in self.enabled:
            for sym in sorted(fresh5):
                r = self._pt(sym, ctx, quiet)
                if r:
                    emit("coinrule_price_tracker", sym, "LONG", r[1], ts5)
        if "liquidation_sweep_pump" in self.enabled:
            for sym in sorted(fresh15):
                r = self._lsp(
                    sym, ctx, oi.get(sym, float("nan")), adp_latest, adp_prev,
                    btc_momentum,
                )
                if r:
                    emit(
                        "liquidation_sweep_pump", sym,
                        Direction(r[2]).name, r[1], ts15,
                    )
        if "mean_reversion_fade" in self.enabled:
            for sym in sorted(fresh15):
                r = self._mrf(sym)
                if r:
                    emit("mean_reversion_fade", sym, Direction(r[2]).name, r[1], ts15)
        if "grid_ladder" in self.enabled:
            for sym in sorted(fresh15):
                r = self._ladder(sym, ctx, grid_policy_allows)
                if r:
                    emit("grid_ladder", sym, "grid", r[1], ts15)
        # dormant set (enabled_strategies override only)
        if "coinrule_twap_momentum_sniper" in self.enabled:
            for sym in sorted(fresh5):
                r = self._twap(sym)
                if r:
                    emit("coinrule_twap_momentum_sniper", sym, "LONG", r[1], ts5)
        if "coinrule_supertrend_swing_reversal" in self.enabled:
            for sym in sorted(fresh5):
                r = self._sts(
                    sym, ctx, adp_diff, adp_diff_prev, dominance_is_losers
                )
                if r:
                    emit(
                        "coinrule_supertrend_swing_reversal", sym,
                        "LONG", r[1], ts5,
                    )
        if "inverse_price_tracker" in self.enabled:
            for sym in sorted(fresh5):
                r = self._ipt(sym, ctx)
                if r:
                    emit("inverse_price_tracker", sym, "LONG", r[1], ts5)
        if "coinrule_buy_low_sell_high" in self.enabled:
            for sym in sorted(fresh15):
                r = self._blsh(sym, market_domination_reversal)
                if r:
                    emit("coinrule_buy_low_sell_high", sym, "LONG", r[1], ts15)
        if "relative_strength_reversal_range" in self.enabled:
            for sym in sorted(fresh15):
                r = self._rsr(sym, ctx)
                if r:
                    emit(
                        "relative_strength_reversal_range", sym,
                        "LONG", r[1], ts15,
                    )
        if "range_failed_breakout_fade" in self.enabled:
            for sym in sorted(fresh15):
                r = self._rfbf(sym, ctx)
                if r:
                    emit(
                        "range_failed_breakout_fade", sym, "SHORT", r[1], ts15
                    )
        if "coinrule_buy_the_dip" in self.enabled:
            for sym in sorted(fresh15):
                r = self._btd(sym, ctx, quiet)
                if r:
                    emit("coinrule_buy_the_dip", sym, "LONG", r[1], ts15)
        if "bb_extreme_reversion" in self.enabled:
            for sym in sorted(fresh15):
                r = self._bbx(sym, ctx)
                if r:
                    emit(
                        "bb_extreme_reversion", sym,
                        Direction(r[2]).name, r[1], ts15,
                    )
        if "range_bb_rsi_mean_reversion" in self.enabled:
            for sym in sorted(fresh15):
                r = self._rbr(sym, ctx)
                if r:
                    emit(
                        "range_bb_rsi_mean_reversion", sym,
                        Direction(r[2]).name, r[1], ts15,
                    )
        return fired
