#!/usr/bin/env python
"""Render the SLO verdict + per-SLO burn history from the event log.

The unified SLO registry (``binquant_tpu/obs/slo.py``) emits ``slo_burn``
on burn entry (then at the sampling cadence while an outage sustains)
and ``slo_recover`` with the burn length on the first clean observation.
This tool reconstructs the burn/recover story — and the best verdict the
log alone supports — without any service in the loop (golden-pinned like
delivery_report — keep format changes deliberate):

    python tools/slo_report.py /tmp/bqt_events.jsonl
    python tools/slo_report.py events.jsonl --slo delivery.autotrade

The live ``GET /debug/slo`` route is the authoritative verdict (it folds
the in-process invariant probes too); this report is the post-mortem
view — which SLOs burned, for how long, and whether the log ends with
any still burning.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

SLO_EVENTS = ("slo_burn", "slo_recover")


def load_slo_events(path: str | Path) -> list[dict]:
    """All SLO events, in file order; corrupt lines (a torn write at
    rotation) are skipped, not fatal."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if record.get("event") in SLO_EVENTS:
                out.append(record)
    return out


def render_report(events: list[dict], slo: str | None = None) -> str:
    """The deterministic report: a burn/recover timeline, then per-SLO
    episode tallies and the log-tail verdict (BURNING when any SLO's
    last event is a burn with no recover after it)."""
    lines: list[str] = []
    # name -> {"kind","budget","unit","burns","recovers","burn_obs_total",
    #          "longest_burn","burning"}
    tally: dict[str, dict] = {}
    for e in events:
        name = e.get("slo", "?")
        if slo and name != slo:
            continue
        cell = tally.setdefault(
            name,
            {
                "kind": e.get("kind", "?"),
                "budget": e.get("budget"),
                "unit": e.get("unit", ""),
                "burns": 0,
                "recovers": 0,
                "burn_obs_total": 0,
                "longest_burn": 0,
                "burning": False,
            },
        )
        if e.get("event") == "slo_burn":
            cell["burning"] = True
            if e.get("budget") is not None:
                cell["budget"] = e["budget"]
            if e.get("unit"):
                cell["unit"] = e["unit"]
            if e.get("entering"):
                cell["burns"] += 1
                lines.append(
                    f"burn     {name:<22} kind={e.get('kind', '?')}"
                    f" budget={e.get('budget')}{e.get('unit', '')}"
                )
            else:
                lines.append(
                    f"burning  {name:<22} still breaching"
                    f" (obs {e.get('burn_obs', '?')})"
                )
        else:  # slo_recover
            cell["burning"] = False
            cell["recovers"] += 1
            obs = int(e.get("burn_obs", 0) or 0)
            cell["burn_obs_total"] += obs
            cell["longest_burn"] = max(cell["longest_burn"], obs)
            lines.append(
                f"recover  {name:<22} after {obs} breaching obs"
            )
    if tally:
        lines.append("")
        lines.append(
            f"{'slo':<22} {'kind':<10} {'budget':>10} {'burns':>6}"
            f" {'recovers':>8} {'longest':>8}  status"
        )
        for name in sorted(tally):
            cell = tally[name]
            budget = (
                f"{cell['budget']}{cell['unit']}"
                if cell["budget"] is not None
                else "?"
            )
            status = "BURNING" if cell["burning"] else "ok"
            lines.append(
                f"{name:<22} {cell['kind']:<10} {budget:>10}"
                f" {cell['burns']:>6} {cell['recovers']:>8}"
                f" {cell['longest_burn']:>8}  {status}"
            )
        burning = sorted(n for n, c in tally.items() if c["burning"])
        lines.append(
            "verdict  BURNING (" + ", ".join(burning) + ")"
            if burning
            else f"verdict  ok ({len(tally)} slo"
            + ("s" if len(tally) != 1 else "")
            + " clean at log tail)"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("log", help="JSONL event log (BQT_EVENT_LOG file)")
    parser.add_argument("--slo", help="render only this SLO's history")
    args = parser.parse_args(argv)

    events = load_slo_events(args.log)
    if not events:
        print(f"no slo events in {args.log}", file=sys.stderr)
        return 1
    print(render_report(events, slo=args.slo))
    return 0


if __name__ == "__main__":
    sys.exit(main())
