"""WebSocket/SSE broadcast hub + the frame outbox (ISSUE 14).

The serving half of the fan-out plane: subscribers connect over
WebSocket (``GET /ws?user=<id>[&cursor=<c>]``) or Server-Sent Events
(``GET /sse?user=<id>[&cursor=<c>]``, ``Last-Event-ID`` honored) and
receive exactly the signal frames the device match kernel addressed to
them. Stdlib-only asyncio, the :class:`~binquant_tpu.obs.exposition.MetricsServer`
idiom — the image carries no websocket package, and RFC 6455's server
side is ~a hundred lines.

Backpressure contract (the PR-13 policy table's "lossy" class, per
connection): every connection owns a BOUNDED queue drained by its writer
task. A slow or stalled consumer fills its queue and overflow frames are
shed with a counted reason (``bqt_fanout_shed_total{reason=slow_consumer}``)
and the connection marked ``gapped`` — the tick thread (and every other
subscriber) never waits. A gapped client recovers by reconnecting with a
cursor: the hub replays the gap from the :class:`BroadcastOutbox` (the
fan-out tier's counterpart of the delivery WAL — append-only JSONL with
packed recipient words per frame, size-bounded by an O(1) two-generation
file swap).

Cursor semantics: every frame carries a monotonically increasing
``seq`` (the SSE ``id``), and frames also carry their ``trace_id`` /
``tick_seq`` provenance stamps; ``cursor=<seq>`` resumes strictly after
that frame, and ``cursor=<trace_id>/<tick_seq>`` resolves through the
outbox to the LAST frame of that traced tick (at-least-once within a
tick — downstream dedupe on the provenance key, the PR-3/PR-13
convention).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import logging
import os
import time
from collections import deque
from pathlib import Path
from urllib.parse import parse_qs

import numpy as np

from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import (
    DELIVERY_CURSOR_LAG,
    FANOUT_CONN_QUEUE_DEPTH,
    FANOUT_CONNECTIONS,
    FANOUT_FRAMES,
    FANOUT_RESUME_FALLBACK,
    FANOUT_RESUME_REPLAYED,
    FANOUT_SHED,
    FANOUT_WRITE_LATENCY,
)

log = logging.getLogger(__name__)

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


# -- RFC 6455 codec helpers (server side + the drill's test client) ----------


def ws_accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _WS_MAGIC).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def ws_encode_frame(
    payload: bytes, opcode: int = 0x1, mask: bytes | None = None
) -> bytes:
    """One FIN frame. Servers send unmasked; the drill's client passes a
    4-byte ``mask`` (clients MUST mask per RFC 6455 §5.3)."""
    head = bytearray([0x80 | (opcode & 0x0F)])
    mask_bit = 0x80 if mask is not None else 0
    n = len(payload)
    if n < 126:
        head.append(mask_bit | n)
    elif n < 1 << 16:
        head.append(mask_bit | 126)
        head += n.to_bytes(2, "big")
    else:
        head.append(mask_bit | 127)
        head += n.to_bytes(8, "big")
    if mask is not None:
        head += mask
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return bytes(head) + payload


async def ws_read_frame(reader: asyncio.StreamReader) -> tuple[int, bytes]:
    """Read one frame → (opcode, unmasked payload). Raises
    ``ConnectionError`` on EOF mid-frame."""
    try:
        b0, b1 = await reader.readexactly(2)
    except asyncio.IncompleteReadError as exc:
        raise ConnectionError("ws peer closed") from exc
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        n = int.from_bytes(await reader.readexactly(2), "big")
    elif n == 127:
        n = int.from_bytes(await reader.readexactly(8), "big")
    mask = await reader.readexactly(4) if masked else None
    payload = await reader.readexactly(n) if n else b""
    if mask:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, payload


# -- outbox ------------------------------------------------------------------


class BroadcastOutbox:
    """Append-only JSONL log of broadcast frames + their packed recipient
    words — what a reconnecting client's cursor replays from. Lossy-tier
    durability: flushed per append, NOT fsynced (a host crash may lose the
    tail; the delivery WAL owns the at-least-once class). Size-bounded by
    a two-generation swap: when the live file reaches ``cap`` entries it
    is renamed to ``<path>.1`` (dropping the previous generation) and
    appends continue into a fresh live file — rotation is one O(1)
    ``os.replace``, never a content rewrite on the tick finalize path (at
    1M-subscription scale a line carries ~170 KB of packed words; a
    rewrite there would stall finalize for the whole retained window).
    Total retention stays within ``cap``..``2 × cap`` entries."""

    def __init__(self, path: str | Path, cap: int = 4096) -> None:
        self.path = Path(path)
        self.cap = max(int(cap), 1)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._gen1 = self.path.with_name(self.path.name + ".1")
        self._lines = sum(1 for _ in open(self.path)) if self.path.exists() else 0
        self._f = open(self.path, "a", encoding="utf-8")
        self.appends = 0
        self.rotations = 0

    def append(self, frame: dict, words: np.ndarray) -> None:
        rec = {
            "frame": frame,
            "w": base64.b64encode(
                np.ascontiguousarray(words, np.uint32).tobytes()
            ).decode("ascii"),
        }
        self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._f.flush()
        self.appends += 1
        self._lines += 1
        if self._lines >= self.cap:
            self._rotate()

    def _rotate(self) -> None:
        self._f.close()
        os.replace(self.path, self._gen1)
        self._f = open(self.path, "a", encoding="utf-8")
        self._lines = 0
        self.rotations += 1

    def _scan(self) -> list[str]:
        out: list[str] = []
        for p in (self._gen1, self.path):  # gen-1 is strictly older
            if not p.exists():
                continue
            with open(p, encoding="utf-8") as f:
                out.extend(
                    line.rstrip("\n") for line in f if line.strip()
                )
        return out

    def entries(self) -> list[tuple[dict, np.ndarray]]:
        """Every (frame, recipient words) pair in append order; torn
        lines skipped."""
        out = []
        for raw in self._scan():
            try:
                rec = json.loads(raw)
                words = np.frombuffer(
                    base64.b64decode(rec["w"]), np.uint32
                )
                out.append((rec["frame"], words))
            except (ValueError, KeyError):
                continue
        return out

    def last_seq(self) -> int:
        """Highest frame seq in the log (-1 when empty) — what a plane
        reopening a persistent outbox seeds its counter PAST, so
        post-restart frames never collide with retained ones (a collision
        would silently hide them from every cursor replay)."""
        best = -1
        for frame, _ in self.entries():
            best = max(best, int(frame.get("seq", -1)))
        return best

    def resolve_cursor(
        self, cursor: str, entries: list | None = None
    ) -> int | None:
        """Cursor string → frame seq to resume AFTER. ``"17"`` is a frame
        seq; ``"<trace_id>/<tick_seq>"`` resolves to that traced tick's
        LAST frame in the log (None = unresolvable — caller treats the
        connect as cursor-less). ``entries`` reuses a caller's scan."""
        cursor = cursor.strip()
        if not cursor:
            return None
        try:
            return int(cursor)
        except ValueError:
            pass
        if "/" not in cursor:
            return None
        trace_id, _, tick_s = cursor.rpartition("/")
        try:
            tick_seq = int(tick_s)
        except ValueError:
            return None
        best = None
        for frame, _ in entries if entries is not None else self.entries():
            if (
                frame.get("trace_id") == trace_id
                and frame.get("tick_seq") == tick_seq
            ):
                best = int(frame["seq"])
        return best

    def replay_after(
        self, seq: int, slot: int, entries: list | None = None
    ) -> list[dict]:
        """Frames with ``seq`` strictly greater whose recipient bit for
        ``slot`` is set — a reconnect's gap. ``entries`` reuses a
        caller's scan."""
        w, bit = slot >> 5, np.uint32(1 << (slot & 31))
        out = []
        for frame, words in entries if entries is not None else self.entries():
            if int(frame.get("seq", -1)) <= seq:
                continue
            if w < len(words) and (words[w] & bit):
                out.append(frame)
        return out

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # pragma: no cover
            pass


class ShardedBroadcastOutbox:
    """``BroadcastOutbox`` split into per-shard partitions under ONE
    global cursor (ISSUE 19). Appends route by the firing symbol's shard
    (``shard_of(frame)``) into ``<path>.pK-of-N`` partition logs — on a
    pod each process would append only the frames of rows it owns — while
    every read-side method (``entries``/``last_seq``/``resolve_cursor``/
    ``replay_after``) serves the MERGED, seq-ordered stream, so the
    fan-out hub sees one coherent subscriber population and cursors from
    unsharded deployments keep resolving unchanged.

    Reshard story mirrors the checkpoint's: partition files from a
    PREVIOUS partition count (and any legacy single-file log at ``path``
    itself) are folded in read-only as "retired" sources — their frames
    stay cursor-replayable and seed ``last_seq`` so new frames never
    collide — while appends go only to the current N live partitions.
    Retired files are bounded by their own old rotation caps and age out
    when their retention window ends.

    Duck-typed drop-in for :class:`BroadcastOutbox` (the hub and plane
    consume only the shared interface); ``cap`` bounds EACH partition,
    keeping total retention ``N × cap .. 2N × cap``."""

    def __init__(
        self,
        path: str | Path,
        n_shards: int,
        cap: int = 4096,
        shard_of=None,
    ) -> None:
        self.path = Path(path)
        self.n_shards = max(int(n_shards), 1)
        self.cap = max(int(cap), 1)
        self._shard_of = shard_of
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._parts = [
            BroadcastOutbox(
                self.path.with_name(
                    f"{self.path.name}.p{k}-of-{self.n_shards}"
                ),
                cap=self.cap,
            )
            for k in range(self.n_shards)
        ]
        # retired read-only sources: a legacy single-file outbox at the
        # base path (+ its .1 generation) and partitions of a different
        # previous count
        self._retired: list[Path] = []
        for p in (
            self.path.with_name(self.path.name + ".1"),
            self.path,
        ):
            if p.exists() and p.is_file():
                self._retired.append(p)
        live = {part.path.name for part in self._parts} | {
            part._gen1.name for part in self._parts
        }
        for p in sorted(self.path.parent.glob(f"{self.path.name}.p*-of-*")):
            if p.name not in live and p.is_file():
                self._retired.append(p)

    @property
    def appends(self) -> int:
        return sum(p.appends for p in self._parts)

    @property
    def rotations(self) -> int:
        return sum(p.rotations for p in self._parts)

    def _route(self, frame: dict) -> int:
        if self._shard_of is not None:
            try:
                k = int(self._shard_of(frame))
                if 0 <= k < self.n_shards:
                    return k
            except Exception:
                pass
        # stable fallback: hash the symbol name (deterministic across
        # restarts — routing only balances load, merge order is by seq)
        sym = str(frame.get("symbol", ""))
        return sum(sym.encode()) % self.n_shards

    def append(self, frame: dict, words: np.ndarray) -> None:
        self._parts[self._route(frame)].append(frame, words)

    def _retired_entries(self) -> list[tuple[dict, np.ndarray]]:
        out = []
        for p in self._retired:
            if not p.exists():
                continue
            try:
                with open(p, encoding="utf-8") as f:
                    lines = [ln.rstrip("\n") for ln in f if ln.strip()]
            except OSError:
                continue
            for raw in lines:
                try:
                    rec = json.loads(raw)
                    words = np.frombuffer(
                        base64.b64decode(rec["w"]), np.uint32
                    )
                    out.append((rec["frame"], words))
                except (ValueError, KeyError):
                    continue
        return out

    def entries(self) -> list[tuple[dict, np.ndarray]]:
        """Every partition's (frame, words) pairs merged into ONE stream
        ordered by the plane's global seq — the single coherent cursor
        timeline subscribers replay against."""
        out = self._retired_entries()
        for part in self._parts:
            out.extend(part.entries())
        out.sort(key=lambda e: int(e[0].get("seq", -1)))
        return out

    def last_seq(self) -> int:
        best = -1
        for frame, _ in self.entries():
            best = max(best, int(frame.get("seq", -1)))
        return best

    def resolve_cursor(
        self, cursor: str, entries: list | None = None
    ) -> int | None:
        ents = entries if entries is not None else self.entries()
        return BroadcastOutbox.resolve_cursor(self, cursor, ents)

    def replay_after(
        self, seq: int, slot: int, entries: list | None = None
    ) -> list[dict]:
        ents = entries if entries is not None else self.entries()
        return BroadcastOutbox.replay_after(self, seq, slot, ents)

    def close(self) -> None:
        for part in self._parts:
            part.close()


# -- connections -------------------------------------------------------------


class _Connection:
    def __init__(
        self, user_id: str, slot: int, transport: str, queue_max: int
    ) -> None:
        self.user_id = user_id
        self.slot = int(slot)
        self.transport = transport  # "ws" | "sse"
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=max(queue_max, 1))
        self.delivered = 0
        self.dropped = 0
        self.replayed = 0
        self.gapped = False
        self.lag_ms_sum = 0.0
        self.lag_ms_max = 0.0
        # highest frame seq WRITTEN to this peer's socket — the hub's
        # cursor-lag watermark compares it against the outbox head to
        # report how far the laggiest consumer trails (ISSUE 16)
        self.last_seq = -1
        self.closed = asyncio.Event()
        # set by FanoutHub._close_conn: the close bookkeeping (per-user
        # totals fold + conn_close event) must run exactly once whether
        # the handler's finally or hub.stop() gets there first
        self.finalized = False

    def offer(self, item: tuple) -> bool:
        try:
            self.queue.put_nowait(item)
            return True
        except asyncio.QueueFull:
            self.dropped += 1
            self.gapped = True
            return False

    def note_delivered(self, t_pub: float | None, seq: int = -1) -> None:
        self.delivered += 1
        if seq > self.last_seq:
            self.last_seq = seq
        if t_pub is not None:
            # subscriber match→socket-write latency: t_pub is stamped by
            # FanoutPlane.on_fired at frame mint, so this spans bitset
            # match + queue dwell + the actual transport write
            lag = (time.perf_counter() - t_pub) * 1000.0
            self.lag_ms_sum += lag
            self.lag_ms_max = max(self.lag_ms_max, lag)
            FANOUT_WRITE_LATENCY.labels(transport=self.transport).observe(lag)

    def stats(self) -> dict:
        return {
            "user": self.user_id,
            "transport": self.transport,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "replayed": self.replayed,
            "gapped": self.gapped,
            "lag_ms_mean": (
                round(self.lag_ms_sum / self.delivered, 3)
                if self.delivered
                else None
            ),
            "lag_ms_max": round(self.lag_ms_max, 3),
            "last_seq": self.last_seq,
            "queue_depth": self.queue.qsize(),
        }


class FanoutHub:
    """The broadcast tier: an asyncio socket server fanning matched frames
    out to per-user WS/SSE connections. ``slot_of`` maps a connecting
    user id to its subscription slot (unknown users are refused with 404
    — subscribe first, then connect)."""

    def __init__(
        self,
        slot_of,
        outbox: BroadcastOutbox | None = None,
        conn_queue_max: int = 256,
        host: str = "0.0.0.0",
        port: int = 0,
        min_seq_of=None,
        tail_cap: int = 0,
    ) -> None:
        self.slot_of = slot_of
        # slot → lowest frame seq the slot's CURRENT owner may receive
        # (slots recycle on unsubscribe; frames below the floor were
        # addressed to a previous owner and must not deliver or replay)
        self.min_seq_of = min_seq_of or (lambda slot: 0)
        self.outbox = outbox
        self.conn_queue_max = int(conn_queue_max)
        self.host = host
        self.port = int(port)
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[_Connection] = set()
        # in-memory ring of the last `tail_cap` broadcast frames (seq,
        # encoded payload, packed recipient words): a reconnect whose
        # numeric cursor lands inside the retained window replays from
        # here instead of re-parsing the whole outbox — the hot path for
        # fresh cursors (ISSUE 20 satellite: the "full outbox scan on
        # every reconnect" bug). Cursors the ring can't serve fall back
        # to the outbox scan with a counted reason.
        self.tail_cap = max(int(tail_cap), 0)
        self._tail: deque | None = (
            deque(maxlen=self.tail_cap) if self.tail_cap else None
        )
        self.tail_resumes = 0
        self.resume_fallbacks: dict[str, int] = {}
        # seq range [lo, hi] excluded from every replay: frames published
        # between a fanout snapshot save and the crash were addressed by
        # a registry whose post-save churn a restore cannot reconstruct
        # (slot may have changed hands) — replaying them against the
        # restored layout risks cross-user misdelivery
        self.replay_excluded: tuple[int, int] | None = None
        self.frames_sent = 0
        self.shed = 0
        self.resumed = 0
        # highest frame seq broadcast so far — the head the fan-out
        # consumer-group cursor lag is measured against (a memory-held
        # mirror; outbox.last_seq() is a full-file scan, unfit for
        # snapshot-rate reads)
        self.head_seq = -1
        # accumulated per-user delivery totals incl. closed connections —
        # the report tool's "hottest subscriptions" feed
        self.totals_by_user: dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("fanout hub listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # fold still-open connections' delivery totals NOW — the plane
        # emits fanout_summary right after stop(), before the handler
        # tasks' finally blocks get a loop turn (_close_conn is
        # idempotent, so the handlers' later calls are no-ops)
        for conn in list(self._conns):
            self._close_conn(conn)
        self._conns.clear()

    @property
    def connections(self) -> int:
        return len(self._conns)

    def close_user(self, user_id: str) -> int:
        """Close every connection bound to ``user_id`` (called on
        unsubscribe: the freed slot may be reclaimed by another user, and
        a connection still holding it would receive the claimant's
        frames). Returns the number of connections closed."""
        victims = [c for c in self._conns if c.user_id == user_id]
        for conn in victims:
            conn.closed.set()
            self._conns.discard(conn)
        return len(victims)

    def rebind_slots(self, reason: str = "compaction") -> int:
        """Re-resolve every open connection's slot after the registry
        re-packed (compaction moves users to new slots); a connection
        whose user vanished closes. The tail ring resets too — its packed
        recipient words address the OLD slot layout and would misdeliver
        against the new one."""
        rebound = 0
        for conn in list(self._conns):
            slot = self.slot_of(conn.user_id)
            if slot is None:
                conn.closed.set()
                self._conns.discard(conn)
                continue
            if int(slot) != conn.slot:
                conn.slot = int(slot)
                rebound += 1
        self.reset_tail()
        get_event_log().emit(
            "fanout_rebind", reason=reason, rebound=rebound
        )
        return rebound

    def reset_tail(self) -> None:
        if self._tail is not None:
            self._tail.clear()

    def cursor_lag(self) -> int:
        """Records-behind-head for the hub's LAGGIEST open connection —
        the fan-out plane's entry in the per-consumer-group cursor-lag
        watermark (the delivery lanes are the other three groups). A
        connection that has written frames trails by ``head_seq -
        last_seq``; one that hasn't yet trails by its queued backlog.
        Refreshes the gauge on read, the watermark pattern (labelled
        fanout_hub — the delivery plane's "fanout" lane is the
        worker-side group; this one is the socket-side consumers)."""
        lag = 0
        for conn in self._conns:
            if conn.last_seq >= 0 and self.head_seq >= 0:
                lag = max(lag, self.head_seq - conn.last_seq)
            else:
                lag = max(lag, conn.queue.qsize())
        DELIVERY_CURSOR_LAG.labels(group="fanout_hub").set(lag)
        return lag

    def snapshot(self) -> dict:
        return {
            "port": self.port if self._server is not None else None,
            "connections": [c.stats() for c in self._conns],
            "frames_sent": self.frames_sent,
            "shed": self.shed,
            "resumed": self.resumed,
            "tail_resumes": self.tail_resumes,
            "tail_retained": len(self._tail) if self._tail is not None else 0,
            "tail_cap": self.tail_cap,
            "resume_fallbacks": dict(self.resume_fallbacks),
            "replay_excluded": (
                list(self.replay_excluded)
                if self.replay_excluded is not None
                else None
            ),
            "head_seq": self.head_seq,
            "cursor_lag": self.cursor_lag(),
            "outbox": (
                {
                    "path": str(self.outbox.path),
                    "appends": self.outbox.appends,
                    "rotations": self.outbox.rotations,
                }
                if self.outbox is not None
                else None
            ),
        }

    # -- broadcast (called from the plane / delivery worker) -----------------

    def broadcast(
        self, frame: dict, words: np.ndarray, t_pub: float | None = None
    ) -> None:
        """Offer one matched frame to every connected recipient — bounded
        ``put_nowait`` per connection, never blocks. Packed-word bit test
        per connection: O(connections), independent of the user count."""
        seq = int(frame.get("seq", 0))
        data: str | None = None
        if self._tail is not None:
            # tail ring feeds BEFORE the no-connections early return: the
            # retained window must cover frames broadcast while nobody
            # was connected, or the first reconnect after a quiet spell
            # would always fall back to the outbox scan
            data = json.dumps(frame, separators=(",", ":"))
            if self._tail and seq <= self._tail[-1][0]:
                # seq went backwards (restore/reshard seam): the ring's
                # in-order invariant broke — reset rather than serve a
                # spliced window
                self._tail.clear()
            self._tail.append(
                (seq, data, np.ascontiguousarray(words, np.uint32).copy())
            )
        if seq > self.head_seq:
            self.head_seq = seq
        if not self._conns:
            return
        if data is None:
            data = json.dumps(frame, separators=(",", ":"))
        for conn in list(self._conns):
            w = conn.slot >> 5
            if w >= len(words) or not (
                int(words[w]) >> (conn.slot & 31) & 1
            ):
                continue
            if seq < self.min_seq_of(conn.slot):
                # an in-flight frame addressed to this slot's PREVIOUS
                # owner (delivery-worker handoff raced an unsubscribe)
                continue
            # queue-depth distribution sampled at offer time — the shape
            # of this histogram is the early-warning for shed storms
            FANOUT_CONN_QUEUE_DEPTH.observe(conn.queue.qsize())
            if not conn.offer((seq, data, t_pub)):
                self.shed += 1
                FANOUT_SHED.labels(reason="slow_consumer").inc()
                get_event_log().emit(
                    "fanout_shed",
                    reason="slow_consumer",
                    user=conn.user_id,
                    transport=conn.transport,
                    seq=seq,
                )

    # -- request handling ----------------------------------------------------

    @staticmethod
    def _http(status: int, reason: str, body: str, ctype="application/json"):
        payload = body.encode("utf-8")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(payload)}\r\n"
            "Connection: close\r\n\r\n"
        )
        return head.encode("ascii") + payload

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn: _Connection | None = None
        try:
            request_line = await asyncio.wait_for(reader.readline(), timeout=5)
            parts = request_line.decode("latin-1").split()
            headers: dict[str, str] = {}
            for _ in range(100):
                line = await asyncio.wait_for(reader.readline(), timeout=5)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                headers[name.strip().lower()] = value.strip()
            if len(parts) < 2 or parts[0] != "GET":
                writer.write(
                    self._http(405, "Method Not Allowed", '{"error":"GET only"}')
                )
                await writer.drain()
                return
            path, _, query = parts[1].partition("?")
            params = parse_qs(query)
            if path not in ("/ws", "/sse"):
                writer.write(self._http(404, "Not Found", '{"error":"not found"}'))
                await writer.drain()
                return
            user = (params.get("user") or [""])[0]
            slot = self.slot_of(user) if user else None
            if slot is None:
                writer.write(
                    self._http(
                        404, "Not Found",
                        '{"error":"unknown user; subscribe before connecting"}',
                    )
                )
                await writer.drain()
                return
            cursor_raw = (params.get("cursor") or [""])[0]
            if path == "/sse" and not cursor_raw:
                cursor_raw = headers.get("last-event-id", "")
            # resume source, cheapest first: a numeric cursor inside the
            # tail ring's window replays from memory (no I/O at all);
            # anything else falls back to the outbox scan — OFF-LOOP and
            # BEFORE registration (a reconnect burst must not freeze
            # broadcast under full-file JSON+base64 parses); the appends-
            # stability loop guarantees no frame lands between the
            # accepted scan and registration
            entries = None
            tail = None
            if cursor_raw and self.outbox is not None:
                tail = self._tail_window_for(cursor_raw)
                if tail is None:
                    entries = await self._scan_outbox_stable()
            conn = _Connection(
                user, slot, "ws" if path == "/ws" else "sse",
                self.conn_queue_max,
            )
            # register, then enqueue the replayed gap SYNCHRONOUSLY (no
            # awaits until the replay is queued): live frames broadcast
            # after this block land in the queue BEHIND the gap
            self._conns.add(conn)
            FANOUT_CONNECTIONS.labels(transport=conn.transport).set(
                sum(1 for c in self._conns if c.transport == conn.transport)
            )
            self._replay_cursor(conn, cursor_raw, entries, tail=tail)
            if path == "/ws":
                await self._serve_ws(conn, reader, writer, headers)
            else:
                await self._serve_sse(conn, writer)
        except (TimeoutError, asyncio.TimeoutError, ConnectionError, OSError):
            pass  # peer went away; cleanup below
        except Exception:
            log.exception("fanout connection handling failed")
        finally:
            if conn is not None:
                self._close_conn(conn)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _scan_outbox_stable(self) -> list:
        """Parse the outbox on a worker thread, re-scanning until no
        frame was appended mid-scan: after the accepted scan the caller
        registers + replays with no intervening await, so every frame is
        either in the scan or broadcast live to the registered queue —
        never lost between the two."""
        for _ in range(3):
            n0 = self.outbox.appends
            entries = await asyncio.to_thread(self.outbox.entries)
            if self.outbox.appends == n0:
                return entries
        # a publish storm outpaced three off-loop scans: take ONE
        # synchronous scan on the loop — briefly blocking, but nothing can
        # append mid-scan, so the no-lost-frame guarantee still holds
        return self.outbox.entries()

    def _count_fallback(self, reason: str) -> None:
        self.resume_fallbacks[reason] = (
            self.resume_fallbacks.get(reason, 0) + 1
        )
        FANOUT_RESUME_FALLBACK.labels(reason=reason).inc()

    def _tail_window_for(self, cursor_raw: str) -> list | None:
        """The reconnect fast path: a numeric cursor whose resume point
        lies inside the tail ring's retained window is served from
        memory — return the ``(seq, data, words)`` window to replay.
        ``None`` means the ring can't serve it and the caller takes the
        outbox scan, with the reason counted
        (``bqt_fanout_resume_fallback_total``): ``trace_cursor`` (a
        trace-id cursor needs the log to resolve), ``tail_off`` (ring
        not configured), ``tail_cold`` (ring empty), ``cursor_gap``
        (cursor older than the ring's first retained frame — the ring
        can't prove it would replay the full gap)."""
        try:
            cursor_seq = int(cursor_raw.strip())
        except ValueError:
            self._count_fallback("trace_cursor")
            return None
        if self._tail is None:
            self._count_fallback("tail_off")
            return None
        if not self._tail:
            self._count_fallback("tail_cold")
            return None
        if cursor_seq < self._tail[0][0] - 1:
            self._count_fallback("cursor_gap")
            return None
        return [t for t in self._tail if t[0] > cursor_seq]

    def _replay_cursor(
        self,
        conn: _Connection,
        cursor_raw: str,
        entries: list | None,
        tail: list | None = None,
    ) -> None:
        if not cursor_raw:
            return
        overflow = 0
        excl = self.replay_excluded

        def _excluded(fseq: int) -> bool:
            return excl is not None and excl[0] <= fseq <= excl[1]

        if tail is not None:
            # in-memory window: same floor + recipient-bit discipline as
            # the outbox path, zero parse cost
            floor = self.min_seq_of(conn.slot) - 1
            w, bitpos = conn.slot >> 5, conn.slot & 31
            for fseq, data, words in tail:
                if fseq <= floor or _excluded(fseq):
                    continue
                if w >= len(words) or not (int(words[w]) >> bitpos & 1):
                    continue
                if conn.offer((fseq, data, None)):
                    conn.replayed += 1
                    self.resumed += 1
                    self.tail_resumes += 1
                    FANOUT_RESUME_REPLAYED.inc()
                else:
                    self.shed += 1
                    overflow += 1
                    FANOUT_SHED.labels(reason="resume_overflow").inc()
        elif entries is not None and self.outbox is not None:
            seq = self.outbox.resolve_cursor(cursor_raw, entries=entries)
            if seq is None:
                return
            # frames below the slot's min-seq floor were addressed to the
            # slot's previous owner — never replayed to the new claimant
            seq = max(seq, self.min_seq_of(conn.slot) - 1)
            for frame in self.outbox.replay_after(
                seq, conn.slot, entries=entries
            ):
                if _excluded(int(frame.get("seq", -1))):
                    continue
                data = json.dumps(frame, separators=(",", ":"))
                if conn.offer((int(frame.get("seq", 0)), data, None)):
                    conn.replayed += 1
                    self.resumed += 1
                    FANOUT_RESUME_REPLAYED.inc()
                else:
                    # a gap larger than the connection queue: the shed is
                    # counted and the client must re-cursor from its last
                    # received seq (at-least-once, never silent)
                    self.shed += 1
                    overflow += 1
                    FANOUT_SHED.labels(reason="resume_overflow").inc()
        else:
            return
        if overflow:
            get_event_log().emit(
                "fanout_shed",
                reason="resume_overflow",
                user=conn.user_id,
                transport=conn.transport,
                count=overflow,
            )
        get_event_log().emit(
            "fanout_resume",
            user=conn.user_id,
            transport=conn.transport,
            cursor=cursor_raw,
            replayed=conn.replayed,
            source="tail" if tail is not None else "outbox",
        )

    def _close_conn(self, conn: _Connection) -> None:
        self._conns.discard(conn)
        conn.closed.set()
        if conn.finalized:
            return
        conn.finalized = True
        # frames still queued at close never reached the peer: counted,
        # never silent (the shed contract holds through shutdown too)
        pending = conn.queue.qsize()
        if pending:
            self.shed += pending
            FANOUT_SHED.labels(reason="close_pending").inc(pending)
            get_event_log().emit(
                "fanout_shed",
                reason="close_pending",
                user=conn.user_id,
                transport=conn.transport,
                count=pending,
            )
        self.totals_by_user[conn.user_id] = (
            self.totals_by_user.get(conn.user_id, 0) + conn.delivered
        )
        FANOUT_CONNECTIONS.labels(transport=conn.transport).set(
            sum(1 for c in self._conns if c.transport == conn.transport)
        )
        get_event_log().emit("fanout_conn_close", **conn.stats())

    # -- transports ----------------------------------------------------------

    async def _pump(self, conn: _Connection, write_frame) -> None:
        """Drain the connection queue through ``write_frame`` until the
        peer disconnects or the hub stops."""
        closed = asyncio.ensure_future(conn.closed.wait())
        try:
            while True:
                getter = asyncio.ensure_future(conn.queue.get())
                done, _ = await asyncio.wait(
                    {getter, closed}, return_when=asyncio.FIRST_COMPLETED
                )
                if getter not in done:
                    getter.cancel()
                    return
                seq, data, t_pub = getter.result()
                await write_frame(seq, data)
                conn.note_delivered(t_pub, seq)
                self.frames_sent += 1
                FANOUT_FRAMES.labels(transport=conn.transport).inc()
        finally:
            closed.cancel()

    async def _serve_sse(self, conn: _Connection, writer) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: keep-alive\r\n\r\n"
        )
        await writer.drain()

        async def write_frame(seq: int, data: str) -> None:
            writer.write(f"id: {seq}\ndata: {data}\n\n".encode())
            await writer.drain()

        await self._pump(conn, write_frame)

    async def _serve_ws(self, conn, reader, writer, headers) -> None:
        key = headers.get("sec-websocket-key")
        if not key:
            writer.write(
                self._http(400, "Bad Request", '{"error":"missing ws key"}')
            )
            await writer.drain()
            return
        writer.write(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {ws_accept_key(key)}\r\n\r\n"
            ).encode("ascii")
        )
        await writer.drain()

        async def write_frame(seq: int, data: str) -> None:
            writer.write(ws_encode_frame(data.encode("utf-8")))
            await writer.drain()

        async def read_control() -> None:
            # client→server traffic is control-only: answer pings, honor
            # close, ignore anything else. The finally matters: a peer
            # that vanishes WITHOUT a close frame (kill -9, partition)
            # surfaces here as ConnectionError, and unless the conn is
            # closed its _pump would block on an empty queue forever — a
            # zombie registration broadcast keeps offering into
            try:
                while True:
                    opcode, payload = await ws_read_frame(reader)
                    if opcode == 0x8:  # close
                        writer.write(ws_encode_frame(payload, opcode=0x8))
                        await writer.drain()
                        return
                    if opcode == 0x9:  # ping → pong
                        writer.write(ws_encode_frame(payload, opcode=0xA))
                        await writer.drain()
            finally:
                conn.closed.set()

        reader_task = asyncio.ensure_future(read_control())
        try:
            await self._pump(conn, write_frame)
        finally:
            reader_task.cancel()
            try:
                await reader_task
            except (asyncio.CancelledError, ConnectionError, Exception):
                pass
