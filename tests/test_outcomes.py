"""Signal-outcome observatory (ISSUE 12).

Covers: the maturation gather's math (LONG/SHORT sign convention, missing
bars, padding slots), registry bounds (cap + eviction), the three-drive
matured-set parity pin (serial / scanned / backtest — the acceptance
criterion), checkpoint round-trip of the open-signal registry (kill
mid-horizon, restore, resumed drive matures the oracle's set),
``signal_outcome`` event joinability to ``signal`` events, the /healthz
scoreboard section, the sweep's economic scoring, and the
tools/outcome_report.py golden.

Engine shapes are shared across the module (capacity 8, window 160) so
the jit cache amortizes; the stream is ``generate_outcome_replay`` —
MID-stream MeanReversionFade hammers with scripted aftermaths, the one
generator whose signals actually mature before EOF.
"""

from __future__ import annotations

import asyncio
import json
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import outcome_report  # noqa: E402

from binquant_tpu.engine.buffer import NUM_FIELDS, Field  # noqa: E402
from binquant_tpu.obs.outcomes import (  # noqa: E402
    OutcomeTracker,
    direction_sign,
    outcome_gather,
    signed_outcome,
)

CAP, WIN = 8, 160
HORIZONS = (1, 4, 16)
ENABLED = {"mean_reversion_fade"}
FIRE_TICKS = (104, 110)
N_TICKS = 128


# -- fixtures -----------------------------------------------------------------


@pytest.fixture(scope="module")
def stream(tmp_path_factory):
    from binquant_tpu.io.replay import generate_outcome_replay

    path = tmp_path_factory.mktemp("outcomes") / "stream.jsonl"
    generate_outcome_replay(
        path, n_symbols=CAP, n_ticks=N_TICKS, fire_ticks=FIRE_TICKS
    )
    return path


@pytest.fixture(scope="module")
def oracle(stream):
    """The uninterrupted serial drive: signals + matured outcome set."""
    from binquant_tpu.io.replay import run_replay

    signals: list = []
    outcomes: list = []
    stats = run_replay(
        stream,
        capacity=CAP,
        window=WIN,
        enabled_strategies=ENABLED,
        incremental=True,
        donate=False,
        collect=signals,
        outcomes=True,
        outcome_horizons=HORIZONS,
        collect_outcomes=outcomes,
    )
    return {"signals": signals, "outcomes": outcomes, "stats": stats}


# -- kernel + tracker units ---------------------------------------------------


def _ring(closes, t0=300_000, step=300, n_rows=2):
    W = len(closes)
    times = np.full((n_rows, W), -1, np.int32)
    vals = np.full((n_rows, W, NUM_FIELDS), np.nan, np.float32)
    for k, c in enumerate(closes):
        times[0, k] = t0 + k * step
        vals[0, k, Field.CLOSE] = c
        vals[0, k, Field.HIGH] = c * 1.01
        vals[0, k, Field.LOW] = c * 0.99
    return times, vals, t0


def test_gather_kernel_math():
    closes = [100, 101, 102, 103, 104, 105, 104, 103, 102, 101]
    times, vals, t0 = _ring(closes)
    entry_ts = t0 + 2 * 300
    rows = np.array([0, 0, -1, -1], np.int32)
    entry = np.array([entry_ts, entry_ts, 0, 0], np.int32)
    horizon = np.array([entry_ts + 300, entry_ts + 4 * 300, 0, 0], np.int32)
    floats, ints = outcome_gather(times, vals, rows, entry, horizon)
    assert floats[0, 0] == closes[2]  # entry close (the anchored bar)
    assert floats[1, 0] == closes[3]  # h=1 forward close
    assert floats[1, 1] == closes[6]  # h=4 forward close
    assert ints[0, 1] == 4  # bars inside (entry, entry+4]
    assert np.isclose(floats[3, 1], max(closes[3:7]) * 1.01)  # window high
    assert np.isclose(floats[2, 1], min(closes[3:7]) * 0.99)  # window low
    # padding slots stay NaN / empty
    assert np.isnan(floats[0, 2]) and ints[0, 2] == 0
    # oldest retained bar is exact int32 (truncation judge)
    assert ints[1, 0] == t0


def test_gather_missing_horizon_bar_uses_last_available():
    """A gap at the exact horizon bar falls back to the latest bar inside
    the window (deterministic across drives — the contract the parity pin
    relies on)."""
    closes = [100, 101, 102, 103, 104, 105]
    times, vals, t0 = _ring(closes)
    times[0, 4] = -1  # kill the bar at entry+2
    rows = np.array([0] * 8, np.int32)
    entry = np.full(8, t0, np.int32)
    horizon = np.full(8, t0 + 4 * 300, np.int32)
    floats, _ = outcome_gather(times, vals, rows, entry, horizon)
    # bars 1,2,3 live; 4 killed → forward close is bar 3's
    assert floats[1, 0] == closes[3]


def test_signed_outcome_convention():
    # LONG: fwd follows price, mae from the low, mfe from the high
    fwd, mae, mfe = signed_outcome(1, 100.0, 103.0, 99.0, 104.0)
    assert fwd == pytest.approx(0.03)
    assert mae == pytest.approx(-0.01)
    assert mfe == pytest.approx(0.04)
    # SHORT mirrors: adverse is the high, favorable the low
    fwd, mae, mfe = signed_outcome(-1, 100.0, 103.0, 99.0, 104.0)
    assert fwd == pytest.approx(-0.03)
    assert mae == pytest.approx(-0.04)
    assert mfe == pytest.approx(0.01)
    # mae <= 0 <= mfe always
    assert signed_outcome(1, 100.0, 101.0, 100.5, 102.0)[1] == 0.0
    # unusable raw gathers → None
    assert signed_outcome(1, float("nan"), 1.0, 1.0, 1.0) is None
    assert signed_outcome(1, 0.0, 1.0, 1.0, 1.0) is None
    assert direction_sign("SHORT") == -1
    assert direction_sign("LONG") == 1
    assert direction_sign("grid") == 1


def test_tracker_cap_eviction_and_restore():
    tr = OutcomeTracker(enabled=True, horizons=(1, 4), cap=2)
    for i, sym in enumerate(("A", "B", "C")):
        tr.register("s", sym, 0, 300_000, "LONG", tick_ms=i)
    assert tr.evictions == 1
    assert [s["symbol"] for s in tr._open] == ["B", "C"]
    # snapshot → restore round-trips the open registry (JSON-safe)
    blob = json.loads(json.dumps(tr.snapshot_open()))
    tr2 = OutcomeTracker(enabled=True, horizons=(1, 4), cap=4)
    tr2.restore_open(blob)
    assert [s["symbol"] for s in tr2._open] == ["B", "C"]
    assert tr2._open[0]["pending"] == [1, 4]


def test_tracker_matures_and_scoreboard():
    closes = [100, 101, 102, 103, 104, 105, 104, 103, 102, 101]
    times, vals, t0 = _ring(closes)

    class Buf:
        pass

    buf = Buf()
    buf.times, buf.values = times, vals
    tr = OutcomeTracker(enabled=True, horizons=(1, 4), cap=8)
    tr.register("s", "LONGY", 0, t0 + 2 * 300, "LONG", tick_ms=1)
    tr.register("s", "SHORTY", 0, t0 + 2 * 300, "SHORT", tick_ms=2)
    # nothing due yet at the entry tick
    assert tr.on_tick(t0 + 2 * 300, buf) == []
    matured = tr.on_tick(t0 + 6 * 300, buf)
    assert len(matured) == 4 and not tr._open
    board = tr.scoreboard()
    assert board["matured"] == 4 and board["truncated"] == 0
    cell = board["per_strategy"]["s"]["4"]
    assert cell["n"] == 2 and cell["hit_rate"] == 0.5
    # LONG and SHORT of the same move cancel in signed-return space
    assert cell["avg_fwd"] == pytest.approx(0.0, abs=1e-9)


def test_tracker_truncation_detected():
    """A window whose entry bar was evicted from the ring must mature as
    truncated, not silently compute on partial history."""
    closes = [100, 101, 102, 103]
    times, vals, t0 = _ring(closes)

    class Buf:
        pass

    buf = Buf()
    buf.times, buf.values = times, vals
    tr = OutcomeTracker(enabled=True, horizons=(1,), cap=8)
    # entry anchored BEFORE the ring's oldest retained bar
    tr.register("s", "OLD", 0, t0 - 300, "LONG", tick_ms=1)
    tr.on_tick(t0 + 3 * 300, buf)
    assert tr.truncated == 1 and tr.matured == 1
    assert not tr.matured_set()  # truncated pairs stay off the scoreboard


# -- the acceptance pin: three drives, one matured set ------------------------


def test_three_drive_outcome_parity(stream, oracle):
    """Serial, scanned, and backtest drives report the IDENTICAL matured
    outcome set on a replayed stream (ISSUE 12 acceptance)."""
    from binquant_tpu.backtest.driver import run_backtest
    from binquant_tpu.io.replay import run_replay

    assert oracle["stats"]["signals"] >= 2
    assert len(oracle["outcomes"]) >= len(HORIZONS) * 2

    scanned: list = []
    s2 = run_replay(
        stream,
        capacity=CAP,
        window=WIN,
        enabled_strategies=ENABLED,
        incremental=True,
        donate=False,
        scanned=True,
        scan_chunk=16,
        outcomes=True,
        outcome_horizons=HORIZONS,
        collect_outcomes=scanned,
    )
    assert s2["scanned_ticks"] > 0  # the fused path actually engaged
    assert scanned == oracle["outcomes"]

    backtest: list = []
    s3 = run_backtest(
        stream,
        capacity=CAP,
        window=WIN,
        enabled_strategies=ENABLED,
        outcomes=True,
        outcome_horizons=HORIZONS,
        collect_outcomes=backtest,
    )
    assert s3["backtest_ticks"] > 0
    assert backtest == oracle["outcomes"]
    # the scripted aftermaths are distinctive: the recovery symbol's h=16
    # return beats the continued-bleed symbol's
    by_sym = {}
    for strategy, sym, _entry, h, fwd, _mae, _mfe, _bars in oracle["outcomes"]:
        if h == 16:
            by_sym.setdefault(sym, []).append(fwd)
    if {"S005USDT", "S006USDT"} <= set(by_sym):
        assert max(by_sym["S005USDT"]) > max(by_sym["S006USDT"])


# -- checkpoint round-trip of the open-signal registry ------------------------


def _drive_serial(engine, seq) -> None:
    async def go():
        for now_ms, klines in seq:
            for k in klines:
                engine.ingest(k)
            await engine.process_tick(now_ms=now_ms)
        await engine.flush_pending()

    asyncio.run(go())


def test_checkpoint_roundtrip_mid_horizon(stream, oracle, tmp_path):
    """Kill mid-horizon, restore, and the resumed drive matures the same
    signal_outcome set as the uninterrupted oracle (ISSUE 12 satellite)."""
    from binquant_tpu.io.checkpoint import CheckpointManager, save_state
    from binquant_tpu.io.replay import make_stub_engine, tick_seq

    seq = tick_seq(stream)
    # cut AFTER the first fire with horizons still pending, BEFORE the
    # second fire — the open registry must carry both facts across
    cut = FIRE_TICKS[0] + 3
    assert cut < FIRE_TICKS[1]

    kw = dict(
        capacity=CAP,
        window=WIN,
        enabled_strategies=ENABLED,
        incremental=True,
        donate=False,
        outcomes=True,
        outcome_horizons=HORIZONS,
    )
    a = make_stub_engine(**kw)
    _drive_serial(a, seq[:cut])
    assert a.outcomes._open, "cut must land mid-horizon (open slots)"
    ckpt = tmp_path / "engine.ckpt.npz"
    save_state(ckpt, a.state, a.registry, host_carries=a.host_carries())

    b = make_stub_engine(**kw)
    assert CheckpointManager(ckpt).try_restore(b)
    assert [s["symbol"] for s in b.outcomes.snapshot_open()] == [
        s["symbol"] for s in a.outcomes.snapshot_open()
    ]
    _drive_serial(b, seq[cut:])

    combined = sorted(a.outcomes.matured_set() | b.outcomes.matured_set())
    assert combined == oracle["outcomes"]
    assert b.outcomes.matured_set(), "post-restore drive matured something"


# -- events / healthz / report surfaces ---------------------------------------


def test_events_healthz_and_report(stream, oracle, tmp_path):
    """signal_outcome events join signal events by trace_id/tick_seq, the
    /healthz snapshot carries the scoreboard, and outcome_report renders
    the captured log."""
    from binquant_tpu.io.replay import make_stub_engine, tick_seq
    from binquant_tpu.obs.events import EventLog, set_event_log

    log_path = tmp_path / "events.jsonl"
    set_event_log(EventLog(log_path))
    try:
        engine = make_stub_engine(
            capacity=CAP,
            window=WIN,
            enabled_strategies=ENABLED,
            incremental=True,
            donate=False,
            outcomes=True,
            outcome_horizons=HORIZONS,
            trace_sample=1.0,
        )
        _drive_serial(engine, tick_seq(stream))
        board = engine.health_snapshot()["outcomes"]
        assert board["enabled"] and board["matured"] == len(oracle["outcomes"])
        assert "mean_reversion_fade" in board["per_strategy"]
        snap = engine._flight_snapshot()
        assert "outcomes_open" in snap and "outcome_evictions" in snap
    finally:
        set_event_log(None)

    events = [
        json.loads(line)
        for line in log_path.read_text().splitlines()
        if line.strip()
    ]
    signals = {
        (e["trace_id"], e["tick_seq"])
        for e in events
        if e.get("event") == "signal"
    }
    outcomes = [e for e in events if e.get("event") == "signal_outcome"]
    assert len(outcomes) == len(oracle["outcomes"])
    for e in outcomes:
        assert e["trace_id"] is not None
        assert (e["trace_id"], e["tick_seq"]) in signals  # the join key
        assert {"strategy", "symbol", "horizon", "fwd_ret", "mae", "mfe"} <= (
            set(e)
        )

    # the scoreboard CLI renders the same log (exit 0, table present)
    assert outcome_report.main([str(log_path)]) == 0


def test_outcome_report_golden(capsys):
    """tools/outcome_report.py renders a deterministic scoreboard table
    (pinned — keep format changes deliberate)."""
    events = [
        {"event": "signal_outcome", "strategy": "mean_reversion_fade",
         "horizon": 4, "fwd_ret": 0.012, "mae": -0.004, "mfe": 0.02},
        {"event": "signal_outcome", "strategy": "mean_reversion_fade",
         "horizon": 4, "fwd_ret": -0.008, "mae": -0.016, "mfe": 0.002},
        {"event": "signal_outcome", "strategy": "activity_burst_pump",
         "horizon": 1, "fwd_ret": 0.004, "mae": 0.0, "mfe": 0.006},
        {"event": "signal_outcome", "strategy": "activity_burst_pump",
         "horizon": 1, "truncated": True},
    ]
    expected = (
        "signal-outcome scoreboard: 3 matured pairs (1 truncated)\n"
        "strategy                        h     n   hit% "
        "  avg_fwd   avg_mae   avg_mfe  worst_mae\n"
        "activity_burst_pump             1     1 100.0% "
        "  +0.0040   +0.0000   +0.0060    +0.0000\n"
        "mean_reversion_fade             4     2  50.0% "
        "  +0.0020   -0.0100   +0.0110    -0.0160"
    )
    assert outcome_report.render_report(events) == expected


# -- sweep economic scoring ---------------------------------------------------


@pytest.mark.slow
def test_sweep_scores_outcomes(stream):
    """run_param_sweep scores combos on forward returns / hit-rate / MAE
    (the ROADMAP-4 economic proxy), not just trigger counts."""
    from binquant_tpu.backtest import run_param_sweep

    res = run_param_sweep(
        stream,
        axes={"mrf.rsi_long_max": [15.0, 35.0]},
        capacity=CAP,
        window=WIN,
        chunk=32,
        horizons=HORIZONS,
    )
    out = res["outcomes"]
    assert out["matured_pairs"] > 0
    assert out["horizons"] == sorted(HORIZONS)
    assert len(out["per_combo"]) == res["P"]
    assert len(out["ranking_by_return"]) == res["P"]
    assert sorted(out["ranking_by_return"]) == list(range(res["P"]))
    scored = [c for c in out["combo_score"] if c["n"]]
    assert scored, "at least one combo matured outcomes"
    for c in scored:
        assert c["hit_rate"] is not None and c["avg_mae"] <= 0.0
