"""Subscription fan-out plane drills (ISSUE 14).

The fan-out plane (``binquant_tpu/fanout``) compiles the user population
into packed uint32 bitset planes and joins every fired tick's deduped
signal set against them in ONE device dispatch; matched frames ride a
cursor-replayable outbox into the WS/SSE broadcast hub. Tier-1 pins the
bitset pack/unpack round trip, registry-churn plane correctness, the
randomized device-kernel-vs-Python-oracle equality, the replayed-burst
recipient-set parity across all four drives (serial / donated / scanned /
backtest), the hub's shed-and-resume contract over real sockets, and the
fanout_report golden. The slow lane (``make fanout-smoke``) adds the
1M-subscription single-dispatch smoke and the chaos drill
(tests/test_scenarios.py side: churn storm + stalled consumers).
"""

from __future__ import annotations

import asyncio
import json
from pathlib import Path

import numpy as np
import pytest

from binquant_tpu.engine.step import STRATEGY_ORDER
from binquant_tpu.enums import MarketRegimeCode
from binquant_tpu.fanout.kernel import (
    DevicePlanes,
    bucket,
    pack_bits_device,
    pack_words_np,
    popcount_words,
    unpack_words_np,
)
from binquant_tpu.fanout.registry import (
    INVALID_REGIME_ROW,
    Subscription,
    SubscriptionRegistry,
)
from binquant_tpu.io.replay import (
    generate_replay_file,
    load_klines_by_tick,
    make_stub_engine,
)

CAPACITY, WINDOW = 32, 120


def _tick_seq(path):
    by_tick = load_klines_by_tick(path)
    return [
        (
            (bucket + 1) * 900 * 1000,
            sorted(by_tick[bucket], key=lambda k: k["open_time"]),
        )
        for bucket in sorted(by_tick)
    ]


# -- bitset pack/unpack properties -------------------------------------------


def test_pack_unpack_roundtrip_property():
    """Host pack ↔ unpack is the identity, the device pack is bit-equal
    to the host pack, and popcount agrees — across shapes and densities
    (the LSB-first word layout every decoder shares)."""
    rng = np.random.default_rng(14)
    for k, users, density in (
        (1, 32, 0.0),
        (3, 64, 1.0),
        (4, 96, 0.5),
        (7, 256, 0.03),
        (2, 1024, 0.9),
    ):
        bits = rng.random((k, users)) < density
        words = pack_words_np(bits)
        assert words.dtype == np.uint32 and words.shape == (k, users // 32)
        assert (unpack_words_np(words) == bits).all()
        assert (pack_bits_device(bits) == words).all()
        assert popcount_words(words) == int(bits.sum())


def test_bucket_padding():
    assert [bucket(n) for n in (0, 1, 4, 5, 8, 9, 100)] == [
        4, 4, 4, 8, 8, 16, 128,
    ]


# -- subscription model -------------------------------------------------------


def test_subscription_validation_and_oracle_predicate():
    with pytest.raises(ValueError):
        Subscription("u", strategies=frozenset({"no_such_strategy"}))
    with pytest.raises(ValueError):
        Subscription("u", regimes=frozenset({len(MarketRegimeCode)}))
    strat = STRATEGY_ORDER[0]
    sub = Subscription(
        "u",
        symbols=frozenset({"BTCUSDT"}),
        strategies=frozenset({strat}),
        regimes=frozenset({0}),
        min_strength=0.5,
    )
    assert sub.matches(strat, "BTCUSDT", 0.6, 0)
    assert sub.matches(strat, "BTCUSDT", -0.6, 0)  # |score| vs floor
    assert not sub.matches(strat, "BTCUSDT", 0.4, 0)  # under floor
    assert not sub.matches(strat, "BTCUSDT", 0.6, 1)  # wrong regime
    assert not sub.matches(strat, "BTCUSDT", 0.6, None)  # invalid ctx
    assert not sub.matches(strat, "ETHUSDT", 0.6, 0)  # wrong symbol
    assert not sub.matches(STRATEGY_ORDER[1], "BTCUSDT", 0.6, 0)
    # wildcards match everything but still gate on strength
    wild = Subscription("w", min_strength=0.25)
    assert wild.matches(strat, "ETHUSDT", 0.25, None)
    assert not wild.matches(strat, "ETHUSDT", 0.2, None)
    # knife-edge floors: the model quantizes min_strength to f32 and the
    # oracle compares in f32, exactly like the device kernel — a score
    # inside the f64->f32 rounding gap must agree on both sides
    edge = Subscription("e", min_strength=0.1)
    assert edge.min_strength == float(np.float32(0.1))
    assert edge.matches(strat, "ETHUSDT", 0.099999999, None)  # == f32(0.1)


def _random_population(rng, n_users, symbols, rows, with_floors=True):
    """A randomized subscription population exercising every criterion
    combination; floors are exact f32 values so the device (f32) and the
    oracle (f64) sit on the same side of every comparison."""
    subs = []
    regimes = list(range(len(MarketRegimeCode)))
    for i in range(n_users):
        sym = (
            None
            if rng.random() < 0.4
            else frozenset(
                rng.choice(symbols, size=rng.integers(1, 4), replace=False)
            )
        )
        strat = (
            None
            if rng.random() < 0.4
            else frozenset(
                rng.choice(
                    STRATEGY_ORDER, size=rng.integers(1, 4), replace=False
                )
            )
        )
        reg = (
            None
            if rng.random() < 0.5
            else frozenset(
                int(r)
                for r in rng.choice(
                    regimes, size=rng.integers(1, 3), replace=False
                )
            )
        )
        floor = (
            float(np.float32(rng.random() * 0.8)) if with_floors else 0.0
        )
        subs.append(
            Subscription(
                f"user{i:04d}",
                symbols=sym,
                strategies=strat,
                regimes=reg,
                min_strength=floor,
            )
        )
    return subs


def _match_users(reg: SubscriptionRegistry, words_row) -> set[str]:
    return set(
        reg.users_of_slots(np.flatnonzero(unpack_words_np(words_row)))
    )


def test_device_match_equals_oracle_randomized():
    """ISSUE 14 acceptance core: the packed device join returns exactly
    the Python oracle's recipient sets — randomized population, every
    regime row including the invalid-context bucket."""
    rng = np.random.default_rng(41)
    symbols = [f"S{i:03d}USDT" for i in range(12)]
    rows = {s: i for i, s in enumerate(symbols)}
    reg = SubscriptionRegistry(symbol_capacity=16, capacity=64)
    for sub in _random_population(rng, 50, symbols, rows):
        reg.add(sub, row_of=rows.get)
    dev = DevicePlanes(reg)
    assert dev.sync() == "full"
    for regime in [None, *range(len(MarketRegimeCode))]:
        k = int(rng.integers(1, 7))
        picks = rng.integers(0, len(symbols), size=k)
        strats = rng.integers(0, len(STRATEGY_ORDER), size=k)
        scores = np.float32(rng.normal(0, 0.6, size=k))
        entries = [
            (STRATEGY_ORDER[si], symbols[ri], float(sc))
            for si, ri, sc in zip(strats, picks, scores)
        ]
        oracle = reg.match_oracle(entries, regime)
        words = dev.match(
            picks.astype(np.int32),
            strats.astype(np.int32),
            scores,
            INVALID_REGIME_ROW if regime is None else regime,
        )
        for i in range(k):
            assert _match_users(reg, words[i]) == oracle[i], (
                regime,
                entries[i],
            )


# -- churn --------------------------------------------------------------------


def test_registry_churn_planes_equal_fresh_build():
    """A random add/update/remove storm leaves planes BIT-IDENTICAL to a
    registry freshly built from the surviving population (freed slots'
    bits vanish; slot reuse rebinds cleanly; floors track)."""
    rng = np.random.default_rng(7)
    symbols = [f"S{i:03d}USDT" for i in range(8)]
    rows = {s: i for i, s in enumerate(symbols)}
    reg = SubscriptionRegistry(symbol_capacity=8, capacity=64)
    live: dict[str, Subscription] = {}
    for step in range(300):
        op = rng.random()
        if op < 0.5 or not live:
            sub = _random_population(rng, 1, symbols, rows)[0]
            sub = Subscription(
                f"user{step:04d}",
                symbols=sub.symbols,
                strategies=sub.strategies,
                regimes=sub.regimes,
                min_strength=sub.min_strength,
            )
            reg.add(sub, row_of=rows.get)
            live[sub.user_id] = sub
        elif op < 0.75:
            uid = str(rng.choice(sorted(live)))
            old = live[uid]
            new = Subscription(
                uid,
                symbols=old.symbols,
                strategies=None,
                regimes=old.regimes,
                min_strength=float(np.float32(rng.random())),
            )
            slot_before = reg.slot_of(uid)
            assert reg.update(new, row_of=rows.get) == slot_before
            live[uid] = new
        else:
            uid = str(rng.choice(sorted(live)))
            reg.remove(uid)
            del live[uid]
    fresh = SubscriptionRegistry(
        symbol_capacity=8, capacity=reg.capacity
    )
    # replay survivors into the SAME slots the churned registry holds
    for uid, sub in sorted(live.items(), key=lambda kv: reg.slot_of(kv[0])):
        fresh._next_slot = reg.slot_of(uid)
        fresh.add(sub, row_of=rows.get)
    assert (fresh.sym_plane == reg.sym_plane).all()
    assert (fresh.strat_plane == reg.strat_plane).all()
    assert (fresh.regime_plane == reg.regime_plane).all()
    assert (fresh.any_masks == reg.any_masks).all()
    occupied = sorted(reg.slot_of(u) for u in live)
    assert (
        fresh.floors[occupied] == reg.floors[occupied]
    ).all()
    empty = sorted(set(range(reg.capacity)) - set(occupied))
    assert np.isinf(reg.floors[empty]).all()


def test_bulk_load_identical_to_sequential_adds():
    rng = np.random.default_rng(99)
    symbols = [f"S{i:03d}USDT" for i in range(8)]
    rows = {s: i for i, s in enumerate(symbols)}
    subs = _random_population(rng, 40, symbols, rows)
    seq_reg = SubscriptionRegistry(symbol_capacity=8, capacity=64)
    for sub in subs:
        seq_reg.add(sub, row_of=rows.get)
    bulk_reg = SubscriptionRegistry(symbol_capacity=8, capacity=64)
    assert bulk_reg.bulk_load(subs, row_of=rows.get) == len(subs)
    for name in ("sym_plane", "strat_plane", "regime_plane", "any_masks"):
        assert (
            getattr(bulk_reg, name) == getattr(seq_reg, name)
        ).all(), name
    assert (bulk_reg.floors == seq_reg.floors).all()
    with pytest.raises(ValueError):
        bulk_reg.bulk_load([subs[0]])


def test_churn_sync_kinds_and_match_kernel_never_retraces():
    """The device-plane sync policy: first use is a FULL push, churn is
    an INCREMENTAL column scatter, capacity growth is full again — and
    incremental churn never retraces the match kernel (stable shapes)."""
    from binquant_tpu.fanout.kernel import _match_impl

    rows = {"BTCUSDT": 0}
    reg = SubscriptionRegistry(symbol_capacity=4, capacity=32)
    reg.add(Subscription("a"), row_of=rows.get)
    dev = DevicePlanes(reg)
    assert dev.sync() == "full"
    assert dev.sync() is None  # already current

    def match_a(expect: set[str]):
        words = dev.match(
            np.array([0], np.int32),
            np.array([0], np.int32),
            np.array([0.5], np.float32),
            INVALID_REGIME_ROW,
        )
        assert _match_users(reg, words[0]) == expect

    match_a({"a"})
    traced_before = _match_impl._cache_size()
    # churn: add/update/remove resync incrementally, results stay exact
    reg.add(Subscription("b", min_strength=0.1), row_of=rows.get)
    assert dev.sync() == "incremental"
    match_a({"a", "b"})
    reg.update(Subscription("b", min_strength=0.9), row_of=rows.get)
    assert dev.sync() == "incremental"
    match_a({"a"})
    reg.remove("a")
    assert dev.sync() == "incremental"
    match_a(set())
    assert _match_impl._cache_size() == traced_before
    # growth: slot capacity doubles, planes rebuild, sync reads full
    for i in range(40):
        reg.add(Subscription(f"g{i:02d}"), row_of=rows.get)
    assert reg.capacity == 64
    assert dev.sync() == "full"
    words = dev.match(
        np.array([0], np.int32),
        np.array([0], np.int32),
        np.array([1.0], np.float32),
        INVALID_REGIME_ROW,
    )
    # the 40 growth wildcards plus b (floor 0.9 <= |1.0|)
    assert popcount_words(words) == 41


def test_symbol_row_refresh_rehomes_subscriptions():
    """Listing churn re-homes engine rows: refresh_rows re-resolves every
    explicit symbol subscription and a freed row's old bits vanish."""
    rows = {"AAAUSDT": 0, "BBBUSDT": 1}
    reg = SubscriptionRegistry(symbol_capacity=4, capacity=32)
    reg.add(
        Subscription("u", symbols=frozenset({"AAAUSDT"})), row_of=rows.get
    )
    assert reg.sym_plane[0, 0] == 1 and reg.sym_plane[1, 0] == 0
    # AAA delists, CCC claims row 0, AAA re-homes to row 2
    rows2 = {"CCCUSDT": 0, "BBBUSDT": 1, "AAAUSDT": 2}
    assert reg.refresh_rows(rows2.get, registry_version=2)
    assert reg.sym_plane[0, 0] == 0 and reg.sym_plane[2, 0] == 1
    # same version short-circuits
    assert not reg.refresh_rows(rows2.get, registry_version=2)


def test_bulk_load_duplicate_leaves_registry_untouched():
    """A duplicate user_id anywhere in the batch must fail BEFORE any
    mutation — a mid-loop failure would leave records registered without
    plane bits (device-vs-oracle divergence no later sync repairs)."""
    rng = np.random.default_rng(5)
    symbols = [f"S{i:03d}USDT" for i in range(8)]
    rows = {s: i for i, s in enumerate(symbols)}
    reg = SubscriptionRegistry(symbol_capacity=8, capacity=64)
    reg.add(Subscription("existing"), row_of=rows.get)
    before = (
        len(reg), reg.version, reg.sym_plane.copy(), reg.strat_plane.copy(),
        reg.any_masks.copy(), reg.floors.copy(),
    )
    batch = _random_population(rng, 5, symbols, rows)
    for bad in (
        batch + [Subscription("existing")],         # collides with a record
        batch + [Subscription(batch[0].user_id)],   # collides within batch
    ):
        with pytest.raises(ValueError):
            reg.bulk_load(bad, row_of=rows.get)
        assert len(reg) == before[0] and reg.version == before[1]
        assert (reg.sym_plane == before[2]).all()
        assert (reg.strat_plane == before[3]).all()
        assert (reg.any_masks == before[4]).all()
        assert (reg.floors == before[5]).all()


# -- replayed-burst parity across the four drives ----------------------------


@pytest.fixture(scope="module")
def burst_stream(tmp_path_factory):
    path = tmp_path_factory.mktemp("fanout") / "burst_16.jsonl"
    generate_replay_file(path, n_symbols=16, n_ticks=60)
    return path


def _fanout_population():
    """A deterministic population over the generated stream's symbols.
    Floors are 0.0 or unreachable so cross-drive comparison is immune to
    low-bit score divergence between batched backends (the per-drive
    oracle check still exercises real floors end-to-end)."""
    s0, s1, s2 = STRATEGY_ORDER[0], STRATEGY_ORDER[3], STRATEGY_ORDER[2]
    return [
        Subscription("all"),  # everything
        Subscription("btc_only", symbols=frozenset({"BTCUSDT"})),
        Subscription(
            "s5_fade",
            symbols=frozenset({"S005USDT"}),
            strategies=frozenset({s1}),
        ),
        Subscription("abp_fans", strategies=frozenset({s0})),
        Subscription("lsp_fans", strategies=frozenset({s2})),
        Subscription("regime_zero", regimes=frozenset({0})),
        Subscription("too_picky", min_strength=1e6),
        Subscription(
            "multi",
            symbols=frozenset({"S001USDT", "S003USDT", "S005USDT"}),
        ),
    ]


def _install_spy(engine, records: list):
    """Wrap the plane's on_fired to also run the Python oracle at the
    exact match input (fired set + tick context) and record per-signal
    ``(tick_ms, strategy, symbol, direction, device_set, oracle_set)``."""
    plane = engine.fanout
    orig = plane.on_fired

    def spy(fired, ctx_scalars, tick_ms=None):
        stats = orig(fired, ctx_scalars, tick_ms=tick_ms)
        regime = int(ctx_scalars.get("market_regime", -1))
        valid = bool(ctx_scalars.get("valid", False))
        oracle = plane.subscriptions.match_oracle(
            [
                (s.strategy, s.symbol, float(s.value.score or 0.0))
                for s in fired
            ],
            regime if valid and 0 <= regime < len(MarketRegimeCode) else None,
        )
        for s, want in zip(fired, oracle):
            frame, words, _t = s.fanout_frame
            records.append(
                (
                    s.tick_ms,
                    s.strategy,
                    s.symbol,
                    str(s.value.direction),
                    frozenset(_match_users(plane.subscriptions, words)),
                    frozenset(want),
                )
            )
        return stats

    plane.on_fired = spy


def _drive(engine, seq, mode: str):
    out = []

    async def go():
        if mode == "scanned":
            out.extend(await engine.process_ticks_scanned(seq))
        elif mode == "backtest":
            out.extend(await engine.process_ticks_backtest(seq))
        else:
            for now_ms, klines in seq:
                for k in klines:
                    engine.ingest(k)
                out.extend(await engine.process_tick(now_ms=now_ms))
        out.extend(await engine.flush_pending())
        await engine.aclose_fanout()

    asyncio.run(go())
    return out


def _fanout_engine(**kwargs):
    return make_stub_engine(
        capacity=CAPACITY, window=WINDOW, fanout=True, **kwargs
    )


def test_replayed_burst_recipient_parity_all_drives(burst_stream):
    """ISSUE 14 acceptance: on a replayed burst every drive's device
    recipient sets equal the Python oracle's, and the (tick, signal,
    recipients) streams are identical across serial / donated / scanned /
    backtest — the match runs at the one shared finalize."""
    seq = _tick_seq(burst_stream)
    streams = {}
    engines = {
        "serial": _fanout_engine(),
        "donated": _fanout_engine(donate=True),
        "scanned": _fanout_engine(),
        "backtest": _fanout_engine(incremental=False, donate=False),
    }
    for mode, engine in engines.items():
        for sub in _fanout_population():
            engine.fanout.subscribe(sub)
        records: list = []
        _install_spy(engine, records)
        _drive(engine, seq, mode)
        # device == oracle, per signal, per drive
        for rec in records:
            assert rec[4] == rec[5], (mode, rec)
        assert engine.fanout.match_dispatches > 0, mode
        streams[mode] = [r[:5] for r in records]
    assert len(streams["serial"]) > 0
    # non-vacuous: someone matched besides the wildcard-only users
    assert any(len(r[4]) > 2 for r in streams["serial"])
    # the too_picky floor (1e6) never matched anyone
    assert all("too_picky" not in r[4] for r in streams["serial"])
    for mode in ("donated", "scanned", "backtest"):
        assert streams[mode] == streams["serial"], mode


def test_fanout_off_is_byte_identical_and_unwired(burst_stream):
    """BQT_FANOUT=0 (the tier-1 default): no plane, no kernel, no frame
    stamps — and the emitted signal stream is identical to the plane-on
    drive (the match is purely additive)."""
    seq = _tick_seq(burst_stream)

    def tuples(fired):
        return [
            (s.tick_ms, s.strategy, s.symbol, str(s.value.direction))
            for s in fired
        ]

    off = make_stub_engine(capacity=CAPACITY, window=WINDOW, fanout=False)
    assert off.fanout is None
    off_fired = _drive(off, seq, "serial")
    assert off.health_snapshot()["fanout"] == {"enabled": False}
    assert all(s.fanout_frame is None for s in off_fired)

    on = _fanout_engine()
    on.fanout.subscribe(Subscription("watcher"))
    on_fired = _drive(on, seq, "serial")
    assert tuples(on_fired) == tuples(off_fired)
    assert len(off_fired) > 0
    snap = on.health_snapshot()["fanout"]
    assert snap["enabled"] and snap["subscriptions"]["users"] == 1
    assert snap["published"] == len(on_fired)


# -- hub: sockets, shed, cursor resume ---------------------------------------


async def _ws_connect(port: int, user: str, cursor: str | None = None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    q = f"/ws?user={user}" + (f"&cursor={cursor}" if cursor else "")
    writer.write(
        (
            f"GET {q} HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
            "Connection: Upgrade\r\nSec-WebSocket-Key: dGhlIHNhbXBsZQ==\r\n"
            "Sec-WebSocket-Version: 13\r\n\r\n"
        ).encode()
    )
    await writer.drain()
    status = await reader.readline()
    while (await reader.readline()) not in (b"\r\n", b""):
        pass
    return reader, writer, status.decode()


async def _ws_read_json(reader):
    from binquant_tpu.fanout.hub import ws_read_frame

    opcode, payload = await ws_read_frame(reader)
    assert opcode == 0x1
    return json.loads(payload)


async def _sse_connect(port: int, user: str, cursor: str | None = None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    q = f"/sse?user={user}" + (f"&cursor={cursor}" if cursor else "")
    writer.write(f"GET {q} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    status = await reader.readline()
    while (await reader.readline()) not in (b"\r\n", b""):
        pass
    return reader, writer, status.decode()


async def _sse_read_json(reader):
    sid = data = None
    while True:
        line = (await reader.readline()).decode().strip()
        if line.startswith("id:"):
            sid = int(line[3:].strip())
        elif line.startswith("data:"):
            data = json.loads(line[5:].strip())
        elif not line and data is not None:
            return sid, data


def _mk_plane(tmp_path, conn_queue_max=256, **kw):
    from binquant_tpu.fanout.plane import FanoutPlane

    class _Rows:
        capacity = 8
        version = 1

        @staticmethod
        def row_of(name):
            return {"BTCUSDT": 0}.get(name)

        @staticmethod
        def to_mapping():
            return {"BTCUSDT": 0}

    return FanoutPlane(
        _Rows(),
        capacity=64,
        outbox_path=str(tmp_path / "outbox.jsonl"),
        conn_queue_max=conn_queue_max,
        **kw,
    )


def _frame(plane, seq_users: set[str], i: int):
    """Mint + publish one synthetic frame addressed to ``seq_users``."""
    slots = sorted(plane.subscriptions.slot_of(u) for u in seq_users)
    bits = np.zeros(plane.subscriptions.capacity, bool)
    bits[slots] = True
    words = pack_words_np(bits[None, :])[0]
    frame = {
        "seq": plane.seq,
        "trace_id": f"trace{i // 2}",
        "tick_seq": i // 2,
        "strategy": "mrf",
        "symbol": "BTCUSDT",
        "direction": "LONG",
        "score": 0.5,
        "recipients": len(slots),
    }
    plane.seq += 1
    if plane.outbox is not None:
        plane.outbox.append(frame, words)
    plane.hub.broadcast(frame, words)
    return frame


def test_hub_ws_sse_delivery_shed_and_cursor_resume(tmp_path):
    """The broadcast tier over real sockets: WS and SSE clients receive
    exactly their addressed frames; a stalled consumer's bounded queue
    sheds with a counted reason while everyone else stays fresh; a
    reconnect with a seq cursor (and a trace/tick provenance cursor)
    replays the gap from the outbox."""
    from binquant_tpu.fanout.hub import _Connection

    plane = _mk_plane(tmp_path, conn_queue_max=64)
    for u in ("amy", "ben", "cal"):
        plane.subscriptions.add(Subscription(u))

    async def go():
        port = await plane.serve(0, host="127.0.0.1")
        r_amy, w_amy, st = await _ws_connect(port, "amy")
        assert "101" in st
        r_ben, w_ben, st = await _sse_connect(port, "ben")
        assert "200" in st
        # unknown user refused with 404 (subscribe first, then connect)
        r_x, w_x = await asyncio.open_connection("127.0.0.1", port)
        w_x.write(b"GET /ws?user=nobody HTTP/1.1\r\nHost: x\r\n\r\n")
        await w_x.drain()
        assert "404" in (await r_x.readline()).decode()
        w_x.close()

        # cal is a STALLED consumer: a registered connection whose writer
        # task never drains its 2-slot queue (a wedged peer, modeled at
        # the queue seam — a live socket's kernel buffer would mask it)
        cal = _Connection(
            "cal", plane.subscriptions.slot_of("cal"), "ws", queue_max=2
        )
        plane.hub._conns.add(cal)

        sent = []
        for i in range(6):
            to = {"amy", "ben", "cal"} if i % 2 == 0 else {"amy"}
            sent.append((_frame(plane, to, i), to))
        # amy (ws) sees all six, ben (sse) the three addressed to him
        for frame, _ in sent:
            got = await asyncio.wait_for(_ws_read_json(r_amy), 5)
            assert got["seq"] == frame["seq"]
        for frame, to in sent:
            if "ben" not in to:
                continue
            sid, got = await asyncio.wait_for(_sse_read_json(r_ben), 5)
            assert sid == frame["seq"] == got["seq"]
        # cal was addressed 3 frames into a 2-slot queue: the overflow
        # shed with a counted reason and the connection marked gapped
        assert cal.dropped == 1 and cal.gapped
        assert plane.hub.shed == 1
        plane.hub._conns.discard(cal)

        # reconnect with a seq cursor: the outbox replays cal's gap
        r_cal2, w_cal2, st = await _ws_connect(port, "cal", cursor="-1")
        assert "101" in st
        cal_seqs = []
        for _ in range(3):  # frames 0, 2, 4 were addressed to cal
            got = await asyncio.wait_for(_ws_read_json(r_cal2), 5)
            cal_seqs.append(got["seq"])
        assert cal_seqs == [0, 2, 4]
        assert plane.hub.resumed >= 3

        # trace/tick cursor resolves through the outbox to that traced
        # tick's LAST frame and resumes strictly after it
        r_amy2, w_amy2, st = await _sse_connect(
            port, "amy", cursor="trace1/1"
        )
        sid, got = await asyncio.wait_for(_sse_read_json(r_amy2), 5)
        assert sid == 4  # trace1/1 covers seqs 2+3 -> resume at 4
        assert plane.hub.frames_sent >= 13
        for w in (w_amy, w_ben, w_amy2, w_cal2):
            w.close()
        await plane.aclose()

    asyncio.run(go())


def test_outbox_rotation_and_cursor_resolution(tmp_path):
    from binquant_tpu.fanout.hub import BroadcastOutbox

    path = tmp_path / "outbox.jsonl"
    box = BroadcastOutbox(path, cap=8)
    words = np.array([1], np.uint32)  # slot 0
    for i in range(20):
        box.append(
            {"seq": i, "trace_id": f"t{i}", "tick_seq": i}, words
        )
    # at cap the live file swapped to the .1 generation (O(1) rename, no
    # content rewrite); retention stays within cap..2*cap entries
    assert box.rotations >= 1
    entries = box.entries()
    assert len(entries) <= 16 and entries[-1][0]["seq"] == 19
    first_kept = entries[0][0]["seq"]
    # seq cursor + trace/tick cursor + unresolvable cursor
    assert box.resolve_cursor("17") == 17
    assert box.resolve_cursor(f"t{first_kept}/{first_kept}") == first_kept
    assert box.resolve_cursor("t0/0") is None  # rotated out
    assert box.resolve_cursor("garbage") is None
    replayed = box.replay_after(17, slot=0)
    assert [f["seq"] for f in replayed] == [18, 19]
    assert box.replay_after(17, slot=1) == []
    box.close()
    # reopen counts the LIVE generation's lines toward the rotation
    # budget, sees both generations, and stays size-bounded as appends
    # continue
    box2 = BroadcastOutbox(path, cap=8)
    assert [f["seq"] for f, _ in box2.entries()] == [
        f["seq"] for f, _ in entries
    ]
    for i in range(20, 36):
        box2.append({"seq": i, "trace_id": f"t{i}", "tick_seq": i}, words)
    assert box2.rotations >= 1
    entries2 = box2.entries()
    assert len(entries2) <= 16 and entries2[-1][0]["seq"] == 35
    box2.close()


def test_fanout_sink_rides_the_delivery_plane(tmp_path):
    """The broadcast tier as a PR-13 consumer group: with the delivery
    plane on, finalize only stamps the frame; the hub handoff happens on
    the fanout lane's worker, and a connected subscriber still receives
    the frame (autotrade/telegram lanes unaffected)."""
    engine = make_stub_engine(
        capacity=16,
        window=WINDOW,
        fanout=True,
        delivery=True,
        delivery_wal=str(tmp_path / "wal.jsonl"),
        delivery_overrides={"delivery_backoff_s": 0.001},
    )
    assert engine.fanout is not None and engine.fanout.sink_attached
    assert "fanout" in engine.delivery._lanes
    engine.fanout.subscribe(Subscription("amy"))

    from binquant_tpu.io.emission import FiredSignal
    from binquant_tpu.schemas import SignalsConsumer

    value = SignalsConsumer(
        autotrade=False,
        current_price=42.0,
        direction="LONG",
        algorithm_name="mrf",
        symbol="TESTUSDT",
        score=0.7,
    )
    signal = FiredSignal(
        STRATEGY_ORDER[0],
        "TESTUSDT",
        0,
        value,
        "- Action: LONG ENTRY\n- msg",
        {"symbol": "TESTUSDT", "algorithm_name": "mrf"},
    )
    signal.trace_id, signal.tick_seq = "tr0", 1

    async def go():
        port = await engine.fanout.serve(0, host="127.0.0.1")
        reader, writer, st = await _ws_connect(port, "amy")
        assert "101" in st
        engine.fanout.on_fired([signal], {"valid": False}, tick_ms=900)
        assert signal.fanout_frame is not None
        engine.delivery.start()
        engine.delivery.enqueue_fired(signal, tick_ms=900)
        assert await engine.delivery.drain(timeout_s=5.0)
        got = await asyncio.wait_for(_ws_read_json(reader), 5)
        assert got["symbol"] == "TESTUSDT" and got["recipients"] == 1
        snap = engine.health_snapshot()
        assert snap["delivery"]["sinks"]["fanout"]["acked"] == 1
        assert snap["delivery"]["sinks"]["telegram"]["acked"] == 1
        assert snap["fanout"]["behind_delivery"]
        writer.close()
        await engine.aclose_delivery()
        await engine.aclose_fanout()

    asyncio.run(go())
    assert len(engine._telegram_sent) == 1


def test_plane_seq_resumes_from_persistent_outbox(tmp_path):
    """A plane reopening an existing outbox seeds its frame seq PAST the
    retained tail — post-restart frames must not collide with logged
    seqs (a collision hides them from every cursor replay)."""
    first = _mk_plane(tmp_path)
    first.subscriptions.add(Subscription("amy"))
    for i in range(3):
        _frame(first, {"amy"}, i)
    assert first.seq == 3
    first.outbox.close()

    second = _mk_plane(tmp_path)
    assert second.seq == 3
    second.subscriptions.add(Subscription("amy"))
    _frame(second, {"amy"}, 99)
    replayed = second.outbox.replay_after(
        1, slot=second.subscriptions.slot_of("amy")
    )
    assert [f["seq"] for f in replayed] == [2, 3]
    second.outbox.close()


def test_unsubscribe_closes_live_connection(tmp_path):
    """Unsubscribing a user closes their open connections — the freed
    slot may be reclaimed, and a connection still bound to it would
    receive the next claimant's frames (cross-user misdelivery)."""
    plane = _mk_plane(tmp_path)
    plane.subscriptions.add(Subscription("amy"))
    plane.subscriptions.add(Subscription("mallory"))

    async def go():
        port = await plane.serve(0, host="127.0.0.1")
        r_mal, w_mal, st = await _ws_connect(port, "mallory")
        assert "101" in st
        assert plane.hub.connections == 1
        slot_before = plane.subscriptions.slot_of("mallory")
        assert plane.unsubscribe("mallory") == slot_before
        assert plane.hub.connections == 0
        # the freed slot is reclaimed off the free list by a new user;
        # mallory's old socket gets a clean EOF, not bob's frames
        plane.subscriptions.add(Subscription("bob"))
        assert plane.subscriptions.slot_of("bob") == slot_before
        _frame(plane, {"bob"}, 0)
        from binquant_tpu.fanout.hub import ws_read_frame

        with pytest.raises(ConnectionError):
            await asyncio.wait_for(ws_read_frame(r_mal), 5)
        w_mal.close()
        await plane.aclose()

    asyncio.run(go())


def test_match_follows_listing_churn_rehoming(tmp_path):
    """The match resolves fired symbols by NAME against the registry the
    planes were synced to — not a dispatch-time row. A re-homed symbol
    still reaches its subscribers; a delisted one matches wildcards
    only (the planes' always-empty no-row bucket)."""
    from types import SimpleNamespace

    class _Rows:
        capacity = 8
        version = 1
        mapping = {"AAAUSDT": 0, "BBBUSDT": 1}

        @classmethod
        def row_of(cls, name):
            return cls.mapping.get(name)

    from binquant_tpu.fanout.plane import FanoutPlane

    plane = FanoutPlane(_Rows, capacity=64, outbox_path=None)
    plane.subscribe(Subscription("fan", symbols=frozenset({"AAAUSDT"})))
    plane.subscribe(Subscription("wild"))

    def fired(symbol):
        return SimpleNamespace(
            strategy=STRATEGY_ORDER[0],
            symbol=symbol,
            value=SimpleNamespace(score=0.9, direction="LONG", autotrade=False),
            trace_id="t0",
            tick_seq=0,
            fanout_frame=None,
        )

    def recipients(symbol):
        sig = fired(symbol)
        plane.on_fired([sig], {"valid": False}, tick_ms=900)
        _frame_dict, words, _t = sig.fanout_frame
        return set(
            plane.subscriptions.users_of_slots(
                np.flatnonzero(unpack_words_np(words))
            )
        )

    assert recipients("AAAUSDT") == {"fan", "wild"}
    # listing churn re-homes AAAUSDT from row 0 to row 2 (row 0 freed)
    _Rows.mapping = {"CCCUSDT": 0, "BBBUSDT": 1, "AAAUSDT": 2}
    _Rows.version = 2
    assert recipients("AAAUSDT") == {"fan", "wild"}
    assert recipients("CCCUSDT") == {"wild"}  # row 0's old bits vanished
    # AAAUSDT delists entirely: explicit subscriber silent, wildcard not
    _Rows.mapping = {"CCCUSDT": 0, "BBBUSDT": 1}
    _Rows.version = 3
    assert recipients("AAAUSDT") == {"wild"}


# -- report golden ------------------------------------------------------------


def test_fanout_report_golden(tmp_path):
    import sys as _sys

    _sys.path.insert(
        0, str(Path(__file__).resolve().parent.parent / "tools")
    )
    import fanout_report

    events = [
        {"event": "fanout_churn", "op": "subscribe", "user": "amy", "slot": 0},
        {"event": "fanout_churn", "op": "subscribe", "user": "cal", "slot": 1},
        {"event": "fanout_churn", "op": "update", "user": "amy", "slot": 0},
        {"event": "fanout_churn", "op": "unsubscribe", "user": "cal",
         "slot": 1},
        {"event": "fanout_publish", "seq": 0, "strategy": "mrf",
         "symbol": "BTCUSDT", "recipients": 3, "trace_id": "t0",
         "tick_seq": 0},
        {"event": "fanout_publish", "seq": 1, "strategy": "mrf",
         "symbol": "BTCUSDT", "recipients": 2, "trace_id": "t1",
         "tick_seq": 1},
        {"event": "fanout_publish", "seq": 2, "strategy": "abp",
         "symbol": "ETHUSDT", "recipients": 1, "trace_id": "t1",
         "tick_seq": 1},
        {"event": "fanout_shed", "reason": "slow_consumer", "user": "cal",
         "transport": "ws", "seq": 1},
        {"event": "fanout_shed", "reason": "slow_consumer", "user": "cal",
         "transport": "ws", "seq": 2},
        {"event": "fanout_resume", "user": "cal", "transport": "ws",
         "cursor": "t0/0", "replayed": 2},
        {"event": "fanout_conn_close", "user": "amy", "transport": "ws",
         "delivered": 3, "dropped": 0, "replayed": 0, "gapped": False,
         "lag_ms_mean": 1.25, "lag_ms_max": 2.5},
        {"event": "fanout_conn_close", "user": "cal", "transport": "ws",
         "delivered": 1, "dropped": 2, "replayed": 2, "gapped": True,
         "lag_ms_mean": None, "lag_ms_max": 0.0},
        {"event": "fanout_summary", "users": 1, "published": 3,
         "matched_recipients": 6, "match_dispatches": 2,
         "recompiles": {"full": 1, "incremental": 1}, "frames_sent": 6,
         "shed": 2, "resumed": 2,
         "top_users": [{"user": "amy", "delivered": 3},
                       {"user": "cal", "delivered": 1}]},
    ]
    log = tmp_path / "events.jsonl"
    with open(log, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    expected = "\n".join([
        "churn    subscribe=2 unsubscribe=1 update=1 (net +1)",
        "resume   cal          (ws) cursor=t0/0 replayed=2",
        "publish  abp/ETHUSDT  1 frame, 1 recipients",
        "publish  mrf/BTCUSDT  2 frames, 5 recipients",
        "shed     slow_consumer = 2",
        "",
        "connection   tport  sent  drop replay gap  lag_mean  lag_max",
        "amy          ws        3     0      0  no     1.2ms    2.5ms",
        "cal          ws        1     2      2 yes         -    0.0ms",
        "",
        "summary  users=1 published=3 recipients=6 dispatches=2"
        " recompiles=full:1/incremental:1",
        "hub      frames_sent=6 shed=2 resumed=2",
        "hottest  top 2 subscriptions:",
        "  amy                       3 delivered",
        "  cal                       1 delivered",
    ])
    assert fanout_report.render_report(
        fanout_report.load_fanout_events(log)
    ) == expected


# -- the 1M-subscription smoke (slow lane) -----------------------------------


@pytest.mark.slow
def test_million_subscription_match_single_dispatch():
    """ISSUE 14 acceptance: ONE dispatch joins >=1M subscriptions against
    a tick's fired slots, and the packed output is bit-identical to a
    vectorized numpy oracle over the whole population."""
    n = 1_000_000
    reg = SubscriptionRegistry(symbol_capacity=8, capacity=n)
    strat_of = np.arange(n) % len(STRATEGY_ORDER)
    floor_of = np.float32((np.arange(n) % 100) / 100.0)
    subs = [
        Subscription(
            f"u{i}",
            strategies=frozenset({STRATEGY_ORDER[strat_of[i]]}),
            min_strength=float(floor_of[i]),
        )
        for i in range(n)
    ]
    assert reg.bulk_load(subs) == n
    dev = DevicePlanes(reg)
    assert dev.sync() == "full"
    fired_strats = np.array([0, 3, 7, 13], np.int32)
    fired_rows = np.zeros(4, np.int32)
    scores = np.array([0.55, -0.10, 0.999, 0.31], np.float32)
    words = dev.match(fired_rows, fired_strats, scores, INVALID_REGIME_ROW)
    slots = np.arange(n)
    expect = np.zeros((4, n), bool)
    for k in range(4):
        expect[k] = (strat_of == fired_strats[k]) & (
            np.abs(scores[k]) >= floor_of
        )
    assert (words == pack_words_np(expect)).all()
    # the match actually fanned out at scale
    assert popcount_words(words) == int(expect.sum()) > 100_000


@pytest.mark.slow
def test_fanout_chaos_drill():
    """Churn storm + stalled consumers + reconnect-with-cursor through
    the chaos seams — every invariant green (see
    sim/chaos.py fanout_chaos_drill)."""
    from binquant_tpu.sim.chaos import fanout_chaos_drill

    facts = fanout_chaos_drill()
    assert facts["ok"], {
        k: v for k, v in facts["checks"].items() if not v
    }


# -- delta plane, compaction, snapshot-warm boot (ISSUE 20) -------------------


def _devices_bit_equal(a: DevicePlanes, b: DevicePlanes) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a._arrays, b._arrays)
    )


def test_delta_stream_device_equals_bulk_oracle():
    """ISSUE 20 acceptance core: a randomized delta stream — adds,
    updates, removes, duplicate re-adds, capacity growth past the
    initial allocation, and an explicit mid-stream compaction — leaves
    the incrementally-patched device planes BIT-IDENTICAL to a device
    freshly bulk-rebuilt from the surviving population after every
    burst."""
    rng = np.random.default_rng(1234)
    symbols = [f"S{i:03d}USDT" for i in range(8)]
    rows = {s: i for i, s in enumerate(symbols)}
    reg = SubscriptionRegistry(symbol_capacity=8, capacity=32)
    dev = DevicePlanes(reg)
    assert dev.sync() == "full"
    live: dict[str, Subscription] = {}
    next_id = 0
    kinds: list[str] = []
    for burst in range(12):
        for _ in range(25):
            op = rng.random()
            if op < 0.45 or not live:
                proto = _random_population(rng, 1, symbols, rows)[0]
                sub = Subscription(
                    f"d{next_id:05d}",
                    symbols=proto.symbols,
                    strategies=proto.strategies,
                    regimes=proto.regimes,
                    min_strength=proto.min_strength,
                )
                next_id += 1
                reg.add(sub, row_of=rows.get)
                live[sub.user_id] = sub
            elif op < 0.55:
                # duplicate: re-adding the identical record keeps the
                # slot and must leave the delta stream coherent
                uid = str(rng.choice(sorted(live)))
                slot = reg.slot_of(uid)
                assert reg.add(live[uid], row_of=rows.get) == slot
            elif op < 0.8:
                uid = str(rng.choice(sorted(live)))
                old = live[uid]
                new = Subscription(
                    uid,
                    symbols=old.symbols,
                    strategies=old.strategies,
                    regimes=old.regimes,
                    min_strength=float(np.float32(rng.random())),
                )
                reg.update(new, row_of=rows.get)
                live[uid] = new
            else:
                uid = str(rng.choice(sorted(live)))
                reg.remove(uid)
                del live[uid]
        if burst == 6:
            reg.compact()  # tombstone fold mid-stream
        kind = dev.sync()
        if kind is not None:
            kinds.append(kind)
        fresh = SubscriptionRegistry(
            symbol_capacity=8, capacity=reg.capacity
        )
        for uid, sub in sorted(
            live.items(), key=lambda kv: reg.slot_of(kv[0])
        ):
            fresh._next_slot = reg.slot_of(uid)
            fresh.add(sub, row_of=rows.get)
        fresh_dev = DevicePlanes(fresh)
        assert fresh_dev.sync() == "full"
        assert _devices_bit_equal(dev, fresh_dev), burst
    # the stream exercised BOTH sync kinds: steady churn patches
    # incrementally; growth wraps + the compaction force full pushes
    assert "incremental" in kinds
    assert kinds.count("full") >= 2
    assert reg.capacity > 32


def test_compaction_threshold_and_match_preserved(tmp_path):
    """maybe_compact honors the fragmentation threshold; the pass
    re-packs slots, shrinks capacity, advances moved users' replay
    floors to the current seq, and the next device sync (full) matches
    exactly the surviving population."""
    plane = _mk_plane(tmp_path, compact_frac=0.0)
    for i in range(80):
        plane.subscribe(Subscription(f"u{i:03d}", min_strength=0.1))
    assert plane.sync_device() == "full"
    plane.seq = 7
    for i in range(0, 80, 2):
        plane.unsubscribe(f"u{i:03d}")
    assert not plane.maybe_compact()  # frac 0 = compaction off
    plane.compact_frac = 0.6
    assert not plane.maybe_compact()  # 40/80 tombstones < 0.6
    plane.compact_frac = 0.0  # keep the churn path from firing it early
    for i in range(1, 21, 2):
        plane.unsubscribe(f"u{i:03d}")
    plane.compact_frac = 0.6
    assert plane.maybe_compact()  # 50/80 crosses the threshold
    reg = plane.subscriptions
    assert plane.compactions == 1
    assert reg._next_slot == 30 and reg.fragmentation() == 0.0
    assert reg.capacity == 64  # shrunk back from the growth to 128
    survivors = {f"u{i:03d}" for i in range(21, 80, 2)}
    for uid in survivors:
        slot = reg.slot_of(uid)
        assert slot < 30
        # every survivor moved: pre-compaction frames must not replay
        # against the new layout
        assert plane._slot_min_seq[slot] == 7
    assert plane.sync_device() == "full"
    words = plane._device.match(
        np.array([0], np.int32),
        np.array([0], np.int32),
        np.array([0.5], np.float32),
        INVALID_REGIME_ROW,
    )
    oracle = reg.match_oracle([(STRATEGY_ORDER[0], "BTCUSDT", 0.5)], None)
    assert _match_users(reg, words[0]) == oracle[0] == survivors


def _mk_snap_plane(tmp_path, rows_map=None, sym_capacity=8, **kw):
    from binquant_tpu.fanout.plane import FanoutPlane

    mapping = dict(rows_map or {"BTCUSDT": 0, "ETHUSDT": 1})

    class _Rows:
        capacity = sym_capacity
        version = 1

        @staticmethod
        def row_of(name):
            return mapping.get(name)

        @staticmethod
        def to_mapping():
            return dict(mapping)

    kw.setdefault("snapshot_path", str(tmp_path / "fanout.snap.npz"))
    return FanoutPlane(_Rows(), capacity=64, **kw)


def test_snapshot_roundtrip_restores_planes_lazily(tmp_path):
    """Warm boot is an array load: the restored registry's planes are
    bit-identical to the donor's, records stay columnar until touched,
    per-slot replay floors survive, and the device takes exactly one
    full push before matching like the donor."""
    rng = np.random.default_rng(11)
    symbols = ["BTCUSDT", "ETHUSDT", "XRPUSDT", "ADAUSDT"]
    rows_map = {s: i for i, s in enumerate(symbols)}
    plane = _mk_snap_plane(tmp_path, rows_map=rows_map)
    subs = _random_population(rng, 40, symbols, rows_map)
    assert plane.bulk_load(subs) == 40
    plane.seq = 5
    for i in range(10):
        plane.unsubscribe(f"user{i:04d}")
    plane.subscribe(Subscription("late", min_strength=0.25))  # floor 5
    assert plane.sync_device() == "full"
    assert plane.maybe_save_snapshot()
    reg = plane.subscriptions

    cold = _mk_snap_plane(tmp_path, rows_map=rows_map)
    assert cold.try_restore_snapshot()
    creg = cold.subscriptions
    assert len(creg) == len(reg) == 31
    for name in (
        "sym_plane", "strat_plane", "regime_plane", "any_masks", "floors"
    ):
        assert np.array_equal(getattr(creg, name), getattr(reg, name)), name
    assert creg._records.lazy_count == 31  # nothing materialized yet
    assert creg.slot_of("late") == reg.slot_of("late")
    got = creg.get("late")
    assert got is not None and got.min_strength == 0.25
    assert creg._records.lazy_count == 30  # one record touched
    assert cold._slot_min_seq == plane._slot_min_seq
    assert cold.seq >= plane.seq
    # matching engine fingerprint: archived symbol rows adopted verbatim
    assert creg._rows_version == cold.engine_registry.version
    assert cold.sync_device() == "full"
    words = cold._device.match(
        np.array([0], np.int32),
        np.array([1], np.int32),
        np.array([0.6], np.float32),
        INVALID_REGIME_ROW,
    )
    oracle = reg.match_oracle([(STRATEGY_ORDER[1], "BTCUSDT", 0.6)], None)
    assert _match_users(creg, words[0]) == oracle[0]


def test_snapshot_shards_roundtrip_and_torn_rejection(tmp_path):
    """The sharded sidecar reassembles exactly; a missing sibling or a
    sibling from an older save generation (stale nonce) is a torn save —
    rejected into a cold start. An odd mesh falls back to one archive."""
    from binquant_tpu.io.checkpoint import _shard_path

    plane = _mk_snap_plane(tmp_path)
    for i in range(20):
        plane.subscribe(
            Subscription(
                f"s{i:02d}",
                symbols=frozenset({"ETHUSDT"}) if i % 3 else None,
                min_strength=0.1 * (i % 4),
            )
        )
    info = plane.save_snapshot(n_shards=4)
    assert info["shard_count"] == 4
    manifest = plane.snapshot_path
    sibs = [_shard_path(manifest, k, 4) for k in range(1, 4)]
    assert all(p.exists() for p in sibs)

    warm = _mk_snap_plane(tmp_path)
    assert warm.try_restore_snapshot()
    wreg, reg = warm.subscriptions, plane.subscriptions
    for name in (
        "sym_plane", "strat_plane", "regime_plane", "any_masks", "floors"
    ):
        assert np.array_equal(getattr(wreg, name), getattr(reg, name)), name

    # torn: missing sibling
    stale = sibs[1].read_bytes()
    sibs[1].unlink()
    cold = _mk_snap_plane(tmp_path)
    assert not cold.try_restore_snapshot()
    assert len(cold.subscriptions) == 0

    # torn: sibling left over from a previous save (nonce mismatch)
    plane.save_snapshot(n_shards=4)
    sibs[1].write_bytes(stale)
    cold2 = _mk_snap_plane(tmp_path)
    assert not cold2.try_restore_snapshot()

    # a mesh that doesn't divide the symbol axis saves monolithic
    assert plane.save_snapshot(n_shards=3)["shard_count"] == 1


def test_snapshot_version_geometry_and_torn_manifest_rejection(tmp_path):
    """Restore rejects (cold start, never a crash): a future layout
    version, plane geometry that disagrees with the running engine, and
    a truncated manifest archive."""
    import binquant_tpu.fanout.snapshot as snap_mod

    plane = _mk_snap_plane(tmp_path)
    plane.subscribe(Subscription("amy"))
    plane.save_snapshot()
    target = plane.snapshot_path

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(snap_mod, "FANOUT_SNAP_VERSION", 99)
        cold = _mk_snap_plane(tmp_path)
        assert not cold.try_restore_snapshot()

    # engine grew its symbol axis while we were down: row meaning changed
    cold = _mk_snap_plane(
        tmp_path, rows_map={"BTCUSDT": 0, "ETHUSDT": 1, "XRPUSDT": 2},
        sym_capacity=16,
    )
    assert not cold.try_restore_snapshot()
    assert len(cold.subscriptions) == 0

    target.write_bytes(target.read_bytes()[:100])  # torn manifest
    cold2 = _mk_snap_plane(tmp_path)
    assert not cold2.try_restore_snapshot()


def test_snapshot_fingerprint_mismatch_forces_row_refresh(tmp_path):
    """Listing churn while the process was down: the archive restores
    (population + floors are still sound) but its symbol rows were
    compiled against a different engine mapping — the first sync
    re-resolves every explicit subscription against the CURRENT rows."""
    donor = _mk_snap_plane(tmp_path)  # BTC->0, ETH->1
    donor.subscribe(Subscription("pin", symbols=frozenset({"BTCUSDT"})))
    slot = donor.subscriptions.slot_of("pin")
    assert (donor.subscriptions.sym_plane[0, slot >> 5] >> (slot & 31)) & 1
    donor.save_snapshot()

    swapped = _mk_snap_plane(
        tmp_path, rows_map={"ETHUSDT": 0, "BTCUSDT": 1}
    )
    assert swapped.try_restore_snapshot()
    reg = swapped.subscriptions
    assert reg._rows_version is None  # archived rows marked unsound
    assert swapped.sync_device() == "full"
    assert (reg.sym_plane[1, slot >> 5] >> (slot & 31)) & 1  # re-homed
    assert not (reg.sym_plane[0, slot >> 5] >> (slot & 31)) & 1


def test_snapshot_restore_excludes_post_save_frames_from_replay(tmp_path):
    """Misdelivery guard across a restart: frames published AFTER the
    snapshot was taken were addressed by post-save churn the restored
    registry can't see (a recycled slot points at a different user) —
    the hub excludes that seq range from every cursor replay."""
    snap = str(tmp_path / "fan.snap.npz")
    plane = _mk_plane(tmp_path, snapshot_path=snap)
    for u in ("amy", "ben"):
        plane.subscribe(Subscription(u))
    for i in range(6):
        _frame(plane, {"amy", "ben"} if i % 2 else {"amy"}, i)
    saved_seq = plane.seq
    assert plane.maybe_save_snapshot()
    # post-save churn: ben leaves, cal claims his slot, frames for cal
    ben_slot = plane.subscriptions.slot_of("ben")
    plane.unsubscribe("ben")
    assert plane.subscribe(Subscription("cal")) == ben_slot
    for i in range(6, 9):
        _frame(plane, {"cal"}, i)
    plane.outbox.close()

    warm = _mk_plane(tmp_path, snapshot_path=snap)
    assert warm.try_restore_snapshot()
    assert warm.subscriptions.slot_of("ben") == ben_slot
    assert "cal" not in warm.subscriptions
    assert warm.hub.replay_excluded == (saved_seq, 8)
    assert warm.seq == 9

    async def go():
        port = await warm.serve(0, host="127.0.0.1")
        reader, writer, st = await _ws_connect(port, "ben", cursor="0")
        assert "101" in st
        seqs = []
        for _ in range(3):  # pre-save frames only — 6..8 are excluded
            got = await asyncio.wait_for(_ws_read_json(reader), 5)
            seqs.append(got["seq"])
        assert seqs == [1, 3, 5]
        # prove nothing from the excluded range follows: the next frame
        # ben reads is a live one published after the reconnect
        _frame(warm, {"ben"}, 99)
        got = await asyncio.wait_for(_ws_read_json(reader), 5)
        assert got["seq"] == 9
        writer.close()
        await warm.aclose()

    asyncio.run(go())


def test_hub_tail_resume_skips_outbox_and_counts_eviction(tmp_path):
    """Satellite 6: a reconnect whose cursor lands inside the retained
    tail ring replays from memory — the persistent outbox is never
    scanned — while an evicted cursor falls back to the outbox with a
    counted reason and still replays in full."""
    plane = _mk_plane(tmp_path, resume_tail=8)
    for u in ("amy", "ben"):
        plane.subscribe(Subscription(u))
    for i in range(12):
        _frame(plane, {"amy", "ben"} if i % 2 else {"ben"}, i)
    assert plane.hub.snapshot()["tail_retained"] == 8

    async def go():
        port = await plane.serve(0, host="127.0.0.1")

        async def _no_scan():
            raise AssertionError("outbox scanned on an in-window cursor")

        real_scan = plane.hub._scan_outbox_stable
        plane.hub._scan_outbox_stable = _no_scan
        reader, writer, st = await _ws_connect(port, "amy", cursor="7")
        assert "101" in st
        seqs = []
        for _ in range(2):  # amy frames past 7 in the ring: 9, 11
            got = await asyncio.wait_for(_ws_read_json(reader), 5)
            seqs.append(got["seq"])
        assert seqs == [9, 11]
        assert plane.hub.tail_resumes == 2  # counted per replayed frame
        assert not plane.hub.resume_fallbacks
        plane.hub._scan_outbox_stable = real_scan

        r2, w2, st = await _ws_connect(port, "ben", cursor="0")
        assert "101" in st
        ben_seqs = []
        for _ in range(11):  # ben rides every frame; floor skips seq 0
            got = await asyncio.wait_for(_ws_read_json(r2), 5)
            ben_seqs.append(got["seq"])
        assert ben_seqs == list(range(1, 12))
        assert plane.hub.resume_fallbacks.get("cursor_gap") == 1
        writer.close()
        w2.close()
        await plane.aclose()

    asyncio.run(go())
