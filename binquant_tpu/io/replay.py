"""Offline replay harness.

Feeds a JSONL file of closed klines through the full pipeline with every
network sink stubbed — the correctness oracle and benchmark A/B the
reference lacks (SURVEY.md §4 implication; BASELINE.json config #2). Each
line is an ``ExtendedKline``-shaped dict; lines are replayed in file order,
with one engine tick per distinct (15m bucket) timestamp group.

Also provides ``generate_replay_file`` to synthesize a market for smoke
runs.
"""

from __future__ import annotations

import asyncio
import json
import time
from pathlib import Path
from typing import Any

import numpy as np


class StubSession:
    """In-memory binbot backend for replay (the reference's tests cut the
    same seam by patching BinbotApi)."""

    class _Resp:
        def __init__(self, payload: Any, status_code: int = 200) -> None:
            self._payload = payload
            self.status_code = status_code
            self.text = json.dumps(payload)

        def json(self) -> Any:
            return self._payload

    def __init__(self, breadth: dict | None = None) -> None:
        self.requests: list[tuple[str, str, Any]] = []
        # scripted market-breadth payload (None = the empty default, which
        # leaves breadth-gated strategies dormant). A dict carrying a
        # "schedule" key scripts a PER-REQUEST breadth stream instead: the
        # list is consumed one entry per market-breadth call (the engine
        # refreshes once per 15m bucket, so entry k feeds bucket k), the
        # last entry repeats, and the sentinel "error" returns HTTP 500 —
        # a stalled upstream whose engine keeps its previous series. This
        # is how the breadth-fault scenario family (ISSUE 15 / ROADMAP
        # 5a) drives stalls and NaN holes mid-run.
        self.breadth = breadth
        self._breadth_calls = 0

    def _breadth_payload(self):
        breadth = self.breadth
        if isinstance(breadth, dict) and "schedule" in breadth:
            schedule = breadth["schedule"]
            idx = min(self._breadth_calls, len(schedule) - 1)
            self._breadth_calls += 1
            entry = schedule[idx] if schedule else None
            if entry == "error":
                return self._Resp({"error": "breadth upstream down"}, 500)
            return self._Resp({"data": entry or {}})
        return self._Resp({"data": breadth or {}})

    def request(self, method: str, url: str, **kwargs):
        self.requests.append((method, url, kwargs.get("json")))
        if "available-fiat" in url:
            return self._Resp({"data": {"amount": 1000.0}})
        if "active-pairs" in url or "excluded" in url or "grid-ladders/active" in url:
            return self._Resp({"data": []})
        if "/bot" in url and method == "POST":
            return self._Resp(
                {"message": "ok", "error": 0, "data": {"pair": "X", "id": "00000000-0000-0000-0000-000000000000"}}
            )
        if "activate" in url:
            return self._Resp(
                {"message": "ok", "error": 0, "data": {"pair": "X"}}
            )
        if "market-breadth" in url:
            return self._breadth_payload()
        return self._Resp({"data": {}})

    def get(self, url, params=None):
        return self.request("GET", url, params=params)


def make_stub_engine(
    capacity: int = 256,
    window: int = 200,
    breadth: dict | None = None,
    pipeline_depth: int = 0,
    enabled_strategies: set[str] | None = None,
    context_config=None,
    incremental: bool | None = None,
    donate: bool | None = None,
    carry_audit_every: int | None = None,
    scan_chunk: int | None = None,
    backtest_chunk: int | None = None,
    session=None,
    telegram_transport=None,
    trace_sample: float | None = None,
    freshness: bool | None = None,
    host_phase: bool | None = None,
    freshness_slo_ms: float | None = None,
    outcomes: bool | None = None,
    outcome_horizons: tuple[int, ...] | None = None,
    outcome_cap: int | None = None,
    delivery: bool | None = None,
    delivery_wal: str | None = None,
    delivery_overrides: dict | None = None,
    fanout: bool | None = None,
    fanout_overrides: dict | None = None,
    ingest_digest: bool | None = None,
    ingest_stale_budget: int | None = None,
    ext_invariant: bool | None = None,
):
    """A SignalEngine wired entirely to stubs (no network).

    ``incremental``/``donate`` override the config's BQT_INCREMENTAL /
    BQT_DONATE defaults so the A/B harness can pin either evaluation path
    and either dispatch variant explicitly; ``carry_audit_every`` /
    ``scan_chunk`` override the drift-audit cadence and the fused-scan
    chunk size (BQT_CARRY_AUDIT_EVERY / BQT_SCAN_CHUNK) for drills that
    need resync boundaries or chunk breaks at test scale.

    Chaos seams (binquant_tpu/sim/chaos.py): ``session`` replaces the
    default StubSession behind BinbotApi (a FlakySession injects 5xx/
    timeout storms), ``telegram_transport`` is awaited before each send is
    recorded (raise to script delivery failures), and ``trace_sample``
    overrides BQT_TRACE_SAMPLE so the scenario lane's crash-ring
    invariant actually traces every tick."""
    import os

    os.environ.setdefault("ENV", "CI")
    from binquant_tpu.config import Config
    from binquant_tpu.io.autotrade import AutotradeConsumer
    from binquant_tpu.io.binbot import BinbotApi
    from binquant_tpu.io.pipeline import SignalEngine
    from binquant_tpu.io.telegram import TelegramConsumer
    from binquant_tpu.regime.context import ContextConfig
    from binquant_tpu.schemas import (
        AutotradeSettingsSchema,
        TestAutotradeSettingsSchema,
    )

    Config.reset()
    config = Config()
    config.__dict__["max_symbols"] = capacity
    config.__dict__["window_bars"] = window
    if incremental is not None:
        config.__dict__["incremental_enabled"] = bool(incremental)
    if donate is not None:
        config.__dict__["donate_enabled"] = bool(donate)
    if carry_audit_every is not None:
        config.__dict__["carry_audit_every_ticks"] = int(carry_audit_every)
    if scan_chunk is not None:
        config.__dict__["scan_chunk"] = int(scan_chunk)
    if backtest_chunk is not None:
        config.__dict__["backtest_chunk"] = int(backtest_chunk)
    # extension-invariant chunk precompute (ISSUE 17): BQT_EXT_INVARIANT
    # override so the governed-parity drills pin the ext path on while the
    # tier-1 default stays on the bit-identical vmapped path
    if ext_invariant is not None:
        config.__dict__["ext_invariant"] = bool(ext_invariant)
    if trace_sample is not None:
        config.__dict__["trace_sample"] = float(trace_sample)
    # ingest-health observatory (ISSUE 15): BQT_INGEST_DIGEST /
    # BQT_INGEST_STALE_BUDGET overrides so the ingest lane pins the
    # observatory on while the tier-1 conftest keeps it off
    if ingest_digest is not None:
        config.__dict__["ingest_digest"] = bool(ingest_digest)
    if ingest_stale_budget is not None:
        config.__dict__["ingest_stale_budget"] = int(ingest_stale_budget)
    # latency observatory (ISSUE 11): BQT_FRESHNESS / BQT_HOST_PHASE /
    # BQT_FRESHNESS_SLO_MS overrides, so the latency lane can pin the
    # observatory on while the tier-1 conftest keeps it off
    if freshness is not None:
        config.__dict__["freshness_enabled"] = bool(freshness)
    if host_phase is not None:
        config.__dict__["host_phase_enabled"] = bool(host_phase)
    if freshness_slo_ms is not None:
        config.__dict__["freshness_slo_ms"] = float(freshness_slo_ms)
    # signal-outcome observatory (ISSUE 12): BQT_OUTCOMES /
    # BQT_OUTCOME_HORIZONS / BQT_OUTCOME_CAP overrides so the outcome
    # lane pins the observatory on while the tier-1 conftest keeps it off
    if outcomes is not None:
        config.__dict__["outcomes_enabled"] = bool(outcomes)
    if outcome_horizons is not None:
        config.__dict__["outcome_horizons"] = tuple(
            int(h) for h in outcome_horizons
        )
    if outcome_cap is not None:
        config.__dict__["outcome_cap"] = int(outcome_cap)
    # durable delivery plane (ISSUE 13): BQT_DELIVERY / BQT_DELIVERY_WAL
    # overrides so the delivery lane pins the plane on (with a tmp WAL and
    # drill-scale queue/backoff/breaker knobs via ``delivery_overrides``,
    # config attr name -> value) while the tier-1 conftest keeps it off
    if delivery is not None:
        config.__dict__["delivery_enabled"] = bool(delivery)
    if delivery_wal is not None:
        config.__dict__["delivery_wal_path"] = str(delivery_wal)
    elif getattr(config, "delivery_enabled", False):
        # never share the LIVE deployment's WAL: a stub run's unacked
        # leftovers must not replay into the next production boot (and
        # vice versa) — stub engines get a fresh throwaway outbox
        import atexit
        import contextlib
        import tempfile

        fd, wal_tmp = tempfile.mkstemp(
            prefix="bqt_stub_", suffix=".wal.jsonl"
        )
        os.close(fd)

        def _discard_stub_wal(path=wal_tmp):
            with contextlib.suppress(OSError):
                os.unlink(path)

        # throwaway means throwaway: drills/tests mint one per stub
        # engine and nothing else ever unlinks it
        atexit.register(_discard_stub_wal)
        config.__dict__["delivery_wal_path"] = wal_tmp
    for key, value in (delivery_overrides or {}).items():
        config.__dict__[key] = value
    # subscription fan-out plane (ISSUE 14): BQT_FANOUT override, plus
    # the same throwaway-outbox rule as the delivery WAL — a stub run's
    # broadcast frames must never replay into (or pollute the retention
    # of) the live deployment's cursor outbox
    if fanout is not None:
        config.__dict__["fanout_enabled"] = bool(fanout)
    if getattr(config, "fanout_enabled", False) and "fanout_outbox_path" not in (
        fanout_overrides or {}
    ):
        import atexit
        import contextlib
        import tempfile

        fd, outbox_tmp = tempfile.mkstemp(
            prefix="bqt_stub_", suffix=".fanout.jsonl"
        )
        os.close(fd)

        def _discard_stub_outbox(path=outbox_tmp):
            for p in (path, path + ".1"):  # live file + rotated generation
                with contextlib.suppress(OSError):
                    os.unlink(p)

        atexit.register(_discard_stub_outbox)
        config.__dict__["fanout_outbox_path"] = outbox_tmp
    for key, value in (fanout_overrides or {}).items():
        config.__dict__[key] = value
    binbot_api = BinbotApi(
        "http://stub",
        session=session if session is not None else StubSession(breadth=breadth),
    )

    sent: list[str] = []

    async def capture_transport(chat_id: str, text: str) -> None:
        if telegram_transport is not None:
            # injected fault transport first: a scripted failure must keep
            # the message OUT of the recorded-sent list (it wasn't sent)
            await telegram_transport(chat_id, text)
        sent.append(text)

    telegram = TelegramConsumer(
        token="", chat_id="stub", transport=capture_transport
    )
    # futures market type so futures-only strategies (MeanReversionFade)
    # are exercised; autotrade stays off (no trade side effects in replay)
    from binquant_tpu.schemas import MarketType

    at_consumer = AutotradeConsumer(
        autotrade_settings=AutotradeSettingsSchema(
            autotrade=False, market_type=MarketType.FUTURES
        ),
        active_test_bots=[],
        all_symbols=[],
        test_autotrade_settings=TestAutotradeSettingsSchema(autotrade=False),
        active_grid_ladders=[],
        binbot_api=binbot_api,
    )
    engine = SignalEngine(
        config=config,
        binbot_api=binbot_api,
        telegram_consumer=telegram,
        at_consumer=at_consumer,
        window=window,
        # small-universe default; production-breadth tests pass the real
        # ContextConfig() (40 fresh / 0.70 coverage)
        context_config=context_config
        or ContextConfig(required_fresh_symbols=4, min_coverage_ratio=0.5),
        pipeline_depth=pipeline_depth,
        enabled_strategies=enabled_strategies,
    )
    engine._telegram_sent = sent  # type: ignore[attr-defined]
    return engine


def load_klines_by_tick(path: str | Path) -> dict[int, list[dict]]:
    """Group a JSONL kline file by 15m bucket (one engine tick each).
    Transparently reads gzip fixtures (checked-in market files).

    A line may carry an optional ``_deliver_bucket`` transport key: the
    candle is handed to the engine at THAT tick instead of its own
    open-time bucket — how scenario streams script delivery faults the
    plain format cannot express (a rewrite storm re-sending an old candle
    ticks later; an exchange outage whose bars all arrive in one catch-up
    drain). The key is popped here; the engine never sees it."""
    import gzip

    opener = gzip.open if str(path).endswith(".gz") else open
    klines_by_tick: dict[int, list[dict]] = {}
    with opener(path, "rt") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            k = json.loads(line)
            deliver = k.pop("_deliver_bucket", None)
            bucket = (
                int(deliver)
                if deliver is not None
                else int(k["open_time"]) // 1000 // 900
            )
            klines_by_tick.setdefault(bucket, []).append(k)
    return klines_by_tick


def tick_seq(path: str | Path) -> list[tuple[int, list[dict]]]:
    """A kline file's delivery-ordered tick sequence: one engine tick per
    15m delivery bucket, ``now_ms`` just after the bucket's bars close —
    THE one copy of the bucket→tick convention every drive (run_replay,
    the scenario runner, the scan drills) shares."""
    klines_by_tick = load_klines_by_tick(path)
    return [
        (
            (bucket + 1) * 900 * 1000,
            sorted(klines_by_tick[bucket], key=lambda k: k["open_time"]),
        )
        for bucket in sorted(klines_by_tick)
    ]


def signal_tuples(fired) -> list[tuple]:
    """Fired signals → the ``(tick_ms, strategy, symbol, direction,
    autotrade)`` comparison tuples every equality harness shares."""
    return [
        (
            s.tick_ms,
            s.strategy,
            s.symbol,
            str(s.value.direction),
            bool(s.value.autotrade),
        )
        for s in fired
    ]


def run_replay(
    path: str | Path,
    capacity: int = 256,
    window: int = 200,
    collect: list | None = None,
    breadth: dict | None = None,
    pipeline_depth: int = 0,
    enabled_strategies: set | None = None,
    dominance_is_losers: bool = False,
    market_domination_reversal: bool = False,
    context_config=None,
    incremental: bool | None = None,
    donate: bool | None = None,
    scanned: bool = False,
    carry_audit_every: int | None = None,
    scan_chunk: int | None = None,
    freshness: bool | None = None,
    host_phase: bool | None = None,
    freshness_slo_ms: float | None = None,
    outcomes: bool | None = None,
    outcome_horizons: tuple[int, ...] | None = None,
    collect_outcomes: list | None = None,
) -> dict:
    """Replay a JSONL kline file; returns run statistics.

    When ``collect`` is a list, every fired signal is appended as a
    ``(tick_ms, strategy, symbol, direction, autotrade)`` tuple — the
    comparison surface for the A/B parity harness. ``breadth`` scripts the
    stub backend's market-breadth series so the breadth-gated paths
    (LiquidationSweepPump routing, grid-only policy) engage.
    ``pipeline_depth`` drives the engine's pipelined tick loop (default 0:
    serial, so host policy state advances with the SAME one-tick lag the
    oracle models); fired signals are attributed to their producing tick
    via ``FiredSignal.tick_ms`` either way, and in-flight ticks are flushed
    at end of file.

    ``scanned=True`` drives the SAME stream through the fused scan engine
    (``SignalEngine.process_ticks_scanned``): runs of clean-append
    incremental ticks collapse into one ``lax.scan`` dispatch each, the
    dispatch-overhead lever for every historical-data lane. The emitted
    signal set is identical to the serial drive by construction (chunk
    breaks + the serial overflow re-run) — pinned by
    tests/test_scan_replay.py. Requires the incremental path.
    """
    engine = make_stub_engine(
        capacity=capacity,
        window=window,
        breadth=breadth,
        pipeline_depth=pipeline_depth,
        enabled_strategies=enabled_strategies,
        context_config=context_config,
        incremental=incremental,
        donate=donate,
        carry_audit_every=carry_audit_every,
        scan_chunk=scan_chunk,
        freshness=freshness,
        host_phase=host_phase,
        freshness_slo_ms=freshness_slo_ms,
        outcomes=outcomes,
        outcome_horizons=outcome_horizons,
    )
    # scripted dominance state (reference: attrs on the evaluator/consumer,
    # NEUTRAL/False in production — scriptable here so the dominance-gated
    # dormant strategies can be exercised in A/B runs)
    engine.at_consumer.market_domination_reversal = market_domination_reversal
    engine.at_consumer.current_market_dominance_is_losers = dominance_is_losers
    seq = tick_seq(path)

    fired_total = 0
    t_start = time.perf_counter()
    latencies = []

    def record(fired) -> None:
        nonlocal fired_total
        fired_total += len(fired)
        if collect is not None:
            collect.extend(signal_tuples(fired))

    async def drive() -> None:
        for tick_ms, klines in seq:
            for k in klines:
                engine.ingest(k)
            # the tick fires just after the bucket's bars CLOSE
            t0 = time.perf_counter()
            fired = await engine.process_tick(now_ms=tick_ms)
            latencies.append((time.perf_counter() - t0) * 1000)
            record(fired)
        record(await engine.flush_pending())
        # retire the delivery plane (when on) before the loop closes:
        # best-effort drain so the stubbed sinks see every signal
        await engine.aclose_delivery()
        # ... and the fan-out plane (when on): emits the fanout_summary
        # scoreboard tools/fanout_report.py renders
        await engine.aclose_fanout()

    async def drive_scanned() -> None:
        record(await engine.process_ticks_scanned(seq))
        record(await engine.flush_pending())
        await engine.aclose_delivery()
        await engine.aclose_fanout()

    asyncio.run(drive_scanned() if scanned else drive())
    wall = time.perf_counter() - t_start
    overflow = engine.latency.stats().get("overflow_fallback", {})
    # latency observatory (ISSUE 11): the run's freshness + host-phase
    # summary rides the stats AND the event log, so make latency-smoke's
    # report tool can render it after the process exits
    latency_summary = None
    if engine.freshness.enabled or engine.host_phase.enabled:
        latency_summary = {
            "freshness": engine.freshness.snapshot(),
            "host_phase": engine.host_phase.snapshot(),
        }
        from binquant_tpu.obs.events import get_event_log

        get_event_log().emit("latency_summary", **latency_summary)
    # signal-outcome observatory (ISSUE 12): matured comparison tuples +
    # the per-strategy scoreboard ride the stats so the parity harness and
    # outcome_report can consume a run without scraping Prometheus
    outcome_summary = None
    if engine.outcomes.enabled:
        if collect_outcomes is not None:
            collect_outcomes.extend(sorted(engine.outcomes.matured_set()))
        outcome_summary = engine.outcomes.scoreboard()
        from binquant_tpu.obs.events import get_event_log as _gel

        _gel().emit("outcome_summary", **outcome_summary)
    return {
        **({"latency": latency_summary} if latency_summary else {}),
        **({"outcomes": outcome_summary} if outcome_summary else {}),
        "ticks": engine.ticks_processed,
        # fused-scan accounting (scanned=True lanes; 0 on the serial drive)
        "scanned_ticks": engine.scanned_ticks,
        "scan_chunks": engine.scan_chunks,
        "scan_overflow_reruns": engine.scan_overflow_reruns,
        # incremental indicator path accounting: the A/B parity tests
        # assert the fast path actually engaged (a vacuously-full run
        # would not be testing the incremental engine at all)
        "incremental_ticks": engine.incremental_ticks,
        "full_recompute_ticks": engine.full_recompute_ticks,
        "donated_ticks": engine.donated_ticks,
        "donated_state_resets": engine.donated_state_resets,
        "signals": fired_total,
        "telegram_messages": len(engine._telegram_sent),  # type: ignore[attr-defined]
        "wall_s": round(wall, 3),
        "tick_p50_ms": round(float(np.percentile(latencies, 50)), 3) if latencies else None,
        "tick_p99_ms": round(float(np.percentile(latencies, 99)), 3) if latencies else None,
        # wire-compaction overflow ticks (>WIRE_MAX_FIRED fired pairs):
        # how often the slow full-summary path ran, and what it cost
        # (p99 also times payload-less fallbacks; the count is exact)
        "overflow_ticks": engine.overflow_ticks,
        "overflow_p99_ms": overflow.get("p99_ms"),
    }


def run_replay_oracle(
    path: str | Path,
    window: int = 200,
    breadth: dict | None = None,
    enabled_strategies: set | None = None,
    dominance_is_losers: bool = False,
    market_domination_reversal: bool = False,
    collect_regimes: list | None = None,
) -> list[tuple]:
    """Replay through the legacy per-symbol pandas backend
    (``backend=reference``, BASELINE config #1); returns the fired
    ``(tick_ms, strategy, symbol, direction, autotrade)`` tuples.

    Mirrors the pipeline's host-side breadth handling: adp pair from the
    (static) series, and the grid-only policy resolved from the PREVIOUS
    tick's regime — the engine reads last tick's policy when building
    HostInputs and refreshes it after the evaluation.
    """
    from binquant_tpu.io.pipeline import breadth_scalars
    from binquant_tpu.oracle import OracleEvaluator
    from binquant_tpu.regime.grid_policy import GridOnlyPolicy
    from binquant_tpu.schemas import MarketBreadthSeries

    evaluator = OracleEvaluator(
        window=window,
        required_fresh_symbols=4,
        min_coverage_ratio=0.5,
        is_futures=True,
        enabled_strategies=enabled_strategies,
    )
    mb = MarketBreadthSeries(**breadth) if breadth else None
    # the SAME resolution the live pipeline uses (one copy of semantics)
    adp_latest, adp_prev, adp_diff, adp_diff_prev, _ = breadth_scalars(mb)

    policy = GridOnlyPolicy.disabled("not_evaluated")
    out: list[tuple] = []
    for tick_ms, klines in tick_seq(path):
        for k in klines:
            evaluator.ingest(k)
        for strategy, sym, direction, autotrade in evaluator.evaluate(
            tick_ms,
            grid_policy_allows=policy.allow_grid_ladder,
            adp_latest=adp_latest,
            adp_prev=adp_prev,
            adp_diff=adp_diff,
            adp_diff_prev=adp_diff_prev,
            dominance_is_losers=dominance_is_losers,
            market_domination_reversal=market_domination_reversal,
        ):
            out.append((tick_ms, strategy, sym, direction, autotrade))
        # next tick's policy from THIS tick's regime (None when invalid)
        policy = GridOnlyPolicy.resolve(evaluator.last_regime, mb)
        if collect_regimes is not None:
            from binquant_tpu.enums import market_regime_label

            code = evaluator.last_regime
            collect_regimes.append(
                (
                    tick_ms,
                    market_regime_label(code) if code is not None else None,
                    float(evaluator.last_strength),
                )
            )
    return out


def run_replay_ab(
    path: str | Path,
    capacity: int = 256,
    window: int = 200,
    breadth: dict | None = None,
    enabled_strategies: set | None = None,
    dominance_is_losers: bool = False,
    market_domination_reversal: bool = False,
    incremental: bool | None = None,
    donate: bool | None = None,
    scanned: bool = False,
    oracle_signals: list | None = None,
) -> dict:
    """A/B parity: the TPU batch path and the per-symbol pandas oracle run
    the same replay and must emit the identical signal set (SURVEY.md §7
    step 8 — the correctness oracle for the batched evaluation).
    ``enabled_strategies`` overrides the live dispatch set in BOTH backends
    (e.g. to A/B the dormant oracle set — VERDICT r2 item 6); the dominance
    flags script the host-resolved market-domination state both backends
    consume. ``scanned=True`` drives the TPU arm through the fused
    scan-chunk engine. ``oracle_signals`` supplies a precomputed oracle run
    for these exact (path, window, breadth, strategy, dominance) arguments —
    the pandas arm costs tens of seconds per sweep, so callers running
    several A/Bs over one fixture compute it once (tests/test_ab_parity.py
    shares one module-scoped run; pass None to compute here)."""
    tpu_signals: list[tuple] = []
    stats = run_replay(
        path,
        capacity=capacity,
        window=window,
        collect=tpu_signals,
        breadth=breadth,
        enabled_strategies=enabled_strategies,
        dominance_is_losers=dominance_is_losers,
        market_domination_reversal=market_domination_reversal,
        incremental=incremental,
        donate=donate,
        scanned=scanned,
    )
    if oracle_signals is None:
        oracle_signals = run_replay_oracle(
            path, window=window, breadth=breadth,
            enabled_strategies=enabled_strategies,
            dominance_is_losers=dominance_is_losers,
            market_domination_reversal=market_domination_reversal,
        )
    tpu_set, oracle_set = set(tpu_signals), set(oracle_signals)
    from collections import Counter

    per_tick = Counter(t for t, *_ in tpu_set)
    return {
        # the largest single-tick fired set (the wire-overflow drill
        # asserts one tick exceeded the compaction slots)
        "per_tick_max": max(per_tick.values()) if per_tick else 0,
        "match": tpu_set == oracle_set,
        "tpu_count": len(tpu_set),
        "oracle_count": len(oracle_set),
        "only_tpu": sorted(tpu_set - oracle_set),
        "only_oracle": sorted(oracle_set - tpu_set),
        "strategies": sorted({s for _, s, _, _, _ in tpu_set}),
        "tpu_stats": stats,
    }


def kline_record(
    symbol: str, ts_s: int, interval_s: int, o, h, low, c, volume,
    trades: float = 300.0,
) -> dict:
    """One ExtendedKline-shaped dict — the single field contract every
    replay generator shares (close_time = open+interval-1ms, taker splits,
    6-dp rounding). The scenario engine (binquant_tpu/sim) builds streams
    from these dicts so stream-level faults (rewrite storms, outage
    redelivery) can be scripted before serialization."""
    return {
        "symbol": symbol,
        "open_time": ts_s * 1000,
        "close_time": (ts_s + interval_s) * 1000 - 1,
        "open": round(float(o), 6),
        "high": round(float(h), 6),
        "low": round(float(low), 6),
        "close": round(float(c), 6),
        "volume": round(float(volume), 3),
        "quote_asset_volume": round(float(volume * c), 3),
        "number_of_trades": trades,
        "taker_buy_base_volume": round(float(volume / 2), 3),
        "taker_buy_quote_volume": round(float(volume * c / 2), 3),
    }


def _kline_json(
    symbol: str, ts_s: int, interval_s: int, o, h, low, c, volume,
    trades: float = 300.0,
) -> str:
    """One ExtendedKline JSONL line (see :func:`kline_record`)."""
    return json.dumps(
        kline_record(symbol, ts_s, interval_s, o, h, low, c, volume, trades)
    ) + "\n"


def generate_burst_replay(
    path: str | Path,
    n_symbols: int = 160,
    n_ticks: int = 108,
    seed: int = 23,
) -> None:
    """A market-wide crash tick that fires MeanReversionFade on EVERY
    symbol simultaneously — more fired (strategy, row) pairs than the
    wire's compaction slots (WIRE_MAX_FIRED=128 at the default 160
    symbols), forcing the overflow fallback through dispatch→emission.
    The drill for engine/step.py's compaction limit (commits
    48301f4/f446a62)."""
    rng = np.random.default_rng(seed)
    t0 = 1_780_272_000
    px = 20 + rng.random(n_symbols) * 100

    with open(path, "w") as f:
        for tick in range(n_ticks):
            ts15 = t0 + tick * 900
            # steady market-wide downtrend keeps every symbol's RSI pinned
            rets = rng.normal(-0.004, 0.002, n_symbols)
            new_px = px * (1 + rets)
            last_tick = tick == n_ticks - 1
            for i in range(n_symbols):
                symbol = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
                o, c = px[i], new_px[i]
                vol15 = abs(rng.normal(1000, 200))
                h, low = max(o, c) * 1.002, min(o, c) * 0.998
                if last_tick:
                    # the same green-hammer recipe the single-symbol
                    # scenario uses, applied market-wide: deep gap down
                    # below the lower band, green close, 3x volume
                    o = px[i] * 0.955
                    c = o * 1.003
                    h, low = c * 1.001, o * 0.997
                    new_px[i] = c
                    vol15 *= 3.0
                f.write(_kline_json(symbol, ts15, 900, o, h, low, c, vol15))
                sub_o = o
                for j in range(3):
                    sub_c = o + (c - o) * (j + 1) / 3
                    sh = max(sub_o, sub_c) * 1.001
                    sl = min(sub_o, sub_c) * 0.999
                    f.write(
                        _kline_json(
                            symbol, ts15 + j * 300, 300,
                            sub_o, sh, sl, sub_c, vol15 / 3,
                        )
                    )
                    sub_o = sub_c
            px = new_px


def generate_dormant_replay(
    path: str | Path,
    n_symbols: int = 24,
    n_ticks: int = 130,
    seed: int = 23,
) -> None:
    """Synthesize a calm RANGE market with crafted setups for the dormant
    oracle set (VERDICT r2 item 6):

    * S002 — BuyTheDip: a −4% shelf drop starting ~22 bars before the end
      (inside the 24-bar lookback), a flat base, then a green reclaim bar
      over prev close + EMA20 on the final tick;
    * S003 — BBExtremeReversion: two consecutive hard red 15m bars ending
      below the 20-bar −2σ band (Connors RSI(2) pins to 0);
    * S004 — RangeBbRsiMeanReversion: a choppy zig-zag bleed (keeps the
      rolling-sum ADX under 32 while RSI(14) sits ≤35) ending in a hammer
      that undershoots −2σ, closes green near its high, below the mid.

    The rest of the universe oscillates gently so the macro regime stays
    RANGE with low stress.
    """
    rng = np.random.default_rng(seed)
    t0 = 1_780_272_000
    assert t0 % 900 == 0
    levels = 20 + rng.random(n_symbols) * 100
    closes = np.zeros((n_ticks, n_symbols))
    # base: per-symbol sine oscillation ±0.25% + tiny noise; BTC flat-ish
    phase = rng.random(n_symbols) * 2 * np.pi
    for i in range(n_symbols):
        wave = 0.0025 * np.sin(2 * np.pi * np.arange(n_ticks) / 16.0 + phase[i])
        noise = rng.normal(0, 0.0006, n_ticks).cumsum() * 0.2
        closes[:, i] = levels[i] * (1 + wave + noise)
    last = n_ticks - 1

    # S002 BuyTheDip: drop over [last-22, last-16], flat base, green pop
    s = 2
    base = closes[last - 30, s]
    for k, t in enumerate(range(last - 22, last - 16)):
        closes[t, s] = base * (1 - 0.007 * (k + 1))
    shelf = base * (1 - 0.042)
    for t in range(last - 16, last):
        closes[t, s] = shelf * (1 + rng.normal(0, 0.0003))
    closes[last, s] = shelf * 1.011  # reclaim: > prev close and > EMA20

    # S003 BBX: two hard red bars to below the lower band
    s = 3
    lvl = closes[last - 2, s]
    closes[last - 1, s] = lvl * 0.975
    closes[last, s] = lvl * 0.950

    # S004 RBR: choppy bleed then hammer (bar shapes set below)
    s = 4
    lvl = closes[last - 20, s]
    px_s4 = lvl
    for k, t in enumerate(range(last - 19, last)):
        px_s4 *= (1 - 0.0035) if k % 2 == 0 else (1 + 0.0015)
        closes[t, s] = px_s4
    closes[last, s] = closes[last - 1, s] * 0.988  # green close, set shapes below

    with open(path, "w") as f:
        for tick in range(n_ticks):
            ts15 = t0 + tick * 900
            for i in range(n_symbols):
                symbol = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
                c = closes[tick, i]
                o = closes[tick - 1, i] if tick else c
                h, low = max(o, c) * 1.001, min(o, c) * 0.999
                vol15 = abs(rng.normal(1000, 150))
                if i == 4 and last - 19 <= tick < last and (tick - (last - 19)) % 2 == 1:
                    # RBR bleed's up-bars carry tall high wicks: +DM then
                    # balances the down-bars' −DM so the rolling-sum ADX
                    # stays under the 32 veto while closes still bleed
                    # (RSI ≤ 35) — the shape the strategy hunts: choppy
                    # range, not a trend
                    h = max(o, c) * 1.0075
                if tick == n_ticks - 1 and i == 4:
                    # RBR hammer: gap down, deep low poke through −2σ,
                    # green close near the candle high
                    o = closes[tick - 1, i] * 0.986
                    low = o * 0.9875
                    h = c * 1.0008
                if tick == n_ticks - 1 and i == 2:
                    # BTD reclaim bar: clean green, modest wicks
                    h, low = c * 1.0005, o * 0.9995
                f.write(_kline_json(symbol, ts15, 900, o, h, low, c, vol15))
                # three 5m sub-bars splitting the 15m move (both buffers
                # must fill for MIN_BARS gates)
                sub_o = o
                for j in range(3):
                    frac = (j + 1) / 3
                    sub_c = o + (c - o) * frac
                    sh, sl = max(sub_o, sub_c) * 1.0005, min(sub_o, sub_c) * 0.9995
                    f.write(_kline_json(symbol, ts15 + j * 300, 300, sub_o, sh, sl, sub_c, vol15 / 3))
                    sub_o = sub_c


def generate_dormant_extended_replay(
    path: str | Path,
    n_symbols: int = 24,
    n_ticks: int = 130,
    seed: int = 31,
) -> None:
    """Scenario for the EXTENDED dormant oracle set (twap sniper,
    supertrend swing reversal, buy-low-sell-high, inverse price tracker,
    RS reversal range):

    * most symbols drift mildly up (advancers-heavy TRANSITIONAL/TREND_UP
      market for IPT's routing; BTC drifts with them);
    * S005 — a strong 15m rally with wide bars (supertrend pinned up,
      micro TREND_UP), then six final ticks of tiny red 5m sub-bars: the
      5m RSI/MFI pin low with MACD negative while the supertrend band
      holds — SupertrendSwingReversal and InversePriceTracker arm at tick
      last-1 (with rising scripted breadth + LOSERS dominance for STS);
    * S006 — an early +15% rally then a slow −0.15%/bar bleed: RSI(14)
      pins ~0 while price stays above MA25 (BuyLowSellHigh with scripted
      domination reversal) and under its 80-bar TWAP (TwapMomentumSniper);
    * final tick — half the universe (incl. BTC) drops ~5% while S007
      pumps +3.5%: a broad RANGE selloff with an RS leader
      (RelativeStrengthReversalRange).
    """
    rng = np.random.default_rng(seed)
    t0 = 1_780_272_000
    assert t0 % 900 == 0
    levels = 20 + rng.random(n_symbols) * 100
    closes = np.zeros((n_ticks, n_symbols))
    for i in range(n_symbols):
        drift = 0.0012 * np.arange(n_ticks)  # mild up-drift (advancers-heavy)
        noise = rng.normal(0, 0.0008, n_ticks).cumsum() * 0.3
        closes[:, i] = levels[i] * (1 + drift + noise)
    last = n_ticks - 1

    # S005: strong rally, then a tiny-red-5m-sub-bar fade over the final
    # six ticks (the fade is shaped in the sub-bar writer below)
    s = 5
    closes[:, s] = levels[s] * (1 + 0.003 * np.arange(n_ticks))
    fade_start = last - 5
    peak = closes[fade_start - 1, s]
    for k, t in enumerate(range(fade_start, last + 1)):
        closes[t, s] = peak * (1 - 0.0012 * (k + 1))

    # S006: flat base → STEEP 14-bar rally (+2%/bar) ending 18 bars before
    # the end → 17-bar slow bleed. The 25-bar MA window then spans the
    # rally's low prices, so the bleed's close stays ABOVE ma25 while the
    # all-red last 14 bars pin RSI(14) at 0 — the BLSH transient.
    s = 6
    rally_end = last - 17
    rally_len = 14
    base = levels[s]
    # gently rising base — a perfectly flat price makes twap == price, an
    # f32-vs-f64 knife edge the A/B comparison can land on either side of
    closes[: rally_end - rally_len, s] = base * (
        1 + 0.0004 * np.arange(rally_end - rally_len)
    )
    base = closes[rally_end - rally_len - 1, s]
    for k, t in enumerate(range(rally_end - rally_len, rally_end)):
        closes[t, s] = base * (1.02 ** (k + 1))
    top = closes[rally_end - 1, s]
    for k, t in enumerate(range(rally_end, last + 1)):
        closes[t, s] = top * (1 - 0.0015 * (k + 1))

    # final-tick broad selloff with an RS leader
    droppers = [0] + list(range(8, 18))  # BTC + ten others
    for i in droppers:
        closes[last, i] = closes[last - 1, i] * 0.948
    closes[last, 7] = closes[last - 1, 7] * 1.035  # S007: the leader

    with open(path, "w") as f:
        for tick in range(n_ticks):
            ts15 = t0 + tick * 900
            for i in range(n_symbols):
                symbol = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
                c = closes[tick, i]
                o = closes[tick - 1, i] if tick else c
                vol15 = abs(rng.normal(1000, 150))
                if i == 5 and tick < fade_start:
                    # wide rally bars keep the supertrend band ~1.5% below
                    h, low = max(o, c) * 1.005, min(o, c) * 0.995
                else:
                    h, low = max(o, c) * 1.001, min(o, c) * 0.999
                f.write(_kline_json(symbol, ts15, 900, o, h, low, c, vol15))
                sub_o = o
                for j in range(3):
                    frac = (j + 1) / 3
                    sub_c = o + (c - o) * frac
                    sh, sl = max(sub_o, sub_c) * 1.0005, min(sub_o, sub_c) * 0.9995
                    f.write(
                        _kline_json(symbol, ts15 + j * 300, 300, sub_o, sh,
                                    sl, sub_c, vol15 / 3)
                    )
                    sub_o = sub_c
            # the fade's sub-bars are strictly monotone red by construction
            # (each 15m fade bar splits into three falling sub-bars above)
    return None


def generate_outcome_replay(
    path: str | Path,
    n_symbols: int = 8,
    n_ticks: int = 128,
    fire_ticks: tuple[int, int] = (104, 110),
    seed: int = 11,
) -> None:
    """MID-stream MeanReversionFade hammers with scripted aftermaths — the
    signal-outcome lane's fixture (ISSUE 12). Unlike the other generators
    (whose crafted setups land on the LAST tick, leaving nothing to
    mature), this stream fires early enough that every 5m-bar horizon up
    to ``3 * (n_ticks - fire_ticks[1] - 1)`` completes before EOF, with
    deliberately opposite aftermaths:

    * S005 — steady bleed, green hammer at ``fire_ticks[0]``, then a
      +0.35%/tick RECOVERY: positive forward returns, small MAE;
    * S006 — the same recipe at ``fire_ticks[1]``, then the bleed simply
      CONTINUES at −0.4%/tick: negative forward returns, deep MAE, tiny
      MFE.

    The rest of the universe random-walks gently (BTC row 0 flat-ish).
    """
    rng = np.random.default_rng(seed)
    t0 = 1_780_272_000
    assert t0 % 900 == 0
    assert n_symbols >= 7 and n_ticks > max(fire_ticks) + 2
    px = 20 + rng.random(n_symbols) * 100

    with open(path, "w") as f:
        for tick in range(n_ticks):
            ts15 = t0 + tick * 900
            rets = rng.normal(0, 0.003, n_symbols)
            for s, fire in zip((5, 6), fire_ticks):
                if tick < fire:
                    rets[s] = -0.006  # bleed: RSI pins low pre-hammer
                elif tick > fire:
                    rets[s] = 0.0035 if s == 5 else -0.004
            new_px = px * (1 + rets)
            for i in range(n_symbols):
                symbol = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
                o, c = px[i], new_px[i]
                vol15 = abs(rng.normal(1000, 200))
                h, low = max(o, c) * 1.002, min(o, c) * 0.998
                if i in (5, 6) and tick == fire_ticks[0 if i == 5 else 1]:
                    # the green-hammer recipe (generate_replay_file): deep
                    # gap below the 20-bar lower band, green close, 3x vol
                    o = px[i] * 0.955
                    c = o * 1.003
                    h, low = c * 1.001, o * 0.997
                    new_px[i] = c
                    vol15 *= 3.0
                f.write(_kline_json(symbol, ts15, 900, o, h, low, c, vol15))
                sub_o = o
                for j in range(3):
                    sub_c = o + (c - o) * (j + 1) / 3
                    sh = max(sub_o, sub_c) * 1.001
                    sl = min(sub_o, sub_c) * 0.999
                    f.write(
                        _kline_json(
                            symbol, ts15 + j * 300, 300,
                            sub_o, sh, sl, sub_c, vol15 / 3,
                        )
                    )
                    sub_o = sub_c
            px = new_px


def generate_replay_file(
    path: str | Path,
    n_symbols: int = 100,
    n_ticks: int = 150,
    seed: int = 7,
) -> None:
    """Synthesize a dual-interval (5m + 15m) market replay with crafted
    setups: an activity burst on S001's 5m stream and a MeanReversionFade
    hammer on S005's 15m stream, so the emission path is exercised."""
    rng = np.random.default_rng(seed)
    # MUST be 15m-bucket-aligned: process_tick derives the evaluated bar's
    # open time from wall clock as bucket*900-900; misaligned open times
    # never match the freshness mask and silently disable every strategy.
    t0 = 1_780_272_000
    assert t0 % 900 == 0
    px = 20 + rng.random(n_symbols) * 100

    with open(path, "w") as f:
        for tick in range(n_ticks):
            ts15 = t0 + tick * 900
            # S005 drifts hard down so its RSI pins low before the hammer
            rets = rng.normal(0, 0.004, n_symbols)
            rets[5] -= 0.008
            last_tick = tick == n_ticks - 1
            if last_tick and n_symbols > 3:
                # LSP setup: BTC up (long route needs btc_momentum > 0)
                # and a +3% pump on S003 (8x volume below)
                rets[0] = 0.005
                rets[3] = 0.03
            new_px = px * (1 + rets)
            for i in range(n_symbols):
                symbol = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
                o, c = px[i], new_px[i]
                vol15 = abs(rng.normal(1000, 200))
                if last_tick and i == 3:
                    vol15 *= 8.0
                h, low = max(o, c) * 1.002, min(o, c) * 0.998
                if last_tick and i == 5:
                    # green hammer: deep gap down (clears the 20-bar lower
                    # band even after it shifts), green close, 3x volume
                    o = px[i] * 0.955
                    c = o * 1.003
                    h, low = c * 1.001, o * 0.997
                    new_px[i] = c
                    vol15 *= 3.0
                f.write(_kline_json(symbol, ts15, 900, o, h, low, c, vol15))
                # three 5m sub-bars splitting the 15m move
                sub_o = o
                for j in range(3):
                    frac = (j + 1) / 3
                    sub_c = o + (c - o) * frac
                    vol5 = vol15 / 3
                    sh, sl = max(sub_o, sub_c) * 1.001, min(sub_o, sub_c) * 0.999
                    if last_tick and i == 1:
                        # activity burst on the LAST 5m bar: +3% jump, green
                        # body at highs, 6x volume, after two up sub-bars
                        if j < 2:
                            sub_c = sub_o * 1.003
                            sh, sl = sub_c * 1.001, sub_o * 0.999
                        else:
                            sub_c = sub_o * 1.03
                            sh, sl = sub_c * 1.002, sub_o * 0.998
                            vol5 *= 8.0
                        new_px[i] = sub_c
                    f.write(_kline_json(symbol, ts15 + j * 300, 300, sub_o, sh, sl, sub_c, vol5))
                    sub_o = sub_c
            px = new_px
