#!/usr/bin/env python
"""Render fan-out plane activity from the JSONL event log.

The subscription fan-out plane (``binquant_tpu/fanout``) narrates its
life as events: ``fanout_churn`` per registry mutation,
``fanout_publish`` per matched frame entering the broadcast tier,
``fanout_shed`` per counted drop (slow consumer / resume overflow),
``fanout_resume`` per reconnect-with-cursor replay, ``fanout_conn_close``
with one connection's delivery scoreboard, and one ``fanout_summary``
when the plane retires. This tool turns an event log back into the
broadcast story — churn volume, per-signal fan-out, per-connection
delivery lag, shed counts, and the top-N hottest subscriptions — without
any service in the loop (golden-pinned like delivery_report — keep
format changes deliberate):

    python tools/fanout_report.py /tmp/bqt_fanout_events.jsonl
    python tools/fanout_report.py events.jsonl --top 5
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

FANOUT_EVENTS = (
    "fanout_churn",
    "fanout_publish",
    "fanout_shed",
    "fanout_resume",
    "fanout_conn_close",
    "fanout_summary",
)


def load_fanout_events(path: str | Path) -> list[dict]:
    """All fan-out plane events, in file order; corrupt lines (a torn
    write at rotation) are skipped, not fatal."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if record.get("event") in FANOUT_EVENTS:
                out.append(record)
    return out


def render_report(events: list[dict], top: int = 10) -> str:
    """The deterministic report: churn tally, per-signal publish volume,
    shed counts by reason, resume timeline, the per-connection delivery
    scoreboard (with lag), and the plane's final summary."""
    lines: list[str] = []
    churn: dict[str, int] = {}
    published: dict[tuple[str, str], list[int]] = {}
    shed: dict[str, int] = {}
    conns: list[dict] = []
    last_summary: dict | None = None
    for e in events:
        kind = e.get("event")
        if kind == "fanout_churn":
            op = e.get("op", "?")
            churn[op] = churn.get(op, 0) + 1
        elif kind == "fanout_publish":
            key = (e.get("strategy", "?"), e.get("symbol", "?"))
            cell = published.setdefault(key, [0, 0])
            cell[0] += 1
            cell[1] += int(e.get("recipients", 0) or 0)
        elif kind == "fanout_shed":
            # aggregated sheds (close_pending, resume_overflow) carry a
            # count field; per-frame sheds (slow_consumer) count as 1
            reason = e.get("reason", "?")
            shed[reason] = shed.get(reason, 0) + int(e.get("count", 1) or 1)
        elif kind == "fanout_resume":
            lines.append(
                f"resume   {e.get('user', '?'):<12} ({e.get('transport', '?')})"
                f" cursor={e.get('cursor', '?')}"
                f" replayed={e.get('replayed', 0)}"
            )
        elif kind == "fanout_conn_close":
            conns.append(e)
        elif kind == "fanout_summary":
            last_summary = e
    if churn:
        net = (
            churn.get("subscribe", 0) - churn.get("unsubscribe", 0)
        )
        lines.insert(
            0,
            "churn    "
            + " ".join(f"{op}={churn[op]}" for op in sorted(churn))
            + f" (net {net:+d})",
        )
    for (strategy, symbol) in sorted(published):
        frames, recipients = published[(strategy, symbol)]
        lines.append(
            f"publish  {strategy}/{symbol}"
            f"  {frames} frame{'s' if frames != 1 else ''},"
            f" {recipients} recipients"
        )
    for reason in sorted(shed):
        lines.append(f"shed     {reason} = {shed[reason]}")
    if conns:
        lines.append("")
        lines.append(
            f"{'connection':<12} {'tport':<5} {'sent':>5} {'drop':>5}"
            f" {'replay':>6} {'gap':>3} {'lag_mean':>9} {'lag_max':>8}"
        )
        for c in sorted(
            conns, key=lambda c: (c.get("user", ""), c.get("transport", ""))
        ):
            mean = c.get("lag_ms_mean")
            lines.append(
                f"{c.get('user', '?'):<12} {c.get('transport', '?'):<5}"
                f" {c.get('delivered', 0):>5} {c.get('dropped', 0):>5}"
                f" {c.get('replayed', 0):>6}"
                f" {'yes' if c.get('gapped') else 'no':>3}"
                f" {(f'{mean:.1f}ms' if mean is not None else '-'):>9}"
                f" {c.get('lag_ms_max', 0):>6.1f}ms"
            )
    if last_summary is not None:
        s = last_summary
        lines.append("")
        recompiles = s.get("recompiles") or {}
        lines.append(
            f"summary  users={s.get('users', 0)}"
            f" published={s.get('published', 0)}"
            f" recipients={s.get('matched_recipients', 0)}"
            f" dispatches={s.get('match_dispatches', 0)}"
            f" recompiles="
            + "/".join(
                f"{k}:{recompiles[k]}" for k in sorted(recompiles)
            )
        )
        lines.append(
            f"hub      frames_sent={s.get('frames_sent', 0)}"
            f" shed={s.get('shed', 0)} resumed={s.get('resumed', 0)}"
        )
        top_users = (s.get("top_users") or [])[:top]
        if top_users:
            lines.append(f"hottest  top {len(top_users)} subscriptions:")
            for row in top_users:
                lines.append(
                    f"  {row.get('user', '?'):<20}"
                    f" {row.get('delivered', 0):>6} delivered"
                )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("log", help="JSONL event log (BQT_EVENT_LOG file)")
    parser.add_argument(
        "--top", type=int, default=10,
        help="hottest-subscription rows rendered from the summary",
    )
    args = parser.parse_args(argv)

    events = load_fanout_events(args.log)
    if not events:
        print(f"no fanout events in {args.log}", file=sys.stderr)
        return 1
    print(render_report(events, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
