"""Dormant / test-covered strategies, batched.

The reference keeps eight strategies off the live dispatch list but fully
test-covered as capability surface (SURVEY.md §2.5). Each is a pure last-bar
kernel here:

* three coinrule rules (``strategies/coinrule/coinrule.py``),
* BuyTheDip (``strategies/coinrule/buy_the_dip.py``),
* BBExtremeReversion (``strategies/coinrule/bb_extreme_reversion.py``),
* InversePriceTracker (``strategies/inverse_price_tracker.py``),
* RangeBbRsiMeanReversion (``strategies/range_bb_rsi_mean_reversion.py``),
* RangeFailedBreakoutFade (``strategies/range_failed_breakout_fade.py``),
* RelativeStrengthReversalRange
  (``strategies/relative_strength_reversal_range.py``).

(The ninth, BinanceAIReport, is pure host-side I/O —
``binquant_tpu/strategies/binance_report_ai.py``.)
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, MarketBuffer
from binquant_tpu.enums import (
    Direction,
    MarketRegimeCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.ops.indicators import supertrend_from
from binquant_tpu.ops.rolling import (
    rolling_mean,
    rolling_mean_last,
    rolling_std_last,
    rolling_sum,
    shift,
)
from binquant_tpu.regime.context import MarketContext
from binquant_tpu.regime.scoring import ScorerWeights, score_signal_candidate
from binquant_tpu.strategies.base import StrategyOutputs
from binquant_tpu.strategies.features import FeaturePack
from binquant_tpu.strategies.spike_hunter import SpikeSignal
from binquant_tpu.utils import jsafe_div


# ---------------------------------------------------------------------------
# Coinrule: twap_momentum_sniper (coinrule.py:53-126)
# ---------------------------------------------------------------------------


def _resample_1h(buf15: MarketBuffer, n_buckets: int):
    """Calendar-aligned 15m→1h OHLC resample, device-side.

    Buckets bars by ``open_time // 3600`` exactly as the reference's
    pandas resample (``producers/context_evaluator.py:392-395``): the last
    bucket is the current (possibly partial) wall-clock hour, preceding
    buckets the full hours before it. Returns (open, high, low, close) of
    shape (S, n_buckets), NaN where an hour has no bars; bars older than
    ``n_buckets`` hours before each symbol's latest bar are dropped.
    """
    import jax

    S, W = buf15.times.shape
    times = buf15.times
    has_bar = times >= 0
    hour = times // 3600
    last_hour = jnp.max(jnp.where(has_bar, hour, -1), axis=1, keepdims=True)
    idx = hour - last_hour + (n_buckets - 1)  # (S, W) bucket per bar
    in_range = has_bar & (idx >= 0) & (idx < n_buckets)
    # out-of-range bars land in a discarded overflow segment
    seg = jnp.where(in_range, idx, n_buckets).astype(jnp.int32)
    pos = jnp.arange(W)

    o = buf15.values[:, :, Field.OPEN]
    h = buf15.values[:, :, Field.HIGH]
    lo = buf15.values[:, :, Field.LOW]
    c = buf15.values[:, :, Field.CLOSE]

    def one(seg_s, o_s, h_s, lo_s, c_s):
        n_seg = n_buckets + 1
        first = jax.ops.segment_min(pos, seg_s, num_segments=n_seg)[:-1]
        last = jax.ops.segment_max(pos, seg_s, num_segments=n_seg)[:-1]
        filled = first <= last  # segment_min returns +inf-ish for empties
        open_1h = jnp.where(filled, o_s[jnp.clip(first, 0, W - 1)], jnp.nan)
        close_1h = jnp.where(filled, c_s[jnp.clip(last, 0, W - 1)], jnp.nan)
        high_1h = jnp.where(
            filled,
            jax.ops.segment_max(h_s, seg_s, num_segments=n_seg)[:-1],
            jnp.nan,
        )
        low_1h = jnp.where(
            filled,
            jax.ops.segment_min(lo_s, seg_s, num_segments=n_seg)[:-1],
            jnp.nan,
        )
        return open_1h, high_1h, low_1h, close_1h

    return jax.vmap(one)(seg, o, h, lo, c)


def twap_momentum_sniper(
    buf15: MarketBuffer,
    pack5: FeaturePack,
    twap_window: int = 20,
) -> StrategyOutputs:
    """TWAP(1h bars) > price with no sharp recent selloff; telemetry-only
    (autotrade=False, "manual_only" route).

    1h bars come from a calendar-aligned resample of the 15m buffer
    (``_resample_1h``), matching the reference's
    ``df.resample("1h")`` (producers/context_evaluator.py:392-395); the
    TWAP is the nan-mean of the last ``twap_window`` wall-clock hours
    (the trailing partial hour included, hours with no bars skipped).
    """
    S, W = buf15.times.shape
    n_buckets = twap_window + 2  # TWAP window + the pair close[-1]/close[-2]
    open_1h, high_1h, low_1h, close_1h = _resample_1h(buf15, n_buckets)

    bar_avg = (open_1h + high_1h + low_1h + close_1h) / 4.0
    twap_last = jnp.nanmean(
        jnp.where(jnp.isfinite(bar_avg[:, -twap_window:]),
                  bar_avg[:, -twap_window:], jnp.nan),
        axis=1,
    )

    # "price_decrease" exactly as written in the reference (l.68-70):
    # close[-1] - close[-2]/close[-1]
    price_decrease = close_1h[:, -1] - jsafe_div(close_1h[:, -2], close_1h[:, -1])

    enough = (pack5.filled >= 10) & (buf15.filled >= 8)
    fired = enough & (twap_last > pack5.close) & (price_decrease > -0.05)

    return StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=jnp.zeros((S,), dtype=bool),  # manual_only
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={"twap": twap_last, "price_decrease": price_decrease},
    )


# ---------------------------------------------------------------------------
# Coinrule: supertrend_swing_reversal (coinrule.py:128-228)
# ---------------------------------------------------------------------------


def supertrend_swing_reversal(
    buf5: MarketBuffer,
    pack5: FeaturePack,
    context: MarketContext,
    long_gate: jnp.ndarray,  # (S,) allows_long_autotrade mask
    adp_diff: jnp.ndarray,  # scalar — breadth[-1]-breadth[-2], NaN if missing
    adp_diff_prev: jnp.ndarray,  # scalar — breadth[-2]-breadth[-3]
    dominance_is_losers: jnp.ndarray,  # scalar bool
    st_up: jnp.ndarray | None = None,  # (S,) bool — carried readout override
) -> StrategyOutputs:
    """Supertrend(10,3) uptrend ∧ RSI<30 ∧ trades>5 ∧ rising ADP twice ∧
    LOSERS dominance. Long; autotrade via the standard long gate.

    ``st_up`` lets the incremental engine inject the supertrend direction
    read from carried scan state (``ops.incremental.SupertrendCarry`` —
    advanced one bar per tick, re-anchored by every full-recompute tick)
    instead of re-running the O(S·W) path-dependent scan here."""
    S = buf5.capacity
    W = buf5.times.shape[1]
    if st_up is None:
        # The reference runs supertrend on the dropna'd enriched frame
        # (coinrule.py:140-143): the series starts after the ma_100 warm-up
        # — 99 rows past the first available bar. The ratchet is
        # path-dependent, so the seed point must match, not just the tail.
        start = (W - pack5.filled + 99).astype(jnp.int32)
        st = supertrend_from(
            buf5.values[:, :, Field.HIGH],
            buf5.values[:, :, Field.LOW],
            buf5.values[:, :, Field.CLOSE],
            start,
            window=10,
            multiplier=3.0,
        )
        st_up = jnp.where(
            jnp.isfinite(st.direction[:, -1]), st.direction[:, -1] > 0, False
        )

    breadth_ok = (
        jnp.isfinite(adp_diff)
        & jnp.isfinite(adp_diff_prev)
        & (adp_diff > 0)
        & (adp_diff_prev > 0)
    )
    fired = (
        st_up
        & (pack5.rsi < 30.0)
        & (pack5.num_trades > 5)
        & breadth_ok
        & dominance_is_losers
        & pack5.valid
    )
    autotrade = fired & jnp.where(context.valid, long_gate, True)
    return StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=autotrade,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "rsi": pack5.rsi,
            "number_of_trades": pack5.num_trades,
            "supertrend_up": st_up,
        },
    )


# ---------------------------------------------------------------------------
# Coinrule: buy_low_sell_high (coinrule.py:230-296)
# ---------------------------------------------------------------------------


def buy_low_sell_high(
    buf15: MarketBuffer,
    pack15: FeaturePack,
    market_domination_reversal: jnp.ndarray,  # scalar bool (host)
) -> StrategyOutputs:
    """RSI<35 ∧ price>MA25 ∧ domination reversal; telemetry-only."""
    S = buf15.capacity
    ma25 = rolling_mean_last(buf15.values[:, :, Field.CLOSE], 25, min_periods=1)
    fired = (
        (pack15.rsi < 35.0)
        & (pack15.close > ma25)
        & market_domination_reversal
        & pack15.valid
    )
    return StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=jnp.zeros((S,), dtype=bool),  # manual_only
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={"rsi": pack15.rsi, "ma_25": ma25},
    )


# ---------------------------------------------------------------------------
# BuyTheDip (buy_the_dip.py)
# ---------------------------------------------------------------------------

BTD_ROUTE_ALLOWED_RANGE = 0  # "symbol_regime_range"/"symbol_regime_transitional"
BTD_ROUTE_NO_CONTEXT = 1
BTD_ROUTE_TRANSITIONING = 2
BTD_ROUTE_STRESS = 3
BTD_ROUTE_MARKET_REGIME = 4
BTD_ROUTE_SYMBOL_REGIME = 5
BTD_ROUTE_QUIET_HOURS = 6


class BTDParams(NamedTuple):
    lookback_candles: int = 24
    lookback_bars_6h: int = 24  # 6h of 15m bars
    dip_min_pct: float = -5.0  # exclusive lower bound
    dip_max_pct: float = -2.0  # exclusive upper bound
    # go-live gate: no fires on bars closing before the strategy's launch
    # (buy_the_dip.py:34 START_TIME = 2026-04-12 23:21 UTC), so a restart
    # backfill can never retro-fire the dip rule
    live_since_s: int = 1_776_036_060


def buy_the_dip(
    buf15: MarketBuffer,
    pack15: FeaturePack,
    context: MarketContext,
    quiet_hours_suppressed: jnp.ndarray,  # scalar bool
    params: BTDParams = BTDParams(),
) -> StrategyOutputs:
    """−2%..−5% dip over the 6h lookback (l.152-159) + reclaim of prior
    close AND EMA20 (l.59-71); trend regimes blocked for entry (l.73-85);
    autotrade only in RANGE/TRANSITIONAL market+micro (l.87-104)."""
    p = params
    S, W = buf15.times.shape
    close = buf15.values[:, :, Field.CLOSE]
    current = pack15.close

    # reference price: last close at or before now-6h. With contiguous 15m
    # bars this is the bar lookback_bars_6h back (close_time <= target).
    idx = W - 1 - p.lookback_bars_6h
    reference = close[:, idx] if idx >= 0 else jnp.full((S,), jnp.nan)
    has_ref = jnp.isfinite(reference) & (buf15.filled > p.lookback_bars_6h)

    change_6h = jsafe_div(current - reference, jnp.abs(reference)) * 100.0
    dip = (change_6h <= p.dip_max_pct) & (change_6h > p.dip_min_pct)

    from binquant_tpu.ops.rolling import ewm_mean_last

    ema20 = ewm_mean_last(close, span=20, min_periods=1)
    reclaimed = (current > pack15.prev_close) & (current > ema20)

    feats = context.features
    market_regime = context.market_regime
    micro = feats.micro_regime
    market_trend_blocked = context.valid & (
        (market_regime == MarketRegimeCode.TREND_DOWN)
        | (market_regime == MarketRegimeCode.TREND_UP)
    )
    symbol_trend_blocked = feats.valid & (
        (micro == MicroRegimeCode.TREND_DOWN) | (micro == MicroRegimeCode.TREND_UP)
    )
    entry_allowed = ~market_trend_blocked & ~symbol_trend_blocked

    # evaluated bar's close time (seconds; the reference compares the
    # close_time stamp — buy_the_dip.py:147-149) vs the go-live date
    live = (buf15.times[:, -1] + 900) >= p.live_since_s

    fired = (
        (pack15.filled >= p.lookback_candles)
        & has_ref
        & dip
        & live
        & entry_allowed
        & reclaimed
        & pack15.valid
    )

    # autotrade routing (l.87-125)
    market_rt = (market_regime == MarketRegimeCode.RANGE) | (
        market_regime == MarketRegimeCode.TRANSITIONAL
    )
    micro_rt_ok = (micro == MicroRegimeCode.RANGE) | (
        micro == MicroRegimeCode.TRANSITIONAL
    )
    micro_blocked = (
        (micro == MicroRegimeCode.TREND_DOWN)
        | (micro == MicroRegimeCode.TREND_UP)
        | (micro == MicroRegimeCode.VOLATILE)
    )
    base_autotrade = (
        context.valid
        & ~context.regime_is_transitioning
        & (context.market_stress_score < 0.35)
        & market_rt
        & jnp.where(feats.valid, ~micro_blocked & micro_rt_ok, True)
    )
    autotrade = base_autotrade & ~quiet_hours_suppressed

    route = jnp.where(
        ~context.valid,
        BTD_ROUTE_NO_CONTEXT,
        jnp.where(
            context.regime_is_transitioning,
            BTD_ROUTE_TRANSITIONING,
            jnp.where(
                context.market_stress_score >= 0.35,
                BTD_ROUTE_STRESS,
                jnp.where(
                    ~market_rt,
                    BTD_ROUTE_MARKET_REGIME,
                    jnp.where(
                        feats.valid & micro_blocked,
                        BTD_ROUTE_SYMBOL_REGIME,
                        BTD_ROUTE_ALLOWED_RANGE,
                    ),
                ),
            ),
        ),
    ).astype(jnp.int32)
    route = jnp.where(
        fired & base_autotrade & quiet_hours_suppressed, BTD_ROUTE_QUIET_HOURS, route
    )

    return StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=fired & autotrade,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "reference_price": jnp.where(has_ref, reference, 0.0),
            "change_6h": jnp.where(has_ref, change_6h, 0.0),
            "route": route,
        },
    )


# ---------------------------------------------------------------------------
# BBExtremeReversion (bb_extreme_reversion.py)
# ---------------------------------------------------------------------------


class BBXParams(NamedTuple):
    enabled: bool = False  # reference ENABLED=False (l.45-46)
    rsi_window: int = 2
    oversold_rsi: float = 5.0
    overbought_rsi: float = 95.0
    max_lower_band_position: float = 0.0
    min_upper_band_position: float = 1.0
    stress_threshold: float = 0.35
    micro_min_strength: float = 0.5


def bb_extreme_reversion(
    buf15: MarketBuffer,
    pack15: FeaturePack,
    context: MarketContext,
    params: BBXParams = BBXParams(),
) -> StrategyOutputs:
    """Connors-style RSI(2) ≤5/≥95 at/beyond the Bollinger bands
    (l.152-232); direction-specific autotrade routing (l.105-135)."""
    p = params
    S = buf15.capacity
    if not p.enabled:
        from binquant_tpu.strategies.base import no_signal

        out = no_signal(S)
        return out

    close = buf15.values[:, :, Field.CLOSE]
    delta = close - shift(close, 1)
    gain = rolling_mean_last(jnp.maximum(delta, 0.0), p.rsi_window)
    loss = rolling_mean_last(jnp.maximum(-delta, 0.0), p.rsi_window)
    rsi2 = jnp.where(
        loss == 0,
        jnp.where(gain == 0, jnp.nan, 100.0),
        100.0 - 100.0 / (1.0 + jsafe_div(gain, jnp.where(loss == 0, 1.0, loss))),
    )
    rsi2 = jnp.clip(jnp.where(jnp.isfinite(gain) & jnp.isfinite(loss), rsi2, jnp.nan), 0, 100)

    band_span = pack15.bb_upper - pack15.bb_lower
    band_position = jnp.where(
        band_span > 0, (pack15.close - pack15.bb_lower) / band_span, 0.5
    )
    buy = (rsi2 <= p.oversold_rsi) & (band_position <= p.max_lower_band_position)
    sell = (rsi2 >= p.overbought_rsi) & (band_position >= p.min_upper_band_position)
    fired = (buy | sell) & jnp.isfinite(rsi2) & (band_span > 0) & pack15.valid

    # base autotrade (supports_autotrade l.88-104) + directional (l.105-135)
    feats = context.features
    base_ok = (
        context.valid
        & ~context.regime_is_transitioning
        & (context.market_stress_score < p.stress_threshold)
        & (context.market_regime == MarketRegimeCode.RANGE)
    )
    trans = feats.micro_transition
    trans_blocked = (
        (trans == MicroTransitionCode.VOLATILITY_EXPANSION)
        | (trans == MicroTransitionCode.BREAKDOWN)
        | (trans == MicroTransitionCode.ENTERED_TRANSITIONAL)
    )
    micro = feats.micro_regime
    shortable = (
        (micro == MicroRegimeCode.RANGE)
        | (micro == MicroRegimeCode.TRANSITIONAL)
        | (micro == MicroRegimeCode.TREND_DOWN)
    )
    directional_ok = (
        feats.valid
        & ~trans_blocked
        & (feats.micro_regime_strength >= p.micro_min_strength)
        & jnp.where(sell, shortable, micro != MicroRegimeCode.TREND_DOWN)
    )
    autotrade = fired & base_ok & directional_ok

    return StrategyOutputs(
        trigger=fired,
        direction=jnp.where(sell, Direction.SHORT, Direction.LONG).astype(jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=autotrade,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "rsi2": jnp.where(jnp.isfinite(rsi2), rsi2, 50.0),
            "band_position": band_position,
            "bb_width": jsafe_div(band_span, pack15.bb_mid),
        },
    )


# ---------------------------------------------------------------------------
# InversePriceTracker (inverse_price_tracker.py)
# ---------------------------------------------------------------------------

IPT_ROUTE_TREND_UP = 0
IPT_ROUTE_TRANSITIONAL_BULLISH = 1
IPT_ROUTE_TRANSITIONAL_TELEMETRY = 2
IPT_ROUTE_RANGE_LEADER = 3
IPT_ROUTE_BLOCKED = 4


class IPTParams(NamedTuple):
    range_rs_min: float = 0.05
    confidence_min: float = 0.4
    followthrough_min: float = -0.1
    adverse_risk_max: float = 0.65
    weights: ScorerWeights = ScorerWeights(
        context_weight=0.35, risk_weight=0.35, support_weight=0.2
    )


def inverse_price_tracker(
    pack5: FeaturePack,
    context: MarketContext,
    params: IPTParams = IPTParams(),
) -> StrategyOutputs:
    """Same oversold trio as PriceTracker, routed to TREND_UP / bullish
    TRANSITIONAL / RANGE-leader markets; telemetry-only (autotrade False)."""
    p = params
    f = pack5
    S = f.close.shape[0]
    enough = (f.filled >= 30) & jnp.isfinite(f.rsi) & jnp.isfinite(f.macd) & jnp.isfinite(f.mfi)
    entry = (f.rsi < 30.0) & (f.macd < 0.0) & (f.mfi < 20.0)

    feats = context.features
    micro = feats.micro_regime

    bullish_transitional_market = (
        (context.market_regime == MarketRegimeCode.TRANSITIONAL)
        & (context.long_tailwind > 0)
        & (context.long_regime_score > context.short_regime_score)
        & (context.long_regime_score > context.range_regime_score)
        & (context.long_regime_score > context.stress_regime_score)
    )
    bullish_transitional_symbol = (
        (micro == MicroRegimeCode.TRANSITIONAL)
        & (feats.trend_score > 0)
        & feats.above_ema20
        & (feats.relative_strength_vs_btc >= 0)
    )
    range_leader = (
        ((micro == MicroRegimeCode.TREND_UP) | (micro == MicroRegimeCode.TRANSITIONAL))
        & (feats.trend_score > 0)
        & (feats.relative_strength_vs_btc >= p.range_rs_min)
    )

    stress_ok = context.market_stress_score < 0.35
    market_trend_up = context.market_regime == MarketRegimeCode.TREND_UP
    market_transitional = context.market_regime == MarketRegimeCode.TRANSITIONAL
    market_range = context.market_regime == MarketRegimeCode.RANGE

    symbol_ok = (micro == MicroRegimeCode.TREND_UP) | bullish_transitional_symbol
    routed = (
        context.valid
        & stress_ok
        & feats.valid
        & (
            ((market_trend_up | market_transitional) & symbol_ok)
            | (market_range & range_leader)
        )
    )
    route = jnp.where(
        routed & market_trend_up,
        IPT_ROUTE_TREND_UP,
        jnp.where(
            routed & bullish_transitional_market,
            IPT_ROUTE_TRANSITIONAL_BULLISH,
            jnp.where(
                routed & market_transitional,
                IPT_ROUTE_TRANSITIONAL_TELEMETRY,
                jnp.where(routed, IPT_ROUTE_RANGE_LEADER, IPT_ROUTE_BLOCKED),
            ),
        ),
    ).astype(jnp.int32)

    local_score = (
        1.0
        + jnp.maximum(0.0, (30.0 - f.rsi) / 30.0) * 0.35
        + jnp.maximum(0.0, (20.0 - f.mfi) / 20.0) * 0.35
        + jnp.minimum(jnp.abs(f.macd) * 100.0, 1.0) * 0.3
    )
    trend_score = jnp.where(
        f.ema21 != 0, jsafe_div(f.ema9 - f.ema21, jnp.abs(f.ema21)), 0.0
    )
    ev = score_signal_candidate(
        context,
        is_short=jnp.asarray(False),
        local_score=local_score,
        symbol_rs=feats.relative_strength_vs_btc,
        symbol_trend=trend_score,
        weights=p.weights,
        emit_threshold=1.0,
    )
    cs = ev.context_score
    telemetry_ok = (
        (cs.confidence >= p.confidence_min)
        & (cs.followthrough_score >= p.followthrough_min)
        & (cs.adverse_excursion_risk <= p.adverse_risk_max)
    )

    fired = entry & enough & routed & telemetry_ok & f.valid
    return StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),
        score=jnp.where(fired, local_score, 0.0),
        autotrade=jnp.zeros((S,), dtype=bool),  # telemetry-only (l.190)
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "rsi": f.rsi,
            "mfi": f.mfi,
            "macd": f.macd,
            "adjusted_score": ev.adjusted_score,
            "route": route,
        },
    )


# ---------------------------------------------------------------------------
# RangeBbRsiMeanReversion (range_bb_rsi_mean_reversion.py)
# ---------------------------------------------------------------------------


class RBRParams(NamedTuple):
    min_candles: int = 40
    adx_window: int = 14
    zscore_window: int = 20
    adx_max: float = 32.0
    long_rsi_max: float = 35.0
    short_rsi_min: float = 65.0
    long_zscore_max: float = -2.0
    short_zscore_min: float = 2.0
    band_touch_tolerance: float = 0.002
    max_market_stress: float = 0.35
    max_symbol_atr_pct: float = 0.04
    max_symbol_bb_width: float = 0.08
    min_rejection_wick_frac: float = 0.30
    long_close_position_min: float = 0.55
    short_close_position_max: float = 0.45


def _adx_rolling_sum(
    high: jnp.ndarray, low: jnp.ndarray, close: jnp.ndarray, window: int
) -> jnp.ndarray:
    """The strategy's inline rolling-sum ADX (l.101-128) — NOT Wilder EWM.
    Returns the last ADX value; 100.0 when NaN (reference l.128)."""
    hd = high - shift(high, 1)
    ld = shift(low, 1) - low
    plus_dm = jnp.where((hd > ld) & (hd > 0), hd, 0.0)
    minus_dm = jnp.where((ld > hd) & (ld > 0), ld, 0.0)
    pc = shift(close, 1)
    tr = jnp.maximum(high - low, jnp.maximum(jnp.abs(high - pc), jnp.abs(low - pc)))
    tr = jnp.where(jnp.isfinite(pc), tr, high - low)
    atr_sum = rolling_sum(tr, window)
    plus_di = 100.0 * jsafe_div(rolling_sum(plus_dm, window), atr_sum)
    minus_di = 100.0 * jsafe_div(rolling_sum(minus_dm, window), atr_sum)
    di_total = plus_di + minus_di
    dx = jnp.where(
        di_total != 0,
        100.0 * jnp.abs(plus_di - minus_di) / jnp.where(di_total != 0, di_total, 1.0),
        0.0,
    )
    dx = jnp.where(jnp.isfinite(atr_sum), dx, jnp.nan)
    adx = rolling_mean(dx, window)[:, -1]
    return jnp.where(jnp.isfinite(adx), adx, 100.0)


def range_bb_rsi_mean_reversion(
    buf15: MarketBuffer,
    pack15: FeaturePack,
    context: MarketContext,
    params: RBRParams = RBRParams(),
) -> StrategyOutputs:
    """RANGE×RANGE fade with ADX<32 veto, ±2σ z-score, wick-rejection
    candle filters. Autotrade on when fired (reference emits with
    autotrade=True via bot_params)."""
    p = params
    f = pack15
    S = buf15.capacity
    high = buf15.values[:, :, Field.HIGH]
    low = buf15.values[:, :, Field.LOW]
    close = buf15.values[:, :, Field.CLOSE]

    feats = context.features
    trans = feats.micro_transition
    routing_ok = (
        context.valid
        & (context.market_stress_score < p.max_market_stress)
        & (context.market_regime == MarketRegimeCode.RANGE)
        & feats.valid
        & (feats.micro_regime == MicroRegimeCode.RANGE)
        & (trans != MicroTransitionCode.BREAKOUT_UP)
        & (trans != MicroTransitionCode.BREAKDOWN)
        & (trans != MicroTransitionCode.VOLATILITY_EXPANSION)
        & (feats.atr_pct <= p.max_symbol_atr_pct)
        & (feats.bb_width <= p.max_symbol_bb_width)
    )

    adx = _adx_rolling_sum(high, low, close, p.adx_window)
    adx_ok = adx <= p.adx_max

    mean = rolling_mean_last(close, p.zscore_window)
    std = rolling_std_last(close, p.zscore_window, ddof=0)
    z = jnp.where((std > 0) & jnp.isfinite(std), (f.close - mean) / jnp.where(std > 0, std, 1.0), 0.0)

    candle_range = f.high - f.low
    range_ok = candle_range > 0
    lower_wick = jnp.minimum(f.open, f.close) - f.low
    upper_wick = f.high - jnp.maximum(f.open, f.close)
    close_position = jsafe_div(f.close - f.low, candle_range)

    bullish_rej = (
        range_ok
        & (f.low <= f.bb_lower * (1.0 + p.band_touch_tolerance))
        & (f.close > f.open)
        & (jsafe_div(lower_wick, candle_range) >= p.min_rejection_wick_frac)
        & (close_position >= p.long_close_position_min)
    )
    bearish_rej = (
        range_ok
        & (f.high >= f.bb_upper * (1.0 - p.band_touch_tolerance))
        & (f.close < f.open)
        & (jsafe_div(upper_wick, candle_range) >= p.min_rejection_wick_frac)
        & (close_position <= p.short_close_position_max)
    )

    long_setup = (
        (f.close <= f.bb_mid) & (f.rsi <= p.long_rsi_max) & (z <= p.long_zscore_max) & bullish_rej
    )
    short_setup = (
        (f.close >= f.bb_mid) & (f.rsi >= p.short_rsi_min) & (z >= p.short_zscore_min) & bearish_rej
    )

    fired = (
        (f.filled >= p.min_candles)
        & jnp.isfinite(f.rsi)
        & routing_ok
        & adx_ok
        & (long_setup | short_setup)
    )
    return StrategyOutputs(
        trigger=fired,
        direction=jnp.where(short_setup, Direction.SHORT, Direction.LONG).astype(jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=fired,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={"adx": adx, "zscore": z, "rsi": f.rsi},
    )


# ---------------------------------------------------------------------------
# RangeFailedBreakoutFade (range_failed_breakout_fade.py)
# ---------------------------------------------------------------------------


def range_failed_breakout_fade(
    spikes: SpikeSignal,
    context: MarketContext,
    avg_return_max: float = -0.005,
) -> StrategyOutputs:
    """Short a fresh bullish spike (any spike flag + upward streak) when the
    market is RANGE, average return < −0.5%, and the symbol is an
    outperformer (RS ≥ 0)."""
    feats = context.features
    long_flags = (
        spikes.cumulative_price_break_flag
        | spikes.volume_cluster_flag
        | spikes.price_break_flag
        | spikes.accel_spike_flag
    )
    spike_ok = long_flags & spikes.upward
    routing_ok = (
        context.valid
        & (context.market_regime == MarketRegimeCode.RANGE)
        & (context.average_return < avg_return_max)
        & feats.valid
        & (feats.relative_strength_vs_btc >= 0)
    )
    fired = spike_ok & routing_ok
    S = spikes.close.shape[0]
    return StrategyOutputs(
        trigger=fired,
        direction=jnp.full((S,), Direction.SHORT, dtype=jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=fired,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "volume_cluster_flag": spikes.volume_cluster_flag,
            "price_break_flag": spikes.price_break_flag,
            "cumulative_price_break_flag": spikes.cumulative_price_break_flag,
            "accel_spike_flag": spikes.accel_spike_flag,
            "volume": spikes.volume,
            "quote_asset_volume": spikes.quote_asset_volume,
        },
    )


# ---------------------------------------------------------------------------
# RelativeStrengthReversalRange (relative_strength_reversal_range.py)
# ---------------------------------------------------------------------------


class RSRParams(NamedTuple):
    avg_return_max: float = -0.02
    rs_vs_btc_min: float = 0.05
    volume_percentile: float = 0.20
    volume_window: int = 96


def relative_strength_reversal_range(
    buf15: MarketBuffer,
    pack15: FeaturePack,
    context: MarketContext,
    params: RSRParams = RSRParams(),
) -> StrategyOutputs:
    """Contrarian long on an RS leader (> +5% vs BTC) during a broad selloff
    (avg return < −2%) with a volume floor at the 20th percentile of the
    last 24h. Telemetry-only while live P&L is collected (l.103-105)."""
    p = params
    S = buf15.capacity
    feats = context.features
    routing_ok = (
        context.valid
        & (context.market_regime == MarketRegimeCode.RANGE)
        & (context.average_return < p.avg_return_max)
        & feats.valid
        & (feats.relative_strength_vs_btc > p.rs_vs_btc_min)
    )

    volume = buf15.values[:, -p.volume_window:, Field.VOLUME]
    finite = jnp.isfinite(volume)
    cnt = jnp.sum(finite, axis=-1)
    s = jnp.sort(jnp.where(finite, volume, jnp.inf), axis=-1)
    rank = p.volume_percentile * (cnt - 1.0)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, p.volume_window - 1)
    hi = jnp.clip(lo + 1, 0, p.volume_window - 1)
    frac = rank - lo
    v_lo = jnp.take_along_axis(s, lo[:, None], axis=-1)[:, 0]
    v_hi = jnp.take_along_axis(
        s, jnp.minimum(hi, jnp.maximum(cnt - 1, 0))[:, None], axis=-1
    )[:, 0]
    floor = v_lo + (v_hi - v_lo) * frac

    fired = (
        (pack15.filled >= p.volume_window)
        & routing_ok
        & (pack15.volume > floor)
        & pack15.valid
    )
    return StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),
        score=jnp.zeros((S,), dtype=jnp.float32),
        autotrade=jnp.zeros((S,), dtype=bool),  # telemetry-only
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "volume_floor": jnp.where(jnp.isfinite(floor), floor, 0.0),
            "relative_strength_vs_btc": feats.relative_strength_vs_btc,
        },
    )
