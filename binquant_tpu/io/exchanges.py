"""Exchange REST clients (Binance spot, KuCoin spot/futures).

Equivalent surface to the pybinbot exchange clients the reference consumes
(SURVEY.md §2.8): ``get_ui_klines``, ``get_ticker_price``,
``get_open_interest``, ``get_mark_price``, ``get_symbol_info``. Sessions are
injectable; only the endpoints binquant actually calls are implemented.
"""

from __future__ import annotations

from typing import Any, NamedTuple


class FuturesSymbolInfo(NamedTuple):
    """Fields the futures margin resolver reads
    (consumers/autotrade_consumer.py:117-123)."""

    symbol: str
    multiplier: float
    lot_size: float
    taker_fee_rate: float


class _RestClient:
    def __init__(self, base_url: str, session: Any | None = None) -> None:
        self.base_url = base_url.rstrip("/")
        if session is None:
            import httpx

            session = httpx.Client(timeout=10)
        self.session = session

    def _get(self, path: str, params: dict | None = None) -> Any:
        resp = self.session.get(f"{self.base_url}{path}", params=params or {})
        resp.raise_for_status()
        return resp.json()


class BinanceApi(_RestClient):
    BASE = "https://api.binance.com"

    def __init__(self, key: str = "", secret: str = "", session: Any | None = None):
        super().__init__(self.BASE, session)
        self.key, self.secret = key, secret

    def get_ui_klines(
        self, symbol: str, interval: str = "15m", limit: int = 400
    ) -> list[list]:
        return self._get(
            "/api/v3/uiKlines",
            {"symbol": symbol, "interval": interval, "limit": limit},
        )

    def get_ticker_price(self, symbol: str) -> float:
        data = self._get("/api/v3/ticker/price", {"symbol": symbol})
        return float(data["price"])

    def get_request_weight(self, resp_headers: dict) -> int:
        """Binance used-weight header (shared/utils.py:70-104 reads
        x-mbx-used-weight-1m for the rate-limit guard)."""
        return int(resp_headers.get("x-mbx-used-weight-1m", 0))


class KucoinApi(_RestClient):
    BASE = "https://api.kucoin.com"

    def __init__(
        self,
        key: str = "",
        secret: str = "",
        passphrase: str = "",
        session: Any | None = None,
    ):
        super().__init__(self.BASE, session)
        self.key, self.secret, self.passphrase = key, secret, passphrase

    def get_ticker_price(self, symbol: str) -> float:
        data = self._get(
            "/api/v1/market/orderbook/level1", {"symbol": symbol}
        )
        return float(data["data"]["price"])

    def get_ui_klines(
        self, symbol: str, interval: str = "15min", limit: int = 400
    ) -> list[list]:
        data = self._get(
            "/api/v1/market/candles", {"symbol": symbol, "type": interval}
        )
        return list(data.get("data", []))[:limit]


class KucoinFutures(_RestClient):
    BASE = "https://api-futures.kucoin.com"

    def __init__(
        self,
        key: str = "",
        secret: str = "",
        passphrase: str = "",
        session: Any | None = None,
    ):
        super().__init__(self.BASE, session)
        self.key, self.secret, self.passphrase = key, secret, passphrase

    def get_symbol_info(self, symbol: str) -> FuturesSymbolInfo:
        data = self._get(f"/api/v1/contracts/{symbol}")["data"]
        return FuturesSymbolInfo(
            symbol=symbol,
            multiplier=float(data.get("multiplier", 1.0)),
            lot_size=float(data.get("lotSize", 1.0)),
            taker_fee_rate=float(data.get("takerFeeRate", 0.0006)),
        )

    def get_mark_price(self, symbol: str) -> float:
        data = self._get(f"/api/v1/mark-price/{symbol}/current")["data"]
        return float(data["value"])

    def get_open_interest(self, symbol: str) -> float:
        data = self._get(f"/api/v1/contracts/{symbol}")["data"]
        return float(data.get("openInterest", 0.0) or 0.0)
