# binquant_tpu — single-container deployment (reference Dockerfile parity:
# one process, heartbeat healthcheck, SIGTERM stop). Deps come from
# pyproject.toml (single source of truth). The default build installs CPU
# jax (container smoke / non-TPU hosts); build with --build-arg EXTRAS=tpu
# on a TPU VM to pull libtpu.
FROM python:3.12-slim

WORKDIR /app
ARG EXTRAS=""

COPY pyproject.toml README.md ./
COPY binquant_tpu ./binquant_tpu
RUN pip install --no-cache-dir ".${EXTRAS:+[$EXTRAS]}"

COPY main.py healthcheck.py bench.py __graft_entry__.py ./

HEALTHCHECK --interval=60s --timeout=10s --retries=3 \
    CMD ["python", "healthcheck.py"]

STOPSIGNAL SIGTERM
CMD ["python", "main.py"]
