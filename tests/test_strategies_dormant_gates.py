"""Dormant-strategy gate matrices — deterministic branch coverage.

Mirrors the reference's per-strategy test files (e.g.
``tests/test_coinrule_buy_the_dip.py``'s 14 gate tests,
``tests/test_range_bb_rsi_mean_reversion.py``): each strategy gets a
deterministic base scenario that MUST fire, then every gate is flipped
one at a time and must block (or flip autotrade only, where the reference
emits with autotrade off).
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd

from binquant_tpu.enums import (
    MarketRegimeCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.strategies import (
    buy_low_sell_high,
    buy_the_dip,
    compute_feature_pack,
    inverse_price_tracker,
    range_bb_rsi_mean_reversion,
    relative_strength_reversal_range,
    twap_momentum_sniper,
)
from binquant_tpu.strategies.dormant import (
    BTD_ROUTE_QUIET_HOURS,
    BTD_ROUTE_STRESS,
)
from tests.test_regime_routing_scoring import mk_context, mk_features
from tests.test_strategies_live import S_CAP, WINDOW, fill_buffer


def flat_df(n=WINDOW, price=100.0, vol_noise=0.0):
    # past BuyTheDip's go-live gate (buy_the_dip.py:34 START_TIME 2026-04-12)
    t0 = 1_776_040_000_000
    close = np.full(n, price)
    if vol_noise:
        close = price * (1 + vol_noise * np.sin(np.arange(n) * 0.9))
    open_ = np.concatenate([[price], close[:-1]])
    return pd.DataFrame(
        {
            "open_time": t0 + 900_000 * np.arange(n, dtype=np.int64),
            "close_time": t0 + 900_000 * np.arange(n, dtype=np.int64) + 899_999,
            "open": open_,
            "high": np.maximum(open_, close) * 1.0005,
            "low": np.minimum(open_, close) * 0.9995,
            "close": close,
            "volume": np.full(n, 1000.0),
            "quote_asset_volume": close * 1000.0,
            "number_of_trades": np.full(n, 500.0),
            "taker_buy_base_volume": np.full(n, 500.0),
            "taker_buy_quote_volume": close * 500.0,
        }
    )


# ---------------------------------------------------------------------------
# BuyTheDip — deterministic dip/reclaim matrix (reference: 14 gate tests)
# ---------------------------------------------------------------------------


def craft_dip(final_close=97.6, dip_level=97.0):
    """Reference bar (-25) at 100, 6h dip to ``dip_level``, final bar at
    ``final_close``. change_6h = final_close - 100 (%). EMA20 after 23
    bars at 97 decays to ~97.27, so 97.6 reclaims it deterministically."""
    df = flat_df()
    n = len(df)
    for j in range(n - 24, n - 1):
        df.loc[df.index[j], "close"] = dip_level
        df.loc[df.index[j], "open"] = dip_level
        df.loc[df.index[j], "high"] = dip_level * 1.0005
        df.loc[df.index[j], "low"] = dip_level * 0.9995
    df.loc[df.index[-1], "open"] = dip_level
    df.loc[df.index[-1], "close"] = final_close
    df.loc[df.index[-1], "high"] = final_close * 1.0005
    df.loc[df.index[-1], "low"] = dip_level * 0.9995
    return df


class TestBuyTheDipGates:
    def _eval(self, df, ctx=None, quiet=False):
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        return buy_the_dip(
            buf, pack, ctx or mk_context(n=S_CAP), jnp.asarray(quiet)
        )

    def test_base_dip_reclaim_fires_with_autotrade(self):
        out = self._eval(craft_dip())
        assert -5.0 < float(out.diagnostics["change_6h"][0]) <= -2.0
        assert bool(out.trigger[0])
        assert bool(out.autotrade[0])

    def test_repeated_signals_without_local_cooldown(self):
        # the reference deliberately has no per-strategy cooldown here
        df = craft_dip()
        assert bool(self._eval(df).trigger[0])
        assert bool(self._eval(df).trigger[0])

    def test_dip_too_small_skips(self):
        # -1.5% > -2% upper bound
        out = self._eval(craft_dip(final_close=98.5))
        assert not bool(out.trigger[0])

    def test_dip_too_deep_skips(self):
        # -5.5% <= -5% lower bound (dip must stay above it)
        out = self._eval(craft_dip(final_close=94.5, dip_level=94.0))
        assert not bool(out.trigger[0])

    def test_requires_reclaim_above_prior_close(self):
        # close 96.9 < prior close 97: no reclaim (still a valid dip %)
        out = self._eval(craft_dip(final_close=96.9))
        assert not bool(out.trigger[0])

    def test_requires_reclaim_above_ema20(self):
        # above prior close (97.05 > 97) but below the ~97.27 EMA20
        out = self._eval(craft_dip(final_close=97.05))
        assert not bool(out.trigger[0])

    def test_market_trend_regimes_block_entry(self):
        for regime in (MarketRegimeCode.TREND_UP, MarketRegimeCode.TREND_DOWN):
            ctx = mk_context(n=S_CAP, market_regime=np.int32(regime))
            assert not bool(self._eval(craft_dip(), ctx).trigger[0])

    def test_symbol_trend_regimes_block_entry(self):
        for micro in (MicroRegimeCode.TREND_UP, MicroRegimeCode.TREND_DOWN):
            ctx = mk_context(
                n=S_CAP,
                features=mk_features(
                    n=S_CAP,
                    micro_regime=np.full(S_CAP, int(micro), np.int32),
                ),
            )
            assert not bool(self._eval(craft_dip(), ctx).trigger[0])

    def test_stress_blocks_autotrade_not_signal(self):
        ctx = mk_context(n=S_CAP, market_stress_score=0.5)
        out = self._eval(craft_dip(), ctx)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == BTD_ROUTE_STRESS

    def test_transitioning_blocks_autotrade_not_signal(self):
        ctx = mk_context(n=S_CAP, regime_is_transitioning=True)
        out = self._eval(craft_dip(), ctx)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])

    def test_quiet_hours_flips_autotrade_only(self):
        out = self._eval(craft_dip(), quiet=True)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])
        assert int(out.diagnostics["route"][0]) == BTD_ROUTE_QUIET_HOURS


# ---------------------------------------------------------------------------
# RangeBbRsiMeanReversion — short rejection + vetoes
# ---------------------------------------------------------------------------


def craft_upper_rejection():
    """Low-noise oscillation (keeps rolling-sum ADX low), a 2-bar pop to
    +2σ, then a bearish upper-wick rejection candle."""
    df = flat_df(vol_noise=0.002)
    n = len(df)
    c2 = float(df["close"].iloc[-4])
    pops = [c2 * 1.012, c2 * 1.024]
    for j, c in enumerate(pops):
        i = n - 3 + j
        df.loc[df.index[i], "open"] = pops[j - 1] if j else c2
        df.loc[df.index[i], "close"] = c
        df.loc[df.index[i], "high"] = c * 1.001
        df.loc[df.index[i], "low"] = (pops[j - 1] if j else c2) * 0.999
    top = pops[-1]
    df.loc[df.index[-1], "open"] = top * 1.001
    df.loc[df.index[-1], "high"] = top * 1.009
    df.loc[df.index[-1], "close"] = top * 0.9985
    df.loc[df.index[-1], "low"] = top * 0.998
    return df


class TestRangeBbRsiShort:
    def _eval(self, df, ctx=None):
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        return range_bb_rsi_mean_reversion(
            buf, pack, ctx or mk_context(n=S_CAP)
        )

    def test_short_rejection_fires(self):
        out = self._eval(craft_upper_rejection())
        assert float(out.diagnostics["adx"][0]) <= 32.0
        assert float(out.diagnostics["zscore"][0]) >= 2.0
        assert float(out.diagnostics["rsi"][0]) >= 65.0
        assert bool(out.trigger[0])
        assert int(out.direction[0]) == 1  # SHORT
        assert bool(out.autotrade[0])

    def test_trending_adx_vetoes(self):
        # a steady ramp makes rolling-sum ADX spike above 32
        df = flat_df()
        n = len(df)
        for j in range(30):
            i = n - 30 + j
            c = 100.0 * (1 + 0.004 * (j + 1))
            df.loc[df.index[i], "open"] = c * 0.998
            df.loc[df.index[i], "close"] = c
            df.loc[df.index[i], "high"] = c * 1.001
            df.loc[df.index[i], "low"] = c * 0.996
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        out = range_bb_rsi_mean_reversion(buf, pack, mk_context(n=S_CAP))
        assert float(out.diagnostics["adx"][0]) > 32.0
        assert not bool(out.trigger[0])

    def test_non_range_market_blocks(self):
        ctx = mk_context(
            n=S_CAP, market_regime=np.int32(MarketRegimeCode.TREND_UP)
        )
        assert not bool(self._eval(craft_upper_rejection(), ctx).trigger[0])

    def test_breakdown_transition_blocks(self):
        ctx = mk_context(
            n=S_CAP,
            features=mk_features(
                n=S_CAP,
                micro_transition=np.full(
                    S_CAP, int(MicroTransitionCode.BREAKDOWN), np.int32
                ),
            ),
        )
        assert not bool(self._eval(craft_upper_rejection(), ctx).trigger[0])

    def test_no_rejection_candle_blocks(self):
        # same pop but the last candle closes green at its highs
        df = craft_upper_rejection()
        top = float(df["close"].iloc[-2])
        df.loc[df.index[-1], "open"] = top
        df.loc[df.index[-1], "close"] = top * 1.008
        df.loc[df.index[-1], "high"] = top * 1.009
        df.loc[df.index[-1], "low"] = top * 0.999
        assert not bool(self._eval(df).trigger[0])


# ---------------------------------------------------------------------------
# RelativeStrengthReversalRange — gate flips
# ---------------------------------------------------------------------------


class TestRelativeStrengthGates:
    def _ctx(self, avg_return=-0.03, rs=0.08):
        return mk_context(
            n=S_CAP,
            average_return=avg_return,
            features=mk_features(
                n=S_CAP,
                relative_strength_vs_btc=np.full(S_CAP, rs, np.float32),
            ),
        )

    def _eval(self, ctx):
        df = flat_df(vol_noise=0.001)
        # the floor is the 20th pct of 24h volume; a constant series makes
        # floor == volume and the strict > gate false — trade above it
        df.loc[df.index[-1], "volume"] = 1200.0
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        return relative_strength_reversal_range(buf, pack, ctx)

    def test_leader_in_selloff_fires_telemetry_only(self):
        out = self._eval(self._ctx())
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])  # telemetry while P&L collects

    def test_rs_below_floor_blocks(self):
        assert not bool(self._eval(self._ctx(rs=0.04)).trigger[0])

    def test_mild_selloff_blocks(self):
        assert not bool(self._eval(self._ctx(avg_return=-0.01)).trigger[0])

    def test_volume_below_floor_blocks(self):
        df = flat_df(vol_noise=0.001)
        # the last bar's volume at the absolute bottom of the 24h window
        df.loc[df.index[-1], "volume"] = 1.0
        buf = fill_buffer({0: df})
        pack = compute_feature_pack(buf)
        out = relative_strength_reversal_range(buf, pack, self._ctx())
        assert not bool(out.trigger[0])


# ---------------------------------------------------------------------------
# TWAP momentum sniper — selloff veto
# ---------------------------------------------------------------------------


class TestTwapSniper:
    def test_sharp_selloff_vetoes(self):
        # price_decrease = close[-1] - close[-2]/close[-1] (the reference's
        # formula, verbatim): with 1h closes ~1.0 and a prior-hour pop to
        # 1.06, the expression goes below -0.05 and vetoes.
        df15 = flat_df(price=1.0)
        # pop the previous CALENDAR hour's bars (the resample buckets by
        # open_time // 3600, so address the bucket, not a trailing block)
        hours = df15["open_time"] // 3_600_000
        prev_hour = hours == (int(hours.iloc[-1]) - 1)
        assert prev_hour.any()
        df15.loc[prev_hour, "close"] = 1.06
        buf15 = fill_buffer({0: df15})
        df5 = flat_df(price=2.0)  # price 2.0 > twap 1.0: twap gate false too
        df5.loc[df5.index[-1], "close"] = 0.5  # price below TWAP -> gate true
        buf5 = fill_buffer({0: df5})
        pack5 = compute_feature_pack(buf5)
        out = twap_momentum_sniper(buf15, pack5)
        assert float(out.diagnostics["price_decrease"][0]) <= -0.05
        assert not bool(out.trigger[0])

    def test_twap_above_price_fires_manual_only(self):
        df15 = flat_df(price=1.0)
        buf15 = fill_buffer({0: df15})
        df5 = flat_df(price=1.0)
        df5.loc[df5.index[-1], "close"] = 0.5
        buf5 = fill_buffer({0: df5})
        pack5 = compute_feature_pack(buf5)
        out = twap_momentum_sniper(buf15, pack5)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])  # manual_only


# ---------------------------------------------------------------------------
# buy_low_sell_high — host-flag gates
# ---------------------------------------------------------------------------


class TestBuyLowSellHigh:
    def _bufpack(self):
        # RSI<35 needs 14 straight losses; close>MA25 needs the mean
        # dragged down — a crash (80) then recovery (102) then a gentle
        # all-red slide gives RSI=0 with close ~100.6 above MA25 ~96
        df = flat_df(price=100.0)
        n = len(df)

        def set_bar(i, c):
            df.loc[df.index[i], "open"] = c * 1.001
            df.loc[df.index[i], "close"] = c
            df.loc[df.index[i], "high"] = c * 1.002
            df.loc[df.index[i], "low"] = c * 0.999

        for i in range(n - 40, n - 25):
            set_bar(i, 80.0)
        for j, i in enumerate(range(n - 25, n - 14)):
            set_bar(i, 80.0 + 22.0 * (j + 1) / 11.0)  # ramp to 102
        for j, i in enumerate(range(n - 14, n)):
            set_bar(i, 102.0 - 0.1 * (j + 1))  # 14 straight losses
        buf = fill_buffer({0: df})
        return buf, compute_feature_pack(buf)

    def test_requires_domination_reversal_flag(self):
        buf, pack = self._bufpack()
        rsi = float(pack.rsi[0])
        ma25_gate = float(pack.close[0])
        out_on = buy_low_sell_high(buf, pack, jnp.asarray(True))
        out_off = buy_low_sell_high(buf, pack, jnp.asarray(False))
        expected = rsi < 35.0 and ma25_gate > float(
            out_on.diagnostics["ma_25"][0]
        )
        assert expected  # the crafted slide must reach the entry zone
        assert bool(out_on.trigger[0])
        assert not bool(out_on.autotrade[0])  # manual_only
        assert not bool(out_off.trigger[0])


# ---------------------------------------------------------------------------
# InversePriceTracker — routing matrix
# ---------------------------------------------------------------------------


class TestInverseTrackerRouting:
    def _oversold_pack(self):
        # strictly falling tail: RSI=0, MFI=0, MACD<0 (same device kernels
        # the live PriceTracker tests pin)
        df = flat_df(price=100.0)
        n = len(df)
        for j in range(25):
            i = n - 25 + j
            c = 100.0 * (1 - 0.004 * (j + 1))
            df.loc[df.index[i], "open"] = c * 1.002
            df.loc[df.index[i], "close"] = c
            df.loc[df.index[i], "high"] = c * 1.003
            df.loc[df.index[i], "low"] = c * 0.998
        buf = fill_buffer({0: df})
        return compute_feature_pack(buf)

    def test_trend_up_market_routes(self):
        pack = self._oversold_pack()
        ctx = mk_context(
            n=S_CAP,
            market_regime=np.int32(MarketRegimeCode.TREND_UP),
            features=mk_features(
                n=S_CAP,
                micro_regime=np.full(
                    S_CAP, int(MicroRegimeCode.TREND_UP), np.int32
                ),
            ),
        )
        out = inverse_price_tracker(pack, ctx)
        assert bool(out.trigger[0])
        assert not bool(out.autotrade[0])  # telemetry-only by design

    def test_range_market_needs_rs_leadership(self):
        pack = self._oversold_pack()
        base = dict(
            micro_regime=np.full(S_CAP, int(MicroRegimeCode.TREND_UP), np.int32),
            trend_score=np.full(S_CAP, 0.01, np.float32),
        )
        leader = mk_context(
            n=S_CAP,
            features=mk_features(
                n=S_CAP,
                relative_strength_vs_btc=np.full(S_CAP, 0.06, np.float32),
                **base,
            ),
        )
        laggard = mk_context(
            n=S_CAP,
            features=mk_features(
                n=S_CAP,
                relative_strength_vs_btc=np.full(S_CAP, 0.01, np.float32),
                **base,
            ),
        )
        assert bool(inverse_price_tracker(pack, leader).trigger[0])
        assert not bool(inverse_price_tracker(pack, laggard).trigger[0])

    def test_stress_blocks(self):
        pack = self._oversold_pack()
        ctx = mk_context(
            n=S_CAP,
            market_regime=np.int32(MarketRegimeCode.TREND_UP),
            market_stress_score=0.5,
            features=mk_features(
                n=S_CAP,
                micro_regime=np.full(
                    S_CAP, int(MicroRegimeCode.TREND_UP), np.int32
                ),
            ),
        )
        assert not bool(inverse_price_tracker(pack, ctx).trigger[0])
