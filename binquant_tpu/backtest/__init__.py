"""Time-batched backtest subsystem (ISSUE 6).

A distinct *backtest backend* next to the per-tick live step and the
ISSUE-5 scanned replay: instead of threading a carried recursion serially
through time, it evaluates the FULL-recompute tick semantics over an
``(S, W+T)`` extended buffer — per-tick right-aligned window views are
gathers, the heavy windowed math time-vectorizes across the whole chunk,
and only the genuinely sequential recursions (market-regime carry, the
PT/MRF dedupe cooldowns, the grid-policy feedback) ride a cheap
``lax.scan``. ``vmap`` over a strategy-parameter axis scores a whole
hyperparameter grid in one dispatch.
"""

from binquant_tpu.backtest.driver import (  # noqa: F401
    run_backtest,
    run_param_sweep,
)
from binquant_tpu.backtest.kernel import (  # noqa: F401
    BACKTEST_STRATEGIES,
    backtest_chunk,
    backtest_chunk_sweep,
)
