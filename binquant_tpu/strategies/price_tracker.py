"""Coinrule PriceTracker — 5m oversold mean-reversion long, batched.

Re-implements ``/root/reference/strategies/coinrule/price_tracker.py``:
entry RSI(14)<30 ∧ MACD<0 ∧ MFI<20 on 5m candles (l.186), local score from
oversold depth (l.190-195), context-adjusted score with the strategy's own
scorer weights (0.35/0.35/0.2, l.54-58) and telemetry gates — bad
followthrough / high risk / low confidence kill the signal (l.229-234) —
its own RANGE-only regime routing with the stable-breadth band and
RS-vs-BTC floor (l.96-155), a 12-bar entry cooldown keyed on close_time
(l.34,78-94) carried as a device array, and quiet-hours autotrade
suppression (l.245-255; wall-clock flag supplied by the host).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from binquant_tpu.enums import (
    MarketRegimeCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.regime.context import MarketContext
from binquant_tpu.regime.scoring import ScorerWeights, score_signal_candidate
from binquant_tpu.strategies.base import StrategyOutputs
from binquant_tpu.strategies.features import FeaturePack
from binquant_tpu.utils import jsafe_div

# Route codes for the host's reason strings (regime_routing l.108-155)
ROUTE_SYMBOL_RANGE = 0  # allowed: "symbol_range"
ROUTE_NO_CONTEXT = 1
ROUTE_TRANSITIONING = 2
ROUTE_STRESS = 3
ROUTE_BREADTH_UNSTABLE = 4
ROUTE_NOT_RANGE = 5
ROUTE_NO_SYMBOL_FEATURES = 6
ROUTE_SYMBOL_TRANSITION = 7
ROUTE_RS_INSUFFICIENT = 8
ROUTE_SYMBOL_REGIME = 9
ROUTE_QUIET_HOURS = 10


class PTParams(NamedTuple):
    """Class constants (l.33-36, 119) + scorer weights (l.54-58) + the
    entry thresholds (l.186, 190-195 — were literals in the kernel)."""

    entry_cooldown_bars: int = 12
    min_rs_vs_btc: float = 0.005
    stress_threshold: float = 0.3  # min(autotrade_stress_threshold, 0.3)
    rsi_oversold: float = 30.0  # entry: RSI(14) < this
    mfi_oversold: float = 20.0  # entry: MFI(14) < this
    macd_entry_max: float = 0.0  # entry: MACD line < this
    weights: ScorerWeights = ScorerWeights(
        context_weight=0.35, risk_weight=0.35, support_weight=0.2
    )


def _has_stable_breadth(context: MarketContext) -> jnp.ndarray:
    """Breadth balanced 0.48–0.62 ∧ tailwind gap ≤ 0.35 (l.96-106)."""
    balanced = (context.advancers_ratio >= 0.48) & (context.advancers_ratio <= 0.62)
    tailwinds = jnp.abs(context.long_tailwind - context.short_tailwind) <= 0.35
    return balanced & tailwinds


def price_tracker(
    pack5: FeaturePack,
    context: MarketContext,
    quiet_hours_suppressed: jnp.ndarray,  # scalar bool (host wall-clock)
    last_signal_close_time: jnp.ndarray,  # (S,) int32 carry, -1 = never
    interval_s: int = 300,
    params: PTParams = PTParams(),
) -> tuple[StrategyOutputs, jnp.ndarray]:
    p = params
    f = pack5
    S = f.close.shape[0]

    # data sufficiency: >=30 bars and recent values present (l.166-173)
    enough = (f.filled >= 30) & jnp.isfinite(f.rsi) & jnp.isfinite(f.macd) & jnp.isfinite(f.mfi)

    entry = (
        (f.rsi < p.rsi_oversold)
        & (f.macd < p.macd_entry_max)
        & (f.mfi < p.mfi_oversold)
    )

    local_score = (
        1.0
        + jnp.maximum(0.0, (p.rsi_oversold - f.rsi) / p.rsi_oversold) * 0.35
        + jnp.maximum(0.0, (p.mfi_oversold - f.mfi) / p.mfi_oversold) * 0.35
        + jnp.minimum(jnp.abs(f.macd) * 100.0, 1.0) * 0.3
    )
    trend_score = jnp.where(
        f.ema21 != 0, jsafe_div(f.ema9 - f.ema21, jnp.abs(f.ema21)), 0.0
    )

    feats = context.features
    evaluation = score_signal_candidate(
        context,
        is_short=jnp.asarray(False),
        local_score=local_score,
        symbol_rs=feats.relative_strength_vs_btc,
        symbol_trend=trend_score,  # local_features override (l.210-212)
        weights=p.weights,
        emit_threshold=1.0,
    )
    cs = evaluation.context_score

    # context required (l.220-221)
    has_context = context.valid

    # telemetry gates (l.229-234)
    telemetry_ok = (
        (cs.followthrough_score >= -0.2)
        & (cs.adverse_excursion_risk <= 0.6)
        & (cs.confidence >= 0.5)
    )

    # --- regime routing (l.108-155): autotrade verdict + reason, signal
    # still emitted when False.
    stable_breadth = _has_stable_breadth(context)
    micro = feats.micro_regime
    trans = feats.micro_transition
    bad_transition = (trans == MicroTransitionCode.BREAKDOWN) | (
        trans == MicroTransitionCode.VOLATILITY_EXPANSION
    )
    rs_ok = feats.relative_strength_vs_btc > p.min_rs_vs_btc

    route = jnp.full((S,), ROUTE_SYMBOL_RANGE, dtype=jnp.int32)

    def set_route(route, cond, code):
        return jnp.where((route == ROUTE_SYMBOL_RANGE) & cond, code, route)

    route = jnp.where(~has_context, ROUTE_NO_CONTEXT, route)
    route = set_route(route, context.regime_is_transitioning, ROUTE_TRANSITIONING)
    route = set_route(
        route, context.market_stress_score >= p.stress_threshold, ROUTE_STRESS
    )
    route = set_route(route, ~stable_breadth, ROUTE_BREADTH_UNSTABLE)
    route = set_route(
        route, context.market_regime != MarketRegimeCode.RANGE, ROUTE_NOT_RANGE
    )
    route = set_route(route, ~feats.valid | (micro < 0), ROUTE_NO_SYMBOL_FEATURES)
    route = set_route(route, bad_transition, ROUTE_SYMBOL_TRANSITION)
    route = set_route(route, ~rs_ok, ROUTE_RS_INSUFFICIENT)
    route = set_route(route, micro != MicroRegimeCode.RANGE, ROUTE_SYMBOL_REGIME)
    autotrade = route == ROUTE_SYMBOL_RANGE

    # --- entry cooldown on close_time (l.78-94)
    elapsed = f.close_time - last_signal_close_time
    cooldown_active = (
        (last_signal_close_time >= 0)
        & (elapsed >= 0)
        & (elapsed < p.entry_cooldown_bars * interval_s)
    )

    fired = entry & enough & has_context & telemetry_ok & ~cooldown_active & f.valid

    # quiet-hours suppression flips autotrade only (l.245-255)
    suppressed = autotrade & quiet_hours_suppressed
    autotrade = autotrade & ~quiet_hours_suppressed
    route = jnp.where(fired & suppressed, ROUTE_QUIET_HOURS, route)

    new_carry = jnp.where(fired, f.close_time, last_signal_close_time).astype(
        jnp.int32
    )
    outputs = StrategyOutputs(
        trigger=fired,
        direction=jnp.zeros((S,), dtype=jnp.int32),  # long-only
        score=jnp.where(fired, local_score, 0.0),
        autotrade=fired & autotrade,
        stop_loss_pct=jnp.zeros((S,), dtype=jnp.float32),
        diagnostics={
            "rsi": f.rsi,
            "macd": f.macd,
            "mfi": f.mfi,
            "adjusted_score": evaluation.adjusted_score,
            "confidence": cs.confidence,
            "followthrough": cs.followthrough_score,
            "risk": cs.adverse_excursion_risk,
            "breadth_stable": stable_breadth,
            "relative_strength_vs_btc": feats.relative_strength_vs_btc,
            "route": route,
            "quiet_hours_suppressed": suppressed,
        },
    )
    return outputs, new_carry
