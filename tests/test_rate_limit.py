"""Binance request-weight guard + parallel backfill (VERDICT r2 item 3).

Round 2 shipped the backoff helper with zero call sites; now the guard
lives inside ``BinanceApi._on_response`` (every response, mirroring the
reference's ``shared/utils.py:70-104``) and backfill fans out over a
bounded thread pool instead of one serial round trip at a time.
"""

import json
import threading
import time as _time

import pytest

import binquant_tpu.io.exchanges as exchanges
from binquant_tpu.io.exchanges import BinanceApi, make_history_fetcher
from binquant_tpu.io.replay import make_stub_engine


def _klines_rows(n=3, t0=1_753_000_200_000):
    rows = []
    for i in range(n):
        t = t0 + i * 900_000
        rows.append([t, "1", "1.1", "0.9", "1.05", "100", t + 899_999,
                     "105", 10, "50", "52", "0"])
    return rows


class HeaderSession:
    """Scripted weight headers; counts requests."""

    class _Resp:
        def __init__(self, payload, headers, status_code=200):
            self._payload = payload
            self.headers = headers
            self.status_code = status_code

        def json(self):
            return self._payload

        def raise_for_status(self):
            if self.status_code >= 400:
                raise RuntimeError(f"http {self.status_code}")

        @property
        def text(self):
            return json.dumps(self._payload)

    def __init__(self, weights):
        self.weights = list(weights)
        self.calls = 0

    def get(self, url, params=None):
        w = self.weights[min(self.calls, len(self.weights) - 1)]
        self.calls += 1
        return self._Resp(_klines_rows(), {"x-mbx-used-weight-1m": str(w)})


def test_backoff_engages_past_soft_cap(monkeypatch):
    sleeps = []
    monkeypatch.setattr(exchanges.time, "sleep", lambda s: sleeps.append(s))
    api = BinanceApi(session=HeaderSession([100, 900, 1050, 1100, 400]))
    for _ in range(5):
        api.get_ui_klines("BTCUSDT")
    # two responses crossed the 1000 soft cap -> two 60 s pauses
    assert sleeps == [60.0, 60.0]
    assert api.backoffs_engaged == 2


def test_backoff_quiet_under_soft_cap(monkeypatch):
    sleeps = []
    monkeypatch.setattr(exchanges.time, "sleep", lambda s: sleeps.append(s))
    api = BinanceApi(session=HeaderSession([100, 500, 999]))
    for _ in range(3):
        api.get_ui_klines("BTCUSDT")
    assert sleeps == []


def test_429_honors_retry_after_and_retries(monkeypatch):
    sleeps = []
    monkeypatch.setattr(exchanges.time, "sleep", lambda s: sleeps.append(s))

    class RateLimitedSession(HeaderSession):
        def get(self, url, params=None):
            self.calls += 1
            if self.calls == 1:
                return self._Resp({}, {"retry-after": "7"}, status_code=429)
            return self._Resp(
                _klines_rows(), {"x-mbx-used-weight-1m": "10"}
            )

    api = BinanceApi(session=RateLimitedSession([]))
    rows = api.get_ui_klines("BTCUSDT")
    assert len(rows) == 3
    assert 7.0 in sleeps  # honored Retry-After before the retry
    assert api.session.calls == 2


def test_backfill_runs_concurrently_and_loads_all():
    """8-way pool: with a 20 ms fetch latency, 16 symbols x 2 intervals
    serial would take >=640 ms; the pool must overlap them (observed
    in-flight concurrency > 1) and still load every bar."""
    engine = make_stub_engine(capacity=32, window=40)
    in_flight = {"now": 0, "max": 0}
    lock = threading.Lock()
    t0 = 1_753_000_200_000

    def fetch(symbol, interval_key):
        with lock:
            in_flight["now"] += 1
            in_flight["max"] = max(in_flight["max"], in_flight["now"])
        _time.sleep(0.02)
        with lock:
            in_flight["now"] -= 1
        step = 300_000 if interval_key == "5m" else 900_000
        return [
            {
                "symbol": symbol,
                "open_time": t0 + i * step,
                "close_time": t0 + (i + 1) * step - 1,
                "open": 1.0, "high": 1.1, "low": 0.9, "close": 1.05,
                "volume": 10.0, "quote_asset_volume": 10.5,
                "number_of_trades": 5, "taker_buy_base_volume": 5.0,
                "taker_buy_quote_volume": 5.2,
            }
            for i in range(4)
        ]

    symbols = [f"S{i:02d}USDT" for i in range(16)]
    loaded = engine.backfill(
        symbols, fetch, now_ms=t0 + 10 * 900_000, concurrency=8
    )
    assert loaded == (16 + 1) * 2 * 4  # +1: BTCUSDT is always seeded first
    assert in_flight["max"] > 1  # genuinely parallel
    assert in_flight["max"] <= 8  # and bounded


def test_backfill_through_binance_client_stays_weight_guarded(monkeypatch):
    """End-to-end: backfill over a BinanceApi whose session reports
    weights past the soft cap must engage the guard (the VERDICT item-3
    'under budget by construction' criterion)."""
    sleeps = []
    monkeypatch.setattr(exchanges.time, "sleep", lambda s: sleeps.append(s))
    engine = make_stub_engine(capacity=16, window=40)
    # weights ramp past the cap partway through the sweep
    weights = [100] * 6 + [1100] + [200] * 100
    api = BinanceApi(session=HeaderSession(weights))
    fetch = make_history_fetcher(api, "binance")
    engine.backfill(
        [f"S{i}USDT" for i in range(4)],
        fetch,
        now_ms=1_753_000_200_000 + 10 * 900_000,
        concurrency=2,
    )
    assert api.backoffs_engaged >= 1
    assert 60.0 in sleeps


def test_weight_header_parse_is_robust():
    api = BinanceApi(session=HeaderSession([0]))
    assert api.get_request_weight({}) == 0
    assert api.get_request_weight({"x-mbx-used-weight-1m": ""}) == 0
    assert api.get_request_weight(None) == 0
    assert api.get_request_weight({"x-mbx-used-weight-1m": "42"}) == 42
