#!/usr/bin/env python
"""Merge every checked-in BENCH_*.json into one ordered trajectory.

Each bench record is a point-in-time measurement of one lane; reading the
perf story across fourteen PRs means opening a dozen files. This tool
flattens every numeric scalar in every ``BENCH_*.json`` (and, with
``--include-multichip``, ``MULTICHIP_*.json``) into dotted metric paths
and merges them into ``BENCH_TRAJECTORY.json``::

    {
      "generated_epoch_s": ...,
      "git_sha": ...,
      "sources": ["BENCH_r01.json", ...],
      "metrics": {
        "tick_p99_ms": [
          {"epoch": 1721..., "value": 38.2, "source": "BENCH_r01.json",
           "git_sha": "..."},
          ...
        ],
        "detail.device.step_ms": [...],
        ...
      }
    }

Ordering: each record's ``measured_at_epoch_s`` stamp (bench.py stamps
every writer since ISSUE 15); records predating the stamp fall back to
the file's mtime, so the series still orders deterministically — rerun
the bench to upgrade a file to a real stamp. Boolean leaves are skipped;
numeric leaves inside lists use ``[i]`` path segments only for short
lists (<= 8) to keep sweep points addressable without exploding the
metric space.

    python tools/bench_trajectory.py            # repo root, writes the file
    python tools/bench_trajectory.py --dir X --dry-run

Gate mode turns the trajectory into a regression tripwire: each
repeatable ``--gate metric:direction:tolerance`` spec compares the
NEWEST point in that metric's merged series against the one before it
(direction ``up`` = bigger is better, ``down`` = smaller is better;
``tolerance`` is the allowed fractional slack). Fewer than two points
passes vacuously — a brand-new lane has no history to regress against.
Any tripped gate exits nonzero, so CI and ``make soak`` can refuse a
run whose headline numbers fell off the recorded trajectory::

    python tools/bench_trajectory.py --gate soak_candles_per_s:up:0.5
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

MAX_LIST = 8  # longer lists are measurement arrays, not named points


def _git_sha() -> str:
    import subprocess

    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
            ).stdout.strip()
            or "unknown"
        )
    except Exception:
        return "unknown"


def flatten(value, prefix: str = "") -> list[tuple[str, float]]:
    out: list[tuple[str, float]] = []
    if isinstance(value, bool):
        return out
    if isinstance(value, (int, float)):
        v = float(value)
        if v == v:  # NaN leaves carry no trajectory information
            out.append((prefix, v))
        return out
    if isinstance(value, dict):
        for k in sorted(value):
            # methodology stamps are provenance, not measurements — a
            # constant `measurement_epoch.epoch = 2` series per record
            # would only pollute the metric namespace
            if k in ("measurement_epoch", "measured_at_epoch_s"):
                continue
            key = f"{prefix}.{k}" if prefix else str(k)
            out.extend(flatten(value[k], key))
        return out
    if isinstance(value, list) and len(value) <= MAX_LIST:
        for i, item in enumerate(value):
            out.extend(flatten(item, f"{prefix}[{i}]"))
    return out


def record_epoch(record: dict, path: Path) -> int:
    """The stamp bench.py writes since ISSUE 15; mtime for older files."""
    stamp = record.get("measured_at_epoch_s")
    if isinstance(stamp, (int, float)) and stamp > 0:
        return int(stamp)
    return int(path.stat().st_mtime)


def build_trajectory(
    bench_dir: Path, include_multichip: bool = False
) -> dict:
    patterns = ["BENCH_*.json"]
    if include_multichip:
        patterns.append("MULTICHIP_*.json")
    files = sorted(
        p
        for pattern in patterns
        for p in bench_dir.glob(pattern)
        if p.name != "BENCH_TRAJECTORY.json"
    )
    metrics: dict[str, list[dict]] = {}
    sources: list[str] = []
    for path in files:
        try:
            record = json.loads(path.read_text())
        except (OSError, ValueError) as e:
            print(f"skipping unreadable {path.name}: {e}", file=sys.stderr)
            continue
        if not isinstance(record, dict):
            continue
        sources.append(path.name)
        epoch = record_epoch(record, path)
        sha = record.get("git_sha", "unknown")
        # the headline metric keeps its declared name; everything else
        # flattens under its structural path
        headline = record.get("metric")
        for key, value in flatten(record):
            name = (
                str(headline)
                if key == "value" and headline
                else key
            )
            metrics.setdefault(name, []).append(
                {
                    "epoch": epoch,
                    "value": value,
                    "source": path.name,
                    "git_sha": sha,
                }
            )
    for series in metrics.values():
        series.sort(key=lambda p: (p["epoch"], p["source"]))
    return {
        "generated_epoch_s": int(time.time()),
        "git_sha": _git_sha(),
        "sources": sources,
        "metrics": metrics,
    }


def parse_gate(spec: str) -> tuple[str, str, float]:
    """``metric:direction:tolerance`` → validated triple. The metric name
    may itself contain dots (flattened paths), so split from the right."""
    parts = spec.rsplit(":", 2)
    if len(parts) != 3:
        raise ValueError(
            f"gate spec {spec!r} is not metric:direction:tolerance"
        )
    metric, direction, tol_text = parts
    if direction not in ("up", "down"):
        raise ValueError(
            f"gate {spec!r}: direction must be up|down, got {direction!r}"
        )
    try:
        tolerance = float(tol_text)
    except ValueError:
        raise ValueError(f"gate {spec!r}: tolerance {tol_text!r} not a number")
    if tolerance < 0:
        raise ValueError(f"gate {spec!r}: tolerance must be >= 0")
    return metric, direction, tolerance


def check_gate(
    trajectory: dict, metric: str, direction: str, tolerance: float
) -> tuple[bool, str]:
    """Newest point vs the previous one. ``up`` regresses when the new
    value falls below ``prev * (1 - tolerance)``; ``down`` when it climbs
    above ``prev * (1 + tolerance)``. Returns (ok, human verdict line)."""
    series = trajectory["metrics"].get(metric)
    if not series:
        return True, f"gate {metric}: no series yet — vacuous pass"
    if len(series) < 2:
        return True, (
            f"gate {metric}: single point "
            f"({series[-1]['value']:g} from {series[-1]['source']}) — "
            "vacuous pass"
        )
    prev, new = series[-2], series[-1]
    if direction == "up":
        bound = prev["value"] * (1.0 - tolerance)
        ok = new["value"] >= bound
        rel = "fell below" if not ok else "holds"
    else:
        bound = prev["value"] * (1.0 + tolerance)
        ok = new["value"] <= bound
        rel = "climbed past" if not ok else "holds"
    return ok, (
        f"gate {metric} [{direction}, tol={tolerance:g}]: "
        f"{new['value']:g} ({new['source']}@{new['git_sha']}) vs "
        f"{prev['value']:g} ({prev['source']}@{prev['git_sha']}) — "
        f"{rel} bound {bound:g} → {'PASS' if ok else 'FAIL'}"
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--dir", default=".", help="directory holding BENCH_*.json"
    )
    parser.add_argument(
        "--include-multichip", action="store_true",
        help="also fold MULTICHIP_*.json dryrun records in",
    )
    parser.add_argument(
        "--dry-run", action="store_true",
        help="print the merged trajectory instead of writing the file",
    )
    parser.add_argument(
        "--metric", help="print just one metric's ordered series and exit"
    )
    parser.add_argument(
        "--gate", action="append", default=[], metavar="METRIC:DIR:TOL",
        help="repeatable regression gate metric:up|down:tolerance — "
        "compare the newest point against the previous one and exit "
        "nonzero past the fractional tolerance (<2 points passes)",
    )
    args = parser.parse_args(argv)

    try:
        gates = [parse_gate(spec) for spec in args.gate]
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    bench_dir = Path(args.dir)
    trajectory = build_trajectory(
        bench_dir, include_multichip=args.include_multichip
    )
    if not trajectory["sources"]:
        print(f"no BENCH_*.json under {bench_dir}", file=sys.stderr)
        return 1
    if args.metric:
        series = trajectory["metrics"].get(args.metric)
        if series is None:
            close = [
                m for m in sorted(trajectory["metrics"]) if args.metric in m
            ]
            print(
                f"unknown metric {args.metric!r}"
                + (f"; close: {', '.join(close[:10])}" if close else ""),
                file=sys.stderr,
            )
            return 2
        print(json.dumps({args.metric: series}, indent=1))
        return 0
    if gates:
        failed = 0
        for metric, direction, tolerance in gates:
            ok, line = check_gate(trajectory, metric, direction, tolerance)
            print(line)
            if not ok:
                failed += 1
        return 1 if failed else 0
    if args.dry_run:
        print(json.dumps(trajectory, indent=1))
        return 0
    out = bench_dir / "BENCH_TRAJECTORY.json"
    with open(out, "w") as f:
        json.dump(trajectory, f, indent=1, sort_keys=True)
        f.write("\n")
    n_points = sum(len(s) for s in trajectory["metrics"].values())
    print(
        f"wrote {out} — {len(trajectory['metrics'])} metrics, "
        f"{n_points} points from {len(trajectory['sources'])} records"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
