"""Subscription fan-out plane (ISSUE 14): registry + bitset compiler,
the one-dispatch device match kernel, and the WebSocket/SSE broadcast
tier behind the durable delivery boundary. See README §Fan-out plane."""

from binquant_tpu.fanout.hub import BroadcastOutbox, FanoutHub
from binquant_tpu.fanout.kernel import (
    DevicePlanes,
    pack_words_np,
    popcount_words,
    unpack_slots,
    unpack_words_np,
)
from binquant_tpu.fanout.plane import FanoutPlane, FanoutSink
from binquant_tpu.fanout.registry import (
    Subscription,
    SubscriptionRegistry,
)

__all__ = [
    "BroadcastOutbox",
    "DevicePlanes",
    "FanoutHub",
    "FanoutPlane",
    "FanoutSink",
    "Subscription",
    "SubscriptionRegistry",
    "pack_words_np",
    "popcount_words",
    "unpack_slots",
    "unpack_words_np",
]
