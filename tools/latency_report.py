#!/usr/bin/env python
"""Render a replay's latency observatory from its JSONL event log.

``run_replay`` emits one ``latency_summary`` event at end of run (the
engine's freshness + host-phase snapshots); every emitted ``signal``
event carries its ``freshness_ms`` stamp and every SLO violation a
``freshness_slo_breach`` event. This tool turns those back into the
"where do the milliseconds go / how stale are signals" tables without
any service in the loop:

    python tools/latency_report.py /tmp/bqt_latency_events.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def load_events(path: str | Path) -> list[dict]:
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def _percentiles(values: list[float]) -> tuple[float, float, float]:
    ordered = sorted(values)

    def at(q: float) -> float:
        if not ordered:
            return float("nan")
        idx = min(int(q * (len(ordered) - 1) + 0.5), len(ordered) - 1)
        return ordered[idx]

    return at(0.5), at(0.99), ordered[-1]


def render(events: list[dict]) -> str:
    lines: list[str] = []
    summaries = [e for e in events if e.get("event") == "latency_summary"]
    breaches = [e for e in events if e.get("event") == "freshness_slo_breach"]
    signals = [
        e
        for e in events
        if e.get("event") == "signal" and e.get("freshness_ms") is not None
    ]

    if summaries:
        summary = summaries[-1]
        freshness = summary.get("freshness") or {}
        lines.append("freshness")
        lines.append(
            f"  signals {freshness.get('signals', 0)}"
            f"  slo_ms {freshness.get('slo_ms', 0)}"
            f"  breaches {freshness.get('slo_breaches', 0)}"
        )
        for stage, ms in sorted((freshness.get("last_ms") or {}).items()):
            lines.append(f"  last {stage:<20} {ms:>10.3f}ms")
        host_phase = summary.get("host_phase") or {}
        phase_ms = host_phase.get("phase_ms") or {}
        if phase_ms:
            lines.append("")
            lines.append("host phases (total ms per drive)")
            for drive in sorted(phase_ms):
                row = phase_ms[drive]
                cells = "  ".join(
                    f"{p}={row[p]['total_ms']:.1f}"
                    for p in sorted(row)
                )
                lines.append(f"  {drive:<9} {cells}")
        occupancy = host_phase.get("occupancy") or {}
        if occupancy:
            lines.append("")
            lines.append(
                "occupancy (chunk wall = device_wait + host + dead_gap)"
            )
            for drive in sorted(occupancy):
                occ = occupancy[drive]
                lines.append(
                    f"  {drive:<9} wall={occ['wall_ms']:.1f}ms"
                    f" device_wait={occ['device_wait_ms']:.1f}ms"
                    f" host={occ['host_ms']:.1f}ms"
                    f" dead_gap={occ['dead_gap_ms']:.1f}ms"
                    f" attributed={occ.get('attributed_pct')}%"
                    f" chunks={occ['chunks']} ticks={occ['ticks']}"
                )
    else:
        lines.append("no latency_summary event (observatory knobs off?)")

    if signals:
        by_strategy: dict[str, list[float]] = {}
        for s in signals:
            by_strategy.setdefault(s["strategy"], []).append(
                float(s["freshness_ms"])
            )
        lines.append("")
        lines.append("per-signal close->emit freshness (ms)")
        for strategy in sorted(by_strategy):
            p50, p99, worst = _percentiles(by_strategy[strategy])
            lines.append(
                f"  {strategy:<28} n={len(by_strategy[strategy]):<4}"
                f" p50={p50:.1f} p99={p99:.1f} max={worst:.1f}"
            )

    if breaches:
        lines.append("")
        lines.append(f"SLO breaches ({len(breaches)})")
        for b in breaches[:10]:
            lines.append(
                f"  {b.get('strategy')}/{b.get('symbol')}"
                f" close_to_sink_ack={b.get('close_to_sink_ack_ms')}ms"
                f" slo={b.get('slo_ms')}ms tick_ms={b.get('tick_ms')}"
            )
        if len(breaches) > 10:
            lines.append(f"  ... {len(breaches) - 10} more")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("log", help="JSONL event log (BQT_EVENT_LOG file)")
    args = parser.parse_args(argv)
    events = load_events(args.log)
    if not events:
        print(f"no events in {args.log}", file=sys.stderr)
        return 1
    print(render(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
