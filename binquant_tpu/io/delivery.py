"""Durable signal delivery plane: crash-safe at-least-once outbox (ISSUE 13).

Emission used to be three hard-coded fire-and-forget sinks riding the tick
thread (``SignalEngine._finalize_tick``): a sink 5xx storm or a process
crash between wire fetch and POST silently lost signals, and a slow sink
held the event loop. This module is the durable boundary ROADMAP item 2
names: finalize *enqueues* and returns, per-sink async workers own the
sink round trips, and the autotrade class survives a process kill.

Three cooperating pieces:

* :class:`DeliveryWal` — an append-only JSONL write-ahead log keyed by
  ``(trace_id, tick_seq, strategy, symbol)`` × sink: a ``put`` record is
  written BEFORE the in-memory enqueue, an ``ack`` record after the sink
  confirmed, and compaction rewrites the file keeping only unacked puts
  (atomic tmp-file + ``os.replace``). On restart :meth:`DeliveryWal.unacked`
  is exactly the set of signals the previous process accepted but never
  delivered — the plane replays them (at-least-once; the ``entry_id`` is
  stamped into the payload's ``metadata["delivery_id"]`` before the WAL
  put, so it travels with every redelivery — even for ticks trace
  sampling skipped — and downstream consumers dedupe on it, the PR-3
  trace_id/tick_seq provenance identity).

* :class:`CircuitBreaker` — per sink: ``closed`` → ``open`` after
  ``threshold`` consecutive failures (every transition is a
  ``delivery_breaker`` event + ``bqt_delivery_breaker_transitions_total``),
  ``open`` → ``half_open`` after the cooldown (ONE probe attempt),
  ``half_open`` → ``closed`` on probe success / back to ``open`` on
  failure. While open, lossy sinks shed immediately and at-least-once
  entries wait (they are already WAL-durable).

* :class:`DeliveryPlane` — per-sink bounded queues + one worker each.
  Per-sink-class policy (:class:`SignalSink.policy <binquant_tpu.io.emission.SignalSink>`):

  - ``at_least_once`` (autotrade): never dropped. WAL-put before enqueue;
    unbounded retries with exponential backoff + jitter (the PR-10
    ``reconnect_delay`` idiom) behind the breaker; a full queue defers the
    entry to the WAL (the worker sweeps unacked entries back in whenever
    its queue runs dry) — bounded memory, unbounded durability.
  - ``lossy`` (telegram, analytics): bounded effort. A full queue, an open
    breaker, or ``retry_max`` exhausted attempts shed the entry with an
    explicit reason (``bqt_delivery_shed_total{sink,reason}``) — under
    pressure the trade path stays fresh and the loss is *counted*, never
    silent.

Delivery acks close the ISSUE-11 freshness loop: when the observatory is
on, ``bqt_sink_delivery_ms{sink}`` now measures candle close →
*acked-through-the-queue* (enqueue lag + queue dwell + sink round trip),
not just the inline call returning.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import (
    DELIVERY_ACKED,
    DELIVERY_BREAKER,
    DELIVERY_BREAKER_STATE,
    DELIVERY_CURSOR_LAG,
    DELIVERY_ENQUEUED,
    DELIVERY_OLDEST_AGE,
    DELIVERY_QUEUE,
    DELIVERY_RETRIES,
    DELIVERY_SHED,
    DELIVERY_WAL_REPLAYED,
    DELIVERY_WAL_UNACKED,
    SINK_DELIVERY,
)

log = logging.getLogger(__name__)

AT_LEAST_ONCE = "at_least_once"
LOSSY = "lossy"


def entry_id_of(
    trace_id: str | None,
    tick_seq: int | None,
    strategy: str,
    symbol: str,
    tick_ms: int | None = None,
) -> str:
    """The delivery-dedupe identity of one fired signal: the PR-3
    trace_id/tick_seq provenance stamps plus the (strategy, symbol) pair
    (one traced tick can fire many pairs). When tracing is sampled off
    the tick's evaluated wall-clock stands in for the trace id — still
    unique per (tick, strategy, symbol), which is all the dedupe needs."""
    tid = trace_id if trace_id else f"t{int(tick_ms or 0)}"
    seq = int(tick_seq) if tick_seq is not None else 0
    return f"{tid}/{seq}/{strategy}/{symbol}"


# -- write-ahead log ----------------------------------------------------------


class DeliveryWal:
    """Append-only JSONL outbox for the at-least-once sink class.

    Records: ``{"op": "put", "id": ..., "sink": ..., "ts_ms": ...,
    "payload": ...}`` and ``{"op": "ack", "id": ..., "sink": ...}``.
    Writes are flushed + fsynced per record — signals are low-rate (a few
    per tick at most) and the whole point is surviving a kill between
    sink call and ack. A torn trailing line (killed mid-write) is skipped
    by the reader, never fatal.
    """

    def __init__(
        self, path: str | Path, fsync: bool = True, compact_every: int = 256
    ) -> None:
        self.path = Path(path)
        self.fsync = bool(fsync)
        self.compact_every = max(int(compact_every), 0)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # live unacked view, seeded from whatever the previous process
        # left behind — ``puts - acks`` process counters can't express a
        # boot backlog (replayed acks have no in-process puts)
        puts, acked = self._scan()
        self._unacked_keys: set[tuple[str, str]] = {
            key for key in puts if key not in acked
        }
        # per-unacked-key put wall clock (epoch ms) for the oldest-record
        # -age watermark; pre-observatory records without a `wall` field
        # fall back to boot time — a conservative LOWER bound on age
        boot_wall_ms = time.time() * 1000.0
        self._put_wall_ms: dict[tuple[str, str], float] = {
            key: float(puts[key].get("wall") or boot_wall_ms)
            for key in self._unacked_keys
        }
        self._f = open(self.path, "a", encoding="utf-8")
        self._acks_since_compact = 0
        self.puts = 0
        self.acks = 0
        self.compactions = 0
        # acks for keys not currently unacked — the zero-duplicate
        # invariant's meter (a worker acking the same entry twice)
        self.dup_acks = 0

    def _append(self, record: dict) -> None:
        self._f.write(json.dumps(record, separators=(",", ":")) + "\n")
        self._f.flush()
        if self.fsync:
            try:
                os.fsync(self._f.fileno())
            except OSError:  # pragma: no cover - exotic filesystems
                pass

    def append_put(
        self,
        entry_id: str,
        sink: str,
        payload: Any,
        ts_ms: int | None = None,
        lag0_ms: float | None = None,
        trace_id: str | None = None,
    ) -> None:
        """``lag0_ms``/``trace_id`` are the ISSUE-16 provenance stamps
        riding the existing put record (additive fields — a pre-16 WAL
        replays fine): the candle-close lag at enqueue and the
        originating tick's trace id, plus the put's own wall clock so a
        replayed ack can report the true cross-process close→ack lag."""
        self.puts += 1
        key = (entry_id, sink)
        self._unacked_keys.add(key)
        wall = time.time() * 1000.0
        self._put_wall_ms[key] = wall
        record = {
            "op": "put",
            "id": entry_id,
            "sink": sink,
            "ts_ms": ts_ms,
            "payload": payload,
            "wall": round(wall, 3),
        }
        if lag0_ms is not None:
            record["lag0"] = round(float(lag0_ms), 3)
        if trace_id is not None:
            record["trace"] = trace_id
        self._append(record)

    def append_ack(self, entry_id: str, sink: str) -> None:
        self.acks += 1
        key = (entry_id, sink)
        if key not in self._unacked_keys:
            self.dup_acks += 1
        self._unacked_keys.discard(key)
        self._put_wall_ms.pop(key, None)
        self._append({"op": "ack", "id": entry_id, "sink": sink})
        self._acks_since_compact += 1
        if self.compact_every and self._acks_since_compact >= self.compact_every:
            self.compact()

    def oldest_put_wall_ms(self, sink: str) -> float | None:
        """Put wall clock of the sink's oldest unacked record (the
        oldest-record-age watermark's anchor); None when fully acked."""
        walls = [
            wall
            for (_, s), wall in self._put_wall_ms.items()
            if s == sink
        ]
        return min(walls) if walls else None

    def unacked_count(self, sink: str | None = None) -> int:
        """Live unacked-entry count (boot backlog included) — what the
        ``bqt_delivery_wal_unacked`` gauge and /healthz report; sustained
        growth means the sink is down."""
        if sink is None:
            return len(self._unacked_keys)
        return sum(1 for _, s in self._unacked_keys if s == sink)

    def _scan(self) -> tuple[dict[tuple[str, str], dict], set[tuple[str, str]]]:
        """(puts by (id, sink) in file order, acked (id, sink) keys)."""
        puts: dict[tuple[str, str], dict] = {}
        acked: set[tuple[str, str]] = set()
        if not self.path.exists():
            return puts, acked
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn trailing line from a mid-write kill
                key = (str(rec.get("id")), str(rec.get("sink")))
                if rec.get("op") == "put":
                    puts[key] = rec
                elif rec.get("op") == "ack":
                    acked.add(key)
        return puts, acked

    def unacked(self) -> list[dict]:
        """Every put without a matching ack, in append order."""
        puts, acked = self._scan()
        return [rec for key, rec in puts.items() if key not in acked]

    def compact(self) -> int:
        """Rewrite the file keeping only unacked puts (atomic replace);
        returns the surviving entry count."""
        pending = self.unacked()
        tmp = self.path.with_suffix(self.path.suffix + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in pending:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:  # pragma: no cover
                pass
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")
        self._acks_since_compact = 0
        self.compactions += 1
        return len(pending)

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # pragma: no cover
            pass


# -- circuit breaker ----------------------------------------------------------


class CircuitBreaker:
    """closed → open (threshold consecutive failures) → half_open (one
    probe after the cooldown) → closed on probe success / open on probe
    failure. Transitions land in the event log + metric family, and in
    ``self.transitions`` for scripted-drill assertions."""

    def __init__(
        self,
        sink: str,
        threshold: int = 5,
        cooldown_s: float = 30.0,
        clock=time.monotonic,
    ) -> None:
        self.sink = sink
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.state = "closed"
        self.consecutive = 0
        self._opened_at: float | None = None
        self.transitions: list[str] = []

    #: gauge encoding for bqt_delivery_breaker_state (level companion to
    #: the transitions counter; alert on >0, not on edges)
    STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}

    def _transition(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        self.transitions.append(state)
        DELIVERY_BREAKER.labels(sink=self.sink, state=state).inc()
        DELIVERY_BREAKER_STATE.labels(sink=self.sink).set(
            self.STATE_CODES.get(state, 0)
        )
        get_event_log().emit(
            "delivery_breaker",
            sink=self.sink,
            state=state,
            consecutive_failures=self.consecutive,
        )

    def allow(self) -> bool:
        """May an attempt run now? An open breaker past its cooldown
        transitions to half_open and admits ONE probe."""
        if self.state == "closed":
            return True
        if self.state == "half_open":
            # one probe is already in flight (the caller that flipped us)
            return False
        if (
            self._opened_at is not None
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._transition("half_open")
            return True
        return False

    def cooldown_remaining(self) -> float:
        if self.state != "open" or self._opened_at is None:
            return 0.0
        return max(self.cooldown_s - (self._clock() - self._opened_at), 0.0)

    def record_success(self) -> None:
        self.consecutive = 0
        if self.state != "closed":
            self._transition("closed")
        self._opened_at = None

    def record_failure(self) -> None:
        self.consecutive += 1
        if self.state == "half_open" or (
            self.state == "closed" and self.consecutive >= self.threshold
        ):
            self._opened_at = self._clock()
            self._transition("open")


# -- the plane ---------------------------------------------------------------


@dataclass
class Envelope:
    """One (signal, sink) delivery unit riding a queue."""

    entry_id: str
    sink: str
    payload: Any
    ts_ms: int | None = None
    attempts: int = 0
    replayed: bool = False  # came back off the WAL (restart / deferral)
    # freshness anchors (live enqueues only): candle-close lag at dispatch
    # plus the dispatch perf_counter — the ack computes close→acked from
    # them. Replayed entries restore lag0_ms + put_wall_ms off the WAL
    # record instead (wall-clock delta since the put — the true
    # cross-process lag), leaving dispatched_at None.
    lag0_ms: float | None = None
    dispatched_at: float | None = None
    # ISSUE-16 provenance: the originating tick's trace id (sink spans +
    # WAL record) and the WAL put's wall clock (replayed-lag anchor)
    trace_id: str | None = None
    put_wall_ms: float | None = None


@dataclass
class _SinkLane:
    sink: Any  # SignalSink
    queue: asyncio.Queue
    breaker: CircuitBreaker
    worker: asyncio.Task | None = None
    inflight: int = 0
    deferred: int = 0  # at-least-once entries parked WAL-only (queue full)
    enqueued: int = 0
    acked: int = 0
    retries: int = 0
    replayed: int = 0
    shed: dict[str, int] = field(default_factory=dict)


class DeliveryPlane:
    """Per-sink outbox: finalize enqueues, workers deliver, acks close the
    loop. See the module docstring for the policy table."""

    def __init__(
        self,
        sinks: list[Any],
        wal_path: str | Path | None = None,
        queue_max: int = 512,
        attempt_timeout_s: float = 5.0,
        retry_max: int = 3,
        backoff_s: float = 0.25,
        backoff_max_s: float = 30.0,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 30.0,
        wal_fsync: bool = True,
        wal_compact_every: int = 256,
        rng: random.Random | None = None,
        freshness: Any | None = None,
        health: Any | None = None,
    ) -> None:
        self.queue_max = max(int(queue_max), 1)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.retry_max = max(int(retry_max), 1)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._rng = rng or random.Random()
        self.freshness = freshness
        # obs/delivery_health.py collector (ISSUE 16): the ack-side
        # close→ack lag consumer + per-attempt sink-span gate
        self.health = health
        self.wal: DeliveryWal | None = (
            DeliveryWal(
                wal_path, fsync=wal_fsync, compact_every=wal_compact_every
            )
            if wal_path
            else None
        )
        self._lanes: dict[str, _SinkLane] = {}
        for sink in sinks:
            self._lanes[sink.name] = _SinkLane(
                sink=sink,
                queue=asyncio.Queue(maxsize=self.queue_max),
                breaker=CircuitBreaker(
                    sink.name,
                    threshold=breaker_threshold,
                    cooldown_s=breaker_cooldown_s,
                ),
            )
        self.started = False
        self.closed = False
        self.wal_replayed = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start the per-sink workers (requires a running loop) and replay
        any unacked WAL entries the previous process left behind.
        Idempotent; ``enqueue_fired`` calls it lazily."""
        if self.started or self.closed:
            return
        self.started = True
        loop = asyncio.get_running_loop()
        for lane in self._lanes.values():
            lane.worker = loop.create_task(
                self._worker(lane), name=f"delivery-{lane.sink.name}"
            )
        self._replay_wal()

    @staticmethod
    def _decode_wal_record(lane: _SinkLane, rec: dict) -> Envelope | None:
        """WAL record → replay Envelope (shared by the boot replay and
        the deferred sweep); undecodable entries are logged and skipped —
        the replay semantics live in exactly one place."""
        try:
            payload = lane.sink.from_wal(rec.get("payload"))
        except Exception:
            log.exception(
                "WAL replay: undecodable %s entry %s; skipping",
                rec.get("sink"),
                rec.get("id"),
            )
            return None
        return Envelope(
            entry_id=str(rec.get("id")),
            sink=lane.sink.name,
            payload=payload,
            ts_ms=rec.get("ts_ms"),
            replayed=True,
            # ISSUE-16 anchors riding the put record: lag-at-enqueue +
            # the put's wall clock let the ack report the TRUE
            # cross-process close→ack lag; absent on pre-16 records
            lag0_ms=rec.get("lag0"),
            put_wall_ms=rec.get("wall"),
            trace_id=rec.get("trace"),
        )

    def _replay_wal(self) -> None:
        if self.wal is None:
            return
        pending = self.wal.unacked()
        if not pending:
            return
        replayed = 0
        for rec in pending:
            lane = self._lanes.get(rec.get("sink", ""))
            if lane is None:
                continue
            env = self._decode_wal_record(lane, rec)
            if env is None:
                continue
            # WAL backlog can exceed the queue bound; the overflow stays
            # deferred (the worker sweeps it back in as the queue drains)
            try:
                lane.queue.put_nowait(env)
            except asyncio.QueueFull:
                lane.deferred += 1
            lane.replayed += 1
            replayed += 1
            DELIVERY_WAL_REPLAYED.labels(sink=lane.sink.name).inc()
        self.wal_replayed = replayed
        if replayed:
            get_event_log().emit(
                "delivery_wal_replay",
                entries=replayed,
                by_sink={
                    n: lane.replayed for n, lane in self._lanes.items()
                    if lane.replayed
                },
            )
            log.info(
                "delivery WAL replay: %d unacked entr%s re-enqueued",
                replayed,
                "y" if replayed == 1 else "ies",
            )

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait until every lane is idle (queue empty, nothing in flight,
        nothing deferred) or the timeout passes; True when fully drained.
        An at-least-once lane mid-outage may never drain — the caller gets
        False and the WAL keeps the entries."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            if all(
                lane.queue.empty() and lane.inflight == 0 and lane.deferred == 0
                for lane in self._lanes.values()
            ):
                return True
            await asyncio.sleep(0.01)
        return False

    async def aclose(self, drain_s: float = 5.0) -> None:
        """Best-effort drain, then stop workers and compact the WAL.
        Undelivered at-least-once entries stay durable for the next boot."""
        if self.closed:
            return
        if self.started:
            await self.drain(timeout_s=drain_s)
        self.closed = True
        for lane in self._lanes.values():
            if lane.worker is not None:
                lane.worker.cancel()
        for lane in self._lanes.values():
            worker = lane.worker
            if worker is None:
                continue
            # Python 3.10's wait_for can SWALLOW a cancellation that lands
            # while the inner deliver is already done (bpo-42130): the
            # worker resumes as if the attempt succeeded and parks back on
            # queue.get never having observed the cancel — a bare `await
            # worker` here then deadlocks the closing task (seen live as a
            # replay-drive hang whenever drain timed out with a worker
            # mid-attempt). Re-cancel on a short timeout until the task
            # actually exits; the loop-top `closed` check in _worker makes
            # the recheck (or the second cancel) land immediately.
            for _ in range(25):
                done, _pending = await asyncio.wait({worker}, timeout=0.2)
                if done:
                    try:
                        await worker
                    except (asyncio.CancelledError, Exception):
                        pass
                    break
                worker.cancel()
            else:
                log.warning(
                    "delivery worker %s ignored shutdown; abandoned",
                    lane.sink.name,
                )
        self.emit_summary()
        if self.wal is not None:
            try:
                self.wal.compact()
            finally:
                self.wal.close()

    def emit_summary(self) -> None:
        """One ``delivery_summary`` event with the per-sink scoreboard —
        what tools/delivery_report.py renders after a drill/replay."""
        get_event_log().emit("delivery_summary", sinks=self._sink_counts())

    # -- enqueue (the tick thread's entire cost) ------------------------------

    def enqueue_fired(
        self,
        signal: Any,
        tick_ms: int | None = None,
        lag0_ms: float | None = None,
        dispatched_at: float | None = None,
    ) -> None:
        """Fan one FiredSignal out to every sink's queue — O(sinks) dict
        ops + one WAL append per durable sink; never blocks, never raises
        into the tick thread."""
        if not self.started:
            self.start()
        # delivery-health fallback anchors: with freshness OFF no caller
        # stamps lag0/dispatched_at, so the lag histogram would stay
        # empty — anchor at the enqueue instead (lag measures
        # enqueue→ack; documented in README §Delivery observatory)
        if (
            lag0_ms is None
            and self.health is not None
            and getattr(self.health, "enabled", False)
        ):
            lag0_ms = 0.0
        if dispatched_at is None and lag0_ms is not None:
            dispatched_at = time.perf_counter()
        for lane in self._lanes.values():
            try:
                payload = lane.sink.encode(signal)
            except Exception:
                log.exception(
                    "sink %s payload encode failed for %s/%s; dropping",
                    lane.sink.name,
                    getattr(signal, "strategy", "?"),
                    getattr(signal, "symbol", "?"),
                )
                self._shed(lane, "encode_error")
                continue
            entry_id = entry_id_of(
                getattr(signal, "trace_id", None),
                getattr(signal, "tick_seq", None),
                getattr(signal, "strategy", "?"),
                getattr(signal, "symbol", "?"),
                tick_ms=tick_ms,
            )
            stamp = getattr(lane.sink, "stamp", None)
            if lane.sink.policy == AT_LEAST_ONCE and stamp is not None:
                # stamped BEFORE the WAL put so the identity rides the
                # payload into the WAL and out again on replay — the
                # downstream dedupe key even when trace sampling left the
                # payload without trace_id/tick_seq metadata
                try:
                    stamp(payload, entry_id)
                except Exception:  # pragma: no cover
                    log.exception(
                        "sink %s payload stamp failed for %s",
                        lane.sink.name,
                        entry_id,
                    )
            env = Envelope(
                entry_id=entry_id,
                sink=lane.sink.name,
                payload=payload,
                ts_ms=tick_ms,
                lag0_ms=lag0_ms,
                dispatched_at=dispatched_at,
                trace_id=getattr(signal, "trace_id", None),
            )
            self.enqueue(env)

    def enqueue(self, env: Envelope) -> None:
        lane = self._lanes[env.sink]
        durable = lane.sink.policy == AT_LEAST_ONCE
        if durable and self.wal is not None and not env.replayed:
            # durability FIRST: once the put is on disk the signal cannot
            # be lost to a crash, full queue, or slow sink
            self.wal.append_put(
                env.entry_id,
                env.sink,
                lane.sink.to_wal(env.payload),
                ts_ms=env.ts_ms,
                lag0_ms=env.lag0_ms,
                trace_id=env.trace_id,
            )
            # the gauge must move on PUTS too: during an outage acks stop
            # but backlog keeps growing — that growth IS the signal
            DELIVERY_WAL_UNACKED.labels(sink=env.sink).set(
                self.wal.unacked_count(env.sink)
            )
        lane.enqueued += 1
        DELIVERY_ENQUEUED.labels(sink=env.sink).inc()
        try:
            lane.queue.put_nowait(env)
        except asyncio.QueueFull:
            if durable and self.wal is not None:
                # bounded backpressure, unbounded durability: the entry is
                # already WAL-resident; the worker sweeps it back in when
                # its queue runs dry
                lane.deferred += 1
            else:
                # no WAL behind this lane (durability disabled) — the
                # bound still holds, the loss is counted
                self._shed(lane, "queue_full")
        DELIVERY_QUEUE.labels(sink=env.sink).set(lane.queue.qsize())

    def _shed(self, lane: _SinkLane, reason: str) -> None:
        lane.shed[reason] = lane.shed.get(reason, 0) + 1
        DELIVERY_SHED.labels(sink=lane.sink.name, reason=reason).inc()
        get_event_log().emit(
            "delivery_shed", sink=lane.sink.name, reason=reason
        )

    # -- workers -------------------------------------------------------------

    def _sweep_deferred(self, lane: _SinkLane) -> bool:
        """Move WAL-deferred entries back into a drained queue; True when
        anything was recovered."""
        if lane.deferred <= 0 or self.wal is None:
            return False
        consumed = 0
        moved = 0
        for rec in self.wal.unacked():
            if rec.get("sink") != lane.sink.name or lane.queue.full():
                continue
            env = self._decode_wal_record(lane, rec)
            # an undecodable entry still consumes its deferral slot —
            # otherwise it re-fails every sweep and drain() never settles
            consumed += 1
            if env is None:
                continue
            lane.queue.put_nowait(env)
            moved += 1
        lane.deferred = max(lane.deferred - consumed, 0)
        return moved > 0

    async def _worker(self, lane: _SinkLane) -> None:
        # loop-top closed check: a worker whose shutdown cancel was
        # swallowed by 3.10's wait_for (see aclose) exits here instead of
        # parking on an empty queue forever
        while not self.closed:
            try:
                env = lane.queue.get_nowait()
            except asyncio.QueueEmpty:
                if self._sweep_deferred(lane):
                    continue
                env = await lane.queue.get()
            lane.inflight += 1
            try:
                await self._deliver(lane, env)
            except asyncio.CancelledError:
                raise
            except Exception:  # a bug in _deliver must not kill the lane
                log.exception(
                    "delivery worker error for sink %s entry %s",
                    lane.sink.name,
                    env.entry_id,
                )
                # the envelope must not vanish either: at-least-once goes
                # back in the queue (it is already WAL-resident — a full
                # queue just defers it to the sweep), lossy is a counted
                # shed; the sleep keeps a deterministically-raising bug
                # from hot-looping the lane
                if lane.sink.policy == AT_LEAST_ONCE:
                    try:
                        lane.queue.put_nowait(env)
                    except asyncio.QueueFull:
                        lane.deferred += 1
                else:
                    self._shed(lane, "worker_error")
                await asyncio.sleep(max(self.backoff_s, 0.05))
            finally:
                lane.inflight -= 1
                DELIVERY_QUEUE.labels(sink=lane.sink.name).set(
                    lane.queue.qsize()
                )

    async def _deliver(self, lane: _SinkLane, env: Envelope) -> None:
        durable = lane.sink.policy == AT_LEAST_ONCE
        backoff = self.backoff_s
        while True:
            if not lane.breaker.allow():
                if not durable:
                    self._shed(lane, "breaker_open")
                    return
                # at-least-once rides out the open window (WAL-durable);
                # wake at least once a second so a scripted clock or a
                # short cooldown is honored promptly
                await asyncio.sleep(
                    min(max(lane.breaker.cooldown_remaining(), 0.01), 1.0)
                )
                continue
            t_attempt = time.perf_counter()
            try:
                await asyncio.wait_for(
                    lane.sink.deliver(env.payload),
                    timeout=self.attempt_timeout_s,
                )
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                env.attempts += 1
                lane.retries += 1
                lane.breaker.record_failure()
                DELIVERY_RETRIES.labels(sink=lane.sink.name).inc()
                self._sink_span(
                    lane, env, t_attempt, env.attempts, type(exc).__name__
                )
                if not durable and env.attempts >= self.retry_max:
                    self._shed(lane, "retries_exhausted")
                    log.warning(
                        "sink %s shed %s after %d attempts: %s",
                        lane.sink.name,
                        env.entry_id,
                        env.attempts,
                        exc,
                    )
                    return
                # PR-10 reconnect_delay idiom: exponential with ±jitter so
                # a herd of retrying workers doesn't re-storm the sink
                from binquant_tpu.io.websocket import reconnect_delay

                await asyncio.sleep(reconnect_delay(backoff, self._rng))
                backoff = min(backoff * 2.0, self.backoff_max_s)
                continue
            lane.breaker.record_success()
            self._sink_span(lane, env, t_attempt, env.attempts + 1, "ok")
            self._ack(lane, env)
            return

    def _sink_span(
        self,
        lane: _SinkLane,
        env: Envelope,
        t0: float,
        attempt: int,
        outcome: str,
    ) -> None:
        """One per-attempt sink span, joined to the originating tick by
        the trace_id riding the envelope/WAL record (ISSUE 16 satellite).
        The tick's trace completed at emit — its span tree is already in
        the log — so these are standalone events tools/trace_report.py
        grafts onto the matching waterfall, extending it past enqueue to
        the ack. Gated like the lag accounting (health on + a trace id);
        the event log never raises."""
        if (
            self.health is None
            or not getattr(self.health, "enabled", False)
            or not env.trace_id
        ):
            return
        get_event_log().emit(
            "sink_span",
            trace_id=env.trace_id,
            sink=lane.sink.name,
            attempt=int(attempt),
            ms=round((time.perf_counter() - t0) * 1000.0, 3),
            outcome=outcome,
            entry_id=env.entry_id,
            replayed=env.replayed,
        )

    def _lag_ms(self, env: Envelope) -> float | None:
        """End-to-end close→ack lag of one confirmed delivery. Live
        entries: candle-close lag at dispatch + the monotonic dwell since.
        Replayed entries: lag-at-put + the WALL-clock delta since the put
        (the delta spans the process kill — exactly the lag a consumer
        experienced). None when no anchors rode the envelope."""
        if env.replayed:
            if env.lag0_ms is None or env.put_wall_ms is None:
                return None
            return float(env.lag0_ms) + max(
                time.time() * 1000.0 - float(env.put_wall_ms), 0.0
            )
        if env.dispatched_at is None or env.lag0_ms is None:
            return None
        return (
            float(env.lag0_ms)
            + (time.perf_counter() - env.dispatched_at) * 1000.0
        )

    def _ack(self, lane: _SinkLane, env: Envelope) -> None:
        lane.acked += 1
        DELIVERY_ACKED.labels(sink=lane.sink.name).inc()
        if lane.sink.policy == AT_LEAST_ONCE and self.wal is not None:
            self.wal.append_ack(env.entry_id, env.sink)
            DELIVERY_WAL_UNACKED.labels(sink=lane.sink.name).set(
                self.wal.unacked_count(lane.sink.name)
            )
        try:
            get_event_log().emit(
                "delivery_ack",
                sink=lane.sink.name,
                id=env.entry_id,
                attempts=env.attempts + 1,
                replayed=env.replayed,
            )
            # ISSUE-11 loop closure: close→acked-through-the-queue.
            # Replayed entries predate this process's clock anchors — no
            # stamp here (the ISSUE-16 lag histogram below covers them
            # through the WAL wall-clock anchor instead).
            if (
                self.freshness is not None
                and getattr(self.freshness, "enabled", False)
                and env.dispatched_at is not None
                and env.lag0_ms is not None
            ):
                SINK_DELIVERY.labels(sink=lane.sink.name).observe(
                    env.lag0_ms
                    + (time.perf_counter() - env.dispatched_at) * 1000.0
                )
            # ISSUE-16: the ack-side close→ack lag (to the FINAL
            # successful ack — this runs once per envelope, after every
            # retry) feeding bqt_delivery_lag_ms + the delivery SLO
            if self.health is not None and getattr(
                self.health, "enabled", False
            ):
                lag_ms = self._lag_ms(env)
                if lag_ms is not None:
                    self.health.on_ack(
                        lane.sink.name,
                        lag_ms,
                        attempts=env.attempts + 1,
                        replayed=env.replayed,
                    )
        except Exception:  # pragma: no cover - observability-side failure
            # the sink confirmed and the WAL ack landed — a failing event
            # log or histogram must not turn a delivered entry into a
            # worker error (which would redeliver it)
            log.exception(
                "delivery ack observability failed for %s", env.entry_id
            )

    # -- introspection --------------------------------------------------------

    def _sink_counts(self) -> dict[str, dict]:
        return {
            name: {
                "policy": lane.sink.policy,
                "enqueued": lane.enqueued,
                "acked": lane.acked,
                "retries": lane.retries,
                "shed": dict(lane.shed),
                "deferred": lane.deferred,
                "wal_replayed": lane.replayed,
                "breaker": lane.breaker.state,
                "breaker_transitions": list(lane.breaker.transitions),
                "queue_depth": lane.queue.qsize(),
                "inflight": lane.inflight,
            }
            for name, lane in self._lanes.items()
        }

    def breaker(self, sink: str) -> CircuitBreaker:
        return self._lanes[sink].breaker

    def lane(self, sink: str) -> _SinkLane:
        return self._lanes[sink]

    def watermarks(self) -> dict:
        """Outbox watermarks per consumer group (ISSUE 16): records
        behind head (queued + inflight + WAL-deferred, i.e. accepted but
        not yet acked in-process) and the oldest unacked WAL record's
        age. Refreshes the bqt_delivery_cursor_lag /
        bqt_delivery_oldest_unacked_ms gauges on read (snapshot-driven —
        the watermarks are levels, not edges)."""
        now_wall_ms = time.time() * 1000.0
        groups: dict[str, dict] = {}
        for name, lane in self._lanes.items():
            cursor_lag = lane.queue.qsize() + lane.inflight + lane.deferred
            DELIVERY_CURSOR_LAG.labels(group=name).set(cursor_lag)
            cell: dict[str, Any] = {
                "cursor_lag": cursor_lag,
                "queue_depth": lane.queue.qsize(),
                "inflight": lane.inflight,
                "deferred": lane.deferred,
            }
            if (
                self.wal is not None
                and lane.sink.policy == AT_LEAST_ONCE
            ):
                oldest = self.wal.oldest_put_wall_ms(name)
                age_ms = (
                    max(now_wall_ms - oldest, 0.0)
                    if oldest is not None
                    else 0.0
                )
                DELIVERY_OLDEST_AGE.labels(sink=name).set(round(age_ms, 3))
                cell["oldest_unacked_ms"] = round(age_ms, 3)
            groups[name] = cell
        return groups

    # -- SLO-plane invariant probes (ISSUE 16) -------------------------------

    def zero_loss_invariant(self) -> dict:
        """PR 13 contract: the at-least-once class NEVER sheds (only
        lossy lanes may drop under pressure)."""
        shed = {
            name: dict(lane.shed)
            for name, lane in self._lanes.items()
            if lane.sink.policy == AT_LEAST_ONCE and lane.shed
        }
        return {"ok": not shed, "durable_sheds": shed}

    def zero_duplicate_invariant(self) -> dict:
        """PR 13 contract: no entry acks twice (sink-side idempotency
        keys make redelivery safe, but a double ack in-process would mean
        the outbox double-delivered). No WAL → vacuously true."""
        dups = self.wal.dup_acks if self.wal is not None else 0
        return {"ok": dups == 0, "dup_acks": dups}

    def breakers_closed_invariant(self) -> dict:
        """An open (or half-open) breaker means a sink is DOWN — the
        verdict must not read green while one is tripped, even if every
        SLO window has since washed clean."""
        open_ = {
            name: lane.breaker.state
            for name, lane in self._lanes.items()
            if lane.breaker.state != "closed"
        }
        return {"ok": not open_, "open": open_}

    def snapshot(self) -> dict:
        """The /healthz ``delivery`` section: per-sink queue/breaker/
        counter state plus WAL occupancy. Attribute reads only — safe
        inline on the event loop (PR-1 contract: a degraded plane keeps
        /healthz at HTTP 200; only a stale heartbeat is 503)."""
        wal = None
        if self.wal is not None:
            wal = {
                "path": str(self.wal.path),
                "puts": self.wal.puts,
                "acks": self.wal.acks,
                "dup_acks": self.wal.dup_acks,
                "unacked": self.wal.unacked_count(),
                "compactions": self.wal.compactions,
                "replayed_at_boot": self.wal_replayed,
            }
        out = {
            "enabled": True,
            "started": self.started,
            "sinks": self._sink_counts(),
            "wal": wal,
            "watermarks": self.watermarks(),
        }
        if self.health is not None and getattr(
            self.health, "enabled", False
        ):
            out["lag"] = self.health.snapshot()
        return out
