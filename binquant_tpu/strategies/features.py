"""Shared per-timeframe feature pack.

The reference enriches each symbol's DataFrame with the same indicator
columns once per kline (``producers/context_evaluator.py:228-251``) and the
strategies read the latest row plus small tails. Here the equivalent is one
batched pass producing last-bar values (and the few short histories
strategies inspect) for all S symbols — each indicator computed exactly once
per tick regardless of how many strategies consume it.

Variant pins (the reference is explicit that variant drift silently shifts
strategy thresholds, ``strategies/mean_reversion_fade.py:44-49``):

* ``rsi`` — simple-rolling-mean RSI (the pybinbot ``Indicators.rsi`` column
  strategies read);
* ``rsi_wilder`` — Wilder/EWM RSI (MeanReversionFade computes this inline);
* ``atr`` — SMA-of-true-range (the ``ATR`` column / accumulator variant);
* ``bb`` — 20-bar mean ± 2σ with population std (ddof=0), matching the
  accumulator's explicit ``std(ddof=0)``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, MarketBuffer
from binquant_tpu.ops.incremental import (
    EwmCarry,
    MomentCarry,
    SumCarry,
    ewm_advance,
    ewm_init,
    ewm_value,
    moment_advance,
    moment_init,
    moment_mean,
    moment_std,
    sum_advance,
    sum_init,
    sum_mean,
    sum_value,
)
from binquant_tpu.ops.indicators import true_range
from binquant_tpu.ops.rolling import (
    ewm_mean,
    ewm_mean_last,
    rolling_mean,
    rolling_mean_last,
    shift,
)
from binquant_tpu.utils import jsafe_div

# Bars of BB-width history retained for LadderDeployer's stability check
# (reference MIN_BB_WIDTH_STABILITY_CANDLES=8, ladder_deployer.py:23).
BB_WIDTH_HISTORY = 8

# Shared window/span constants (one source of truth for the full-window
# pack, the incremental carry, and the context features that read the same
# carry — see symbol_features_from_carry in regime/context.py).
RSI_WINDOW = 14
MFI_WINDOW = 14
BB_WINDOW = 20
ATR_WINDOW = 14
ATR_MA_WINDOW = 20
VOLUME_MA_WINDOW = 20
MACD_FAST, MACD_SLOW, MACD_SIGNAL = 12, 26, 9

# ewm alpha for a pandas span
_A = lambda span: 2.0 / (span + 1.0)
# The deepest buffer column the one-bar advance reads: the leaver of the
# widest sum/moment window plus its own prev-close lookback.
MIN_INCREMENTAL_WINDOW = max(BB_WINDOW, VOLUME_MA_WINDOW) + 2


class FeaturePack(NamedTuple):
    """Last-bar indicator batch for one timeframe. All arrays (S,) f32
    unless noted; NaN marks not-ready (insufficient history)."""

    open_time: jnp.ndarray  # (S,) int32 seconds
    close_time: jnp.ndarray  # (S,) int32 seconds (open_time + duration)
    open: jnp.ndarray
    high: jnp.ndarray
    low: jnp.ndarray
    close: jnp.ndarray
    prev_close: jnp.ndarray
    volume: jnp.ndarray
    quote_volume: jnp.ndarray
    num_trades: jnp.ndarray
    rsi: jnp.ndarray  # simple-rolling-mean RSI(14)
    rsi_wilder: jnp.ndarray  # Wilder/EWM RSI(14)
    macd: jnp.ndarray  # MACD line (12/26)
    macd_signal: jnp.ndarray  # 9-span EMA of the line
    mfi: jnp.ndarray  # MFI(14)
    bb_upper: jnp.ndarray
    bb_mid: jnp.ndarray
    bb_lower: jnp.ndarray
    bb_widths: jnp.ndarray  # (S, BB_WIDTH_HISTORY) trailing (u-l)/mid
    atr: jnp.ndarray  # SMA-of-TR ATR(14)
    atr_ma: jnp.ndarray  # 20-bar SMA of the ATR series
    volume_ma: jnp.ndarray  # 20-bar SMA of volume
    ema9: jnp.ndarray
    ema21: jnp.ndarray
    filled: jnp.ndarray  # (S,) int32 valid bar count
    valid: jnp.ndarray  # (S,) bool — row has any bars


def compute_feature_pack(buf: MarketBuffer) -> FeaturePack:
    close = buf.values[:, :, Field.CLOSE]
    high = buf.values[:, :, Field.HIGH]
    low = buf.values[:, :, Field.LOW]
    open_ = buf.values[:, :, Field.OPEN]
    volume = buf.values[:, :, Field.VOLUME]

    # --- RSI (both variants), full-window EWM for exact warm-up parity
    delta = close - shift(close, 1)
    gain = jnp.maximum(delta, 0.0)
    loss = jnp.maximum(-delta, 0.0)
    avg_gain_w = ewm_mean_last(gain, alpha=1.0 / 14, min_periods=14)
    avg_loss_w = ewm_mean_last(loss, alpha=1.0 / 14, min_periods=14)
    denom_w = avg_gain_w + avg_loss_w
    rsi_wilder = jnp.where(
        denom_w != 0, 100.0 * avg_gain_w / jnp.where(denom_w != 0, denom_w, 1.0), 50.0
    )
    rsi_wilder = jnp.where(
        jnp.isfinite(avg_gain_w) & jnp.isfinite(avg_loss_w), rsi_wilder, jnp.nan
    )
    avg_gain_s = rolling_mean_last(gain, 14)
    avg_loss_s = rolling_mean_last(loss, 14)
    denom_s = avg_gain_s + avg_loss_s
    rsi_sma = jnp.where(
        denom_s != 0, 100.0 * avg_gain_s / jnp.where(denom_s != 0, denom_s, 1.0), 50.0
    )
    rsi_sma = jnp.where(
        jnp.isfinite(avg_gain_s) & jnp.isfinite(avg_loss_s), rsi_sma, jnp.nan
    )

    # --- MACD: line needs its full series for the signal EMA
    macd_line = ewm_mean(close, span=12, min_periods=1) - ewm_mean(
        close, span=26, min_periods=1
    )
    macd_last = macd_line[:, -1]
    macd_signal = ewm_mean_last(macd_line, span=9, min_periods=1)

    # --- MFI(14) from the trailing 15 bars
    tp = (high + low + close) / 3.0
    flow = tp * volume
    tp_delta = tp - shift(tp, 1)
    pos_flow = jnp.where(tp_delta > 0, flow, 0.0)[:, -14:]
    neg_flow = jnp.where(tp_delta < 0, flow, 0.0)[:, -14:]
    flow_ok = jnp.isfinite(tp_delta[:, -14:])
    pos_sum = jnp.sum(jnp.where(flow_ok, pos_flow, 0.0), axis=-1)
    neg_sum = jnp.sum(jnp.where(flow_ok, neg_flow, 0.0), axis=-1)
    total = pos_sum + neg_sum
    mfi = jnp.where(total != 0, 100.0 * pos_sum / jnp.where(total != 0, total, 1.0), 50.0)
    mfi = jnp.where(jnp.sum(flow_ok, axis=-1) >= 14, mfi, jnp.nan)

    # --- Bollinger 20/2σ(ddof=0), last bar + trailing width history
    k = BB_WIDTH_HISTORY
    tail = close[:, -(20 + k - 1):]
    mids = rolling_mean(tail, 20)[:, -k:]
    # population std over each trailing-20 slice of the tail
    from binquant_tpu.ops.rolling import rolling_std

    stds = rolling_std(tail, 20, ddof=0)[:, -k:]
    uppers = mids + 2.0 * stds
    lowers = mids - 2.0 * stds
    bb_widths = jsafe_div(uppers - lowers, mids)
    bb_upper = uppers[:, -1]
    bb_mid = mids[:, -1]
    bb_lower = lowers[:, -1]

    # --- ATR(14) SMA variant + its own 20-bar MA. 35-bar slice, drop the
    # first TR (its prev_close falls outside the slice) -> 34 true TRs.
    tr = true_range(high[:, -35:], low[:, -35:], close[:, -35:])[:, 1:]
    atr_series = rolling_mean(tr, 14)  # (S, 34) with warm-up NaN
    atr = atr_series[:, -1]
    atr_ma = rolling_mean_last(atr_series, 20)

    volume_ma = rolling_mean_last(volume, 20)
    ema9 = ewm_mean_last(close, span=9, min_periods=1)
    ema21 = ewm_mean_last(close, span=21, min_periods=1)

    duration = buf.values[:, -1, Field.DURATION_S]
    duration = jnp.where(jnp.isfinite(duration), duration, 0.0).astype(jnp.int32)
    return FeaturePack(
        open_time=buf.times[:, -1],
        close_time=buf.times[:, -1] + duration,
        open=open_[:, -1],
        high=high[:, -1],
        low=low[:, -1],
        close=close[:, -1],
        prev_close=close[:, -2],
        volume=volume[:, -1],
        quote_volume=buf.values[:, -1, Field.QUOTE_VOLUME],
        num_trades=buf.values[:, -1, Field.NUM_TRADES],
        rsi=rsi_sma,
        rsi_wilder=rsi_wilder,
        macd=macd_last,
        macd_signal=macd_signal,
        mfi=mfi,
        bb_upper=bb_upper,
        bb_mid=bb_mid,
        bb_lower=bb_lower,
        bb_widths=bb_widths,
        atr=atr,
        atr_ma=atr_ma,
        volume_ma=volume_ma,
        ema9=ema9,
        ema21=ema21,
        filled=buf.filled,
        valid=buf.filled > 0,
    )


# ---------------------------------------------------------------------------
# Incremental carry: the same pack in O(1) bytes per symbol per tick
# ---------------------------------------------------------------------------


class FeatureCarry(NamedTuple):
    """Carried indicator state for ONE timeframe buffer, (S,)/(S, k) leaves.

    ``last_ts`` is the bar open-time the carry is synced to (-1 = empty /
    never synced); :func:`advance_feature_carry` advances a row only on a
    clean single-bar append (new latest ts whose previous slot holds
    exactly ``last_ts``). ema20/ema50 ride here too so the 15m carry also
    feeds the market-context symbol features (one advance, two consumers).
    """

    last_ts: jnp.ndarray  # (S,) int32
    ema9: EwmCarry
    ema21: EwmCarry
    ema20: EwmCarry
    ema50: EwmCarry
    macd_fast: EwmCarry
    macd_slow: EwmCarry
    macd_sig: EwmCarry
    gain_w: EwmCarry  # Wilder RSI avg gain (alpha=1/14)
    loss_w: EwmCarry
    gain_s: SumCarry  # simple-RSI rolling gain sum (14)
    loss_s: SumCarry
    pos_flow: SumCarry  # MFI flows (14)
    neg_flow: SumCarry
    close_m: MomentCarry  # Bollinger mid/std + context mid/std (20)
    vol_m: MomentCarry  # volume MA (20)
    tr_m: MomentCarry  # SMA-of-TR ATR (14)
    atr_hist: jnp.ndarray  # (S, ATR_MA_WINDOW) trailing ATR values
    bb_width_hist: jnp.ndarray  # (S, BB_WIDTH_HISTORY) trailing widths


def _empty_ewm(num_symbols: int) -> EwmCarry:
    return EwmCarry(
        mean=jnp.zeros((num_symbols,), jnp.float32),
        rel=jnp.full((num_symbols,), -1, jnp.int32),
    )


def _empty_sum(num_symbols: int) -> SumCarry:
    return SumCarry(
        wsum=jnp.zeros((num_symbols,), jnp.float32),
        cnt=jnp.zeros((num_symbols,), jnp.int32),
    )


def _empty_moment(num_symbols: int) -> MomentCarry:
    return MomentCarry(
        center=jnp.zeros((num_symbols,), jnp.float32),
        wsum=jnp.zeros((num_symbols,), jnp.float32),
        wsq=jnp.zeros((num_symbols,), jnp.float32),
        cnt=jnp.zeros((num_symbols,), jnp.int32),
    )


def empty_feature_carry(num_symbols: int) -> FeatureCarry:
    return FeatureCarry(
        last_ts=jnp.full((num_symbols,), -1, jnp.int32),
        ema9=_empty_ewm(num_symbols),
        ema21=_empty_ewm(num_symbols),
        ema20=_empty_ewm(num_symbols),
        ema50=_empty_ewm(num_symbols),
        macd_fast=_empty_ewm(num_symbols),
        macd_slow=_empty_ewm(num_symbols),
        macd_sig=_empty_ewm(num_symbols),
        gain_w=_empty_ewm(num_symbols),
        loss_w=_empty_ewm(num_symbols),
        gain_s=_empty_sum(num_symbols),
        loss_s=_empty_sum(num_symbols),
        pos_flow=_empty_sum(num_symbols),
        neg_flow=_empty_sum(num_symbols),
        close_m=_empty_moment(num_symbols),
        vol_m=_empty_moment(num_symbols),
        tr_m=_empty_moment(num_symbols),
        atr_hist=jnp.full((num_symbols, ATR_MA_WINDOW), jnp.nan, jnp.float32),
        bb_width_hist=jnp.full(
            (num_symbols, BB_WIDTH_HISTORY), jnp.nan, jnp.float32
        ),
    )


def init_feature_carry(buf: MarketBuffer) -> FeatureCarry:
    """Carry from the full window — every sub-carry evaluates the SAME
    expressions as the full-window pack, so a full recompute re-anchors
    the incremental path bit-identically (the resync the engine's fallback
    and drift audit rely on)."""
    W = buf.window
    assert W >= 36, f"window {W} too short for carry init (need >= 36)"
    close = buf.values[:, :, Field.CLOSE]
    high = buf.values[:, :, Field.HIGH]
    low = buf.values[:, :, Field.LOW]
    volume = buf.values[:, :, Field.VOLUME]

    delta = close - shift(close, 1)
    gain = jnp.maximum(delta, 0.0)
    loss = jnp.maximum(-delta, 0.0)

    macd_fast = ewm_init(close, _A(MACD_FAST))
    macd_slow = ewm_init(close, _A(MACD_SLOW))
    macd_line = ewm_mean(close, span=MACD_FAST, min_periods=1) - ewm_mean(
        close, span=MACD_SLOW, min_periods=1
    )
    tp = (high + low + close) / 3.0
    flow = tp * volume
    tp_delta = tp - shift(tp, 1)
    pos_flow_series = jnp.where(
        jnp.isfinite(tp_delta), jnp.where(tp_delta > 0, flow, 0.0), jnp.nan
    )
    neg_flow_series = jnp.where(
        jnp.isfinite(tp_delta), jnp.where(tp_delta < 0, flow, 0.0), jnp.nan
    )
    tr = true_range(high[:, -35:], low[:, -35:], close[:, -35:])[:, 1:]
    atr_series = rolling_mean(tr, ATR_WINDOW)

    k = BB_WIDTH_HISTORY
    tail = close[:, -(BB_WINDOW + k - 1):]
    mids = rolling_mean(tail, BB_WINDOW)[:, -k:]
    from binquant_tpu.ops.rolling import rolling_std

    stds = rolling_std(tail, BB_WINDOW, ddof=0)[:, -k:]
    widths = jsafe_div(4.0 * stds, mids)  # (upper-lower)/mid = 4σ/mid

    return FeatureCarry(
        last_ts=buf.times[:, -1].astype(jnp.int32),
        ema9=ewm_init(close, _A(9)),
        ema21=ewm_init(close, _A(21)),
        ema20=ewm_init(close, _A(20)),
        ema50=ewm_init(close, _A(50)),
        macd_fast=macd_fast,
        macd_slow=macd_slow,
        macd_sig=ewm_init(macd_line, _A(MACD_SIGNAL)),
        gain_w=ewm_init(gain, 1.0 / RSI_WINDOW),
        loss_w=ewm_init(loss, 1.0 / RSI_WINDOW),
        gain_s=sum_init(gain, RSI_WINDOW),
        loss_s=sum_init(loss, RSI_WINDOW),
        pos_flow=sum_init(pos_flow_series, MFI_WINDOW),
        neg_flow=sum_init(neg_flow_series, MFI_WINDOW),
        close_m=moment_init(close, BB_WINDOW),
        vol_m=moment_init(volume, VOLUME_MA_WINDOW),
        tr_m=moment_init(tr, ATR_WINDOW),
        atr_hist=atr_series[:, -ATR_MA_WINDOW:].astype(jnp.float32),
        bb_width_hist=widths.astype(jnp.float32),
    )


def _col(buf: MarketBuffer, pos: int, f: Field) -> jnp.ndarray:
    """(S,) column read — O(1) bytes per symbol, the whole point."""
    return buf.values[:, pos, int(f)]


def _tr_at(buf: MarketBuffer, pos: int) -> jnp.ndarray:
    """True range of the bar at ``pos`` from its own + previous columns."""
    h, lo = _col(buf, pos, Field.HIGH), _col(buf, pos, Field.LOW)
    pc = _col(buf, pos - 1, Field.CLOSE)
    hl = h - lo
    tr = jnp.maximum(hl, jnp.maximum(jnp.abs(h - pc), jnp.abs(lo - pc)))
    return jnp.where(jnp.isfinite(pc), tr, hl)


def _gain_loss_at(buf: MarketBuffer, pos: int):
    delta = _col(buf, pos, Field.CLOSE) - _col(buf, pos - 1, Field.CLOSE)
    fin = jnp.isfinite(delta)
    gain = jnp.where(fin, jnp.maximum(delta, 0.0), jnp.nan)
    loss = jnp.where(fin, jnp.maximum(-delta, 0.0), jnp.nan)
    return gain, loss


def _flows_at(buf: MarketBuffer, pos: int):
    tp = (
        _col(buf, pos, Field.HIGH)
        + _col(buf, pos, Field.LOW)
        + _col(buf, pos, Field.CLOSE)
    ) / 3.0
    tp_prev = (
        _col(buf, pos - 1, Field.HIGH)
        + _col(buf, pos - 1, Field.LOW)
        + _col(buf, pos - 1, Field.CLOSE)
    ) / 3.0
    tpd = tp - tp_prev
    flow = tp * _col(buf, pos, Field.VOLUME)
    fin = jnp.isfinite(tpd)
    pos_f = jnp.where(fin, jnp.where(tpd > 0, flow, 0.0), jnp.nan)
    neg_f = jnp.where(fin, jnp.where(tpd < 0, flow, 0.0), jnp.nan)
    return pos_f, neg_f


def carry_advance_masks(
    buf: MarketBuffer, last_ts: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(advanced, stale) row masks for a one-bar carry advance — the single
    copy of the clean-append test every carry family shares (the pack carry
    here, the strategy/supertrend/beta-corr carries in engine/step.py):

    * ``advanced`` — new latest ts whose previous slot holds exactly
      ``last_ts`` (a clean single-bar append; safe to advance);
    * ``stale`` — the latest ts moved any other way (reset row reclaimed,
      desync): keep the carry and let readers NaN-mask until the host's
      full-recompute resync lands.
    """
    ts = buf.times[:, -1]
    prev_ts = buf.times[:, -2]
    advanced = (ts >= 0) & (ts != last_ts) & (prev_ts == last_ts)
    stale = (ts != last_ts) & ~advanced
    return advanced, stale


def advance_feature_carry(
    buf: MarketBuffer,
    carry: FeatureCarry,
    masks: tuple[jnp.ndarray, jnp.ndarray] | None = None,
) -> tuple[FeatureCarry, jnp.ndarray]:
    """Advance per-symbol carries by the buffer's newest bar.

    Reads ~a dozen (S,) columns instead of the (S, W) window. Per row:

    * clean append (new latest ts, previous slot == ``last_ts``) → advance;
    * unchanged latest ts → keep (no new bar this tick);
    * anything else (reset row reclaimed, desync) → keep and flag STALE in
      the returned (S,) bool mask — readers NaN-mask stale rows and the
      host schedules a full recompute, which re-inits every row.

    Returns (carry', stale_mask). Mid-history rewrites do NOT change the
    latest ts and are invisible here by design — the HOST detects them
    from the update stream and routes the tick to the full step
    (io/pipeline.py), which is the only way to rebuild windowed sums whose
    interior changed.

    ``masks`` lets a caller that already ran :func:`carry_advance_masks`
    (engine/step.py advances every carry family under ONE copy of the
    clean-append decision) pass its ``(advanced, stale)`` through instead
    of recomputing them here — keeping a single mask source the strategy
    carries can never silently desync from.
    """
    W = buf.window
    assert W >= MIN_INCREMENTAL_WINDOW, (
        f"window {W} too short for incremental advance "
        f"(need >= {MIN_INCREMENTAL_WINDOW})"
    )
    ts = buf.times[:, -1]
    advanced, stale = (
        masks if masks is not None else carry_advance_masks(buf, carry.last_ts)
    )

    close_new = _col(buf, -1, Field.CLOSE)
    vol_new = _col(buf, -1, Field.VOLUME)
    gain_new, loss_new = _gain_loss_at(buf, -1)
    gain_old, loss_old = _gain_loss_at(buf, -(RSI_WINDOW + 1))
    pos_new, neg_new = _flows_at(buf, -1)
    pos_old, neg_old = _flows_at(buf, -(MFI_WINDOW + 1))
    tr_new = _tr_at(buf, -1)
    tr_old = _tr_at(buf, -(ATR_WINDOW + 1))

    ema9 = ewm_advance(carry.ema9, close_new, _A(9))
    ema21 = ewm_advance(carry.ema21, close_new, _A(21))
    ema20 = ewm_advance(carry.ema20, close_new, _A(20))
    ema50 = ewm_advance(carry.ema50, close_new, _A(50))
    macd_fast = ewm_advance(carry.macd_fast, close_new, _A(MACD_FAST))
    macd_slow = ewm_advance(carry.macd_slow, close_new, _A(MACD_SLOW))
    line_new = ewm_value(macd_fast, 1) - ewm_value(macd_slow, 1)
    macd_sig = ewm_advance(carry.macd_sig, line_new, _A(MACD_SIGNAL))
    gain_w = ewm_advance(carry.gain_w, gain_new, 1.0 / RSI_WINDOW)
    loss_w = ewm_advance(carry.loss_w, loss_new, 1.0 / RSI_WINDOW)
    gain_s = sum_advance(carry.gain_s, gain_new, gain_old)
    loss_s = sum_advance(carry.loss_s, loss_new, loss_old)
    pos_flow = sum_advance(carry.pos_flow, pos_new, pos_old)
    neg_flow = sum_advance(carry.neg_flow, neg_new, neg_old)
    close_m = moment_advance(
        carry.close_m, close_new, _col(buf, -(BB_WINDOW + 1), Field.CLOSE)
    )
    vol_m = moment_advance(
        carry.vol_m, vol_new, _col(buf, -(VOLUME_MA_WINDOW + 1), Field.VOLUME)
    )
    tr_m = moment_advance(carry.tr_m, tr_new, tr_old)

    atr_today = moment_mean(tr_m, ATR_WINDOW)
    atr_hist = jnp.concatenate(
        [carry.atr_hist[:, 1:], atr_today[:, None]], axis=1
    )
    mid = moment_mean(close_m, BB_WINDOW)
    std = moment_std(close_m, BB_WINDOW, ddof=0)
    width_today = jsafe_div(4.0 * std, mid)
    bb_width_hist = jnp.concatenate(
        [carry.bb_width_hist[:, 1:], width_today[:, None]], axis=1
    )

    new = FeatureCarry(
        last_ts=ts.astype(jnp.int32),
        ema9=ema9,
        ema21=ema21,
        ema20=ema20,
        ema50=ema50,
        macd_fast=macd_fast,
        macd_slow=macd_slow,
        macd_sig=macd_sig,
        gain_w=gain_w,
        loss_w=loss_w,
        gain_s=gain_s,
        loss_s=loss_s,
        pos_flow=pos_flow,
        neg_flow=neg_flow,
        close_m=close_m,
        vol_m=vol_m,
        tr_m=tr_m,
        atr_hist=atr_hist,
        bb_width_hist=bb_width_hist,
    )

    def sel(n, o):
        mask = advanced if n.ndim == 1 else advanced[:, None]
        return jnp.where(mask, n, o)

    return jax.tree_util.tree_map(sel, new, carry), stale


def _ratio_100(num: jnp.ndarray, den_other: jnp.ndarray) -> jnp.ndarray:
    """The pack's 100·a/(a+b) with the 50.0 flat-case override and NaN
    propagation (shared by both RSI variants and MFI)."""
    denom = num + den_other
    out = jnp.where(
        denom != 0, 100.0 * num / jnp.where(denom != 0, denom, 1.0), 50.0
    )
    return jnp.where(jnp.isfinite(num) & jnp.isfinite(den_other), out, jnp.nan)


def ext_gather(series: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Per-tick gather from an (S, L) extended series: ``out[t, s] =
    series[s, idx[t, s]]`` — the (T, S) batch of what each backtest tick's
    right-aligned window view would hold at the gathered column. The
    broadcast is a view; only the (T, S) result materializes."""
    T = idx.shape[0]
    b = jnp.broadcast_to(series[None], (T,) + series.shape)
    return jnp.take_along_axis(b, idx[:, :, None], axis=2)[..., 0]


def compute_feature_pack_ext(
    ext_times: jnp.ndarray,  # (S, L) int32, -1 pad
    ext_vals: jnp.ndarray,  # (S, L, F) f32, NaN pad
    counts: jnp.ndarray,  # (T, S) int32 bars applied through tick t
    filled0: jnp.ndarray,  # (S,) pre-chunk fill
    window: int,
) -> FeaturePack:
    """The T per-tick FeaturePacks from ONE pass over the extended series.

    The extension-invariant twin of vmapping :func:`compute_feature_pack`
    over T gathered (S, W) window views: every rolling/EWM kernel runs once
    over the (S, L = W + N) extension, and tick t's pack is the gather at
    ``last = counts[t] + window - 1``. Returns a FeaturePack whose leaves
    are (T, S)-leading ((T, S, BB_WIDTH_HISTORY) for ``bb_widths``).

    Numeric contract (the BQT_EXT_INVARIANT tolerance interface — see
    README §Backtest): positional fields (bar values, times, filled) are
    bit-identical to the view path. Windowed sums/means/stds anchor their
    cumsum at the series start instead of each view's window start —
    equal in exact arithmetic, f32-ulp apart. EWM fields additionally see
    the pre-window prefix the view path truncates, a ``(1-alpha)^W``-scale
    divergence for rows with more than ``window`` bars of history.
    Strategies gating on these fields declare a gate margin
    (strategies/params.py ``declared_gate_margins``) and parity is pinned
    as set-equality outside that margin."""
    from binquant_tpu.ops.rolling import rolling_std, rolling_sum

    close = ext_vals[:, :, Field.CLOSE]
    high = ext_vals[:, :, Field.HIGH]
    low = ext_vals[:, :, Field.LOW]
    open_ = ext_vals[:, :, Field.OPEN]
    volume = ext_vals[:, :, Field.VOLUME]

    last = (counts + (window - 1)).astype(jnp.int32)  # (T, S)
    g = lambda s: ext_gather(s, last)

    # --- RSI (both variants): the view's NaN gating survives unchanged —
    # leading padding NaNs count as missing for both anchors
    delta = close - shift(close, 1)
    gain = jnp.maximum(delta, 0.0)
    loss = jnp.maximum(-delta, 0.0)
    rsi_wilder = _ratio_100(
        g(ewm_mean(gain, alpha=1.0 / RSI_WINDOW, min_periods=RSI_WINDOW)),
        g(ewm_mean(loss, alpha=1.0 / RSI_WINDOW, min_periods=RSI_WINDOW)),
    )
    rsi_sma = _ratio_100(
        g(rolling_mean(gain, RSI_WINDOW)), g(rolling_mean(loss, RSI_WINDOW))
    )

    # --- MACD over the full extension (the vmapped path's dominant EWM
    # matmul cost: T × O(W²) collapses to one O(L²))
    macd_line = ewm_mean(close, span=MACD_FAST, min_periods=1) - ewm_mean(
        close, span=MACD_SLOW, min_periods=1
    )
    macd_last = g(macd_line)
    macd_signal = g(ewm_mean(macd_line, span=MACD_SIGNAL, min_periods=1))

    # --- MFI: NaN-marked flow series + NaN-aware rolling sums reproduce
    # the view's sum(flow_ok) >= 14 gate exactly (rolling_sum is NaN iff
    # fewer than MFI_WINDOW finite deltas in the trailing window)
    tp = (high + low + close) / 3.0
    flow = tp * volume
    tp_delta = tp - shift(tp, 1)
    fin = jnp.isfinite(tp_delta)
    pos_series = jnp.where(fin, jnp.where(tp_delta > 0, flow, 0.0), jnp.nan)
    neg_series = jnp.where(fin, jnp.where(tp_delta < 0, flow, 0.0), jnp.nan)
    mfi = _ratio_100(
        g(rolling_sum(pos_series, MFI_WINDOW)),
        g(rolling_sum(neg_series, MFI_WINDOW)),
    )

    # --- Bollinger: full-series rolling moments, width history via a
    # trailing-k column gather (the view's last-k width positions)
    mids = rolling_mean(close, BB_WINDOW)
    stds = rolling_std(close, BB_WINDOW, ddof=0)
    uppers = mids + 2.0 * stds
    lowers = mids - 2.0 * stds
    width_series = jsafe_div(uppers - lowers, mids)
    k = BB_WIDTH_HISTORY
    T = last.shape[0]
    hist_cols = last[:, :, None] + jnp.arange(-(k - 1), 1, dtype=jnp.int32)
    bb_widths = jnp.take_along_axis(
        jnp.broadcast_to(width_series[None], (T,) + width_series.shape),
        hist_cols,
        axis=2,
    )

    # --- ATR: full-series TR + rolling means. The view path's dropped
    # first TR (its prev_close outside the 35-slice) is never among the
    # positions ``atr``/``atr_ma`` consume (deepest reach: last - 32), so
    # the consumed value sets are identical.
    tr = true_range(high, low, close)
    atr_series = rolling_mean(tr, ATR_WINDOW)
    atr = g(atr_series)
    atr_ma = g(rolling_mean(atr_series, ATR_MA_WINDOW))

    volume_ma = g(rolling_mean(volume, VOLUME_MA_WINDOW))
    ema9 = g(ewm_mean(close, span=9, min_periods=1))
    ema21 = g(ewm_mean(close, span=21, min_periods=1))

    open_time = ext_gather(ext_times, last)
    duration = g(ext_vals[:, :, Field.DURATION_S])
    duration = jnp.where(jnp.isfinite(duration), duration, 0.0).astype(jnp.int32)
    filled = jnp.minimum(filled0[None, :] + counts, window).astype(jnp.int32)
    return FeaturePack(
        open_time=open_time,
        close_time=open_time + duration,
        open=g(open_),
        high=g(high),
        low=g(low),
        close=g(close),
        prev_close=ext_gather(close, last - 1),
        volume=g(volume),
        quote_volume=g(ext_vals[:, :, Field.QUOTE_VOLUME]),
        num_trades=g(ext_vals[:, :, Field.NUM_TRADES]),
        rsi=rsi_sma,
        rsi_wilder=rsi_wilder,
        macd=macd_last,
        macd_signal=macd_signal,
        mfi=mfi,
        bb_upper=g(uppers),
        bb_mid=g(mids),
        bb_lower=g(lowers),
        bb_widths=bb_widths,
        atr=atr,
        atr_ma=atr_ma,
        volume_ma=volume_ma,
        ema9=ema9,
        ema21=ema21,
        filled=filled,
        valid=filled > 0,
    )


def feature_pack_from_carry(
    buf: MarketBuffer, carry: FeatureCarry, stale: jnp.ndarray
) -> FeaturePack:
    """The FeaturePack readout from carried state — the fast-path twin of
    :func:`compute_feature_pack` (same masks, same formulas; parity pinned
    in tests/test_ops_parity.py + tests/test_incremental.py). Raw bar
    fields come from the buffer's last columns; indicator fields of STALE
    rows are NaN-masked (defense in depth — the host already routes
    desynced ticks to the full step)."""
    close = buf.values[:, -1, Field.CLOSE]

    avg_gain_w = ewm_value(carry.gain_w, RSI_WINDOW)
    avg_loss_w = ewm_value(carry.loss_w, RSI_WINDOW)
    rsi_wilder = _ratio_100(avg_gain_w, avg_loss_w)
    rsi_sma = _ratio_100(
        sum_mean(carry.gain_s, RSI_WINDOW), sum_mean(carry.loss_s, RSI_WINDOW)
    )

    macd_last = ewm_value(carry.macd_fast, 1) - ewm_value(carry.macd_slow, 1)
    macd_signal = ewm_value(carry.macd_sig, 1)

    mfi = _ratio_100(
        sum_value(carry.pos_flow, MFI_WINDOW),
        sum_value(carry.neg_flow, MFI_WINDOW),
    )

    bb_mid = moment_mean(carry.close_m, BB_WINDOW)
    bb_std = moment_std(carry.close_m, BB_WINDOW, ddof=0)
    bb_upper = bb_mid + 2.0 * bb_std
    bb_lower = bb_mid - 2.0 * bb_std

    atr = moment_mean(carry.tr_m, ATR_WINDOW)
    hist_fin = jnp.isfinite(carry.atr_hist)
    hist_cnt = jnp.sum(hist_fin, axis=-1)
    atr_ma = jnp.where(
        hist_cnt >= ATR_MA_WINDOW,
        jnp.sum(jnp.where(hist_fin, carry.atr_hist, 0.0), axis=-1)
        / jnp.maximum(hist_cnt, 1),
        jnp.nan,
    )
    volume_ma = moment_mean(carry.vol_m, VOLUME_MA_WINDOW)

    nanify = lambda v: jnp.where(stale, jnp.nan, v)
    duration = buf.values[:, -1, Field.DURATION_S]
    duration = jnp.where(jnp.isfinite(duration), duration, 0.0).astype(jnp.int32)
    return FeaturePack(
        open_time=buf.times[:, -1],
        close_time=buf.times[:, -1] + duration,
        open=buf.values[:, -1, Field.OPEN],
        high=buf.values[:, -1, Field.HIGH],
        low=buf.values[:, -1, Field.LOW],
        close=close,
        prev_close=buf.values[:, -2, Field.CLOSE],
        volume=buf.values[:, -1, Field.VOLUME],
        quote_volume=buf.values[:, -1, Field.QUOTE_VOLUME],
        num_trades=buf.values[:, -1, Field.NUM_TRADES],
        rsi=nanify(rsi_sma),
        rsi_wilder=nanify(rsi_wilder),
        macd=nanify(macd_last),
        macd_signal=nanify(macd_signal),
        mfi=nanify(mfi),
        bb_upper=nanify(bb_upper),
        bb_mid=nanify(bb_mid),
        bb_lower=nanify(bb_lower),
        bb_widths=jnp.where(stale[:, None], jnp.nan, carry.bb_width_hist),
        atr=nanify(atr),
        atr_ma=nanify(atr_ma),
        volume_ma=nanify(volume_ma),
        ema9=nanify(ewm_value(carry.ema9, 1)),
        ema21=nanify(ewm_value(carry.ema21, 1)),
        filled=buf.filled,
        valid=buf.filled > 0,
    )


def compute_feature_pack_incremental(
    buf: MarketBuffer, carry: FeatureCarry
) -> tuple[FeaturePack, FeatureCarry]:
    """One-bar advance + readout: the O(1)-bytes-per-symbol pack."""
    new_carry, stale = advance_feature_carry(buf, carry)
    return feature_pack_from_carry(buf, new_carry, stale), new_carry
