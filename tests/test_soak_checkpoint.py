"""Soak + checkpoint continuity over a thousand-tick session (VERDICT r3
item 8).

A long stylized-facts market (events throughout, not just a crafted final
tick) runs through the PIPELINED engine three ways:

* **unbroken** — one engine, 1200 ticks, periodic checkpoints exactly as
  consume_loop takes them (flush_pending before every save);
* **killed** — the same feed dies at an arbitrary mid-bucket tick with a
  dispatched tick still in flight (hard kill: no shutdown flush);
* **resumed** — a fresh engine restores the last checkpoint and replays
  the feed from there.

Continuity contract: signals attributed to ticks at or before the last
checkpoint, plus everything the resumed engine emits, must equal the
unbroken run's stream exactly — the crash loses nothing (the
at-least-once window between checkpoint and kill is re-emitted
identically by the resumed engine) and duplicates nothing.
"""

from __future__ import annotations

import asyncio

import pytest

from binquant_tpu.io.checkpoint import CheckpointManager
from binquant_tpu.io.market_sim import MarketSimConfig, write_market_file
from binquant_tpu.io.replay import load_klines_by_tick, make_stub_engine

CAP, WIN = 16, 130  # shared suite shape — tick_step compile cache hit
SAVE_EVERY = 97
KILL_AT = 700  # arbitrary, deliberately NOT a save boundary


@pytest.fixture(scope="module")
def soak_feed(tmp_path_factory):
    path = tmp_path_factory.mktemp("soak") / "soak.jsonl.gz"
    write_market_file(
        path,
        MarketSimConfig(
            n_symbols=8,
            hours=300,  # 1200 15m ticks
            seed=31,
            event_start_hour=26,  # events as soon as MIN_BARS allows
            n_cascades=3,
            n_pumps=30,
        ),
    )
    by_tick = load_klines_by_tick(path)
    return sorted(by_tick), by_tick


def _engine(ckpt_path):
    engine = make_stub_engine(capacity=CAP, window=WIN, pipeline_depth=1)
    engine.checkpoint = CheckpointManager(ckpt_path, every_ticks=SAVE_EVERY)
    return engine


def _signals(fired):
    return {
        (s.tick_ms, s.strategy, s.symbol, str(s.value.direction))
        for s in fired
    }


async def _run(engine, buckets, by_tick, start=0, stop=None, final_flush=True):
    """Drive the pipelined loop with consume_loop's checkpoint discipline:
    flush in-flight ticks, then save, whenever the cadence hits."""
    out = set()
    for bucket in buckets[start:stop]:
        for k in sorted(by_tick[bucket], key=lambda k: k["open_time"]):
            engine.ingest(k)
        tick_ms = (bucket + 1) * 900 * 1000
        out |= _signals(await engine.process_tick(now_ms=tick_ms))
        if engine.checkpoint.should_save(engine):
            out |= _signals(await engine.flush_pending())
            engine.checkpoint.maybe_save(engine)
    if final_flush:
        out |= _signals(await engine.flush_pending())
    return out


@pytest.mark.slow
def test_soak_kill_restore_stream_identical(soak_feed, tmp_path):
    buckets, by_tick = soak_feed
    assert len(buckets) >= 1100

    async def go():
        # --- unbroken reference run
        unbroken = await _run(
            _engine(tmp_path / "unbroken.npz"), buckets, by_tick
        )

        # --- killed run: dies at KILL_AT with a tick still in flight
        killed = _engine(tmp_path / "killed.npz")
        seen = await _run(
            killed, buckets, by_tick, stop=KILL_AT, final_flush=False
        )
        assert killed._pending, "kill must strand a dispatched tick"
        last_save = (KILL_AT // SAVE_EVERY) * SAVE_EVERY
        last_save_ms = (buckets[last_save - 1] + 1) * 900 * 1000
        survived = {s for s in seen if s[0] <= last_save_ms}
        lost_window = {s for s in seen if s[0] > last_save_ms}

        # --- resumed run: restore the last checkpoint, replay from there
        resumed = make_stub_engine(capacity=CAP, window=WIN, pipeline_depth=1)
        resumed.checkpoint = CheckpointManager(
            tmp_path / "killed.npz", every_ticks=SAVE_EVERY
        )
        assert resumed.checkpoint.try_restore(resumed)
        assert resumed.ticks_processed == last_save
        replayed = await _run(
            resumed, buckets, by_tick, start=last_save
        )
        return unbroken, survived, lost_window, replayed

    unbroken, survived, lost_window, replayed = asyncio.run(go())

    assert unbroken, "soak must fire signals (eventful market)"
    # continuity: checkpointed prefix + resumed tail == unbroken stream
    combined = survived | replayed
    assert combined == unbroken, {
        "missing": sorted(unbroken - combined)[:5],
        "extra": sorted(combined - unbroken)[:5],
    }
    # the crash-lost window was re-emitted identically, not dropped
    assert lost_window <= replayed
