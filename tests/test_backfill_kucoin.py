"""Startup backfill + KuCoin websocket protocol tests.

Round-1 judge items 3/4: the engine must seed both interval buffers from
REST history so strategies can fire on the FIRST live tick, and the KuCoin
connector must speak the real protocol (bullet-token handshake, ≤300-topic
batches, *USDTM futures filter, in-progress-candle close detection).
"""

import asyncio
import json

import numpy as np
import pandas as pd
import pytest

from binquant_tpu.io.exchanges import (
    make_history_fetcher,
    normalize_binance_klines,
    normalize_kucoin_klines,
)
from binquant_tpu.io.replay import make_stub_engine
from binquant_tpu.io.websocket import (
    KucoinKlinesConnector,
    WebsocketClientFactory,
    parse_kucoin_candle_message,
)
from binquant_tpu.schemas import SymbolModel
from tests.conftest import make_ohlcv

T0 = 1_753_000_200  # 15m-bucket aligned


# ---------------------------------------------------------------------------
# REST row normalization
# ---------------------------------------------------------------------------


class TestNormalization:
    def test_binance_rows(self):
        rows = [
            [T0 * 1000, "1.0", "2.0", "0.5", "1.5", "10", T0 * 1000 + 899_999,
             "15", 42, "6", "9", "0"],
        ]
        out = normalize_binance_klines("BTCUSDT", rows)
        k = out[0]
        assert k["symbol"] == "BTCUSDT"
        assert k["open_time"] == T0 * 1000
        assert k["close_time"] == T0 * 1000 + 899_999
        assert (k["open"], k["high"], k["low"], k["close"]) == (1.0, 2.0, 0.5, 1.5)
        assert k["quote_asset_volume"] == 15.0

    def test_kucoin_rows_newest_first_reversed(self):
        rows = [  # KuCoin returns newest first
            [str(T0 + 900), "2.0", "2.5", "2.6", "1.9", "20", "44"],
            [str(T0), "1.0", "1.5", "1.6", "0.9", "10", "14"],
        ]
        out = normalize_kucoin_klines("BTC-USDT", rows, 900)
        assert [k["open_time"] for k in out] == [T0 * 1000, (T0 + 900) * 1000]
        k = out[0]
        # spot order: [t, open, close, high, low, vol, turnover]
        assert (k["open"], k["close"], k["high"], k["low"]) == (1.0, 1.5, 1.6, 0.9)
        assert k["close_time"] == T0 * 1000 + 900_000 - 1

    def test_kucoin_futures_rows(self):
        from binquant_tpu.io.exchanges import normalize_kucoin_futures_klines

        rows = [  # futures order: [t_ms, open, high, low, close, vol]
            [T0 * 1000, 1.0, 1.6, 0.9, 1.5, 10.0],
            [(T0 + 300) * 1000, 1.5, 1.7, 1.4, 1.6, 12.0],
        ]
        out = normalize_kucoin_futures_klines("XBTUSDTM", rows, 300)
        assert [k["open_time"] for k in out] == [T0 * 1000, (T0 + 300) * 1000]
        k = out[0]
        assert (k["open"], k["high"], k["low"], k["close"]) == (1.0, 1.6, 0.9, 1.5)
        assert k["close_time"] == T0 * 1000 + 300_000 - 1
        assert k["symbol"] == "XBTUSDTM"


class TestFetcherSymbolForms:
    """The engine tracks undashed ids; each exchange API wants its own
    symbol form. A mismatch silently loads ZERO bars (round-2 review)."""

    def test_kucoin_spot_translates_to_dashed_and_back(self):
        seen = []

        class Api:
            def get_ui_klines(self, symbol, interval, limit=400):
                seen.append((symbol, interval))
                return [[str(T0), "1.0", "1.5", "1.6", "0.9", "10", "14"]]

        fetch = make_history_fetcher(
            Api(), "kucoin", market_type="spot",
            api_symbol_of=lambda s: {"BTCUSDT": "BTC-USDT"}.get(s, s),
        )
        out = fetch("BTCUSDT", "15m")
        assert seen == [("BTC-USDT", "15min")]  # API got the dashed form
        assert out[0]["symbol"] == "BTCUSDT"  # engine id preserved

    def test_kucoin_futures_uses_granularity_minutes(self):
        seen = []

        class Api:
            def get_ui_klines(self, symbol, granularity, limit=400):
                seen.append((symbol, granularity))
                return [[T0 * 1000, 1.0, 1.6, 0.9, 1.5, 10.0]]

        fetch = make_history_fetcher(Api(), "kucoin", market_type="futures")
        out = fetch("XBTUSDTM", "5m")
        assert seen == [("XBTUSDTM", 5)]
        assert out[0]["symbol"] == "XBTUSDTM"
        assert out[0]["close_time"] - out[0]["open_time"] == 300_000 - 1

    def test_kucoin_error_envelope_raises(self):
        # HTTP 200 + error code must raise, not silently return [] —
        # a silent empty turns the whole startup backfill into a no-op
        from binquant_tpu.io.exchanges import KucoinApi, KucoinFutures

        class Sess:
            def get(self, url, params=None):
                class R:
                    status_code = 200

                    def raise_for_status(self):
                        pass

                    def json(self):
                        return {"code": "400100", "msg": "bad symbol"}

                return R()

        with pytest.raises(RuntimeError, match="400100"):
            KucoinApi(session=Sess()).get_ui_klines("NOPE", "15min")
        with pytest.raises(RuntimeError, match="400100"):
            KucoinFutures(session=Sess()).get_ui_klines("NOPE", 15)

    def test_kucoin_futures_rest_paginates_time_range(self):
        # the endpoint caps ~200 rows/request AND returns server-default
        # recent rows without from/to — 400 bars must arrive as two
        # contiguous ≤200-bar pages, deduped and oldest-first
        from binquant_tpu.io.exchanges import KucoinFutures

        calls = []

        class Sess:
            def get(self, url, params=None):
                calls.append(dict(params or {}))

                class R:
                    status_code = 200

                    def raise_for_status(self):
                        pass

                    def json(self):
                        p = calls[-1]
                        bar = 15 * 60_000
                        data = [
                            [t, 1.0, 2.0, 0.5, 1.5, 10.0]
                            for t in range(p["from"], p["to"], bar)
                        ]
                        return {"code": "200000", "data": data}

                return R()

        rows = KucoinFutures(session=Sess()).get_ui_klines(
            "XBTUSDTM", 15, limit=400
        )
        assert len(calls) == 2
        bar = 15 * 60_000
        for p in calls:
            assert p["to"] - p["from"] == 200 * bar
        # contiguous: second (older) page ends where the first began
        assert calls[1]["to"] == calls[0]["from"]
        assert len(rows) == 400
        times = [int(r[0]) for r in rows]
        assert times == sorted(times)
        assert times[-1] - times[0] == 399 * bar


# ---------------------------------------------------------------------------
# Backfill: strategies can fire on the first live tick
# ---------------------------------------------------------------------------


class TestBackfill:
    def _history(self, n_symbols=12, n_bars=140):
        rng = np.random.default_rng(5)
        hist = {}
        for i in range(n_symbols):
            sym = "BTCUSDT" if i == 0 else f"S{i:03d}USDT"
            # S005 grinds down so its Wilder RSI pins oversold pre-hammer
            drift = -0.006 if i == 5 else 0.0
            hist[sym] = pd.DataFrame(
                make_ohlcv(
                    rng, n=n_bars, start_price=30 + i, vol=0.006, drift=drift,
                    t0=T0 * 1000, interval_ms=900_000,
                )
            )
        return hist

    def _fetch_for(self, hist):
        def fetch(symbol, interval_key):
            df = hist[symbol]
            step = 900_000 if interval_key == "15m" else 300_000
            out = []
            for j, r in df.iterrows():
                t = T0 * 1000 + j * step
                out.append(
                    {
                        "symbol": symbol,
                        "open_time": t,
                        "close_time": t + step - 1,
                        "open": float(r["open"]),
                        "high": float(r["high"]),
                        "low": float(r["low"]),
                        "close": float(r["close"]),
                        "volume": float(r["volume"]),
                        "quote_asset_volume": float(r["volume"] * r["close"]),
                        "number_of_trades": 100.0,
                        "taker_buy_base_volume": 0.0,
                        "taker_buy_quote_volume": 0.0,
                    }
                )
            return out

        return fetch

    def test_buffers_seeded_and_first_tick_can_fire(self):
        hist = self._history()
        # craft an MRF hammer on the LAST CLOSED 15m bar of S005USDT
        df = hist["S005USDT"]
        prev_close = float(df["close"].iloc[-2])
        o = prev_close * 0.94
        c = o * 1.003
        df.loc[df.index[-1], ["open", "high", "low", "close"]] = [
            o, c * 1.001, o * 0.997, c,
        ]
        df.loc[df.index[-1], "volume"] = float(df["volume"].iloc[-40:].mean()) * 4

        engine = make_stub_engine(capacity=16, window=200)
        n_bars = len(df)
        # "now": just after the last 15m bar closed
        now_ms = (T0 + n_bars * 900) * 1000 + 1000
        loaded = engine.backfill(
            list(hist), self._fetch_for(hist), now_ms=now_ms, chunk=5
        )
        assert loaded > 0
        # both buffers are seeded
        filled5 = np.asarray(engine.state.buf5.filled)
        filled15 = np.asarray(engine.state.buf15.filled)
        row = engine.registry.row_of("S005USDT")
        assert filled15[row] >= 100
        assert filled5[row] > 0
        assert engine.registry.row_of("BTCUSDT") == 0

        # the FIRST live tick evaluates the backfilled state and fires
        fired = asyncio.run(engine.process_tick(now_ms=now_ms))
        assert any(
            f.strategy == "mean_reversion_fade" and f.symbol == "S005USDT"
            for f in fired
        )

    def test_open_bar_not_loaded(self):
        hist = self._history(n_symbols=2, n_bars=10)
        engine = make_stub_engine(capacity=16, window=64)
        # now = mid-way through bar 9 -> only bars 0..8 are closed
        now_ms = (T0 + 9 * 900 + 450) * 1000
        engine.backfill(list(hist), self._fetch_for(hist), now_ms=now_ms)
        times15 = np.asarray(engine.state.buf15.times)
        row = engine.registry.row_of("S001USDT")
        assert int(times15[row].max()) == T0 + 8 * 900

    def test_fetch_failure_isolated(self):
        hist = self._history(n_symbols=3, n_bars=10)
        calls = []

        def flaky(symbol, interval_key):
            calls.append(symbol)
            if symbol == "S001USDT":
                raise RuntimeError("rest down")
            return self._fetch_for(hist)(symbol, interval_key)

        engine = make_stub_engine(capacity=16, window=64)
        now_ms = (T0 + 20 * 900) * 1000
        loaded = engine.backfill(list(hist), flaky, now_ms=now_ms)
        assert loaded > 0
        assert engine.registry.row_of("S002USDT") is not None


# ---------------------------------------------------------------------------
# KuCoin websocket protocol
# ---------------------------------------------------------------------------


def _spot_frame(symbol="BTC-USDT", interval="15min", t=T0, close="1.5"):
    return json.dumps(
        {
            "type": "message",
            "topic": f"/market/candles:{symbol}_{interval}",
            "subject": "trade.candles.update",
            "data": {
                "symbol": symbol,
                "candles": [str(t), "1.0", close, "2.0", "0.5", "10", "14"],
                "time": t * 1_000_000_000,
            },
        }
    )


def _futures_frame(symbol="XBTUSDTM", interval="15min", t=T0):
    return json.dumps(
        {
            "type": "message",
            "topic": f"/contractMarket/limitCandle:{symbol}_{interval}",
            "subject": "candle.stick",
            "data": {"symbol": symbol, "candles": [str(t), "1.0", "2.0", "0.5", "1.5", "10"]},
        }
    )


class TestKucoinParsing:
    def test_spot_frame_field_order(self):
        sym, iv, k = parse_kucoin_candle_message(_spot_frame(), "spot")
        assert (sym, iv) == ("BTC-USDT", "15min")
        assert k["symbol"] == "BTCUSDT"
        # spot candle order [t, open, close, high, low, ...]
        assert (k["open"], k["close"], k["high"], k["low"]) == (1.0, 1.5, 2.0, 0.5)
        assert k["quote_asset_volume"] == 14.0
        assert k["close_time"] == T0 * 1000 + 900_000 - 1

    def test_futures_frame_field_order(self):
        sym, iv, k = parse_kucoin_candle_message(_futures_frame(), "futures")
        assert sym == "XBTUSDTM"
        # futures candle order [t, open, high, low, close, vol]
        assert (k["open"], k["high"], k["low"], k["close"]) == (1.0, 2.0, 0.5, 1.5)

    def test_noise_dropped(self):
        assert parse_kucoin_candle_message('{"type":"welcome"}', "spot") is None
        assert parse_kucoin_candle_message('{"type":"pong"}', "spot") is None
        assert parse_kucoin_candle_message("junk{", "spot") is None


class TestKucoinConnector:
    def _connector(self, market_type="futures", n=5):
        if market_type == "futures":
            symbols = [SymbolModel(id=f"S{i}USDTM") for i in range(n)] + [
                SymbolModel(id="SPOTUSDT")  # filtered out of futures topics
            ]
        else:
            symbols = [
                SymbolModel(id=f"S{i}USDT", base_asset=f"S{i}", quote_asset="USDT")
                for i in range(n)
            ]
        return KucoinKlinesConnector(
            asyncio.Queue(),
            symbols,
            market_type=market_type,
            token_fetch=lambda: ("wss://fake", "tok", 18.0),
            connect=lambda *_: None,
        )

    def test_futures_topics_filter_usdtm(self):
        conn = self._connector("futures")
        topics = [t for chunk in conn._chunks() for t in chunk]
        assert all(t.startswith("/contractMarket/limitCandle:") for t in topics)
        assert not any("SPOTUSDT" in t for t in topics)
        # both intervals per contract
        assert "/contractMarket/limitCandle:S0USDTM_5min" in topics
        assert "/contractMarket/limitCandle:S0USDTM_15min" in topics

    def test_spot_topics_use_dashed_symbols(self):
        conn = self._connector("spot")
        topics = [t for chunk in conn._chunks() for t in chunk]
        assert "/market/candles:S0-USDT_15min" in topics

    def test_topic_batches_capped_at_300(self):
        symbols = [SymbolModel(id=f"S{i}USDTM") for i in range(400)]
        conn = KucoinKlinesConnector(
            asyncio.Queue(), symbols, market_type="futures",
            token_fetch=lambda: ("wss://fake", "tok", 18.0),
            connect=lambda *_: None,
        )
        chunks = conn._chunks()
        assert all(len(c) <= 300 for c in chunks)
        assert sum(len(c) for c in chunks) == 800  # 400 contracts x 2 intervals

    def test_subscribe_messages_batched_under_uplink_limit(self):
        """300 individual subscribes would blow KuCoin's ~100 uplink
        msgs/10s per-connection limit (invisible with response=False);
        suffixes must be comma-joined ≤100 per message."""
        sent = []

        class FakeWs:
            async def send(self, msg):
                sent.append(json.loads(msg))

            def __aiter__(self):
                return self

            async def __anext__(self):
                await asyncio.sleep(3600)  # hold the connection open

        class FakeConnect:
            def __init__(self, url):
                pass

            async def __aenter__(self):
                return FakeWs()

            async def __aexit__(self, *a):
                return False

        symbols = [SymbolModel(id=f"S{i}USDTM") for i in range(150)]
        conn = KucoinKlinesConnector(
            asyncio.Queue(), symbols, market_type="futures",
            token_fetch=lambda: ("wss://fake", "tok", 18.0),
            connect=FakeConnect,
        )
        topics = conn._chunks()[0]
        assert len(topics) == 300

        async def drive():
            task = asyncio.create_task(conn._run_client(0, topics))
            await asyncio.sleep(1.0)
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

        asyncio.run(drive())
        subs = [m for m in sent if m.get("type") == "subscribe"]
        assert len(subs) == 3  # 300 suffixes / 100 per message
        for m in subs:
            prefix, suffixes = m["topic"].split(":", 1)
            assert prefix == "/contractMarket/limitCandle"
            assert 1 <= len(suffixes.split(",")) <= 100

    def test_closed_candle_emitted_when_open_time_advances(self):
        conn = self._connector("futures")

        async def drive():
            p1 = parse_kucoin_candle_message(_futures_frame(t=T0), "futures")
            await conn._on_candle(*p1)  # in-progress: nothing emitted
            assert conn.queue.qsize() == 0
            # refinement of the SAME candle: still nothing
            await conn._on_candle(*p1)
            assert conn.queue.qsize() == 0
            p2 = parse_kucoin_candle_message(
                _futures_frame(t=T0 + 900), "futures"
            )
            await conn._on_candle(*p2)  # next bar opened -> previous closed
            assert conn.queue.qsize() == 1
            emitted = conn.queue.get_nowait()
            assert emitted["open_time"] == T0 * 1000
            assert emitted["symbol"] == "XBTUSDTM"

        asyncio.run(drive())


def test_factory_selects_kucoin():
    symbols = [SymbolModel(id="S0USDTM")]
    factory = WebsocketClientFactory(
        asyncio.Queue(), symbols, exchange_id="kucoin", market_type="futures",
        token_fetch=lambda: ("wss://fake", "tok", 18.0),
        connect=lambda *_: None,
    )
    conn = factory.create_connector()
    assert isinstance(conn, KucoinKlinesConnector)
    assert conn.intervals == ("5min", "15min")
