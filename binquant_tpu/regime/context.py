"""Batched market-context construction + regime annotation.

Re-implements, as one jit'd pass over the ``(S, W)`` market buffer, what the
reference does per candle in Python:

* per-symbol features — EMA20/50, ATR-14, BB-20/2σ width, trend score,
  last-bar return (``live_market_context_accumulator.py:244-297``),
* coverage gates — ≥40 fresh symbols AND ≥70% of the tracked universe
  (``live_market_context_accumulator.py:13-14,95-103,196-204``),
* RS-vs-BTC rewrite — ``return_pct - btc_return`` for every non-BTC symbol
  (``l.117-123``),
* masked aggregates — advancers/decliners, breadth, %>EMA, average
  trend/ATR/BB-width (``l.135-163``),
* derived scores — btc_regime_score, market_stress_score, long/short
  tailwinds with the reference's exact weights (``l.165-194``),
* macro regime ladder + transition event/strength/stable-since and
  per-symbol micro regime ladder + transitions
  (``regime_transitions.py:45-232``) against a carried previous state.

Scalar formulas are kept bit-identical to the reference (same clamps, same
weights) so the pandas-oracle parity tests can assert to float tolerance.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, MarketBuffer
from binquant_tpu.enums import (
    MarketRegimeCode,
    MarketTransitionCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.ops.indicators import true_range
from binquant_tpu.ops.rolling import (
    ewm_mean_last,
    rolling_mean_last,
    rolling_std_last,
)
from binquant_tpu.utils import jclamp, jnon_negative, jsafe_div, jsafe_pct

# Reference constants (live_market_context_accumulator.py:13-14,
# regime_transitions.py:23)
REQUIRED_FRESH_SYMBOLS = 40
MIN_COVERAGE_RATIO = 0.70
TRANSITION_STRENGTH_FLOOR = 0.08


class ContextConfig(NamedTuple):
    """Static gate thresholds (overridable for small-universe tests)."""

    required_fresh_symbols: int = REQUIRED_FRESH_SYMBOLS
    min_coverage_ratio: float = MIN_COVERAGE_RATIO


class SymbolFeatureArrays(NamedTuple):
    """Per-symbol feature batch, (S,) each. ``valid`` gates everything."""

    valid: jnp.ndarray  # bool — fresh & >=2 bars
    timestamp: jnp.ndarray  # int32 seconds of latest bar
    close: jnp.ndarray
    return_pct: jnp.ndarray
    ema20: jnp.ndarray
    ema50: jnp.ndarray
    above_ema20: jnp.ndarray  # bool
    above_ema50: jnp.ndarray  # bool
    trend_score: jnp.ndarray
    relative_strength_vs_btc: jnp.ndarray
    atr_pct: jnp.ndarray
    bb_width: jnp.ndarray
    micro_regime: jnp.ndarray  # int32 MicroRegimeCode, -1 where invalid
    micro_regime_strength: jnp.ndarray
    micro_transition: jnp.ndarray  # int32 MicroTransitionCode, -1 none
    micro_transition_strength: jnp.ndarray


class RegimeCarry(NamedTuple):
    """Cross-tick regime state (the reference's previous-context lookup).

    Two slots: the *previous* fields hold the last context from a STRICTLY
    older timestamp (the reference's ``_get_previous_context`` skips
    ``known_timestamp >= timestamp`` — transitions always anchor on the
    prior bucket), while the *stage* fields hold the latest evaluation of
    the current timestamp. Mid-bucket re-evaluations overwrite only the
    stage, so same-bucket refinements can't fire spurious transitions; the
    stage is promoted to previous when a strictly newer timestamp arrives.
    """

    has_prev: jnp.ndarray  # bool scalar
    market_regime: jnp.ndarray  # int32 scalar MarketRegimeCode
    market_scores: jnp.ndarray  # (4,) long/short/range/stress
    stable_since: jnp.ndarray  # int32 seconds
    micro_has_prev: jnp.ndarray  # (S,) bool
    micro_regime: jnp.ndarray  # (S,) int32
    micro_strength: jnp.ndarray  # (S,)
    stage_ts: jnp.ndarray  # int32 scalar, -1 = empty
    stage_valid: jnp.ndarray  # bool scalar
    stage_regime: jnp.ndarray  # int32 scalar
    stage_scores: jnp.ndarray  # (4,)
    stage_stable_since: jnp.ndarray  # int32
    stage_micro_valid: jnp.ndarray  # (S,) bool
    stage_micro_regime: jnp.ndarray  # (S,) int32
    stage_micro_strength: jnp.ndarray  # (S,)


class MarketContext(NamedTuple):
    """Device-side LiveMarketContext: scalars + per-symbol feature batch."""

    valid: jnp.ndarray  # bool — coverage gates passed
    timestamp: jnp.ndarray  # int32 seconds
    fresh_count: jnp.ndarray  # int32 (effective_count)
    total_tracked_symbols: jnp.ndarray  # int32
    coverage_ratio: jnp.ndarray
    btc_present: jnp.ndarray  # bool
    advancers: jnp.ndarray  # int32
    decliners: jnp.ndarray  # int32
    advancers_ratio: jnp.ndarray
    decliners_ratio: jnp.ndarray
    advancers_decliners_ratio: jnp.ndarray
    average_return: jnp.ndarray
    average_relative_strength_vs_btc: jnp.ndarray
    pct_above_ema20: jnp.ndarray
    pct_above_ema50: jnp.ndarray
    average_trend_score: jnp.ndarray
    average_atr_pct: jnp.ndarray
    average_bb_width: jnp.ndarray
    btc_return: jnp.ndarray
    btc_trend_score: jnp.ndarray
    btc_regime_score: jnp.ndarray
    market_stress_score: jnp.ndarray
    long_tailwind: jnp.ndarray
    short_tailwind: jnp.ndarray
    market_regime: jnp.ndarray  # int32 MarketRegimeCode
    previous_market_regime: jnp.ndarray  # int32, -1 none
    market_regime_transition: jnp.ndarray  # int32 MarketTransitionCode, -1 none
    market_regime_transition_strength: jnp.ndarray
    long_regime_score: jnp.ndarray
    short_regime_score: jnp.ndarray
    range_regime_score: jnp.ndarray
    stress_regime_score: jnp.ndarray
    regime_is_transitioning: jnp.ndarray  # bool
    regime_stable_since: jnp.ndarray  # int32 seconds
    features: SymbolFeatureArrays


def initial_regime_carry(num_symbols: int) -> RegimeCarry:
    return RegimeCarry(
        has_prev=jnp.asarray(False),
        market_regime=jnp.asarray(-1, dtype=jnp.int32),
        market_scores=jnp.zeros((4,), dtype=jnp.float32),
        stable_since=jnp.asarray(-1, dtype=jnp.int32),
        micro_has_prev=jnp.zeros((num_symbols,), dtype=bool),
        micro_regime=jnp.full((num_symbols,), -1, dtype=jnp.int32),
        micro_strength=jnp.zeros((num_symbols,), dtype=jnp.float32),
        stage_ts=jnp.asarray(-1, dtype=jnp.int32),
        stage_valid=jnp.asarray(False),
        stage_regime=jnp.asarray(-1, dtype=jnp.int32),
        stage_scores=jnp.zeros((4,), dtype=jnp.float32),
        stage_stable_since=jnp.asarray(-1, dtype=jnp.int32),
        stage_micro_valid=jnp.zeros((num_symbols,), dtype=bool),
        stage_micro_regime=jnp.full((num_symbols,), -1, dtype=jnp.int32),
        stage_micro_strength=jnp.zeros((num_symbols,), dtype=jnp.float32),
    )


# ---------------------------------------------------------------------------
# Per-symbol features (live_market_context_accumulator.py:244-297)
# ---------------------------------------------------------------------------


def _assemble_symbol_feature_values(
    latest_close: jnp.ndarray,
    prev_close: jnp.ndarray,
    times_last: jnp.ndarray,
    filled: jnp.ndarray,
    eligible: jnp.ndarray,
    ema20: jnp.ndarray,
    ema50: jnp.ndarray,
    atr: jnp.ndarray,
    mid: jnp.ndarray,
    std: jnp.ndarray,
) -> SymbolFeatureArrays:
    """Derived per-symbol features from last-bar indicator VALUES — shared
    by the full-window path, the incremental-carry path, and the backtest
    extension-invariant path so the three can only diverge in the
    (parity-tested) indicator readouts themselves. Shape-agnostic: (S,)
    per-tick inputs or (T, S) batched ones (compute_symbol_features_ext)."""
    bb_upper = mid + 2.0 * std
    bb_lower = mid - 2.0 * std
    atr_pct = jnp.where(latest_close != 0, jsafe_div(atr, latest_close), 0.0)
    bb_width = jnp.where(mid != 0, jsafe_div(bb_upper - bb_lower, jnp.abs(mid)), 0.0)
    trend_score = jnp.where(ema50 != 0, jsafe_div(ema20 - ema50, jnp.abs(ema50)), 0.0)

    valid = eligible & (filled >= 2)
    return SymbolFeatureArrays(
        valid=valid,
        timestamp=times_last,
        close=latest_close,
        return_pct=jsafe_pct(latest_close, prev_close),
        ema20=ema20,
        ema50=ema50,
        above_ema20=latest_close > ema20,
        above_ema50=latest_close > ema50,
        trend_score=trend_score,
        relative_strength_vs_btc=jnp.zeros_like(latest_close),
        atr_pct=atr_pct,
        bb_width=bb_width,
        micro_regime=jnp.full(latest_close.shape, -1, dtype=jnp.int32),
        micro_regime_strength=jnp.zeros_like(latest_close),
        micro_transition=jnp.full(latest_close.shape, -1, dtype=jnp.int32),
        micro_transition_strength=jnp.zeros_like(latest_close),
    )


def _assemble_symbol_features(
    buf: MarketBuffer,
    eligible: jnp.ndarray,
    ema20: jnp.ndarray,
    ema50: jnp.ndarray,
    atr: jnp.ndarray,
    mid: jnp.ndarray,
    std: jnp.ndarray,
) -> SymbolFeatureArrays:
    """Buffer-reading shim over :func:`_assemble_symbol_feature_values`."""
    close = buf.values[:, :, Field.CLOSE]
    return _assemble_symbol_feature_values(
        close[:, -1], close[:, -2], buf.times[:, -1], buf.filled,
        eligible, ema20, ema50, atr, mid, std,
    )


def compute_symbol_features(
    buf: MarketBuffer, eligible: jnp.ndarray
) -> SymbolFeatureArrays:
    """Batched `_compute_symbol_features` over every buffer row.

    ``eligible`` is the fresh mask; a row is valid when additionally it has
    ≥2 bars (the reference's ``len(history) < 2`` early-out). RS-vs-BTC is
    filled by :func:`compute_market_context` (it needs BTC's return).
    """
    close = buf.values[:, :, Field.CLOSE]
    high = buf.values[:, :, Field.HIGH]
    low = buf.values[:, :, Field.LOW]

    # last-value kernels: the per-tick path reads only the latest bar's
    # indicator values, so avoid materializing full-window series (O(W) per
    # row instead of O(W²) for the EWM matmuls).
    ema20 = ewm_mean_last(close, span=20, min_periods=1)
    ema50 = ewm_mean_last(close, span=50, min_periods=1)
    tr_tail = true_range(high[:, -15:], low[:, -15:], close[:, -15:])
    atr = rolling_mean_last(tr_tail, 14, min_periods=1)
    mid = rolling_mean_last(close, 20, min_periods=1)
    std = rolling_std_last(close, 20, min_periods=1, ddof=0)
    std = jnp.where(jnp.isfinite(std), std, 0.0)  # pandas .fillna(0.0)
    return _assemble_symbol_features(buf, eligible, ema20, ema50, atr, mid, std)


def compute_symbol_features_ext(
    ext_times: jnp.ndarray,  # (S, L) int32
    ext_vals: jnp.ndarray,  # (S, L, F)
    counts: jnp.ndarray,  # (T, S)
    filled0: jnp.ndarray,  # (S,)
    window: int,
    eligible: jnp.ndarray,  # (T, S) fresh & tracked per tick
) -> SymbolFeatureArrays:
    """T ticks of :func:`compute_symbol_features` from ONE pass over the
    backtest's (S, L = W + N) extended buffers (leaves (T, S)-leading).

    Same numeric contract as ``compute_feature_pack_ext``: the derived
    assembly is elementwise-identical; the indicator readouts anchor at
    the series start instead of each view's window start (f32-ulp for the
    rolling moments, ``(1-alpha)^W``-scale for the EMAs). The view path's
    one structural quirk carries over exactly: its 15-bar TR tail's first
    element (the prev-close-outside-slice h-l fallback) is excluded by the
    trailing-14 mean, so the consumed TR positions match the full-series
    true_range here."""
    from binquant_tpu.ops.rolling import ewm_mean, rolling_mean, rolling_std
    from binquant_tpu.strategies.features import ext_gather

    close = ext_vals[:, :, Field.CLOSE]
    high = ext_vals[:, :, Field.HIGH]
    low = ext_vals[:, :, Field.LOW]
    last = (counts + (window - 1)).astype(jnp.int32)
    g = lambda s: ext_gather(s, last)

    ema20 = g(ewm_mean(close, span=20, min_periods=1))
    ema50 = g(ewm_mean(close, span=50, min_periods=1))
    tr = true_range(high, low, close)
    atr = g(rolling_mean(tr, 14, min_periods=1))
    mid = g(rolling_mean(close, 20, min_periods=1))
    std = g(rolling_std(close, 20, min_periods=1, ddof=0))
    std = jnp.where(jnp.isfinite(std), std, 0.0)
    filled = jnp.minimum(filled0[None, :] + counts, window).astype(jnp.int32)
    return _assemble_symbol_feature_values(
        g(close), ext_gather(close, last - 1), ext_gather(ext_times, last),
        filled, eligible, ema20, ema50, atr, mid, std,
    )


def symbol_features_from_carry(
    buf: MarketBuffer, carry, eligible: jnp.ndarray, stale: jnp.ndarray
) -> SymbolFeatureArrays:
    """The same symbol features read from the 15m ``FeatureCarry`` in O(1)
    bytes per symbol (the incremental tick's path). ``min_periods=1``
    readouts of the SAME carried sums the feature pack uses — no second
    advance. Rows flagged ``stale`` (carry desynced from the window) are
    excluded from ``valid`` so they cannot feed the market aggregates with
    stale values before the host's full-recompute resync lands."""
    from binquant_tpu.ops.incremental import (
        ewm_value,
        moment_mean,
        moment_std,
    )
    from binquant_tpu.strategies.features import ATR_WINDOW, BB_WINDOW

    ema20 = ewm_value(carry.ema20, 1)
    ema50 = ewm_value(carry.ema50, 1)
    atr = moment_mean(carry.tr_m, ATR_WINDOW, min_periods=1)
    mid = moment_mean(carry.close_m, BB_WINDOW, min_periods=1)
    std = moment_std(carry.close_m, BB_WINDOW, min_periods=1, ddof=0)
    std = jnp.where(jnp.isfinite(std), std, 0.0)
    feats = _assemble_symbol_features(
        buf, eligible & ~stale, ema20, ema50, atr, mid, std
    )
    return feats


def _masked_mean(x: jnp.ndarray, mask: jnp.ndarray, count: jnp.ndarray) -> jnp.ndarray:
    return jsafe_div(jnp.sum(jnp.where(mask, x, 0.0)), jnp.maximum(count, 1))


# ---------------------------------------------------------------------------
# Macro regime ladder + transitions (regime_transitions.py:45-160)
# ---------------------------------------------------------------------------


def _market_transition_event(
    prev_regime: jnp.ndarray, regime: jnp.ndarray
) -> jnp.ndarray:
    """Vector decision table of `_market_transition_event` (l.234-249)."""
    T = MarketTransitionCode
    R = MarketRegimeCode
    return jnp.where(
        regime == R.HIGH_STRESS,
        T.STRESS_SPIKE,
        jnp.where(
            (prev_regime == R.HIGH_STRESS) & (regime != R.HIGH_STRESS),
            T.STRESS_RELIEF,
            jnp.where(
                regime == R.TREND_UP,
                T.ENTERED_TREND_UP,
                jnp.where(
                    regime == R.TREND_DOWN,
                    T.ENTERED_TREND_DOWN,
                    jnp.where(regime == R.RANGE, T.ENTERED_RANGE, T.LOST_REGIME_EDGE),
                ),
            ),
        ),
    ).astype(jnp.int32)


def _micro_transition_event(
    prev_regime: jnp.ndarray, regime: jnp.ndarray
) -> jnp.ndarray:
    """Vector decision table of `_symbol_transition_event` (l.251-278)."""
    T = MicroTransitionCode
    R = MicroRegimeCode
    from_range_like = (prev_regime == R.RANGE) | (prev_regime == R.TRANSITIONAL)
    return jnp.where(
        regime == R.VOLATILE,
        T.VOLATILITY_EXPANSION,
        jnp.where(
            from_range_like & (regime == R.TREND_UP),
            T.BREAKOUT_UP,
            jnp.where(
                from_range_like & (regime == R.TREND_DOWN),
                T.BREAKDOWN,
                jnp.where(
                    (prev_regime == R.TREND_DOWN) & (regime == R.TREND_UP),
                    T.RECOVERY,
                    jnp.where(
                        (prev_regime == R.TREND_UP) & (regime == R.RANGE),
                        T.MEAN_REVERSION,
                        jnp.where(
                            regime == R.TREND_UP,
                            T.ENTERED_TREND_UP,
                            jnp.where(
                                regime == R.TREND_DOWN,
                                T.ENTERED_TREND_DOWN,
                                jnp.where(
                                    regime == R.RANGE,
                                    T.ENTERED_RANGE,
                                    T.ENTERED_TRANSITIONAL,
                                ),
                            ),
                        ),
                    ),
                ),
            ),
        ),
    ).astype(jnp.int32)


def _annotate_market_regime(
    ctx: dict[str, jnp.ndarray], carry: RegimeCarry, timestamp: jnp.ndarray
) -> dict[str, jnp.ndarray]:
    """Macro scores → regime ladder → transition annotation (l.45-160)."""
    R = MarketRegimeCode
    breadth_score = jclamp((ctx["advancers_ratio"] - 0.5) / 0.25)
    trend_participation = jclamp(
        ((ctx["pct_above_ema20"] + ctx["pct_above_ema50"]) - 1.0) * 1.4
    )
    avg_trend_bias = jclamp(ctx["average_trend_score"] * 20.0)
    calm_score = jclamp(1.0 - ctx["market_stress_score"], 0.0, 1.0)

    long_score = jclamp(
        0.3 * jnon_negative(ctx["long_tailwind"])
        + 0.24 * jnon_negative(ctx["btc_regime_score"])
        + 0.2 * jnon_negative(breadth_score)
        + 0.14 * jnon_negative(trend_participation)
        + 0.12 * calm_score,
        0.0,
        1.0,
    )
    short_score = jclamp(
        0.28 * jnon_negative(ctx["short_tailwind"])
        + 0.24 * jnon_negative(-ctx["btc_regime_score"])
        + 0.16 * jnon_negative(-breadth_score)
        + 0.1 * jnon_negative(-avg_trend_bias)
        + 0.22 * ctx["market_stress_score"],
        0.0,
        1.0,
    )
    range_score = jclamp(
        0.32 * (1.0 - jnp.abs(breadth_score))
        + 0.22 * (1.0 - jnp.abs(ctx["btc_regime_score"]))
        + 0.24 * calm_score
        + 0.12 * (1.0 - jnp.abs(avg_trend_bias))
        + 0.1 * (1.0 - jnp.abs(ctx["long_tailwind"] - ctx["short_tailwind"])),
        0.0,
        1.0,
    )
    stress_score = jclamp(
        0.7 * ctx["market_stress_score"]
        + 0.18 * jnon_negative(-ctx["average_return"] * 20.0)
        + 0.12 * jnon_negative(short_score - long_score),
        0.0,
        1.0,
    )

    dominant = jnp.maximum(
        jnp.maximum(long_score, short_score), jnp.maximum(range_score, stress_score)
    )
    regime = jnp.where(
        (stress_score >= 0.5) & (ctx["market_stress_score"] >= 0.35),
        R.HIGH_STRESS,
        jnp.where(
            (long_score >= 0.44) & (long_score >= short_score + 0.08),
            R.TREND_UP,
            jnp.where(
                (short_score >= 0.42) & (short_score >= long_score + 0.08),
                R.TREND_DOWN,
                jnp.where(range_score >= 0.5, R.RANGE, R.TRANSITIONAL),
            ),
        ),
    ).astype(jnp.int32)

    prev_regime = jnp.where(carry.has_prev, carry.market_regime, -1).astype(jnp.int32)
    changed = carry.has_prev & (prev_regime != regime)

    scores = jnp.stack([long_score, short_score, range_score, stress_score])
    max_delta = jnp.max(jnp.abs(scores - carry.market_scores))
    transition_strength = jnp.where(
        changed, jclamp(dominant + max_delta - 0.25, 0.0, 1.0), 0.0
    )
    transition = jnp.where(
        changed, _market_transition_event(prev_regime, regime), -1
    ).astype(jnp.int32)
    regime_is_transitioning = (regime == R.TRANSITIONAL) | (
        changed & (transition_strength >= TRANSITION_STRENGTH_FLOOR)
    )

    # stable_since anchoring (l.151-160): reset unless the carried regime is
    # unchanged and had a valid anchor.
    keep_anchor = carry.has_prev & (prev_regime == regime) & (carry.stable_since >= 0)
    stable_since = jnp.where(keep_anchor, carry.stable_since, timestamp).astype(
        jnp.int32
    )

    ctx.update(
        market_regime=regime,
        previous_market_regime=prev_regime,
        market_regime_transition=transition,
        market_regime_transition_strength=transition_strength,
        long_regime_score=long_score,
        short_regime_score=short_score,
        range_regime_score=range_score,
        stress_regime_score=stress_score,
        regime_is_transitioning=regime_is_transitioning,
        regime_stable_since=stable_since,
    )
    return ctx


def _annotate_micro_regimes(
    feats: SymbolFeatureArrays, carry: RegimeCarry
) -> SymbolFeatureArrays:
    """Per-symbol regime ladder + transitions, batched (l.162-232)."""
    R = MicroRegimeCode
    up_score = jclamp(
        0.45 * jnon_negative(feats.trend_score * 30.0)
        + 0.2 * feats.above_ema20.astype(jnp.float32)
        + 0.15 * feats.above_ema50.astype(jnp.float32)
        + 0.2 * jnon_negative(feats.relative_strength_vs_btc * 20.0),
        0.0,
        1.0,
    )
    down_score = jclamp(
        0.45 * jnon_negative(-feats.trend_score * 30.0)
        + 0.2 * (~feats.above_ema20).astype(jnp.float32)
        + 0.15 * (~feats.above_ema50).astype(jnp.float32)
        + 0.2 * jnon_negative(-feats.relative_strength_vs_btc * 20.0),
        0.0,
        1.0,
    )
    range_score = jclamp(
        0.38 * (1.0 - jnp.minimum(jnp.abs(feats.trend_score) * 30.0, 1.0))
        + 0.34 * (1.0 - jnp.minimum(feats.bb_width / 0.08, 1.0))
        + 0.28 * (1.0 - jnp.minimum(feats.atr_pct / 0.04, 1.0)),
        0.0,
        1.0,
    )
    volatile_score = jclamp(
        0.55 * jnp.minimum(feats.atr_pct / 0.05, 1.0)
        + 0.45 * jnp.minimum(feats.bb_width / 0.12, 1.0),
        0.0,
        1.0,
    )

    strength = jnp.maximum(
        jnp.maximum(up_score, down_score), jnp.maximum(range_score, volatile_score)
    )
    regime = jnp.where(
        (volatile_score >= 0.72) & (jnp.abs(feats.return_pct) >= 0.015),
        R.VOLATILE,
        jnp.where(
            (up_score >= 0.52) & (up_score >= down_score + 0.1),
            R.TREND_UP,
            jnp.where(
                (down_score >= 0.52) & (down_score >= up_score + 0.1),
                R.TREND_DOWN,
                jnp.where(range_score >= 0.5, R.RANGE, R.TRANSITIONAL),
            ),
        ),
    ).astype(jnp.int32)

    had_prev = carry.micro_has_prev & (carry.micro_regime >= 0)
    changed = had_prev & (carry.micro_regime != regime)
    transition = jnp.where(
        changed, _micro_transition_event(carry.micro_regime, regime), -1
    ).astype(jnp.int32)
    transition_strength = jnp.where(
        changed,
        jclamp(strength + jnp.abs(strength - carry.micro_strength) - 0.25, 0.0, 1.0),
        0.0,
    )

    return feats._replace(
        micro_regime=jnp.where(feats.valid, regime, -1).astype(jnp.int32),
        micro_regime_strength=jnp.where(feats.valid, strength, 0.0),
        micro_transition=jnp.where(feats.valid, transition, -1).astype(jnp.int32),
        micro_transition_strength=jnp.where(feats.valid, transition_strength, 0.0),
    )


# ---------------------------------------------------------------------------
# Full context build (_build_context, l.95-242)
# ---------------------------------------------------------------------------


@jax.jit
def compute_market_context(
    buf: MarketBuffer,
    fresh: jnp.ndarray,  # (S,) bool — latest bar == evaluated tick
    tracked: jnp.ndarray,  # (S,) bool — registry-occupied rows
    btc_row: jnp.ndarray,  # int32 scalar; -1 when BTC untracked
    timestamp: jnp.ndarray,  # int32 seconds tick being evaluated
    carry: RegimeCarry,
    cfg: ContextConfig = ContextConfig(),
    feats: SymbolFeatureArrays | None = None,
) -> tuple[MarketContext, RegimeCarry]:
    """One tick's LiveMarketContext for the whole market + updated carry.

    When the coverage gates fail, ``context.valid`` is False and the carry is
    returned unchanged (the reference returns None and keeps the previous
    context as the transition anchor).

    ``feats`` lets the incremental tick path inject symbol features read
    from carried indicator state (:func:`symbol_features_from_carry`)
    instead of the full-window recompute; None = compute here.
    """
    S = buf.capacity
    if feats is None:
        feats = compute_symbol_features(buf, fresh & tracked)

    # --- BTC features: taken from its row even when BTC itself is not fresh
    # (the reference computes them from the store regardless, l.105-106).
    btc_ok = (btc_row >= 0) & (btc_row < S)
    safe_btc = jnp.clip(btc_row, 0, S - 1)
    btc_has_bars = buf.filled[safe_btc] >= 2
    btc_present = btc_ok & btc_has_bars
    btc_return = jnp.where(btc_present, feats.return_pct[safe_btc], 0.0)
    btc_trend = jnp.where(btc_present, feats.trend_score[safe_btc], 0.0)

    # --- RS-vs-BTC rewrite (l.117-123): every symbol except BTC itself.
    is_btc_row = jnp.arange(S) == safe_btc
    rs = jnp.where(
        btc_present & ~is_btc_row, feats.return_pct - btc_return, 0.0
    )
    feats = feats._replace(relative_strength_vs_btc=rs)

    # --- masked aggregates (l.135-163)
    m = feats.valid
    effective = jnp.sum(m.astype(jnp.int32))
    total_tracked = jnp.sum(tracked.astype(jnp.int32))
    total_tracked = jnp.maximum(total_tracked, effective)  # l.196

    advancers = jnp.sum((m & (feats.return_pct > 0)).astype(jnp.int32))
    decliners = jnp.sum((m & (feats.return_pct < 0)).astype(jnp.int32))
    advancers_ratio = jsafe_div(advancers, jnp.maximum(effective, 1))
    decliners_ratio = jsafe_div(decliners, jnp.maximum(effective, 1))
    adv_dec_ratio = jsafe_div(advancers, jnp.maximum(decliners, 1))

    average_return = _masked_mean(feats.return_pct, m, effective)
    average_rs = _masked_mean(feats.relative_strength_vs_btc, m, effective)
    pct_above_ema20 = _masked_mean(feats.above_ema20.astype(jnp.float32), m, effective)
    pct_above_ema50 = _masked_mean(feats.above_ema50.astype(jnp.float32), m, effective)
    average_trend = _masked_mean(feats.trend_score, m, effective)
    average_atr_pct = _masked_mean(feats.atr_pct, m, effective)
    average_bb_width = _masked_mean(feats.bb_width, m, effective)

    # --- derived scores (l.165-194)
    breadth_balance = jclamp((advancers_ratio - decliners_ratio) * 1.5)
    ema_balance = jclamp(((pct_above_ema20 + pct_above_ema50) - 1.0) * 1.5)
    average_return_score = jclamp(average_return * 12.0)
    btc_regime_score = jnp.where(
        btc_present, jclamp(btc_return * 12.0 + btc_trend * 6.0), 0.0
    )
    stress_from_volatility = jclamp((average_atr_pct - 0.02) * 12.0, 0.0, 1.0)
    stress_from_bandwidth = jclamp((average_bb_width - 0.08) * 4.0, 0.0, 1.0)
    stress_from_selloff = jclamp((-average_return) * 16.0, 0.0, 1.0)
    market_stress_score = (
        0.4 * stress_from_volatility
        + 0.25 * stress_from_bandwidth
        + 0.35 * stress_from_selloff
    )
    long_tailwind = jclamp(
        0.4 * breadth_balance
        + 0.2 * ema_balance
        + 0.25 * btc_regime_score
        + 0.15 * average_return_score
        - 0.35 * market_stress_score
    )
    short_tailwind = jclamp(
        -0.35 * breadth_balance
        - 0.15 * ema_balance
        - 0.2 * btc_regime_score
        - 0.15 * average_return_score
        + 0.45 * market_stress_score
    )

    # --- coverage gates (l.95-103, 196-204)
    required = jnp.maximum(
        cfg.required_fresh_symbols,
        jnp.ceil(total_tracked * cfg.min_coverage_ratio).astype(jnp.int32),
    )
    coverage_ratio = jsafe_div(effective, jnp.maximum(total_tracked, 1))
    valid = (
        (effective >= required)
        & (total_tracked > 0)
        & (effective >= cfg.required_fresh_symbols)
        & (coverage_ratio >= cfg.min_coverage_ratio)
    )

    ctx: dict[str, jnp.ndarray] = dict(
        advancers_ratio=advancers_ratio,
        pct_above_ema20=pct_above_ema20,
        pct_above_ema50=pct_above_ema50,
        average_trend_score=average_trend,
        average_return=average_return,
        market_stress_score=market_stress_score,
        btc_regime_score=btc_regime_score,
        long_tailwind=long_tailwind,
        short_tailwind=short_tailwind,
    )
    # Promote the staged context to "previous" only when this evaluation is
    # strictly newer than the staged timestamp; same-timestamp refinements
    # keep comparing against the prior bucket.
    newer = timestamp.astype(jnp.int32) > carry.stage_ts
    promote = newer & carry.stage_valid
    promote_micro = newer & carry.stage_micro_valid
    eff_carry = carry._replace(
        has_prev=carry.has_prev | promote,
        market_regime=jnp.where(promote, carry.stage_regime, carry.market_regime),
        market_scores=jnp.where(promote, carry.stage_scores, carry.market_scores),
        stable_since=jnp.where(
            promote, carry.stage_stable_since, carry.stable_since
        ),
        micro_has_prev=carry.micro_has_prev | promote_micro,
        micro_regime=jnp.where(
            promote_micro, carry.stage_micro_regime, carry.micro_regime
        ),
        micro_strength=jnp.where(
            promote_micro, carry.stage_micro_strength, carry.micro_strength
        ),
    )

    ctx = _annotate_market_regime(ctx, eff_carry, timestamp)
    feats = _annotate_micro_regimes(feats, eff_carry)

    context = MarketContext(
        valid=valid,
        timestamp=timestamp.astype(jnp.int32),
        fresh_count=effective,
        total_tracked_symbols=total_tracked,
        coverage_ratio=coverage_ratio,
        btc_present=btc_present,
        advancers=advancers,
        decliners=decliners,
        advancers_ratio=advancers_ratio,
        decliners_ratio=decliners_ratio,
        advancers_decliners_ratio=adv_dec_ratio,
        average_return=average_return,
        average_relative_strength_vs_btc=average_rs,
        pct_above_ema20=pct_above_ema20,
        pct_above_ema50=pct_above_ema50,
        average_trend_score=average_trend,
        average_atr_pct=average_atr_pct,
        average_bb_width=average_bb_width,
        btc_return=btc_return,
        btc_trend_score=btc_trend,
        btc_regime_score=btc_regime_score,
        market_stress_score=market_stress_score,
        long_tailwind=long_tailwind,
        short_tailwind=short_tailwind,
        market_regime=ctx["market_regime"],
        previous_market_regime=ctx["previous_market_regime"],
        market_regime_transition=ctx["market_regime_transition"],
        market_regime_transition_strength=ctx["market_regime_transition_strength"],
        long_regime_score=ctx["long_regime_score"],
        short_regime_score=ctx["short_regime_score"],
        range_regime_score=ctx["range_regime_score"],
        stress_regime_score=ctx["stress_regime_score"],
        regime_is_transitioning=ctx["regime_is_transitioning"],
        regime_stable_since=ctx["regime_stable_since"],
        features=feats,
    )

    # --- carry update: the promoted previous slots persist untouched; only
    # the STAGE is overwritten by this evaluation (and only when valid —
    # reference: None contexts are never stored, l.101-103).
    new_scores = jnp.stack(
        [
            ctx["long_regime_score"],
            ctx["short_regime_score"],
            ctx["range_regime_score"],
            ctx["stress_regime_score"],
        ]
    )
    micro_update = valid & feats.valid
    ts32 = timestamp.astype(jnp.int32)
    new_carry = eff_carry._replace(
        stage_ts=jnp.where(newer, ts32, carry.stage_ts).astype(jnp.int32),
        stage_valid=jnp.where(newer, valid, carry.stage_valid | valid),
        stage_regime=jnp.where(
            valid,
            ctx["market_regime"],
            jnp.where(newer, jnp.int32(-1), carry.stage_regime),
        ).astype(jnp.int32),
        stage_scores=jnp.where(
            valid, new_scores, jnp.where(newer, 0.0, carry.stage_scores)
        ),
        stage_stable_since=jnp.where(
            valid,
            ctx["regime_stable_since"],
            jnp.where(newer, jnp.int32(-1), carry.stage_stable_since),
        ).astype(jnp.int32),
        stage_micro_valid=jnp.where(
            newer, micro_update, carry.stage_micro_valid | micro_update
        ),
        stage_micro_regime=jnp.where(
            micro_update,
            feats.micro_regime,
            jnp.where(newer, jnp.int32(-1), carry.stage_micro_regime),
        ).astype(jnp.int32),
        stage_micro_strength=jnp.where(
            micro_update,
            feats.micro_regime_strength,
            jnp.where(newer, 0.0, carry.stage_micro_strength),
        ),
    )
    return context, new_carry
