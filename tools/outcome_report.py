#!/usr/bin/env python
"""Render the signal-outcome scoreboard from the JSONL event log.

The outcome tracker (``binquant_tpu/obs/outcomes.py``) emits one
``signal_outcome`` event per matured (signal, horizon) pair — joinable to
its ``signal`` event by trace_id/tick_seq. This tool folds an event log
back into the per-(strategy, horizon) scoreboard without any service in
the loop (golden-pinned like trace_report/scenario_report — keep format
changes deliberate):

    python tools/outcome_report.py /tmp/bqt_outcome_events.jsonl
    python tools/outcome_report.py events.jsonl --strategy mean_reversion_fade
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

# runnable as a plain script: the repo root is the tool dir's parent
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def load_outcome_events(path: str | Path) -> list[dict]:
    """All ``signal_outcome`` events, in file order; corrupt lines (a
    torn write at rotation) are skipped, not fatal."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if record.get("event") == "signal_outcome":
                out.append(record)
    return out


def aggregate(events: list[dict]) -> dict:
    """(strategy, horizon) scoreboard cells from raw events — folded
    through the live tracker's own ``_Agg`` cell (one fold, one rounding;
    ``obs.outcomes`` is importable without jax, the obs-package idiom),
    so this report can never drift from the /healthz scoreboard."""
    from binquant_tpu.obs.outcomes import _Agg

    cells: dict[tuple[str, int], _Agg] = {}
    truncated = 0
    for e in events:
        if e.get("truncated"):
            truncated += 1
            continue
        key = (str(e.get("strategy", "?")), int(e.get("horizon", 0)))
        cells.setdefault(key, _Agg()).add(
            float(e.get("fwd_ret", 0.0)),
            float(e.get("mae", 0.0)),
            float(e.get("mfe", 0.0)),
        )
    return {"cells": cells, "truncated": truncated}


def render_report(events: list[dict]) -> str:
    agg = aggregate(events)
    cells = agg["cells"]
    matured = sum(c.n for c in cells.values())
    lines = [
        f"signal-outcome scoreboard: {matured} matured pairs "
        f"({agg['truncated']} truncated)"
    ]
    header = (
        f"{'strategy':<28} {'h':>4} {'n':>5} {'hit%':>6} "
        f"{'avg_fwd':>9} {'avg_mae':>9} {'avg_mfe':>9} {'worst_mae':>10}"
    )
    lines.append(header)
    for (strategy, h), c in sorted(cells.items()):
        lines.append(
            f"{strategy:<28} {h:>4} {c.n:>5} "
            f"{100.0 * c.hits / c.n:>5.1f}% "
            f"{c.sum_fwd / c.n:>+9.4f} {c.sum_mae / c.n:>+9.4f} "
            f"{c.sum_mfe / c.n:>+9.4f} {c.worst_mae:>+10.4f}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("log", help="JSONL event log (BQT_EVENT_LOG file)")
    parser.add_argument(
        "--strategy", help="render only this strategy's scoreboard rows"
    )
    parser.add_argument(
        "--json", action="store_true",
        help="dump the aggregated cells as JSON instead of the table",
    )
    args = parser.parse_args(argv)

    events = load_outcome_events(args.log)
    if args.strategy:
        events = [e for e in events if e.get("strategy") == args.strategy]
    if not events:
        print(f"no signal_outcome events in {args.log}", file=sys.stderr)
        return 1
    if args.json:
        agg = aggregate(events)
        out = {
            f"{s}@{h}": c.as_dict()
            for (s, h), c in sorted(agg["cells"].items())
        }
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    print(render_report(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
