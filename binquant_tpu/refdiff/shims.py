"""In-process stand-ins for the reference's third-party imports.

The reference codebase (``/root/reference``) has four dependencies that are
not installed here: ``pybinbot`` (the platform SDK, an external PyPI
package), ``pandera``, ``python-telegram-bot`` and ``python-dotenv``. Its
own test suite cuts the network seam at exactly this boundary
(``/root/reference/tests/conftest.py:34-49`` patches the four ``BinbotApi``
constructors); this module cuts the same seam for the differential harness,
but with functional fakes instead of Mocks so the full provider chain runs.

What the shim provides and where it comes from:

* pydantic models / enums / helpers (``SignalsConsumer``, ``BotBase``,
  ``SymbolModel``, ``Position``, ``MarketType``, ``round_numbers``, ...)
  — re-exported from this repo's own pybinbot-surface replica
  (``binquant_tpu.schemas`` / ``enums`` / ``utils``, SURVEY.md §2.8), so
  the differential run doubles as a compatibility test of that replica.
* ``Candles`` / ``Indicators`` — re-implemented here from the surface
  documented in SURVEY.md §2.8 (``producers/context_evaluator.py:228-251``
  consumes them). pybinbot's source is not in the environment, so these
  formulas are the transcription's (shared with ``binquant_tpu/oracle``)
  — NOT independently verified by the differential. Everything under
  ``/root/reference`` itself executes verbatim.

UNVERIFIED-SEMANTICS LEDGER — shim decisions with NO external oracle
(pybinbot 1.11.5 is an absent PyPI dep and the reference's own tests
don't cover them; if real pybinbot differs, production behavior differs
from the replica AND this differential cannot see it):

* ``Indicators.set_twap`` horizon (window=20): chosen to match the
  transcription's 20-hour TWAP (oracle ``_twap``); the real default is
  unknown.
* ``Indicators.set_supertrend``'s ``df["supertrend"]`` column: pinned as
  the BOOLEAN confirmed-uptrend flag (False during ATR warm-up) because
  its only consumer truth-tests it (``coinrule.py:160``); if the real
  SDK stores the band line there, the production gate is always-truthy.
* ``Candles.post_process`` keeps enrichment warm-up NaNs (pins the
  MA-``.size`` sufficiency gates at 100 raw bars); a dropna variant
  would shift dispatch eligibility by 99 bars. The dormant dispatch
  wrapper documents where each interpretation is applied.
* network clients (``BinbotApi``, ``KucoinApi``, ``KucoinFutures``,
  ``BinanceApi``) — recording fakes wired to the active
  :class:`binquant_tpu.refdiff.driver.ReferenceHub`.
"""

from __future__ import annotations

import html as _html
import logging
import os
import sys
import types
from enum import Enum
from pathlib import Path

import numpy as np
import pandas as pd

REFERENCE_PATH = os.environ.get("BQT_REFERENCE_PATH", "/root/reference")

# The active market-data hub (set by driver.run_replay_reference); module
# global so the provider-constructed clients (KlinesProvider builds its own
# BinbotApi/KucoinFutures — klines_provider.py:42-53) can find it.
_ACTIVE_HUB = None


def set_active_hub(hub) -> None:
    global _ACTIVE_HUB
    _ACTIVE_HUB = hub


def reference_available() -> bool:
    return (Path(REFERENCE_PATH) / "producers" / "context_evaluator.py").is_file()


# ---------------------------------------------------------------------------
# pybinbot data layer: Candles + Indicators (SURVEY.md §2.8)
# ---------------------------------------------------------------------------

# UI kline row layout (klines_provider.py:130-149: "open_time, open, high,
# low, close, volume, close_time" first seven columns)
_UI_COLUMNS = [
    "open_time",
    "open",
    "high",
    "low",
    "close",
    "volume",
    "close_time",
    "quote_asset_volume",
    "number_of_trades",
    "taker_buy_base_asset_volume",
    "taker_buy_quote_asset_volume",
]

_OHLC_REQUIRED = list(_UI_COLUMNS)


class Candles:
    """Raw UI-kline rows → validated OHLC DataFrame.

    Behavior pinned by the reference's own ``tests/test_ohlc.py`` (missing
    columns / coercion / all-NaN errors) and its call sites
    (``context_evaluator.py:352-421``: pre_process → enrichment →
    post_process, plus the 15m→1h resample)."""

    def __init__(self, exchange=None, candles=None) -> None:
        self.exchange = exchange
        self.candles = list(candles) if candles else []

    def pre_process(self) -> pd.DataFrame:
        if not self.candles:
            return pd.DataFrame()
        width = max(len(row) for row in self.candles)
        cols = _UI_COLUMNS[: min(width, len(_UI_COLUMNS))]
        rows = [list(row[: len(cols)]) for row in self.candles]
        df = pd.DataFrame(rows, columns=cols)
        for missing in _UI_COLUMNS[len(cols):]:
            df[missing] = 0.0
        df = self.ensure_ohlc(df)
        return df.sort_values("open_time").reset_index(drop=True)

    def ensure_ohlc(self, df: pd.DataFrame) -> pd.DataFrame:
        missing = [c for c in _OHLC_REQUIRED if c not in df.columns]
        if missing:
            raise ValueError(f"missing required columns: {', '.join(missing)}")
        df = df.copy()
        for col in _OHLC_REQUIRED:
            coerced = pd.to_numeric(df[col], errors="coerce")
            if len(coerced) and coerced.isna().all() and df[col].notna().any():
                raise ValueError(f"column {col} is entirely non-numeric")
            df[col] = coerced
        df["open_time"] = df["open_time"].astype("int64")
        df["close_time"] = df["close_time"].astype("int64")
        return df

    def post_process(self, df: pd.DataFrame) -> pd.DataFrame:
        # Enrichment leaves NaN warm-up rows in place; the evaluator's
        # MA-sufficiency gates (`context_evaluator.py:361-365,424-429`) use
        # `.size`, i.e. row COUNT — so post-processing must not drop them.
        return df.reset_index(drop=True)

    def resample(self, df: pd.DataFrame, interval: str = "1h") -> pd.DataFrame:
        if df.empty:
            return pd.DataFrame()
        step_ms = {"1h": 3_600_000, "4h": 14_400_000, "6h": 21_600_000}[interval]
        bucket = df["open_time"] // step_ms
        g = df.groupby(bucket)
        out = pd.DataFrame(
            {
                "open_time": g["open_time"].first() // step_ms * step_ms,
                "open": g["open"].first(),
                "high": g["high"].max(),
                "low": g["low"].min(),
                "close": g["close"].last(),
                "close_time": g["close_time"].last(),
                "volume": g["volume"].sum(),
                "quote_asset_volume": g["quote_asset_volume"].sum(),
                "number_of_trades": g["number_of_trades"].sum(),
                "taker_buy_base_asset_volume": g["taker_buy_base_asset_volume"].sum(),
                "taker_buy_quote_asset_volume": g["taker_buy_quote_asset_volume"].sum(),
            }
        )
        return out.reset_index(drop=True).sort_values("open_time").reset_index(drop=True)


class Indicators:
    """The enrichment columns ``indicators_enrichment`` expects
    (``context_evaluator.py:228-251``). Formulas shared with the
    transcription (``binquant_tpu/oracle/evaluator.py`` cites each)."""

    @staticmethod
    def moving_averages(df: pd.DataFrame, window: int = 7) -> pd.DataFrame:
        df[f"ma_{window}"] = df["close"].rolling(window).mean()
        return df

    @staticmethod
    def macd(df: pd.DataFrame) -> pd.DataFrame:
        close = df["close"]
        ema12 = close.ewm(span=12, adjust=False, min_periods=1).mean()
        ema26 = close.ewm(span=26, adjust=False, min_periods=1).mean()
        df["macd"] = ema12 - ema26
        df["macd_signal"] = df["macd"].ewm(span=9, adjust=False, min_periods=1).mean()
        return df

    @staticmethod
    def rsi(df: pd.DataFrame) -> pd.DataFrame:
        # Simple-rolling-mean RSI(14) — the shared-column variant MRF's
        # docstring contrasts with its inline Wilder RSI
        # (mean_reversion_fade.py:44-48); oracle `_rsi14_sma`.
        delta = df["close"].diff()
        avg_gain = delta.clip(lower=0).rolling(14, min_periods=14).mean()
        avg_loss = (-delta).clip(upper=None, lower=0).rolling(14, min_periods=14).mean()
        denom = avg_gain + avg_loss
        df["rsi"] = (100.0 * avg_gain / denom).where(denom != 0, 50.0)
        return df

    @staticmethod
    def mfi(df: pd.DataFrame, window: int = 14) -> float:
        # Money-flow index of the last `window` bars (oracle `_pt`).
        tp = (df["high"] + df["low"] + df["close"]) / 3.0
        flow = tp * df["volume"]
        tp_delta = tp.diff()
        last = tp_delta.tail(window)
        if len(last) < window or last.isna().any():
            return float("nan")
        pos = float(flow.tail(window)[last > 0].sum())
        neg = float(flow.tail(window)[last < 0].sum())
        total = pos + neg
        return 100.0 * pos / total if total != 0 else 50.0

    @staticmethod
    def ma_spreads(df: pd.DataFrame) -> pd.DataFrame:
        for fast, slow in ((7, 25), (25, 100)):
            df[f"ma_{fast}_{slow}_spread"] = (
                df[f"ma_{fast}"] - df[f"ma_{slow}"]
            ) / df[f"ma_{slow}"].abs().replace(0, np.nan)
        return df

    @staticmethod
    def bollinguer_spreads(df: pd.DataFrame, window: int = 20) -> pd.DataFrame:
        close = df["close"]
        mid = close.rolling(window).mean()
        std = close.rolling(window).std(ddof=0)
        df["bb_mid"] = mid
        df["bb_upper"] = mid + 2 * std
        df["bb_lower"] = mid - 2 * std
        return df

    @staticmethod
    def set_twap(df: pd.DataFrame, window: int = 20) -> pd.DataFrame:
        # rolling mean of OHLC bar averages; the TWAP sniper consumes it
        # on 1h bars with a 20-hour horizon (oracle `_twap`)
        bar_avg = (df["open"] + df["high"] + df["low"] + df["close"]) / 4.0
        df["twap"] = bar_avg.rolling(window, min_periods=1).mean()
        return df

    @staticmethod
    def atr(df: pd.DataFrame, window: int = 14) -> pd.DataFrame:
        prev_close = df["close"].shift(1)
        tr = pd.concat(
            [
                df["high"] - df["low"],
                (df["high"] - prev_close).abs(),
                (df["low"] - prev_close).abs(),
            ],
            axis=1,
        ).max(axis=1)
        df["ATR"] = tr.rolling(window).mean()
        return df

    @staticmethod
    def set_supertrend(
        df: pd.DataFrame, period: int = 10, multiplier: float = 3.0
    ) -> pd.DataFrame:
        # Wilder-ATR band ratchet + flip state (oracle `_sts`).
        close, high, low = df["close"], df["high"], df["low"]
        pc = close.shift(1)
        tr = pd.concat([high - low, (high - pc).abs(), (low - pc).abs()], axis=1).max(
            axis=1
        )
        tr = tr.where(pc.notna(), high - low)
        atr = tr.ewm(alpha=1.0 / period, adjust=False, min_periods=period).mean()
        hl2 = (high + low) / 2.0
        upper = (hl2 + multiplier * atr).to_numpy()
        lower = (hl2 - multiplier * atr).to_numpy()
        closes = close.to_numpy()
        n = len(closes)
        st_dir = np.ones(n)
        st_line = np.full(n, np.nan)
        fu, fl, d, prev = np.inf, -np.inf, 1.0, 0.0
        for i in range(n):
            ub = upper[i] if np.isfinite(upper[i]) else np.inf
            lb = lower[i] if np.isfinite(lower[i]) else -np.inf
            fu = ub if (ub < fu or prev > fu) else fu
            fl = lb if (lb > fl or prev < fl) else fl
            d = 1.0 if closes[i] > fu else (-1.0 if closes[i] < fl else d)
            st_dir[i] = d
            st_line[i] = fl if d > 0 else fu
            prev = closes[i]
        # "supertrend" is the boolean uptrend flag — its only consumer
        # truth-tests it (`coinrule.py:160 bool(df["supertrend"].iloc[-1])`);
        # a band-line float there would be always-truthy. Until the ATR
        # warm-up completes there is no confirmed trend: flag False (the
        # engine/oracle pin the same semantic — supertrend_from emits NaN
        # direction before atr_ready).
        df["supertrend"] = (st_dir > 0) & atr.notna().to_numpy()
        df["supertrend_line"] = st_line
        df["supertrend_direction"] = st_dir
        return df


# ---------------------------------------------------------------------------
# pybinbot network clients — recording fakes bound to the active hub
# ---------------------------------------------------------------------------


class BinbotErrors(Exception):
    """Carries ``.message`` — the consumer logs it directly
    (autotrade_consumer.py ``except BinbotErrors as e: logging.info(e.message)``)."""

    def __init__(self, message: str = "", *args) -> None:
        super().__init__(message, *args)
        self.message = message


class BinbotError(BinbotErrors):
    pass


class _HubClient:
    def __init__(self, *args, **kwargs) -> None:
        self.hub = _ACTIVE_HUB

    def _login_service_account(self):  # conftest parity
        return None


class BinbotApi(_HubClient):
    def get_autotrade_settings(self):
        return self.hub.autotrade_settings

    def get_test_autotrade_settings(self):
        return self.hub.test_autotrade_settings

    def get_symbols(self):
        return list(self.hub.symbols)

    def get_single_symbol(self, symbol):
        return next(s for s in self.hub.symbols if s.id == symbol)

    def edit_symbol(self, symbol, **payload):
        self.hub.symbol_edits.append((self.hub.current_tick_ms, symbol, payload))
        return {"message": "ok"}

    def get_active_pairs(self, collection_name: str | None = None):
        return []

    def get_active_grid_ladders(self):
        return []

    async def get_market_breadth(self):
        return self.hub.breadth

    def get_available_fiat(self, *a, **k):
        return 1000.0

    def filter_excluded_symbols(self, symbols):
        return symbols

    def dispatch_create_signal(self, **kwargs):
        self.hub.record_signal(kwargs)

    def submit_bot_event_logs(self, *a, **k):
        return {"message": "ok"}

    def submit_paper_trading_event_logs(self, *a, **k):
        return {"message": "ok"}

    def clean_margin_short(self, *a, **k):
        return {"message": "ok"}

    def create_bot(self, payload):
        self.hub.bot_calls.append(("create_bot", payload))
        return {"message": "ok", "error": 0, "data": {"pair": getattr(payload, "pair", ""), "id": "0" * 32}}

    def activate_bot(self, *a, **k):
        self.hub.bot_calls.append(("activate_bot", a or k))
        return {"message": "ok", "error": 0}

    def create_paper_bot(self, payload):
        self.hub.bot_calls.append(("create_paper_bot", payload))
        return {"message": "ok", "error": 0, "data": {"pair": getattr(payload, "pair", ""), "id": "0" * 32}}

    def activate_paper_bot(self, *a, **k):
        return {"message": "ok", "error": 0}

    def delete_paper_bot(self, *a, **k):
        return {"message": "ok", "error": 0}

    def calculate_grid_levels(self, *a, **k):
        return {"levels": []}

    def create_grid_ladder(self, payload):
        self.hub.bot_calls.append(("create_grid_ladder", payload))
        return {"message": "ok", "error": 0}


class _ExchangeApi(_HubClient):
    def get_ui_klines(self, symbol: str, interval: str, limit: int = 400, **kw):
        return self.hub.ui_klines(symbol, interval, limit)

    def get_ticker_price(self, symbol: str):
        return self.hub.last_price(symbol)

    def get_mark_price(self, symbol: str):
        return self.hub.last_price(symbol)

    def get_open_interest(self, symbol: str):
        return self.hub.open_interest(symbol)

    def get_symbol_info(self, symbol: str):
        # futures contract spec consumed by the sizing math
        # (autotrade_consumer.py:117-131)
        return types.SimpleNamespace(
            multiplier=1.0, lot_size=1.0, taker_fee_rate=0.0006
        )


class BinanceApi(_ExchangeApi):
    pass


class KucoinApi(_ExchangeApi):
    pass


class KucoinFutures(_ExchangeApi):
    pass


class AsyncSpotWebsocketStreamClient:
    """Constructible inert stand-in (the factory tests instantiate these);
    actually RUNNING a stream is out of scope for the harness."""

    def __init__(self, *a, **k) -> None:
        self.args, self.kwargs = a, k

    def subscribe_klines(self, *a, **k) -> None:
        return None

    async def run_forever(self, *a, **k) -> None:
        raise RuntimeError("refdiff harness does not drive websockets")


class AsyncKucoinWebsocketClient(AsyncSpotWebsocketStreamClient):
    pass


# ---------------------------------------------------------------------------
# Interval enums (pybinbot *KlineIntervals with .get_ms())
# ---------------------------------------------------------------------------

_INTERVAL_MS = {
    "1m": 60_000, "1min": 60_000,
    "5m": 300_000, "5min": 300_000,
    "15m": 900_000, "15min": 900_000,
    "1h": 3_600_000, "1hour": 3_600_000,
}


class BinanceKlineIntervals(str, Enum):
    one_minute = "1m"
    five_minutes = "5m"
    fifteen_minutes = "15m"
    one_hour = "1h"

    def get_ms(self) -> int:
        return _INTERVAL_MS[self.value]


class KucoinKlineIntervals(str, Enum):
    ONE_MINUTE = "1min"
    FIVE_MINUTES = "5min"
    FIFTEEN_MINUTES = "15min"
    ONE_HOUR = "1hour"

    def get_ms(self) -> int:
        return _INTERVAL_MS[self.value]


def timestamp_sort_key(value):
    """Sortable numeric key for mixed timestamp payloads, None when
    unusable — `grid_only_policy.py:78-81` filters on `is not None`.
    Delegates to the engine-side implementation so the reference and the
    engine can never order the same breadth payload differently."""
    from binquant_tpu.regime.grid_policy import (
        timestamp_sort_key as _engine_sort_key,
    )

    return _engine_sort_key(value)


def configure_logging(*a, **k) -> None:
    logging.basicConfig(level=logging.WARNING)


def _build_pybinbot_module() -> types.ModuleType:
    from binquant_tpu import enums as _enums
    from binquant_tpu import schemas as _schemas
    from binquant_tpu import utils as _utils
    from pydantic import BaseModel

    mod = types.ModuleType("pybinbot")

    class KlineProduceModel(_schemas.KlineProduceModel):
        # the connector payload carries the market type of the producing
        # stream (klines_provider.py:322-329)
        market_type: _enums.MarketType | None = None

    class AutotradeSettingsSchema(_schemas.AutotradeSettingsSchema):
        # pybinbot field names the repo replica renamed/omitted
        telegram_signals: bool = False
        grid_max_active_ladders: int = 3

    class TestAutotradeSettingsSchema(AutotradeSettingsSchema):
        __test__ = False
        test_autotrade: bool = True

    class KlineSchema(BaseModel):
        """Typing-only stand-in for pybinbot's pandera KlineSchema."""

    class GridLadderStatus(str, Enum):
        pending = "pending"
        active = "active"
        completed = "completed"
        cancelled = "cancelled"

    class GridLadderRecord(BaseModel):
        """Active-ladder record as served by GET grid-ladders/active —
        consumed generically (attr/key reads) by the autotrade consumer."""

        model_config = {"extra": "allow"}

        symbol: str
        fiat: str = "USDT"
        exchange: str = "kucoin"
        market_type: str = "FUTURES"
        algorithm_name: str = "grid_ladder"
        status: GridLadderStatus = GridLadderStatus.pending
        range_low: float = 0.0
        range_high: float = 0.0
        grid_step: float = 0.0
        level_count: int = 0
        total_margin: float = 0.0
        breakout_low: float = 0.0
        breakout_high: float = 0.0

    for name, obj in {
        # data layer
        "Candles": Candles,
        "Indicators": Indicators,
        "KlineSchema": KlineSchema,
        # models (repo SDK replica — binquant_tpu/schemas.py)
        "SignalsConsumer": _schemas.SignalsConsumer,
        "HABollinguerSpread": _schemas.HABollinguerSpread,
        "BotBase": _schemas.BotBase,
        "BotModel": _schemas.BotModel,
        "BotResponse": _schemas.BotResponse,
        "OrderBase": _schemas.OrderBase,
        "DealBase": _schemas.DealBase,
        "DealType": _enums.DealType,
        "RecoveryParams": _schemas.RecoveryParams,
        "CloseConditions": _schemas.CloseConditions,
        "GridDeploymentRequest": _schemas.GridDeploymentRequest,
        "SymbolModel": _schemas.SymbolModel,
        "MarketBreadthSeries": _schemas.MarketBreadthSeries,
        "KlineProduceModel": KlineProduceModel,
        "AutotradeSettingsSchema": AutotradeSettingsSchema,
        "TestAutotradeSettingsSchema": TestAutotradeSettingsSchema,
        "GridLadderRecord": GridLadderRecord,
        "GridLadderStatus": GridLadderStatus,
        # enums
        "Position": _schemas.Position,
        "MarketType": _enums.MarketType,
        "ExchangeId": _enums.ExchangeId,
        "MarketDominance": _enums.MarketDominance,
        "Status": _enums.Status,
        "BinanceKlineIntervals": BinanceKlineIntervals,
        "KucoinKlineIntervals": KucoinKlineIntervals,
        # helpers
        "round_numbers": _utils.round_numbers,
        "timestamp_to_datetime": _utils.timestamp_to_datetime,
        "timestamp_sort_key": timestamp_sort_key,
        "configure_logging": configure_logging,
        # network clients
        "BinbotApi": BinbotApi,
        "BinanceApi": BinanceApi,
        "KucoinApi": KucoinApi,
        "KucoinFutures": KucoinFutures,
        "AsyncSpotWebsocketStreamClient": AsyncSpotWebsocketStreamClient,
        "AsyncKucoinWebsocketClient": AsyncKucoinWebsocketClient,
        "BinbotErrors": BinbotErrors,
        "BinbotError": BinbotError,
    }.items():
        setattr(mod, name, obj)
    return mod


def _build_pandera_module() -> tuple[types.ModuleType, types.ModuleType]:
    pandera = types.ModuleType("pandera")
    typing_mod = types.ModuleType("pandera.typing")

    class DataFrame:
        """``TypedDataFrame[KlineSchema]`` annotation support only."""

        def __class_getitem__(cls, item):
            return pd.DataFrame

    typing_mod.DataFrame = DataFrame
    typing_mod.Series = pd.Series
    pandera.typing = typing_mod
    return pandera, typing_mod


def _build_telegram_modules() -> dict[str, types.ModuleType]:
    telegram = types.ModuleType("telegram")
    constants = types.ModuleType("telegram.constants")
    error = types.ModuleType("telegram.error")
    helpers = types.ModuleType("telegram.helpers")

    class Bot:
        def __init__(self, token=None, *a, **k) -> None:
            self.token = token

        async def send_message(self, *a, **k) -> None:
            return None

    class ParseMode:
        HTML = "HTML"
        MARKDOWN = "Markdown"

    class TelegramError(Exception):
        pass

    class RetryAfter(TelegramError):
        def __init__(self, retry_after: float = 1.0) -> None:
            super().__init__(f"retry after {retry_after}")
            self.retry_after = retry_after

    class TimedOut(TelegramError):
        pass

    telegram.Bot = Bot
    constants.ParseMode = ParseMode
    error.TelegramError = TelegramError
    error.RetryAfter = RetryAfter
    error.TimedOut = TimedOut
    # quote=True: the reference's sanitizer regexes match &#x27;/&quot;
    # (telegram.helpers.escape escapes quotes)
    helpers.escape = lambda text: _html.escape(str(text), quote=True)
    telegram.constants = constants
    telegram.error = error
    telegram.helpers = helpers
    return {
        "telegram": telegram,
        "telegram.constants": constants,
        "telegram.error": error,
        "telegram.helpers": helpers,
    }


def _build_dotenv_module() -> types.ModuleType:
    dotenv = types.ModuleType("dotenv")
    dotenv.load_dotenv = lambda *a, **k: False
    return dotenv


def install_shims() -> str:
    """Register the shims in ``sys.modules`` and put the reference on the
    import path. Idempotent. Returns the reference path."""
    if "pybinbot" not in sys.modules:
        mod = _build_pybinbot_module()
        sys.modules["pybinbot"] = mod
        # package-shaped submodules some reference tests patch directly
        # (e.g. `pybinbot.apis.binbot.base.BinbotApi`)
        mod.__path__ = []  # mark as package
        apis = types.ModuleType("pybinbot.apis")
        apis.__path__ = []
        binbot_pkg = types.ModuleType("pybinbot.apis.binbot")
        binbot_pkg.__path__ = []
        base = types.ModuleType("pybinbot.apis.binbot.base")
        base.BinbotApi = mod.BinbotApi
        binbot_pkg.base = base
        apis.binbot = binbot_pkg
        mod.apis = apis
        sys.modules["pybinbot.apis"] = apis
        sys.modules["pybinbot.apis.binbot"] = binbot_pkg
        sys.modules["pybinbot.apis.binbot.base"] = base
        # pybinbot.streaming.kucoin.kucoin_async_client (factory tests
        # patch DefaultClient at this path)
        streaming = types.ModuleType("pybinbot.streaming")
        streaming.__path__ = []
        kucoin = types.ModuleType("pybinbot.streaming.kucoin")
        kucoin.__path__ = []
        kac = types.ModuleType("pybinbot.streaming.kucoin.kucoin_async_client")

        class DefaultClient:
            def __init__(self, *a, **k) -> None:
                self.args, self.kwargs = a, k

        kac.DefaultClient = DefaultClient
        kucoin.kucoin_async_client = kac
        streaming.kucoin = kucoin
        mod.streaming = streaming
        sys.modules["pybinbot.streaming"] = streaming
        sys.modules["pybinbot.streaming.kucoin"] = kucoin
        sys.modules["pybinbot.streaming.kucoin.kucoin_async_client"] = kac
    if "pandera" not in sys.modules:
        pandera, typing_mod = _build_pandera_module()
        sys.modules["pandera"] = pandera
        sys.modules["pandera.typing"] = typing_mod
    if "telegram" not in sys.modules:
        sys.modules.update(_build_telegram_modules())
    if "dotenv" not in sys.modules:
        sys.modules["dotenv"] = _build_dotenv_module()
    if REFERENCE_PATH not in sys.path:
        # append (not prepend): the reference's generic top-level names
        # (shared, models, strategies, ...) must never shadow repo modules
        sys.path.append(REFERENCE_PATH)
    os.environ.setdefault("ENV", "CI")
    return REFERENCE_PATH
