"""Differential-verification harness: execute the REFERENCE code itself.

Every other correctness layer in this repo compares the TPU batch path
against a builder-transcribed pandas oracle (``binquant_tpu/oracle``). If
the transcription misread a reference formula, both sides inherit the bug
and stay green. This package closes that hole (VERDICT r4 item 1): it
imports the reference implementation from ``/root/reference`` (read-only)
and replays the SAME fixtures through the reference's own
``KlinesProvider.aggregate_data`` → ``ContextEvaluator.process_data``
chain — market state store, context accumulator, regime transition
detector, strategies, autotrade gates all executing verbatim — then diffs
the emitted signal set against both the transcribed oracle and the TPU
batch path.

The only code NOT executed verbatim is the external ``pybinbot`` PyPI
package (not installed in this environment, zero egress): ``shims``
provides its SDK surface — pydantic models/enums re-exported from this
repo's own SDK replica (``binquant_tpu.schemas``/``enums``/``utils``),
plus ``Candles``/``Indicators`` re-implemented from the surface
documented in SURVEY.md §2.8. Indicator-column math is therefore shared
with the transcription and NOT independently verified by this harness;
everything under ``/root/reference`` itself is.

Usage: ``tests/test_reference_differential.py`` (slow suite).
"""

from binquant_tpu.refdiff.driver import run_replay_reference  # noqa: F401
from binquant_tpu.refdiff.shims import install_shims, reference_available  # noqa: F401
