"""Pipelined production tick loop (VERDICT round-2 item 1).

``SignalEngine.process_tick`` dispatches tick i and emits tick i-depth
(whose wire D2H already landed) — the measurement model bench.py always
assumed, now implemented by the engine itself. These tests pin:

* deferral mechanics: with depth=1 a call returns the PREVIOUS tick's
  signals; in-flight ticks are finalized by ``flush_pending``;
* attribution: emitted signals carry ``tick_ms`` of the tick that
  produced them, not the call that evicted them;
* equivalence: a full replay at depth 1 emits exactly the signal set the
  serial (depth 0) path emits, each attributed to the same tick.
"""

import asyncio

import pytest

from binquant_tpu.io.replay import (
    generate_replay_file,
    load_klines_by_tick,
    make_stub_engine,
    run_replay,
)

CAP, WIN = 16, 130


@pytest.fixture(scope="module")
def market_path(tmp_path_factory):
    path = tmp_path_factory.mktemp("pipelined") / "rp.jsonl"
    # enough ticks that MIN_BARS(=100) passes and the crafted last-tick
    # setups (activity burst on S001, MRF hammer on S005) actually fire
    generate_replay_file(path, n_symbols=8, n_ticks=110)
    return path


def test_depth1_defers_one_tick_and_flush_recovers(market_path):
    engine = make_stub_engine(capacity=CAP, window=WIN, pipeline_depth=1)
    by_tick = load_klines_by_tick(market_path)
    buckets = sorted(by_tick)

    returned: list[tuple[int, list]] = []

    async def go():
        for b in buckets:
            for k in sorted(by_tick[b], key=lambda k: k["open_time"]):
                engine.ingest(k)
            tick_ms = (b + 1) * 900 * 1000
            returned.append((tick_ms, await engine.process_tick(now_ms=tick_ms)))
        return await engine.flush_pending()

    tail = asyncio.run(go())

    # the first call cannot emit anything: its own tick is still in flight
    assert returned[0][1] == []
    # every emitted signal is attributed to the PRIOR tick, not the caller
    for call_ms, fired in returned:
        for s in fired:
            assert s.tick_ms == call_ms - 900 * 1000
    # the last tick's signals only surface via the flush — and the crafted
    # last-tick setups guarantee it is non-empty
    assert tail, "flush_pending must emit the in-flight final tick"
    last_ms = (buckets[-1] + 1) * 900 * 1000
    assert all(s.tick_ms == last_ms for s in tail)
    assert not engine._pending


def test_pipelined_replay_equals_serial_replay(market_path):
    serial: list[tuple] = []
    run_replay(market_path, capacity=CAP, window=WIN, collect=serial,
               pipeline_depth=0)
    pipelined: list[tuple] = []
    run_replay(market_path, capacity=CAP, window=WIN, collect=pipelined,
               pipeline_depth=1)
    assert serial, "scenario must fire at least one signal"
    assert set(serial) == set(pipelined)


def test_consume_loop_finalizes_pending_on_idle(market_path):
    """A quiet feed must not strand a dispatched tick in the pipeline:
    consume_loop flushes pending ticks after one idle interval instead of
    waiting for the next candle burst (code-review r3 finding)."""
    engine = make_stub_engine(capacity=CAP, window=WIN, pipeline_depth=1)
    by_tick = load_klines_by_tick(market_path)
    first = sorted(by_tick)[0]

    async def go():
        # one burst arrives over the queue; then the feed goes quiet
        queue: asyncio.Queue = asyncio.Queue()
        for k in sorted(by_tick[first], key=lambda k: k["open_time"]):
            queue.put_nowait(k)
        loop_task = asyncio.create_task(
            engine.consume_loop(queue, tick_interval_s=0.2)
        )
        # interval 1 dispatches the tick (pending=1); the next idle
        # interval must finalize it (pending=0, wire consumed)
        finalized = False
        for _ in range(300):
            await asyncio.sleep(0.1)
            if engine.ticks_processed >= 1 and not engine._pending:
                finalized = True
                break
        loop_task.cancel()
        try:
            await loop_task
        except asyncio.CancelledError:
            pass
        assert engine.ticks_processed >= 1
        assert finalized, "pending tick was never finalized on idle"
        assert engine.latency.stats().get("wire_fetch", {}).get("n", 0) >= 1

    asyncio.run(go())


def test_depth_zero_is_same_tick(market_path):
    engine = make_stub_engine(capacity=CAP, window=WIN, pipeline_depth=0)
    by_tick = load_klines_by_tick(market_path)
    buckets = sorted(by_tick)

    async def go():
        total = []
        for b in buckets:
            for k in sorted(by_tick[b], key=lambda k: k["open_time"]):
                engine.ingest(k)
            tick_ms = (b + 1) * 900 * 1000
            fired = await engine.process_tick(now_ms=tick_ms)
            for s in fired:
                assert s.tick_ms == tick_ms
            total.extend(fired)
        assert not engine._pending  # serial mode never leaves work behind
        return total

    assert asyncio.run(go())


def test_depth2_donated_equals_serial_oracle(market_path):
    """ISSUE 9 composition pin: depth-2 pipelining + donation. The
    double-buffered step (``tick_step_wire_db``) donates a rotated spare
    slot instead of the input state, so host finalize of tick i overlaps
    the dispatch of tick i+1 with donated buffers live — previously
    ``_use_donated_step`` hard-disabled donation past depth 1. The drive
    must actually donate (no silent fallback), never reset cold, and emit
    exactly the depth-0 serial oracle's signal set."""
    serial: list[tuple] = []
    run_replay(market_path, capacity=CAP, window=WIN, collect=serial,
               pipeline_depth=0, donate=False)
    db: list[tuple] = []
    stats = run_replay(market_path, capacity=CAP, window=WIN, collect=db,
                       pipeline_depth=2, donate=True)
    assert stats["donated_ticks"] > 0, "depth-2 drive never donated"
    assert stats["donated_state_resets"] == 0
    assert serial, "scenario must fire at least one signal"
    assert set(serial) == set(db)


@pytest.mark.slow
def test_depth2_donated_overflow_burst(tmp_path):
    """The depth-2 donated drive through a >WIRE_MAX_FIRED crash tick: the
    overflow fallback re-evaluates from the tick's EAGERLY-captured post
    state (later dispatches have already replaced self.state by finalize
    time) + the pre-tick small-carry snapshots. Emitted set must equal the
    depth-0 serial oracle's, signal for signal."""
    from binquant_tpu.engine.step import WIRE_MAX_FIRED
    from binquant_tpu.io.replay import generate_burst_replay

    n_symbols = 160
    assert n_symbols > WIRE_MAX_FIRED
    path = tmp_path / "burst_depth2.jsonl"
    generate_burst_replay(path, n_symbols=n_symbols, n_ticks=108)

    serial: list[tuple] = []
    run_replay(path, capacity=256, window=200, collect=serial,
               pipeline_depth=0, donate=False)
    db: list[tuple] = []
    stats = run_replay(path, capacity=256, window=200, collect=db,
                       pipeline_depth=2, donate=True)
    assert stats["overflow_ticks"] >= 1, "burst never overflowed the wire"
    assert stats["donated_ticks"] > 0
    assert stats["donated_state_resets"] == 0
    assert serial
    assert set(serial) == set(db)
