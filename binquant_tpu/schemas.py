"""Pydantic wire/domain models.

Covers both the reference's own models (``market_regime/models.py``,
``models/bot.py``, ``models/strategies.py``) and the pybinbot SDK schema
surface binquant consumes (``SURVEY.md`` §2.8): ``SignalsConsumer``,
``KlineProduceModel``, ``BotBase``, ``GridDeploymentRequest``,
``HABollinguerSpread``, ``SymbolModel``, ``AutotradeSettingsSchema``,
``MarketBreadthSeries``, ``BotResponse`` — so a reference user finds the
same emitted payload shapes here.
"""

from __future__ import annotations

from datetime import datetime
from enum import Enum
from typing import Any
from uuid import UUID, uuid4

from pydantic import BaseModel, ConfigDict, Field, field_validator, model_validator

from binquant_tpu.enums import (
    MarketRegime,
    MarketRegimeTransition,
    MarketType,
    MicroRegime,
    MicroRegimeTransition,
    SignalKind,
    Status,
)


class Position(str, Enum):
    long = "long"
    short = "short"


class CloseConditions(str, Enum):
    dynamic_trailing = "dynamic_trailing"
    timestamp = "timestamp"
    market_reversal = "market_reversal"


def _normalize_direction(value: str) -> str:
    return value.upper().strip()


def _canonicalize_symbol(value: str) -> str:
    return value.upper().strip().replace("-", "").replace("_", "")


# ---------------------------------------------------------------------------
# Kline ingest payloads (reference producers/klines_connector.py:154-164)
# ---------------------------------------------------------------------------


class KlineProduceModel(BaseModel):
    """One closed candle as produced by the websocket connector."""

    symbol: str
    open_time: str
    close_time: str
    open_price: str
    high_price: str
    low_price: str
    close_price: str
    volume: str


class ExtendedKline(BaseModel):
    """Full closed-candle payload kept by the TPU ring buffer.

    Superset of ``KlineProduceModel`` carrying the extra Binance kline fields
    (quote volume, trade count, taker-buy splits) that several strategies'
    features need (quote-volume spike ratios, trade-count floors).
    """

    symbol: str
    open_time: int
    close_time: int
    open: float
    high: float
    low: float
    close: float
    volume: float
    quote_asset_volume: float = 0.0
    number_of_trades: float = 0.0
    taker_buy_base_volume: float = 0.0
    taker_buy_quote_volume: float = 0.0

    @classmethod
    def from_produce_model(cls, m: KlineProduceModel | dict[str, Any]) -> "ExtendedKline":
        if isinstance(m, dict):
            m = KlineProduceModel.model_validate(m)
        return cls(
            symbol=m.symbol,
            open_time=int(float(m.open_time)),
            close_time=int(float(m.close_time)),
            open=float(m.open_price),
            high=float(m.high_price),
            low=float(m.low_price),
            close=float(m.close_price),
            volume=float(m.volume),
        )


# ---------------------------------------------------------------------------
# Market regime models (reference market_regime/models.py)
# ---------------------------------------------------------------------------


class SymbolMarketFeatures(BaseModel):
    model_config = ConfigDict(extra="forbid")

    symbol: str
    timestamp: int
    close: float
    return_pct: float
    ema20: float
    ema50: float
    above_ema20: bool
    above_ema50: bool
    trend_score: float
    relative_strength_vs_btc: float
    atr_pct: float
    bb_width: float
    micro_regime: MicroRegime | None = None
    micro_regime_strength: float = Field(default=0.0, ge=0.0, le=1.0)
    micro_regime_transition: MicroRegimeTransition | None = None
    micro_regime_transition_strength: float = Field(default=0.0, ge=0.0, le=1.0)

    @field_validator("symbol")
    @classmethod
    def validate_symbol(cls, value: str) -> str:
        return value.strip().upper()

    @field_validator("timestamp")
    @classmethod
    def validate_timestamp(cls, value: int) -> int:
        if value < 0:
            raise ValueError("timestamp must be non-negative")
        return value


class LiveMarketContext(BaseModel):
    model_config = ConfigDict(extra="forbid")

    timestamp: int
    fresh_count: int
    total_tracked_symbols: int
    coverage_ratio: float = Field(ge=0.0, le=1.0)
    btc_symbol: str
    btc_present: bool
    confidence: float = Field(ge=0.0, le=1.0)
    is_provisional: bool
    advancers: int
    decliners: int
    advancers_ratio: float = Field(ge=0.0, le=1.0)
    decliners_ratio: float = Field(ge=0.0, le=1.0)
    advancers_decliners_ratio: float = Field(ge=0.0)
    average_return: float
    average_relative_strength_vs_btc: float
    pct_above_ema20: float = Field(ge=0.0, le=1.0)
    pct_above_ema50: float = Field(ge=0.0, le=1.0)
    average_trend_score: float
    average_atr_pct: float = Field(ge=0.0)
    average_bb_width: float = Field(ge=0.0)
    btc_return: float
    btc_trend_score: float
    btc_regime_score: float = Field(ge=-1.0, le=1.0)
    market_stress_score: float = Field(ge=0.0, le=1.0)
    long_tailwind: float = Field(ge=-1.0, le=1.0)
    short_tailwind: float = Field(ge=-1.0, le=1.0)
    market_regime: MarketRegime | None = None
    previous_market_regime: MarketRegime | None = None
    market_regime_transition: MarketRegimeTransition | None = None
    market_regime_transition_strength: float = Field(default=0.0, ge=0.0, le=1.0)
    long_regime_score: float = Field(default=0.0, ge=0.0, le=1.0)
    short_regime_score: float = Field(default=0.0, ge=0.0, le=1.0)
    range_regime_score: float = Field(default=0.0, ge=0.0, le=1.0)
    stress_regime_score: float = Field(default=0.0, ge=0.0, le=1.0)
    regime_is_transitioning: bool = False
    regime_stable_since: int | None = Field(
        default=None,
        description="Timestamp (ms) when the current market_regime was first entered.",
    )
    symbol_features: dict[str, SymbolMarketFeatures] = Field(default_factory=dict)
    metadata: dict[str, Any] = Field(default_factory=dict)

    @field_validator("btc_symbol")
    @classmethod
    def validate_btc_symbol(cls, value: str) -> str:
        return value.strip().upper()

    @field_validator(
        "timestamp", "fresh_count", "total_tracked_symbols", "advancers", "decliners"
    )
    @classmethod
    def validate_non_negative_ints(cls, value: int) -> int:
        if value < 0:
            raise ValueError("value must be non-negative")
        return value

    @property
    def is_full(self) -> bool:
        return not self.is_provisional

    @model_validator(mode="after")
    def validate_consistency(self) -> "LiveMarketContext":
        if self.fresh_count > self.total_tracked_symbols:
            raise ValueError("fresh_count cannot exceed total_tracked_symbols")
        if self.advancers + self.decliners > self.fresh_count:
            raise ValueError("advancers plus decliners cannot exceed fresh_count")
        return self

    def get_symbol_features(self, symbol: str) -> SymbolMarketFeatures | None:
        normalized = symbol.strip().upper()
        direct = self.symbol_features.get(normalized)
        if direct is not None:
            return direct
        canonical = _canonicalize_symbol(normalized)
        for known_symbol, features in self.symbol_features.items():
            if _canonicalize_symbol(known_symbol) == canonical:
                return features
        return None


class MarketContextScore(BaseModel):
    model_config = ConfigDict(extra="forbid")

    symbol: str
    direction: str
    context_timestamp: int | None
    confidence: float = Field(ge=0.0, le=1.0)
    long_tailwind: float = Field(ge=-1.0, le=1.0)
    short_tailwind: float = Field(ge=-1.0, le=1.0)
    breadth_score: float = Field(ge=-1.0, le=1.0)
    btc_alignment_score: float = Field(ge=-1.0, le=1.0)
    cross_asset_confirmation: float = Field(ge=-1.0, le=1.0)
    market_stress_score: float = Field(ge=0.0, le=1.0)
    followthrough_score: float = Field(ge=-1.0, le=1.0)
    adverse_excursion_risk: float = Field(ge=0.0, le=1.0)
    override_strength: float = Field(ge=0.0, le=1.0)
    supportiveness_score: float = Field(ge=-1.0, le=1.0)
    metadata: dict[str, Any] = Field(default_factory=dict)

    @field_validator("symbol")
    @classmethod
    def validate_symbol(cls, value: str) -> str:
        return value.strip().upper()

    @field_validator("direction")
    @classmethod
    def validate_direction(cls, value: str) -> str:
        return _normalize_direction(value)


class SignalContextEvaluation(BaseModel):
    model_config = ConfigDict(arbitrary_types_allowed=True, extra="forbid")

    symbol: str
    direction: str
    local_score: float
    local_features: dict[str, float]
    adjusted_score: float
    emit: bool = Field(default=True)
    context_score: MarketContextScore


# ---------------------------------------------------------------------------
# Bot / trade payloads (pybinbot BotBase surface, shared/autotrade.py:73-89)
# ---------------------------------------------------------------------------


class RecoveryParams(BaseModel):
    """Bounded-recovery (reversal) parameters on a bot.

    Field set pinned by the reference's own tests
    (``/root/reference/tests/test_autotrade_consumer.py:589-594``): the
    recovery re-enters along the SOURCE trade's path with its contracts
    and realized loss carried over."""

    reversal_path: str = "source"
    source_contracts: float = 0
    source_loss_fiat: float = 0
    stop_loss_pct: float = 0


class OrderBase(BaseModel):
    order_id: int = 0
    order_type: str = ""
    time_in_force: str = ""
    timestamp: float = 0
    order_side: str = ""
    pair: str = ""
    qty: float = 0
    status: str = ""
    price: float = 0
    deal_type: str = "base_order"


class DealBase(BaseModel):
    current_price: float = 0
    take_profit_price: float = 0
    trailling_stop_loss_price: float = 0
    trailling_profit_price: float = 0
    stop_loss_price: float = 0
    total_commissions: float = 0
    margin_loan_id: int = 0
    margin_short_loan_principal: float = 0
    opening_price: float = 0
    opening_qty: float = 0
    opening_timestamp: float = 0
    closing_price: float = 0
    closing_qty: float = 0
    closing_timestamp: float = 0


class BotBase(BaseModel):
    """Bot creation payload sent to the binbot REST API."""

    model_config = ConfigDict(use_enum_values=True)

    pair: str
    name: str = "terminal"
    fiat: str = "USDT"
    # platform default quote asset (pinned by the reference's
    # tests/test_producer.py json-mode payload assertions)
    quote_asset: str = "USDC"
    fiat_order_size: float = 15.0
    candlestick_interval: str = "15m"
    close_condition: CloseConditions = CloseConditions.dynamic_trailing
    cooldown: int = 0
    dynamic_trailing: bool = False
    logs: list[str] = Field(default_factory=list)
    mode: str = "manual"
    status: Status = Status.inactive
    stop_loss: float = 0.0
    take_profit: float = 2.3
    trailing: bool = True
    trailing_deviation: float = 0.63
    trailing_profit: float = 2.3
    margin_short_reversal: bool = False
    position: Position = Position.long
    market_type: MarketType = MarketType.SPOT
    leverage: float = 1.0
    recovery_params: RecoveryParams | None = None
    created_at: float = 0.0
    updated_at: float = 0.0


class OrderModel(OrderBase):
    pass


class DealModel(DealBase):
    @field_validator("margin_loan_id", mode="before")
    @classmethod
    def validate_margin_loan_id(cls, value: Any) -> Any:
        if isinstance(value, float):
            return int(value)
        return value


class BotModel(BotBase):
    """Bot as returned by the binbot API (id + deal + orders filled in)."""

    id: UUID = Field(default_factory=uuid4)
    deal: DealModel = Field(default_factory=DealModel)
    orders: list[OrderModel] = Field(default_factory=list)

    model_config = ConfigDict(from_attributes=True, use_enum_values=True)


class BotResponse(BaseModel):
    message: str = ""
    error: int = 0
    data: BotModel | None = None


# ---------------------------------------------------------------------------
# Grid deployment (pybinbot GridDeploymentRequest surface,
# strategies/grid/ladder_deployer.py:116-130)
# ---------------------------------------------------------------------------


class GridDeploymentRequest(BaseModel):
    symbol: str
    fiat: str
    exchange: str
    market_type: MarketType
    algorithm_name: str
    generated_at: datetime
    range_low: float
    range_high: float
    breakout_low: float
    breakout_high: float
    total_margin: float
    level_count: int
    leverage: float = 1.0
    current_price: float = 0.0
    current_regime: str | None = None
    context: dict[str, Any] = Field(default_factory=dict)
    indicators: dict[str, Any] = Field(default_factory=dict)
    allocation_pct: float | None = None
    cash_reserve_pct: float | None = None
    metadata: dict[str, Any] = Field(default_factory=dict)

    model_config = ConfigDict(use_enum_values=True)


# ---------------------------------------------------------------------------
# The Signal object (pybinbot SignalsConsumer surface)
# ---------------------------------------------------------------------------


class HABollinguerSpread(BaseModel):
    bb_high: float = 0.0
    bb_mid: float = 0.0
    bb_low: float = 0.0


class SignalsConsumer(BaseModel):
    """The Signal emitted to all three sinks (telegram/analytics/autotrade)."""

    autotrade: bool = False
    current_price: float = 0.0
    direction: str = "LONG"
    score: float = 0.0
    volume: float = 0.0
    signal_kind: SignalKind = SignalKind.standard
    algorithm_name: str = ""
    symbol: str = ""
    bot_params: BotBase | None = None
    grid_params: GridDeploymentRequest | None = None
    bb_spreads: HABollinguerSpread | None = None
    metadata: dict[str, Any] = Field(default_factory=dict)

    model_config = ConfigDict(use_enum_values=True)


# ---------------------------------------------------------------------------
# Symbols & settings (pybinbot SymbolModel / AutotradeSettingsSchema surface)
# ---------------------------------------------------------------------------


class SymbolModel(BaseModel):
    id: str
    base_asset: str = ""
    quote_asset: str = "USDT"
    active: bool = True
    is_margin_trading_allowed: bool = False
    price_precision: int = 6
    qty_precision: int = 6
    min_notional: float = 5.0
    cooldown: int = 0
    cooldown_start_ts: int = 0
    leverage: float = 1.0
    futures_leverage: float = 1.0
    blacklist_reason: str = ""


class AutotradeSettingsSchema(BaseModel):
    autotrade: bool = False
    exchange_id: str = "binance"
    market_type: MarketType = MarketType.SPOT
    candlestick_interval: str = "15m"
    fiat: str = "USDT"
    base_order_size: float = 15.0
    stop_loss: float = 3.0
    take_profit: float = 2.3
    trailing: bool = True
    trailing_deviation: float = 0.63
    trailing_profit: float = 2.3
    autoswitch: bool = False
    max_active_autotrade_bots: int = 10
    grid_total_margin: float = 10.0
    grid_level_count: int = 7
    max_active_grid_ladders: int = 3
    grid_allocation_pct: float | None = 60.0
    grid_cash_reserve_pct: float | None = 40.0
    test_autotrade: bool = False

    model_config = ConfigDict(use_enum_values=True)


class TestAutotradeSettingsSchema(AutotradeSettingsSchema):
    __test__ = False  # pydantic model, not a pytest class

    test_autotrade: bool = True


class MarketBreadthSeries(BaseModel):
    """Rolling market-breadth time series from the binbot analytics API.

    The live endpoint serves `timestamp` as ISO-8601 strings (newest
    first) and may null out individual MA entries; the reference's own
    tests pin that payload shape (e.g.
    ``/root/reference/tests/test_klines_provider.py:189-200``), so the
    model must accept it — consumers order by timestamp and drop
    non-finite entries themselves (``regime/grid_policy.py``,
    ``io/pipeline.breadth_scalars``). Extra fields (avg_gain, avg_loss,
    total_volume, ...) are retained untyped."""

    model_config = ConfigDict(extra="allow")

    timestamp: list[int | str] = Field(default_factory=list)
    market_breadth: list[float | None] = Field(default_factory=list)
    market_breadth_ma: list[float | None] = Field(default_factory=list)
    adp: list[float | None] = Field(default_factory=list)
    adp_ma: list[float | None] = Field(default_factory=list)
    advancers: list[float | None] = Field(default_factory=list)
    decliners: list[float | None] = Field(default_factory=list)


# ---------------------------------------------------------------------------
# Structured strategy decisions (reference models/strategies.py:4-15)
# ---------------------------------------------------------------------------


class BBExtremeReversionDecision(BaseModel):
    fired: bool
    direction: str | None = None
    reason: str = ""
    connors_rsi: float | None = None
    close: float | None = None
    bb_high: float | None = None
    bb_low: float | None = None
    score: float = 0.0
